"""Comm/compute overlap: evidence from the COMPILED 8-chip TPU schedule.

The reference hides halo-exchange latency with Irecv → local SpMM → Waitany
(``Parallel-GCN/main.c:238-299``).  Round 3 proved our split-edge structure
gives XLA the same freedom (the local-src slot passes have no data dependence
on the all_to_all) but could not show actual concurrency: the virtual CPU
mesh serializes collectives and this host has one physical chip.

This test extracts the evidence that does NOT need 8 chips (VERDICT r3 item
4): AOT-compile the real ``FullBatchTrainer`` train step against an 8-chip
v5e TOPOLOGY (``jax.experimental.topologies`` — compile-only, no devices) and
assert, in the scheduled HLO, that the halo ``all-to-all`` compiles to async
``-start``/``-done`` pairs with real compute (fusions — the local slot
passes) scheduled inside the start→done window.  That is the compiled-program
form of "communication overlaps local aggregation".

HLO parsing rides the repo's ONE parser (``sgcn_tpu.analysis.hlo`` — the
same module the mode-matrix auditor uses on lowered StableHLO), so the
start/done pairing logic cannot drift between this test and the audit.
"""

import re

import numpy as np
import pytest

from sgcn_tpu.analysis import hlo
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.train import FullBatchTrainer

# AOT-compiling the 8-chip v5e train step costs ~8 min on this 2-core box
# (and needs a jaxlib whose TPU AOT path works at all) — far past the tier-1
# budget, so it runs only in the unfiltered suite
pytestmark = pytest.mark.slow


K = 8


@pytest.fixture(scope="module")
def v5e_mesh():
    import jax
    from jax.experimental import topologies
    from jax.sharding import Mesh

    try:
        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:2x4")
    except Exception as e:                       # noqa: BLE001
        pytest.skip(f"v5e topology AOT unavailable: {e!r}")
    return Mesh(np.array(topo.devices).reshape(K), ("v",))


@pytest.fixture(scope="module")
def step_text(v5e_mesh, n=4096, avg_deg=12, f=64):
    """Compile one real train step for the v5e slice; return scheduled HLO.

    Compiled with the framework's async-collective flag
    (``utils/backend.py::ASYNC_COLLECTIVE_FLAGS`` — v5e's DEFAULT is a
    synchronous all-to-all, measured on this exact program; the trainer CLI
    and bench set the flag via ``enable_tpu_async_collectives``)."""
    from sgcn_tpu.io.datasets import ba_graph
    from sgcn_tpu.prep import normalize_adjacency

    ahat = normalize_adjacency(ba_graph(n, avg_deg // 2, seed=1))
    pv = balanced_random_partition(n, K, seed=2)
    plan = build_comm_plan(ahat, pv, K)
    tr = FullBatchTrainer(plan, fin=f, widths=[f, 8])
    lowered = tr.lower_step(v5e_mesh, fin=f)
    return lowered.compile(compiler_options={
        "xla_tpu_enable_async_all_to_all": "true"}).as_text()


def test_halo_all_to_all_is_async_and_overlapped(step_text):
    # pair each async start with ITS done via the SSA value name:
    #   %all-to-all-start.N = ... all-to-all-start(...)
    #   %all-to-all-done.M  = ... all-to-all-done(%all-to-all-start.N)
    # (hlo.async_windows raises on an unknown-start done or an unmatched
    # start — a malformed schedule must fail loudly, not read as zero)
    assert hlo.count_async_starts(step_text) >= 2, (
        "no async all-to-all pairs in schedule — was the program compiled "
        "with xla_tpu_enable_async_all_to_all?")
    windows = hlo.async_windows(step_text)
    # Every layer's local-src slot pass is independent of its own exchange
    # by construction (ops/pspmm.py::pspmm_overlap), so the latency-hiding
    # scheduler must put real compute inside every real exchange window.
    # Measured on this program: 3 windows, 83-192 fusions each.
    assert len(windows) >= 2 and all(w > 0 for w in windows), (
        f"async windows carry no compute: fusions-in-window={windows}")


def test_grad_allreduce_present(step_text):
    """The dense-grad psum (GPU/PGCN.py:150-154 role) must appear in the same
    compiled program — all-reduce over all 8 chips."""
    assert re.search(r"all-reduce", step_text), \
        "no all-reduce in compiled step"
