"""Pallas SpMM kernel vs the default XLA path (interpret mode on CPU)."""

import numpy as np
import jax.numpy as jnp

from sgcn_tpu.ops import spmm_local
from sgcn_tpu.ops.pallas_spmm import build_dst_tiles, spmm_pallas
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition


def test_build_dst_tiles_roundtrip(ahat):
    n = ahat.shape[0]
    plan = build_comm_plan(ahat, np.zeros(n, dtype=np.int64), 1)
    ed, es, ew = plan.edge_dst[0], plan.edge_src[0], plan.edge_w[0]
    tsrc, tld, tw, padded = build_dst_tiles(ed, es, ew, plan.b, tb=16)
    assert padded % 16 == 0
    # every real edge appears exactly once with its weight (pads are 0)
    np.testing.assert_allclose(np.sort(tw[tw != 0]), np.sort(ew[ew != 0]),
                               rtol=0, atol=0)


def test_pallas_matches_xla(ahat):
    n = ahat.shape[0]
    rng = np.random.default_rng(0)
    plan = build_comm_plan(ahat, np.zeros(n, dtype=np.int64), 1)
    ed, es, ew = plan.edge_dst[0], plan.edge_src[0], plan.edge_w[0]
    f = 8
    table = jnp.asarray(rng.standard_normal((plan.b + plan.r, f)), jnp.float32)
    want = np.asarray(spmm_local(
        jnp.asarray(ed), jnp.asarray(es), jnp.asarray(ew), table, plan.b))
    tb = 16
    tsrc, tld, tw, padded = build_dst_tiles(ed, es, ew, plan.b, tb=tb)
    got = np.asarray(spmm_pallas(
        jnp.asarray(tsrc), jnp.asarray(tld), jnp.asarray(tw), table,
        tb=tb, interpret=True))[: plan.b]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_pallas_partitioned_blocks(ahat):
    """Kernel also serves per-chip blocks (table = [local; halo])."""
    n = ahat.shape[0]
    rng = np.random.default_rng(1)
    pv = balanced_random_partition(n, 4, seed=2)
    plan = build_comm_plan(ahat, pv, 4)
    f = 8
    for p in range(4):
        table = jnp.asarray(
            rng.standard_normal((plan.b + plan.r, f)), jnp.float32)
        want = np.asarray(spmm_local(
            jnp.asarray(plan.edge_dst[p]), jnp.asarray(plan.edge_src[p]),
            jnp.asarray(plan.edge_w[p]), table, plan.b))
        tsrc, tld, tw, _ = build_dst_tiles(
            plan.edge_dst[p], plan.edge_src[p], plan.edge_w[p], plan.b, tb=8)
        got = np.asarray(spmm_pallas(
            jnp.asarray(tsrc), jnp.asarray(tld), jnp.asarray(tw), table,
            tb=8, interpret=True))[: plan.b]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
