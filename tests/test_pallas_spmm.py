"""Pallas SpMM kernel vs the default XLA path (interpret mode on CPU)."""

import numpy as np
import jax.numpy as jnp

from sgcn_tpu.ops import spmm_local
from sgcn_tpu.ops.pallas_spmm import build_dst_tiles, spmm_pallas
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition


def test_build_dst_tiles_roundtrip(ahat):
    n = ahat.shape[0]
    plan = build_comm_plan(ahat, np.zeros(n, dtype=np.int64), 1)
    ed, es, ew = plan.edge_dst[0], plan.edge_src[0], plan.edge_w[0]
    tsrc, tld, tw, padded = build_dst_tiles(ed, es, ew, plan.b, tb=16)
    assert padded % 16 == 0
    # every real edge appears exactly once with its weight (pads are 0)
    np.testing.assert_allclose(np.sort(tw[tw != 0]), np.sort(ew[ew != 0]),
                               rtol=0, atol=0)


def test_pallas_matches_xla(ahat):
    n = ahat.shape[0]
    rng = np.random.default_rng(0)
    plan = build_comm_plan(ahat, np.zeros(n, dtype=np.int64), 1)
    ed, es, ew = plan.edge_dst[0], plan.edge_src[0], plan.edge_w[0]
    f = 8
    table = jnp.asarray(rng.standard_normal((plan.b + plan.r, f)), jnp.float32)
    want = np.asarray(spmm_local(
        jnp.asarray(ed), jnp.asarray(es), jnp.asarray(ew), table, plan.b))
    tb = 16
    tsrc, tld, tw, padded = build_dst_tiles(ed, es, ew, plan.b, tb=tb)
    got = np.asarray(spmm_pallas(
        jnp.asarray(tsrc), jnp.asarray(tld), jnp.asarray(tw), table,
        tb=tb, interpret=True))[: plan.b]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_pallas_partitioned_blocks(ahat):
    """Kernel also serves per-chip blocks (table = [local; halo])."""
    n = ahat.shape[0]
    rng = np.random.default_rng(1)
    pv = balanced_random_partition(n, 4, seed=2)
    plan = build_comm_plan(ahat, pv, 4)
    f = 8
    for p in range(4):
        table = jnp.asarray(
            rng.standard_normal((plan.b + plan.r, f)), jnp.float32)
        want = np.asarray(spmm_local(
            jnp.asarray(plan.edge_dst[p]), jnp.asarray(plan.edge_src[p]),
            jnp.asarray(plan.edge_w[p]), table, plan.b))
        tsrc, tld, tw, _ = build_dst_tiles(
            plan.edge_dst[p], plan.edge_src[p], plan.edge_w[p], plan.b, tb=8)
        got = np.asarray(spmm_pallas(
            jnp.asarray(tsrc), jnp.asarray(tld), jnp.asarray(tw), table,
            tb=8, interpret=True))[: plan.b]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def _build_dst_tiles_reference(edge_dst, edge_src, edge_w, num_rows, tb):
    """The ORIGINAL per-tile Python-loop builder, kept verbatim as the
    equality oracle for the vectorized ``build_dst_tiles`` (ISSUE-15
    satellite: the O(T) interpreted loop was replaced by sliced numpy
    assignment; output must be bit-identical)."""
    edge_dst = np.asarray(edge_dst)
    edge_src = np.asarray(edge_src)
    edge_w = np.asarray(edge_w)
    t = -(-num_rows // tb)
    tile_of_edge = edge_dst // tb
    counts = np.bincount(tile_of_edge, minlength=t)
    emax = max(8, int(counts.max()))
    emax = -(-emax // 8) * 8
    tsrc = np.zeros((t, emax), np.int32)
    tw = np.zeros((t, emax), np.float32)
    tld = np.full((t, emax), tb - 1, np.int32)
    starts = np.zeros(t + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    for i in range(t):
        s, e = starts[i], starts[i + 1]
        c = e - s
        tsrc[i, :c] = edge_src[s:e]
        tw[i, :c] = edge_w[s:e]
        tld[i, :c] = edge_dst[s:e] - i * tb
    return tsrc, tld, tw, t * tb


def test_vectorized_build_dst_tiles_matches_old_loop(ahat):
    """Satellite pin: the vectorized builder's output equals the old
    per-tile loop's EXACTLY (same pads, same slot order) on a real plan's
    edge families, across tile sizes."""
    n = ahat.shape[0]
    pv = balanced_random_partition(n, 4, seed=2)
    plan = build_comm_plan(ahat, pv, 4)
    for p in range(4):
        for dst, src, w in ((plan.ledge_dst[p], plan.ledge_src[p],
                             plan.ledge_w[p]),
                            (plan.hedge_dst[p], plan.hedge_src[p],
                             plan.hedge_w[p])):
            for tb in (8, 16, 64):
                want = _build_dst_tiles_reference(dst, src, w, plan.b, tb)
                got = build_dst_tiles(dst, src, w, plan.b, tb=tb)
                for a, b in zip(got, want[:3]):
                    np.testing.assert_array_equal(a, b)
                assert got[3] == want[3]


class _FitsPlan:
    """Minimal plan stub for the VMEM budget rule."""

    def __init__(self, b, r):
        self.b, self.r = b, r
        self.rr_sizes = None
        self.symmetric = True

    def ragged_round_sizes(self):
        raise ValueError("stub has no square counts")


def test_pallas_fits_itemsize_boundary(monkeypatch):
    """Satellite: the VMEM budget check is itemsize-aware — the old
    hard-coded 4 B/elem charged bf16 compute_dtype tables DOUBLE.  Pin
    both dtypes exactly at the budget boundary."""
    from sgcn_tpu.ops.pallas_spmm import pallas_spmm_fits

    b, r, fmax = 100, 80, 32
    plan = _FitsPlan(b, r)
    # f32: budget exactly b·fmax·4 on the larger (local) table → fits;
    # one byte less → does not
    monkeypatch.setenv("SGCN_PALLAS_VMEM", str(b * fmax * 4))
    assert pallas_spmm_fits(plan, fmax, [8])
    monkeypatch.setenv("SGCN_PALLAS_VMEM", str(b * fmax * 4 - 1))
    assert not pallas_spmm_fits(plan, fmax, [8])
    # bf16: the same boundary sits at 2 B/elem — the old check refused it
    monkeypatch.setenv("SGCN_PALLAS_VMEM", str(b * fmax * 2))
    assert pallas_spmm_fits(plan, fmax, [8], compute_dtype="bfloat16")
    assert not pallas_spmm_fits(plan, fmax, [8])     # f32 needs 2×
    monkeypatch.setenv("SGCN_PALLAS_VMEM", str(b * fmax * 2 - 1))
    assert not pallas_spmm_fits(plan, fmax, [8], compute_dtype="bfloat16")


def test_pallas_fits_gat_and_ragged_tables(monkeypatch):
    """The fits rule charges the GAT combined (B+R)·(fout+1) table and,
    on the ragged schedule, the ring concat's ΣS_d height instead of the
    dense halo pad."""
    from sgcn_tpu.ops.pallas_spmm import pallas_spmm_fits

    plan = _FitsPlan(100, 80)
    widths = [15]                                    # fout+1 = 16 lanes
    need = (plan.b + plan.r) * 16 * 4
    monkeypatch.setenv("SGCN_PALLAS_VMEM", str(need))
    assert pallas_spmm_fits(plan, 8, widths, model="gat")
    monkeypatch.setenv("SGCN_PALLAS_VMEM", str(need - 1))
    assert not pallas_spmm_fits(plan, 8, widths, model="gat")
    # ragged: a pre-built ring larger than r must be charged
    plan.rr_sizes = (200, 0, 40)
    fmax = 32
    monkeypatch.setenv("SGCN_PALLAS_VMEM", str(240 * fmax * 4 - 1))
    assert not pallas_spmm_fits(plan, fmax, [8], schedule="ragged")
    monkeypatch.setenv("SGCN_PALLAS_VMEM", str(240 * fmax * 4))
    assert pallas_spmm_fits(plan, fmax, [8], schedule="ragged")


def test_tile_classes_cover_and_align():
    """Class structure: covers every tile, aligns to bucket row boundaries
    rounded to tiles, collapses to one class for a flat histogram."""
    from sgcn_tpu.ops.pallas_spmm import tile_classes_from_buckets

    assert tile_classes_from_buckets(((64, 4),), 64, 16) == (4,)
    assert tile_classes_from_buckets(((16, 28), (48, 2)), 64, 16) == (1, 3)
    assert tile_classes_from_buckets(None, 100, 16) == (7,)
    # boundaries inside a tile round UP, never split a tile
    ct = tile_classes_from_buckets(((10, 9), (54, 2)), 64, 16)
    assert sum(ct) == 4 and all(c > 0 for c in ct)


def test_trainer_plan_driven_pallas_parity(ahat, monkeypatch):
    """Plan-driven kernel choice (VERDICT r3 #9): with SGCN_PALLAS_SPMM=1
    the symmetric GCN trainer must auto-select the VMEM Pallas aggregator
    (per-chip tables fit the budget at this size) and train to the SAME
    losses and predictions as the default ELL path."""
    from sgcn_tpu.ops.pallas_spmm import PALLAS_PLAN_FIELDS, use_pallas_spmm
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    n = ahat.shape[0]
    k, fin, widths = 4, 12, [8, 4]
    pv = balanced_random_partition(n, k, seed=5)
    plan = build_comm_plan(ahat, pv, k)
    assert plan.symmetric
    rng = np.random.default_rng(3)
    feats = rng.standard_normal((n, fin)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)

    def run():
        tr = FullBatchTrainer(plan, fin=fin, widths=widths, seed=2)
        data = make_train_data(plan, feats, labels)
        losses = [tr.step(data) for _ in range(4)]
        return tr, losses, tr.predict(data)

    monkeypatch.setenv("SGCN_PALLAS_SPMM", "0")
    _, losses_ell, pred_ell = run()

    monkeypatch.setenv("SGCN_PALLAS_SPMM", "1")
    assert use_pallas_spmm(plan, fin, widths)
    tr_p, losses_pal, pred_pal = run()
    assert tr_p.plan_fields == PALLAS_PLAN_FIELDS     # choice actually taken
    np.testing.assert_allclose(losses_pal, losses_ell, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(pred_pal, pred_ell, rtol=1e-3, atol=1e-4)


def test_minibatch_shared_step_never_resolves_pallas(ahat, monkeypatch):
    """The mini-batch trainer's ONE compiled step serves EVERY per-batch
    plan, but the Pallas tile layout is per-plan (per-class Emax_c statics,
    ptile_* arrays built by ensure_pallas_tiles on plans[0] only) — so the
    shared envelope must stay on the slot-pass/ELL aggregators even when
    the VMEM rule would fire (allow_pallas=False through
    resolve_forward_setup).  Before the guard, batch 1's step crashed
    stacking the never-built ptile_* arrays of its plan."""
    from sgcn_tpu.ops.pallas_spmm import use_pallas_spmm
    from sgcn_tpu.train.minibatch import MiniBatchTrainer

    n = ahat.shape[0]
    k, fin, widths = 4, 12, [8, 4]
    pv = balanced_random_partition(n, k, seed=5)
    monkeypatch.setenv("SGCN_PALLAS_SPMM", "1")
    # non-vacuous: the full-batch rule WOULD fire at this size
    assert use_pallas_spmm(build_comm_plan(ahat, pv, k), fin, widths)

    mb = MiniBatchTrainer(ahat, pv, k, fin=fin, widths=widths,
                          batch_size=n // 2, nbatches=2)
    assert not any(f.startswith("ptile_") for f in mb.inner.plan_fields)
    rng = np.random.default_rng(3)
    feats = rng.standard_normal((n, fin)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    batches = mb.make_batches(feats, labels)
    assert len(batches) == 2
    for b in batches:                  # batch != 0 was the crash scenario
        assert np.isfinite(mb.step(b))


def test_gat_pallas_mask_tiles_ship_int8(ahat, monkeypatch):
    """ship_arrays narrows the GAT 0/1 mask tiles (ptile_cw) to int8 like
    cell_w/ctail_w — the padded f32 tile form is real per-chip argument
    bytes at products scale; gat_pallas_pass upcasts in-program."""
    from sgcn_tpu.train.fullbatch import resolve_forward_setup

    n = ahat.shape[0]
    k, fin, widths = 4, 12, [8, 4]
    pv = balanced_random_partition(n, k, seed=5)
    plan = build_comm_plan(ahat, pv, k)
    monkeypatch.setenv("SGCN_PALLAS_SPMM", "1")
    setup = resolve_forward_setup(plan, fin, widths, model="gat",
                                  comm_schedule="a2a")
    assert "ptile_cw" in setup.plan_fields
    arrays = setup.ship_arrays(plan)
    assert arrays["ptile_cw"].dtype == np.int8
    assert set(np.unique(arrays["ptile_cw"])) <= {0, 1}
