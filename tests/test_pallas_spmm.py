"""Pallas SpMM kernel vs the default XLA path (interpret mode on CPU)."""

import numpy as np
import jax.numpy as jnp

from sgcn_tpu.ops import spmm_local
from sgcn_tpu.ops.pallas_spmm import build_dst_tiles, spmm_pallas
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition


def test_build_dst_tiles_roundtrip(ahat):
    n = ahat.shape[0]
    plan = build_comm_plan(ahat, np.zeros(n, dtype=np.int64), 1)
    ed, es, ew = plan.edge_dst[0], plan.edge_src[0], plan.edge_w[0]
    tsrc, tld, tw, padded = build_dst_tiles(ed, es, ew, plan.b, tb=16)
    assert padded % 16 == 0
    # every real edge appears exactly once with its weight (pads are 0)
    np.testing.assert_allclose(np.sort(tw[tw != 0]), np.sort(ew[ew != 0]),
                               rtol=0, atol=0)


def test_pallas_matches_xla(ahat):
    n = ahat.shape[0]
    rng = np.random.default_rng(0)
    plan = build_comm_plan(ahat, np.zeros(n, dtype=np.int64), 1)
    ed, es, ew = plan.edge_dst[0], plan.edge_src[0], plan.edge_w[0]
    f = 8
    table = jnp.asarray(rng.standard_normal((plan.b + plan.r, f)), jnp.float32)
    want = np.asarray(spmm_local(
        jnp.asarray(ed), jnp.asarray(es), jnp.asarray(ew), table, plan.b))
    tb = 16
    tsrc, tld, tw, padded = build_dst_tiles(ed, es, ew, plan.b, tb=tb)
    got = np.asarray(spmm_pallas(
        jnp.asarray(tsrc), jnp.asarray(tld), jnp.asarray(tw), table,
        tb=tb, interpret=True))[: plan.b]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_pallas_partitioned_blocks(ahat):
    """Kernel also serves per-chip blocks (table = [local; halo])."""
    n = ahat.shape[0]
    rng = np.random.default_rng(1)
    pv = balanced_random_partition(n, 4, seed=2)
    plan = build_comm_plan(ahat, pv, 4)
    f = 8
    for p in range(4):
        table = jnp.asarray(
            rng.standard_normal((plan.b + plan.r, f)), jnp.float32)
        want = np.asarray(spmm_local(
            jnp.asarray(plan.edge_dst[p]), jnp.asarray(plan.edge_src[p]),
            jnp.asarray(plan.edge_w[p]), table, plan.b))
        tsrc, tld, tw, _ = build_dst_tiles(
            plan.edge_dst[p], plan.edge_src[p], plan.edge_w[p], plan.b, tb=8)
        got = np.asarray(spmm_pallas(
            jnp.asarray(tsrc), jnp.asarray(tld), jnp.asarray(tw), table,
            tb=8, interpret=True))[: plan.b]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_trainer_plan_driven_pallas_parity(ahat, monkeypatch):
    """Plan-driven kernel choice (VERDICT r3 #9): with SGCN_PALLAS_SPMM=1
    the symmetric GCN trainer must auto-select the VMEM Pallas aggregator
    (per-chip tables fit the budget at this size) and train to the SAME
    losses and predictions as the default ELL path."""
    from sgcn_tpu.ops.pallas_spmm import PALLAS_PLAN_FIELDS, use_pallas_spmm
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    n = ahat.shape[0]
    k, fin, widths = 4, 12, [8, 4]
    pv = balanced_random_partition(n, k, seed=5)
    plan = build_comm_plan(ahat, pv, k)
    assert plan.symmetric
    rng = np.random.default_rng(3)
    feats = rng.standard_normal((n, fin)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)

    def run():
        tr = FullBatchTrainer(plan, fin=fin, widths=widths, seed=2)
        data = make_train_data(plan, feats, labels)
        losses = [tr.step(data) for _ in range(4)]
        return tr, losses, tr.predict(data)

    monkeypatch.setenv("SGCN_PALLAS_SPMM", "0")
    _, losses_ell, pred_ell = run()

    monkeypatch.setenv("SGCN_PALLAS_SPMM", "1")
    assert use_pallas_spmm(plan, fin, widths)
    tr_p, losses_pal, pred_pal = run()
    assert tr_p.plan_fields == PALLAS_PLAN_FIELDS     # choice actually taken
    np.testing.assert_allclose(losses_pal, losses_ell, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(pred_pal, pred_ell, rtol=1e-3, atol=1e-4)
