"""Suite-hygiene lint: expensive tests must be slow-marked or budgeted.

The tier-1 run executes under ONE external timeout (ROADMAP.md); the seed
regressed to rc=124 because unmarked expensive tests ate it silently.  Two
mechanisms now guard that, and this module asserts both exist and bite:

  * **static half** (here): every test module that spawns subprocess
    meshes — re-execing Python with a forced device count, multi-process
    rendezvous, trainer-CLI children — must either carry a
    ``@pytest.mark.slow`` marking for its expensive tests or appear in the
    explicit tier-1 budget allowlist below WITH a justification.  A new
    subprocess-spawning module therefore forces a conscious decision at
    review time instead of a silent timeout at driver time.
  * **runtime half** (``conftest.pytest_runtest_makereport``): any unmarked
    test whose call phase overruns the per-test budget is turned into a
    failure naming the fix.
"""

import ast
import os
import re

import conftest

TESTS = os.path.dirname(os.path.abspath(__file__))

# Modules that spawn subprocesses yet legitimately run in the tier-1 budget:
# each entry records WHY (the measured cost under the 870 s tier-1 budget at
# the time it was added).  Adding a module here is a reviewed decision —
# that is the point of the lint.
SUBPROCESS_BUDGET_ALLOWLIST = {
    "test_cli.py": "end-to-end file-pipeline CLIs on a 150-vertex graph; "
                   "~10 children, each seconds on the forced-CPU backend, "
                   "plus the sgcn_tpu.analysis --fast smoke (2-mode HLO "
                   "subset, ~15 s)",
    "test_multihost.py": "2-process x 4-vdev rendezvous on a 48-vertex "
                         "graph — the only multi-process coverage tier-1 has",
    "test_import_ogb.py": "offline importer script on a tiny synthetic "
                          "snapshot; no mesh, no training",
    "test_real_datasets.py": "k=4 CLI train on the committed cora fixture "
                             "(k=8 variant IS slow-marked)",
    "test_metrics_cli.py": "two trainer children on the small cora fixture "
                           "(--metrics-out + --profile telemetry smoke, and "
                           "the ragged-schedule wire-reconciliation smoke; "
                           "~50 s together)",
    "test_validate_bench.py": "two validate_bench.py CLI children — pure "
                              "stdlib JSON checks, sub-second, no jax",
    "test_bench_trend.py": "three bench_trend.py CLI children — pure "
                           "stdlib JSON trend checks, sub-second, no jax",
    "test_serve.py": "one serve-CLI child + one obs_report render on the "
                     "small cora fixture (closed-loop micro-batch smoke, "
                     "24 queries, one compiled bucket; ~1 min)",
    "test_resilience.py": "the PR-13 crash-resume acceptance matrix: 9 "
                          "kill/corrupt + resume triples (3 trainer-CLI "
                          "children each) on the cora graph fixture with "
                          "the SYNTHETIC f=16 feature harness (narrow "
                          "features keep each child ~5 s) plus one "
                          "obs_report render — the bit-identity contract "
                          "is only provable by killing REAL subprocess "
                          "runs (docs/resilience.md); whole module "
                          "measured 127 s at PR-13 (ROADMAP budget note "
                          "re-measured accordingly)",
}

# Modules that run the static-analysis MATRIX auditor
# (sgcn_tpu.analysis.hlo_audit.run_audit — a full run lowers every
# supported mode's real program, ~75 s at HEAD and growing with the
# matrix): same reviewed-budget contract as the subprocess allowlist.  A
# single one-program .lower() is cheap and not gated; the matrix sweep is
# the class that can silently eat the tier-1 budget as modes are added.
MATRIX_AUDIT_BUDGET_ALLOWLIST = {
    "test_analysis.py": "ONE module-scoped full-matrix run (~130 s at "
                        "PR-15 HEAD, 48 mode entries incl. the eight "
                        "pallas modes, lowering only — no "
                        "compile/execute) shared by every matrix "
                        "assertion, plus per-mode mutation audits "
                        "(~2-4 s each)",
    "test_cli.py": "the analysis CLI smoke child runs --fast (2 modes), "
                   "never the full matrix",
    "test_memory_obs.py": "ONE module-scoped COMPILE sweep over the "
                          "8-mode representative slice (~30 s at HEAD — "
                          "one mode per array family the footprint model "
                          "itemizes) shared by every reconciliation "
                          "assertion; the full 48-mode compile matrix "
                          "(run_memory_audit, ~3 min) is slow-marked",
}

# matches ANY invocation of the auditor — in-process (run_audit, or its
# compiling sibling run_memory_audit/memory_audit_mode, ISSUE 18 — that
# one COMPILES every program, strictly pricier than lowering) or the
# CLI in either flavor: a full-matrix CLI child is exactly the expensive
# case this lint exists to catch, so --fast must NOT be required to match
# (the allowlist notes say which flavor each entry is budgeted for).  The
# lookahead excludes plain SUBMODULE imports (sgcn_tpu.analysis.registry
# etc. — cheap, no audit); naming the package itself (the `-m` CLI form
# or a package import) still matches.
_MATRIX_AUDIT_RE = re.compile(
    r"run_(memory_)?audit\(|memory_audit_mode\(|sgcn_tpu\.analysis(?![.\w])")

_SPAWN_RE = re.compile(
    r"subprocess\.(run|Popen|check_output|check_call)"
    r"|dryrun_multichip\(|_run_vdev_child\(")


def _module_matches(path: str, pattern: re.Pattern) -> bool:
    with open(path) as fh:
        return bool(pattern.search(fh.read()))


def _module_has_slow_marker(path: str) -> bool:
    with open(path) as fh:
        src = fh.read()
    return "mark.slow" in src


def _budget_lint_offenders(pattern: re.Pattern, allowlist: dict) -> list:
    """ONE implementation of the budget lint walk (subprocess meshes AND
    matrix-audit sweeps ride it): modules matching ``pattern`` must be
    slow-marked or allowlisted.  This module itself is excluded — it NAMES
    the patterns."""
    offenders = []
    for name in sorted(os.listdir(TESTS)):
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        if name == os.path.basename(__file__):
            continue
        path = os.path.join(TESTS, name)
        if not _module_matches(path, pattern):
            continue
        if name in allowlist:
            continue
        if _module_has_slow_marker(path):
            continue
        offenders.append(name)
    return offenders


def _assert_allowlist_live(pattern: re.Pattern, allowlist: dict,
                           what: str) -> None:
    """A stale allowlist is its own hygiene failure: every entry must name
    a live module that still matches (else the entry is dead weight
    masking future regressions)."""
    for name in allowlist:
        path = os.path.join(TESTS, name)
        assert os.path.exists(path), f"allowlisted {name} no longer exists"
        assert _module_matches(path, pattern), (
            f"allowlisted {name} no longer {what} — drop the entry")


def test_subprocess_mesh_tests_are_slow_marked_or_budgeted():
    offenders = _budget_lint_offenders(_SPAWN_RE,
                                       SUBPROCESS_BUDGET_ALLOWLIST)
    assert not offenders, (
        f"test modules {offenders} spawn subprocess meshes but carry no "
        "@pytest.mark.slow and are not in SUBPROCESS_BUDGET_ALLOWLIST — "
        "mark the expensive tests slow, or allowlist the module here WITH "
        "a measured tier-1 budget justification")


def test_matrix_audit_tests_are_slow_marked_or_budgeted():
    """The PR-9 extension of this lint: a module invoking the mode-matrix
    auditor carries a slow mark or a reviewed budget justification — the
    audit's cost scales with the supported matrix, so a new audit-driven
    test is a conscious budget decision exactly like a subprocess mesh."""
    offenders = _budget_lint_offenders(_MATRIX_AUDIT_RE,
                                       MATRIX_AUDIT_BUDGET_ALLOWLIST)
    assert not offenders, (
        f"test modules {offenders} run the static-analysis matrix auditor "
        "but carry no @pytest.mark.slow and are not in "
        "MATRIX_AUDIT_BUDGET_ALLOWLIST — the matrix sweep's cost grows "
        "with every supported mode; budget it consciously")


def test_matrix_audit_allowlist_entries_exist_and_audit():
    _assert_allowlist_live(_MATRIX_AUDIT_RE, MATRIX_AUDIT_BUDGET_ALLOWLIST,
                           "runs the matrix auditor")


def test_allowlist_entries_exist_and_spawn():
    _assert_allowlist_live(_SPAWN_RE, SUBPROCESS_BUDGET_ALLOWLIST,
                           "spawns subprocesses")


def test_runtime_budget_hook_active():
    """The conftest per-test wall-clock tripwire exists, has a sane default,
    and is wired as a hookwrapper (the runtime half of this lint)."""
    assert conftest.TIER1_PER_TEST_BUDGET_S > 0
    assert conftest.TIER1_PER_TEST_BUDGET_S <= 870, (
        "per-test budget exceeds the whole tier-1 suite budget")
    hook = conftest.pytest_runtest_makereport
    # pluggy attaches the hookimpl opts dict to the function; a plain
    # function here means the @pytest.hookimpl(hookwrapper=True) decorator
    # was dropped and the tripwire silently stopped firing
    opts = None
    for attr in dir(hook):
        v = getattr(hook, attr, None)
        if isinstance(v, dict) and ("hookwrapper" in v or "wrapper" in v):
            opts = v
            break
    assert opts is not None and (opts.get("hookwrapper")
                                 or opts.get("wrapper")), (
        "pytest_runtest_makereport lost its hookimpl(hookwrapper=True) "
        "registration")


def test_every_slow_marker_is_collectable():
    """Slow markers must parse as real pytest marks (a typo'd marker would
    silently run the expensive test in tier-1): every module using
    ``mark.slow`` must import pytest and apply it via pytestmark, a
    decorator, or pytest.param marks."""
    for name in sorted(os.listdir(TESTS)):
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        if name == os.path.basename(__file__):
            continue                    # this module NAMES the marker in prose
        path = os.path.join(TESTS, name)
        with open(path) as fh:
            src = fh.read()
        if "mark.slow" not in src:
            continue
        tree = ast.parse(src)
        imports = {a.name for node in ast.walk(tree)
                   if isinstance(node, ast.Import) for a in node.names}
        assert "pytest" in imports, (
            f"{name} uses mark.slow without importing pytest")
