"""Suite-hygiene lint: expensive tests must be slow-marked or budgeted.

The tier-1 run executes under ONE external timeout (ROADMAP.md); the seed
regressed to rc=124 because unmarked expensive tests ate it silently.  Two
mechanisms now guard that, and this module asserts both exist and bite:

  * **static half** (here): every test module that spawns subprocess
    meshes — re-execing Python with a forced device count, multi-process
    rendezvous, trainer-CLI children — must either carry a
    ``@pytest.mark.slow`` marking for its expensive tests or appear in the
    explicit tier-1 budget allowlist below WITH a justification.  A new
    subprocess-spawning module therefore forces a conscious decision at
    review time instead of a silent timeout at driver time.
  * **runtime half** (``conftest.pytest_runtest_makereport``): any unmarked
    test whose call phase overruns the per-test budget is turned into a
    failure naming the fix.
"""

import ast
import os
import re

import conftest

TESTS = os.path.dirname(os.path.abspath(__file__))

# Modules that spawn subprocesses yet legitimately run in the tier-1 budget:
# each entry records WHY (the measured cost under the 870 s tier-1 budget at
# the time it was added).  Adding a module here is a reviewed decision —
# that is the point of the lint.
SUBPROCESS_BUDGET_ALLOWLIST = {
    "test_cli.py": "end-to-end file-pipeline CLIs on a 150-vertex graph; "
                   "~10 children, each seconds on the forced-CPU backend",
    "test_multihost.py": "2-process x 4-vdev rendezvous on a 48-vertex "
                         "graph — the only multi-process coverage tier-1 has",
    "test_import_ogb.py": "offline importer script on a tiny synthetic "
                          "snapshot; no mesh, no training",
    "test_real_datasets.py": "k=4 CLI train on the committed cora fixture "
                             "(k=8 variant IS slow-marked)",
    "test_metrics_cli.py": "two trainer children on the small cora fixture "
                           "(--metrics-out + --profile telemetry smoke, and "
                           "the ragged-schedule wire-reconciliation smoke; "
                           "~50 s together)",
    "test_validate_bench.py": "two validate_bench.py CLI children — pure "
                              "stdlib JSON checks, sub-second, no jax",
    "test_bench_trend.py": "three bench_trend.py CLI children — pure "
                           "stdlib JSON trend checks, sub-second, no jax",
    "test_serve.py": "one serve-CLI child + one obs_report render on the "
                     "small cora fixture (closed-loop micro-batch smoke, "
                     "24 queries, one compiled bucket; ~1 min)",
}

_SPAWN_RE = re.compile(
    r"subprocess\.(run|Popen|check_output|check_call)"
    r"|dryrun_multichip\(|_run_vdev_child\(")


def _module_spawns_subprocesses(path: str) -> bool:
    with open(path) as fh:
        src = fh.read()
    return bool(_SPAWN_RE.search(src))


def _module_has_slow_marker(path: str) -> bool:
    with open(path) as fh:
        src = fh.read()
    return "mark.slow" in src


def test_subprocess_mesh_tests_are_slow_marked_or_budgeted():
    offenders = []
    for name in sorted(os.listdir(TESTS)):
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        path = os.path.join(TESTS, name)
        if not _module_spawns_subprocesses(path):
            continue
        if name in SUBPROCESS_BUDGET_ALLOWLIST:
            continue
        if _module_has_slow_marker(path):
            continue
        offenders.append(name)
    assert not offenders, (
        f"test modules {offenders} spawn subprocess meshes but carry no "
        "@pytest.mark.slow and are not in SUBPROCESS_BUDGET_ALLOWLIST — "
        "mark the expensive tests slow, or allowlist the module here WITH "
        "a measured tier-1 budget justification")


def test_allowlist_entries_exist_and_spawn():
    """A stale allowlist is its own hygiene failure: every entry must name a
    live module that still spawns subprocesses (else the entry is dead
    weight masking future regressions)."""
    for name in SUBPROCESS_BUDGET_ALLOWLIST:
        path = os.path.join(TESTS, name)
        assert os.path.exists(path), f"allowlisted {name} no longer exists"
        assert _module_spawns_subprocesses(path), (
            f"allowlisted {name} no longer spawns subprocesses — drop the "
            "entry")


def test_runtime_budget_hook_active():
    """The conftest per-test wall-clock tripwire exists, has a sane default,
    and is wired as a hookwrapper (the runtime half of this lint)."""
    assert conftest.TIER1_PER_TEST_BUDGET_S > 0
    assert conftest.TIER1_PER_TEST_BUDGET_S <= 870, (
        "per-test budget exceeds the whole tier-1 suite budget")
    hook = conftest.pytest_runtest_makereport
    # pluggy attaches the hookimpl opts dict to the function; a plain
    # function here means the @pytest.hookimpl(hookwrapper=True) decorator
    # was dropped and the tripwire silently stopped firing
    opts = None
    for attr in dir(hook):
        v = getattr(hook, attr, None)
        if isinstance(v, dict) and ("hookwrapper" in v or "wrapper" in v):
            opts = v
            break
    assert opts is not None and (opts.get("hookwrapper")
                                 or opts.get("wrapper")), (
        "pytest_runtest_makereport lost its hookimpl(hookwrapper=True) "
        "registration")


def test_every_slow_marker_is_collectable():
    """Slow markers must parse as real pytest marks (a typo'd marker would
    silently run the expensive test in tier-1): every module using
    ``mark.slow`` must import pytest and apply it via pytestmark, a
    decorator, or pytest.param marks."""
    for name in sorted(os.listdir(TESTS)):
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        if name == os.path.basename(__file__):
            continue                    # this module NAMES the marker in prose
        path = os.path.join(TESTS, name)
        with open(path) as fh:
            src = fh.read()
        if "mark.slow" not in src:
            continue
        tree = ast.parse(src)
        imports = {a.name for node in ast.walk(tree)
                   if isinstance(node, ast.Import) for a in node.names}
        assert "pytest" in imports, (
            f"{name} uses mark.slow without importing pytest")
