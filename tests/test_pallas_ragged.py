"""Schedule-agnostic Pallas aggregation (ISSUE 15): ragged fold fused into
the VMEM kernel, GAT slot-pass kernels, degree-binned kernel dispatch.

Acceptance contracts pinned here:

  * ragged-pallas trains f32-BIT-identically (``==``) to a2a-pallas on the
    cora fixture for GCN and GAT (same tile fold order — the halo tiles
    read the ring's receive concat at plan-re-based positions);
  * the pallas family stays allclose-pinned against the ELL slot-pass
    path;
  * the ragged-pallas step program passes the new ``halo-materialization``
    audit rule (per-live-round permutes, NO (R, f) halo-table scatter) and
    the rule is NON-vacuous: a seeded program that assembles the HBM halo
    table first fails it (the PR-10 mutation-check norm);
  * the degree-binned per-bucket kernel choice (hub classes fall back to
    the XLA gather form past the serial-chain cap) lands in the decision
    log and preserves parity.
"""

import os
from unittest import mock

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from sgcn_tpu.io.datasets import load_npz_dataset
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.partition.emit import read_partvec
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")
WIDTHS = [16, 7]


@pytest.fixture(scope="module")
def cora8():
    """The 8-vdev cora fixture of the acceptance criteria: real cora under
    its checked-in 8-part hp partition vector."""
    a, feats, labels = load_npz_dataset(os.path.join(FIX, "cora2708.npz"))
    ahat = normalize_adjacency(a)
    pv = read_partvec(os.path.join(FIX, "cora2708.8.hp"))
    plan = build_comm_plan(ahat, pv, 8)
    assert plan.symmetric
    return plan, feats.astype(np.float32), labels.astype(np.int32)


@pytest.fixture(autouse=True)
def _force_pallas_budget(monkeypatch):
    """Every test here FORCES the kernel family where it asks for it; the
    VMEM budget is raised so the cora tables (fin=1433 conservative fmax)
    always fit — the budget rule itself is unit-tested in
    test_pallas_spmm."""
    monkeypatch.setenv("SGCN_PALLAS_VMEM", str(64 * 1024 * 1024))


def _train(plan, feats, labels, model, schedule, steps=3, widths=None,
           **kw):
    tr = FullBatchTrainer(plan, fin=feats.shape[1],
                          widths=list(widths or WIDTHS), seed=3,
                          model=model, comm_schedule=schedule, **kw)
    data = make_train_data(plan, feats, labels)
    losses = np.asarray([tr.step(data) for _ in range(steps)], np.float64)
    params = [np.asarray(x) for x in jax.tree.leaves(
        jax.tree.map(np.asarray, tr.params))]
    return tr, losses, params


def _assert_bit_equal(la, pa, lb, pb):
    np.testing.assert_array_equal(la, lb)
    for x, y in zip(pa, pb):
        np.testing.assert_array_equal(x, y)


@pytest.mark.parametrize("halo_dtype", [None, "bfloat16"],
                         ids=["f32", "bf16wire"])
def test_gcn_ragged_pallas_bit_identical_to_a2a(cora8, monkeypatch,
                                                halo_dtype):
    """ACCEPTANCE: --comm-schedule ragged with the Pallas aggregator
    constructs and trains, f32-bit-identical (==) to a2a-pallas on cora —
    the fastest kernel and the leanest wire compose, at both wire
    dtypes."""
    plan, feats, labels = cora8
    monkeypatch.setenv("SGCN_PALLAS_SPMM", "1")
    tra, la, pa = _train(plan, feats, labels, "gcn", "a2a",
                         halo_dtype=halo_dtype)
    trr, lr, pr = _train(plan, feats, labels, "gcn", "ragged",
                         halo_dtype=halo_dtype)
    assert "pallas_tb" in tra._fwd_static
    assert "pallas_tb" in trr._fwd_static
    from sgcn_tpu.ops.pallas_spmm import (PALLAS_PLAN_FIELDS,
                                          PALLAS_PLAN_FIELDS_RAGGED)
    assert tra.plan_fields == PALLAS_PLAN_FIELDS
    assert trr.plan_fields == PALLAS_PLAN_FIELDS_RAGGED
    _assert_bit_equal(la, pa, lr, pr)


@pytest.mark.parametrize("form_env", ["1", pytest.param(
    "0", marks=pytest.mark.slow)], ids=["fused", "split"])
def test_gat_ragged_pallas_bit_identical_to_a2a(cora8, monkeypatch,
                                                form_env):
    """ACCEPTANCE (GAT half): the attention slot passes ride the VMEM
    kernel on both transports, bit-identically — fused (fout+1) table in
    tier-1, the split pair in the full suite."""
    plan, feats, labels = cora8
    monkeypatch.setenv("SGCN_PALLAS_SPMM", "1")
    monkeypatch.setenv("SGCN_GAT_FUSED", form_env)
    kw = {"activation": "none"}
    tra, la, pa = _train(plan, feats, labels, "gat", "a2a", **kw)
    trr, lr, pr = _train(plan, feats, labels, "gat", "ragged", **kw)
    assert "pallas_tb" in tra._fwd_static
    from sgcn_tpu.models.gat import (GAT_PLAN_FIELDS_PALLAS,
                                     GAT_PLAN_FIELDS_PALLAS_RAGGED)
    assert tra.plan_fields == GAT_PLAN_FIELDS_PALLAS
    assert trr.plan_fields == GAT_PLAN_FIELDS_PALLAS_RAGGED
    _assert_bit_equal(la, pa, lr, pr)


def test_pallas_family_allclose_vs_ell(cora8, monkeypatch):
    """The pallas family stays allclose-pinned against the ELL slot-pass
    path (the pre-existing contract, now under BOTH schedules)."""
    plan, feats, labels = cora8
    monkeypatch.setenv("SGCN_PALLAS_SPMM", "0")
    _, le, _ = _train(plan, feats, labels, "gcn", "ragged")
    monkeypatch.setenv("SGCN_PALLAS_SPMM", "1")
    _, lp, _ = _train(plan, feats, labels, "gcn", "ragged")
    np.testing.assert_allclose(lp, le, rtol=1e-4, atol=1e-5)


def test_dispatch_decision_in_manifest(cora8, monkeypatch, tmp_path):
    """The per-bucket kernel choice lands in the decision log and, through
    attach_recorder, in the run manifest's comm_schedule block."""
    from sgcn_tpu.obs import RunRecorder, load_run

    plan, feats, labels = cora8
    monkeypatch.setenv("SGCN_PALLAS_SPMM", "1")
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=1,
                          comm_schedule="ragged")
    disp = tr.comm_decision["pallas_dispatch"]
    assert disp["schedule"] == "ragged" and disp["model"] == "gcn"
    for fam in ("local", "halo"):
        assert disp[fam], fam
        for c in disp[fam]:
            assert set(c) == {"tiles", "emax", "kernel"}
            assert c["kernel"] in ("vmem", "ell")
    rec = RunRecorder(str(tmp_path), config={"model": "gcn"})
    tr.attach_recorder(rec)
    data = make_train_data(plan, feats, labels)
    tr.step(data)
    rec.close()
    run = load_run(str(tmp_path))
    assert run.manifest["comm_schedule"]["pallas_dispatch"] == disp


def hub_graph(n: int, hub_deg: int) -> sp.csr_matrix:
    """A ring plus one hub wired to ``hub_deg`` vertices — the one-hub BA
    shape whose single fat tile used to inflate EVERY tile's Emax."""
    i = np.arange(n)
    rows = [i, i, np.zeros(hub_deg, np.int64)]
    cols = [(i + 1) % n, (i - 1) % n, 1 + np.arange(hub_deg)]
    a = sp.csr_matrix((np.ones(2 * n + hub_deg, np.float32),
                       (np.concatenate(rows), np.concatenate(cols))),
                      shape=(n, n))
    a = ((a + a.T) > 0).astype(np.float32)
    a.setdiag(0)
    a.eliminate_zeros()
    return sp.csr_matrix(a)


def test_degree_binned_hub_fallback(monkeypatch):
    """Per-bucket dispatch: with the serial-chain cap forced tight, the
    hub's tile class falls back to the XLA form while the low-degree mass
    stays on the VMEM kernel — and the mixed program remains bit-identical
    across schedules and allclose vs the ELL path.  Also pins that the
    binned layout strictly shrinks padded slots vs the old global-Emax
    pad on this shape."""
    n, k = 512, 8
    ahat = normalize_adjacency(hub_graph(n, 200))
    pv = balanced_random_partition(n, k, seed=0)
    plan = build_comm_plan(ahat, pv, k)
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((n, 12)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)

    monkeypatch.setenv("SGCN_PALLAS_SPMM", "1")
    monkeypatch.setenv("SGCN_PALLAS_EMAX", "48")   # force the hub off VMEM
    plan.ensure_pallas_tiles(tb=16)
    lcl = plan.pallas_lclasses
    assert len(lcl) > 1, "hub fixture produced a single tile class"
    # binned padding strictly below the global-Emax pad
    global_pad = sum(t for t, _e in lcl) * max(e for _t, e in lcl)
    binned_pad = sum(t * e for t, e in lcl)
    assert binned_pad < global_pad
    from sgcn_tpu.ops.pallas_spmm import _assign_kernels
    kerns = {kern for _t, _e, kern in _assign_kernels(lcl)}
    assert kerns == {"vmem", "ell"}, kerns

    # parity with the forced-tight cap: a2a-pallas == ragged-pallas, both
    # allclose to ELL.  tb must divide consistently — the trainer builds
    # its own tb=256 layout on this plan, so rebuild at default tb and
    # keep the tight cap (classes may then be all-vmem at tb=256; the
    # kernel-mix pin above used the tb=16 layout)
    plan2 = build_comm_plan(ahat, pv, k)
    monkeypatch.setenv("SGCN_PALLAS_SPMM", "0")
    _, le, _ = _train(plan2, feats, labels, "gcn", "ragged",
                      widths=[8, 4])
    monkeypatch.setenv("SGCN_PALLAS_SPMM", "1")
    tra, la, pa = _train(plan2, feats, labels, "gcn", "a2a",
                         widths=[8, 4])
    trr, lr, pr = _train(plan2, feats, labels, "gcn", "ragged",
                         widths=[8, 4])
    _assert_bit_equal(la, pa, lr, pr)
    np.testing.assert_allclose(lr, le, rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------- audit rules
def test_ragged_pallas_audit_green_and_expectation_nonvacuous():
    """The new audit modes lower green AND the halo-materialization
    expectation actually forbids something on the audit fixture (a
    collision-emptied list would make the rule vacuous)."""
    from sgcn_tpu.analysis.hlo_audit import audit_mode, lower_mode
    from sgcn_tpu.analysis.modes import Mode

    mode = Mode("train", "gcn", "ragged", pallas=True)
    (label, _text, exp), = lower_mode(mode)
    assert label == "step"
    assert exp.forbidden_scatters, (
        "forbidden-scatter list empty on the audit fixture — the "
        "halo-materialization rule checks nothing")
    entry = audit_mode(mode)
    assert entry["ok"], entry
    gat = audit_mode(Mode("train", "gat", "ragged", gat_form="fused",
                          pallas=True))
    assert gat["ok"], gat


def test_mutation_halo_table_materialized(monkeypatch):
    """MUTATION CHECK (the PR-10 norm): seed a ragged-pallas program that
    scatters the ring receives into an HBM (R, f) halo table before the
    kernel — bit-identical output, same collectives, same wire shapes;
    ONLY the halo-materialization rule can catch it, and it must."""
    import sgcn_tpu.ops.pallas_spmm as ps
    from sgcn_tpu.analysis.hlo_audit import (audit_mode, audit_plan)
    from sgcn_tpu.analysis.modes import Mode

    plan = audit_plan()
    plan.ensure_ragged()
    rhalo = np.asarray(plan.rhalo_dst)
    orig = ps.pallas_ring_concat

    def materializing(x, rsend_idx, rr_sizes, axis_name, halo_dtype=None):
        ring = orig(x, rsend_idx, rr_sizes, axis_name, halo_dtype)
        p = jax.lax.axis_index(axis_name)
        dst = jnp.take(jnp.asarray(rhalo), p, axis=0)
        halo = jnp.zeros((plan.r, x.shape[-1]), x.dtype).at[dst].set(
            ring, mode="drop")
        # consume the table so the scatter survives trace-time DCE; the
        # 0·sum keeps the math bit-identical — exactly the silent
        # regression shape the rule exists for
        return ring + 0.0 * jnp.sum(halo)

    with mock.patch.object(ps, "pallas_ring_concat", materializing):
        entry = audit_mode(Mode("train", "gcn", "ragged", pallas=True))
    assert not entry["ok"]
    rules = {v["rule"] for prog in entry["programs"].values()
             for v in prog["violations"]}
    assert "halo-materialization" in rules, rules
