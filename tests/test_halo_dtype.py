"""Exchange-only bf16 (``halo_dtype``): numerics parity + narrowed wire.

VERDICT r4 item 4: the multi-chip win of bf16 is ICI bytes, which only the
a2a buffer sees — cast exactly the send buffer, upcast after the halo
gather, leave tables/activations f32.
"""

import numpy as np
import pytest

from sgcn_tpu.io.datasets import er_graph
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data


@pytest.fixture(scope="module")
def setup():
    n, k = 4000, 8
    ahat = normalize_adjacency(er_graph(n, 8, seed=0))
    pv = balanced_random_partition(n, k, seed=1)
    plan = build_comm_plan(ahat, pv, k)
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((n, 16)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    return plan, feats, labels


def _fit(plan, feats, labels, **kw):
    tr = FullBatchTrainer(plan, fin=16, widths=[8, 4], seed=2, **kw)
    data = make_train_data(plan, feats, labels)
    r = tr.fit(data, epochs=4, verbose=False)
    return tr, r["loss_history"]


def test_halo_bf16_numerics_parity(setup):
    """Training under the bf16 wire tracks f32 training to bf16 tolerance —
    only boundary rows are quantized, local rows not at all."""
    plan, feats, labels = setup
    _, ref = _fit(plan, feats, labels)
    _, bf = _fit(plan, feats, labels, halo_dtype="bfloat16")
    np.testing.assert_allclose(bf, ref, rtol=5e-3, atol=5e-3)
    assert not np.allclose(bf, ref, rtol=0, atol=0), \
        "bf16 wire changed nothing — cast not applied?"


def test_halo_bf16_wire_is_narrow(setup):
    """The lowered step carries bf16 all_to_alls and NO f32 ones — both
    directions (forward halo + backward gradient exchange)."""
    plan, feats, labels = setup
    tr = FullBatchTrainer(plan, fin=16, widths=[8, 4], seed=2,
                          halo_dtype="bfloat16")
    data = make_train_data(plan, feats, labels)
    from sgcn_tpu.parallel.mesh import shard_stacked
    data = type(data)(**shard_stacked(tr.mesh, vars(data)))
    txt = tr._step.lower(
        tr.params, tr.opt_state, tr.pa, data.h0, data.labels,
        data.train_valid).as_text()
    import re
    a2a_types = re.findall(r'"?stablehlo\.all_to_all"?.*?->\s*tensor<[0-9x]*(f32|bf16)>', txt)
    assert a2a_types, "no all_to_all in lowered step?"
    assert set(a2a_types) == {"bf16"}, a2a_types


def test_gat_rejects_halo_dtype(setup):
    plan, *_ = setup
    with pytest.raises(ValueError, match="GCN-trainer lever"):
        FullBatchTrainer(plan, fin=16, widths=[8, 4], model="gat",
                         halo_dtype="bfloat16")
