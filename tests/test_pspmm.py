"""Distributed pspmm forward/backward parity vs dense ground truth.

The op under test is the analogue of PSpMM (GPU/PGCN.py:121-134): forward =
halo exchange + local SpMM must equal dense Â·H; backward through the same op
must equal Âᵀ·g with the reversed exchange (GPU/PGCN.py:129-134)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from sgcn_tpu.ops import pspmm_exchange, pspmm_overlap
from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d, shard_stacked
from sgcn_tpu.partition import balanced_random_partition, random_partition

from sgcn_tpu.models.gcn import GCN_PLAN_FIELDS_GEN as OVERLAP_FIELDS
from sgcn_tpu.models.gcn import GCN_PLAN_FIELDS_SYM as SYM_FIELDS


def _overlap_args(pa):
    return tuple(pa[f] for f in OVERLAP_FIELDS)


def _run_pspmm(plan, mesh, h_global, f):
    h_blocks = plan.scatter_rows(h_global)
    pa = {
        "send_idx": plan.send_idx, "halo_src": plan.halo_src,
        "edge_dst": plan.edge_dst, "edge_src": plan.edge_src,
        "edge_w": plan.edge_w,
    }
    pa = shard_stacked(mesh, pa)
    h_blocks = shard_stacked(mesh, h_blocks)

    def per_chip(pa, h):
        pa = jax.tree.map(lambda x: x[0], pa)
        out = pspmm_exchange(h[0], pa["send_idx"], pa["halo_src"],
                             pa["edge_dst"], pa["edge_src"], pa["edge_w"])
        return out[None]

    fn = jax.jit(jax.shard_map(per_chip, mesh=mesh,
                               in_specs=(P("v"), P("v")),
                               out_specs=P("v")))
    return np.asarray(fn(pa, h_blocks)), pa, h_blocks


@pytest.mark.parametrize("k,partfn", [(2, balanced_random_partition),
                                      (4, balanced_random_partition),
                                      (8, random_partition)])
def test_forward_parity(ahat, k, partfn):
    n = ahat.shape[0]
    f = 5
    pv = partfn(n, k, seed=11)
    plan = build_comm_plan(ahat, pv, k)
    mesh = make_mesh_1d(k)
    h = np.random.default_rng(4).standard_normal((n, f)).astype(np.float32)
    out_blocks, _, _ = _run_pspmm(plan, mesh, h, f)
    got = plan.gather_rows(out_blocks)
    expected = ahat @ h
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("k,partfn", [(2, balanced_random_partition),
                                      (4, balanced_random_partition),
                                      (8, random_partition)])
def test_overlap_forward_parity(ahat, k, partfn):
    """The split-edge-list (comm/compute-overlap) formulation must compute the
    same Â·H: Â·H_local + Σ Â·Ĥ_r (Parallel-GCN/main.c:238-299)."""
    n = ahat.shape[0]
    f = 5
    pv = partfn(n, k, seed=11)
    plan = build_comm_plan(ahat, pv, k)
    # split invariants: every edge lands in exactly one of the two lists
    np.testing.assert_array_equal(plan.lnnz + plan.hnnz, plan.nnz)
    assert (plan.ledge_src < plan.b).all()
    assert (plan.hedge_src < plan.r).all()
    mesh = make_mesh_1d(k)
    h = np.random.default_rng(4).standard_normal((n, f)).astype(np.float32)
    h_blocks = shard_stacked(mesh, plan.scatter_rows(h))
    pa = shard_stacked(mesh, {f_: getattr(plan, f_) for f_ in OVERLAP_FIELDS})

    def per_chip(pa, h):
        pa = jax.tree.map(lambda x: x[0], pa)
        return pspmm_overlap(h[0], *_overlap_args(pa))[None]

    fn = jax.jit(jax.shard_map(per_chip, mesh=mesh,
                               in_specs=(P("v"), P("v")),
                               out_specs=P("v")))
    got = plan.gather_rows(np.asarray(fn(pa, h_blocks)))
    np.testing.assert_allclose(got, ahat @ h, rtol=1e-4, atol=1e-5)


def test_overlap_backward_parity(ahat):
    """Gradient through pspmm_overlap must equal Âᵀ·w, covering the
    transposed all_to_all of the split formulation."""
    n = ahat.shape[0]
    k = 4
    f = 3
    pv = balanced_random_partition(n, k, seed=13)
    plan = build_comm_plan(ahat, pv, k)
    mesh = make_mesh_1d(k)
    rng = np.random.default_rng(7)
    h = rng.standard_normal((n, f)).astype(np.float32)
    wgt = rng.standard_normal((n, f)).astype(np.float32)
    pa = shard_stacked(mesh, {f_: getattr(plan, f_) for f_ in OVERLAP_FIELDS})
    hb = shard_stacked(mesh, plan.scatter_rows(h))
    wb = shard_stacked(mesh, plan.scatter_rows(wgt))

    def per_chip(pa, h, w):
        pa = jax.tree.map(lambda x: x[0], pa)

        def obj(hl):
            out = pspmm_overlap(hl, *_overlap_args(pa))
            # per-chip LOCAL objective: its grad is still the GLOBAL
            # d(sum over chips)/dh — every chip runs the same transposed
            # exchange, so cotangents for rows this chip owns arrive from
            # all consumers.  (A psum'd objective hits the old
            # psum-transposes-to-psum convention on jaxlib 0.4.37 and
            # comes back k-times inflated; the local form is
            # convention-independent.)
            return jnp.sum(out * w[0])

        return jax.grad(obj)(h[0])[None]

    fn = jax.jit(jax.shard_map(per_chip, mesh=mesh,
                               in_specs=(P("v"), P("v"), P("v")),
                               out_specs=P("v")))
    got = plan.gather_rows(np.asarray(fn(pa, hb, wb)))
    np.testing.assert_allclose(got, ahat.T @ wgt, rtol=1e-4, atol=1e-5)


def _sym_args(pa):
    return tuple(pa[f] for f in SYM_FIELDS)


@pytest.mark.parametrize("k", [2, 4, 8])
def test_ell_sym_forward_parity(ahat, k):
    """The ELL + symmetric-backward fast path must also compute dense Â·H."""
    from sgcn_tpu.ops import pspmm_ell_sym
    n = ahat.shape[0]
    f = 5
    plan = build_comm_plan(ahat, balanced_random_partition(n, k, seed=11), k)
    assert plan.symmetric            # Â of an undirected graph
    # ELL invariants: main + tail covers exactly the local edges
    ell_edges = (plan.ell_w != 0).sum() + plan.ltail_nnz.sum()
    assert ell_edges == (plan.ledge_w != 0).sum()
    mesh = make_mesh_1d(k)
    h = np.random.default_rng(4).standard_normal((n, f)).astype(np.float32)
    hb = shard_stacked(mesh, plan.scatter_rows(h))
    pa = shard_stacked(mesh, {f_: getattr(plan, f_) for f_ in SYM_FIELDS})

    def per_chip(pa, h):
        pa = jax.tree.map(lambda x: x[0], pa)
        return pspmm_ell_sym(h[0], *_sym_args(pa), plan.ell_buckets)[None]

    fn = jax.jit(jax.shard_map(per_chip, mesh=mesh,
                               in_specs=(P("v"), P("v")), out_specs=P("v")))
    got = plan.gather_rows(np.asarray(fn(pa, hb)))
    np.testing.assert_allclose(got, ahat @ h, rtol=1e-4, atol=1e-5)


def test_ell_sym_backward_parity(ahat):
    """The symmetric custom VJP (bwd = forward applied to g) must equal
    Âᵀ·w = Â·w, including the exchange in the backward."""
    from sgcn_tpu.ops import pspmm_ell_sym
    n = ahat.shape[0]
    k = 4
    f = 3
    plan = build_comm_plan(ahat, balanced_random_partition(n, k, seed=13), k)
    mesh = make_mesh_1d(k)
    rng = np.random.default_rng(7)
    h = rng.standard_normal((n, f)).astype(np.float32)
    wgt = rng.standard_normal((n, f)).astype(np.float32)
    pa = shard_stacked(mesh, {f_: getattr(plan, f_) for f_ in SYM_FIELDS})
    hb = shard_stacked(mesh, plan.scatter_rows(h))
    wb = shard_stacked(mesh, plan.scatter_rows(wgt))

    def per_chip(pa, h, w):
        pa = jax.tree.map(lambda x: x[0], pa)

        def obj(hl):
            out = pspmm_ell_sym(hl, *_sym_args(pa), plan.ell_buckets)
            # per-chip LOCAL objective: its grad is still the GLOBAL
            # d(sum over chips)/dh — every chip runs the same transposed
            # exchange, so cotangents for rows this chip owns arrive from
            # all consumers.  (A psum'd objective hits the old
            # psum-transposes-to-psum convention on jaxlib 0.4.37 and
            # comes back k-times inflated; the local form is
            # convention-independent.)
            return jnp.sum(out * w[0])

        return jax.grad(obj)(h[0])[None]

    fn = jax.jit(jax.shard_map(per_chip, mesh=mesh,
                               in_specs=(P("v"), P("v"), P("v")),
                               out_specs=P("v")))
    got = plan.gather_rows(np.asarray(fn(pa, hb, wb)))
    np.testing.assert_allclose(got, ahat.T @ wgt, rtol=1e-4, atol=1e-5)


def test_directed_graph_detected_not_symmetric():
    """A directed adjacency must opt out of the symmetric fast path, and the
    general path's mechanical transpose must stay exact (Âᵀ ≠ Â here)."""
    import scipy.sparse as sp
    rng = np.random.default_rng(3)
    n, k, f = 40, 4, 3
    dense = (rng.random((n, n)) < 0.2).astype(np.float32)
    np.fill_diagonal(dense, 0)
    a = sp.csr_matrix(dense)                  # deliberately asymmetric
    plan = build_comm_plan(a, balanced_random_partition(n, k, seed=5), k)
    assert not plan.symmetric
    mesh = make_mesh_1d(k)
    h = rng.standard_normal((n, f)).astype(np.float32)
    wgt = rng.standard_normal((n, f)).astype(np.float32)
    pa = shard_stacked(mesh, {f_: getattr(plan, f_) for f_ in OVERLAP_FIELDS})
    hb = shard_stacked(mesh, plan.scatter_rows(h))
    wb = shard_stacked(mesh, plan.scatter_rows(wgt))

    def per_chip(pa, h, w):
        pa = jax.tree.map(lambda x: x[0], pa)

        def obj(hl):
            out = pspmm_overlap(hl, *_overlap_args(pa))
            # per-chip LOCAL objective: its grad is still the GLOBAL
            # d(sum over chips)/dh — every chip runs the same transposed
            # exchange, so cotangents for rows this chip owns arrive from
            # all consumers.  (A psum'd objective hits the old
            # psum-transposes-to-psum convention on jaxlib 0.4.37 and
            # comes back k-times inflated; the local form is
            # convention-independent.)
            return jnp.sum(out * w[0])

        return jax.grad(obj)(h[0])[None]

    fn = jax.jit(jax.shard_map(per_chip, mesh=mesh,
                               in_specs=(P("v"), P("v"), P("v")),
                               out_specs=P("v")))
    got = plan.gather_rows(np.asarray(fn(pa, hb, wb)))
    np.testing.assert_allclose(got, a.T @ wgt, rtol=1e-4, atol=1e-5)


def _collective_taint(jaxpr):
    """(tainted_eqns, eqns): which inner-jaxpr eqns transitively depend on the
    all_to_all collective (var-level dataflow taint)."""
    from jax.extend.core import Literal
    inner = None
    for e in jaxpr.eqns:
        if "shard" in e.primitive.name:
            inner = e.params["jaxpr"]
    assert inner is not None
    tainted_vars: set = set()
    tainted_eqns = []
    for e in inner.eqns:
        invars = [v for v in e.invars if not isinstance(v, Literal)]
        hit = e.primitive.name == "all_to_all" or any(
            v in tainted_vars for v in invars)
        if hit:
            tainted_vars.update(e.outvars)
            tainted_eqns.append(e)
    return tainted_eqns, inner.eqns


def test_overlap_local_spmm_independent_of_collective(ahat):
    """The overlap property itself: in the split formulation the local
    segment-sum (scatter-add) must NOT depend on the all_to_all — that
    dependence freedom is what lets the TPU scheduler hide the exchange
    behind local compute (the Irecv/compute/Waitany structure of
    Parallel-GCN/main.c:238-299).  The combined formulation, by contrast,
    aggregates through the concatenated [h; halo] table, so every
    scatter-add depends on the collective."""
    n = ahat.shape[0]
    k = 4
    plan = build_comm_plan(ahat, balanced_random_partition(n, k, seed=1), k)
    mesh = make_mesh_1d(k)
    h = np.zeros((k, plan.b, 5), np.float32)
    pao = {f: getattr(plan, f) for f in OVERLAP_FIELDS}
    pac = {f: getattr(plan, f)
           for f in ("send_idx", "halo_src", "edge_dst", "edge_src", "edge_w")}

    def overlap_chip(pa, h):
        pa = jax.tree.map(lambda x: x[0], pa)
        return pspmm_overlap(h[0], *_overlap_args(pa))[None]

    def combined_chip(pa, h):
        pa = jax.tree.map(lambda x: x[0], pa)
        return pspmm_exchange(h[0], pa["send_idx"], pa["halo_src"],
                              pa["edge_dst"], pa["edge_src"], pa["edge_w"])[None]

    def agg_taint(fn, pa):
        sm = jax.shard_map(fn, mesh=mesh, in_specs=(P("v"), P("v")),
                           out_specs=P("v"))
        tainted, eqns = _collective_taint(jax.make_jaxpr(sm)(pa, h))
        aggs = [e for e in eqns if "scatter" in e.primitive.name]
        assert aggs, "expected scatter-add aggregation eqns in the jaxpr"
        return [e in tainted for e in aggs]

    assert not all(agg_taint(overlap_chip, pao)), \
        "overlap form: local scatter-add must be collective-independent"
    assert all(agg_taint(combined_chip, pac)), \
        "combined form should depend on the collective everywhere"


def test_backward_parity(ahat):
    """grad_h of sum(w ⊙ (Â·H)) must equal Âᵀ·w — exercised through the full
    halo exchange so the transposed all_to_all path is covered."""
    n = ahat.shape[0]
    k = 4
    f = 3
    pv = balanced_random_partition(n, k, seed=13)
    plan = build_comm_plan(ahat, pv, k)
    mesh = make_mesh_1d(k)
    rng = np.random.default_rng(7)
    h = rng.standard_normal((n, f)).astype(np.float32)
    wgt = rng.standard_normal((n, f)).astype(np.float32)

    pa = shard_stacked(mesh, {
        "send_idx": plan.send_idx, "halo_src": plan.halo_src,
        "edge_dst": plan.edge_dst, "edge_src": plan.edge_src,
        "edge_w": plan.edge_w,
    })
    hb = shard_stacked(mesh, plan.scatter_rows(h))
    wb = shard_stacked(mesh, plan.scatter_rows(wgt))

    def per_chip(pa, h, w):
        pa = jax.tree.map(lambda x: x[0], pa)

        def obj(hl):
            out = pspmm_exchange(hl, pa["send_idx"], pa["halo_src"],
                                 pa["edge_dst"], pa["edge_src"], pa["edge_w"])
            # per-chip LOCAL objective: its grad is still the GLOBAL
            # d(sum over chips)/dh — every chip runs the same transposed
            # exchange, so cotangents for rows this chip owns arrive from
            # all consumers.  (A psum'd objective hits the old
            # psum-transposes-to-psum convention on jaxlib 0.4.37 and
            # comes back k-times inflated; the local form is
            # convention-independent.)
            return jnp.sum(out * w[0])

        return jax.grad(obj)(h[0])[None]

    fn = jax.jit(jax.shard_map(per_chip, mesh=mesh,
                               in_specs=(P("v"), P("v"), P("v")),
                               out_specs=P("v")))
    got = plan.gather_rows(np.asarray(fn(pa, hb, wb)))
    expected = ahat.T @ wgt
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_scan_slot_path_matches_unrolled(ahat, monkeypatch):
    """The scan-over-slots form (huge-graph memory path) must compute the
    same SpMM and GAT aggregation as the unrolled form."""
    import importlib
    # attribute access on the package resolves to the re-exported FUNCTION
    # named pspmm; go through the module registry for the module object
    pspmm_mod = importlib.import_module("sgcn_tpu.ops.pspmm")
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    n = ahat.shape[0]
    k = 4
    rng = np.random.default_rng(3)
    feats = rng.standard_normal((n, 6)).astype(np.float32)
    labels = rng.integers(0, 3, size=n).astype(np.int32)
    pv = balanced_random_partition(n, k, seed=9)
    plan = build_comm_plan(ahat, pv, k)

    def losses(model):
        kw = {"model": "gat", "activation": "none"} if model == "gat" else {}
        tr = FullBatchTrainer(plan, fin=6, widths=[5, 3], seed=4, **kw)
        data = make_train_data(plan, feats, labels)
        return [tr.step(data) for _ in range(3)]

    ref_gcn = losses("gcn")
    ref_gat = losses("gat")
    # with the limit at 1, every bucket wider than the wb<=2 escape takes
    # the scan branch — make sure such buckets exist, so the comparison
    # below genuinely exercises scan-vs-unrolled (both models go through
    # the ONE bucketed_slot_reduce in ops.pspmm, which reads this module
    # global at trace time)
    assert any(wb > 2 for _, wb in plan.ell_buckets)
    assert any(wb > 2 for _, wb in plan.ensure_cell().cell_buckets)
    monkeypatch.setattr(pspmm_mod, "_CONCURRENT_TEMP_LIMIT", 1)
    np.testing.assert_allclose(losses("gcn"), ref_gcn, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(losses("gat"), ref_gat, rtol=1e-5, atol=1e-6)
