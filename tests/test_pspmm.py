"""Distributed pspmm forward/backward parity vs dense ground truth.

The op under test is the analogue of PSpMM (GPU/PGCN.py:121-134): forward =
halo exchange + local SpMM must equal dense Â·H; backward through the same op
must equal Âᵀ·g with the reversed exchange (GPU/PGCN.py:129-134)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from sgcn_tpu.ops import pspmm_exchange
from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d, shard_stacked
from sgcn_tpu.partition import balanced_random_partition, random_partition


def _run_pspmm(plan, mesh, h_global, f):
    h_blocks = plan.scatter_rows(h_global)
    pa = {
        "send_idx": plan.send_idx, "halo_src": plan.halo_src,
        "edge_dst": plan.edge_dst, "edge_src": plan.edge_src,
        "edge_w": plan.edge_w,
    }
    pa = shard_stacked(mesh, pa)
    h_blocks = shard_stacked(mesh, h_blocks)

    def per_chip(pa, h):
        pa = jax.tree.map(lambda x: x[0], pa)
        out = pspmm_exchange(h[0], pa["send_idx"], pa["halo_src"],
                             pa["edge_dst"], pa["edge_src"], pa["edge_w"])
        return out[None]

    fn = jax.jit(jax.shard_map(per_chip, mesh=mesh,
                               in_specs=(P("v"), P("v")),
                               out_specs=P("v")))
    return np.asarray(fn(pa, h_blocks)), pa, h_blocks


@pytest.mark.parametrize("k,partfn", [(2, balanced_random_partition),
                                      (4, balanced_random_partition),
                                      (8, random_partition)])
def test_forward_parity(ahat, k, partfn):
    n = ahat.shape[0]
    f = 5
    pv = partfn(n, k, seed=11)
    plan = build_comm_plan(ahat, pv, k)
    mesh = make_mesh_1d(k)
    h = np.random.default_rng(4).standard_normal((n, f)).astype(np.float32)
    out_blocks, _, _ = _run_pspmm(plan, mesh, h, f)
    got = plan.gather_rows(out_blocks)
    expected = ahat @ h
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_backward_parity(ahat):
    """grad_h of sum(w ⊙ (Â·H)) must equal Âᵀ·w — exercised through the full
    halo exchange so the transposed all_to_all path is covered."""
    n = ahat.shape[0]
    k = 4
    f = 3
    pv = balanced_random_partition(n, k, seed=13)
    plan = build_comm_plan(ahat, pv, k)
    mesh = make_mesh_1d(k)
    rng = np.random.default_rng(7)
    h = rng.standard_normal((n, f)).astype(np.float32)
    wgt = rng.standard_normal((n, f)).astype(np.float32)

    pa = shard_stacked(mesh, {
        "send_idx": plan.send_idx, "halo_src": plan.halo_src,
        "edge_dst": plan.edge_dst, "edge_src": plan.edge_src,
        "edge_w": plan.edge_w,
    })
    hb = shard_stacked(mesh, plan.scatter_rows(h))
    wb = shard_stacked(mesh, plan.scatter_rows(wgt))

    def per_chip(pa, h, w):
        pa = jax.tree.map(lambda x: x[0], pa)

        def obj(hl):
            out = pspmm_exchange(hl, pa["send_idx"], pa["halo_src"],
                                 pa["edge_dst"], pa["edge_src"], pa["edge_w"])
            return jax.lax.psum(jnp.sum(out * w[0]), "v")

        return jax.grad(obj)(h[0])[None]

    fn = jax.jit(jax.shard_map(per_chip, mesh=mesh,
                               in_specs=(P("v"), P("v"), P("v")),
                               out_specs=P("v")))
    got = plan.gather_rows(np.asarray(fn(pa, hb, wb)))
    expected = ahat.T @ wgt
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)
