"""End-to-end CLI integration: preprocess → partition → SHP → train.

Exercises the same file-pipeline layering as the reference (SURVEY.md §1):
stages communicate only through files on disk.  Subprocesses run on forced
CPU with k virtual devices (the trainer CLI's ``-b cpu`` backend does this
itself); module CLIs are invoked via ``python -m``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(args, **kw):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # let -b cpu set its own device count
    env["PYTHONPATH"] = REPO
    return subprocess.run([sys.executable, "-m", *args], capture_output=True,
                          text=True, cwd=REPO, env=env, timeout=600, **kw)


@pytest.fixture(scope="module")
def pipeline(tmp_path_factory):
    """prep + partition once for all CLI tests."""
    d = tmp_path_factory.mktemp("cli")
    from sgcn_tpu.io.datasets import er_graph
    from sgcn_tpu.io.mtx import write_mtx
    write_mtx(str(d / "g.mtx"), er_graph(150, 8, seed=3))

    r = run_cli(["sgcn_tpu.prep", "-a", str(d / "g.mtx"), "-o", str(d),
                 "-n", "g", "-l", "2", "-f", "8", "-c", "3"])
    assert r.returncode == 0, r.stderr
    r = run_cli(["sgcn_tpu.partition", "-a", str(d / "g.A.mtx"), "-k", "4",
                 "-m", "hp,rp"])
    assert r.returncode == 0, r.stderr
    return d


def test_prep_outputs(pipeline):
    d = pipeline
    for f in ("g.A.mtx", "g.H.mtx", "g.Y.mtx", "config"):
        assert (d / f).exists(), f
    toks = (d / "config").read_text().split()
    assert toks[0] == "2" and toks[1] == "150"


def test_partition_outputs(pipeline):
    d = pipeline
    from sgcn_tpu.partition import read_partvec
    for suf in ("hp", "rp"):
        pv = read_partvec(str(d / f"g.A.mtx.4.{suf}"))
        assert pv.shape == (150,)
        assert pv.max() < 4


def test_train_cli_fullbatch(pipeline):
    d = pipeline
    r = run_cli(["sgcn_tpu.train", "-a", str(d / "g.A.mtx"),
                 "-p", str(d / "g.A.mtx.4.hp"), "-b", "cpu", "-s", "4",
                 "-l", "2", "-f", "6", "--epochs", "2"])
    assert r.returncode == 0, r.stderr
    report = json.loads(r.stdout.strip().splitlines()[-1])
    assert report["epochs"] == 2
    assert report["total_send_volume"] > 0


def test_shp_to_minibatch_train(pipeline):
    """SHP pickles feed the mini-batch trainer (the reference's coupling:
    GPU/SHP/main.py:131-140 → PGCN-Mini-batch.py:217-218)."""
    d = pipeline
    r = run_cli(["sgcn_tpu.shp", "-p", str(d / "g.A.mtx"), "-k", "3",
                 "-s", "4", "-b", "30", "-m", "3", "-o", str(d)])
    assert r.returncode == 0, r.stderr
    assert (d / "partvec.stchp.3").exists()
    r = run_cli(["sgcn_tpu.train", "-a", str(d / "g.A.mtx"),
                 "-p", str(d / "partvec.stchp.3"), "-b", "cpu", "-s", "3",
                 "-l", "2", "-f", "6", "-n", "40", "--epochs", "1"])
    assert r.returncode == 0, r.stderr
    report = json.loads(r.stdout.strip().splitlines()[-1])
    assert report["nbatches"] > 0


def test_train_cli_gat_default_activation_none(pipeline):
    """PGAT semantic fidelity: the reference stacks bare PGAT modules with no
    inter-layer nonlinearity (GPU/PGAT.py:202-213), so --model gat must not
    silently apply relu; --activation overrides."""
    d = pipeline
    r = run_cli(["sgcn_tpu.train", "-a", str(d / "g.A.mtx"),
                 "-p", str(d / "g.A.mtx.4.hp"), "-b", "cpu", "-s", "4",
                 "-l", "2", "-f", "6", "--model", "gat", "--epochs", "1"])
    assert r.returncode == 0, r.stderr
    report = json.loads(r.stdout.strip().splitlines()[-1])
    assert report["activation"] == "none"
    r = run_cli(["sgcn_tpu.train", "-a", str(d / "g.A.mtx"),
                 "-p", str(d / "g.A.mtx.4.hp"), "-b", "cpu", "-s", "4",
                 "-l", "2", "-f", "6", "--model", "gat", "--epochs", "1",
                 "--activation", "elu"])
    assert r.returncode == 0, r.stderr
    report = json.loads(r.stdout.strip().splitlines()[-1])
    assert report["activation"] == "elu"


def test_train_cli_bce_loss_reports_err(pipeline):
    """The MPI stack's loss flavor: sigmoid+BCE training with the `err`
    metric in the rank-0 report (Parallel-GCN/main.c:70-90,318-335)."""
    d = pipeline
    r = run_cli(["sgcn_tpu.train", "-a", str(d / "g.A.mtx"),
                 "-p", str(d / "g.A.mtx.4.hp"), "-b", "cpu", "-s", "4",
                 "-l", "2", "-f", "6", "--loss", "bce",
                 "--activation", "sigmoid", "--epochs", "2"])
    assert r.returncode == 0, r.stderr
    report = json.loads(r.stdout.strip().splitlines()[-1])
    assert report["loss"] == "bce"
    assert report["err"] > 0


def test_train_cli_rejects_bad_partvec(pipeline):
    d = pipeline
    (d / "bad.part").write_text("0 1 2\n")
    r = run_cli(["sgcn_tpu.train", "-a", str(d / "g.A.mtx"),
                 "-p", str(d / "bad.part"), "-b", "cpu", "-s", "4",
                 "-l", "2", "-f", "4"])
    assert r.returncode != 0
    assert "partvec length" in r.stderr


def test_train_cli_profile_writes_trace(pipeline, tmp_path):
    """--profile DIR captures a jax.profiler trace of the run (the tracing
    half of SURVEY.md §5.1; the phase-timer half is utils/timers.py)."""
    d = pipeline
    prof_dir = tmp_path / "prof"
    r = run_cli(["sgcn_tpu.train", "-a", str(d / "g.A.mtx"),
                 "-p", str(d / "g.A.mtx.4.hp"), "-b", "cpu", "-s", "4",
                 "-l", "2", "-f", "8", "--epochs", "2",
                 "--profile", str(prof_dir)])
    assert r.returncode == 0, r.stderr
    report = json.loads(r.stdout.strip().splitlines()[-1])
    assert report["epochs"] == 2
    traces = list(prof_dir.rglob("*.xplane.pb")) + \
        list(prof_dir.rglob("*.trace.json.gz"))
    assert traces, f"no trace files under {prof_dir}"


def test_baseline_cli_oracle(pipeline):
    """python -m sgcn_tpu.baselines oracle = the DGL/gcn.py role: dense
    single-process training on the preprocessor outputs (README.md:150-166)."""
    d = pipeline
    r = run_cli(["sgcn_tpu.baselines", "oracle", "-a", str(d / "g.A.mtx"),
                 "-f", str(d / "g.H.mtx"), "-y", str(d / "g.Y.mtx"),
                 "-c", str(d / "config"), "--epochs", "3"])
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["baseline"] == "oracle" and rep["epochs"] == 3
    assert np.isfinite(rep["final_loss"])
    assert "epoch 2" in r.stderr                   # per-epoch loss lines


def test_baseline_cli_cagnet(pipeline):
    """python -m sgcn_tpu.baselines cagnet = the Cagnet/main.c role:
    uniform-block 1D broadcast inference with the phase-time breakdown
    (Cagnet/main.c:35-38,395-413)."""
    d = pipeline
    r = run_cli(["sgcn_tpu.baselines", "cagnet", "-a", str(d / "g.A.mtx"),
                 "-c", str(d / "config"), "-s", "4", "--epochs", "2"])
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["baseline"] == "cagnet1d" and rep["epochs"] == 2
    assert {"data_comm", "local_spmm"} <= set(rep["phases"])
    assert rep["send_volume_per_exchange"] > 0


def test_train_cli_checkpoint_resume(pipeline, tmp_path):
    """--save-checkpoint / --resume: training continues from saved state
    (capability beyond the reference, which re-randomizes every run —
    SURVEY.md §5.4)."""
    d = pipeline
    ckpt = str(tmp_path / "state")
    base = ["sgcn_tpu.train", "-a", str(d / "g.A.mtx"),
            "-p", str(d / "g.A.mtx.4.hp"), "-b", "cpu", "-s", "4",
            "-l", "2", "-f", "8", "--warmup", "0"]
    r = run_cli(base + ["--epochs", "3", "--save-checkpoint", ckpt])
    assert r.returncode == 0, r.stderr
    rep1 = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep1["checkpoint"].endswith(".npz")

    r = run_cli(base + ["--epochs", "2", "--resume", ckpt])
    assert r.returncode == 0, r.stderr
    # resumed optimization must start from the trained state, not re-init:
    # per-epoch loss lines print as "epoch 0: loss X"
    def first_epoch_loss(res):
        lines = (res.stdout + res.stderr).splitlines()
        return float([l for l in lines if l.startswith("epoch 0")][0]
                     .split()[-1])

    first_resumed = first_epoch_loss(r)
    first_fresh = first_epoch_loss(run_cli(base + ["--epochs", "1"]))
    assert first_resumed < first_fresh


def test_analysis_cli_fast_smoke():
    """``python -m sgcn_tpu.analysis --fast --json``: the AST hygiene pass
    plus the 2-mode HLO smoke subset, emitting the schema-validated JSON
    report on stdout with rc 0 — the CI face of the static-analysis
    subsystem (the full matrix runs in tests/test_analysis.py)."""
    r = run_cli(["sgcn_tpu.analysis", "--fast", "--json"])
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["schema"] == "sgcn_analysis_report" and rep["ok"] is True
    assert rep["fast"] is True
    assert rep["hlo"]["n_modes"] == 2 and rep["hlo"]["ok"] is True
    assert set(rep["ast"]["rules"]) == {
        "traced-host-free", "sanctioned-sync-only", "consumer-registered",
        "mode-flag-enumerated"}
    assert all(e["ok"] for e in rep["ast"]["rules"].values())


def test_package_dispatcher_lists_tools():
    r = run_cli(["sgcn_tpu"])
    assert r.returncode == 0, r.stderr
    for mod in ("sgcn_tpu.prep", "sgcn_tpu.partition", "sgcn_tpu.train",
                "sgcn_tpu.shp", "sgcn_tpu.baselines"):
        assert mod in r.stdout


def test_package_dispatcher_rejects_arguments():
    r = run_cli(["sgcn_tpu", "train", "-a", "x.mtx"])
    assert r.returncode == 2
    assert "sgcn_tpu.train" in r.stderr      # points at the real module


def test_train_cli_memory_budget_gate(pipeline):
    """ISSUE 18 acceptance shape: an over-budget (plan, mode) is rejected
    AT PLAN TIME — nonzero exit, the itemized per-family breakdown on
    stderr, no traceback (a clean SystemExit, not an OOM mid-compile);
    a generous budget trains normally."""
    d = pipeline
    base = ["sgcn_tpu.train", "-a", str(d / "g.A.mtx"),
            "-p", str(d / "g.A.mtx.4.hp"), "-b", "cpu", "-s", "4",
            "-l", "2", "-f", "6", "--epochs", "1"]
    r = run_cli([*base, "--memory-budget", "1K"])
    assert r.returncode == 1, r.stdout
    assert "exceeds --memory-budget 1,024 B" in r.stderr
    assert "per-family breakdown" in r.stderr
    assert "params" in r.stderr and "TOTAL" in r.stderr
    assert "Traceback" not in r.stderr
    r = run_cli([*base, "--memory-budget", "1G"])
    assert r.returncode == 0, r.stderr
    # a malformed size is an argparse error (exit 2), naming the flag
    r = run_cli([*base, "--memory-budget", "lots"])
    assert r.returncode == 2
    assert "--memory-budget" in r.stderr
