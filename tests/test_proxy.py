"""Shard-proxy fidelity: one chip's program of a k-way plan on one device."""

import numpy as np
import pytest

from sgcn_tpu.io.datasets import er_graph
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.parallel.proxy import shard_proxy_data, shard_proxy_plan
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.prep import normalize_adjacency


@pytest.fixture(scope="module")
def kplan():
    n, k = 3000, 4
    ahat = normalize_adjacency(er_graph(n, 8, seed=0))
    pv = balanced_random_partition(n, k, seed=1)
    return ahat, build_comm_plan(ahat, pv, k)


def test_proxy_plan_shapes(kplan):
    _, plan = kplan
    proxy = shard_proxy_plan(plan, chip=2)
    assert proxy.k == 1
    # padded per-chip shapes are untouched — the whole point
    assert (proxy.b, proxy.s, proxy.r, proxy.e) == \
        (plan.b, plan.s, plan.r, plan.e)
    # stacked arrays sliced to the chip; per-chip view keeps (k, S)
    assert proxy.send_idx.shape == (1,) + plan.send_idx.shape[1:]
    np.testing.assert_array_equal(proxy.send_idx[0], plan.send_idx[2])
    np.testing.assert_array_equal(proxy.ell_idx[0], plan.ell_idx[2])
    assert proxy.ell_buckets == plan.ell_buckets
    assert proxy.part_sizes.shape == (1,)
    # comm counters zero the TRUE self-slot (column 2), not [0, 0]
    assert proxy.predicted_send_volume[0] == plan.predicted_send_volume[2]
    assert proxy.predicted_message_count[0] == plan.predicted_message_count[2]
    from sgcn_tpu.utils.stats import CommStats
    st = CommStats.from_plan(proxy)
    assert st.send_volume_per_exchange[0] == plan.predicted_send_volume[2]
    assert st.recv_volume_per_exchange.shape == (1,)


def test_proxy_slicing_is_field_driven(kplan):
    """Slicing follows the plan's explicit classification, not a shape
    coincidence: an unclassified field that LOOKS per-chip-stacked fails
    loudly, and the classification list itself stays in sync with the
    dataclass (every listed non-None field really is (k, ...))."""
    import dataclasses

    from sgcn_tpu.parallel.plan import PER_CHIP_ARRAY_FIELDS

    from sgcn_tpu.parallel.plan import CommPlan

    _, plan = kplan
    # every classified, materialized field carries the stacked leading axis
    for name in PER_CHIP_ARRAY_FIELDS:
        v = getattr(plan, name)
        if v is not None:
            assert v.shape[0] == plan.k, name

    # a future field that looks per-chip-stacked but is unclassified must
    # raise, not silently slice or pass through whole
    @dataclasses.dataclass
    class RoguePlan(CommPlan):
        rogue_field: np.ndarray | None = None

    rogue = RoguePlan(
        **{f.name: getattr(plan, f.name) for f in dataclasses.fields(plan)},
        rogue_field=np.zeros((plan.k, 3), dtype=np.float32))
    with pytest.raises(ValueError, match="not classified"):
        shard_proxy_plan(rogue, chip=1)


def test_proxy_asymmetric_stats_fail_loudly(kplan):
    """CommStats on an asymmetric proxied plan must refuse to fabricate
    recv counters (round-5 advisor finding)."""
    import dataclasses

    from sgcn_tpu.utils.stats import CommStats

    _, plan = kplan
    proxy = shard_proxy_plan(plan, chip=0)
    asym = dataclasses.replace(proxy, symmetric=False)
    with pytest.raises(ValueError, match="ASYMMETRIC"):
        CommStats.from_plan(asym)


def test_proxy_trains_gcn_and_gat(kplan):
    """The proxy runs chip 0's full train step (send gather, halo gather,
    bucketed SpMM, backward, Adam) on a 1-device mesh with finite losses —
    for both model families.  Numerical values are NOT the 4-chip run's
    (halo contents are the chip's own sent rows); shapes, gather counts and
    flops are."""
    from sgcn_tpu.train import FullBatchTrainer

    ahat, plan = kplan
    n = plan.n
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((n, 16)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    proxy = shard_proxy_plan(plan, chip=0)
    data = shard_proxy_data(plan, 0, feats, labels)
    assert data.h0.shape == (1, plan.b, 16)

    for model in ("gcn", "gat"):
        tr = FullBatchTrainer(proxy, fin=16, widths=[8, 4], seed=2,
                              model=model)
        losses = tr.run_epochs(data, 3)
        assert np.all(np.isfinite(losses)), (model, losses)


def test_proxy_halo_buffer_materializes(kplan):
    """The size-1-axis optimization_barrier keeps the send-side gather in
    the compiled program (proxy fidelity: the real k-chip program gathers
    the send buffer before the exchange)."""
    import jax

    from sgcn_tpu.train import FullBatchTrainer

    _, plan = kplan
    proxy = shard_proxy_plan(plan, chip=0)
    tr = FullBatchTrainer(proxy, fin=16, widths=[8, 4], seed=2)
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((plan.n, 16)).astype(np.float32)
    labels = rng.integers(0, 4, plan.n).astype(np.int32)
    data = shard_proxy_data(plan, 0, feats, labels)
    txt = tr._step.lower(
        tr.params, tr.opt_state, tr.pa, data.h0, data.labels,
        data.train_valid).as_text()
    # one barrier per exchange: 2 layers x (fwd + bwd) collapse to the
    # custom-VJP pair's shared forward = at least 2 in the lowered module
    assert txt.count("optimization_barrier") >= 2
