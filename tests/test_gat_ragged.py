"""GAT over the ragged ppermute-ring schedule (``comm_schedule='ragged'``):
the multi-lane transport that makes ``--comm-schedule`` model-agnostic.

Contract pinned here (docs/comm_schedule.md, GAT section):

  * f32 BIT-parity with the dense a2a schedule on the 8-part cora fixture —
    losses and trained parameters exactly equal — for every table form the
    GAT forward ships: the fused ``(fout+1)``-lane ``[p ‖ u]`` table, the
    split feature+scalar pair (whose two dense dispatches collapse into one
    two-lane ring), and the packed-bf16 ``(fout/2+1)``-lane table
    (``SGCN_GAT_FUSED`` ∈ {0, 1, 2} × compute dtype {f32, bf16});
  * ``auto`` is model-agnostic: it selects ragged on a skewed partition /
    a2a on a well-packed one for GAT too (the scored wire-byte efficiency
    reduces to the row ratio — lane weights cancel, see
    ``resolve_comm_schedule``); the GCN-side Pallas-VMEM exception stays
    GCN-only;
  * the attribution and CommStats wire gauges carry the REAL GAT lane widths
    and reconcile exactly between the report and the obs event stream, under
    both schedules (the gauge-reconciliation smoke of the satellite task).
"""

import os

import numpy as np
import pytest
import scipy.sparse as sp

from sgcn_tpu.io.datasets import load_npz_dataset
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition.emit import read_partvec
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")

WIDTHS = [16, 7]


@pytest.fixture(scope="module")
def cora8():
    """The 8-vdev cora fixture of the acceptance criteria: real cora under
    its checked-in 8-part hp partition vector."""
    a, feats, labels = load_npz_dataset(os.path.join(FIX, "cora2708.npz"))
    ahat = normalize_adjacency(a)
    pv = read_partvec(os.path.join(FIX, "cora2708.8.hp"))
    plan = build_comm_plan(ahat, pv, 8)
    assert plan.symmetric
    return plan, feats.astype(np.float32), labels.astype(np.int32)


def ring_graph(n: int) -> sp.csr_matrix:
    i = np.arange(n)
    rows = np.concatenate([i, i])
    cols = np.concatenate([(i + 1) % n, (i - 1) % n])
    return sp.csr_matrix((np.ones(2 * n, np.float32), (rows, cols)),
                         shape=(n, n))


@pytest.fixture(scope="module")
def skewplan():
    """Ring graph under a contiguous 8-part cut — only 2 of the 7 ring
    rounds carry rows, padding efficiency far below the auto threshold."""
    n, k = 512, 8
    plan = build_comm_plan(normalize_adjacency(ring_graph(n)),
                           np.repeat(np.arange(k), n // k), k)
    assert plan.padding_efficiency() < 0.5
    return plan


# (compute dtype, SGCN_GAT_FUSED) — the full acceptance cross product.
# Form actually exercised per config: f32/0 = split pair (two dense
# dispatches vs ONE two-lane ring), f32/1 and f32/2 = fused (fout+1 fits a
# tile at these widths, so 1 and 2 compile the SAME table program), bf16/*
# = packed bit-pair table for the even-width layer and the bf16 fused (1/2)
# or split (0) table for the odd-width output layer.  Tier-1 runs the three
# NAMED table forms once each — split (f32/0), fused (f32/1), packed-bf16
# (bf16/1) — at ~40-60 s of 8-vdev GAT compile per config; the remaining
# cross-product points are slow-marked (forced-fused pins only the env
# lever at these widths; bf16/0 differs from bf16/1 only on the odd output
# layer's table) and run in the full `pytest tests/` suite.
FORMS = [(None, "0"), (None, "1"),
         pytest.param(None, "2", marks=pytest.mark.slow),
         pytest.param("bfloat16", "0", marks=pytest.mark.slow),
         ("bfloat16", "1"),
         pytest.param("bfloat16", "2", marks=pytest.mark.slow)]


def _form_id(p):
    d, f = (p.values if hasattr(p, "values") else p)
    return f"{d or 'f32'}-fused{f}"


@pytest.mark.parametrize("dtype,fused", FORMS,
                         ids=[_form_id(p) for p in FORMS])
def test_trainer_bit_identical_on_cora8(cora8, monkeypatch, dtype, fused):
    """THE acceptance contract: GAT trains under the ragged schedule with
    f32 losses and parameters bit-identical to the a2a path, per table
    form."""
    monkeypatch.setenv("SGCN_GAT_FUSED", fused)
    plan, feats, labels = cora8
    kw = dict(fin=feats.shape[1], widths=WIDTHS, model="gat",
              activation="none", seed=3, compute_dtype=dtype)
    tr_a = FullBatchTrainer(plan, **kw)
    tr_r = FullBatchTrainer(plan, comm_schedule="ragged", **kw)
    assert tr_r.comm_schedule == "ragged"
    data = make_train_data(plan, feats, labels)
    la = [tr_a.step(data) for _ in range(3)]
    lr = [tr_r.step(data) for _ in range(3)]
    assert la == lr                                  # bitwise, not allclose
    for pa, pr in zip(tr_a.params, tr_r.params):
        for key in ("w", "a1", "a2"):
            np.testing.assert_array_equal(np.asarray(pa[key]),
                                          np.asarray(pr[key]))
    # the two schedules agree on the true volume and disagree on the wire
    ra, rr = tr_a.stats.report(), tr_r.stats.report()
    assert ra["true_rows_per_exchange"] == rr["true_rows_per_exchange"]
    assert rr["wire_rows_per_exchange"] < ra["wire_rows_per_exchange"]
    assert rr["halo_bytes_wire_per_step"] < ra["halo_bytes_wire_per_step"]
    assert ra["halo_bytes_true_per_step"] == rr["halo_bytes_true_per_step"]


def test_auto_model_agnostic_select(skewplan, cora8):
    """'auto' is model-agnostic: ragged on the skewed partition, a2a on the
    well-packed hp cora plan, for GAT just like GCN."""
    tr = FullBatchTrainer(skewplan, fin=12, widths=[8, 4], model="gat",
                          activation="none", comm_schedule="auto")
    assert tr.comm_schedule == "ragged"

    plan, feats, _ = cora8
    if plan.padding_efficiency() >= 0.5:
        tr_b = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS,
                                model="gat", activation="none",
                                comm_schedule="auto")
        assert tr_b.comm_schedule == "a2a"


def test_auto_keeps_ragged_in_pallas_regime(skewplan, monkeypatch):
    """The old GCN-only VMEM exception is GONE (ISSUE 15): the Pallas
    aggregator is schedule-agnostic (``pspmm_pallas_ragged``), so on the
    same skewed plan 'auto' keeps ragged for BOTH models even with the
    kernel forced on — the transport and the kernel are now independent
    choices (kernel per degree bucket, after the transport resolves)."""
    from sgcn_tpu.ops.pallas_spmm import use_pallas_spmm
    from sgcn_tpu.parallel.plan import resolve_comm_schedule

    monkeypatch.setenv("SGCN_PALLAS_SPMM", "1")
    assert use_pallas_spmm(skewplan, 12, [8, 4])
    assert resolve_comm_schedule("auto", [skewplan], "gcn",
                                 fin=12, widths=[8, 4]) == "ragged"
    assert resolve_comm_schedule("auto", [skewplan], "gat",
                                 fin=12, widths=[8, 4]) == "ragged"


def test_gat_ragged_needs_symmetric(cora8):
    """Explicit ragged with an asymmetric edge pattern fails loudly at
    construction (the backward table rides the same ring)."""
    import dataclasses

    plan, feats, _ = cora8
    aplan = dataclasses.replace(plan, symmetric=False)
    with pytest.raises(ValueError, match="asymmetric"):
        FullBatchTrainer(aplan, fin=feats.shape[1], widths=WIDTHS,
                         model="gat", comm_schedule="ragged")


def test_gat_lane_widths_model():
    """The shared lane model: fused fout+1, packed fout/2+1, bf16-odd
    (fout+1)/2 f32-lane equivalents."""
    from sgcn_tpu.models.gat import gat_exchange_lane_widths

    assert gat_exchange_lane_widths([16, 7]) == [17, 8]
    assert gat_exchange_lane_widths([16, 7], "bfloat16") == [9, 4]
    assert gat_exchange_lane_widths([8], "bfloat16") == [5]


def test_gauge_reconciliation_smoke(cora8, tmp_path):
    """Satellite contract: CommStats' report and the obs event stream agree
    EXACTLY on GAT wire accounting — rows, real-lane-width bytes,
    efficiency, schedule — under both transports, with the ragged wire
    strictly below the dense one at equal true volume."""
    from sgcn_tpu.obs import RunRecorder, load_run

    plan, feats, labels = cora8
    data = make_train_data(plan, feats, labels)
    reports = {}
    for sched in ("a2a", "ragged"):
        d = tmp_path / sched
        tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS,
                              model="gat", activation="none", seed=1,
                              comm_schedule=sched)
        rec = RunRecorder(str(d), config={"model": "gat",
                                          "comm_schedule": sched})
        tr.attach_recorder(rec)
        for _ in range(2):
            tr.step(data)
        rec.close()
        report = tr.stats.report()
        for ev in load_run(str(d)).steps():
            comm, roof = ev["comm"], ev["roofline"]
            assert comm["comm_schedule"] == roof["comm_schedule"] == sched
            assert comm["wire_rows_per_exchange"] == \
                roof["halo_wire_rows_per_exchange"]
            assert comm["padding_efficiency"] == roof["padding_efficiency"]
            assert comm["halo_bytes_true_per_step"] == \
                roof["halo_bytes_true_per_step"]
            assert comm["halo_bytes_wire_per_step"] == \
                roof["halo_bytes_wire_per_step"]
            assert roof["halo_bytes_wire_per_step"] >= \
                roof["halo_bytes_true_per_step"]
        reports[sched] = report
    assert reports["a2a"]["halo_bytes_true_per_step"] == \
        reports["ragged"]["halo_bytes_true_per_step"]
    assert reports["ragged"]["halo_bytes_wire_per_step"] < \
        reports["a2a"]["halo_bytes_wire_per_step"]
    # the byte gauges are the lane-weighted form of the row gauges
    from sgcn_tpu.models.gat import gat_exchange_lane_widths
    lane_b = 2 * sum(gat_exchange_lane_widths(WIDTHS)) * 4
    for sched, rep in reports.items():
        assert rep["halo_bytes_true_per_step"] == \
            rep["true_rows_per_exchange"] * lane_b
        assert rep["halo_bytes_wire_per_step"] == \
            rep["wire_rows_per_exchange"] * lane_b


def test_minibatch_gat_ragged_shared_envelope():
    """The mini-batch trainer composes with GAT + ragged: shared per-round
    envelope, bit-identical to its a2a twin batch for batch."""
    from sgcn_tpu.train.minibatch import MiniBatchTrainer

    n, k = 512, 8
    ahat = normalize_adjacency(ring_graph(n))
    pv = np.repeat(np.arange(k), n // k)
    rng = np.random.default_rng(4)
    feats = rng.standard_normal((n, 12)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    kw = dict(fin=12, widths=[8, 4], batch_size=128, nbatches=2, seed=4,
              model="gat", activation="none")
    tr_a = MiniBatchTrainer(ahat, pv, k, comm_schedule="a2a", **kw)
    tr_r = MiniBatchTrainer(ahat, pv, k, comm_schedule="ragged", **kw)
    assert tr_r.inner.comm_schedule == "ragged"
    assert len({p.rr_sizes for p in tr_r.plans}) == 1   # shared envelope
    ba = tr_a.make_batches(feats, labels)
    br = tr_r.make_batches(feats, labels)
    la = [tr_a.step(b) for b in ba]
    lr = [tr_r.step(b) for b in br]
    assert la == lr                                  # bitwise, not allclose
