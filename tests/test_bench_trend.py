"""Tier-1 gate: the bench trend contract (``scripts/bench_trend.py``).

Two halves:

  * the checked-in ``BENCH_r*.json`` history passes ``--check`` — wiring
    the so-far-unused bench trajectory into CI as an enforced contract
    (a landed regression fails the suite the commit it lands);
  * the gate's own semantics — tolerance bands per metric kind,
    degradation-marker awareness (a degraded round is a gap, never a
    comparison point), deterministic-counter strictness — pinned on
    synthetic histories, including the synthetic REGRESSED artifact the
    acceptance criteria require to fail.

Plus the measured-provenance rule ``scripts/validate_bench.py`` grew with
the trend gate: an epoch-time claim from round 6 on must say it was
measured live (``measured: true``) or carry a degradation marker.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from bench_trend import (DEFAULT_TIME_BAND, check_series, check_tree,  # noqa: E402
                         extract_series, load_history)
from validate_bench import check_measured_provenance  # noqa: E402


def _rec(value, metric="fullbatch_gcn_epoch_time", rc=0, **parsed_extra):
    parsed = {"metric": metric, "value": value, "unit": "s",
              "measured": True, **parsed_extra}
    return {"n": 1, "cmd": "python bench.py", "rc": rc, "tail": "x",
            "parsed": parsed}


def _write_history(tmp_path, records):
    for rnd, rec in records:
        with open(tmp_path / f"BENCH_r{rnd:02d}.json", "w") as fh:
            json.dump(rec, fh)
    return str(tmp_path)


def test_checked_in_history_passes_the_gate():
    problems, report = check_tree(REPO)
    assert not problems, "\n".join(problems)
    assert "fullbatch_gcn_epoch_time" in report
    assert "gate: clean" in report


def test_gate_fails_on_synthetic_regressed_artifact(tmp_path):
    """The acceptance shape: append one regressed round to a healthy
    history and --check must fail naming the series."""
    # band anchor = median of previous points (0.30, 0.10) = 0.20
    root = _write_history(tmp_path, [
        (1, _rec(0.30)), (2, _rec(0.10)),
        (3, _rec(0.20 * DEFAULT_TIME_BAND * 2)),   # 2x outside the band
    ])
    problems, report = check_tree(root)
    assert len(problems) == 1
    assert "fullbatch_gcn_epoch_time" in problems[0]
    assert "regression" in problems[0]
    assert "VIOLATIONS" in report
    # the same history minus the bad round is clean
    os.remove(os.path.join(root, "BENCH_r03.json"))
    problems, _ = check_tree(root)
    assert not problems


def test_gate_anchor_is_median_not_best(tmp_path):
    """One lucky fast outlier must not permanently tighten the gate: the
    band anchors on the MEDIAN previous point, and the default band sits
    above this host's documented 1.665x cross-session drift (BASELINE.md:
    identical code 2.18 s vs 3.63 s)."""
    assert DEFAULT_TIME_BAND > 1.665
    root = _write_history(tmp_path, [
        (1, _rec(0.30)), (2, _rec(0.02)),          # r02 is a lucky outlier
        (3, _rec(0.30)),   # normal again — a best-anchored 2x band (0.04)
    ])                     # would flag it; median anchor 0.16 clears it
    problems, _ = check_tree(root)
    assert not problems


def test_gate_is_degradation_marker_aware(tmp_path):
    """A degraded/skipped/rc!=0 round is a GAP: reported, never compared —
    so it can neither fake a regression nor hide one by becoming the
    'best previous' point."""
    root = _write_history(tmp_path, [
        (1, _rec(0.30)),
        # marked null — and its partial 8-dev diagnostic counters must NOT
        # enter the zero-band series either
        (2, _rec(None, degraded="flagship deadline", km1_8dev=99999,
                 n_8dev=40000, graph_8dev="ba", partitioner_8dev="hp")),
        (3, {"n": 1, "cmd": "x", "rc": 124, "tail": "timeout"}),  # hard fail
        (4, _rec(0.25)),
    ])
    series, gaps = extract_series(load_history(root))
    key = ("time", "fullbatch_gcn_epoch_time", "er", "s",
           None, None, None, None, None, None)
    assert [r for r, _ in series[key]] == [1, 4]
    assert [r for r, _ in gaps] == [2, 3]
    assert "deadline" in gaps[0][1]
    assert not any(k[0] == "counter" for k in series)
    assert not check_series(series)


def test_gate_only_bands_wall_clock_units(tmp_path):
    """Only unit == "s" series are gate-able (lower-is-better by
    construction); a throughput-style metric improving UPWARD forms a
    report-only series and must not trip the band."""
    root = _write_history(tmp_path, [
        (1, _rec(10.0, metric="minibatch_throughput", unit="it/s")),
        (2, _rec(20.0, metric="minibatch_throughput", unit="it/s")),
    ])
    series, _ = extract_series(load_history(root))
    key = ("metric", "minibatch_throughput", "er", "it/s",
           None, None, None, None, None, None)
    assert [v for _, v in series[key]] == [10.0, 20.0]
    assert not check_series(series)
    # ...and the report labels the trend neutrally (an upward throughput
    # series is not a "regression")
    problems, report = check_tree(root)
    assert not problems
    assert "net change: 10 -> 20" in report
    assert "regression" not in report


def test_gate_scopes_series_by_config(tmp_path):
    """A config change (different graph family) starts a NEW series — a
    slower number on a different workload is not a regression."""
    root = _write_history(tmp_path, [
        (1, _rec(0.05, graph="er")),
        (2, _rec(0.50, graph="ba")),       # 10x slower, different graph
    ])
    series, _ = extract_series(load_history(root))
    assert not check_series(series)
    # scalar bench-config fields scope a wall-clock series too: a bigger
    # problem size is a different measurement, not a regression — and
    # partitioner "none" normalizes to absent (the r01/r02 history shape)
    (tmp_path / "cfg").mkdir()
    root2 = _write_history(tmp_path / "cfg", [
        (1, _rec(0.05)),
        (2, _rec(0.05, partitioner="none")),
        (3, _rec(5.00, n=200000)),         # 100x slower at a bigger n
    ])
    series, _ = extract_series(load_history(root2))
    assert not check_series(series)
    key = ("time", "fullbatch_gcn_epoch_time", "er", "s",
           None, None, None, None, None, None)
    assert [r for r, _ in series[key]] == [1, 2]   # 'none' == absent
    # render survives the mixed None/int cfg slots in series keys
    problems, report = check_tree(root2)
    assert not problems
    assert "n=200000" in report


def test_gate_rejects_non_finite_values(tmp_path):
    """A NaN/Infinity value must not enter a series: every NaN comparison
    is False, so one poisoned point (or median anchor) would make the gate
    read clean forever."""
    root = _write_history(tmp_path, [(1, _rec(0.10)), (2, _rec(0.10))])
    with open(tmp_path / "BENCH_r03.json", "w") as fh:
        fh.write('{"n": 3, "cmd": "x", "rc": 0, "tail": "x", "parsed": '
                 '{"metric": "fullbatch_gcn_epoch_time", "value": NaN, '
                 '"unit": "s", "measured": true}}')
    series, _ = extract_series(load_history(root))
    key = ("time", "fullbatch_gcn_epoch_time", "er", "s",
           None, None, None, None, None, None)
    assert [r for r, _ in series[key]] == [1, 2]   # NaN round excluded
    assert not check_series(series)


def test_gate_zero_band_for_deterministic_counters(tmp_path):
    """Plan-derived counters (km1, comm rows) are reproducible bit-for-bit:
    within one diagnostic config they may never increase."""
    base = dict(n_8dev=40000, graph_8dev="ba", partitioner_8dev="hp")
    root = _write_history(tmp_path, [
        (1, _rec(0.05, km1_8dev=1000, **base)),
        (2, _rec(0.05, km1_8dev=1001, **base)),      # +1 row regression
    ])
    problems = check_series(extract_series(load_history(root))[0])
    assert any("km1_8dev" in p and "never regress" in p for p in problems)
    # a DIFFERENT config's larger km1 is a new series, not a violation
    (tmp_path / "o").mkdir()
    root2 = _write_history(tmp_path / "o", [
        (1, _rec(0.05, km1_8dev=1000, **base)),
        (2, _rec(0.05, km1_8dev=9999, **dict(base, n_8dev=120000))),
    ])
    assert not check_series(extract_series(load_history(root2))[0])


def test_pallas_ragged_counters_registered_zero_band(tmp_path):
    """The kernel × schedule A/B counters (ISSUE 15) register as zero-band
    series scoped on (n, graph, k); the zero-halo-table contract of the
    pallas ragged arm is literally a zero that may never move."""
    def _prab(halo_bytes):
        return {"pallas_ragged_ab_8dev": {
            "n": 12000, "graph": "ba", "k": 8,
            "ell_ragged": {"epoch_s": 0.1, "measured": True,
                           "wire_rows_per_exchange": 24096,
                           "halo_table_bytes_per_step": 0},
            "pallas_ragged": {"epoch_s": 0.2, "measured": True,
                              "wire_rows_per_exchange": 24096,
                              "halo_table_bytes_per_step": halo_bytes},
            "pallas_a2a": {"epoch_s": 0.2, "measured": True,
                           "wire_rows_per_exchange": 28736,
                           "halo_table_bytes_per_step": 1000}}}

    root = _write_history(tmp_path, [
        (1, _rec(0.05, **_prab(0))), (2, _rec(0.05, **_prab(4096)))])
    series, _ = extract_series(load_history(root))
    key = [k for k in series
           if k[1] == "pallas_ragged_pallas_ragged_halo_table_bytes_per_step"]
    assert key and series[key[0]] == [(1, 0.0), (2, 4096.0)]
    problems = check_series(series)
    assert any("halo_table_bytes_per_step" in p and "never regress" in p
               for p in problems)
    # emulate-mode epoch times are NOT tracked series (never a CPU claim)
    assert not any("pallas" in k[1] and "epoch" in k[1] for k in series)


def test_cli_check_mode_exit_codes(tmp_path):
    """--check is the gate (rc 1 on violation); report mode always rc 0."""
    root = _write_history(tmp_path, [(1, _rec(0.10)), (2, _rec(0.90))])
    script = os.path.join(REPO, "scripts", "bench_trend.py")
    r = subprocess.run([sys.executable, script, root, "--check"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "VIOLATIONS" in r.stdout
    r = subprocess.run([sys.executable, script, root],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    r = subprocess.run([sys.executable, script, root, "--check",
                        "--time-band", "20"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0               # per-metric band is a dial


# ------------------------------------------------- measured provenance rule

def test_epoch_time_claims_need_measured_provenance():
    """From round 6 on, a numeric epoch-time value must carry
    measured:true or a degradation marker; earlier rounds are
    grandfathered (retro-stamping provenance onto history would itself be
    a hand-edit)."""
    naked = {"n": 7, "cmd": "x", "rc": 0, "tail": "",
             "parsed": {"metric": "fullbatch_gcn_epoch_time", "value": 0.1,
                        "unit": "s"}}
    errs = check_measured_provenance(naked, 7)
    assert any("measured:true" in e for e in errs)
    # round 6 is the FIRST enforced round: the checked-in history ends at
    # r05, so the next generated record must not slip through the gate
    assert check_measured_provenance(naked, 6)
    assert not check_measured_provenance(naked, 5)       # grandfathered
    assert not check_measured_provenance(naked, 4)       # grandfathered
    ok = json.loads(json.dumps(naked))
    ok["parsed"]["measured"] = True
    assert not check_measured_provenance(ok, 7)
    degraded = json.loads(json.dumps(naked))
    degraded["parsed"]["value"] = None
    degraded["parsed"]["degraded"] = "deadline"
    assert not check_measured_provenance(degraded, 9)
    # a present-but-untrue flag is a violation at ANY round
    lying = json.loads(json.dumps(naked))
    lying["parsed"]["measured"] = "yes"
    assert any("live measurement" in e
               for e in check_measured_provenance(lying, 3))
    # ...including on a FAILED round (rc != 0) — exactly the hand-edit
    # shape the rule exists to catch; only the numeric-claim rule is
    # rc-gated
    failed_lying = json.loads(json.dumps(lying))
    failed_lying["rc"] = 1
    assert any("live measurement" in e
               for e in check_measured_provenance(failed_lying, 7))
    failed_clean = json.loads(json.dumps(naked))
    failed_clean["rc"] = 1
    assert not check_measured_provenance(failed_clean, 7)


def test_bench_emits_the_measured_flag():
    """bench.py's flagship and minibatch emissions carry measured: True
    next to the live differential value (string-level pin: the flag's
    emission site sits right where the value is rounded in)."""
    with open(os.path.join(REPO, "bench.py")) as fh:
        src = fh.read()
    assert src.count('"measured": True') >= 2


def _serve_rec(p50, wire_q, nnz=160000):
    arms = {"a2a": {"achieved_qps": 40.0, "latency_p50_ms": p50,
                    "latency_p99_ms": p50 * 3,
                    "wire_rows_per_exchange": 1000,
                    "wire_rows_per_query": 187.5},
            "ragged": {"achieved_qps": 42.0, "latency_p50_ms": p50,
                       "latency_p99_ms": p50 * 3,
                       "wire_rows_per_exchange": 600,
                       "wire_rows_per_query": wire_q}}
    return _rec(0.1, serve_qps_8dev={
        "n": 20000, "graph": "ba", "nnz": nnz, "nlayers": 2, "k": 8,
        "offered_qps": 50.0, "max_batch": 16, "measured": True,
        "arms": arms})


def test_serve_series_registration(tmp_path):
    """The serving series after ISSUE 18: measured QPS stays REPORT-ONLY
    (no universal better-direction once arms saturate differently), the
    latency quantiles register under the GATED "latency" kind, and the
    plan-derived wire-row gauges stay zero-band counters scoped to the
    serve config; a wire-row increase within one config trips the gate."""
    from bench_trend import _SERVE_CFG_KEYS

    root = _write_history(tmp_path, [
        (1, _serve_rec(4.0, 112.5)), (2, _serve_rec(5.0, 112.5)),
    ])
    block = _serve_rec(0, 0)["parsed"]["serve_qps_8dev"]
    cfg = tuple(block[k] for k in _SERVE_CFG_KEYS)
    series, _ = extract_series(load_history(root))
    lat_key = ("latency", "serve_ragged_latency_p50_ms", "serve", "ms") + cfg
    assert [v for _, v in series[lat_key]] == [4.0, 5.0]
    qps_key = ("metric", "serve_ragged_achieved_qps", "serve", "qps") + cfg
    assert qps_key in series            # QPS: still report-only
    ctr_key = ("counter", "serve_ragged_wire_rows_per_query") + cfg
    assert [v for _, v in series[ctr_key]] == [112.5, 112.5]
    assert not check_series(series)     # +25% p50: inside the 2x band
    # a denser graph (different nnz) is a NEW series, not a regression
    with open(os.path.join(root, "BENCH_r03.json"), "w") as fh:
        json.dump(_serve_rec(4.0, 300.0, nnz=640000), fh)
    series, _ = extract_series(load_history(root))
    assert not check_series(series)
    # but a wire-row regression within ONE config DOES trip the zero band
    with open(os.path.join(root, "BENCH_r04.json"), "w") as fh:
        json.dump(_serve_rec(4.0, 150.0), fh)
    series, _ = extract_series(load_history(root))
    problems = check_series(series)
    assert any("serve_ragged_wire_rows_per_query" in p for p in problems)


def test_serve_latency_gate_trips_on_regression(tmp_path):
    """ISSUE 18 satellite: serve latency is no longer report-only — a
    quantile beyond the 2x median-anchored band fails --check with the
    serve-latency message (the same synthetic-regressed-artifact shape the
    wall-clock gate is pinned with)."""
    root = _write_history(tmp_path, [
        (1, _serve_rec(4.0, 112.5)), (2, _serve_rec(5.0, 112.5)),
        (3, _serve_rec(4.5, 112.5)),
        (4, _serve_rec(4.5 * DEFAULT_TIME_BAND * 2, 112.5)),
    ])
    problems = check_series(extract_series(load_history(root))[0])
    lat_hits = [p for p in problems if "latency" in p]
    assert lat_hits, problems
    assert any("serve-latency regression" in p for p in lat_hits)
    # both quantiles of both arms regressed in the synthetic record
    assert any("serve_ragged_latency_p99_ms" in p for p in lat_hits)


def test_memory_footprint_counters_zero_band(tmp_path):
    """ISSUE 18 satellite: the analytic per-chip footprint gauges register
    as zero-band counters scoped by (n, nnz, k) — a byte of growth in any
    family within one config trips the gate; a different graph size is a
    new series."""
    from bench_trend import _MEMORY_CFG_KEYS

    def mem_rec(ws, nnz=160000):
        return _rec(0.1, memory_footprint_8dev={
            "n": 20000, "nnz": nnz, "k": 8, "graph": "ba", "fin": 32,
            "nlayers": 2, "analytic": True, "modes": {
                "train_gcn_a2a": {"analytic": True, "model_bytes": 1000 + ws,
                                  "params_bytes": 400,
                                  "workspace_bytes": ws},
            }})

    root = _write_history(tmp_path, [(1, mem_rec(600)), (2, mem_rec(600))])
    series, _ = extract_series(load_history(root))
    cfg = tuple(mem_rec(0)["parsed"]["memory_footprint_8dev"][k]
                for k in _MEMORY_CFG_KEYS)
    key = ("counter", "memory_train_gcn_a2a_workspace_bytes") + cfg
    assert [v for _, v in series[key]] == [600.0, 600.0]
    assert ("counter", "memory_train_gcn_a2a_model_bytes") + cfg in series
    assert not check_series(series)
    # a different nnz scopes a fresh series — no cross-config comparison
    with open(os.path.join(root, "BENCH_r03.json"), "w") as fh:
        json.dump(mem_rec(9000, nnz=640000), fh)
    series, _ = extract_series(load_history(root))
    assert not check_series(series)
    # one byte of growth within the SAME config is a regression
    with open(os.path.join(root, "BENCH_r04.json"), "w") as fh:
        json.dump(mem_rec(601), fh)
    problems = check_series(extract_series(load_history(root))[0])
    assert any("memory_train_gcn_a2a_workspace_bytes" in p
               for p in problems), problems
    assert any("may never regress" in p for p in problems)
