"""I/O and preprocessing tests (reference: preprocess/GrB-GNN-IDG.py)."""

import numpy as np
import scipy.sparse as sp

from sgcn_tpu.io import ModelConfig, read_config, read_mtx, write_config, write_mtx
from sgcn_tpu.prep import normalize_adjacency, preprocess, synthetic_features, synthetic_labels


def test_mtx_roundtrip(tmp_path, graph):
    p = str(tmp_path / "g.mtx")
    write_mtx(p, graph)
    back = read_mtx(p)
    assert (back != graph).nnz == 0


def test_config_roundtrip(tmp_path):
    cfg = ModelConfig(nlayers=3, nvtx=100, widths=[16, 16, 4])
    p = str(tmp_path / "config")
    write_config(p, cfg)
    back = read_config(p)
    assert back == cfg
    assert back.nout == 4
    assert back.layer_dims(8) == [(8, 16), (16, 16), (16, 4)]


def test_normalize_golden():
    # path graph 0-1-2: A+I degrees are [2,3,2] on rows and cols.
    a = sp.csr_matrix(np.array([[0, 1, 0], [1, 0, 1], [0, 1, 0]], dtype=np.float32))
    ah = normalize_adjacency(a).toarray()
    d = np.array([2.0, 3.0, 2.0])
    expected = (np.array([[1, 1, 0], [1, 1, 1], [0, 1, 1]], dtype=np.float32)
                / np.sqrt(d)[:, None] / np.sqrt(d)[None, :])
    np.testing.assert_allclose(ah, expected, rtol=1e-6)


def test_normalize_strips_and_adds_self_loops():
    # existing self-loop must be stripped then identity re-added exactly once
    a = sp.csr_matrix(np.array([[5, 1], [1, 0]], dtype=np.float32))
    ah = normalize_adjacency(a).toarray()
    # degrees of (A-diag+I): each row/col has 2 nnz
    np.testing.assert_allclose(ah, np.full((2, 2), 0.5), rtol=1e-6)


def test_preprocess_outputs(tmp_path, graph):
    cfg = preprocess(graph, str(tmp_path), "er", nlayers=2, hidden=8, nclasses=3)
    assert cfg.nvtx == graph.shape[0]
    assert cfg.widths == [8, 3]
    a = read_mtx(str(tmp_path / "er.A.mtx"))
    h = read_mtx(str(tmp_path / "er.H.mtx"))
    y = read_mtx(str(tmp_path / "er.Y.mtx"))
    assert a.shape == graph.shape
    assert (a.diagonal() > 0).all()          # self-loops present
    assert h.shape[0] == cfg.nvtx and (h.toarray() == 1).all()
    assert y.shape == (cfg.nvtx, 3)
    np.testing.assert_array_equal(np.asarray(y.sum(axis=1)).ravel(), 1.0)
    assert read_config(str(tmp_path / "config")) == cfg


def test_synthetic_shapes():
    h = synthetic_features(10, 4)
    y = synthetic_labels(10, 2, seed=3)
    assert h.shape == (10, 4)
    assert y.shape == (10, 2)
