"""The cost models' shared project-first rule must match the compiled
program: ``models/gcn.py::exchange_widths`` (used by the bench roofline and
the 8-chip epoch model) vs the actual all_to_all lane widths in the lowered
train step."""

import re

import numpy as np
import pytest

from sgcn_tpu.io.datasets import er_graph
from sgcn_tpu.models.gcn import exchange_widths
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.parallel.mesh import shard_stacked
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data


def _lowered_a2a_widths(fin, widths):
    n, k = 1200, 4
    ahat = normalize_adjacency(er_graph(n, 6, seed=0))
    pv = balanced_random_partition(n, k, seed=1)
    plan = build_comm_plan(ahat, pv, k)
    tr = FullBatchTrainer(plan, fin=fin, widths=widths, seed=0)
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((n, fin)).astype(np.float32)
    labels = rng.integers(0, widths[-1], n).astype(np.int32)
    data = make_train_data(plan, feats, labels)
    data = type(data)(**shard_stacked(tr.mesh, vars(data)))
    txt = tr._step.lower(
        tr.params, tr.opt_state, tr.pa, data.h0, data.labels,
        data.train_valid).as_text()
    # all_to_all operands are (k, S, f) buffers — the trailing dim is the
    # exchanged lane width
    dims = [int(m.group(1)) for m in re.finditer(
        r'stablehlo\.all_to_all.*?->\s*tensor<\d+x\d+x(\d+)xf32>', txt)]
    assert dims, "no all_to_all in lowered step"
    return sorted(set(dims))


@pytest.mark.parametrize("fin,widths", [
    (12, [8, 4]),          # aggregate-first everywhere (narrow inputs)
    (300, [8, 4]),         # wide input: layer 1 projects first, ships 8
])
def test_exchange_widths_match_lowered_program(fin, widths):
    want = sorted(set(exchange_widths(fin, widths)))
    got = _lowered_a2a_widths(fin, widths)
    assert got == want, (got, want)
