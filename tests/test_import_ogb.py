"""OGB/Reddit import path (VERDICT r4 item 6), driven on synthetic
directories that mimic each on-disk layout — the real downloads need egress
this box lacks; the converter is what must be ready."""

import gzip
import os
import subprocess
import sys

import numpy as np
import pytest
import scipy.sparse as sp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "import_ogb.py")


def _fake_ogb(root, n=60, f=5, ncls=4, seed=0):
    """Materialize the raw-CSV layout the ogb package writes."""
    rng = np.random.default_rng(seed)
    raw = os.path.join(root, "raw")
    os.makedirs(raw)
    # a directed edge list (arxiv-style): the importer must symmetrize
    m = 4 * n
    edges = rng.integers(0, n, size=(m, 2))
    edges = edges[edges[:, 0] != edges[:, 1]]
    with gzip.open(os.path.join(raw, "edge.csv.gz"), "wt") as fh:
        for s, d in edges:
            fh.write(f"{s},{d}\n")
    feats = rng.standard_normal((n, f)).astype(np.float32)
    with gzip.open(os.path.join(raw, "node-feat.csv.gz"), "wt") as fh:
        for row in feats:
            fh.write(",".join(f"{x:.6f}" for x in row) + "\n")
    labels = rng.integers(0, ncls, n)
    with gzip.open(os.path.join(raw, "node-label.csv.gz"), "wt") as fh:
        fh.write("\n".join(str(x) for x in labels) + "\n")
    sd = os.path.join(root, "split", "time")
    os.makedirs(sd)
    perm = rng.permutation(n)
    cuts = {"train": perm[: n // 2], "valid": perm[n // 2: 3 * n // 4],
            "test": perm[3 * n // 4:]}
    for name, idx in cuts.items():
        with gzip.open(os.path.join(sd, f"{name}.csv.gz"), "wt") as fh:
            fh.write("\n".join(str(x) for x in sorted(idx)) + "\n")
    return edges, feats, labels, cuts


def _run(args):
    return subprocess.run([sys.executable, SCRIPT, *args],
                          capture_output=True, text=True, cwd=REPO)


def test_import_ogb_layout(tmp_path):
    root = tmp_path / "ogbn_tiny"
    edges, feats, labels, cuts = _fake_ogb(str(root))
    out = str(tmp_path / "tiny")
    r = _run([str(root), "--kind", "ogb", "-o", out])
    assert r.returncode == 0, r.stderr

    from sgcn_tpu.io.datasets import load_npz_dataset
    a, f2, y2 = load_npz_dataset(out + ".npz")
    assert (a != a.T).nnz == 0, "importer must symmetrize"
    assert a.diagonal().sum() == 0
    np.testing.assert_allclose(f2, feats, atol=1e-5)
    np.testing.assert_array_equal(y2, labels)
    # every original directed edge is present in the symmetric graph
    al = a.tolil()
    for s, d in edges[:50]:
        assert al[s, d] != 0 and al[d, s] != 0
    z = np.load(out + ".splits.npz")
    for name, idx in cuts.items():
        m = z[f"{name}_mask"]
        np.testing.assert_array_equal(np.flatnonzero(m), np.sort(idx))

    # ...and the output feeds the real trainer pipeline end to end
    from sgcn_tpu.parallel import build_comm_plan
    from sgcn_tpu.partition import balanced_random_partition
    from sgcn_tpu.prep import normalize_adjacency
    from sgcn_tpu.train import FullBatchTrainer, make_train_data
    ahat = normalize_adjacency(a)
    plan = build_comm_plan(ahat, balanced_random_partition(a.shape[0], 2), 2)
    tr = FullBatchTrainer(plan, fin=f2.shape[1],
                          widths=[8, int(y2.max()) + 1])
    data = make_train_data(plan, f2, y2, train_mask=z["train_mask"],
                           eval_mask=z["test_mask"])
    assert np.isfinite(tr.step(data))


def test_import_reddit_layout(tmp_path):
    rng = np.random.default_rng(1)
    n, f = 50, 6
    root = tmp_path / "reddit"
    os.makedirs(root)
    feats = rng.standard_normal((n, f)).astype(np.float32)
    labels = rng.integers(0, 5, n)
    nt = rng.choice([1, 2, 3], size=n, p=[0.6, 0.2, 0.2])
    np.savez(root / "reddit_data.npz", feature=feats, label=labels,
             node_types=nt)
    coo = sp.random(n, n, density=0.1, random_state=2, format="coo")
    np.savez(root / "reddit_graph.npz", data=coo.data.astype(np.float32),
             row=coo.row, col=coo.col)
    out = str(tmp_path / "reddit_out")
    r = _run([str(root), "--kind", "reddit", "-o", out])
    assert r.returncode == 0, r.stderr
    from sgcn_tpu.io.datasets import load_npz_dataset
    a, f2, y2 = load_npz_dataset(out + ".npz")
    assert (a != a.T).nnz == 0
    np.testing.assert_allclose(f2, feats, atol=1e-5)
    z = np.load(out + ".splits.npz")
    assert int(z["train_mask"].sum()) == int((nt == 1).sum())


def test_import_npz_passthrough(tmp_path):
    from sgcn_tpu.io.datasets import er_graph, save_npz_dataset
    rng = np.random.default_rng(3)
    n = 80
    a = er_graph(n, 4, seed=0)
    feats = sp.random(n, 9, density=0.3, random_state=1, format="csr")
    labels = rng.integers(0, 3, n)
    src = str(tmp_path / "cora_like.npz")
    save_npz_dataset(src, a, feats, labels)
    out = str(tmp_path / "cora_out")
    r = _run([src, "--kind", "npz", "-o", out])
    assert r.returncode == 0, r.stderr
    z = np.load(out + ".splits.npz")
    assert z["train_mask"].sum() > 0 and z["test_mask"].sum() > 0
    assert not np.any(z["train_mask"] * z["test_mask"])
