"""Trainer-CLI telemetry smoke on the cora fixture (tier-1).

One child run covers the whole acceptance surface of the run-telemetry
subsystem: ``--profile DIR`` (profiler trace directory created, non-empty)
plus ``--metrics-out DIR`` (manifest + per-step JSONL) in stale-halo mode,
so the events must carry

  * comm fields that EXACTLY reconcile with the final ``CommStats.report()``
    line the CLI prints (hidden + exposed == total, volumes included);
  * roofline utilization populated from the analytic cost model;
  * drift-gauge fields, present and finite, with the full-sync schedule
    visible in ``sync_step``/``staleness_age``;
  * the measured-time layer (PR-7): span events for every step/epoch
    phase, a per-step ``measured_vs_model`` block whose measured
    phase-time total reconciles with ``PhaseTimer.report()`` to <1%, and
    a manifest ``profile`` block pointing at a parseable profiler trace;

and ``scripts/obs_report.py`` must render the directory.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "fixtures")


@pytest.fixture(scope="module")
def telemetry_run(tmp_path_factory):
    """ONE CLI child shared by every assertion below (the child pays the
    jax-import + compile cost once; tier-1 budget discipline)."""
    d = tmp_path_factory.mktemp("obs")
    prof, metrics = str(d / "prof"), str(d / "run")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # let -b cpu set its own device count
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, "-m", "sgcn_tpu.train",
         "--npz", os.path.join(FIX, "cora_like.npz"),
         "-p", os.path.join(FIX, "cora_like.4.hp"),
         "-b", "cpu", "-s", "4", "-l", "2", "--normalize",
         "--epochs", "3", "--warmup", "1",
         "--halo-staleness", "1", "--sync-every", "2",
         "--profile", prof, "--metrics-out", metrics],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    assert r.returncode == 0, r.stderr
    report = json.loads(r.stdout.strip().splitlines()[-1])
    return prof, metrics, report


def test_profile_trace_written(telemetry_run):
    prof, _, _ = telemetry_run
    traces = []
    for root, _dirs, files in os.walk(prof):
        traces += [f for f in files
                   if f.endswith((".xplane.pb", ".trace.json.gz"))]
    assert traces, f"no profiler trace files under {prof}"


def test_manifest_and_events_validate(telemetry_run):
    _, metrics, _ = telemetry_run
    from sgcn_tpu.obs import load_run
    log = load_run(metrics)             # load_run re-validates every record
    m = log.manifest
    assert m["run_kind"] == "train"
    assert m["plan"]["k"] == 4 and m["plan"]["symmetric"] is True
    assert len(m["plan"]["digest"]) == 16
    assert m["partitioner"]["partvec"].endswith("cora_like.4.hp")
    assert m["backend"]["device_count"] == 4
    assert len(log.steps()) == 4        # 1 warmup + 3 timed epochs
    assert len(log.summaries()) == 1


def test_step_comm_reconciles_with_commstats_report(telemetry_run):
    """hidden + exposed == total, and the LAST step's cumulative snapshot
    equals the end-of-run CommStats.report() line the CLI printed."""
    _, metrics, report = telemetry_run
    from sgcn_tpu.obs import load_run
    steps = load_run(metrics).steps()
    for ev in steps:
        c = ev["comm"]
        assert (c["exposed_exchanges"] + c["hidden_exchanges"]
                == c["exchanges"])
        assert (c["exposed_send_volume"] + c["hidden_send_volume"]
                == c["total_send_volume"])
    last = steps[-1]["comm"]
    for key in ("exchanges", "exposed_exchanges", "hidden_exchanges",
                "total_send_volume", "exposed_send_volume",
                "hidden_send_volume", "max_send_volume", "total_send_msgs"):
        assert last[key] == report[key], (key, last[key], report[key])


def test_roofline_populated_from_cost_model(telemetry_run):
    _, metrics, _ = telemetry_run
    from sgcn_tpu.obs import load_run
    steps = load_run(metrics).steps()
    for ev in steps:
        r = ev["roofline"]
        assert r["gather_GB"] > 0
        assert r["achieved_gather_GBs"] > 0
        assert 0 < r["stream_ceiling_frac"] < 1
        assert r["exposed_comm_frac"] in (0.0, 1.0)  # stale A/B per step
    # the full-sync schedule shows up as exposed steps: step 1 (carry init)
    # and every sync-every-th step
    fracs = [ev["roofline"]["exposed_comm_frac"] for ev in steps]
    assert fracs[0] == 1.0 and 0.0 in fracs


def test_drift_gauges_present_and_finite(telemetry_run):
    _, metrics, _ = telemetry_run
    from sgcn_tpu.obs import load_run
    steps = load_run(metrics).steps()
    for ev in steps:
        d = ev["drift"]
        assert isinstance(d["sync_step"], bool)
        assert d["staleness_age"] >= 0
        for fld in ("halo_drift_rms", "halo_drift_rel",
                    "halo_quant_err_rms"):
            assert len(d[fld]) == 2          # one gauge per layer
            assert np.all(np.isfinite(d[fld])), (fld, d)
    assert steps[0]["drift"]["sync_step"] is True     # carry init
    ages = [ev["drift"]["staleness_age"] for ev in steps]
    assert max(ages) <= 2                   # --sync-every 2 bounds the age


def test_span_events_thread_the_step_and_epoch_paths(telemetry_run):
    """Every optimizer step emits a nested 'step' span under its epoch's
    'train_step' span (warmup steps under 'warmup') — measured phase times
    in the SAME stream as the analytic gauges."""
    _, metrics, _ = telemetry_run
    from sgcn_tpu.obs import load_run
    log = load_run(metrics)
    spans = [e for e in log.events if e["kind"] == "span"]
    steps = [s for s in spans if s["name"] == "step"]
    assert len(steps) == 4              # 1 warmup + 3 timed epochs
    assert {s["parent"] for s in steps} == {"warmup", "train_step"}
    assert all(s["depth"] == 1 for s in steps)
    assert [s["step"] for s in steps] == [1, 2, 3, 4]
    epochs = [s for s in spans if s["name"] == "train_step"]
    assert len(epochs) == 3 and all(s["depth"] == 0 for s in epochs)
    # span durations ARE the step wall times the step events carry
    walls = [e["wall_s"] for e in log.steps()]
    for sp, w in zip(steps, walls):
        assert abs(sp["dur_s"] - w) < 1e-6


def test_measured_vs_model_reconciles_with_phase_timer(telemetry_run):
    """The acceptance inequality: the measured phase-time total across the
    per-step measured_vs_model blocks reconciles with PhaseTimer.report()
    (the 'step' phase the spans feed) to <1%."""
    _, metrics, _ = telemetry_run
    from sgcn_tpu.obs import load_run
    steps = load_run(metrics).steps()
    mvms = [ev["measured_vs_model"] for ev in steps]
    assert all(isinstance(m, dict) for m in mvms)
    measured_total = sum(m["phase_total_s"] for m in mvms)
    # the LAST step's phases snapshot is taken after its span exits, so it
    # covers every step span of the run
    ph = steps[-1]["phases"]["step"]
    assert ph["count"] == len(steps)
    assert abs(measured_total - ph["total_s"]) < 0.01 * ph["total_s"]
    for ev in steps:
        gs = ev["measured_vs_model"]["components"]["gather_stream"]
        assert gs["measured_s"] > 0 and gs["model_s"] > 0
        # the seconds-space ratio is the roofline fraction, inverted
        # (both sides round to a few significant digits)
        frac = ev["roofline"]["stream_ceiling_frac"]
        assert abs(gs["ratio"] * frac - 1.0) < 0.01


def test_profile_trace_recorded_in_manifest_and_parses(telemetry_run):
    """--profile and --metrics-out compose: the manifest records the trace
    path + gzip'd size, and the trace parses into classified op time from
    the run directory alone."""
    _, metrics, _ = telemetry_run
    from sgcn_tpu.obs import load_run, summarize_trace, trace_path_for_run
    log = load_run(metrics)
    prof = log.manifest["profile"]
    assert prof["trace_files"], "no trace files recorded in the manifest"
    entry = prof["trace_files"][0]
    assert os.path.exists(entry["path"])
    assert entry["bytes"] == os.path.getsize(entry["path"])
    tpath = trace_path_for_run(log.manifest, metrics)
    assert tpath == entry["path"]
    ts = summarize_trace(tpath)
    assert ts.n_events > 0
    assert sum(ts.classes.values()) > 0
    assert 0 <= ts.exposed_comm_s <= ts.comm_s + 1e-9


@pytest.fixture(scope="module")
def ragged_run(tmp_path_factory):
    """A second CLI child on the cora fixture under the RAGGED schedule
    (exact mode) — with the module's stale/a2a child above, --metrics-out
    has run under both transports, the gauge-reconciliation smoke of the
    comm-schedule work (docs/comm_schedule.md)."""
    d = tmp_path_factory.mktemp("obs_ragged")
    metrics = str(d / "run")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, "-m", "sgcn_tpu.train",
         "--npz", os.path.join(FIX, "cora_like.npz"),
         "-p", os.path.join(FIX, "cora_like.4.hp"),
         "-b", "cpu", "-s", "4", "-l", "2", "--normalize",
         "--epochs", "2", "--warmup", "1",
         "--comm-schedule", "ragged", "--metrics-out", metrics],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    assert r.returncode == 0, r.stderr
    report = json.loads(r.stdout.strip().splitlines()[-1])
    return metrics, report


def _assert_wire_reconciles(metrics, report):
    """CommStats' printed report and the obs events must agree on wire
    accounting EXACTLY — rows, bytes, efficiency, schedule."""
    from sgcn_tpu.obs import load_run

    log = load_run(metrics)
    steps = log.steps()
    for ev in steps:
        comm, roof = ev["comm"], ev["roofline"]
        assert comm["comm_schedule"] == roof["comm_schedule"]
        assert comm["wire_rows_per_exchange"] == \
            roof["halo_wire_rows_per_exchange"]
        assert comm["padding_efficiency"] == roof["padding_efficiency"]
        # bytes are rows × Σ layer widths × itemsize × 2 on BOTH sides of
        # the split, so the true/wire byte ratio must equal the true/wire
        # ROW ratio the CommStats side reports — byte-for-byte, no slack
        assert (roof["halo_bytes_wire_per_step"]
                * comm["true_rows_per_exchange"]
                == roof["halo_bytes_true_per_step"]
                * comm["wire_rows_per_exchange"])
        assert roof["halo_bytes_wire_per_step"] >= \
            roof["halo_bytes_true_per_step"]
    last = steps[-1]["comm"]
    for key in ("comm_schedule", "wire_rows_per_exchange", "wire_rows_total",
                "true_rows_per_exchange", "padding_efficiency"):
        assert last[key] == report[key], (key, last[key], report[key])


def test_wire_gauges_reconcile_under_both_schedules(telemetry_run,
                                                    ragged_run):
    """The satellite contract: --metrics-out under BOTH schedules, CommStats
    report and obs events agreeing on wire bytes exactly; the ragged run's
    wire strictly below the dense run's at equal true volume."""
    _, metrics_a2a, report_a2a = telemetry_run
    metrics_rag, report_rag = ragged_run
    _assert_wire_reconciles(metrics_a2a, report_a2a)
    _assert_wire_reconciles(metrics_rag, report_rag)
    assert report_a2a["comm_schedule"] == "a2a"
    assert report_rag["comm_schedule"] == "ragged"
    assert report_a2a["true_rows_per_exchange"] == \
        report_rag["true_rows_per_exchange"]
    assert report_rag["wire_rows_per_exchange"] < \
        report_a2a["wire_rows_per_exchange"]


def test_obs_report_renders(telemetry_run):
    _, metrics, _ = telemetry_run
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         metrics],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "drift gauges" in out
    assert "exposed" in out and "hidden" in out
    assert "stream-ceiling" in out
    # the measured-time layer renders too: spans, the per-step
    # measured-vs-model reconciliation, and the trace-derived attribution
    assert "spans:" in out
    assert "measured vs model" in out
    assert "trace (" in out and "measured op classes" in out
