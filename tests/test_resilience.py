"""Preemption tolerance (PR-13, docs/resilience.md): durable checkpoints,
bit-identical resume, and the fault-injection harness that proves both.

Two layers of coverage:

  * **unit** — atomic-write crash safety, fault-spec grammar, deterministic
    corruption, the stalled-vs-slow heartbeat classifier, checkpoint
    checksum/truncation detection (the clear error, not a numpy
    deep-failure), keep-last-K rotation, and the fallback ordering of
    ``CheckpointManager.load_latest``;
  * **integration** (the acceptance surface) — for every mode family
    {exact, stale, replica, replica×stale} × {a2a, ragged} on the cora
    fixture: a REAL trainer-CLI run is hard-killed by the injected fault
    right after its step-4 checkpoint commits (``os._exit``, rc 43), a new
    process resumes with ``--resume auto``, and the resumed losses AND
    final params are ``==`` (f32 bit-for-bit) the uninterrupted run's,
    with the cumulative CommStats totals reconciling across the seam.
    The corrupted-latest path is driven by the harness too
    (``corrupt-after-save``): the resume must fall back to the previous
    intact checkpoint with a logged warning and still hit bit-identity.

The CLI children use the committed cora graph fixture with the synthetic
feature harness (``-f 16``) — the graph is the real fixture, the narrow
features keep each child's compile+train cost inside the tier-1 budget
(see tests/test_collection_lint.py SUBPROCESS_BUDGET_ALLOWLIST).
"""

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

from sgcn_tpu.resilience import faults
from sgcn_tpu.resilience.atomic import atomic_write, atomic_write_json
from sgcn_tpu.resilience.checkpoint import CheckpointManager
from sgcn_tpu.utils.checkpoint import (
    CheckpointCorruptError, load_checkpoint, read_checkpoint_meta,
    save_checkpoint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "fixtures")

# ---------------------------------------------------------------------------
# unit layer
# ---------------------------------------------------------------------------


def test_atomic_write_crash_leaves_original(tmp_path):
    p = str(tmp_path / "f.json")
    atomic_write_json(p, {"v": 1})
    # a writer that dies mid-block must leave the original intact and no
    # temp litter under any name
    with pytest.raises(RuntimeError, match="boom"):
        with atomic_write(p, "w") as fh:
            fh.write('{"v":')
            raise RuntimeError("boom")
    assert json.load(open(p)) == {"v": 1}
    assert os.listdir(tmp_path) == ["f.json"]
    # a completed rewrite replaces atomically
    atomic_write_json(p, {"v": 2})
    assert json.load(open(p)) == {"v": 2}
    with pytest.raises(ValueError, match="write-only"):
        with atomic_write(p, "r+"):
            pass


def test_fault_spec_grammar():
    s = faults.parse_fault("kill-after-save:4")
    assert (s.kind, s.step) == ("kill-after-save", 4)
    s = faults.parse_fault("corrupt-after-save:6:truncate")
    assert (s.step, s.mode) == (6, "truncate")
    assert faults.parse_fault("corrupt-after-save:2").mode == "bitflip"
    s = faults.parse_fault("stall:dryrun:30")
    assert (s.phase, s.seconds) == ("dryrun", 30.0)
    for bad in ("kill-after-save", "kill-after-save:x", "nope:1",
                "corrupt-after-save:2:shred", "stall:dryrun"):
        with pytest.raises(ValueError, match="grammar"):
            faults.parse_fault(bad)


def test_corrupt_file_deterministic(tmp_path):
    p = str(tmp_path / "blob")
    open(p, "wb").write(bytes(range(256)) * 4)
    faults.corrupt_file(p, mode="bitflip")
    data = open(p, "rb").read()
    assert len(data) == 1024
    ref = bytes(range(256)) * 4
    assert sum(a != b for a, b in zip(data, ref)) == 1   # exactly one byte
    faults.corrupt_file(p, mode="truncate")
    assert os.path.getsize(p) == int(1024 * 0.6)


def test_classify_stall(tmp_path):
    import time

    d = str(tmp_path)
    hb = os.path.join(d, "heartbeat.jsonl")
    # no heartbeat file at all: indistinguishable from wedged
    assert faults.classify_stall(d) == ("stalled", None)
    now = time.time()
    with open(hb, "w") as fh:
        fh.write(json.dumps({"ts": now - 300}) + "\n")
        fh.write(json.dumps({"ts": now - 5}) + "\n")
    kind, age = faults.classify_stall(d, now=now, threshold_s=60)
    assert kind == "slow" and age == pytest.approx(5, abs=0.1)
    kind, age = faults.classify_stall(d, now=now + 600, threshold_s=60)
    assert kind == "stalled" and age == pytest.approx(605, abs=0.1)


# --------------------------------------------------- tiny in-process trainer
@pytest.fixture(scope="module")
def tiny():
    """One small symmetric plan + data, shared by the in-process
    checkpoint unit tests (er_graph — the subprocess layer below owns the
    cora-fixture acceptance runs)."""
    from conftest import er_graph
    from sgcn_tpu.parallel import build_comm_plan
    from sgcn_tpu.partition import balanced_random_partition
    from sgcn_tpu.prep import normalize_adjacency
    from sgcn_tpu.train import make_train_data

    a = normalize_adjacency(er_graph(48))
    pv = balanced_random_partition(48, 4, seed=0)
    plan = build_comm_plan(a, pv, 4)
    feats = np.random.default_rng(0).standard_normal((48, 6)).astype(
        np.float32)
    labels = (np.arange(48) % 3).astype(np.int32)
    return plan, make_train_data(plan, feats, labels)


def _trainer(plan, **kw):
    from sgcn_tpu.train import FullBatchTrainer

    return FullBatchTrainer(plan, fin=6, widths=[8, 3], seed=1, **kw)


def test_corruption_raises_clear_error_not_numpy_failure(tiny, tmp_path):
    """The checksum loader's contract: a truncated or bit-flipped .npz
    fails with CheckpointCorruptError naming the damage — never a numpy/
    zipfile deep-failure leaking out of the loader."""
    plan, data = tiny
    tr = _trainer(plan, halo_staleness=1, sync_every=2)
    for _ in range(3):
        tr.step(data)
    good = save_checkpoint(tr, str(tmp_path / "ck.npz"), step=3)

    trunc = str(tmp_path / "trunc.npz")
    open(trunc, "wb").write(open(good, "rb").read())
    faults.corrupt_file(trunc, mode="truncate")
    with pytest.raises(CheckpointCorruptError,
                       match="truncated|damaged|unreadable"):
        read_checkpoint_meta(trunc)
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(_trainer(plan, halo_staleness=1, sync_every=2),
                        trunc)

    flip = str(tmp_path / "flip.npz")
    open(flip, "wb").write(open(good, "rb").read())
    faults.corrupt_file(flip, mode="bitflip")
    with pytest.raises(CheckpointCorruptError,
                       match="checksum|unreadable|corrupt"):
        load_checkpoint(_trainer(plan, halo_staleness=1, sync_every=2),
                        flip)
    # the intact file still loads cleanly after all that — and as a FULL
    # restore (the partial flag telemetry reads is false)
    tr_ok = _trainer(plan, halo_staleness=1, sync_every=2)
    assert load_checkpoint(tr_ok, good) == 3
    assert tr_ok.last_restore_partial is False

    # metadata is covered too: a tampered __step__ whose recorded CRC no
    # longer matches fails as loudly as a damaged leaf (a silent
    # wrong-step resume is exactly what the checksums exist to prevent)
    with np.load(good) as d:
        arrs = {k: d[k] for k in d.files}
    arrs["__step__"] = np.asarray(999, dtype=np.int64)
    tampered = str(tmp_path / "tampered.npz")
    np.savez(tampered, **arrs)
    with pytest.raises(CheckpointCorruptError, match="metadata|__step__"):
        read_checkpoint_meta(tampered)

    # the standalone integrity probe (no trainer needed): intact passes
    # and returns the meta block, every damage flavor raises
    from sgcn_tpu.utils.checkpoint import verify_checkpoint_file
    assert verify_checkpoint_file(good)["step"] == 3
    for bad in (trunc, flip, tampered):
        with pytest.raises(CheckpointCorruptError):
            verify_checkpoint_file(bad)


def test_rotation_and_fallback_ordering(tiny, tmp_path):
    """keep-last-K rotation; load_latest walks newest-first, falls back
    past corrupt files with a warning, raises only when NOTHING is
    intact."""
    plan, data = tiny
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=2)
    tr = _trainer(plan, halo_staleness=1, sync_every=2)
    for i in range(1, 7):
        tr.step(data)
        if i % 2 == 0:
            mgr.save(tr, step=i)
    assert [s for s, _ in mgr.checkpoints()] == [4, 6]   # 2 rotated away

    faults.corrupt_file(mgr.path_for(6), mode="bitflip")
    tr2 = _trainer(plan, halo_staleness=1, sync_every=2)
    with pytest.warns(RuntimeWarning, match="corrupt"):
        step, path, skipped = mgr.load_latest(tr2)
    assert step == 4 and path.endswith("ckpt_00000004.npz")
    assert [os.path.basename(s) for s in skipped] == ["ckpt_00000006.npz"]

    faults.corrupt_file(mgr.path_for(4), mode="truncate")
    with pytest.raises(CheckpointCorruptError, match="all 2 checkpoint"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            mgr.load_latest(_trainer(plan, halo_staleness=1, sync_every=2))

    with pytest.raises(FileNotFoundError, match="nothing to resume"):
        CheckpointManager(str(tmp_path / "empty")).load_latest(tr2)
    with pytest.raises(ValueError, match="keep_last"):
        CheckpointManager(str(tmp_path / "x"), keep_last=0)


def test_rotation_never_deletes_the_fresh_save(tiny, tmp_path):
    """A reused directory holding HIGHER-stamped checkpoints from a
    previous run must not make step-ordered rotation delete the file this
    run just wrote — and the shadowing hazard is warned about loudly."""
    plan, data = tiny
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=3)
    tr = _trainer(plan)
    tr.step(data)
    for s in (10, 15, 20):              # stale files from a "previous run"
        mgr.save(tr, step=s)
    tr2 = _trainer(plan)
    tr2.step(data)
    with pytest.warns(RuntimeWarning, match="PAST this run"):
        path = mgr.save(tr2, step=5)
    assert os.path.exists(path)          # the fresh save survived rotation
    assert 5 in [s for s, _ in mgr.checkpoints()]


def test_manager_sweeps_stale_temp_litter(tiny, tmp_path):
    """A kill mid-save strands an atomic-write temp file; the FIRST save
    of a new run sweeps it (save(), not __init__: every rank constructs a
    manager, only the coordinator writes — a non-writer rank sweeping a
    shared filesystem could unlink a live coordinator's in-flight temp),
    so repeated preemptions cannot grow the directory past the
    keep-last-K disk bound."""
    plan, data = tiny
    d = tmp_path / "ck"
    d.mkdir()
    stray = d / "ckpt_00000004.npz.tmp.12345"
    stray.write_bytes(b"half-written")
    keepme = d / "unrelated.txt"
    keepme.write_text("not ours")
    mgr = CheckpointManager(str(d))
    assert stray.exists()               # construction alone must NOT sweep
    tr = _trainer(plan)
    tr.step(data)
    mgr.save(tr, step=1)
    assert not stray.exists()
    assert keepme.exists()


def test_partial_state_and_mode_mismatch_warn_loudly(tiny, tmp_path):
    """Old (v1) checkpoints load params-only with the loud PARTIAL STATE
    warning; a carry-mode mismatch between file and trainer is named, not
    silently dropped."""
    import jax

    plan, data = tiny
    tr = _trainer(plan, halo_staleness=1, sync_every=2)
    for _ in range(2):
        tr.step(data)
    # v1-format file: leaves + step only (what pre-PR-13 writers produced)
    leaves = jax.tree.leaves((tr.params, tr.opt_state))
    old = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    old["__step__"] = np.asarray(2, dtype=np.int64)
    oldpath = str(tmp_path / "old.npz")
    np.savez(oldpath, **old)
    with pytest.warns(RuntimeWarning, match="PARTIAL STATE"):
        assert load_checkpoint(
            _trainer(plan, halo_staleness=1, sync_every=2), oldpath) == 2
    # stale-mode checkpoint into an exact trainer: carry ignored, loudly
    ck = save_checkpoint(tr, str(tmp_path / "stale.npz"), step=2)
    with pytest.warns(RuntimeWarning, match="IGNORED"):
        assert load_checkpoint(_trainer(plan), ck) == 2
    meta = read_checkpoint_meta(ck)
    assert meta["version"] >= 2 and meta["n_carry"] > 0
    assert meta["state"]["carry"] == "halo_carry"


def test_controller_state_survives_resume(tiny, tmp_path):
    """The PR-12 controller's mid-run retune is algorithmic state: the
    EFFECTIVE sync_every and the retune log must cross the seam."""
    plan, data = tiny
    tr = _trainer(plan, halo_staleness=1, sync_every=4,
                  auto_tune_sync=True)
    assert tr.controller is not None
    for _ in range(2):
        tr.step(data)
    # inject a retune as the drift band would
    tr.sync_every = tr.controller.observe(2, 0.001)   # below band: widen
    assert tr.sync_every == 8 and len(tr.controller.decisions) == 1
    ck = save_checkpoint(tr, str(tmp_path / "ctl.npz"), step=2)
    tr2 = _trainer(plan, halo_staleness=1, sync_every=4,
                   auto_tune_sync=True)
    load_checkpoint(tr2, ck)
    assert tr2.sync_every == 8
    assert tr2.controller.sync_every == 8
    assert tr2.controller.decisions == tr.controller.decisions
    assert tr2.comm_decision["controller"]["retunes"]


def test_obs_checkpoint_resume_events_render(tiny, tmp_path):
    """run_resumable emits schema-v4 checkpoint events under a recorder;
    resume events land via record_resume; obs_report renders both."""
    from sgcn_tpu.obs import RunRecorder, load_run
    from sgcn_tpu.resilience.runner import run_resumable

    plan, data = tiny
    d = str(tmp_path / "run")
    mgr = CheckpointManager(str(tmp_path / "ck"))
    tr = _trainer(plan)
    rec = RunRecorder(d, config={}, run_kind="train")
    tr.attach_recorder(rec)
    report = run_resumable(tr, data, 4, manager=mgr, checkpoint_every=2,
                           verbose=False)
    rec.record_resume(step=2, path=mgr.path_for(2), fallback=True,
                      skipped=[mgr.path_for(4)])
    rec.close()
    assert len(report["losses"]) == 4
    log = load_run(d)                    # re-validates every event
    assert len(log.checkpoints()) == 2
    assert log.checkpoints()[0]["step"] == 2
    assert log.resumes()[0]["fallback"] is True
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"), d],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "resilience:" in r.stdout and "FELL BACK" in r.stdout
    assert "last checkpoint: step 4" in r.stdout


# ---------------------------------------------------------------------------
# integration layer: the fault-injection harness on the cora fixture
# ---------------------------------------------------------------------------

# the acceptance matrix: {exact, stale, replica, replica×stale} × {a2a,
# ragged}.  sync_every=2 keeps a sync/refresh step INSIDE the resumed
# stretch, so the restored schedule counters are actually load-bearing.
MODES = {
    "exact-a2a": [],
    "exact-ragged": ["--comm-schedule", "ragged"],
    "stale-a2a": ["--halo-staleness", "1", "--sync-every", "2"],
    "stale-ragged": ["--halo-staleness", "1", "--sync-every", "2",
                     "--comm-schedule", "ragged"],
    "replica-a2a": ["--replica-budget", "8", "--sync-every", "2"],
    "replica-ragged": ["--replica-budget", "8", "--sync-every", "2",
                       "--comm-schedule", "ragged"],
    "repstale-a2a": ["--replica-budget", "8", "--halo-staleness", "1",
                     "--sync-every", "2"],
    "repstale-ragged": ["--replica-budget", "8", "--halo-staleness", "1",
                        "--sync-every", "2", "--comm-schedule", "ragged"],
}
TOTAL_STEPS = 6          # --warmup 0 --epochs 6
KILL_STEP = 4            # fault fires after the step-4 save commits


def _run_cli(mode_flags, ckdir, extra=(), env_extra=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)      # let -b cpu set its own device count
    env["PYTHONPATH"] = REPO
    env.pop(faults.FAULT_ENV, None)
    if env_extra:
        env.update(env_extra)
    cmd = [sys.executable, "-m", "sgcn_tpu.train",
           "-a", os.path.join(FIX, "cora_like.A.mtx"),
           "-p", os.path.join(FIX, "cora_like.4.hp"),
           "-b", "cpu", "-s", "4", "-l", "2", "-f", "16",
           "--warmup", "0", "--epochs", str(TOTAL_STEPS),
           "--checkpoint-dir", str(ckdir), "--checkpoint-every", "2",
           *mode_flags, *extra]
    return subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                          env=env, timeout=420)


def _leaves(path):
    with np.load(path) as d:
        n = sum(1 for f in d.files if f.startswith("leaf_"))
        return [d[f"leaf_{i}"] for i in range(n)]


def _assert_crash_resume_parity(mode, tmp_path, fault, expect_resume_step,
                                expect_fallback):
    flags = MODES[mode]
    # uninterrupted baseline (own checkpoint dir; identical schedule)
    r = _run_cli(flags, tmp_path / "a",
                 extra=["--save-checkpoint", str(tmp_path / "final_a.npz")])
    assert r.returncode == 0, r.stderr[-3000:]
    base = json.loads(r.stdout.strip().splitlines()[-1])
    assert len(base["losses"]) == TOTAL_STEPS

    # kill a REAL run mid-flight via the injected fault (hard os._exit
    # right after the step-KILL_STEP checkpoint commits)
    r = _run_cli(flags, tmp_path / "b",
                 env_extra={faults.FAULT_ENV: fault})
    assert r.returncode == faults.FAULT_EXIT_CODE, (
        f"fault did not fire (rc={r.returncode}):\n{r.stderr[-2000:]}")

    # new process, --resume auto: completes the remainder of the schedule
    r = _run_cli(flags, tmp_path / "b",
                 extra=["--resume", "auto",
                        "--save-checkpoint", str(tmp_path / "final_b.npz")])
    assert r.returncode == 0, r.stderr[-3000:]
    res = json.loads(r.stdout.strip().splitlines()[-1])
    assert res["resumed"]["step"] == expect_resume_step
    assert res["resumed"]["fallback"] is expect_fallback

    # THE contract: losses == (f32 bit-for-bit via exact float repr) and
    # final params ==, with comm totals reconciling across the seam
    assert res["losses"] == base["losses"][expect_resume_step:], (
        f"{mode}: resumed losses diverge from the uninterrupted tail")
    fa = _leaves(str(tmp_path / "final_a.npz"))
    fb = _leaves(str(tmp_path / "final_b.npz"))
    assert len(fa) == len(fb)
    for i, (x, y) in enumerate(zip(fa, fb)):
        assert x.dtype == y.dtype and (x == y).all(), (
            f"{mode}: param leaf {i} not bit-identical after resume")
    for key in ("exchanges", "hidden_exchanges", "total_send_volume",
                "wire_rows_total", "exposed_send_volume",
                "hidden_send_volume"):
        assert base[key] == res[key], (
            f"{mode}: cumulative {key} does not reconcile across the "
            f"seam ({base[key]} vs {res[key]})")
    return r


@pytest.mark.parametrize("mode", list(MODES))
def test_crash_resume_bit_identity(mode, tmp_path):
    """Kill-at-step + resume == uninterrupted, per mode family × transport
    (the PR-13 acceptance matrix), driven end to end by the fault
    harness."""
    _assert_crash_resume_parity(
        mode, tmp_path, fault=f"kill-after-save:{KILL_STEP}",
        expect_resume_step=KILL_STEP, expect_fallback=False)


def test_minibatch_durable_resume(tmp_path):
    """The mini-batch flavor of the durable path: checkpoint-every counts
    EPOCHS (saved through the inner trainer), kill-after-save fires at the
    epoch-2 save, and --resume auto completes the remaining epochs without
    repeating the warm-up (durability + resumability, no bit-identity
    claim — docs/resilience.md)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO

    def run(extra, fault=None):
        e = dict(env)
        e.pop(faults.FAULT_ENV, None)
        if fault:
            e[faults.FAULT_ENV] = fault
        return subprocess.run(
            [sys.executable, "-m", "sgcn_tpu.train",
             "-a", os.path.join(FIX, "cora_like.A.mtx"),
             "-p", os.path.join(FIX, "cora_like.4.hp"),
             "-b", "cpu", "-s", "4", "-l", "2", "-f", "16", "-n", "200",
             "--warmup", "1", "--epochs", "4",
             "--checkpoint-dir", str(tmp_path / "ck"),
             "--checkpoint-every", "2", *extra],
            capture_output=True, text=True, cwd=REPO, env=e, timeout=420)

    r = run([], fault="kill-after-save:2")
    assert r.returncode == faults.FAULT_EXIT_CODE, r.stderr[-2000:]
    assert [os.path.basename(p) for _, p in
            CheckpointManager(str(tmp_path / "ck")).checkpoints()] \
        == ["ckpt_00000002.npz"]
    r = run(["--resume", "auto"])
    assert r.returncode == 0, r.stderr[-3000:]
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["resumed"]["step"] == 2
    assert rep["epochs"] == 4 and rep["start_epoch"] == 2


def test_corrupted_latest_falls_back_and_stays_bit_identical(tmp_path):
    """The corrupt-after-save fault damages the step-4 checkpoint and THEN
    kills: --resume auto must detect the corruption, warn, fall back to
    the intact step-2 checkpoint, and STILL reach bit-identity — proven by
    the harness, not hand-staged files."""
    r = _assert_crash_resume_parity(
        "stale-a2a", tmp_path,
        fault=f"corrupt-after-save:{KILL_STEP}:bitflip",
        expect_resume_step=KILL_STEP - 2, expect_fallback=True)
    assert "corrupt" in r.stderr and "falling back" in r.stderr