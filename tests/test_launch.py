"""Launcher plumbing tests (single-process paths + SLURM env arithmetic)."""

import os

import numpy as np

from sgcn_tpu.parallel.launch import (
    global_mesh_1d, init_distributed, slurm_rendezvous_env,
)


def test_init_distributed_single_process():
    ctx = init_distributed()
    assert ctx.num_processes == 1
    assert ctx.process_id == 0
    assert ctx.is_coordinator
    assert ctx.global_devices >= 1


def test_global_mesh_covers_devices():
    mesh = global_mesh_1d()
    import jax
    assert mesh.devices.size == len(jax.devices())
    sub = global_mesh_1d(4)
    assert sub.devices.size == 4


def test_slurm_rendezvous_arithmetic(monkeypatch):
    monkeypatch.setenv("SLURM_NPROCS", "6")
    monkeypatch.setenv("SLURM_PROCID", "2")
    monkeypatch.setenv("SLURM_JOBID", "987654321")
    monkeypatch.setenv("MASTER_ADDR", "node0")
    monkeypatch.delenv("SGCN_COORDINATOR", raising=False)
    monkeypatch.delenv("MASTER_PORT", raising=False)
    coord, nprocs, pid = slurm_rendezvous_env()
    # port = 10000 + last 4 digits of the job id (reference launcher rule)
    assert coord == "node0:14321"
    assert nprocs == 6 and pid == 2


def test_slurm_rendezvous_absent(monkeypatch):
    for var in ("SLURM_NPROCS", "SLURM_PROCID", "MASTER_ADDR",
                "SGCN_COORDINATOR"):
        monkeypatch.delenv(var, raising=False)
    assert slurm_rendezvous_env() is None


def test_rendezvous_retries_once_with_backoff(monkeypatch):
    """PR-13 stalled-peer handling: one initialize timeout gets ONE retry
    after a backoff (heartbeats marking stalled/retry), a second failure
    raises the clear stalled-peer error — never an unbounded hang, never
    an uninterpretable stack from deep inside the rendezvous."""
    from sgcn_tpu.parallel import launch

    monkeypatch.setenv("SGCN_RENDEZVOUS_BACKOFF", "0")
    monkeypatch.setenv("SGCN_RENDEZVOUS_TIMEOUT", "7")
    calls, beats, naps, downs = [], [], [], []
    monkeypatch.setattr(launch.time, "sleep", lambda s: naps.append(s))
    # a timed-out initialize leaves jax's distributed client set; the
    # retry must shut it down or the second initialize refuses outright
    monkeypatch.setattr(launch.jax.distributed, "shutdown",
                        lambda: downs.append(1))

    def hb(event, **fields):
        beats.append((event, fields.get("detail", "")))

    # transient peer: first attempt times out, retry succeeds
    def flaky_init(**kw):
        calls.append(kw)
        if len(calls) == 1:
            raise RuntimeError("Barrier timed out: peer 3 never arrived")

    monkeypatch.setattr(launch.jax.distributed, "initialize", flaky_init)
    launch._initialize_with_retry(hb, "2 processes @ node0:1234",
                                  coordinator_address="node0:1234",
                                  num_processes=2, process_id=0)
    assert len(calls) == 2 and len(naps) == 1 and len(downs) == 1
    events = [e for e, _ in beats]
    assert events == ["rendezvous:start", "rendezvous:stalled",
                      "rendezvous:start", "rendezvous:done"]
    # the per-attempt timeout knob reaches jax when its API has one
    import inspect
    if "initialization_timeout" in inspect.signature(
            launch.jax.distributed.initialize).parameters:
        assert calls[0].get("initialization_timeout") == 7

    # dead peer: both attempts fail → the clear stalled-peer error
    calls.clear(), beats.clear()

    def dead_init(**kw):
        calls.append(kw)
        raise RuntimeError("Barrier timed out")

    monkeypatch.setattr(launch.jax.distributed, "initialize", dead_init)
    try:
        launch._initialize_with_retry(hb, "2 processes @ node0:1234",
                                      coordinator_address="node0:1234",
                                      num_processes=2, process_id=0)
        raise AssertionError("expected RuntimeError")
    except RuntimeError as e:
        assert "stalled" in str(e) and "node0:1234" in str(e)
    assert len(calls) == 2
    assert [e for e, _ in beats][-1] == "rendezvous:failed"

    # non-timeout failure: retried, but NOT misdiagnosed as a stalled peer
    calls.clear(), beats.clear()

    def misconfig_init(**kw):
        calls.append(kw)
        raise RuntimeError("address already in use")

    monkeypatch.setattr(launch.jax.distributed, "initialize",
                        misconfig_init)
    try:
        launch._initialize_with_retry(hb, "2 processes @ node0:1234",
                                      coordinator_address="node0:1234",
                                      num_processes=2, process_id=0)
        raise AssertionError("expected RuntimeError")
    except RuntimeError as e:
        assert "NOT a timeout" in str(e) and "stalled" not in str(e)
    assert [e for e, _ in beats][1] == "rendezvous:error"
