"""Launcher plumbing tests (single-process paths + SLURM env arithmetic)."""

import os

import numpy as np

from sgcn_tpu.parallel.launch import (
    global_mesh_1d, init_distributed, slurm_rendezvous_env,
)


def test_init_distributed_single_process():
    ctx = init_distributed()
    assert ctx.num_processes == 1
    assert ctx.process_id == 0
    assert ctx.is_coordinator
    assert ctx.global_devices >= 1


def test_global_mesh_covers_devices():
    mesh = global_mesh_1d()
    import jax
    assert mesh.devices.size == len(jax.devices())
    sub = global_mesh_1d(4)
    assert sub.devices.size == 4


def test_slurm_rendezvous_arithmetic(monkeypatch):
    monkeypatch.setenv("SLURM_NPROCS", "6")
    monkeypatch.setenv("SLURM_PROCID", "2")
    monkeypatch.setenv("SLURM_JOBID", "987654321")
    monkeypatch.setenv("MASTER_ADDR", "node0")
    monkeypatch.delenv("SGCN_COORDINATOR", raising=False)
    monkeypatch.delenv("MASTER_PORT", raising=False)
    coord, nprocs, pid = slurm_rendezvous_env()
    # port = 10000 + last 4 digits of the job id (reference launcher rule)
    assert coord == "node0:14321"
    assert nprocs == 6 and pid == 2


def test_slurm_rendezvous_absent(monkeypatch):
    for var in ("SLURM_NPROCS", "SLURM_PROCID", "MASTER_ADDR",
                "SGCN_COORDINATOR"):
        monkeypatch.delenv(var, raising=False)
    assert slurm_rendezvous_env() is None
