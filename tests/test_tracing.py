"""Unit tests for the measured-time profiling layer (sgcn_tpu.obs.tracing)
and its schema/recorder integration:

  * PhaseTimer nesting — child time attributed to the child only, reentrant
    same-name entry no longer double-counts (the pre-fix corruption), and
    the inclusive side keeps the whole-region semantics ``fit()`` times with;
  * SpanTimer — nested spans over the shared timer, span events through the
    recorder, ``emit_span``/``scoped_span`` env-gating;
  * trace parser — op classification into the attribution vocabulary, the
    overlap/exposed/straggler math on a synthetic trace, and a real parse
    of the checked-in 8-vdev trace artifact;
  * measured_vs_model — block construction, schema validation of the
    ratio/abs-err join, rejection of inconsistent joins;
  * schema v2 back-compat — the frozen v1 fixture run dir loads clean, a
    v1 stream may not carry the v2-only span kind.
"""

import gzip
import json
import os
import time

import numpy as np
import pytest

from sgcn_tpu.utils.timers import PhaseTimer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "fixtures")


# ------------------------------------------------------- PhaseTimer nesting

def test_phase_timer_nested_child_only_attribution():
    t = PhaseTimer()
    # wide sleep gap: a loaded host can overshoot the short sleep, and the
    # ordering assertion below must not flake on scheduler jitter
    with t.phase("outer"):
        time.sleep(0.01)
        with t.phase("inner"):
            time.sleep(0.08)
    rep = t.report()
    # self time: the child's 0.08 s belongs to the child ONLY
    assert rep["inner"]["total_s"] >= 0.08
    assert rep["outer"]["total_s"] < rep["inner"]["total_s"]
    # inclusive keeps the whole-region meaning
    assert rep["outer"]["inclusive_s"] >= 0.09
    assert abs(rep["outer"]["inclusive_s"]
               - (rep["outer"]["total_s"] + rep["inner"]["total_s"])) < 0.01
    # Σ self times == elapsed wall: nothing counted twice
    assert t.inclusive_total("outer") == rep["outer"]["inclusive_s"]


def test_phase_timer_reentrant_same_name_no_double_count():
    """The satellite fix: re-entering a phase under itself used to add BOTH
    frames' full durations (totals ~2x wall)."""
    t = PhaseTimer()
    with t.phase("a"):
        time.sleep(0.02)
        with t.phase("a"):
            time.sleep(0.02)
    # self-time halves sum to the single wall duration
    assert 0.035 < t.totals["a"] < 0.08
    # inclusive is reentrancy-guarded: the outermost frame counts once
    assert 0.035 < t.inclusive["a"] < 0.08
    assert t.counts["a"] == 2


def test_phase_timer_sync_callable_still_runs():
    t = PhaseTimer()
    hit = []
    with t.phase("p", sync=lambda: (hit.append(1), np.zeros(1))[1]):
        pass
    assert hit == [1]
    assert t.counts["p"] == 1


def test_phase_timer_raising_sync_unwinds_the_stack():
    """Async dispatch errors surface exactly at the block_until_ready sync
    point; a raising sync must still pop/account its frame — a dead frame
    would silently poison every later phase's attribution."""
    t = PhaseTimer()

    def boom():
        raise RuntimeError("dispatch error")

    with pytest.raises(RuntimeError):
        with t.phase("bad", sync=boom):
            pass
    assert t._stack == []
    assert t.counts["bad"] == 1
    # subsequent accounting is uncorrupted: a fresh phase attributes its
    # own time (not to a leftover frame) and reentrancy still works
    with t.phase("good"):
        time.sleep(0.02)
    assert t.totals["good"] >= 0.02
    assert t.inclusive["good"] >= 0.02


# ---------------------------------------------------------------- span API

def test_span_timer_nesting_and_events(tmp_path):
    from sgcn_tpu.obs import RunRecorder, load_run
    from sgcn_tpu.obs.tracing import SpanTimer

    d = str(tmp_path / "run")
    with RunRecorder(d, config={}) as rec:
        st = SpanTimer(recorder=rec)
        with st.span("train_step", step=1) as outer:
            time.sleep(0.01)
            with st.span("step", step=1) as inner:
                time.sleep(0.01)
        assert outer.dur_s > inner.dur_s > 0
    log = load_run(d)
    spans = [e for e in log.events if e["kind"] == "span"]
    # exit order: the inner span closes (and is emitted) first
    assert [s["name"] for s in spans] == ["step", "train_step"]
    assert spans[0]["parent"] == "train_step" and spans[0]["depth"] == 1
    assert "parent" not in spans[1] and spans[1]["depth"] == 0
    assert spans[0]["step"] == 1
    # the span generalizes PhaseTimer: both names landed in the timer too
    assert st.timer.counts["step"] == st.timer.counts["train_step"] == 1


def test_emit_span_env_gated(tmp_path, monkeypatch):
    from sgcn_tpu.obs import RunRecorder, load_run
    from sgcn_tpu.obs.tracing import emit_span, scoped_span

    d = str(tmp_path / "bench_run")
    monkeypatch.delenv("SGCN_METRICS_OUT", raising=False)
    emit_span("no:dir", 0.1)
    assert not os.path.exists(os.path.join(d, "events.jsonl"))
    monkeypatch.setenv("SGCN_METRICS_OUT", d)
    with scoped_span("bench:flagship", phase="flagship"):
        pass
    emit_span("bench:stale_ab", 0.25, phase="ab_child", detail="n=100")
    # a KILLED bench leaves events.jsonl with no manifest — the completed
    # measurements must still load (manifest {}), like heartbeat-only dirs
    partial = load_run(d)
    assert partial.manifest == {}
    assert [e["name"] for e in partial.events] == ["bench:flagship",
                                                   "bench:stale_ab"]
    # the bench flow creates the manifest at emission time; the earlier
    # span appends survive in the same stream
    with RunRecorder(d, config={}, run_kind="bench") as rec:
        rec.record_summary({"metric": "x", "value": 1})
    log = load_run(d)
    names = [e["name"] for e in log.events if e["kind"] == "span"]
    assert names == ["bench:flagship", "bench:stale_ab"]
    assert all(e["pid"] == os.getpid() for e in log.events
               if e["kind"] == "span")


# ------------------------------------------------------------- trace parser

def test_classify_op_vocabulary():
    from sgcn_tpu.obs.tracing import classify_op

    assert classify_op("all-to-all.6") == "exchange"
    assert classify_op("collective-permute-start.1") == "exchange"
    assert classify_op("Rendezvous") == "collective_wait"
    assert classify_op("Wait for rendezvous callback") == "collective_wait"
    assert classify_op("all-to-all-done.2") == "collective_wait"
    # point-to-point transfer pairs: start = exchange, completion = wait
    assert classify_op("send.3") == "exchange"
    assert classify_op("recv.3") == "exchange"
    assert classify_op("recv-done.2") == "collective_wait"
    assert classify_op("copy_gather_fusion.2") == "spmm"
    assert classify_op("wrapped_scatter.4") == "spmm"
    assert classify_op("select_slice_fusion.7") == "spmm"
    assert classify_op("dot_general.3") == "dense"
    assert classify_op("wrapped_broadcast") == "other"
    # async COPY completion is not comm wait (only collective -done ops are)
    assert classify_op("copy-done.1") == "other"
    # dtype casts are not dense math (`convolution` yes, `convert` no)
    assert classify_op("convert.5") == "other"
    assert classify_op("convolution.1") == "dense"
    # host/runtime scaffolding is not device op time
    assert classify_op("$profiler.py:246 trace") is None
    assert classify_op("end: copy.17") is None
    assert classify_op("ThunkExecutor::Execute") is None
    assert classify_op("PjitFunction(per_chip)") is None


def _synthetic_trace(tmp_path, events):
    path = str(tmp_path / "t.trace.json.gz")
    with gzip.open(path, "wt") as fh:
        json.dump({"traceEvents": events}, fh)
    return path


def test_summarize_trace_overlap_and_skew(tmp_path):
    """Hand-built two-device trace: device A's collective is half covered by
    concurrent compute, device B is a straggler with 2x busy time."""
    from sgcn_tpu.obs.tracing import summarize_trace

    ev = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/device:TPU:1"}},
        # device 0: 100 µs compute, then a 100 µs all-to-all whose first
        # 50 µs overlaps a second compute op on another thread
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 100,
         "name": "copy_gather_fusion.1"},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 100, "dur": 100,
         "name": "all-to-all.1"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 100, "dur": 50,
         "name": "dot_general.1"},
        # device 1: pure compute, twice device 0's busy window
        {"ph": "X", "pid": 2, "tid": 1, "ts": 0, "dur": 500,
         "name": "copy_gather_fusion.2"},
    ]
    ts = summarize_trace(_synthetic_trace(tmp_path, ev))
    assert ts.n_events == 4
    us = 1e-6
    assert abs(ts.classes["spmm"] - 600 * us) < 1e-12
    assert abs(ts.classes["exchange"] - 100 * us) < 1e-12
    assert abs(ts.comm_s - 100 * us) < 1e-12
    # 50 of the 100 µs collective ran under concurrent compute
    assert abs(ts.exposed_comm_s - 50 * us) < 1e-12
    assert abs(ts.measured_overlap_frac - 0.5) < 1e-9
    assert ts.skew is not None
    assert ts.skew["straggler"] == "/device:TPU:1"
    # busy: dev0 200 µs (0..200 union), dev1 500 µs -> max/mean = 500/350
    assert abs(ts.skew["busy_max_over_mean"] - 500 / 350) < 1e-9
    per = ts.per_step(2)
    assert abs(per["exchange_s"] - 50 * us) < 1e-12


def test_summarize_trace_duplicate_process_names(tmp_path):
    """Distinct pids sharing process_name metadata (merged multi-host
    captures) must stay distinct devices — collapsing them would shrink the
    straggler denominator and overwrite per-class seconds."""
    from sgcn_tpu.obs.tracing import summarize_trace

    ev = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 100,
         "name": "copy_gather_fusion.1"},
        {"ph": "X", "pid": 2, "tid": 1, "ts": 0, "dur": 300,
         "name": "copy_gather_fusion.2"},
    ]
    ts = summarize_trace(_synthetic_trace(tmp_path, ev))
    us = 1e-6
    assert len(ts.devices) == 2
    assert abs(ts.classes["spmm"] - 400 * us) < 1e-12
    assert ts.skew is not None               # two devices, 2x skew visible
    assert abs(ts.skew["busy_max_over_mean"] - 300 / 200) < 1e-9
    assert ts.skew["straggler"].startswith("/device:TPU:0")


def test_summarize_trace_drops_host_pids_when_devices_exist(tmp_path):
    """A real TPU profile carries host/runtime pids next to the device
    pids; their wall time is not device op time — the host must not
    inflate class totals or be elected straggler.  (A CPU-backend trace
    has no /device: pid, so its /host:CPU stays in — pinned by
    test_summarize_trace_checked_in_artifact.)"""
    from sgcn_tpu.obs.tracing import summarize_trace

    ev = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/device:TPU:1"}},
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 100,
         "name": "copy_gather_fusion.1"},
        {"ph": "X", "pid": 2, "tid": 1, "ts": 0, "dur": 200,
         "name": "copy_gather_fusion.2"},
        # classifiable host activity, much longer than any device op
        {"ph": "X", "pid": 9, "tid": 1, "ts": 0, "dur": 9000,
         "name": "wrapped_broadcast"},
    ]
    ts = summarize_trace(_synthetic_trace(tmp_path, ev))
    us = 1e-6
    assert set(ts.devices) == {"/device:TPU:0", "/device:TPU:1"}
    assert ts.n_events == 2                           # host op not counted
    assert ts.classes.get("other", 0.0) == 0.0        # host op dropped
    assert abs(ts.classes["spmm"] - 300 * us) < 1e-12
    assert ts.skew is not None
    assert ts.skew["straggler"] == "/device:TPU:1"    # never the host


def test_summarize_trace_checked_in_artifact():
    """The committed 8-vdev CPU trace parses and classifies: the overlap
    evidence run shipped all-to-alls and gather fusions, so both classes
    must be non-empty and exposure bounded by total comm."""
    from sgcn_tpu.obs.tracing import summarize_trace

    ts = summarize_trace(os.path.join(
        REPO, "bench_artifacts", "overlap_8dev_cpu.trace.json.gz"))
    assert ts.n_events > 100
    assert ts.classes["exchange"] > 0
    assert ts.classes["spmm"] > 0
    assert 0 <= ts.exposed_comm_s <= ts.comm_s + 1e-9
    assert ts.measured_overlap_frac is not None
    assert 0 <= ts.measured_overlap_frac <= 1
    # one /host:CPU process -> no per-device skew on the CPU backend
    assert ts.skew is None


def test_find_trace_files_and_manifest_profile(tmp_path):
    from sgcn_tpu.obs import RunRecorder, load_run
    from sgcn_tpu.obs.tracing import find_trace_files, trace_path_for_run

    prof = tmp_path / "prof" / "plugins" / "profile" / "run1"
    prof.mkdir(parents=True)
    tpath = prof / "host.trace.json.gz"
    with gzip.open(str(tpath), "wt") as fh:
        json.dump({"traceEvents": []}, fh)
    hits = find_trace_files(str(tmp_path / "prof"))
    assert len(hits) == 1
    assert hits[0]["path"] == str(tpath)
    assert hits[0]["bytes"] == os.path.getsize(str(tpath))

    d = str(tmp_path / "run")
    with RunRecorder(d, config={}) as rec:
        rec.set_profile(str(tmp_path / "prof"))
    log = load_run(d)
    pb = log.manifest["profile"]
    assert pb["dir"] == str(tmp_path / "prof")
    assert pb["trace_files"][0]["path"] == str(tpath)
    assert trace_path_for_run(log.manifest, d) == str(tpath)

    # relocated run dir: the manifest's absolute paths are stale, but a
    # trace copied under the run dir itself still resolves (last-resort
    # rundir glob — 'from the run directory alone' holds anywhere)
    moved = tmp_path / "moved_run"
    moved.mkdir()
    inner = moved / "host.trace.json.gz"
    with gzip.open(str(inner), "wt") as fh:
        json.dump({"traceEvents": []}, fh)
    stale = {"profile": {"dir": "/nonexistent/prof",
                         "trace_files": [{"path": "/nonexistent/t.gz",
                                          "bytes": 1}]}}
    assert trace_path_for_run(stale, str(moved)) == os.path.abspath(str(inner))
    assert trace_path_for_run(stale, str(tmp_path / "nowhere")) is None


# -------------------------------------------------------- measured vs model

def test_measured_vs_model_block_and_validation():
    from sgcn_tpu.obs import validate_event
    from sgcn_tpu.obs.attribution import STREAM_CEILING_GBS
    from sgcn_tpu.obs.tracing import measured_vs_model_block

    class Cost:
        gather_bytes = 655_000_000      # exactly 1 ms at the stream ceiling

    blk = measured_vs_model_block(Cost(), wall_s=0.004)
    gs = blk["components"]["gather_stream"]
    assert abs(gs["model_s"] - 655e6 / (STREAM_CEILING_GBS * 1e9)) < 1e-12
    assert gs["measured_s"] == 0.004
    assert abs(gs["ratio"] - 4.0) < 1e-6
    assert abs(gs["abs_err_s"] - 0.003) < 1e-9
    assert blk["phase_total_s"] == 0.004
    ev = {"v": 2, "ts": 1.0, "kind": "step", "step": 1, "loss": 1.0,
          "wall_s": 0.004, "measured_vs_model": blk}
    validate_event(ev)                  # the block round-trips the schema

    # an inconsistent join (ratio not measured/model) is a writer bug
    bad = {"phase_total_s": 0.004,
           "components": {"gather_stream": dict(gs, ratio=1.0)}}
    with pytest.raises(ValueError, match="inconsistent"):
        validate_event(dict(ev, measured_vs_model=bad))
    # a missing analytic side is a writer bug (model_s must be computable)
    with pytest.raises(ValueError, match="model_s"):
        validate_event(dict(ev, measured_vs_model={
            "phase_total_s": 0.004, "components": {"x": {"measured_s": 1.0}}}))
    with pytest.raises(ValueError, match="phase_total_s"):
        validate_event(dict(ev, measured_vs_model={"components": {
            "x": {"model_s": 1.0, "measured_s": None}}}))


def test_measured_vs_model_trace_join():
    from sgcn_tpu.obs.attribution import ICI_CEILING_GBS
    from sgcn_tpu.obs.tracing import measured_vs_model_block

    class Cost:
        gather_bytes = 1_000_000

    # exposed vs exposed: measured exposed_comm_s (NOT total collective
    # seconds — hidden comm is overlap, not model error) against the
    # analytic exposed wire bytes serialized at the nominal ICI rate.  The
    # model side must NOT scale with the step wall: exposed_comm_frac is a
    # fraction of the step's exchanges, so a frac x wall model would read
    # every exact run's compute share as cost-model error.
    ehb = 0.004 * ICI_CEILING_GBS * 1e9     # 4 ms of wire at the ceiling
    blk = measured_vs_model_block(
        Cost(), wall_s=0.01,
        trace_per_step={"exchange_s": 0.005, "collective_wait_s": 0.001,
                        "exposed_comm_s": 0.003},
        exposed_halo_bytes=ehb)
    ex = blk["components"]["exchange"]
    assert ex["measured_s"] == 0.003   # exposed only, 3ms of 6ms total
    assert ex["model_s"] == 0.004      # ehb / ICI ceiling, wall-independent
    assert abs(ex["ratio"] - 0.75) < 1e-6
    # no exposed_halo_bytes -> no exchange join (TraceSummary.per_step
    # alone carries no analytic side)
    blk = measured_vs_model_block(
        Cost(), wall_s=0.01, trace_per_step={"exposed_comm_s": 0.002})
    assert "exchange" not in blk["components"]


# ------------------------------------------------------- schema back-compat

def test_v1_fixture_run_loads_clean():
    """The frozen v1 run dir (pre-span, pre-measured_vs_model) must load
    through the CURRENT loader without modification — the one-release
    back-compat contract of schema.py."""
    from sgcn_tpu.obs import load_run

    log = load_run(os.path.join(FIX, "v1_run"))
    assert log.manifest["v"] == 1
    assert [e["kind"] for e in log.events] == ["step", "step", "eval",
                                               "summary"]
    steps = log.steps()
    assert steps[0]["roofline"]["comm_schedule"] == "a2a"
    assert steps[1]["drift"]["sync_step"] is False
    assert len(log.heartbeats) == 2
    # and the v1 stream round-trips the validator directly
    from sgcn_tpu.obs import validate_event
    for ev in log.events + log.heartbeats:
        validate_event(ev)


def test_v1_stream_may_not_carry_v2_kinds():
    from sgcn_tpu.obs import validate_event

    with pytest.raises(ValueError, match="kind"):
        validate_event({"v": 1, "ts": 1.0, "kind": "span",
                        "name": "x", "dur_s": 0.1})
    # a v2 stream may not carry the v3-only serve kind either
    with pytest.raises(ValueError, match="kind"):
        validate_event({"v": 2, "ts": 1.0, "kind": "serve", "queries": 1,
                        "achieved_qps": 1.0, "latency_p50_ms": 1.0,
                        "latency_p95_ms": 1.0, "latency_p99_ms": 1.0})
    # unknown version is rejected outright
    with pytest.raises(ValueError, match="version"):
        validate_event({"v": 99, "ts": 1.0, "kind": "step", "step": 1,
                        "loss": 1.0, "wall_s": 0.1})


def test_v2_span_event_validates():
    from sgcn_tpu.obs import validate_event

    validate_event({"v": 2, "ts": 1.0, "kind": "span", "name": "step",
                    "dur_s": 0.25, "parent": "train_step", "depth": 1,
                    "step": 4, "pid": 123})
    with pytest.raises(ValueError, match="dur_s"):
        validate_event({"v": 2, "ts": 1.0, "kind": "span", "name": "x",
                        "dur_s": -0.1})
    with pytest.raises(ValueError, match="non-finite"):
        validate_event({"v": 2, "ts": 1.0, "kind": "span", "name": "x",
                        "dur_s": float("nan")})


def test_v5_fixture_run_loads_clean():
    """The frozen v5 run dir (pre-memory: no ``memory`` event kind, no
    manifest ``memory`` block) must load through the CURRENT loader without
    modification — the one-release back-compat contract, re-pinned at the
    v5 -> v6 bump (ISSUE 18)."""
    from sgcn_tpu.obs import load_run, validate_event

    log = load_run(os.path.join(FIX, "v5_run"))
    assert log.manifest["v"] == 5
    assert "memory" not in log.manifest
    assert [e["kind"] for e in log.events] == [
        "span", "step", "span", "span", "step", "span", "span", "step",
        "span", "summary", "summary"]
    assert len(log.heartbeats) == 2
    assert all(e["v"] == 5 for e in log.events + log.heartbeats)
    for ev in log.events + log.heartbeats:
        validate_event(ev)


def test_v5_stream_may_not_carry_v6_kinds():
    from sgcn_tpu.obs import validate_event

    with pytest.raises(ValueError, match="kind"):
        validate_event({"v": 5, "ts": 1.0, "kind": "memory",
                        "program": "train_step", "model_bytes": 1024})


def test_v6_memory_event_validates():
    from sgcn_tpu.obs import validate_event

    # model-only (plan-time) and with the XLA measured join + ratio
    validate_event({"v": 6, "ts": 1.0, "kind": "memory",
                    "program": "train_step", "workload": "train",
                    "model_bytes": 2048})
    validate_event({"v": 6, "ts": 1.0, "kind": "memory",
                    "program": "bucket0", "workload": "serve",
                    "model_bytes": 2048, "measured_peak_bytes": 1024,
                    "argument_bytes": 512, "output_bytes": 256,
                    "temp_bytes": 256, "alias_bytes": 0,
                    "generated_code_bytes": 4096, "ratio": 0.5,
                    "budget_bytes": 1 << 30})
    with pytest.raises(ValueError, match="workload"):
        validate_event({"v": 6, "ts": 1.0, "kind": "memory",
                        "program": "x", "workload": "infer",
                        "model_bytes": 1})
    with pytest.raises(ValueError, match="non-finite/negative"):
        validate_event({"v": 6, "ts": 1.0, "kind": "memory",
                        "program": "x", "model_bytes": -1})
    # the ratio must agree with its own endpoints
    with pytest.raises(ValueError, match="ratio"):
        validate_event({"v": 6, "ts": 1.0, "kind": "memory",
                        "program": "x", "model_bytes": 1000,
                        "measured_peak_bytes": 500, "ratio": 2.0})
