"""Broadcast-1D baseline parity: must equal the dense oracle and the
partitioned path layer math (same Â, same weights) — SURVEY.md §2.3's
"1D uniform broadcast" row."""

import numpy as np

from sgcn_tpu.baselines.cagnet1d import BroadcastGCN1D
from sgcn_tpu.baselines.oracle import DenseOracle
from sgcn_tpu.partition import balanced_random_partition

K = 4


def test_broadcast_matches_oracle(ahat):
    n = ahat.shape[0]
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((n, 10)).astype(np.float32)
    pv = balanced_random_partition(n, K, seed=2)
    bc = BroadcastGCN1D(ahat, pv, K, fin=10, widths=[8, 3],
                        activation="sigmoid", seed=4)
    oracle = DenseOracle(ahat, fin=10, widths=[8, 3],
                         activation="sigmoid", final_activation="sigmoid",
                         seed=4)
    got = bc.forward(feats)
    want = oracle.predict(feats)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_broadcast_phase_report(ahat):
    n = ahat.shape[0]
    feats = np.random.default_rng(1).standard_normal((n, 6)).astype(np.float32)
    pv = balanced_random_partition(n, K, seed=2)
    bc = BroadcastGCN1D(ahat, pv, K, fin=6, widths=[4], seed=0)
    report, out = bc.run_epochs(feats, epochs=2)
    assert out.shape == (n, 4)
    assert report["epochs"] == 2
    assert "data_comm" in report["phases"] and "local_spmm" in report["phases"]
    # 2 epochs x 1 layer
    assert report["phases"]["data_comm"]["count"] == 2
    # broadcast volume is worse than any halo plan: (k-1) * n rows per layer
    assert report["send_volume_per_exchange"] == (K - 1) * n


def test_broadcast_fused_matches_unfused(ahat):
    n = ahat.shape[0]
    feats = np.random.default_rng(2).standard_normal((n, 6)).astype(np.float32)
    pv = balanced_random_partition(n, K, seed=5)
    a = BroadcastGCN1D(ahat, pv, K, fin=6, widths=[5, 3], seed=7)
    b = BroadcastGCN1D(ahat, pv, K, fin=6, widths=[5, 3], seed=7, fused=True)
    np.testing.assert_allclose(a.forward(feats), b.forward(feats),
                               rtol=1e-5, atol=1e-6)
