"""Unit tests for the observability vocabulary (SURVEY.md §5.1/§5.5):
CommStats' 8-number SUM/MAX report and its conservation invariants, and the
PhaseTimer phase breakdown (the CAGNET baseline's
data_comm/local_spmm/... accounting, Cagnet/main.c:35-38,395-413).

The deeper invariant — measured trainer volume == partitioner-predicted
connectivity — is covered end-to-end in test_minibatch/test_cli; these pin
the counter algebra itself.
"""

import numpy as np

from sgcn_tpu.io.datasets import er_graph
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.utils.stats import CommStats
from sgcn_tpu.utils.timers import PhaseTimer


def _plan(n=200, k=4, seed=0):
    ahat = normalize_adjacency(er_graph(n, 6, seed))
    pv = balanced_random_partition(n, k, seed=seed + 1)
    return build_comm_plan(ahat, pv, k)


def test_commstats_conservation_and_report():
    plan = _plan()
    st = CommStats.from_plan(plan)
    # every row some rank sends, exactly one rank receives (and vice versa):
    # global send volume == global recv volume, same for message counts
    assert st.send_volume_per_exchange.sum() == st.recv_volume_per_exchange.sum()
    assert st.send_msgs_per_exchange.sum() == st.recv_msgs_per_exchange.sum()

    st.count_step(nlayers=3)       # 3 fwd + 3 bwd exchanges
    st.count_forward(nlayers=2)    # inference adds fwd-only exchanges
    st.count_step(nlayers=3, hidden=True)   # a pipelined (stale) step
    assert st.exchanges == 14
    assert st.hidden_exchanges == 6
    rep = st.report()
    per_ex = int(st.send_volume_per_exchange.sum())
    assert rep["total_send_volume"] == 14 * per_ex
    assert rep["total_recv_volume"] == rep["total_send_volume"]
    assert rep["max_send_volume"] == 14 * int(st.send_volume_per_exchange.max())
    # hidden/exposed split: totals keep the reference meaning (all bytes
    # cross the wire); the split attributes them to the critical path or not
    assert rep["exposed_exchanges"] == 8
    assert rep["hidden_exchanges"] == 6
    assert rep["exposed_send_volume"] == 8 * per_ex
    assert rep["hidden_send_volume"] == 6 * per_ex
    assert set(rep) == {
        "total_send_volume", "max_send_volume", "total_send_msgs",
        "max_send_msgs", "total_recv_volume", "max_recv_volume",
        "total_recv_msgs", "max_recv_msgs", "exchanges",
        "exposed_exchanges", "hidden_exchanges", "exposed_send_volume",
        "hidden_send_volume",
        # the padded-vs-true wire split of the selected exchange schedule
        # (docs/comm_schedule.md), including the exposed/hidden wire-row
        # split the controller A/B judges on (PR-12)
        "comm_schedule", "true_rows_per_exchange", "wire_rows_per_exchange",
        "wire_rows_total", "exposed_wire_rows_total",
        "hidden_wire_rows_total", "padding_efficiency"}
    # wire accounting defaults to the dense a2a schedule and reconciles
    assert rep["comm_schedule"] == "a2a"
    assert rep["true_rows_per_exchange"] == per_ex
    assert rep["wire_rows_per_exchange"] >= per_ex
    assert rep["wire_rows_total"] == 14 * rep["wire_rows_per_exchange"]


def test_commstats_merged_report_matches_manual_sum():
    """merged_report = per-rank sums across batch plans first, SUM/MAX over
    ranks second (the reference shares one counter dict across batches)."""
    plans = [_plan(seed=s) for s in (0, 1)]
    stats = [CommStats.from_plan(p) for p in plans]
    stats[0].count_step(nlayers=2)
    stats[1].count_step(nlayers=2)
    stats[1].count_step(nlayers=2)
    merged = CommStats.merged_report(stats)
    sv = (stats[0].send_volume_per_exchange * stats[0].exchanges
          + stats[1].send_volume_per_exchange * stats[1].exchanges)
    assert merged["total_send_volume"] == int(sv.sum())
    assert merged["max_send_volume"] == int(sv.max())


def test_phase_timer_breakdown():
    t = PhaseTimer()
    for _ in range(3):
        with t.phase("data_comm"):
            pass
    with t.phase("local_spmm", sync=lambda: np.zeros(1)):
        pass
    rep = t.report()
    assert rep["data_comm"]["count"] == 3
    assert rep["local_spmm"]["count"] == 1
    assert rep["local_spmm"]["total_s"] >= 0
    np.testing.assert_allclose(
        rep["data_comm"]["avg_s"], rep["data_comm"]["total_s"] / 3)


def test_merged_report_mixed_hidden_exposed_multichip():
    """merged_report over a MIXED stats list — one counter trained stale
    (hidden exchanges), one exact, one inference-only — must carry the
    hidden/exposed split through the merge with each counter's OWN
    per-exchange volume, and still reconcile (hidden + exposed == total)."""
    plans = [_plan(seed=s) for s in (0, 1, 2)]
    stats = [CommStats.from_plan(p) for p in plans]
    stats[0].count_step(nlayers=2, hidden=True)      # pipelined steps
    stats[0].count_step(nlayers=2, hidden=True)
    stats[0].count_step(nlayers=2)                   # one full-sync step
    stats[1].count_step(nlayers=2)                   # exact-mode trainer
    stats[2].count_forward(nlayers=2)                # inference only
    merged = CommStats.merged_report(stats)

    assert merged["exchanges"] == 12 + 4 + 2
    assert merged["hidden_exchanges"] == 8
    assert merged["exposed_exchanges"] == merged["exchanges"] - 8
    # volumes: each counter's split uses ITS plan's per-exchange volume
    per = [int(s.send_volume_per_exchange.sum()) for s in stats]
    assert merged["hidden_send_volume"] == 8 * per[0]
    assert merged["exposed_send_volume"] == (4 * per[0] + 4 * per[1]
                                             + 2 * per[2])
    assert (merged["hidden_send_volume"] + merged["exposed_send_volume"]
            == merged["total_send_volume"])
    # the 8-number half still matches the manual per-rank sum
    sv = sum(s.send_volume_per_exchange * s.exchanges for s in stats)
    assert merged["total_send_volume"] == int(sv.sum())
    assert merged["max_send_volume"] == int(sv.max())


def test_shard_proxy_asymmetric_plan_raises():
    """The asymmetric-plan shard-proxy path: CommStats.from_plan on a proxy
    slice must REFUSE to fabricate recv counters (per-chip recv == send only
    holds for a symmetric exchange pattern) — previously only the happy
    path was pinned."""
    import pytest
    import scipy.sparse as sp

    from sgcn_tpu.parallel.proxy import shard_proxy_plan

    # a genuinely asymmetric adjacency (directed edges)
    rng = np.random.default_rng(3)
    dense = (rng.random((60, 60)) < 0.1).astype(np.float32)
    np.fill_diagonal(dense, 0)
    a = sp.csr_matrix(dense)
    pv = balanced_random_partition(60, 4, seed=5)
    plan = build_comm_plan(a, pv, 4)
    assert not plan.symmetric

    proxy = shard_proxy_plan(plan, chip=1)
    with pytest.raises(ValueError, match="ASYMMETRIC"):
        CommStats.from_plan(proxy)

    # the symmetric proxy stays the happy path (recv derived from send)
    splan = _plan(n=60, k=4, seed=9)
    st = CommStats.from_plan(shard_proxy_plan(splan, chip=2))
    assert st.k == 1
    assert (st.recv_volume_per_exchange == st.send_volume_per_exchange).all()


# ---------------------------------------------------------------------------
# run-telemetry subsystem (sgcn_tpu.obs): schema, recorder, attribution
# ---------------------------------------------------------------------------

def test_schema_validates_and_rejects():
    import pytest

    from sgcn_tpu.obs import SCHEMA_VERSION, validate_event

    ok = {"v": SCHEMA_VERSION, "ts": 1.0, "kind": "step", "step": 3,
          "loss": 0.5, "wall_s": 0.01,
          "comm": {"exchanges": 4, "exposed_exchanges": 2,
                   "hidden_exchanges": 2, "exposed_send_volume": 10,
                   "hidden_send_volume": 10, "total_send_volume": 20}}
    validate_event(ok)
    with pytest.raises(ValueError, match="kind"):
        validate_event({"v": SCHEMA_VERSION, "ts": 1.0, "kind": "nope"})
    with pytest.raises(ValueError, match="version"):
        validate_event({**ok, "v": 999})
    with pytest.raises(ValueError, match="missing required"):
        validate_event({"v": SCHEMA_VERSION, "ts": 1.0, "kind": "step",
                        "step": 1})
    with pytest.raises(ValueError, match="non-finite"):
        validate_event({**ok, "wall_s": float("nan")})
    # the split reconciliation is part of the schema itself
    bad = dict(ok, comm=dict(ok["comm"], hidden_exchanges=3))
    with pytest.raises(ValueError, match="hidden/exposed"):
        validate_event(bad)


def test_schema_v4_checkpoint_resume_events():
    """PR-13 resilience kinds: checkpoint/resume validate under v4, are
    rejected for older stream versions (a v3 stream must not carry them),
    and the serve ``shed`` key is typed + non-negative when present."""
    import pytest

    from sgcn_tpu.obs import SCHEMA_VERSION, validate_event

    ck = {"v": SCHEMA_VERSION, "ts": 1.0, "kind": "checkpoint", "step": 4,
          "path": "/runs/ckpt_00000004.npz", "bytes": 1234, "wall_s": 0.1}
    validate_event(ck)
    rs = {"v": SCHEMA_VERSION, "ts": 1.0, "kind": "resume", "step": 2,
          "path": "/runs/ckpt_00000002.npz", "fallback": True,
          "skipped": ["/runs/ckpt_00000004.npz"]}
    validate_event(rs)
    with pytest.raises(ValueError, match="kind"):
        validate_event({**ck, "v": 3})      # v3 stream may not carry v4 kind
    with pytest.raises(ValueError, match="missing required"):
        validate_event({"v": SCHEMA_VERSION, "ts": 1.0, "kind": "checkpoint",
                        "step": 4})
    with pytest.raises(ValueError, match="negative"):
        validate_event({**ck, "bytes": -1})
    sv = {"v": SCHEMA_VERSION, "ts": 1.0, "kind": "serve", "queries": 10,
          "achieved_qps": 5.0, "latency_p50_ms": 1.0, "latency_p95_ms": 2.0,
          "latency_p99_ms": 3.0, "shed": 2, "shed_factor": 2.0}
    validate_event(sv)
    with pytest.raises(ValueError, match="shed"):
        validate_event({**sv, "shed": -1})


def test_recorder_checkpoint_resume_roundtrip(tmp_path):
    from sgcn_tpu.obs import RunRecorder, load_run

    d = str(tmp_path / "run")
    with RunRecorder(d, config={}, run_kind="train") as rec:
        rec.record_checkpoint(step=2, path="/x/ckpt_00000002.npz",
                              wall_s=0.05, bytes=100)
        rec.record_resume(step=2, path="/x/ckpt_00000002.npz",
                          fallback=True, skipped=["/x/ckpt_00000004.npz"])
    log = load_run(d)
    assert [e["kind"] for e in log.events] == ["checkpoint", "resume"]
    assert log.checkpoints()[0]["bytes"] == 100
    assert log.resumes()[0]["fallback"] is True


def test_recorder_roundtrip(tmp_path):
    from sgcn_tpu.obs import RunRecorder, load_run

    plan = _plan()
    d = str(tmp_path / "run")
    with RunRecorder(d, config={"epochs": 2}, run_kind="train") as rec:
        rec.set_plan(plan, partitioner={"kind": "rp", "k": plan.k})
        rec.record_step(step=1, loss=1.5, wall_s=0.25, grad_norm=2.0)
        rec.record_eval(step=1, loss=1.4, acc=0.5)
        rec.record_heartbeat("unit:ping", detail="from test")
        rec.record_summary({"epochs": 2, "value": np.float32(1.25)})
    log = load_run(d)
    assert log.manifest["config"]["epochs"] == 2
    assert log.manifest["plan"]["n"] == plan.n
    assert log.manifest["partitioner"]["kind"] == "rp"
    assert len(log.manifest["plan"]["digest"]) == 16
    assert [e["kind"] for e in log.events] == ["step", "eval", "heartbeat",
                                               "summary"]
    assert log.summaries()[0]["report"]["value"] == 1.25  # numpy coerced
    # digest is stable for the same plan, different for a different one
    from sgcn_tpu.obs import plan_digest
    assert plan_digest(plan) == log.manifest["plan"]["digest"]
    assert plan_digest(_plan(seed=7)) != log.manifest["plan"]["digest"]


def test_recorder_refuses_invalid_event(tmp_path):
    import pytest

    from sgcn_tpu.obs import RunRecorder

    with RunRecorder(str(tmp_path / "r"), config={}) as rec:
        with pytest.raises(ValueError):
            rec.record_step(step=1, loss=1.0, wall_s=float("nan"))


def test_heartbeat_env_gated(tmp_path, monkeypatch):
    import json
    import os

    from sgcn_tpu.obs import heartbeat, load_run

    d = str(tmp_path / "hb")
    monkeypatch.delenv("SGCN_METRICS_OUT", raising=False)
    heartbeat("should:not:write")
    assert not os.path.exists(os.path.join(d, "heartbeat.jsonl"))
    monkeypatch.setenv("SGCN_METRICS_OUT", d)
    heartbeat("phase:start", phase="unit", detail="x")
    heartbeat("phase:done", phase="unit")
    path = os.path.join(d, "heartbeat.jsonl")
    assert os.path.exists(path)
    recs = [json.loads(line) for line in open(path)]
    assert [r["event"] for r in recs] == ["phase:start", "phase:done"]
    # a heartbeat-ONLY directory (the launch/dryrun workflow — no recorder,
    # no manifest) must still load; manifest comes back empty
    log = load_run(d)
    assert log.manifest == {} and len(log.heartbeats) == 2


def test_step_cost_model_and_roofline():
    from sgcn_tpu.models.gcn import exchange_widths
    from sgcn_tpu.obs import (STREAM_CEILING_GBS, gather_bytes_per_epoch,
                              roofline_fields, step_cost)

    plan = _plan()
    fin, widths = 16, [32, 8]
    cost = step_cost(plan, fin, widths)
    assert cost.nlayers == 2
    assert cost.widths == exchange_widths(fin, widths)
    # the gather-byte model is THE bench.py roofline numerator (moved here)
    assert cost.gather_bytes == gather_bytes_per_epoch(plan, fin, widths)
    # per-layer blocks reconcile with the totals
    assert sum(pl["spmm_flops"] for pl in cost.per_layer) == cost.spmm_flops
    assert sum(pl["dense_flops"] for pl in cost.per_layer) == cost.dense_flops
    assert cost.step_flops == 2 * cost.spmm_flops + 3 * cost.dense_flops
    # halo bytes: global send rows at f32, 2L exchanges per step
    send_rows = int(plan.predicted_send_volume.sum())
    assert cost.halo_send_rows == send_rows
    assert cost.halo_bytes_per_step == 2 * sum(
        send_rows * w * 4 for w in cost.widths)
    # bf16 compute halves both streams
    bf = step_cost(plan, fin, widths, compute_dtype="bfloat16")
    assert bf.gather_bytes == gather_bytes_per_epoch(plan, fin, widths,
                                                     itemsize=2)
    assert bf.halo_bytes_per_step == cost.halo_bytes_per_step // 2

    roof = roofline_fields(cost, wall_s=0.01, exchanges=4,
                           exposed_exchanges=1)
    assert roof["achieved_gather_GBs"] == float(
        f"{cost.gather_bytes / 0.01 / 1e9:.4g}")
    assert roof["stream_ceiling_frac"] == float(
        f"{cost.gather_bytes / 0.01 / 1e9 / STREAM_CEILING_GBS:.4g}")
    assert roof["exposed_comm_frac"] == 0.25
    # exposed bytes charge the WIRE volume of the selected schedule (the
    # padded slots cross ICI too — docs/comm_schedule.md), not the Σ(λ−1)
    # true volume the pre-split model under-counted with
    assert roof["exposed_halo_bytes"] == cost.halo_bytes_wire_per_step // 4
    assert roof["halo_bytes_true_per_step"] == cost.halo_bytes_per_step
    assert roof["halo_bytes_wire_per_step"] >= roof["halo_bytes_true_per_step"]
    assert roof["comm_schedule"] == "a2a"
