"""Unit tests for the observability vocabulary (SURVEY.md §5.1/§5.5):
CommStats' 8-number SUM/MAX report and its conservation invariants, and the
PhaseTimer phase breakdown (the CAGNET baseline's
data_comm/local_spmm/... accounting, Cagnet/main.c:35-38,395-413).

The deeper invariant — measured trainer volume == partitioner-predicted
connectivity — is covered end-to-end in test_minibatch/test_cli; these pin
the counter algebra itself.
"""

import numpy as np

from sgcn_tpu.io.datasets import er_graph
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.utils.stats import CommStats
from sgcn_tpu.utils.timers import PhaseTimer


def _plan(n=200, k=4, seed=0):
    ahat = normalize_adjacency(er_graph(n, 6, seed))
    pv = balanced_random_partition(n, k, seed=seed + 1)
    return build_comm_plan(ahat, pv, k)


def test_commstats_conservation_and_report():
    plan = _plan()
    st = CommStats.from_plan(plan)
    # every row some rank sends, exactly one rank receives (and vice versa):
    # global send volume == global recv volume, same for message counts
    assert st.send_volume_per_exchange.sum() == st.recv_volume_per_exchange.sum()
    assert st.send_msgs_per_exchange.sum() == st.recv_msgs_per_exchange.sum()

    st.count_step(nlayers=3)       # 3 fwd + 3 bwd exchanges
    st.count_forward(nlayers=2)    # inference adds fwd-only exchanges
    st.count_step(nlayers=3, hidden=True)   # a pipelined (stale) step
    assert st.exchanges == 14
    assert st.hidden_exchanges == 6
    rep = st.report()
    per_ex = int(st.send_volume_per_exchange.sum())
    assert rep["total_send_volume"] == 14 * per_ex
    assert rep["total_recv_volume"] == rep["total_send_volume"]
    assert rep["max_send_volume"] == 14 * int(st.send_volume_per_exchange.max())
    # hidden/exposed split: totals keep the reference meaning (all bytes
    # cross the wire); the split attributes them to the critical path or not
    assert rep["exposed_exchanges"] == 8
    assert rep["hidden_exchanges"] == 6
    assert rep["exposed_send_volume"] == 8 * per_ex
    assert rep["hidden_send_volume"] == 6 * per_ex
    assert set(rep) == {
        "total_send_volume", "max_send_volume", "total_send_msgs",
        "max_send_msgs", "total_recv_volume", "max_recv_volume",
        "total_recv_msgs", "max_recv_msgs", "exchanges",
        "exposed_exchanges", "hidden_exchanges", "exposed_send_volume",
        "hidden_send_volume"}


def test_commstats_merged_report_matches_manual_sum():
    """merged_report = per-rank sums across batch plans first, SUM/MAX over
    ranks second (the reference shares one counter dict across batches)."""
    plans = [_plan(seed=s) for s in (0, 1)]
    stats = [CommStats.from_plan(p) for p in plans]
    stats[0].count_step(nlayers=2)
    stats[1].count_step(nlayers=2)
    stats[1].count_step(nlayers=2)
    merged = CommStats.merged_report(stats)
    sv = (stats[0].send_volume_per_exchange * stats[0].exchanges
          + stats[1].send_volume_per_exchange * stats[1].exchanges)
    assert merged["total_send_volume"] == int(sv.sum())
    assert merged["max_send_volume"] == int(sv.max())


def test_phase_timer_breakdown():
    t = PhaseTimer()
    for _ in range(3):
        with t.phase("data_comm"):
            pass
    with t.phase("local_spmm", sync=lambda: np.zeros(1)):
        pass
    rep = t.report()
    assert rep["data_comm"]["count"] == 3
    assert rep["local_spmm"]["count"] == 1
    assert rep["local_spmm"]["total_s"] >= 0
    np.testing.assert_allclose(
        rep["data_comm"]["avg_s"], rep["data_comm"]["total_s"] / 3)
