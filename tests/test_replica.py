"""Hot-halo replication (``--replica-budget B``): persistent per-layer
replicas of the plan's top-B boundary rows on their consumer chips
(``CommPlan.ensure_replicas``, ``ops/pspmm.py::pspmm_replica[_ragged]``,
docs/replication.md) — CaPGNN-style feature caching (ROADMAP item 2).

Contract pinned here:

  * ``sync_every=1`` replica training is f32-BIT-identical to the exact
    no-replica path on the cora fixture under BOTH transports — losses AND
    parameters ``==`` (the refresh program IS the exact program plus the
    replica gathers; the ragged flavor chains the PR-4/PR-6 parity);
  * the replica (non-refresh) step ships the SHRUNKEN exchange: per-pair
    buckets and ring rounds lose exactly the replicated rows' shipments
    (Σλ of the selection), and the approximate run stays finite with the
    fused ``run_epochs`` reproducing per-step ``step()``;
  * the replica carries are per-layer ``(RP, f_ℓ)`` tables at the
    EXCHANGED widths (same lockstep rule as the stale carries);
  * telemetry: the ``replica`` event block (schema ``REPLICA_KEYS``) is
    emitted and schema-valid, drift is measured at each refresh, and the
    cumulative ``CommStats`` byte gauges reconcile EXACTLY with the sum of
    per-step roofline figures (replica steps booked at the shrunken
    volumes);
  * the native cache-aware km1 driver's objective is <= the cache-blind
    partition's objective under an INDEPENDENT numpy evaluator, at equal
    balance;
  * construction-time gates: GAT, staleness composition, compute_dtype,
    and the mini-batch trainer all reject replication with clear errors.
"""

import os

import numpy as np
import pytest

from sgcn_tpu.io.datasets import load_npz_dataset
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition.emit import read_partvec
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")

WIDTHS = [16, 7]
BUDGET = 24


@pytest.fixture(scope="module")
def cora():
    """The committed cora-format fixture + its 4-way hp partvec."""
    a, feats, labels = load_npz_dataset(os.path.join(FIX, "cora_like.npz"))
    ahat = normalize_adjacency(a)
    pv = read_partvec(os.path.join(FIX, "cora_like.4.hp"))
    plan = build_comm_plan(ahat, pv, 4)
    return plan, feats.astype(np.float32), labels.astype(np.int32)


@pytest.fixture(scope="module")
def exact_run(cora):
    """Exact no-replica reference: 4 losses + trained parameters, shared
    by both transports' bit-identity assertions (one compile)."""
    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=3)
    d = make_train_data(plan, feats, labels)
    losses = [tr.step(d) for _ in range(4)]
    return losses, [np.asarray(w) for w in tr.params]


@pytest.mark.parametrize("schedule", ["a2a", "ragged"])
def test_replica_sync1_bit_identical_to_exact(cora, exact_run, schedule):
    """THE acceptance contract: ``--replica-budget B>0 --sync-every 1``
    trains cora with losses and parameters exactly equal to the exact
    no-replica path's, under both transports — every step runs the refresh
    program, which is the exact program plus the replica-row gathers."""
    plan, feats, labels = cora
    exact_losses, exact_params = exact_run
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=3,
                          comm_schedule=schedule, replica_budget=BUDGET,
                          sync_every=1)
    assert tr.replica_budget == BUDGET
    assert plan.replica_rows == BUDGET
    d = make_train_data(plan, feats, labels)
    lc = [tr.step(d) for _ in range(4)]
    assert lc == exact_losses                        # bitwise, not allclose
    for wa, wb in zip(exact_params, tr.params):
        np.testing.assert_array_equal(wa, np.asarray(wb))


def test_replica_layout_invariants(cora):
    """Selection + shrunken-layout bookkeeping: the shrunken buckets lose
    exactly the replicated rows' Σλ shipments, the replica slots cover the
    same Σλ receive positions, and the shrunken wire never exceeds the
    full one under either transport."""
    plan, _, _ = cora
    plan.ensure_ragged()
    plan.ensure_replicas(BUDGET)
    lam, cons = plan.replica_scores()
    assert int(lam.sum()) == int(plan.send_counts.sum())
    assert plan.replica_rows == BUDGET
    saving = plan.replica_send_saving
    assert saving >= BUDGET            # every boundary row has λ >= 1
    assert (int(plan.nrep_send_counts.sum())
            == int(plan.send_counts.sum()) - saving)
    assert int(plan.rep_counts.sum()) == saving
    for sched in ("a2a", "ragged"):
        assert (plan.wire_rows_per_exchange(sched, replica=True)
                <= plan.wire_rows_per_exchange(sched))
        for shrunk, full in zip(plan.wire_buffer_shapes(sched, replica=True),
                                plan.wire_buffer_shapes(sched)):
            assert np.prod(shrunk) <= np.prod(full)
    # carries ride the exchanged widths, RP rows each (stale-carry lockstep)
    from sgcn_tpu.models.gcn import exchange_widths
    shapes = plan.replica_carry_shapes(1433, WIDTHS)
    fs = exchange_widths(1433, WIDTHS)
    assert shapes["reps"] == [(plan.rp, f) for f in fs]
    assert shapes["greps"] == shapes["reps"]


def test_replica_run_epochs_parity(cora):
    """The fused multi-step path reproduces per-step ``step()`` exactly,
    refresh scheduling included."""
    plan, feats, labels = cora
    d = make_train_data(plan, feats, labels)
    kw = dict(fin=feats.shape[1], widths=WIDTHS, seed=5,
              comm_schedule="ragged", replica_budget=BUDGET, sync_every=3)
    ta = FullBatchTrainer(plan, **kw)
    la = [ta.step(d) for _ in range(5)]
    tb = FullBatchTrainer(plan, **kw)
    lb = tb.run_epochs(d, 5)
    np.testing.assert_array_equal(np.asarray(la, np.float32),
                                  np.asarray(lb, np.float32))
    for wa, wb in zip(ta.params, tb.params):
        np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    # stats booked identically: refresh steps at the full volumes, replica
    # steps at the shrunken ones
    ra, rb = ta.stats.report(), tb.stats.report()
    assert ra == rb
    assert ra["replica_exchanges"] == 2 * len(WIDTHS) * 3   # steps 1,2,4
    assert ra["halo_bytes_true_total"] < 5 * ra["halo_bytes_true_per_step"]


def test_replica_telemetry_books_and_reconciles(cora, tmp_path):
    """Recorder path: the ``replica`` block is emitted and schema-valid
    (load_run re-validates), drift is measured at refreshes, the roofline
    prices replica steps at the shrunken volumes, and the cumulative
    CommStats byte gauges equal the event stream's per-step sums EXACTLY
    — the gauge-reconciliation smoke of the satellite."""
    from sgcn_tpu.obs import RunRecorder, load_run

    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=0,
                          replica_budget=BUDGET, sync_every=3)
    d = make_train_data(plan, feats, labels)
    rec = RunRecorder(str(tmp_path / "run"), config={"replica": BUDGET})
    tr.attach_recorder(rec)
    for _ in range(5):
        tr.step(d)
    rec.close()
    log = load_run(str(tmp_path / "run"))          # schema re-validated
    steps = [e for e in log.events if e["kind"] == "step"]
    assert len(steps) == 5
    blocks = [s["replica"] for s in steps]
    assert [b["sync_step"] for b in blocks] == [True, False, False, True,
                                                False]
    assert [b["refresh_age"] for b in blocks] == [0, 1, 2, 3, 1]
    assert all(b["replica_rows"] == BUDGET for b in blocks)
    # drift exists only at refreshes (fresh values only exist on the wire
    # there); step 4's refresh erased 3 steps of drift — nonzero because
    # the exchanged rows move with the weights (cora is project-first).
    # The INITIALIZING refresh (step 1) reports zero: its in-graph gauge
    # compares against the zero-init carry (initialization magnitude, not
    # drift) and must not dominate the operator's max/mean.
    assert blocks[3]["replica_drift_rms"][-1] > 0
    assert blocks[0]["replica_drift_rms"] == [0.0, 0.0]
    assert blocks[1]["replica_drift_rms"] == [0.0, 0.0]
    # replica steps priced at the shrunken wire, refreshes at the full one
    wire = [s["roofline"]["halo_wire_rows_per_exchange"] for s in steps]
    assert wire[0] == wire[3] == plan.wire_rows_per_exchange("a2a")
    assert wire[1] == plan.wire_rows_per_exchange("a2a", replica=True)
    assert wire[1] < wire[0]
    # exact reconciliation, replica-step resolution included
    comm = steps[-1]["comm"]
    assert comm["halo_bytes_true_total"] == sum(
        s["roofline"]["halo_bytes_true_per_step"] for s in steps)
    assert comm["halo_bytes_wire_total"] == sum(
        s["roofline"]["halo_bytes_wire_per_step"] for s in steps)
    # every replica-mode exchange is synchronous — nothing hidden
    assert comm["hidden_exchanges"] == 0
    assert comm["exposed_exchanges"] == comm["exchanges"]
    # the rendered report carries the replica gauge lines
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(os.path.dirname(FIX), "..",
                                   "scripts", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    text = mod.render(str(tmp_path / "run"))
    assert "replica gauges (hot-halo replication)" in text
    assert f"replica rows: {BUDGET}" in text


def test_native_cache_aware_km1(cora):
    """The partitioner acceptance inequality: the cache-aware RB driver's
    km1_cache is <= the cache-blind partition's cache objective under an
    independent numpy evaluator, at equal balance caps, and the native and
    numpy objective implementations agree bit-for-bit."""
    import scipy.sparse as sp

    from sgcn_tpu.io.datasets import load_npz_dataset as _l  # noqa: F401
    from sgcn_tpu.partition import (partition_hypergraph_colnet,
                                    partition_hypergraph_colnet_cache)
    from sgcn_tpu.partition.native import cache_aware_km1

    a, _, _ = load_npz_dataset(os.path.join(FIX, "cora_like.npz"))
    k, B = 4, 48
    pv_blind, km1_blind = partition_hypergraph_colnet(a, k, seed=0)
    pv_c, km1_c, km1_cache = partition_hypergraph_colnet_cache(
        a, k, B, seed=0)
    assert km1_cache == cache_aware_km1(a, pv_c, B)
    assert km1_cache <= cache_aware_km1(a, pv_blind, B)
    assert km1_cache <= km1_c
    w = np.maximum(np.diff(sp.csr_matrix(a).indptr), 1)
    cap = 1.03 * w.sum() / k
    wc = np.array([w[pv_c == p].sum() for p in range(k)])
    assert wc.max() <= cap + w.max()     # same slack rule as the driver


def test_replica_gating(cora):
    """Construction-time gates: clear errors for every unsupported combo
    (mirrors analysis/modes.py::is_supported and the CLI conflicts)."""
    plan, feats, labels = cora
    fin = feats.shape[1]
    with pytest.raises(ValueError, match="GAT"):
        FullBatchTrainer(plan, fin=fin, widths=WIDTHS, model="gat",
                         replica_budget=8)
    # replica × staleness COMPOSES since PR-12 (tests/test_replica_stale.py);
    # the remaining deferred composition is the delta cache
    with pytest.raises(ValueError, match="deferred"):
        FullBatchTrainer(plan, fin=fin, widths=WIDTHS, halo_staleness=1,
                         halo_delta=True, replica_budget=8)
    with pytest.raises(ValueError, match="f32 non-remat"):
        FullBatchTrainer(plan, fin=fin, widths=WIDTHS,
                         compute_dtype="bfloat16", replica_budget=8)
    with pytest.raises(ValueError, match="replica_budget must be >= 0"):
        FullBatchTrainer(plan, fin=fin, widths=WIDTHS, replica_budget=-1)
    with pytest.raises(ValueError, match="replication is not supported"):
        FullBatchTrainer(plan, fin=fin, widths=WIDTHS, model="gat",
                         replica_budget="auto")
    # sync_every now legal with EITHER lever, still not alone
    with pytest.raises(ValueError, match="sync_every"):
        FullBatchTrainer(plan, fin=fin, widths=WIDTHS, sync_every=2)
    from sgcn_tpu.train.minibatch import MiniBatchTrainer
    a, _, _ = load_npz_dataset(os.path.join(FIX, "cora_like.npz"))
    with pytest.raises(ValueError, match="mini-batch"):
        MiniBatchTrainer(normalize_adjacency(a), np.asarray(plan.owner), 4,
                         fin=fin, widths=WIDTHS, batch_size=64,
                         replica_budget=8)


def test_replica_budget_clamps_to_boundary(cora):
    """A budget above the boundary row count clamps (everything
    replicated — the communication-free limit) and still trains: replica
    steps ship empty buckets, refreshes the full exchange."""
    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS,
                          replica_budget=10**7, sync_every=2)
    assert plan.replica_rows < 10**7
    assert int(plan.nrep_send_counts.sum()) == 0
    d = make_train_data(plan, feats, labels)
    losses = [tr.step(d) for _ in range(3)]
    assert np.all(np.isfinite(losses))
    rep = tr.stats.report()
    assert rep["true_rows_per_exchange_replica"] == 0
