"""Multi-process (multi-host) integration: 2 processes × 4 virtual CPU
devices each, rendezvous over local TCP — the working equivalent of the
reference's 3-node SLURM launch (``GPU/pytorch.3node.slurm:46-56`` +
``GPU/PGCN.py:241-260``, ``dist.init_process_group`` over MASTER_ADDR).

Each subprocess: ``jax.distributed.initialize`` → 8-device global mesh →
identical plan from the same seeds → ``make_train_data_multihost`` (each
process materializes ONLY its chips' blocks) → 3 training steps.  The
parent runs the same problem single-process on its own 8 virtual devices
and asserts the loss trajectories match exactly — data placement must not
change the math.
"""

import json
import socket
import subprocess
import sys
import os

import numpy as np
import pytest

_WORKER = r"""
import json, sys
import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

coord, pid = sys.argv[1], int(sys.argv[2])
jax.distributed.initialize(coordinator_address=coord, num_processes=2,
                           process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 4

sys.path.insert(0, {repo!r})
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.parallel.launch import global_mesh_1d
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data_multihost
import scipy.sparse as sp

rng = np.random.default_rng(1)
n = 48
dense = rng.random((n, n)) < 0.15
dense = np.triu(dense, 1); dense = dense | dense.T
ahat = normalize_adjacency(sp.csr_matrix(dense.astype(np.float32)))
pv = balanced_random_partition(n, 8, seed=3)
plan = build_comm_plan(ahat, pv, 8)
mesh = global_mesh_1d(8)
feats = np.random.default_rng(7).standard_normal((n, 6)).astype(np.float32)
labels = (np.arange(n) % 3).astype(np.int32)

# each process only needs ITS chips' rows: blank out everything else to
# prove remote rows are never read
from sgcn_tpu.parallel.mesh import local_chip_slice
sl = local_chip_slice(mesh)
mine = np.isin(pv, np.arange(8)[sl])
feats_local = np.where(mine[:, None], feats, 0.0).astype(np.float32)
labels_local = np.where(mine, labels, 0).astype(np.int32)

tr = FullBatchTrainer(plan, fin=6, widths=[5, 3], mesh=mesh, seed=11)
data = make_train_data_multihost(plan, mesh, feats_local, labels_local)
losses = [float(tr.step(data)) for _ in range(3)]
if jax.process_index() == 0:
    print("LOSSES " + json.dumps(losses), flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_training_matches_single(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = _WORKER.replace("{repo!r}", repr(repo))
    script = tmp_path / "worker.py"
    script.write_text(worker)
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTEST_CURRENT_TEST", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, str(i)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        if rc != 0 and "Multiprocess computations aren't implemented" in err:
            # jaxlib builds before multi-process CPU collectives (observed
            # 0.4.36) cannot run this path at all — an environment gap, not
            # a code regression; the sharding/placement logic it exercises
            # is covered single-process by make_train_data_multihost tests
            pytest.skip("this jaxlib has no multi-process CPU collectives")
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
    line = [ln for ln in outs[0][1].splitlines() if ln.startswith("LOSSES ")]
    assert line, outs[0][1]
    losses_mp = json.loads(line[0][len("LOSSES "):])

    # single-process reference on this process's own 8 virtual devices,
    # same seeds → identical trajectory expected
    import scipy.sparse as sp
    from sgcn_tpu.parallel import build_comm_plan
    from sgcn_tpu.partition import balanced_random_partition
    from sgcn_tpu.prep import normalize_adjacency
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    rng = np.random.default_rng(1)
    n = 48
    dense = rng.random((n, n)) < 0.15
    dense = np.triu(dense, 1)
    dense = dense | dense.T
    ahat2 = normalize_adjacency(sp.csr_matrix(dense.astype(np.float32)))
    pv = balanced_random_partition(n, 8, seed=3)
    plan = build_comm_plan(ahat2, pv, 8)
    feats = np.random.default_rng(7).standard_normal((n, 6)).astype(np.float32)
    labels = (np.arange(n) % 3).astype(np.int32)
    tr = FullBatchTrainer(plan, fin=6, widths=[5, 3], seed=11)
    data = make_train_data(plan, feats, labels)
    losses_sp = [float(tr.step(data)) for _ in range(3)]
    np.testing.assert_allclose(losses_mp, losses_sp, rtol=1e-5, atol=1e-6)
