"""Tier-1 gate: every checked-in bench evidence file passes the validator.

``scripts/validate_bench.py`` encodes the evidence contracts (driver-record
shape, graceful-degradation markers, measurement-quality consistency, the
pow2-k RB constraint from the PR-2 review incident); running it in the
tier-1 flow means a hand-edited or unreproducible artifact fails CI the
commit it lands, not a review round later.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from validate_bench import (check_bench_record, check_multichip_record,  # noqa: E402
                            check_pallas_ragged_ab, check_products_ksweep,
                            check_ragged_ab, check_ragged_stale_ab,
                            check_serve_qps, validate_tree)


def test_checked_in_artifacts_validate():
    problems = validate_tree(REPO)
    assert not problems, "\n".join(problems)


def test_validator_catches_null_value_without_marker():
    rec = {"n": 1, "cmd": "x", "rc": 0, "tail": "",
           "parsed": {"metric": "m", "value": None, "unit": "s"}}
    assert any("skipped/degraded" in e for e in check_bench_record(rec))
    rec["parsed"]["degraded"] = "flagship phase exceeded its deadline"
    assert not check_bench_record(rec)


def test_validator_resume_provenance_rule():
    """PR-13: a parsed result claiming ``resumed: true`` must name the
    checkpoint that seeded it (step + format version); a present-but-
    untrue flag is a violation anywhere (the ``measured``-flag rule)."""
    from validate_bench import check_resume_provenance

    assert not check_resume_provenance({"metric": "m", "value": 1.0})
    # the trainer CLI's own shape: the resumed block IS the identity
    cli = {"metric": "m", "value": 1.0,
           "resumed": {"step": 4, "path": "/ck/ckpt_00000004.npz",
                       "fallback": False}}
    assert not check_resume_provenance(cli)
    cli["resumed"] = {"fallback": True}           # identity fields missing
    assert any("identity" in e for e in check_resume_provenance(cli))
    bare = {"metric": "m", "value": 1.0, "resumed": True}
    assert any("checkpoint_meta" in e for e in check_resume_provenance(bare))
    bare["checkpoint_meta"] = {"step": 4}          # missing version
    assert any("checkpoint_meta" in e for e in check_resume_provenance(bare))
    bare["checkpoint_meta"] = {"step": 4, "version": 2}
    assert not check_resume_provenance(bare)
    lied = {"metric": "m", "value": 1.0, "resumed": "yes"}
    assert any("provenance flag" in e for e in check_resume_provenance(lied))
    # rides check_bench_record for driver records (rc-independent flag
    # integrity, meta requirement on claims)
    rec = {"n": 1, "cmd": "x", "rc": 0, "tail": "",
           "parsed": {"metric": "m", "value": 1.0, "resumed": True}}
    assert any("checkpoint_meta" in e for e in check_bench_record(rec))
    rec["parsed"]["checkpoint_meta"] = {"step": 4, "version": 2}
    assert not check_bench_record(rec)


def test_validator_catches_impossible_measurement_block():
    rec = {"n": 1, "cmd": "x", "rc": 0, "tail": "",
           "parsed": {"metric": "m", "value": 1.0, "unit": "s",
                      "measurement": {"clean_estimates": 5,
                                      "target_estimates": 3}}}
    assert any("measurement" in e for e in check_bench_record(rec))


def test_validator_catches_silent_multichip_failure():
    assert any("skipped/degraded" in e for e in check_multichip_record(
        {"n_devices": 8, "ok": False, "rc": 0}))
    # non-zero rc is its own explanation (historical round-1/5 records)
    assert not check_multichip_record({"n_devices": 8, "ok": False,
                                       "rc": 124})


def test_validator_enforces_pow2_rb_constraint():
    """The PR-2 incident shape: hp_rb data at non-pow2 k is unreproducible
    with the code at HEAD and must fail validation."""
    bad = {"sweep": {"ba": {"9": {"hp": {"km1": 5, "time_s": 1.0},
                                  "hp_rb": {"km1": 4, "time_s": 1.0}}}}}
    errs = check_products_ksweep(bad)
    assert any("hp_rb" in e and "unreproducible" in e for e in errs)
    ok = {"sweep": {"ba": {"32": {"hp": {"km1": 5, "time_s": 1.0},
                                  "hp_rb": {"km1": 4, "time_s": 1.0}},
                           "8": {"hp": {"km1": 7, "time_s": 1.0}}}}}
    assert not check_products_ksweep(ok)


def _rab_entry(**over):
    e = {"epoch_s_a2a": 0.03, "epoch_s_ragged": 0.02,
         "padding_efficiency": 0.4, "padded_true_ratio_a2a": 2.5,
         "wire_rows_a2a": 1000, "wire_rows_ragged": 600, "true_rows": 400}
    e.update(over)
    return e


def test_validator_ragged_ab_contract():
    """The a2a-vs-ragged A/B block: null needs a degradation marker; a
    config's per-round wire rows can never exceed the global pad, its
    padded/true ratio never drop below 1 (both are hand-edit tells)."""
    assert any("ragged_ab_degraded" in e for e in check_ragged_ab(
        {"ragged_ab_8dev": None}))
    assert not check_ragged_ab({"ragged_ab_8dev": None,
                                "ragged_ab_degraded": "deadline"})
    ok = {"ragged_ab_8dev": {"random": _rab_entry(), "hp": _rab_entry()}}
    assert not check_ragged_ab(ok)
    bad_wire = {"ragged_ab_8dev": {
        "hp": _rab_entry(wire_rows_ragged=2000)}}
    assert any("global pad" in e for e in check_ragged_ab(bad_wire))
    bad_ratio = {"ragged_ab_8dev": {
        "hp": _rab_entry(padded_true_ratio_a2a=0.8)}}
    assert any("below 1" in e for e in check_ragged_ab(bad_ratio))
    bad_pe = {"ragged_ab_8dev": {"hp": _rab_entry(padding_efficiency=1.7)}}
    assert any("padding_efficiency" in e for e in check_ragged_ab(bad_pe))
    assert any("no random/hp" in e
               for e in check_ragged_ab({"ragged_ab_8dev": {}}))


def _rsab_arm(frac, wire, nl=2, **over):
    a = {"epoch_s": 0.03, "wire_rows_per_exchange": wire,
         "exposed_comm_frac": frac,
         "exposed_wire_rows_per_step": round(frac * wire * 2 * nl, 2)}
    a.update(over)
    return a


def _rsab_block(**over):
    b = {"arms": {"a2a_stale": _rsab_arm(0.25, 1000),
                  "ragged_exact": _rsab_arm(1.0, 600),
                  "ragged_stale": _rsab_arm(0.25, 600)},
         "clean_pairs": 3,
         "note": "epoch speed is not the asserted figure — exposed-comm "
                 "accounting is"}
    b.update(over)
    return b


def test_validator_ragged_stale_ab_contract():
    """The composed-mode three-way block (PR-6): null needs a degradation
    marker; the composed arm must be <= both single levers on the exposed
    fraction and STRICTLY below both on exposed wire rows per step, and
    the honest-measurement note must be present."""
    assert any("ragged_stale_ab_degraded" in e for e in check_ragged_stale_ab(
        {"ragged_stale_ab_8dev": None}))
    assert not check_ragged_stale_ab(
        {"ragged_stale_ab_8dev": None, "ragged_stale_ab_degraded": "deadline"})
    assert not check_ragged_stale_ab({"ragged_stale_ab_8dev": _rsab_block()})
    # composed fraction above a single lever's — acceptance violated
    bad_frac = _rsab_block()
    bad_frac["arms"]["ragged_stale"] = _rsab_arm(0.5, 600)
    errs = check_ragged_stale_ab({"ragged_stale_ab_8dev": bad_frac})
    assert any("exposed_comm_frac" in e and "acceptance" in e for e in errs)
    # composed exposed wire rows not strictly below a2a+stale (same wire)
    bad_wire = _rsab_block()
    bad_wire["arms"]["ragged_stale"] = _rsab_arm(0.25, 1000)
    errs = check_ragged_stale_ab({"ragged_stale_ab_8dev": bad_wire})
    assert any("STRICTLY" in e for e in errs)
    # the honest-measurement note is part of the contract
    no_note = _rsab_block(note="timings")
    assert any("note" in e for e in check_ragged_stale_ab(
        {"ragged_stale_ab_8dev": no_note}))
    assert any("missing arm" in e for e in check_ragged_stale_ab(
        {"ragged_stale_ab_8dev": {"arms": {"a2a_stale": _rsab_arm(1, 10)}}}))


def _prab_arm(wire, halo_bytes, **over):
    a = {"epoch_s": 0.1, "measured": True,
         "wire_rows_per_exchange": wire,
         "halo_table_bytes_per_step": halo_bytes}
    a.update(over)
    return a


def _prab_block(**over):
    b = {"n": 12000, "graph": "ba", "k": 8,
         "timing": "EMULATE-mode kernels; epoch speed is reported "
                   "honestly but is never the claim",
         "ell_ragged": _prab_arm(24096, 0),
         "pallas_ragged": _prab_arm(24096, 0),
         "pallas_a2a": _prab_arm(28736, 37011456)}
    b.update(over)
    return b


def test_validator_pallas_ragged_ab_contract():
    """The kernel × schedule block (ISSUE 15): null needs a degradation
    marker; the pallas ragged arm must ship the ELL arm's EXACT wire,
    strictly below the a2a pad, and book zero halo-table bytes; epoch
    times need measured provenance and the honest note."""
    assert any("degraded" in e for e in check_pallas_ragged_ab(
        {"pallas_ragged_ab_8dev": None}))
    assert not check_pallas_ragged_ab(
        {"pallas_ragged_ab_8dev": None,
         "pallas_ragged_ab_degraded": "deadline"})
    assert not check_pallas_ragged_ab(
        {"pallas_ragged_ab_8dev": _prab_block()})
    # kernel silently changed the transport (different wire)
    drift = _prab_block(pallas_ragged=_prab_arm(20000, 0))
    assert any("must not touch the transport" in e
               for e in check_pallas_ragged_ab(
                   {"pallas_ragged_ab_8dev": drift}))
    # halo table crept back into the ragged arm
    crept = _prab_block(pallas_ragged=_prab_arm(24096, 4096))
    assert any("ZERO HBM halo-table" in e for e in check_pallas_ragged_ab(
        {"pallas_ragged_ab_8dev": crept}))
    # the a2a arm's analytic model must book a positive figure
    broke = _prab_block(pallas_a2a=_prab_arm(28736, 0))
    assert any("analytic model broke" in e for e in check_pallas_ragged_ab(
        {"pallas_ragged_ab_8dev": broke}))
    # provenance + honest note
    unprov = _prab_block(ell_ragged=_prab_arm(24096, 0, measured=False))
    assert any("measured" in e for e in check_pallas_ragged_ab(
        {"pallas_ragged_ab_8dev": unprov}))
    assert any("honest-measurement" in e for e in check_pallas_ragged_ab(
        {"pallas_ragged_ab_8dev": _prab_block(timing="timings")}))
    assert any("missing" in e for e in check_pallas_ragged_ab(
        {"pallas_ragged_ab_8dev": {"timing": "never the claim"}}))


def _replica_cfg(true_total_rep=900, wire_step_rep=80.0, **over):
    c = {"epoch_s_noreplica": 0.2, "epoch_s_replica": 0.21,
         "replica_speedup": 0.95, "clean_pairs": 6, "steps": 49,
         "replica_rows": 64, "replica_send_saving": 500,
         "true_rows_per_exchange": 3000,
         "true_rows_per_exchange_replica": 2500,
         "wire_rows_per_exchange": 4000,
         "wire_rows_per_exchange_replica": 3600,
         "halo_bytes_true_total_noreplica": 1000,
         "halo_bytes_true_total_replica": true_total_rep,
         "wire_rows_per_step_noreplica": 100.0,
         "wire_rows_per_step_replica": wire_step_rep}
    c.update(over)
    return c


def _replica_block(**over):
    b = {"replica_budget": 64, "sync_every": 4,
         "random": _replica_cfg(),
         "hp": _replica_cfg(km1=3000, km1_blind=3010,
                            km1_cache_aware=2400,
                            km1_cache_blind_partition=2500),
         "note": "the wire/true-byte accounting is the asserted figure; "
                 "CPU-mesh epoch speed is not the claim"}
    b.update(over)
    return b


def test_validator_replica_ab_contract():
    """The hot-halo-replication block (PR-10): null needs a degradation
    marker; shrunken figures may never exceed the full ones; the hp arm
    must win STRICTLY on true bytes and wire rows/step; the cache-aware
    km1 must be <= the blind partition's cache objective; and the
    honest-measurement note is part of the contract."""
    from validate_bench import check_replica_ab

    assert any("replica_ab_degraded" in e for e in check_replica_ab(
        {"replica_ab_8dev": None}))
    assert not check_replica_ab(
        {"replica_ab_8dev": None, "replica_ab_degraded": "deadline"})
    assert not check_replica_ab({"replica_ab_8dev": _replica_block()})
    # a shrunken figure above the full one — a hand-edit tell
    grew = _replica_block()
    grew["random"]["true_rows_per_exchange_replica"] = 9999
    assert any("never grow" in e for e in check_replica_ab(
        {"replica_ab_8dev": grew}))
    # non-strict hp win on true bytes — acceptance violated
    tie = _replica_block()
    tie["hp"]["halo_bytes_true_total_replica"] = \
        tie["hp"]["halo_bytes_true_total_noreplica"]
    assert any("STRICTLY" in e for e in check_replica_ab(
        {"replica_ab_8dev": tie}))
    # cache-aware km1 above the blind partition's objective
    worse = _replica_block()
    worse["hp"]["km1_cache_aware"] = 2600
    assert any("km1_cache_aware" in e for e in check_replica_ab(
        {"replica_ab_8dev": worse}))
    # B must be positive and the note present
    assert any("replica_budget" in e for e in check_replica_ab(
        {"replica_ab_8dev": _replica_block(replica_budget=0)}))
    assert any("note" in e for e in check_replica_ab(
        {"replica_ab_8dev": _replica_block(note="timings only")}))


def _ctrl_arm(exposed, **over):
    a = {"epoch_s": 0.01, "steps": 60, "wire_rows_per_exchange": 12000,
         "exposed_comm_frac": 0.25, "exposed_wire_rows_per_step": exposed,
         "hidden_wire_rows_per_step": 9000.0}
    a.update(over)
    return a


def _ctrl_block(controller=12000.0, **over):
    b = {"n": 20000, "graph": "ba", "k": 8, "km1": 9000,
         "replica_budget": 1250, "sync_every": 4, "clean_pairs": 6,
         "arms": {
             "controller": _ctrl_arm(
                 controller, resolved_schedule="ragged",
                 replica_budget=900, sync_every_final=8, retunes=1),
             "a2a_exact": _ctrl_arm(64000.0, exposed_comm_frac=1.0),
             "ragged_exact": _ctrl_arm(50000.0, exposed_comm_frac=1.0),
             "ragged_stale": _ctrl_arm(12700.0),
             "replica_stale": _ctrl_arm(12700.0),
         },
         "note": "exposed wire rows per step is the asserted figure; "
                 "CPU-mesh epoch speed is not the claim"}
    b.update(over)
    return b


def test_validator_controller_ab_contract():
    """The adaptive-controller block (PR-12): null needs a degradation
    marker; the controller arm must be <= EVERY static arm on exposed
    wire rows/step and STRICTLY below at least one; all five arms must be
    present; the honest-measurement note is part of the contract — and
    the checker fails on a synthetic violation (the satellite's
    unit-test requirement)."""
    from validate_bench import check_controller_ab

    assert any("controller_ab_degraded" in e for e in check_controller_ab(
        {"controller_ab_8dev": None}))
    assert not check_controller_ab(
        {"controller_ab_8dev": None, "controller_ab_degraded": "deadline"})
    assert not check_controller_ab({"controller_ab_8dev": _ctrl_block()})
    # synthetic violation: controller above a static arm
    worse = _ctrl_block(controller=13000.0)
    errs = check_controller_ab({"controller_ab_8dev": worse})
    assert any("above static arm" in e for e in errs)
    # universal tie is not a win
    tie = _ctrl_block()
    for nm in tie["arms"]:
        tie["arms"][nm]["exposed_wire_rows_per_step"] = 500.0
    assert any("STRICTLY" in e for e in check_controller_ab(
        {"controller_ab_8dev": tie}))
    assert any("missing arm" in e for e in check_controller_ab(
        {"controller_ab_8dev": {"arms": {"controller": _ctrl_arm(1.0)}}}))
    assert any("note" in e for e in check_controller_ab(
        {"controller_ab_8dev": _ctrl_block(note="timings only")}))


def _serve_arm(wire, **over):
    a = {"achieved_qps": 48.0, "latency_p50_ms": 4.0, "latency_p99_ms": 11.0,
         "queries": 200, "compiles": 2, "buckets": [8, 16],
         "wire_rows_per_exchange": wire,
         "wire_rows_per_query": round(wire * 3 / 16, 3),
         "true_rows_per_exchange": min(400, wire)}
    a.update(over)
    return a


def _serve_block(**over):
    b = {"measured": True, "offered_qps": 50.0,
         "arms": {"a2a": _serve_arm(1000), "ragged": _serve_arm(600)},
         "note": "CPU-mesh latency is not the claim; the wire-row "
                 "accounting is the asserted figure"}
    b.update(over)
    return b


def test_validator_serve_qps_contract():
    """The serving-bench block (PR-8): null needs a degradation marker;
    latency claims need measured:true provenance; a runtime recompile
    (compiles > buckets) violates the bucket contract; the ragged arm must
    win the wire-row accounting STRICTLY; the honest-measurement note is
    required."""
    assert any("serve_qps_degraded" in e for e in check_serve_qps(
        {"serve_qps_8dev": None}))
    assert not check_serve_qps({"serve_qps_8dev": None,
                                "serve_qps_degraded": "deadline"})
    assert not check_serve_qps({"serve_qps_8dev": _serve_block()})
    errs = check_serve_qps({"serve_qps_8dev": _serve_block(measured=False)})
    assert any("measured:true" in e for e in errs)
    bad_q = _serve_block()
    bad_q["arms"]["ragged"] = _serve_arm(600, latency_p50_ms=20.0)
    assert any("quantiles" in e for e in check_serve_qps(
        {"serve_qps_8dev": bad_q}))
    bad_c = _serve_block()
    bad_c["arms"]["a2a"] = _serve_arm(1000, compiles=5)
    assert any("recompile" in e for e in check_serve_qps(
        {"serve_qps_8dev": bad_c}))
    bad_w = _serve_block()
    bad_w["arms"]["ragged"] = _serve_arm(1000)
    assert any("STRICTLY" in e for e in check_serve_qps(
        {"serve_qps_8dev": bad_w}))
    no_note = _serve_block(note="fast")
    assert any("note" in e for e in check_serve_qps(
        {"serve_qps_8dev": no_note}))
    assert any("missing arm" in e for e in check_serve_qps(
        {"serve_qps_8dev": _serve_block(arms={"a2a": _serve_arm(10)})}))
    # the block rides check_bench_record like the other A/B families
    rec = {"n": 1, "cmd": "x", "rc": 0, "tail": "",
           "parsed": {"metric": "serve_qps_ab", "value": None,
                      "degraded": "no mesh",
                      "serve_qps_8dev": None}}
    assert any("serve_qps_degraded" in e for e in check_bench_record(rec))


def _subgraph_arm(rows, flops, **over):
    a = {"achieved_qps": 40.0, "latency_p50_ms": 5.0, "latency_p99_ms": 20.0,
         "queries": 200, "compiles": 6,
         "rows_per_query": rows, "flops_per_query": flops,
         "wire_rows_per_query": 1.0}
    a.update(over)
    return a


def _subgraph_block(**over):
    b = {"measured": True,
         "arms": {"full": _subgraph_arm(4000.0, 3.6e6),
                  "subgraph": _subgraph_arm(100.0, 1.5e5)},
         "analytic": {"chunking": "fixed max_batch=16",
                      "full_rows_per_query": 4000.0,
                      "full_flops_per_query": 3.6e6,
                      "subgraph_rows_per_query": 100.0,
                      "subgraph_flops_per_query": 1.5e5,
                      "wire_rows_per_query": 1.0},
         "rows_per_query_cut": 40.0,
         "flops_per_query_cut": 24.0,
         "note": "the asserted figures are the ANALYTIC per-query gauges; "
                 "CPU-mesh latency is not the cross-arm claim"}
    b.update(over)
    return b


def test_validator_serve_subgraph_contract():
    """The sub-graph serving A/B block (PR-14): null needs a degradation
    marker; latency claims need measured:true; both analytic per-query
    cuts must be ≥10× AND derivable from their own arms; the honest note
    must name the ANALYTIC gauges."""
    from validate_bench import check_serve_subgraph_ab

    assert any("serve_subgraph_degraded" in e for e in
               check_serve_subgraph_ab({"serve_subgraph_ab_8dev": None}))
    assert not check_serve_subgraph_ab(
        {"serve_subgraph_ab_8dev": None,
         "serve_subgraph_degraded": "deadline"})
    assert not check_serve_subgraph_ab(
        {"serve_subgraph_ab_8dev": _subgraph_block()})
    errs = check_serve_subgraph_ab(
        {"serve_subgraph_ab_8dev": _subgraph_block(measured=False)})
    assert any("measured:true" in e for e in errs)
    # a cut below the acceptance floor fails
    weak = _subgraph_block(rows_per_query_cut=4.0)
    weak["analytic"]["subgraph_rows_per_query"] = 1000.0
    assert any(">=10x" in e for e in check_serve_subgraph_ab(
        {"serve_subgraph_ab_8dev": weak}))
    # a summary cut that disagrees with the deterministic analytic block
    # is a hand-edit tell
    lied = _subgraph_block(flops_per_query_cut=50.0)
    assert any("derivable" in e for e in check_serve_subgraph_ab(
        {"serve_subgraph_ab_8dev": lied}))
    # the asserted cuts must come from the DETERMINISTIC block, not the
    # real-clock arms
    no_det = _subgraph_block()
    del no_det["analytic"]
    assert any("analytic" in e for e in check_serve_subgraph_ab(
        {"serve_subgraph_ab_8dev": no_det}))
    assert any("missing arm" in e for e in check_serve_subgraph_ab(
        {"serve_subgraph_ab_8dev": _subgraph_block(
            arms={"full": _subgraph_arm(1.0, 1.0)})}))
    assert any("note" in e for e in check_serve_subgraph_ab(
        {"serve_subgraph_ab_8dev": _subgraph_block(note="fast")}))
    # the block rides check_bench_record like the other A/B families
    rec = {"n": 1, "cmd": "x", "rc": 0, "tail": "",
           "parsed": {"metric": "serve_subgraph_ab", "value": None,
                      "degraded": "no mesh",
                      "serve_subgraph_ab_8dev": None}}
    assert any("serve_subgraph_degraded" in e
               for e in check_bench_record(rec))


def test_validator_rejects_unresolved_comm_schedule():
    rec = {"n": 1, "cmd": "x", "rc": 0, "tail": "",
           "parsed": {"metric": "m", "value": 1.0, "unit": "s",
                      "comm_schedule": "auto"}}
    assert any("resolved schedule" in e for e in check_bench_record(rec))
    rec["parsed"]["comm_schedule"] = "ragged"
    assert not check_bench_record(rec)


def test_validator_rejects_nonstandard_json(tmp_path):
    d = tmp_path
    (d / "bench_artifacts").mkdir()
    (d / "BENCH_r01.json").write_text(
        '{"n": 1, "cmd": "x", "rc": 0, "tail": "", '
        '"parsed": {"metric": "m", "value": NaN}}')
    problems = validate_tree(str(d))
    assert any("unparseable" in p and "NaN" in p for p in problems)


def test_validator_cli_exit_codes(tmp_path):
    import subprocess

    script = os.path.join(REPO, "scripts", "validate_bench.py")
    r = subprocess.run([sys.executable, script, str(REPO)],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    bad = tmp_path
    (bad / "MULTICHIP_r99.json").write_text(
        json.dumps({"n_devices": 8, "ok": False, "rc": 0}))
    r = subprocess.run([sys.executable, script, str(bad)],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "violation" in r.stdout


def _clean_analysis_report(n_modes=48):
    modes = {
        f"train/gcn/a2a/s0/m{i}": {
            "ok": True,
            "programs": {"step": {"ok": True, "violations": [],
                                  "census": {"all_to_all": 3}}},
        } for i in range(n_modes)
    }
    return {
        "schema": "sgcn_analysis_report", "v": 1, "fast": False,
        "ok": True,
        "hlo": {"modes": modes, "n_modes": n_modes, "ok": True},
        "ast": {"rules": {"traced-host-free": {"ok": True,
                                               "violations": []}},
                "ok": True},
    }


def test_validator_accepts_clean_analysis_report():
    from validate_bench import check_analysis_report

    assert not check_analysis_report(_clean_analysis_report())


def test_validator_rejects_red_or_fast_analysis_report():
    from validate_bench import check_analysis_report

    rec = _clean_analysis_report()
    rec["ok"] = False
    assert any("red report" in e for e in check_analysis_report(rec))
    rec = _clean_analysis_report()
    rec["fast"] = True
    assert any("FULL-matrix" in e for e in check_analysis_report(rec))


def test_validator_rejects_inconsistent_analysis_report():
    """The hand-edit tells: an ok flag contradicting its own violation
    list, a shrunk matrix, an n_modes count that disagrees with the
    entries."""
    from validate_bench import check_analysis_report

    rec = _clean_analysis_report()
    mid = next(iter(rec["hlo"]["modes"]))
    rec["hlo"]["modes"][mid]["programs"]["step"]["violations"] = [
        {"rule": "wire-dtype", "detail": "seeded"}]
    assert any("contradicts" in e for e in check_analysis_report(rec))

    rec = _clean_analysis_report(n_modes=5)
    assert any("floor" in e for e in check_analysis_report(rec))

    rec = _clean_analysis_report()
    rec["hlo"]["n_modes"] = 999
    assert any("inconsistent" in e for e in check_analysis_report(rec))

    rec = _clean_analysis_report()
    rec["ast"]["rules"]["traced-host-free"]["ok"] = False
    assert any("ast.rules" in e for e in check_analysis_report(rec))


def test_validator_rejects_hand_flipped_top_level_ok():
    """The one-line hand-edit: a mode entry is red (ok:false WITH recorded
    violations — internally consistent) but the top-level ok/hlo.ok were
    flipped green.  Green-only must hold per entry."""
    from validate_bench import check_analysis_report

    rec = _clean_analysis_report()
    mid = next(iter(rec["hlo"]["modes"]))
    entry = rec["hlo"]["modes"][mid]
    entry["ok"] = False
    entry["programs"]["step"]["ok"] = False
    entry["programs"]["step"]["violations"] = [
        {"rule": "wire-dtype", "detail": "f32 wire under bf16"}]
    assert any("green in every mode" in e
               for e in check_analysis_report(rec))
    rec["ast"]["rules"]["traced-host-free"] = {
        "ok": False, "violations": ["x"]}
    assert any("green in every rule" in e
               for e in check_analysis_report(rec))


def test_memory_provenance_rule():
    """ISSUE 18: a numeric ``*_bytes`` claim anywhere in a bench block
    needs ``analytic: true`` or ``measured: true`` provenance — its own
    dict's or inherited from an enclosing block; the flag-integrity half
    (a present-but-untrue ``analytic``) fires in ANY round."""
    from validate_bench import (MEMORY_PROVENANCE_SINCE,
                                check_memory_provenance)

    def rec(block, rc=0):
        return {"n": 1, "cmd": "x", "rc": rc, "tail": "",
                "parsed": {"metric": "m", "value": 0.1, "unit": "s",
                           "measured": True, "memory_footprint_8dev": block}}

    naked = rec({"modes": {"train_gcn_a2a": {"model_bytes": 1000}}})
    errs = check_memory_provenance(naked, MEMORY_PROVENANCE_SINCE)
    assert any("model_bytes" in e and "provenance" in e for e in errs)
    # rounds before the gate (and failed rounds) are grandfathered
    assert not check_memory_provenance(
        naked, MEMORY_PROVENANCE_SINCE - 1)
    assert not check_memory_provenance(
        rec({"modes": {"m": {"model_bytes": 1}}}, rc=1),
        MEMORY_PROVENANCE_SINCE)
    # the flag on the claiming dict itself satisfies the rule...
    assert not check_memory_provenance(
        rec({"modes": {"m": {"analytic": True, "model_bytes": 1}}}), 9)
    # ...and so does an ANCESTOR block's flag (bench.py stamps both)
    assert not check_memory_provenance(
        rec({"analytic": True,
             "modes": {"m": {"model_bytes": 1, "params_bytes": 2}}}), 9)
    # measured: true (XLA memory_analysis) is the other accepted provenance
    assert not check_memory_provenance(
        rec({"modes": {"m": {"measured": True, "peak_bytes": 1}}}), 9)
    # a present-but-untrue analytic flag lies about plan-derivation —
    # violation at ANY round, even grandfathered/failed ones
    for lying_round, rc in ((1, 0), (9, 1)):
        errs = check_memory_provenance(
            rec({"modes": {"m": {"analytic": "yes"}}}, rc=rc), lying_round)
        assert any("analytic=" in e for e in errs), (lying_round, rc)
    # non-record shapes and byte-free blocks stay silent
    assert not check_memory_provenance({"rc": 0}, 9)
    assert not check_memory_provenance(rec({"modes": {"m": {"x": 1}}}), 9)


def test_bench_memory_block_carries_analytic_flag():
    """bench.py's memory_footprint_8dev emission stamps analytic: True at
    the block AND per-mode level (string-level pin, like the measured
    flag's) — the provenance rule above would reject the block without
    them from MEMORY_PROVENANCE_SINCE on."""
    with open(os.path.join(REPO, "bench.py")) as fh:
        src = fh.read()
    assert '"analytic": True' in src
    assert "memory_footprint_8dev" in src
