"""Plan-contract lint (tier-1): the field-name tuples that ship plan arrays
to devices must stay in sync with the ``CommPlan`` dataclass itself.

The PR-2 shard-proxy incident class: a new per-chip plan field that is not
classified in ``PER_CHIP_ARRAY_FIELDS`` mis-slices (or loudly fails) under
``shard_proxy_plan``, and a consumer tuple naming a field that no longer
exists only explodes at trainer-construction time deep in a run.  This lint
fails the commit that introduces either skew — including for the ragged
exchange fields, covered from day one.

The registry of consumer tuples lives in ``sgcn_tpu.analysis.registry``
(PR-9 consolidation): this test validates its entries against the
dataclass and the shard proxy, and the AST hygiene pass
(``analysis.ast_rules``) fails any NEW ``*_FIELDS*`` tuple that is not
registered there — so a tuple cannot exist outside this lint's sight.
"""

import dataclasses

import numpy as np

from sgcn_tpu.analysis.registry import resolve_consumer_tuples

_REGISTRY = resolve_consumer_tuples()
from sgcn_tpu.io.datasets import er_graph
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.parallel.plan import (_GLOBAL_ARRAY_FIELDS,
                                    PER_CHIP_ARRAY_FIELDS, CommPlan)
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.prep import normalize_adjacency

SERVE_ROUTER_FIELDS = _REGISTRY["SERVE_ROUTER_FIELDS"]
GAT_PLAN_FIELDS_RAGGED = _REGISTRY["GAT_PLAN_FIELDS_RAGGED"]
STALE_PLAN_FIELDS_RAGGED = _REGISTRY["STALE_PLAN_FIELDS_RAGGED"]
GCN_PLAN_FIELDS_RAGGED = _REGISTRY["GCN_PLAN_FIELDS_RAGGED"]

# the registry's consumer tuples plus the two classification tuples —
# everything below validates THESE entries (one dict, one home)
CONSUMER_TUPLES = {
    "PER_CHIP_ARRAY_FIELDS": PER_CHIP_ARRAY_FIELDS,
    "_GLOBAL_ARRAY_FIELDS": _GLOBAL_ARRAY_FIELDS,
    **_REGISTRY,
}


def _full_plan():
    """A k=4 plan with EVERY lazy layout built (cell, pallas tiles, ragged,
    replicas), n ≠ k so a shape coincidence cannot mask a
    misclassification."""
    n, k = 200, 4
    ahat = normalize_adjacency(er_graph(n, 6, seed=0))
    pv = balanced_random_partition(n, k, seed=1)
    plan = build_comm_plan(ahat, pv, k)
    plan.ensure_cell()
    plan.ensure_pallas_tiles(tb=64)
    plan.ensure_ragged()
    plan.ensure_pallas_ragged_tiles()
    plan.ensure_pallas_cell_tiles(tb=64)
    plan.ensure_pallas_cell_ragged_tiles()
    plan.ensure_replicas(12)
    return plan


def test_every_tuple_names_real_dataclass_fields():
    names = {f.name for f in dataclasses.fields(CommPlan)}
    for tup_name, tup in CONSUMER_TUPLES.items():
        unknown = [f for f in tup if f not in names]
        assert not unknown, (
            f"{tup_name} names non-existent CommPlan fields {unknown} — "
            "the tuple and the dataclass have drifted apart")


def test_every_array_field_is_classified():
    """Every ndarray field of a fully-built plan is either per-chip-stacked
    (classified + leading k axis) or global — nothing unclassified, nothing
    misclassified."""
    plan = _full_plan()
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        if not isinstance(v, np.ndarray):
            continue
        if f.name in PER_CHIP_ARRAY_FIELDS:
            assert v.shape[0] == plan.k, (
                f"CommPlan.{f.name} is classified per-chip but has shape "
                f"{v.shape} (k={plan.k})")
        elif f.name in _GLOBAL_ARRAY_FIELDS:
            continue
        else:
            raise AssertionError(
                f"CommPlan.{f.name} is an ndarray field classified in "
                "NEITHER PER_CHIP_ARRAY_FIELDS nor _GLOBAL_ARRAY_FIELDS — "
                "the shard proxy cannot know how to slice it")


def test_shipped_field_tuples_are_sliceable():
    """Every field a model forward ships must survive the shard proxy: the
    arrays the trainers put on devices are exactly the ones the proxy must
    slice per chip."""
    from sgcn_tpu.parallel.proxy import shard_proxy_plan

    plan = _full_plan()
    proxy = shard_proxy_plan(plan, chip=1)      # raises on any drift
    for tup_name in ("PALLAS_PLAN_FIELDS", "PALLAS_PLAN_FIELDS_RAGGED",
                     "GAT_PLAN_FIELDS", "GAT_PLAN_FIELDS_RAGGED",
                     "GAT_PLAN_FIELDS_PALLAS",
                     "GAT_PLAN_FIELDS_PALLAS_RAGGED",
                     "GCN_PLAN_FIELDS_SYM", "GCN_PLAN_FIELDS_GEN",
                     "GCN_PLAN_FIELDS_RAGGED", "STALE_PLAN_FIELDS_RAGGED"):
        for f in CONSUMER_TUPLES[tup_name]:
            v = getattr(plan, f)
            assert isinstance(v, np.ndarray), (
                f"{tup_name}: {f} not materialized on a fully-built plan")
            assert f in PER_CHIP_ARRAY_FIELDS, (
                f"{tup_name}: shipped field {f} is not per-chip-classified "
                "— shard_map would misshard it")
            assert getattr(proxy, f).shape == (1,) + v.shape[1:], f


def test_ragged_fields_covered_on_day_one():
    """The PR-4 fields specifically: classified, built by ensure_ragged,
    named by the ragged forward tuple."""
    ragged_arrays = ("rsend_idx", "rhalo_dst", "redge_dst", "redge_src",
                     "redge_w")
    for f in ragged_arrays:
        assert f in PER_CHIP_ARRAY_FIELDS, f
    plan = _full_plan()
    for f in ragged_arrays:
        assert isinstance(getattr(plan, f), np.ndarray), f
    assert isinstance(plan.rr_sizes, tuple)
    assert isinstance(plan.rr_edge_sizes, tuple)
    assert set(GCN_PLAN_FIELDS_RAGGED) <= set(PER_CHIP_ARRAY_FIELDS)
    # the PR-5 GAT-ragged tuple rides the SAME ensure_ragged arrays — no
    # new dataclass fields, but the consumer tuple is covered day one
    assert set(GAT_PLAN_FIELDS_RAGGED) <= set(PER_CHIP_ARRAY_FIELDS)
    assert {"rsend_idx", "rhalo_dst"} <= set(GAT_PLAN_FIELDS_RAGGED)
    # the PR-6 composed stale × ragged tuple too: same ring arrays (the
    # round-structured carries replace send_idx/halo_src — receives live
    # in the carry, the fold rides redge_*), covered day one
    assert set(STALE_PLAN_FIELDS_RAGGED) <= set(PER_CHIP_ARRAY_FIELDS)
    assert {"rsend_idx", "redge_dst"} <= set(STALE_PLAN_FIELDS_RAGGED)
    assert not {"send_idx", "halo_src"} & set(STALE_PLAN_FIELDS_RAGGED)


def test_serve_fields_covered_on_day_one():
    """The PR-8 serve subsystem under the same static gates: the router's
    fields are GLOBAL vertex-indexed (never per-chip — routing runs on the
    host over the full square plan), and the engine ships ONLY the model
    tuples already under contract (`resolve_forward_setup` returns them),
    so a new forward field cannot bypass this lint via the serving path."""
    from sgcn_tpu.train.fullbatch import resolve_forward_setup

    for f in SERVE_ROUTER_FIELDS:
        assert f in _GLOBAL_ARRAY_FIELDS, (
            f"SERVE_ROUTER_FIELDS names {f}, which is not classified "
            "global — the router would mis-read a per-chip-stacked array")
        assert f not in PER_CHIP_ARRAY_FIELDS, f
    covered = {tuple(sorted(t)) for n, t in CONSUMER_TUPLES.items()
               if n not in ("PER_CHIP_ARRAY_FIELDS", "_GLOBAL_ARRAY_FIELDS",
                            "SERVE_ROUTER_FIELDS")}
    plan = _full_plan()
    for model in ("gcn", "gat"):
        for sched in ("a2a", "ragged"):
            setup = resolve_forward_setup(plan, fin=16, widths=[16, 4],
                                          model=model, comm_schedule=sched)
            assert tuple(sorted(setup.plan_fields)) in covered, (
                f"serve/{model}/{sched} ships {setup.plan_fields}, which "
                "is not one of the contract tuples above")
