"""Distributed-vs-single-device training parity — the automated form of the
reference's accuracy-parity experiment (GPU/PGCN-Accuracy.py, README.md:110)
with the dense oracle in the DGL/gcn.py role."""

import numpy as np
import pytest

from sgcn_tpu.baselines import DenseOracle
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.train import FullBatchTrainer, make_train_data


def _dataset(ahat, f=6, c=3, seed=9):
    n = ahat.shape[0]
    rng = np.random.default_rng(seed)
    feats = rng.standard_normal((n, f)).astype(np.float32)
    labels = rng.integers(0, c, size=n).astype(np.int32)
    return feats, labels


@pytest.mark.parametrize("k", [2, 4])
def test_loss_parity_with_oracle(ahat, k):
    n = ahat.shape[0]
    feats, labels = _dataset(ahat)
    widths = [8, 3]
    pv = balanced_random_partition(n, k, seed=21)
    plan = build_comm_plan(ahat, pv, k)
    trainer = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths, seed=42)
    data = make_train_data(plan, feats, labels)
    oracle = DenseOracle(ahat, fin=feats.shape[1], widths=widths, seed=42)

    dist_losses = [trainer.step(data) for _ in range(6)]
    oracle_losses = oracle.fit(feats, labels, epochs=6)
    np.testing.assert_allclose(dist_losses, oracle_losses, rtol=2e-4, atol=1e-5)

    got = trainer.predict(data)
    expected = oracle.predict(feats)
    np.testing.assert_allclose(got, expected, rtol=2e-3, atol=2e-4)


def test_eval_and_accuracy(ahat):
    n = ahat.shape[0]
    feats, labels = _dataset(ahat)
    pv = balanced_random_partition(n, 4, seed=22)
    plan = build_comm_plan(ahat, pv, 4)
    trainer = FullBatchTrainer(plan, fin=feats.shape[1], widths=[8, 3], seed=1)
    mask = (np.arange(n) % 2 == 0).astype(np.float32)   # train/eval split
    data = make_train_data(plan, feats, labels, train_mask=mask,
                           eval_mask=1.0 - mask)
    for _ in range(3):
        trainer.step(data)
    loss, acc = trainer.evaluate(data)
    assert np.isfinite(loss)
    assert 0.0 <= acc <= 1.0


def test_fit_reports_reference_stats(ahat):
    n = ahat.shape[0]
    feats, labels = _dataset(ahat)
    pv = balanced_random_partition(n, 4, seed=23)
    plan = build_comm_plan(ahat, pv, 4)
    trainer = FullBatchTrainer(plan, fin=feats.shape[1], widths=[8, 3])
    data = make_train_data(plan, feats, labels)
    report = trainer.fit(data, epochs=2, warmup=1, verbose=False)
    # 3 steps × 2 layers × fwd+bwd exchanges
    assert trainer.stats.exchanges == 3 * 2 * 2
    expected_vol = plan.predicted_send_volume.sum() * trainer.stats.exchanges
    assert report["total_send_volume"] == expected_vol
    assert report["epochs"] == 2 and report["epoch_s"] > 0
    assert len(report["loss_history"]) == 2
    # loss should be decreasing on this easy overfit task
    assert report["loss_history"][-1] < report["loss_history"][0] * 1.5


def test_wide_input_project_first_parity(ahat):
    """Width-aware layer scheduling (project-then-aggregate for wide inputs)
    must match the oracle's fixed aggregate-first order — same math."""
    import numpy as np
    from sgcn_tpu.baselines import DenseOracle
    from sgcn_tpu.parallel import build_comm_plan
    from sgcn_tpu.partition import balanced_random_partition
    from sgcn_tpu.train import FullBatchTrainer, make_train_data
    from sgcn_tpu.models.gcn import PROJECT_FIRST_MIN_FIN

    n = ahat.shape[0]
    fin = PROJECT_FIRST_MIN_FIN + 44     # forces the project-first branch
    rng = np.random.default_rng(11)
    feats = rng.standard_normal((n, fin)).astype(np.float32)
    labels = rng.integers(0, 3, n).astype(np.int32)
    pv = balanced_random_partition(n, 4, seed=6)
    plan = build_comm_plan(ahat, pv, 4)
    tr = FullBatchTrainer(plan, fin=fin, widths=[8, 3], seed=3)
    oracle = DenseOracle(ahat, fin=fin, widths=[8, 3], seed=3)
    data = make_train_data(plan, feats, labels)
    np.testing.assert_allclose(tr.predict(data), oracle.predict(feats),
                               rtol=2e-3, atol=2e-4)
    dist = [tr.step(data) for _ in range(4)]
    orac = oracle.fit(feats, labels, epochs=4)
    np.testing.assert_allclose(dist, orac, rtol=2e-3, atol=2e-4)


def test_bf16_compute_tracks_f32(ahat):
    """Mixed-precision option: same trajectory within bf16 tolerance."""
    import numpy as np
    from sgcn_tpu.parallel import build_comm_plan
    from sgcn_tpu.partition import balanced_random_partition
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    n = ahat.shape[0]
    rng = np.random.default_rng(4)
    feats = rng.standard_normal((n, 12)).astype(np.float32)
    labels = rng.integers(0, 3, n).astype(np.int32)
    pv = balanced_random_partition(n, 4, seed=1)
    plan = build_comm_plan(ahat, pv, 4)
    data = make_train_data(plan, feats, labels)
    f32 = FullBatchTrainer(plan, fin=12, widths=[8, 3], seed=2)
    b16 = FullBatchTrainer(plan, fin=12, widths=[8, 3], seed=2,
                           compute_dtype="bfloat16")
    l32 = [f32.step(data) for _ in range(5)]
    l16 = [b16.step(data) for _ in range(5)]
    np.testing.assert_allclose(l16, l32, rtol=0.05, atol=0.02)
    assert l16[-1] < l16[0]


def test_run_epochs_matches_sequential_steps(ahat):
    """The on-device epoch loop (one dispatch, lax.fori_loop) must follow the
    exact trajectory of sequential step() calls — it exists purely to remove
    per-dispatch host latency from multi-epoch timing (bench protocol)."""
    n = ahat.shape[0]
    feats, labels = _dataset(ahat)
    pv = balanced_random_partition(n, 4, seed=13)
    plan = build_comm_plan(ahat, pv, 4)
    data = make_train_data(plan, feats, labels)
    seq = FullBatchTrainer(plan, fin=feats.shape[1], widths=[8, 3], seed=7)
    fused = FullBatchTrainer(plan, fin=feats.shape[1], widths=[8, 3], seed=7)
    seq_losses = [seq.step(data) for _ in range(5)]
    fused_losses = fused.run_epochs(data, 5)
    np.testing.assert_allclose(fused_losses, seq_losses, rtol=2e-5, atol=1e-6)
    # params identical afterward, and stats counted all 5 steps
    for a, b in zip(np.asarray(seq.params, dtype=object).ravel(),
                    np.asarray(fused.params, dtype=object).ravel()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    assert fused.stats.exchanges == seq.stats.exchanges


def test_remat_matches_plain(ahat):
    """jax.checkpoint rematerialization must not change the math."""
    import numpy as np
    from sgcn_tpu.parallel import build_comm_plan
    from sgcn_tpu.partition import balanced_random_partition
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    n = ahat.shape[0]
    rng = np.random.default_rng(8)
    feats = rng.standard_normal((n, 10)).astype(np.float32)
    labels = rng.integers(0, 3, n).astype(np.int32)
    pv = balanced_random_partition(n, 4, seed=2)
    plan = build_comm_plan(ahat, pv, 4)
    data = make_train_data(plan, feats, labels)
    plain = FullBatchTrainer(plan, fin=10, widths=[8, 8, 3], seed=4)
    rem = FullBatchTrainer(plan, fin=10, widths=[8, 8, 3], seed=4, remat=True)
    lp = [plain.step(data) for _ in range(4)]
    lr = [rem.step(data) for _ in range(4)]
    np.testing.assert_allclose(lr, lp, rtol=1e-5, atol=1e-6)
