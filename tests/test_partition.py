"""Native partitioner invariants: validity, balance, beats-random quality
(SURVEY.md §7.3: accept any partition beating random by the expected margin),
and the L2 file-family round trip."""

import numpy as np
import pytest
import scipy.sparse as sp

from sgcn_tpu.io.config import ModelConfig
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import (
    balanced_random_partition, partition_graph, partition_hypergraph_colnet,
    read_buff, read_conn, read_partvec, read_partvec_pickle, write_partvec,
    write_partvec_pickle, write_rank_files,
)


def community_graph(n=600, c=6, seed=0):
    """Planted-community graph: partitioners should find the communities."""
    rng = np.random.default_rng(seed)
    comm = rng.integers(0, c, n)
    rows, cols = [], []
    members = [np.where(comm == ci)[0] for ci in range(c)]
    for _ in range(n * 6):
        i = int(rng.integers(0, n))
        if rng.random() < 0.9:
            m = members[comm[i]]
            j = int(m[rng.integers(0, len(m))])
        else:
            j = int(rng.integers(0, n))
        if i != j:
            rows.append(i)
            cols.append(j)
    a = sp.coo_matrix((np.ones(len(rows), np.float32), (rows, cols)), shape=(n, n))
    return sp.csr_matrix(((a + a.T) > 0).astype(np.float32))


def _cut(a, pv):
    coo = a.tocoo()
    return int((pv[coo.row] != pv[coo.col]).sum()) // 2


def _km1(a, pv):
    """Standard connectivity-1: Σ over columns (nets) of (#parts among the
    column's pin rows − 1). Equals halo send volume when every vertex's own
    column has a diagonal nonzero (i.e. after self-loop normalization)."""
    coo = a.tocoo()
    total = 0
    for v in range(a.shape[0]):
        rows = coo.row[coo.col == v]
        if len(rows):
            total += len(np.unique(pv[rows])) - 1
    return total


@pytest.fixture(scope="module")
def cgraph():
    return community_graph()


@pytest.mark.parametrize("k", [2, 4, 8])
def test_graph_partitioner(cgraph, k):
    n = cgraph.shape[0]
    pv, cut = partition_graph(cgraph, k, imbalance=0.05, seed=1)
    assert pv.shape == (n,) and pv.min() >= 0 and pv.max() < k
    sizes = np.bincount(pv, minlength=k)
    assert sizes.max() <= (1.05 * n / k) + 1
    assert cut == _cut(cgraph, pv)              # self-reported metric is honest
    rand_cut = _cut(cgraph, balanced_random_partition(n, k, seed=9))
    assert cut < 0.6 * rand_cut                 # beats random by a wide margin


@pytest.mark.parametrize("k", [2, 4, 8])
def test_hypergraph_partitioner(cgraph, k):
    n = cgraph.shape[0]
    pv, km1 = partition_hypergraph_colnet(cgraph, k, imbalance=0.05, seed=1)
    assert pv.shape == (n,) and pv.min() >= 0 and pv.max() < k
    assert km1 == _km1(cgraph, pv)   # self-reported metric is honest
    rand = _km1(cgraph, balanced_random_partition(n, k, seed=9))
    assert km1 < 0.6 * rand
    # balance is on cell weight = row nnz
    w = np.asarray(cgraph.sum(axis=1)).ravel()
    pw = np.bincount(pv, weights=w, minlength=k)
    assert pw.max() <= 1.06 * w.sum() / k + w.max()


def test_hp_beats_gp_on_volume(cgraph):
    """The paper's claim: connectivity-objective partitioning gives lower comm
    volume than edge-cut partitioning.  The two solve different balance
    constraints, mirroring the reference exactly: hp balances cells weighted
    by row nnz (PaToH, ``GCN-HP/main.cpp:298-301``), gp balances unit vertex
    counts (METIS default, ``GCN-GP/main.cpp:334``) — so on instances where
    the nnz cap binds, gp may squeeze out a lower volume by exceeding the
    nnz balance hp must honor (observed: k=6 here, gp nnz-imbalance 1.13 vs
    hp's 1.03 cap).  The bar: hp within 5% everywhere, strictly better on
    the majority of k, and never worse-balanced on nnz."""
    wins = 0
    w = np.asarray(cgraph.sum(axis=1)).ravel()
    for k in (4, 6, 8):
        pv_g, _ = partition_graph(cgraph, k, seed=1)
        pv_h, _ = partition_hypergraph_colnet(cgraph, k, seed=1)
        vol_g = build_comm_plan(cgraph, pv_g, k).predicted_send_volume.sum()
        vol_h = build_comm_plan(cgraph, pv_h, k).predicted_send_volume.sum()
        assert vol_h <= 1.05 * vol_g, (k, vol_h, vol_g)
        wins += vol_h <= vol_g
        bal_g = np.bincount(pv_g, weights=w, minlength=k).max()
        bal_h = np.bincount(pv_h, weights=w, minlength=k).max()
        assert bal_h <= bal_g * 1.001, (k, bal_h, bal_g)
    assert wins >= 2, wins


def test_partvec_roundtrip(tmp_path):
    pv = np.array([0, 1, 2, 1, 0], dtype=np.int64)
    p1 = str(tmp_path / "pv.txt")
    p2 = str(tmp_path / "pv.pkl")
    write_partvec(p1, pv)
    write_partvec_pickle(p2, pv)
    np.testing.assert_array_equal(read_partvec(p1), pv)
    np.testing.assert_array_equal(read_partvec_pickle(p2), pv)


def test_rank_files_consistent_with_plan(tmp_path, ahat):
    """conn/buff files must agree with the runtime comm plan (the reference's
    offline conn.r/buff.r are consumed by the trainer at startup —
    Parallel-GCN/main.c:456-551)."""
    n = ahat.shape[0]
    k = 4
    pv = balanced_random_partition(n, k, seed=3)
    y = sp.csr_matrix((np.ones(n, np.float32),
                       (np.arange(n), np.arange(n) % 3)), shape=(n, 3))
    cfg = ModelConfig(nlayers=2, nvtx=n, widths=[8, 3])
    write_rank_files(str(tmp_path), ahat, y, pv, k, cfg)
    plan = build_comm_plan(ahat, pv, k)
    for r in range(k):
        conn = read_conn(str(tmp_path / f"conn.{r}"))
        buff = read_buff(str(tmp_path / f"buff.{r}"))
        for q, gids in conn.items():
            assert len(gids) == plan.send_counts[r, q]
            assert (pv[gids] == r).all()        # we only send rows we own
        for q, cnt in buff.items():
            assert cnt == plan.send_counts[q, r]
        # A.r holds exactly the rows owned by r
        with open(tmp_path / f"A.{r}") as f:
            hdr = f.readline().split()
            assert int(hdr[0]) == n
            rows = {int(line.split()[0]) for line in f}
        assert rows.issubset(set(np.where(pv == r)[0]))


def test_partitioners_beat_random_on_community_graph():
    """On a community-structured graph the multilevel partitioners must cut
    far less than random — the quality margin SURVEY.md §7.3 requires."""
    from sgcn_tpu.io.datasets import planted_partition
    from sgcn_tpu.parallel import build_comm_plan
    from sgcn_tpu.prep import normalize_adjacency

    a, _, _ = planted_partition(n=240, nclasses=4, p_in=0.3, p_out=0.01,
                                seed=5)
    ahat = normalize_adjacency(a)
    k = 4
    vols = {}
    for name, pv in (
        ("hp", partition_hypergraph_colnet(ahat, k, seed=1)[0]),
        ("gp", partition_graph(ahat, k, seed=1)[0]),
        ("rp", balanced_random_partition(240, k, seed=1)),
    ):
        vols[name] = int(build_comm_plan(ahat, pv, k)
                         .predicted_send_volume.sum())
    # random sends nearly everything; the real partitioners should find the
    # planted communities and cut at most half of random's volume
    assert vols["hp"] < 0.5 * vols["rp"], vols
    assert vols["gp"] < 0.5 * vols["rp"], vols


def test_recursive_bisection_path(monkeypatch):
    """SGCN_HP_RB=1 routes power-of-two k through recursive bisection
    (native partition_hypergraph_rb): complete assignment, balanced parts,
    correct self-reported km1, and quality >= the direct driver's ballpark
    (r5: at k >= 32 RB measured 12% BETTER at products scale)."""
    from sgcn_tpu.io.datasets import dcsbm_graph
    from sgcn_tpu.prep import normalize_adjacency

    ahat = normalize_adjacency(
        dcsbm_graph(4000, ncomm=8, avg_deg=12, seed=3)).tocsr()
    n, k = ahat.shape[0], 8

    def km1_of(pv):
        coo = ahat.tocoo()
        pairs = np.unique(coo.col.astype(np.int64) * k + pv[coo.row])
        return int(len(pairs) - len(np.unique(pairs // k)))

    monkeypatch.setenv("SGCN_HP_RB", "1")
    pv_rb, km1_rb = partition_hypergraph_colnet(ahat, k, seed=0)
    pv_rb = np.asarray(pv_rb)
    assert pv_rb.shape == (n,) and pv_rb.min() >= 0 and pv_rb.max() < k
    assert km1_of(pv_rb) == km1_rb
    cnt = np.bincount(pv_rb, minlength=k)
    assert cnt.max() / cnt.mean() < 1.3
    monkeypatch.setenv("SGCN_HP_RB", "0")
    _, km1_direct = partition_hypergraph_colnet(ahat, k, seed=0)
    assert km1_rb <= 1.15 * km1_direct, (km1_rb, km1_direct)
