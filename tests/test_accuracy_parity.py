"""The reference's correctness criterion: partitioned training must match
non-partitioned predictive performance (GPU/PGCN-Accuracy.py, README.md:110)."""

from sgcn_tpu.io.datasets import planted_partition as planted_graph
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train.accuracy import run_accuracy_parity, train_test_split_masks


def test_split_masks_disjoint():
    tr, te = train_test_split_masks(50, 0.6, seed=1)
    assert tr.sum() == 30 and te.sum() == 20
    assert (tr * te).sum() == 0


def test_accuracy_parity_full_and_minibatch():
    a, feats, labels = planted_graph()
    ahat = normalize_adjacency(a)
    n = a.shape[0]
    pv = balanced_random_partition(n, 4, seed=2)
    train, test = train_test_split_masks(n, 0.6, seed=3)
    res = run_accuracy_parity(
        ahat, feats, labels, pv, k=4, widths=[16, 3],
        train_mask=train, test_mask=test, epochs=30, lr=0.05,
        batch_size=48, seed=0)
    # the graph is learnable at all
    assert res["oracle_test_acc"] > 0.6
    # partitioned full-batch IS the same computation — tight parity
    assert abs(res["fullbatch_test_acc"] - res["oracle_test_acc"]) < 0.05
    # mini-batch sees subsampled neighborhoods — allow a wider band but it
    # must stay in the same quality regime (the reference's claim)
    assert res["minibatch_test_acc"] > res["oracle_test_acc"] - 0.15
