"""The adaptive communication controller PR (ISSUE 12): replica ×
staleness composition (``ops/pspmm.py::pspmm_replica_stale[_ragged]``),
drift-driven partial refresh (``--refresh-band``,
``pspmm_replica_partial``) and the runtime controller
(``train/controller.py``) — docs/comm_schedule.md, docs/replication.md.

Contract pinned here:

  * COMPOSED ``--replica-budget B --halo-staleness 1`` trains under BOTH
    transports, f32-BIT-identical to the exact no-replica path at
    ``--sync-every 1`` (losses AND parameters ``==``) — the sync program
    is exactly the stale mode's full-sync program;
  * the composed carry is the STALE carry (no replica_carry exists — the
    halo carry subsumes the replica tables), stale steps are booked
    hidden AND replica (shrunken wire) with the exposed/hidden wire-row
    split reconciling, and the fused ``run_epochs`` reproduces per-step
    ``step()``;
  * PARTIAL refresh ships only drifted rows, booked at the ACTUAL
    shipped counts with exact CommStats ↔ step-event ↔ roofline
    reconciliation; band semantics (0 → every drifted row, huge → none);
  * the controller's band-crossing ``sync_every`` retune is
    DETERMINISTIC in the injected gauge sequence, and the trainer applies
    + logs its decisions into the manifest ``comm_schedule`` block;
  * ``--replica-budget auto`` resolves at the λ·degree knee with the
    scoring inputs in the decision log;
  * MUTATION checks: the new composed audit-matrix modes fail the
    wire-shape rule on a seeded full-width stale-step exchange (both
    transports) — the shrunken-wire contract is not vacuous.
"""

import os

import numpy as np
import pytest

from sgcn_tpu.io.datasets import load_npz_dataset
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition.emit import read_partvec
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")

WIDTHS = [16, 7]
BUDGET = 24


@pytest.fixture(scope="module")
def cora():
    """The committed cora-format fixture + its 4-way hp partvec."""
    a, feats, labels = load_npz_dataset(os.path.join(FIX, "cora_like.npz"))
    ahat = normalize_adjacency(a)
    pv = read_partvec(os.path.join(FIX, "cora_like.4.hp"))
    plan = build_comm_plan(ahat, pv, 4)
    return plan, feats.astype(np.float32), labels.astype(np.int32)


@pytest.fixture(scope="module")
def exact_run(cora):
    """Exact no-replica no-staleness reference: 4 losses + trained
    parameters, shared by both transports' bit-identity assertions."""
    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=3)
    d = make_train_data(plan, feats, labels)
    losses = [tr.step(d) for _ in range(4)]
    return losses, [np.asarray(w) for w in tr.params]


# ------------------------------------------------------- composed mode
@pytest.mark.parametrize("schedule", ["a2a", "ragged"])
def test_composed_sync1_bit_identical_to_exact(cora, exact_run, schedule):
    """THE acceptance contract: ``--replica-budget B --halo-staleness 1
    --sync-every 1`` trains cora with losses and parameters exactly equal
    to the exact path's under both transports — every step runs the
    full-sync program, which is ``pspmm_stale``'s sync program verbatim."""
    plan, feats, labels = cora
    exact_losses, exact_params = exact_run
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=3,
                          comm_schedule=schedule, halo_staleness=1,
                          replica_budget=BUDGET, sync_every=1)
    assert tr.replica_budget == BUDGET
    assert not hasattr(tr, "replica_carry")     # the stale carry subsumes it
    d = make_train_data(plan, feats, labels)
    lc = [tr.step(d) for _ in range(4)]
    assert lc == exact_losses                   # bitwise, not allclose
    for wa, wb in zip(exact_params, tr.params):
        np.testing.assert_array_equal(wa, np.asarray(wb))


def test_composed_run_epochs_parity_and_booking(cora):
    """The fused multi-step path reproduces per-step ``step()`` exactly,
    and the booking marks stale steps hidden AND replica-shrunken with
    the subset-priced splits reconciling."""
    plan, feats, labels = cora
    d = make_train_data(plan, feats, labels)
    kw = dict(fin=feats.shape[1], widths=WIDTHS, seed=5,
              comm_schedule="ragged", halo_staleness=1,
              replica_budget=BUDGET, sync_every=3)
    ta = FullBatchTrainer(plan, **kw)
    la = [ta.step(d) for _ in range(5)]
    tb = FullBatchTrainer(plan, **kw)
    lb = tb.run_epochs(d, 5)
    np.testing.assert_array_equal(np.asarray(la, np.float32),
                                  np.asarray(lb, np.float32))
    for wa, wb in zip(ta.params, tb.params):
        np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    ra, rb = ta.stats.report(), tb.stats.report()
    assert ra == rb
    nl = len(WIDTHS)
    # steps 0 and 3 sync; 1, 2, 4 are stale+shrunken: hidden AND replica
    assert ra["hidden_exchanges"] == 2 * nl * 3
    assert ra["replica_exchanges"] == 2 * nl * 3
    assert ra["hidden_replica_exchanges"] == 2 * nl * 3
    assert (ra["exposed_send_volume"] + ra["hidden_send_volume"]
            == ra["total_send_volume"])
    assert (ra["exposed_wire_rows_total"] + ra["hidden_wire_rows_total"]
            == ra["wire_rows_total"])
    # hidden exchanges rode the SHRUNKEN ring; exposed ones the full ring
    full = plan.wire_rows_per_exchange("ragged")
    shrunk = plan.wire_rows_per_exchange("ragged", replica=True)
    assert shrunk < full
    assert ra["hidden_wire_rows_total"] == shrunk * 2 * nl * 3
    assert ra["exposed_wire_rows_total"] == full * 2 * nl * 2


def test_composed_carry_is_stale_shaped(cora):
    """The composed trainer's carry IS the stale carry — ring-envelope
    halos under ragged, dense (R, f) under a2a, and the ragged-composed
    plan ships the carry scatter map ``nrep_ring_dst`` whose kept
    positions cover exactly the non-replica receive slots."""
    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS,
                          comm_schedule="ragged", halo_staleness=1,
                          replica_budget=BUDGET, sync_every=2)
    shapes = plan.stale_carry_shapes(feats.shape[1], WIDTHS,
                                     comm_schedule="ragged")
    st = sum(plan.rr_sizes)
    assert [tuple(h.shape[1:]) for h in tr.halo_carry["halos"]] \
        == shapes["halos"] == [(st, f) for _, f in shapes["halos"]]
    # nrep_ring_dst: every non-pad entry is a valid full-ring position,
    # and the number of pad entries matches the shrunken ring's padding
    nr = np.asarray(plan.nrep_ring_dst)
    valid = nr < st
    assert int(valid.sum()) == int(plan.nrep_send_counts.sum())
    # kept positions are exactly the full-ring positions NOT replicated:
    # together with rep_ring_pos they cover each chip's receive set
    for q in range(plan.k):
        kept = set(nr[q][nr[q] < st].tolist())
        reps = set(np.asarray(plan.rep_ring_pos)[q][
            : int(plan.rep_counts[q])].tolist())
        assert not (kept & reps)


def test_composed_gating(cora):
    """Construction-time gates of the new compositions."""
    plan, feats, labels = cora
    fin = feats.shape[1]
    with pytest.raises(ValueError, match="deferred"):
        FullBatchTrainer(plan, fin=fin, widths=WIDTHS, halo_staleness=1,
                         halo_delta=True, replica_budget=8)
    with pytest.raises(ValueError, match="refresh_band"):
        FullBatchTrainer(plan, fin=fin, widths=WIDTHS, refresh_band=0.1)
    with pytest.raises(ValueError, match="deferred"):
        FullBatchTrainer(plan, fin=fin, widths=WIDTHS, halo_staleness=1,
                         replica_budget=8, sync_every=2, refresh_band=0.1)
    with pytest.raises(ValueError, match="a2a"):
        FullBatchTrainer(plan, fin=fin, widths=WIDTHS,
                         comm_schedule="ragged", replica_budget=8,
                         sync_every=2, refresh_band=0.1)
    with pytest.raises(ValueError, match="refresh_band must be >= 0"):
        FullBatchTrainer(plan, fin=fin, widths=WIDTHS, replica_budget=8,
                         sync_every=2, refresh_band=-0.5)


# ----------------------------------------------------- partial refresh
def test_partial_refresh_accounting(cora, tmp_path):
    """``--refresh-band 0``: every drifted replica row refreshes; the
    per-step event counts, the CommStats cumulative booking and the
    roofline byte figures reconcile EXACTLY at the actual shipped rows,
    and strictly fewer rows ship than a full refresh would."""
    from sgcn_tpu.obs import RunRecorder, load_run

    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=0,
                          replica_budget=BUDGET, sync_every=2,
                          refresh_band=0.0)
    d = make_train_data(plan, feats, labels)
    rec = RunRecorder(str(tmp_path / "run"), config={"band": 0.0})
    tr.attach_recorder(rec)
    for _ in range(6):
        tr.step(d)
    rec.close()
    log = load_run(str(tmp_path / "run"))          # schema re-validated
    steps = [e for e in log.events if e["kind"] == "step"]
    blocks = [s["replica"] for s in steps]
    # step 0: full (initializing); steps 2, 4: partial; 1, 3, 5: replica
    assert blocks[0].get("refresh_kind") == "full"
    partials = [b for b in blocks if b.get("refresh_kind") == "partial"]
    assert len(partials) == 2
    shipped = [sum(b["refresh_rows"]) for b in partials]
    saving = plan.replica_send_saving            # full refresh = Σλ rows
    assert all(0 < s <= saving for s in shipped), (shipped, saving)
    # exact booking at the actual rows, fwd + bwd
    rep = tr.stats.report()
    assert rep["partial_refresh_steps"] == 2
    assert rep["partial_refresh_rows_total"] == 2 * sum(shipped)
    assert rep["partial_refresh_wire_rows_total"] == (
        2 * len(WIDTHS) * 2 * plan.partial_refresh_wire_rows)
    # roofline ↔ CommStats byte reconciliation, partial steps included
    assert rep["halo_bytes_true_total"] == sum(
        s["roofline"]["halo_bytes_true_per_step"] for s in steps)
    assert rep["halo_bytes_wire_total"] == sum(
        s["roofline"]["halo_bytes_wire_per_step"] for s in steps)
    # the wire totals carry the side channel on top of the base exchanges
    base = (plan.wire_rows_per_exchange("a2a") * 2 * len(WIDTHS) * 1
            + plan.wire_rows_per_exchange("a2a", replica=True)
            * 2 * len(WIDTHS) * 5)
    assert rep["wire_rows_total"] == base + rep[
        "partial_refresh_wire_rows_total"]
    # rendered report carries the partial-refresh line
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(os.path.dirname(FIX), "..",
                                   "scripts", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert "partial refreshes: 2" in mod.render(str(tmp_path / "run"))


def test_partial_refresh_strictly_fewer_rows_on_hp(cora):
    """THE acceptance figure on the skewed-hp fixture: with a meaningful
    band, partial refreshes ship STRICTLY fewer rows than the full
    refreshes would re-ship for the replica set (and more than zero —
    the band is doing selection, not disabling refresh)."""
    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=0,
                          replica_budget=BUDGET, sync_every=2,
                          refresh_band=0.5)
    d = make_train_data(plan, feats, labels)
    for _ in range(6):
        tr.step(d)
    rep = tr.stats.report()
    full_rows = (2 * plan.replica_send_saving
                 * rep["partial_refresh_steps"])   # fwd+bwd per refresh
    assert 0 < rep["partial_refresh_rows_total"] < full_rows


def test_partial_refresh_band_semantics(cora):
    """A band above any possible drift ships ZERO rows (the replica
    tables keep their step-0 values) and the run stays finite; the
    booked count says so."""
    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=0,
                          replica_budget=BUDGET, sync_every=2,
                          refresh_band=1e12)
    d = make_train_data(plan, feats, labels)
    reps0 = None
    losses = []
    for i in range(5):
        losses.append(tr.step(d))
        if i == 0:
            reps0 = [np.asarray(r) for r in tr.replica_carry["reps"]]
    assert np.all(np.isfinite(losses))
    rep = tr.stats.report()
    assert rep["partial_refresh_steps"] == 2
    assert rep["partial_refresh_rows_total"] == 0
    for a, b in zip(reps0, tr.replica_carry["reps"]):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_partial_refresh_bf16_lockstep(cora):
    """Sender/receiver lockstep under the narrow wire: with ``--halo-dtype
    bfloat16`` the full-refresh baseline anchors at the WIRE-QUANTIZED
    value (what consumers actually received), so after any sequence of
    partial refreshes every consumer's replica row equals the owner's
    baseline row BIT-FOR-BIT — the quantization error must not become
    permanent sender/receiver disagreement."""
    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=0,
                          replica_budget=BUDGET, sync_every=2,
                          refresh_band=0.0, halo_dtype="bfloat16")
    d = make_train_data(plan, feats, labels)
    for _ in range(5):
        tr.step(d)
    reps = [np.asarray(r) for r in tr.replica_carry["reps"]]
    bases = [np.asarray(b) for b in tr.replica_carry["rep_base"]]
    s = plan.s
    for q in range(plan.k):
        for i in range(int(plan.rep_counts[q])):
            rank = int(plan.rep_slots[q, i])
            slot = int(plan.halo_src[q, rank])
            o, j = slot // s, slot % s
            row = int(plan.send_idx[o, q, j])
            pos = int(np.searchsorted(
                plan.rep_rows[o, : int(plan.rep_row_counts[o])], row))
            for layer in range(len(WIDTHS)):
                np.testing.assert_array_equal(reps[layer][q, i],
                                              bases[layer][o, pos])


# ---------------------------------------------------------- controller
def test_controller_band_crossing_determinism():
    """The retune rule is a pure function of the injected gauge sequence:
    above-band halves (floored), below-band doubles (capped), inside-band
    holds; identical inputs give identical decision logs."""
    from sgcn_tpu.train.controller import CommController

    drifts = [0.1, 0.9, 0.9, 0.01, 0.001, 0.2, 0.0, 0.0, 0.0]

    def run():
        c = CommController(sync_every=8, upper=0.5, lower=0.02,
                           min_sync=2, max_sync=16)
        return [c.observe(i, x) for i, x in enumerate(drifts)], c

    seq, c = run()
    #        hold halve halve dbl  dbl  hold dbl  dbl(cap) cap
    assert seq == [8, 4, 2, 4, 8, 8, 16, 16, 16]
    assert c.sync_every == 16 and c.initial_sync_every == 8
    rules = [d["rule"] for d in c.decisions]
    assert rules == ["drift above band", "drift above band",
                     "drift below band", "drift below band",
                     "drift below band"]
    seq2, c2 = run()
    assert seq2 == seq and c2.decisions == c.decisions
    # floor clamp: repeated above-band never goes below min_sync
    c3 = CommController(sync_every=4, min_sync=2)
    for i in range(4):
        c3.observe(i, 1e9)
    assert c3.sync_every == 2
    with pytest.raises(ValueError, match="sync_every"):
        CommController(sync_every=0)
    with pytest.raises(ValueError, match="lower < upper"):
        CommController(sync_every=4, lower=0.9, upper=0.5)


def test_controller_retunes_trainer_and_logs_manifest(cora, tmp_path):
    """``--comm-schedule auto`` + a sync schedule activates the
    controller; with the band forced below the measured drift the trainer
    WIDENS its effective sync_every mid-run and the decisions land in the
    run manifest's ``comm_schedule.controller`` block (rendered by
    obs_report)."""
    from sgcn_tpu.obs import RunRecorder, load_run

    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=0,
                          comm_schedule="auto", halo_staleness=1,
                          replica_budget=BUDGET, sync_every=2)
    assert tr.controller is not None
    assert tr.comm_decision["controller"]["retunes"] == []
    # force every observed drift below the band -> widen on each sync
    tr.controller.lower = 1e30
    tr.controller.upper = 1e31
    d = make_train_data(plan, feats, labels)
    rec = RunRecorder(str(tmp_path / "run"), config={})
    tr.attach_recorder(rec)
    for _ in range(7):
        tr.step(d)
    rec.close()
    assert tr.sync_every > 2
    ctl = tr.comm_decision["controller"]
    assert ctl["retunes"] and ctl["retunes"][0]["rule"] == "drift below band"
    m = load_run(str(tmp_path / "run")).manifest
    assert m["comm_schedule"]["controller"]["retunes"] == ctl["retunes"]
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(os.path.dirname(FIX), "..",
                                   "scripts", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    text = mod.render(str(tmp_path / "run"))
    assert "controller (drift-banded sync_every retune)" in text
    assert "drift below band" in text


def test_controller_inactive_without_auto_or_schedule(cora):
    """An explicit transport keeps the controller off (static settings
    stay static), as does a missing sync schedule under 'auto'."""
    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS,
                          comm_schedule="ragged", halo_staleness=1,
                          sync_every=2)
    assert tr.controller is None
    tr2 = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS,
                           comm_schedule="auto")
    assert tr2.controller is None


def test_replica_auto_budget_and_decision_log(cora):
    """``--replica-budget auto`` resolves at the λ·degree knee (B > 0 on
    the skewed cora boundary), deterministically, with the scoring inputs
    and the replica-aware wire figures in the decision log."""
    from sgcn_tpu.parallel.plan import choose_replica_budget

    plan, feats, labels = cora
    knee = {}
    b1 = choose_replica_budget(plan, decision=knee)
    assert b1 == choose_replica_budget(plan)     # deterministic
    assert 0 < b1 <= knee["boundary_rows"]
    assert 0 < knee["score_covered"] <= 1
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS,
                          comm_schedule="auto", replica_budget="auto",
                          sync_every=2)
    assert tr.replica_budget == b1
    dec = tr.comm_decision
    assert dec["replica_auto"]["chosen"] == b1
    assert dec["replica_budget"] == b1
    # replica-aware scoring: the shrunken wire figures are logged and can
    # only be <= the full ones
    assert dec["wire_rows_a2a_replica"] <= dec["wire_rows_a2a"]
    assert dec["wire_rows_ragged_replica"] <= dec["wire_rows_ragged"]
    assert dec["true_rows_replica"] < dec["true_rows"]
    d = make_train_data(plan, feats, labels)
    assert np.isfinite(tr.step(d))


# ------------------------------------------------------ mutation checks
def _audit_composed(schedule):
    from sgcn_tpu.analysis.hlo_audit import audit_mode
    from sgcn_tpu.analysis.modes import Mode

    return audit_mode(Mode("train", "gcn", schedule, staleness=1,
                           replica=True))


def test_mutation_composed_full_width_stale_a2a(monkeypatch):
    """Seeded violation for the composed a2a mode: the stale step ships
    the FULL exchange instead of the shrunken buckets (the carry merge
    keeps the same bits at sync-every-1, so only the compiled wire shape
    betrays it) — the wire-shape rule must fail on the stale program."""
    import importlib

    pspmm = importlib.import_module("sgcn_tpu.ops.pspmm")
    real = pspmm._replica_stale_exchange

    def full_wire(x, halo_in, send_idx, halo_src, nrep_send_idx,
                  nrep_halo_src, rep_slots, axis_name, wire_dtype, fresh):
        return real(x, halo_in, send_idx, halo_src, send_idx, halo_src,
                    rep_slots, axis_name, wire_dtype, fresh)

    monkeypatch.setattr(pspmm, "_replica_stale_exchange", full_wire)
    entry = _audit_composed("a2a")
    assert not entry["programs"]["stale"]["ok"]
    assert any(v["rule"] == "wire-shape"
               for v in entry["programs"]["stale"]["violations"])
    assert entry["programs"]["sync"]["ok"]       # syncs SHOULD ship full


def test_mutation_composed_full_width_stale_ragged(monkeypatch):
    """Same seeded violation on the ring: the stale step ships the full
    per-round sizes instead of ``nrep_rr_sizes`` — wire-shape fails."""
    import importlib

    import jax.numpy as jnp

    pspmm = importlib.import_module("sgcn_tpu.ops.pspmm")
    real = pspmm._replica_stale_ring_exchange

    def full_ring(x, halo_in, rsend_idx, nrep_rsend_idx, nrep_ring_dst,
                  rr_sizes, nrep_rr_sizes, axis_name, wire_dtype, fresh):
        return real(x, halo_in, rsend_idx, rsend_idx,
                    jnp.arange(rsend_idx.shape[0],
                               dtype=nrep_ring_dst.dtype),
                    rr_sizes, rr_sizes, axis_name, wire_dtype, fresh)

    monkeypatch.setattr(pspmm, "_replica_stale_ring_exchange", full_ring)
    entry = _audit_composed("ragged")
    assert not entry["programs"]["stale"]["ok"]
    assert any(v["rule"] == "wire-shape"
               for v in entry["programs"]["stale"]["violations"])
    assert entry["programs"]["sync"]["ok"]
