"""Real-dataset ingestion + the cora-role accuracy experiment.

The reference's accuracy story is a run on real cora data
(``GPU/PGCN-Accuracy.py``, ``README.md:110``) pulled from sparse.tamu.edu/OGB
as ``.mtx`` (``README.md:11``).  Zero egress, so the repo commits a
deterministic cora-format fixture (``tests/fixtures/cora_like.*``, regenerated
by ``scripts/make_cora_fixture.py``) in both real-data layouts — the
planetoid/ogbn ``.npz`` snapshot and the MatrixMarket ``A/H/Y`` family — and
these tests drive the full CLI pipeline over it.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest
import scipy.sparse as sp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "fixtures")


def run_cli(args, **kw):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # let -b cpu set its own device count
    env["PYTHONPATH"] = REPO
    return subprocess.run([sys.executable, "-m", *args], capture_output=True,
                          text=True, cwd=REPO, env=env, timeout=600, **kw)


def fixture(name):
    return os.path.join(FIX, name)


def test_npz_roundtrip(tmp_path):
    from sgcn_tpu.io.datasets import (cora_like, load_npz_dataset,
                                      save_npz_dataset)
    a, feats, labels = cora_like(n=200, seed=3)
    p = str(tmp_path / "snap.npz")
    save_npz_dataset(p, a, feats, labels)
    a2, f2, y2 = load_npz_dataset(p)
    assert (a != a2).nnz == 0
    np.testing.assert_array_equal(np.asarray(feats.todense()), f2)
    np.testing.assert_array_equal(labels, y2)
    # dense-feature storage flavor
    save_npz_dataset(p, a, f2, labels)
    a3, f3, y3 = load_npz_dataset(p)
    np.testing.assert_array_equal(f2, f3)


def test_npz_fixture_matches_mtx_family():
    """The two committed layouts carry the same dataset."""
    from sgcn_tpu.io.datasets import load_npz_dataset
    from sgcn_tpu.io.mtx import read_mtx
    from sgcn_tpu.prep import normalize_adjacency
    a, feats, labels = load_npz_dataset(fixture("cora_like.npz"))
    ahat = read_mtx(fixture("cora_like.A.mtx"))
    h = read_mtx(fixture("cora_like.H.mtx"))
    y = read_mtx(fixture("cora_like.Y.mtx"))
    assert np.abs(normalize_adjacency(a) - ahat).max() < 1e-6
    np.testing.assert_array_equal(np.asarray(h.todense()), feats)
    np.testing.assert_array_equal(np.asarray(y.todense()).argmax(1), labels)


def test_cora_like_format():
    """Fixture has cora's format: binary sparse BoW, 7 classes, undirected."""
    from sgcn_tpu.io.datasets import load_npz_dataset
    a, feats, labels = load_npz_dataset(fixture("cora_like.npz"))
    assert a.shape == (600, 600)
    assert (a != a.T).nnz == 0
    assert set(np.unique(feats)) <= {0.0, 1.0}
    assert sp.csr_matrix(feats).nnz < 0.25 * feats.size   # sparse, like cora
    assert labels.max() == 6 and labels.min() == 0


def test_planetoid_split_semantics():
    from sgcn_tpu.io.datasets import planetoid_split
    labels = np.arange(300) % 7
    train, test = planetoid_split(labels, per_class=20, ntest=100, seed=0)
    counts = np.bincount(labels[train == 1.0], minlength=7)
    assert (counts == 20).all()                 # exactly per_class per class
    assert test.sum() == 100
    assert ((train == 1.0) & (test == 1.0)).sum() == 0   # disjoint


def test_cli_accuracy_experiment_mtx_family():
    """The PGCN-Accuracy run (GPU/PGCN-Accuracy.py): oracle vs partitioned
    trainer on the committed fixture through the file-based CLI, test
    accuracy parity asserted — the reference's README.md:110 protocol."""
    r = run_cli(["sgcn_tpu.train",
                 "-a", fixture("cora_like.A.mtx"),
                 "--features-mtx", fixture("cora_like.H.mtx"),
                 "--labels-mtx", fixture("cora_like.Y.mtx"),
                 "-p", fixture("cora_like.4.hp"),
                 "-b", "cpu", "-s", "4", "-l", "2", "--hidden", "32",
                 "--experiment", "accuracy", "--epochs", "30"])
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["oracle_test_acc"] > 0.6          # far above 1/7 chance
    assert abs(rep["oracle_test_acc"] - rep["fullbatch_test_acc"]) < 0.05


def test_cli_accuracy_experiment_npz_minibatch():
    """Same experiment from the .npz snapshot, mini-batch flavor included."""
    r = run_cli(["sgcn_tpu.train",
                 "--npz", fixture("cora_like.npz"), "--normalize",
                 "-p", fixture("cora_like.4.hp"),
                 "-b", "cpu", "-s", "4", "-l", "2", "--hidden", "32",
                 "--experiment", "accuracy", "--epochs", "30", "-n", "200"])
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["oracle_test_acc"] > 0.6
    assert abs(rep["oracle_test_acc"] - rep["minibatch_test_acc"]) < 0.05


@pytest.mark.parametrize(
    "k", [4, pytest.param(8, marks=pytest.mark.slow)])  # k=8 re-runs the
    # same 1433-wide CLI pipeline for ~75 s of tier-1 budget; k=4 is the
    # budgeted representative
def test_cli_accuracy_cora_true_shape(k):
    """The accuracy experiment at cora's TRUE dims (VERDICT r3 item 3):
    2708 x 1433 x 7, planetoid split (20/class train, 1000 test), oracle vs
    k-way partitioned full-batch AND mini-batch, through the .npz snapshot
    ingestion path end-to-end.  The reference's protocol is the real-cora
    run of ``GPU/PGCN-Accuracy.py`` (README.md:110); real-cora GCN accuracy
    is ~0.81, and the fixture's learnability is calibrated to land in that
    band (measured 0.85 oracle / 0.85 full-batch / 0.83 mini-batch)."""
    r = run_cli(["sgcn_tpu.train",
                 "--npz", fixture("cora2708.npz"), "--normalize",
                 "-p", fixture(f"cora2708.{k}.hp"),
                 "-b", "cpu", "-s", str(k), "-l", "2", "--hidden", "16",
                 "--experiment", "accuracy", "--epochs", "60", "-n", "256"])
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["oracle_test_acc"] > 0.75           # cora-band accuracy
    assert abs(rep["oracle_test_acc"] - rep["fullbatch_test_acc"]) < 0.03
    assert abs(rep["oracle_test_acc"] - rep["minibatch_test_acc"]) < 0.05
