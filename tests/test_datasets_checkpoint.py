"""Datasets + checkpoint/resume tests."""

import numpy as np

from sgcn_tpu.io.datasets import er_graph, karate, planted_partition, save_fixture
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data
from sgcn_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def test_karate_structure():
    a, labels = karate()
    assert a.shape == (34, 34)
    assert a.nnz == 156                      # 78 undirected edges
    assert (a != a.T).nnz == 0               # symmetric
    assert a.diagonal().sum() == 0           # no self loops
    assert labels.shape == (34,)
    assert set(np.unique(labels)) == {0, 1}


def test_planted_partition_learnable():
    a, feats, labels = planted_partition(n=60, nclasses=3, seed=1)
    assert a.shape == (60, 60)
    assert feats.shape == (60, 3)
    assert (a != a.T).nnz == 0


def test_er_graph():
    a = er_graph(500, avg_deg=10, seed=0)
    assert a.shape == (500, 500)
    assert (a != a.T).nnz == 0
    deg = np.asarray(a.sum(axis=1)).ravel()
    assert 5 < deg.mean() < 15


def test_save_fixture_roundtrip(tmp_path):
    from sgcn_tpu.io.mtx import read_mtx
    a, labels = karate()
    paths = save_fixture(str(tmp_path / "karate"), a, labels)
    ahat = read_mtx(paths["A"])
    assert ahat.shape == (34, 34)
    y = read_mtx(paths["Y"])
    assert y.shape == (34, 2)
    np.testing.assert_array_equal(
        np.asarray(y.todense()).argmax(1), labels)


def test_checkpoint_roundtrip(tmp_path):
    a, labels = karate()
    ahat = normalize_adjacency(a)
    n = 34
    feats = np.eye(2, dtype=np.float32)[labels]
    pv = balanced_random_partition(n, 2, seed=0)
    plan = build_comm_plan(ahat, pv, 2)
    data = make_train_data(plan, feats, labels)

    tr = FullBatchTrainer(plan, fin=2, widths=[8, 2], seed=1)
    for _ in range(3):
        tr.step(data)
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(tr, path, step=3)
    expected = tr.predict(data)

    tr2 = FullBatchTrainer(plan, fin=2, widths=[8, 2], seed=99)
    assert load_checkpoint(tr2, path) == 3
    np.testing.assert_allclose(tr2.predict(data), expected, rtol=1e-6)
    # resumed training continues identically to uninterrupted training
    l1 = tr.step(data)
    l2 = tr2.step(data)
    assert abs(l1 - l2) < 1e-6


def test_checkpoint_shape_mismatch_rejected(tmp_path):
    a, labels = karate()
    ahat = normalize_adjacency(a)
    pv = balanced_random_partition(34, 2, seed=0)
    plan = build_comm_plan(ahat, pv, 2)
    tr = FullBatchTrainer(plan, fin=2, widths=[8, 2], seed=1)
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(tr, path)
    other = FullBatchTrainer(plan, fin=2, widths=[16, 2], seed=1)
    try:
        load_checkpoint(other, path)
        assert False, "expected ValueError"
    except ValueError:
        pass


def test_checkpoint_provenance_recorded_and_verified(tmp_path):
    """PR-8: the .npz records plan digest + model config; a wrong-plan
    restore fails with the clear digest message (not a tree-shape error
    deep inside replicate), and a pre-provenance checkpoint (no metadata
    keys) still loads."""
    from sgcn_tpu.obs.recorder import plan_digest
    from sgcn_tpu.utils.checkpoint import read_checkpoint_meta

    a, labels = karate()
    ahat = normalize_adjacency(a)
    feats = np.eye(2, dtype=np.float32)[labels]
    pv = balanced_random_partition(34, 2, seed=0)
    plan = build_comm_plan(ahat, pv, 2)
    tr = FullBatchTrainer(plan, fin=2, widths=[8, 2], seed=1)
    path = save_checkpoint(tr, str(tmp_path / "ckpt.npz"), step=5)
    meta = read_checkpoint_meta(path)
    assert meta["step"] == 5
    assert meta["plan_digest"] == plan_digest(plan)
    assert meta["model_config"]["model"] == "gcn"
    assert meta["model_config"]["fin"] == 2
    assert meta["model_config"]["widths"] == [8, 2]
    # wrong partition, same shapes: the digest check fires with the clear
    # message (before provenance this restored with no record of the
    # mismatch); verify=False is the documented deliberate override —
    # weights are partition-independent
    other_plan = build_comm_plan(ahat, balanced_random_partition(
        34, 2, seed=7), 2)
    other = FullBatchTrainer(other_plan, fin=2, widths=[8, 2], seed=1)
    try:
        load_checkpoint(other, path)
        assert False, "expected ValueError"
    except ValueError as e:
        assert "plan digest mismatch" in str(e)
    assert load_checkpoint(other, path, verify=False) == 5
    # the mini-batch trainer checkpoints through its inner (per-BATCH
    # plan): the digest is suppressed — not a stable run identity — so a
    # cross-batch-shape resume is not a digest error; config still recorded
    from sgcn_tpu.train.minibatch import MiniBatchTrainer
    mb = MiniBatchTrainer(ahat, pv, 2, fin=2, widths=[8, 2],
                          batch_size=20, seed=0)
    mpath = save_checkpoint(mb.inner, str(tmp_path / "mb.npz"), step=1)
    mmeta = read_checkpoint_meta(mpath)
    assert mmeta["plan_digest"] is None
    assert mmeta["model_config"]["widths"] == [8, 2]
    # pre-provenance file (leaves + step only) still loads
    import jax
    leaves = jax.tree.leaves((tr.params, tr.opt_state))
    old = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    old["__step__"] = np.asarray(3, dtype=np.int64)
    oldpath = str(tmp_path / "old.npz")
    np.savez(oldpath, **old)
    tr2 = FullBatchTrainer(plan, fin=2, widths=[8, 2], seed=9)
    assert load_checkpoint(tr2, oldpath) == 3


def test_ba_graph_power_law():
    """ba_graph must produce the hub-heavy profile the bucketed layout is
    designed around (er_graph never exercises hub spill)."""
    from sgcn_tpu.io.datasets import ba_graph
    a = ba_graph(5000, 5, seed=1)
    assert (a != a.T).nnz == 0
    deg = np.asarray(a.sum(axis=1)).ravel()
    assert deg.max() > 10 * deg.mean()          # heavy tail
    assert abs(deg.mean() - 10) < 3             # ~2m average degree
    assert a.diagonal().sum() == 0
