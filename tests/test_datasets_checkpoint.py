"""Datasets + checkpoint/resume tests."""

import numpy as np

from sgcn_tpu.io.datasets import er_graph, karate, planted_partition, save_fixture
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data
from sgcn_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def test_karate_structure():
    a, labels = karate()
    assert a.shape == (34, 34)
    assert a.nnz == 156                      # 78 undirected edges
    assert (a != a.T).nnz == 0               # symmetric
    assert a.diagonal().sum() == 0           # no self loops
    assert labels.shape == (34,)
    assert set(np.unique(labels)) == {0, 1}


def test_planted_partition_learnable():
    a, feats, labels = planted_partition(n=60, nclasses=3, seed=1)
    assert a.shape == (60, 60)
    assert feats.shape == (60, 3)
    assert (a != a.T).nnz == 0


def test_er_graph():
    a = er_graph(500, avg_deg=10, seed=0)
    assert a.shape == (500, 500)
    assert (a != a.T).nnz == 0
    deg = np.asarray(a.sum(axis=1)).ravel()
    assert 5 < deg.mean() < 15


def test_save_fixture_roundtrip(tmp_path):
    from sgcn_tpu.io.mtx import read_mtx
    a, labels = karate()
    paths = save_fixture(str(tmp_path / "karate"), a, labels)
    ahat = read_mtx(paths["A"])
    assert ahat.shape == (34, 34)
    y = read_mtx(paths["Y"])
    assert y.shape == (34, 2)
    np.testing.assert_array_equal(
        np.asarray(y.todense()).argmax(1), labels)


def test_checkpoint_roundtrip(tmp_path):
    a, labels = karate()
    ahat = normalize_adjacency(a)
    n = 34
    feats = np.eye(2, dtype=np.float32)[labels]
    pv = balanced_random_partition(n, 2, seed=0)
    plan = build_comm_plan(ahat, pv, 2)
    data = make_train_data(plan, feats, labels)

    tr = FullBatchTrainer(plan, fin=2, widths=[8, 2], seed=1)
    for _ in range(3):
        tr.step(data)
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(tr, path, step=3)
    expected = tr.predict(data)

    tr2 = FullBatchTrainer(plan, fin=2, widths=[8, 2], seed=99)
    assert load_checkpoint(tr2, path) == 3
    np.testing.assert_allclose(tr2.predict(data), expected, rtol=1e-6)
    # resumed training continues identically to uninterrupted training
    l1 = tr.step(data)
    l2 = tr2.step(data)
    assert abs(l1 - l2) < 1e-6


def test_checkpoint_shape_mismatch_rejected(tmp_path):
    a, labels = karate()
    ahat = normalize_adjacency(a)
    pv = balanced_random_partition(34, 2, seed=0)
    plan = build_comm_plan(ahat, pv, 2)
    tr = FullBatchTrainer(plan, fin=2, widths=[8, 2], seed=1)
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(tr, path)
    other = FullBatchTrainer(plan, fin=2, widths=[16, 2], seed=1)
    try:
        load_checkpoint(other, path)
        assert False, "expected ValueError"
    except ValueError:
        pass


def test_ba_graph_power_law():
    """ba_graph must produce the hub-heavy profile the bucketed layout is
    designed around (er_graph never exercises hub spill)."""
    from sgcn_tpu.io.datasets import ba_graph
    a = ba_graph(5000, 5, seed=1)
    assert (a != a.T).nnz == 0
    deg = np.asarray(a.sum(axis=1)).ravel()
    assert deg.max() > 10 * deg.mean()          # heavy tail
    assert abs(deg.mean() - 10) < 3             # ~2m average degree
    assert a.diagonal().sum() == 0
