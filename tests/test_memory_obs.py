"""Tier-1 gate for the memory-observability subsystem (``sgcn_tpu/obs/
memory.py`` + ``analysis/hlo_audit.py::run_memory_audit`` — ISSUE 18).

Four layers of assurance:

  * **reconciliation at HEAD** — a representative slice of the supported
    matrix (one mode per array family the model itemizes: dense-a2a halo
    tables, ragged+stale carries, replica carries, the GAT packed wire,
    Pallas tiles, the minibatch envelope, serve buckets, the sub-graph
    forward) compiles its REAL program and XLA's ``memory_analysis()``
    figures reconcile against the analytic model within ``MEM_MODEL_TOL``
    under the one-sided contract the module docstring states;
  * **mutation check** — a seeded ``donate_argnums`` strip provably trips
    the ``memory-model`` rule's alias floor (a lint that cannot fail is
    decoration);
  * **budget gate** — ``check_memory_budget`` rejects an over-budget
    (plan, mode) with the itemized per-family table, at plan time;
  * **gauge reconciliation** — the manifest ``memory`` block a real
    recorded run writes equals the model recomputed from the same
    (plan, config), and round-trips ``validate_manifest``.

The module-scoped ``rep_report`` fixture compiles the representative
programs ONCE (~60 s at HEAD — inside the tier-1 per-test budget, charged
to the first test that uses it).  The FULL 48-mode compile sweep is the
slow-marked ``test_full_matrix_memory_audit`` (~3 min).
"""

import os

import numpy as np
import pytest

from sgcn_tpu.analysis.hlo_audit import (AUDIT_FIN, AUDIT_WIDTHS, audit_plan,
                                         memory_audit_mode, run_memory_audit)
from sgcn_tpu.analysis.modes import Mode
from sgcn_tpu.obs.memory import (MEM_MODEL_TOL, MemoryBudgetError,
                                 MemoryModel, check_memory_budget,
                                 memory_model, model_param_bytes, parse_bytes,
                                 reconcile)

# one mode per array family the analytic model itemizes — the calibration
# set MEM_MODEL_TOL was derived on (worst observed peak/total ratio: the
# packed-wire GAT ragged mode at ~1.8 on CPU-compiled programs)
REP_MODES = (
    Mode("train", "gcn", "a2a"),                                 # halo_tables
    Mode("train", "gcn", "ragged", staleness=1,
         halo_dtype="bfloat16"),                                 # halo_carries
    Mode("train", "gcn", "a2a", replica=True),                   # replica_carries
    Mode("train", "gat", "ragged", gat_form="packed"),           # gat wire
    Mode("train", "gcn", "ragged", pallas=True),                 # pallas_tiles
    Mode("minibatch", "gcn", "a2a"),                             # envelope
    Mode("serve", "gcn", "ragged"),                              # bucket fwd
    Mode("serve_subgraph", "gcn", "a2a"),                        # subgraph fwd
)


@pytest.fixture(scope="module")
def rep_report():
    return {m.mode_id: memory_audit_mode(m) for m in REP_MODES}


def _violations(entry):
    return [v for prog in entry["programs"].values()
            for v in prog["violations"]]


# -------------------------------------------------- reconciliation at HEAD
def test_representative_modes_reconcile(rep_report):
    """Acceptance criterion: every representative program's measured peak /
    arguments / alias reconcile against the analytic model at HEAD."""
    bad = {mid: _violations(e) for mid, e in rep_report.items()
           if not e["ok"]}
    assert not bad, f"memory-model violations at HEAD: {bad}"


def test_measured_join_present_and_banded(rep_report):
    """The CPU backend exposes memory_analysis, so the join must actually
    be there (a sweep of skipped=True entries would pass vacuously), and
    every measured peak sits inside the calibrated band."""
    for mid, entry in rep_report.items():
        assert entry["model_bytes"] > 0, mid
        for label, prog in entry["programs"].items():
            assert not prog.get("skipped"), (mid, label)
            assert prog["measured"] is not None, (mid, label)
            assert 0.0 < prog["ratio"] <= MEM_MODEL_TOL, (
                f"{mid}/{label}: peak/model ratio {prog['ratio']:.2f} "
                f"outside (0, {MEM_MODEL_TOL}]")


def test_family_itemization_per_mode(rep_report):
    """Each representative mode's model itemizes the family it was picked
    for — the per-family lines of the budget table cannot silently
    collapse into 'workspace'."""
    plan = audit_plan()

    def fams(workload, **kw):
        return memory_model(plan, AUDIT_FIN, AUDIT_WIDTHS,
                            workload=workload, **kw).families

    assert fams("train", comm_schedule="a2a")["halo_tables"] > 0
    assert fams("train", comm_schedule="ragged")["halo_tables"] == 0
    assert fams("train", comm_schedule="ragged",
                halo_staleness=1)["halo_carries"] > 0
    assert fams("train", comm_schedule="a2a",
                replica_budget=12)["replica_carries"] > 0
    assert fams("serve", comm_schedule="ragged")["opt_state"] == 0
    # the audit entries carry the same totals the standalone model computes
    a2a = rep_report["train/gcn/a2a/s0/f32"]
    assert a2a["model_bytes"] == memory_model(
        plan, AUDIT_FIN, AUDIT_WIDTHS, workload="train",
        comm_schedule="a2a").total_bytes


# --------------------------------------------------------- mutation check
def test_donation_strip_trips_alias_floor(monkeypatch):
    """Seeded mutation: stripping ``donate_argnums`` from every jit zeroes
    XLA's alias bytes, and the memory-model rule's alias floor must fail
    DETERMINISTICALLY (this is the no-vacuous-lint criterion for the
    reconciliation contract's donation leg)."""
    import jax

    real_jit = jax.jit

    def stripped_jit(*args, **kwargs):
        kwargs.pop("donate_argnums", None)
        return real_jit(*args, **kwargs)

    monkeypatch.setattr(jax, "jit", stripped_jit)
    entry = memory_audit_mode(Mode("train", "gcn", "a2a"))
    assert not entry["ok"]
    viols = _violations(entry)
    assert viols and all(v["rule"] == "memory-model" for v in viols)
    assert any("alias" in v["detail"] for v in viols), viols


# ------------------------------------------------- reconcile() unit checks
def _toy_model(workload="train"):
    return MemoryModel(workload=workload,
                       families={"params": 1000, "opt_state": 2000,
                                 "workspace": 7000})


def test_reconcile_upper_envelope_and_argument_subset():
    m = _toy_model()
    ok = reconcile(m, {"argument_bytes": 3000, "output_bytes": 1000,
                       "temp_bytes": 2000, "alias_bytes": 3000,
                       "generated_code_bytes": 1, "peak_bytes": 3000})
    assert ok["ok"] and ok["block"]["total"]["ratio"] == 0.3
    # peak above model x tol — the envelope violation
    bad = reconcile(m, {"argument_bytes": 3000, "output_bytes": 1000,
                        "temp_bytes": 50_000, "alias_bytes": 3000,
                        "generated_code_bytes": 1, "peak_bytes": 51_000})
    assert not bad["ok"] and "exceeds the analytic total" in \
        bad["violations"][0]
    # arguments beyond the modeled resident set (jit never invents inputs)
    bad = reconcile(m, {"argument_bytes": 5000, "output_bytes": 0,
                        "temp_bytes": 0, "alias_bytes": 3000,
                        "generated_code_bytes": 1, "peak_bytes": 2000})
    assert not bad["ok"] and "resident arguments" in bad["violations"][0]


def test_reconcile_serve_must_not_alias():
    bad = reconcile(_toy_model("serve"),
                    {"argument_bytes": 1000, "output_bytes": 100,
                     "temp_bytes": 100, "alias_bytes": 64,
                     "generated_code_bytes": 1, "peak_bytes": 1136})
    assert not bad["ok"] and "must not be donated" in bad["violations"][0]


def test_reconcile_absent_join_is_ok():
    out = reconcile(_toy_model(), None)
    assert out["ok"] and out["block"]["total"]["measured_bytes"] is None


# ------------------------------------------------------------ budget gate
def test_budget_gate_rejects_with_itemized_table():
    plan = audit_plan()
    model = memory_model(plan, AUDIT_FIN, AUDIT_WIDTHS, workload="train",
                         comm_schedule="a2a")
    with pytest.raises(MemoryBudgetError) as ei:
        check_memory_budget(model, 1024, what="test trainer")
    msg = str(ei.value)
    assert "exceeds --memory-budget 1,024 B" in msg
    assert "per-family breakdown" in msg and "TOTAL" in msg
    for fam in ("params", "opt_state", "workspace"):
        assert fam in msg, f"budget table misses the {fam} line"
    # under budget (and no budget at all) pass silently
    check_memory_budget(model, model.total_bytes)
    check_memory_budget(model, None)
    with pytest.raises(ValueError, match="> 0"):
        check_memory_budget(model, 0)


def test_parse_bytes():
    assert parse_bytes("1024") == 1024
    assert parse_bytes("2K") == 2048
    assert parse_bytes("16G") == 16 * 1024 ** 3
    assert parse_bytes("1.5M") == int(1.5 * 1024 ** 2)
    assert parse_bytes("2KB") == 2048          # trailing B tolerated
    for bad in ("", "abc", "-1", "0", "nan"):
        with pytest.raises(ValueError):
            parse_bytes(bad)


# ------------------------------------------------------- model vs real init
def test_param_bytes_pin_real_init():
    """``model_param_bytes`` prices exactly what the init functions
    allocate — the params line of the budget table cannot drift from the
    real weight trees."""
    import jax

    from sgcn_tpu.models.gat import init_gat_params
    from sgcn_tpu.models.gcn import init_gcn_params

    dims = list(zip([AUDIT_FIN] + list(AUDIT_WIDTHS)[:-1],
                    list(AUDIT_WIDTHS)))
    rng = jax.random.PRNGKey(0)
    gcn = sum(int(np.prod(w.shape)) * 4 for w in init_gcn_params(rng, dims))
    assert model_param_bytes(AUDIT_FIN, AUDIT_WIDTHS, model="gcn") == gcn
    gat = sum(int(np.prod(leaf.shape)) * 4
              for layer in init_gat_params(rng, dims)
              for leaf in layer.values())
    assert model_param_bytes(AUDIT_FIN, AUDIT_WIDTHS, model="gat") == gat


# ------------------------------------------------- gauge reconciliation
def test_manifest_memory_block_reconciles(tmp_path):
    """A real recorded run's manifest ``memory`` block equals the model
    recomputed from the same (plan, config), validates through
    ``validate_manifest``, and a measured-join memory EVENT round-trips
    ``validate_event`` with the ``measured_peak_bytes`` vocabulary."""
    from sgcn_tpu.obs import (RunRecorder, load_run, validate_event,
                              validate_manifest)
    from sgcn_tpu.obs.memory import measure_compiled
    from sgcn_tpu.train import FullBatchTrainer

    plan = audit_plan()
    tr = FullBatchTrainer(plan, fin=AUDIT_FIN, widths=list(AUDIT_WIDTHS))
    with RunRecorder(str(tmp_path), config={"model": "gcn"}) as rec:
        tr.attach_recorder(rec)
        measured = measure_compiled(tr.lower_step().compile())
        assert measured is not None       # CPU exposes memory_analysis
        rec.record_memory("step", tr.memory, measured=measured,
                          budget_bytes=1 << 30)

    log = load_run(str(tmp_path))
    validate_manifest(log.manifest)
    blk = log.manifest["memory"]
    want = memory_model(plan, AUDIT_FIN, AUDIT_WIDTHS, workload="train",
                        comm_schedule=tr.comm_schedule)
    assert {k: v["model_bytes"] for k, v in blk["families"].items()} == \
        {k: int(v) for k, v in want.families.items()}
    assert blk["total"]["model_bytes"] == want.total_bytes

    mems = [e for e in log.events if e["kind"] == "memory"]
    assert len(mems) == 1
    ev = mems[0]
    validate_event(ev)
    assert ev["measured_peak_bytes"] == measured["peak_bytes"]
    assert ev["alias_bytes"] >= want.donated_floor_bytes
    assert abs(ev["ratio"] - measured["peak_bytes"] / want.total_bytes) \
        < 1e-9
    assert ev["budget_bytes"] == 1 << 30


# ------------------------------------------------------- full sweep (slow)
@pytest.mark.slow
def test_full_matrix_memory_audit():
    """The full 48-mode compile sweep: every supported mode's every program
    reconciles (the tier-1 slice above covers one mode per family; this is
    the exhaustive nightly face of the same contract)."""
    report = run_memory_audit()
    bad = {mid: _violations(e) for mid, e in report["modes"].items()
           if not e["ok"]}
    assert not bad, f"memory-model violations: {bad}"
    assert report["n_modes"] >= 40
