"""SHP stochastic hypergraph model tests (GPU/SHP/main.py capability)."""

import numpy as np
import scipy.sparse as sp

from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.shp import (
    communication_volume,
    generate_stochastic_hypergraph,
    run_shp,
    sample_sparse_submatrix,
)


def test_sample_submatrix_structure(ahat):
    rng = np.random.default_rng(0)
    s = sample_sparse_submatrix(ahat, 20, rng)
    # global row space preserved, empty cols dropped
    assert s.shape[0] == ahat.shape[0]
    assert s.shape[1] <= ahat.shape[1]
    assert (np.diff(sp.csc_matrix(s).indptr) > 0).all()
    # every nonzero row belongs to the sampled subset (<= 20 distinct rows)
    assert len(np.unique(sp.coo_matrix(s).row)) <= 20


def test_stochastic_hypergraph_hstack(ahat):
    rng = np.random.default_rng(1)
    stc = generate_stochastic_hypergraph(ahat, nbatches=3, batch_size=15,
                                         rng=rng)
    assert stc.shape[0] == ahat.shape[0]


def test_communication_volume_matches_definition():
    # column 0 touches parts {0,1} -> 1; column 1 touches {0} -> 0
    rows = np.array([0, 1, 2])
    cols = np.array([0, 0, 1])
    s = sp.coo_matrix((np.ones(3), (rows, cols)), shape=(4, 2))
    pv = np.array([0, 1, 0, 1])
    assert communication_volume(s, pv) == 1
    # λ-1 over one column with 3 parts
    s2 = sp.coo_matrix((np.ones(3), (np.array([0, 1, 2]), np.zeros(3, int))),
                       shape=(3, 1))
    assert communication_volume(s2, np.array([0, 1, 2])) == 2


def test_communication_volume_consistent_with_plan(ahat):
    """Full-graph λ-1 via SHP's counter == the comm plan's predicted volume."""
    from sgcn_tpu.parallel import build_comm_plan
    n = ahat.shape[0]
    pv = balanced_random_partition(n, 4, seed=2)
    plan = build_comm_plan(ahat, pv, 4)
    # column-net volume counts each column's (λ-1); the plan counts sent rows
    # per destination — the same quantity summed over chips
    vol = communication_volume(ahat, pv)
    assert vol == int(plan.predicted_send_volume.sum())


def test_run_shp_end_to_end(ahat):
    res = run_shp(ahat, k=3, nsampled_batches=4, batch_size=16, sim_iters=6,
                  seed=1)
    n = ahat.shape[0]
    for key in ("partvec_hp", "partvec_stchp"):
        pv = res[key]
        assert pv.shape == (n,)
        assert pv.min() >= 0 and pv.max() < 3
    assert res["sim_comm_volume_hp"] >= 0
    assert res["sim_comm_volume_stchp"] >= 0
