"""Driver entry-point gates.

Round 1's driver check failed because ``dryrun_multichip(8)`` demanded an
8-device mesh from a backend already initialized on one real TPU chip
(MULTICHIP_r01.json rc=1).  These tests pin the fix: the entry point must
self-provision a virtual CPU mesh, in-process when the backend already has
enough devices and via subprocess re-exec when it does not.
"""

import pytest

import __graft_entry__ as graft


def test_dryrun_multichip_in_process():
    # conftest provides 8 virtual CPU devices, so this takes the direct path;
    # dryrun degrades to a status dict instead of raising, so assert ok
    assert graft.dryrun_multichip(8)["ok"] is True


@pytest.mark.slow   # full re-exec of the 16-device dry run: ~85 s of the
                    # tier-1 budget for a pure subprocess-plumbing variant of
                    # the in-process test above
def test_dryrun_multichip_subprocess_self_provisions():
    # asking for more devices than the live backend has forces the driver
    # fallback: re-exec in a subprocess with the virtual-mesh env vars
    # (ok must be asserted — a deadline/backend degradation returns a
    # marked dict instead of raising)
    assert graft.dryrun_multichip(16)["ok"] is True


def test_entry_forward_compiles():
    import jax
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (64, 4)
