"""Driver entry-point gates.

Round 1's driver check failed because ``dryrun_multichip(8)`` demanded an
8-device mesh from a backend already initialized on one real TPU chip
(MULTICHIP_r01.json rc=1).  These tests pin the fix: the entry point must
self-provision a virtual CPU mesh, in-process when the backend already has
enough devices and via subprocess re-exec when it does not.
"""

import __graft_entry__ as graft


def test_dryrun_multichip_in_process():
    # conftest provides 8 virtual CPU devices, so this takes the direct path
    graft.dryrun_multichip(8)


def test_dryrun_multichip_subprocess_self_provisions():
    # asking for more devices than the live backend has forces the driver
    # fallback: re-exec in a subprocess with the virtual-mesh env vars
    graft.dryrun_multichip(16)


def test_entry_forward_compiles():
    import jax
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (64, 4)
