"""Pipelined stale-halo exchange (``--halo-staleness``): the trainer's
bounded-staleness mode (PipeGCN-style features+gradients, ``pspmm_stale``)
with halo-delta caching and the periodic full-sync schedule.

Contract pinned here:

  * ``halo_staleness=0`` (the default) IS the pre-existing trainer — same
    code path, bit-identical losses and parameters on the cora fixture;
  * ``sync_every=1`` makes every step a full-sync step, which is exact-mode
    math — losses match the exact trainer to f32 tolerance;
  * staleness-1 training converges to oracle-parity test accuracy on the
    cora fixture within a bounded extra-epoch budget;
  * the delta cache's wire is bf16 (and only the FEATURE wire — the
    gradient exchange keeps its own dtype);
  * ``CommStats`` splits hidden (pipelined) from exposed (sync) exchanges.
"""

import os
import re

import numpy as np
import pytest

from sgcn_tpu.io.datasets import er_graph, load_npz_dataset
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.parallel.mesh import shard_stacked
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.partition.emit import read_partvec
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


@pytest.fixture(scope="module")
def cora():
    """The committed cora-format fixture + its 4-way hp partvec."""
    a, feats, labels = load_npz_dataset(os.path.join(FIX, "cora_like.npz"))
    ahat = normalize_adjacency(a)
    pv = read_partvec(os.path.join(FIX, "cora_like.4.hp"))
    plan = build_comm_plan(ahat, pv, 4)
    return plan, feats.astype(np.float32), labels.astype(np.int32)


@pytest.fixture(scope="module")
def erplan():
    n, k = 800, 8
    ahat = normalize_adjacency(er_graph(n, 8, seed=0))
    pv = balanced_random_partition(n, k, seed=1)
    plan = build_comm_plan(ahat, pv, k)
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((n, 16)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    return plan, feats, labels


@pytest.fixture(scope="module")
def exact_losses(erplan):
    """8 exact-mode training losses — the shared reference for every
    tracking assertion (one trainer compile for the whole module)."""
    plan, feats, labels = erplan
    tr = FullBatchTrainer(plan, fin=16, widths=[8, 4], seed=2)
    d = make_train_data(plan, feats, labels)
    return [tr.step(d) for _ in range(8)]


def _params_np(tr):
    return [np.asarray(w) for w in tr.params]


def test_staleness0_bit_identical_to_default(cora):
    """``halo_staleness=0`` must be THE default trainer: same program, same
    bits — losses and parameters exactly equal after training on the cora
    fixture."""
    plan, feats, labels = cora
    tr_default = FullBatchTrainer(plan, fin=feats.shape[1], widths=[16, 7],
                                  seed=3)
    tr_zero = FullBatchTrainer(plan, fin=feats.shape[1], widths=[16, 7],
                               seed=3, halo_staleness=0)
    d = make_train_data(plan, feats, labels)
    l_default = [tr_default.step(d) for _ in range(3)]
    l_zero = [tr_zero.step(d) for _ in range(3)]
    assert l_default == l_zero                       # bitwise, not allclose
    for a, b in zip(_params_np(tr_default), _params_np(tr_zero)):
        np.testing.assert_array_equal(a, b)
    # and the exact path carries no stale machinery at all
    assert not hasattr(tr_zero, "halo_carry")


def test_sync_every_1_is_exact_math(erplan, exact_losses):
    """Every-step full sync consumes only fresh halos — the stale program
    degenerates to exact-mode math (different program, same numbers)."""
    plan, feats, labels = erplan
    d = make_train_data(plan, feats, labels)
    tr_sync = FullBatchTrainer(plan, fin=16, widths=[8, 4], seed=2,
                               halo_staleness=1, sync_every=1)
    got = [tr_sync.step(d) for _ in range(5)]
    np.testing.assert_allclose(got, exact_losses[:5], rtol=1e-5, atol=1e-6)


def test_stale1_tracks_run_epochs_and_stats(erplan, exact_losses):
    """Plain staleness-1: finite, tracks exact training closely after a few
    steps; the fused ``run_epochs`` path reproduces per-step ``step()``
    (including the sync-step scheduling around the loop); and CommStats
    books the sync steps (0, 3, 6) as exposed, the rest as hidden."""
    plan, feats, labels = erplan
    d = make_train_data(plan, feats, labels)
    tr_a = FullBatchTrainer(plan, fin=16, widths=[8, 4], seed=2,
                            halo_staleness=1, sync_every=3)
    la = [tr_a.step(d) for _ in range(8)]
    assert np.all(np.isfinite(la))
    assert abs(la[-1] - exact_losses[-1]) < 5e-2
    tr_b = FullBatchTrainer(plan, fin=16, widths=[8, 4], seed=2,
                            halo_staleness=1, sync_every=3)
    lb = tr_b.run_epochs(d, 8)
    np.testing.assert_allclose(lb, la, rtol=2e-4, atol=1e-5)

    # exposed/hidden accounting: 8 steps, sync at 0/3/6 → 3 exposed
    rep = tr_a.stats.report()
    nl = tr_a.nlayers
    per_ex = int(tr_a.stats.send_volume_per_exchange.sum())
    assert rep["exchanges"] == 8 * 2 * nl
    assert rep["exposed_exchanges"] == 3 * 2 * nl
    assert rep["hidden_exchanges"] == 5 * 2 * nl
    assert rep["hidden_send_volume"] == per_ex * 5 * 2 * nl
    assert rep["exposed_send_volume"] == per_ex * 3 * 2 * nl
    assert rep["total_send_volume"] == \
        rep["hidden_send_volume"] + rep["exposed_send_volume"]
    # run_epochs books the same schedule as per-step driving
    assert tr_b.stats.report() == rep


def test_stale1_convergence_oracle_parity(cora):
    """The accuracy contract: staleness-1 (with the delta wire and periodic
    sync — the full pipelined config) reaches oracle-parity test accuracy on
    the cora fixture within a 1.5× epoch budget."""
    from sgcn_tpu.baselines import DenseOracle
    from sgcn_tpu.io.datasets import planetoid_split

    plan, feats, labels = cora
    train_mask, test_mask = planetoid_split(labels, per_class=20, seed=0)
    widths = [32, int(labels.max()) + 1]
    epochs = 30

    # oracle on the same normalized adjacency the plan was built from
    ahat, _, _ = load_npz_dataset(os.path.join(FIX, "cora_like.npz"))
    oracle = DenseOracle(normalize_adjacency(ahat), fin=feats.shape[1],
                         widths=widths, seed=7)
    oracle.fit(feats, labels, mask=train_mask, epochs=epochs)
    pred = oracle.predict(feats).argmax(1)
    oracle_acc = float((pred == labels)[test_mask == 1.0].mean())
    assert oracle_acc > 0.6                       # far above 1/7 chance

    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths, seed=7,
                          halo_staleness=1, halo_delta=True, sync_every=10)
    d = make_train_data(plan, feats, labels, train_mask=train_mask,
                        eval_mask=test_mask)
    tr.run_epochs(d, int(epochs * 1.5))           # bounded extra-epoch budget
    _, acc = tr.evaluate(d)
    assert acc >= oracle_acc - 0.05, (acc, oracle_acc)


def test_delta_wire_is_bf16_feature_only(erplan):
    """The delta cache ships bf16 on the FEATURE wire; the gradient
    exchange keeps f32 (its own ``halo_dtype`` lever) — so the lowered
    stale step carries BOTH a bf16 and an f32 all_to_all."""
    plan, feats, labels = erplan
    tr = FullBatchTrainer(plan, fin=16, widths=[8, 4], seed=2,
                          halo_staleness=1, halo_delta=True)
    d = make_train_data(plan, feats, labels)
    d = type(d)(**shard_stacked(tr.mesh, vars(d)))
    txt = tr._step_stale.lower(
        tr.params, tr.opt_state, tr.halo_carry, tr.pa, d.h0, d.labels,
        d.train_valid).as_text()
    a2a_types = re.findall(
        r'"?stablehlo\.all_to_all"?.*?->\s*tensor<[0-9x]*(f32|bf16)>', txt)
    assert a2a_types, "no all_to_all in lowered stale step?"
    assert set(a2a_types) == {"bf16", "f32"}, a2a_types

    # with halo_dtype='bfloat16' the gradient wire narrows too
    tr2 = FullBatchTrainer(plan, fin=16, widths=[8, 4], seed=2,
                           halo_staleness=1, halo_delta=True,
                           halo_dtype="bfloat16")
    txt2 = tr2._step_stale.lower(
        tr2.params, tr2.opt_state, tr2.halo_carry, tr2.pa, d.h0, d.labels,
        d.train_valid).as_text()
    a2a_types2 = re.findall(
        r'"?stablehlo\.all_to_all"?.*?->\s*tensor<[0-9x]*(f32|bf16)>', txt2)
    assert set(a2a_types2) == {"bf16"}, a2a_types2


def test_delta_numerics_track_exact(erplan, exact_losses):
    """bf16 delta accumulation quantizes only boundary rows — training must
    track the exact trainer to bf16-wire tolerance over several steps."""
    plan, feats, labels = erplan
    d = make_train_data(plan, feats, labels)
    tr = FullBatchTrainer(plan, fin=16, widths=[8, 4], seed=2,
                          halo_staleness=1, halo_delta=True, sync_every=2)
    l_d = [tr.step(d) for _ in range(6)]
    np.testing.assert_allclose(l_d, exact_losses[:6], rtol=1e-2, atol=1e-2)


def test_stale_carry_shapes_follow_exchange_widths(erplan):
    """The plan's carry-shape helper mirrors the forward's project-first
    exchanged widths, and the delta baseline matches the send buffer."""
    from sgcn_tpu.models.gcn import exchange_widths

    plan, *_ = erplan
    fin, widths = 300, [64, 4]          # wide input → project-first layer 0
    shapes = plan.stale_carry_shapes(fin, widths, delta=True)
    fs = exchange_widths(fin, widths)
    assert fs[0] == 64                  # projected before the exchange
    assert shapes["halos"] == [(plan.r, f) for f in fs]
    assert shapes["ghalos"] == shapes["halos"]
    assert shapes["bases"] == [(plan.k, plan.s, f) for f in fs]
    nd = plan.stale_carry_shapes(fin, widths, delta=False)
    assert nd["bases"] == [(1, 1, 1)] * len(fs)


def test_stale_mode_gating(erplan):
    """Invalid knob combinations fail loudly at construction."""
    plan, *_ = erplan
    with pytest.raises(ValueError, match="halo_staleness"):
        FullBatchTrainer(plan, fin=16, widths=[8, 4], halo_staleness=2)
    with pytest.raises(ValueError, match="requires halo_staleness"):
        FullBatchTrainer(plan, fin=16, widths=[8, 4], halo_delta=True)
    with pytest.raises(ValueError, match="requires halo_staleness"):
        FullBatchTrainer(plan, fin=16, widths=[8, 4], sync_every=4)
    with pytest.raises(ValueError, match="GCN hot path"):
        FullBatchTrainer(plan, fin=16, widths=[8, 4], model="gat",
                         halo_staleness=1)
    with pytest.raises(ValueError, match="f32 non-remat"):
        FullBatchTrainer(plan, fin=16, widths=[8, 4], halo_staleness=1,
                         compute_dtype="bfloat16")


def test_stale_rejects_asymmetric_plan():
    """The stale custom backward assumes Â = Âᵀ; an asymmetric plan must be
    rejected, not silently mis-trained."""
    import scipy.sparse as sp

    n, k = 60, 4
    rng = np.random.default_rng(0)
    a = sp.csr_matrix((rng.random((n, n)) < 0.1).astype(np.float32))
    a.setdiag(0)
    a.eliminate_zeros()
    pv = balanced_random_partition(n, k, seed=1)
    plan = build_comm_plan(a, pv, k)
    assert not plan.symmetric
    with pytest.raises(ValueError, match="asymmetric"):
        FullBatchTrainer(plan, fin=8, widths=[4, 3], halo_staleness=1)
