"""Composed stale × ragged mode (``--comm-schedule ragged --halo-staleness
1``): the round-structured stale carry on the per-round ppermute ring
(``ops/pspmm.py::pspmm_stale_ragged``) — both perf levers at once
(PipeGCN-complete, ROADMAP open item 1).

Contract pinned here (docs/comm_schedule.md, docs/stale_halo.md):

  * ``sync_every=1`` composed training is f32-BIT-identical to the dense
    exact path on the cora fixture — losses AND parameters ``==`` (the
    fresh fold chains the PR-4 ragged parity through the stale carry);
  * the composed stale run is finite, tracks exact training, books its
    exchanges hidden/exposed like the dense stale mode, and the fused
    ``run_epochs`` path reproduces per-step ``step()``;
  * the carry shapes are ROUND-STRUCTURED (``(Σ_d S_d, f)`` ring receive
    buffers, delta baseline on the same envelope — not ``(k, S, f)``);
  * the ``--halo-delta`` sync step re-bases on an f32 wire, so delta +
    ``sync_every=1`` is ALSO exact (drift resets to zero, not to one bf16
    rounding);
  * drift gauges gain the per-round staleness-age vector and the wire
    gauges (rows, lane-weighted bytes, per-step itemsize split) reconcile
    EXACTLY between ``CommStats`` and the obs event stream;
  * ``auto`` under staleness switches to the wire-byte-only rule (the
    hidden exchange makes the latency threshold moot) and the decision log
    lands in the run manifest.
"""

import os

import numpy as np
import pytest

from sgcn_tpu.io.datasets import load_npz_dataset
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition.emit import read_partvec
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")

WIDTHS = [16, 7]


@pytest.fixture(scope="module")
def cora():
    """The committed cora-format fixture + its 4-way hp partvec."""
    a, feats, labels = load_npz_dataset(os.path.join(FIX, "cora_like.npz"))
    ahat = normalize_adjacency(a)
    pv = read_partvec(os.path.join(FIX, "cora_like.4.hp"))
    plan = build_comm_plan(ahat, pv, 4)
    return plan, feats.astype(np.float32), labels.astype(np.int32)


@pytest.fixture(scope="module")
def exact_run(cora):
    """Dense exact-path reference: 4 losses + the trained parameters —
    shared by the bit-identity and the delta-rebase assertions (one
    compile for the module)."""
    plan, feats, labels = cora
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=3)
    d = make_train_data(plan, feats, labels)
    losses = [tr.step(d) for _ in range(4)]
    return losses, [np.asarray(w) for w in tr.params]


def test_composed_sync1_bit_identical_to_dense_exact(cora, exact_run):
    """THE acceptance contract: (ragged, staleness=1, sync_every=1) trains
    cora with losses and parameters exactly equal to the dense exact
    path's — every step consumes the fresh ring receives through the same
    round-order fold, so the PR-4 bit-parity chain survives the carry."""
    plan, feats, labels = cora
    exact_losses, exact_params = exact_run
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=3,
                          comm_schedule="ragged", halo_staleness=1,
                          sync_every=1)
    assert tr.comm_schedule == "ragged" and tr.halo_staleness == 1
    d = make_train_data(plan, feats, labels)
    lc = [tr.step(d) for _ in range(4)]
    assert lc == exact_losses                        # bitwise, not allclose
    for wa, wb in zip(exact_params, tr.params):
        np.testing.assert_array_equal(wa, np.asarray(wb))


@pytest.mark.slow
def test_composed_run_epochs_parity(cora):
    """The fused on-device epoch loop threads the ROUND-STRUCTURED carry
    through its fori body exactly like per-step ``step()`` dispatch —
    losses and CommStats booking agree (slow: compiles a second composed
    trainer plus the multi-step program; the per-step contracts run tier-1
    in test_composed_telemetry_tracks_books_and_reconciles)."""
    plan, feats, labels = cora
    d = make_train_data(plan, feats, labels)
    kw = dict(fin=feats.shape[1], widths=WIDTHS, seed=3,
              comm_schedule="ragged", halo_staleness=1, sync_every=3)
    tr_a = FullBatchTrainer(plan, **kw)
    la = [tr_a.step(d) for _ in range(4)]
    tr_b = FullBatchTrainer(plan, **kw)
    lb = tr_b.run_epochs(d, 4)
    np.testing.assert_allclose(lb, la, rtol=2e-4, atol=1e-5)
    assert tr_b.stats.report() == tr_a.stats.report()


def test_round_structured_carry_shapes(cora):
    """The schedule-aware carry contract: ragged carries are round-major
    ring receive buffers at the exchanged widths; the delta baseline rides
    the same (Σ_d S_d, f) envelope instead of the dense (k, S, f) pad; an
    un-built ragged layout fails loudly; the dense branch is unchanged."""
    from sgcn_tpu.models.gcn import exchange_widths

    plan, feats, labels = cora
    plan.ensure_ragged()
    fin, widths = 300, [64, 4]          # wide input → project-first layer 0
    fs = exchange_widths(fin, widths)
    st = max(1, sum(plan.rr_sizes))
    shapes = plan.stale_carry_shapes(fin, widths, delta=True,
                                     comm_schedule="ragged")
    assert shapes["halos"] == [(st, f) for f in fs]
    assert shapes["ghalos"] == shapes["halos"]
    assert shapes["bases"] == [(st, f) for f in fs]
    nd = plan.stale_carry_shapes(fin, widths, delta=False,
                                 comm_schedule="ragged")
    assert nd["bases"] == [(1, 1)] * len(fs)
    # dense branch keeps the PR-2 contract
    dense = plan.stale_carry_shapes(fin, widths, delta=True)
    assert dense["halos"] == [(plan.r, f) for f in fs]
    assert dense["bases"] == [(plan.k, plan.s, f) for f in fs]
    # un-built layout fails loudly (round sizes ARE the carry layout)
    fresh = build_comm_plan(
        normalize_adjacency(load_npz_dataset(
            os.path.join(FIX, "cora_like.npz"))[0]),
        read_partvec(os.path.join(FIX, "cora_like.4.hp")), 4)
    with pytest.raises(ValueError, match="ensure_ragged"):
        fresh.stale_carry_shapes(fin, widths, comm_schedule="ragged")


def test_delta_sync_rebase_is_exact(cora, exact_run):
    """The f32 re-base contract: with --halo-delta, every sync step ships
    the full f32 row and resets BOTH ends exactly — so delta at
    sync_every=1 is bit-identical to the exact path (drift resets to zero,
    not to one bf16 rounding), composed mode included."""
    plan, feats, labels = cora
    exact_losses, _ = exact_run
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=3,
                          comm_schedule="ragged", halo_staleness=1,
                          halo_delta=True, sync_every=1)
    d = make_train_data(plan, feats, labels)
    ld = [tr.step(d) for _ in range(4)]
    assert ld == exact_losses                        # bitwise, not allclose


def test_composed_telemetry_tracks_books_and_reconciles(cora, tmp_path,
                                                        exact_run):
    """Composed staleness-1 with a periodic sync, ONE telemetry trainer
    (tier-1 budget: this single run carries the tracking, booking AND
    reconciliation contracts): training is finite and tracks the exact
    path; CommStats books sync steps exposed / stale steps hidden with the
    RAGGED wire gauges; the report and the obs event stream agree EXACTLY
    on wire accounting — rows, bytes (cumulative totals at per-step
    itemsize resolution), efficiency, schedule; the drift block carries
    the per-round staleness-age vector; scripts/obs_report.py renders it."""
    from sgcn_tpu.obs import RunRecorder, load_run

    plan, feats, labels = cora
    exact_losses, _ = exact_run
    d = make_train_data(plan, feats, labels)
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS, seed=3,
                          comm_schedule="ragged", halo_staleness=1,
                          sync_every=3)
    rec = RunRecorder(str(tmp_path), config={"model": "gcn"})
    tr.attach_recorder(rec)
    losses = [tr.step(d) for _ in range(4)]
    rec.close()

    # finite, and tracking the exact trajectory under bounded staleness
    assert np.all(np.isfinite(losses))
    assert abs(losses[-1] - exact_losses[-1]) < 5e-2
    rep = tr.stats.report()
    nl = tr.nlayers
    assert rep["comm_schedule"] == "ragged"
    assert rep["exchanges"] == 4 * 2 * nl
    assert rep["exposed_exchanges"] == 2 * 2 * nl     # sync at steps 0 and 3
    assert rep["hidden_exchanges"] == 2 * 2 * nl
    assert rep["wire_rows_per_exchange"] == \
        plan.wire_rows_per_exchange("ragged")
    assert rep["wire_rows_per_exchange"] < plan.wire_rows_per_exchange("a2a")

    log = load_run(str(tmp_path))
    # the schedule-selection decision log landed in the manifest
    dec = log.manifest["comm_schedule"]
    assert dec["resolved"] == "ragged" and dec["rule"] == "explicit"

    steps = log.steps()
    assert len(steps) == 4
    tot_true = tot_wire = 0
    for ev in steps:
        comm, roof, drift = ev["comm"], ev["roofline"], ev["drift"]
        assert comm["comm_schedule"] == roof["comm_schedule"] == "ragged"
        assert comm["wire_rows_per_exchange"] == \
            roof["halo_wire_rows_per_exchange"]
        assert comm["padding_efficiency"] == roof["padding_efficiency"]
        assert comm["halo_bytes_true_per_step"] == \
            roof["halo_bytes_true_per_step"]
        assert comm["halo_bytes_wire_per_step"] == \
            roof["halo_bytes_wire_per_step"]
        assert roof["halo_bytes_wire_per_step"] >= \
            roof["halo_bytes_true_per_step"]
        tot_true += roof["halo_bytes_true_per_step"]
        tot_wire += roof["halo_bytes_wire_per_step"]
        # hidden steps report exposed_comm_frac 0, sync steps 1
        assert roof["exposed_comm_frac"] == \
            (1.0 if drift["sync_step"] else 0.0)
        # per-round staleness-age vector: one entry per ring round, age 0
        # on sync steps, the staleness age on stale steps, null for empty
        ra = drift["round_age"]
        assert len(ra) == len(plan.rr_sizes)
        for sd, age in zip(plan.rr_sizes, ra):
            if sd == 0:
                assert age is None
            else:
                assert age == (0 if drift["sync_step"]
                               else drift["staleness_age"])
    # cumulative byte totals reconcile with the event-sum EXACTLY
    last = steps[-1]["comm"]
    rep = tr.stats.report()
    assert last["halo_bytes_true_total"] == tot_true == \
        rep["halo_bytes_true_total"]
    assert last["halo_bytes_wire_total"] == tot_wire == \
        rep["halo_bytes_wire_total"]

    # the report renderer shows the round-age line
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(os.path.dirname(FIX), "..",
                                   "scripts", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.render(str(tmp_path))
    assert "round ages (ragged ring)" in out


def test_per_step_wire_itemsize_split(cora):
    """The attribution itemsize split (satellite contract), host-side only:
    under --halo-delta the stale-step feature wire is bf16 and the sync
    (re-base) step's is FULL f32 — regardless of --halo-dtype, which
    governs the gradient wire alone.  The cost model per step kind and
    CommStats' count_step override must agree exactly."""
    plan, feats, _ = cora
    lane = None
    for hd in (None, "bfloat16"):
        tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS,
                              seed=3, comm_schedule="ragged",
                              halo_staleness=1, halo_delta=True,
                              halo_dtype=hd, sync_every=2)
        if lane is None:
            lane = sum(tr.stats.lane_widths)
        rows = int(plan.predicted_send_volume.sum())
        bwd = 2 if hd == "bfloat16" else 4
        sync = tr._step_cost_model(sync_step=True)
        stale = tr._step_cost_model(sync_step=False)
        # sync: f32 re-base fwd + halo_dtype bwd; stale: bf16 fwd
        assert sync.halo_bytes_true_per_step == rows * lane * (4 + bwd)
        assert stale.halo_bytes_true_per_step == rows * lane * (2 + bwd)
        # CommStats books the same figures step by step
        tr.stats.count_step(nlayers=2, hidden=False, wire_itemsize=4)
        assert tr.stats.halo_bytes_true_total == rows * lane * (4 + bwd)
        tr.stats.count_step(nlayers=2, hidden=True)
        assert tr.stats.halo_bytes_true_total == \
            rows * lane * (4 + bwd) + rows * lane * (2 + bwd)


def test_auto_under_staleness_uses_wire_rule(cora):
    """'auto' + staleness switches to the wire-byte-only rule: the hidden
    exchange takes the k−1 ring dispatches off the critical path, so
    ragged wins whenever it ships fewer wire rows (which the k−1 < k round
    structure guarantees on any supported plan) — and the decision log
    names the rule."""
    from sgcn_tpu.parallel.plan import resolve_comm_schedule

    plan, feats, _ = cora
    dec = {}
    got = resolve_comm_schedule("auto", [plan], "gcn", halo_staleness=1,
                                decision=dec)
    assert got == "ragged"
    assert "wire-byte rule" in dec["rule"]
    assert dec["wire_rows_ragged"] < dec["wire_rows_a2a"]
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS,
                          halo_staleness=1, comm_schedule="auto")
    assert tr.comm_schedule == "ragged"
    assert tr.halo_staleness == 1


def test_composed_gating(cora):
    """The REAL remaining unsupported combos still fail loudly — the
    staleness gates (GAT, asymmetric, bf16/remat) apply under the ragged
    schedule exactly as under the dense one."""
    import dataclasses

    plan, feats, _ = cora
    with pytest.raises(ValueError, match="GCN hot path"):
        FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS,
                         model="gat", comm_schedule="ragged",
                         halo_staleness=1)
    with pytest.raises(ValueError, match="f32 non-remat"):
        FullBatchTrainer(plan, fin=feats.shape[1], widths=WIDTHS,
                         comm_schedule="ragged", halo_staleness=1,
                         compute_dtype="bfloat16")
    aplan = dataclasses.replace(plan, symmetric=False)
    with pytest.raises(ValueError, match="asymmetric"):
        FullBatchTrainer(aplan, fin=feats.shape[1], widths=WIDTHS,
                         comm_schedule="ragged", halo_staleness=1)
