"""Serving subsystem tests (tier-1): ``sgcn_tpu/serve/``.

The contracts pinned here:

  * **router ownership** — every vertex routes to the chip owning its plan
    row (the plan's relabeling IS the routing table);
  * **forward parity** — the AOT-compiled serve program's logits are
    f32-BIT-identical (``==``) to the trainer's ``evaluate()``/``predict``
    path on the cora fixture, for GCN and GAT under BOTH comm schedules
    (the shared ``resolve_forward_setup`` is what makes this hold — a
    drifted second copy of the selection rules would break it here first);
  * **bucket/no-recompile** — pre-compiled padded batch-size buckets serve
    every batch size without a runtime compile (``compile_count`` pinned);
  * **deadline batching** — the micro-batcher flushes on max-batch OR the
    oldest query's latency budget, deterministically (injected clock);
  * **checkpoint provenance** — a wrong-plan / wrong-config restore fails
    with a clear message at load (the PR-8 satellite), never as a deep
    tree-shape error or a cleanly-restored wrong model;
  * **serve telemetry** — the schema-v3 ``serve`` event round-trips through
    ``RunRecorder``/``load_run`` and rejects quantile inversions, and the
    CLI (``python -m sgcn_tpu.serve``) produces a loadable run directory.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "fixtures")

from conftest import er_graph  # noqa: E402
from sgcn_tpu.io.datasets import load_npz_dataset  # noqa: E402
from sgcn_tpu.parallel import build_comm_plan  # noqa: E402
from sgcn_tpu.partition import balanced_random_partition  # noqa: E402
from sgcn_tpu.partition.emit import read_partvec  # noqa: E402
from sgcn_tpu.prep import normalize_adjacency  # noqa: E402
from sgcn_tpu.serve import (MicroBatcher, ServeEngine, VertexRouter,  # noqa: E402
                            default_buckets, run_loadgen,
                            synthetic_query_ids)
from sgcn_tpu.train import FullBatchTrainer, make_train_data  # noqa: E402
from sgcn_tpu.utils.checkpoint import save_checkpoint  # noqa: E402


@pytest.fixture(scope="module")
def cora():
    """The committed cora-format fixture under its 4-part hp partition —
    the dataset the parity acceptance criterion names."""
    a, feats, labels = load_npz_dataset(os.path.join(FIX, "cora_like.npz"))
    ahat = normalize_adjacency(a)
    pv = read_partvec(os.path.join(FIX, "cora_like.4.hp"))
    plan = build_comm_plan(ahat, pv, 4)
    return {"plan": plan, "feats": np.asarray(feats, np.float32),
            "labels": labels, "widths": [16, 7]}


@pytest.fixture(scope="module")
def tiny():
    """48-vertex plan for the cheap mechanical tests."""
    ahat = normalize_adjacency(er_graph())
    pv = balanced_random_partition(48, 4, seed=0)
    plan = build_comm_plan(ahat, pv, 4)
    feats = np.random.default_rng(0).standard_normal((48, 8)).astype(
        np.float32)
    labels = (np.arange(48) % 3).astype(np.int32)
    return {"plan": plan, "feats": feats, "labels": labels,
            "widths": [8, 3]}


# ---------------------------------------------------------------- router
def test_router_ownership_matches_plan(cora):
    plan = cora["plan"]
    router = VertexRouter(plan)
    qids = np.arange(plan.n)
    owners, locals_ = router.lookup(qids)
    np.testing.assert_array_equal(owners, plan.owner)
    np.testing.assert_array_equal(locals_, plan.local_idx)
    groups = router.route(np.arange(0, plan.n, 7))
    for chip, ids in groups.items():
        assert (plan.owner[ids] == chip).all()
    # every grouped id appears exactly once
    allids = np.concatenate(list(groups.values()))
    np.testing.assert_array_equal(np.sort(allids), np.arange(0, plan.n, 7))
    with pytest.raises(ValueError, match="out of range"):
        router.lookup([plan.n])


# ----------------------------------------------------------------- parity
@pytest.mark.parametrize("model,sched", [
    ("gcn", "a2a"), ("gcn", "ragged"),
    ("gat", "a2a"), ("gat", "ragged"),
])
def test_forward_parity_bit_identical(cora, model, sched, tmp_path):
    """Serve logits ``==`` trainer evaluate/predict logits (f32 bit
    identity) on the cora fixture — the acceptance criterion.  The gcn/a2a
    case additionally round-trips through a real checkpoint (training
    steps + provenance-verified engine load); the others share params
    directly, which pins the same program-level parity without re-paying
    the optimizer compile per config."""
    plan, feats, labels = cora["plan"], cora["feats"], cora["labels"]
    widths = cora["widths"]
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths,
                          model=model, comm_schedule=sched,
                          activation="none" if model == "gat" else "relu",
                          seed=1)
    data = make_train_data(plan, feats, labels)
    if (model, sched) == ("gcn", "a2a"):
        for _ in range(2):
            tr.step(data)
        ckpt = save_checkpoint(tr, str(tmp_path / "ckpt.npz"), step=2)
        eng = ServeEngine(plan, fin=feats.shape[1], widths=widths,
                          model=model, comm_schedule=sched, checkpoint=ckpt,
                          max_batch=plan.n, buckets=(plan.n,))
        assert eng.checkpoint_meta["step"] == 2
    else:
        import jax
        eng = ServeEngine(plan, fin=feats.shape[1], widths=widths,
                          model=model, comm_schedule=sched,
                          params=jax.tree.map(np.asarray, tr.params),
                          max_batch=plan.n, buckets=(plan.n,))
    eng.set_features(feats)
    expected = tr.predict(data).astype(np.float32)     # eval-path logits
    got = eng.query(np.arange(plan.n))
    assert got.dtype == np.float32
    assert np.array_equal(got, expected), (
        f"{model}/{sched}: serve logits differ from evaluate() "
        f"(max |diff| {np.abs(got - expected).max()})")
    # a shuffled sub-batch returns the same rows, in query order
    sel = np.random.default_rng(0).permutation(plan.n)[:17]
    np.testing.assert_array_equal(eng.query(sel), expected[sel])


# ----------------------------------------------------- buckets / recompile
def test_bucket_ladder_and_no_recompile(tiny):
    plan, feats = tiny["plan"], tiny["feats"]
    assert default_buckets(16) == (1, 2, 4, 8, 16)
    assert default_buckets(12) == (1, 2, 4, 8, 12)
    eng = ServeEngine(plan, fin=feats.shape[1], widths=tiny["widths"],
                      max_batch=8, buckets=(2, 8))
    eng.set_features(feats)
    assert eng.compile_count == 2          # every bucket pre-compiled
    for nq in (1, 2, 3, 8, 5, 2, 8):
        out = eng.query(np.arange(nq))
        assert out.shape == (nq, tiny["widths"][-1])
    assert eng.compile_count == 2, (
        "a served batch size triggered a recompile — the bucket contract "
        "is exactly that no query count may")
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        eng.batcher.bucket_for(9)
    g = eng.gauges()
    assert g["compiles"] == 2
    assert g["wire_rows_per_batch"] == 2 * plan.wire_rows_per_exchange(
        eng.comm_schedule)


def test_batcher_deadline_and_full_flush():
    """Deterministic deadline semantics on an injected clock: flush fires
    on max-batch immediately, else once the OLDEST pending query has
    waited the budget."""
    now = [0.0]
    b = MicroBatcher(max_batch=3, latency_budget_ms=100.0, buckets=(1, 3),
                     clock=lambda: now[0])
    assert b.submit(1) is None
    assert b.poll() is None                      # budget not reached
    now[0] = 0.05
    assert b.poll() is None
    assert b.submit(2) is None
    now[0] = 0.1                                 # head is 100 ms old
    flushed = b.poll()
    assert [p.qid for p in flushed] == [1, 2]
    assert b.deadline_flushes == 1 and b.full_flushes == 0
    # max-batch flush: third submit returns the batch synchronously
    assert b.submit(3) is None
    assert b.submit(4) is None
    flushed = b.submit(5)
    assert [p.qid for p in flushed] == [3, 4, 5]
    assert b.full_flushes == 1
    assert len(b) == 0 and b.flush() is None
    with pytest.raises(ValueError, match="below max_batch"):
        MicroBatcher(max_batch=8, buckets=(1, 4))


# ---------------------------------------------------------------- loadgen
class _FakeEngine:
    """Deterministic engine stand-in: executing a batch takes a fixed
    simulated service time on the injected clock."""

    def __init__(self, batcher, clock_box, service_s=0.01):
        self.batcher = batcher
        self._clock = clock_box
        self._service = service_s
        self.batches = []

    def query(self, qids):
        self._clock[0] += self._service
        self.batches.append(list(qids))
        return np.zeros((len(qids), 2), np.float32)


def test_loadgen_open_loop_latency_accounting():
    """Open loop on a fake clock: arrivals on the offered schedule, flushes
    by max-batch, latency measured from the SCHEDULED arrival (queue time
    counts)."""
    now = [0.0]

    def clock():
        return now[0]

    def sleep(dt):
        now[0] += dt

    b = MicroBatcher(max_batch=4, latency_budget_ms=1000.0, buckets=(4,),
                     clock=clock)
    eng = _FakeEngine(b, now, service_s=0.01)
    res = run_loadgen(eng, np.arange(8), offered_qps=100.0,
                      clock=clock, sleep=sleep)
    assert res.queries == 8
    assert res.batches == 2 and res.batch_sizes == [4, 4]
    assert b.full_flushes == 2 and b.deadline_flushes == 0
    # batch 1 executes at t=0.03 (arrival of q3) + 0.01 service = 0.04;
    # q0 arrived at t=0 → 40 ms, q3 at t=0.03 → 10 ms
    assert res.latencies_ms[0] == pytest.approx(40.0)
    assert res.latencies_ms[3] == pytest.approx(10.0)
    assert res.p99_ms >= res.p95_ms >= res.p50_ms > 0
    assert res.achieved_qps > 0


def test_loadgen_deadline_drains_partial_batch():
    """An OPEN-loop trickle below max-batch must still complete within
    ~the budget: the deadline flush serves it (the server cannot know the
    trace ended)."""
    now = [0.0]

    def clock():
        return now[0]

    def sleep(dt):
        now[0] += dt

    b = MicroBatcher(max_batch=8, latency_budget_ms=50.0, buckets=(8,),
                     clock=clock)
    eng = _FakeEngine(b, now, service_s=0.001)
    res = run_loadgen(eng, np.arange(3), offered_qps=1000.0,
                      clock=clock, sleep=sleep)
    assert res.queries == 3
    assert b.deadline_flushes == 1          # budget fired, not max-batch
    # head waited exactly its 50 ms budget + 1 ms service
    assert max(res.latencies_ms) == pytest.approx(51.0)


def test_loadgen_closed_loop_tail_drains_immediately():
    """The CLOSED-loop tail is an ordinary flush, not a budget wait: the
    generator knows no further query is coming, so waiting out the
    latency budget would deflate the ceiling QPS the probe publishes."""
    now = [0.0]

    def clock():
        return now[0]

    def sleep(dt):
        now[0] += dt

    b = MicroBatcher(max_batch=8, latency_budget_ms=50.0, buckets=(8,),
                     clock=clock)
    eng = _FakeEngine(b, now, service_s=0.001)
    res = run_loadgen(eng, np.arange(3), offered_qps=None,
                      clock=clock, sleep=sleep)
    assert res.queries == 3 and res.batches == 1
    assert b.deadline_flushes == 0 and b.full_flushes == 0
    # no budget wait anywhere in the window: just the one service time
    assert res.window_s == pytest.approx(0.001)
    assert max(res.latencies_ms) == pytest.approx(1.0)


def test_batcher_shed_split_is_explicit_and_counted():
    """Deadline shedding (PR-13, docs/resilience.md): a flushed query whose
    age already exceeds budget × shed_factor at dispatch is returned as an
    explicit shed marker — never served, never a silent p99 outlier."""
    now = [0.0]
    b = MicroBatcher(max_batch=4, latency_budget_ms=100.0, buckets=(4,),
                     clock=lambda: now[0], shed_factor=2.0)
    b.submit(1, t_arrival=0.0)          # will be 0.25 s old: past 2×budget
    b.submit(2, t_arrival=0.2)          # 0.05 s old: within budget
    now[0] = 0.25
    keep, shed = b.split_shed(b.flush())
    assert [p.qid for p in keep] == [2]
    assert [p.qid for p in shed] == [1]
    assert b.shed_count == 1
    # no shed_factor → pre-existing behavior: everything dispatches
    b2 = MicroBatcher(max_batch=4, latency_budget_ms=100.0, buckets=(4,),
                      clock=lambda: now[0])
    b2.submit(1, t_arrival=0.0)
    keep, shed = b2.split_shed(b2.flush())
    assert [p.qid for p in keep] == [1] and shed == []
    assert b2.shed_count == 0
    # shedding below the deadline flush itself is rejected loudly
    with pytest.raises(ValueError, match="shed_factor"):
        MicroBatcher(max_batch=4, buckets=(4,), shed_factor=0.5)


def test_loadgen_sheds_overdue_queries_out_of_quantiles():
    """The loadgen path: shed queries are counted in ``ServeResult.shed``
    (and the serve-event ``shed`` key) but excluded from the served count
    and every latency quantile — under overload the published p99
    describes queries that were actually answered."""
    now = [0.0]

    def clock():
        return now[0]

    def sleep(dt):
        now[0] += dt

    # service time far above the arrival spacing: an open-loop overload.
    # budget 10 ms, shed_factor 2 → anything older than 20 ms at dispatch
    # sheds instead of blowing the tail.
    b = MicroBatcher(max_batch=2, latency_budget_ms=10.0, buckets=(2,),
                     clock=clock, shed_factor=2.0)
    eng = _FakeEngine(b, now, service_s=0.1)
    res = run_loadgen(eng, np.arange(6), offered_qps=1000.0,
                      clock=clock, sleep=sleep)
    assert res.shed > 0
    assert res.queries + res.shed == 6
    # every SERVED latency beat the shed cutoff at its dispatch; the shed
    # ones would have been >= 20 ms and appear in no quantile
    assert res.queries == len(res.latencies_ms)
    assert res.summary()["shed"] == res.shed


def test_synthetic_query_ids_range_and_skew():
    q = synthetic_query_ids(100, 500, seed=1)
    assert q.min() >= 0 and q.max() < 100
    qs = synthetic_query_ids(100, 500, seed=1, skew=1.2)
    assert qs.min() >= 0 and qs.max() < 100
    # a power-law draw concentrates: its top vertex count dominates uniform's
    assert np.bincount(qs).max() > np.bincount(q).max()


# ----------------------------------------------------- checkpoint provenance
def test_checkpoint_digest_mismatch_raises(tiny, tmp_path):
    plan, feats, labels = tiny["plan"], tiny["feats"], tiny["labels"]
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=tiny["widths"],
                          seed=0)
    ckpt = save_checkpoint(tr, str(tmp_path / "c.npz"))
    other_pv = balanced_random_partition(48, 4, seed=9)
    other = build_comm_plan(normalize_adjacency(er_graph()), other_pv, 4)
    with pytest.raises(ValueError, match="plan digest mismatch"):
        ServeEngine(other, fin=feats.shape[1], widths=tiny["widths"],
                    checkpoint=ckpt, precompile=False)
    with pytest.raises(ValueError, match="model config mismatch"):
        ServeEngine(plan, fin=feats.shape[1], widths=[16, 3],
                    checkpoint=ckpt, precompile=False)
    # activation is part of the served function: the same params under a
    # different activation would serve different logits — must fail loudly
    with pytest.raises(ValueError, match="mismatch on 'activation'"):
        ServeEngine(plan, fin=feats.shape[1], widths=tiny["widths"],
                    activation="none", checkpoint=ckpt, precompile=False)
    # the matching plan+config loads (and records the saved step)
    eng = ServeEngine(plan, fin=feats.shape[1], widths=tiny["widths"],
                      checkpoint=ckpt, precompile=False, max_batch=8)
    assert eng.checkpoint_meta["plan_digest"] is not None


# ------------------------------------------------------------- telemetry
def test_serve_event_schema_roundtrip(tmp_path):
    from sgcn_tpu.obs import RunRecorder, load_run
    from sgcn_tpu.obs.schema import validate_event

    with RunRecorder(str(tmp_path), run_kind="serve") as rec:
        rec.record_serve(queries=100, achieved_qps=42.5,
                         latency_p50_ms=3.0, latency_p95_ms=9.0,
                         latency_p99_ms=12.0, mode="open", offered_qps=50.0,
                         batches=10, mean_batch=10.0, compiles=0,
                         buckets=[1, 8], comm_schedule="ragged",
                         wire_rows_per_query=12.5)
    log = load_run(str(tmp_path))
    (sv,) = log.serves()
    assert sv["achieved_qps"] == 42.5 and sv["comm_schedule"] == "ragged"
    # quantile inversion is a writer bug the schema rejects
    bad = dict(sv, latency_p50_ms=20.0)
    with pytest.raises(ValueError, match="quantiles out of order"):
        validate_event(bad)
    # the serve kind is v3-only: a v2 stream must not carry it
    with pytest.raises(ValueError, match="unknown event kind"):
        validate_event(dict(sv, v=2))


def test_serve_cli_smoke(tmp_path):
    """End-to-end CLI on the committed cora fixture: closed-loop window,
    one-line JSON with measured provenance, loadable run directory with a
    serve event, rendered by obs_report."""
    rundir = str(tmp_path / "run")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # let -b cpu set its own device count
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, "-m", "sgcn_tpu.serve",
         "--npz", os.path.join(FIX, "cora_like.npz"), "--normalize",
         "-p", os.path.join(FIX, "cora_like.4.hp"),
         "-b", "cpu", "-s", "4", "--random-init",
         "-l", "2", "--hidden", "16",
         "--qps", "0", "--queries", "24", "--max-batch", "8",
         "--buckets", "8", "--latency-budget-ms", "100",
         "--metrics-out", rundir],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=420)
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["metric"] == "serve_qps" and rep["measured"] is True
    assert rep["value"] > 0 and rep["queries"] == 24
    assert rep["latency_p50_ms"] <= rep["latency_p99_ms"]
    assert rep["compiles"] == 1          # one bucket, zero runtime compiles
    from sgcn_tpu.obs import load_run
    log = load_run(rundir)
    (sv,) = log.serves()
    assert sv["queries"] == 24 and sv["mode"] == "closed"
    assert sv["compiles"] == 1
    spans = {e["name"] for e in log.events if e["kind"] == "span"}
    assert {"serve:route", "serve:batch", "serve:compile_lookup",
            "serve:forward"} <= spans
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         rundir],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "serve windows: 1" in out.stdout
    assert "no-recompile contract" in out.stdout
