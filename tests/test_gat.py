"""Distributed GAT vs dense single-device GAT oracle (SURVEY.md §4 strategy)."""

import numpy as np
import pytest

from sgcn_tpu.baselines.gat_oracle import DenseGATOracle
from sgcn_tpu.models.gat import init_gat_params
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.train import FullBatchTrainer, make_train_data

K = 4


@pytest.fixture(scope="module")
def setup(ahat):
    n = ahat.shape[0]
    rng = np.random.default_rng(7)
    partvec = balanced_random_partition(n, K, seed=3)
    plan = build_comm_plan(ahat, partvec, K)
    feats = rng.standard_normal((n, 12)).astype(np.float32)
    labels = (rng.integers(0, 4, n)).astype(np.int32)
    return plan, feats, labels


def test_gat_forward_parity(ahat, setup):
    plan, feats, labels = setup
    widths = [8, 4]
    tr = FullBatchTrainer(plan, fin=12, widths=widths, model="gat",
                          activation="none", final_activation="none", seed=5)
    oracle = DenseGATOracle(ahat, fin=12, widths=widths,
                            activation="none", final_activation="none", seed=5)
    data = make_train_data(plan, feats, labels)
    got = tr.predict(data)
    want = oracle.predict(feats)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_gat_training_parity(ahat, setup):
    plan, feats, labels = setup
    widths = [8, 4]
    tr = FullBatchTrainer(plan, fin=12, widths=widths, model="gat",
                          activation="none", lr=0.01, seed=5)
    oracle = DenseGATOracle(ahat, fin=12, widths=widths,
                            activation="none", lr=0.01, seed=5)
    data = make_train_data(plan, feats, labels)
    dist_losses = [tr.step(data) for _ in range(6)]
    oracle_losses = oracle.fit(feats, labels, epochs=6)
    np.testing.assert_allclose(dist_losses, oracle_losses, rtol=2e-3, atol=2e-4)
    assert dist_losses[-1] < dist_losses[0]


def test_gat_elu_variant_runs(ahat, setup):
    plan, feats, labels = setup
    tr = FullBatchTrainer(plan, fin=12, widths=[8, 4], model="gat",
                          activation="elu", seed=0)
    data = make_train_data(plan, feats, labels)
    losses = [tr.step(data) for _ in range(4)]
    assert np.isfinite(losses).all()


def test_gat_params_shapes():
    import jax
    params = init_gat_params(jax.random.PRNGKey(0), [(12, 8), (8, 4)])
    assert params[0]["w"].shape == (12, 8)
    assert params[0]["a1"].shape == (8,)
    assert params[1]["a2"].shape == (4,)


def test_edge_softmax_matches_dense():
    """The COO-edge-list softmax helper must equal a dense masked softmax."""
    import jax.numpy as jnp
    from sgcn_tpu.models.gat import edge_softmax
    rng = np.random.default_rng(5)
    n, deg = 12, 4
    dst = np.repeat(np.arange(n), deg).astype(np.int32)
    src = rng.integers(0, n, size=n * deg).astype(np.int32)
    scores = rng.standard_normal(n * deg).astype(np.float32)
    mask = rng.random(n * deg) < 0.8          # some padding edges
    alpha = np.asarray(edge_softmax(jnp.asarray(scores), jnp.asarray(mask),
                                    jnp.asarray(dst), n))
    dense = np.full((n, n * deg), -np.inf)
    dense[dst[mask], np.arange(n * deg)[mask]] = scores[mask]
    with np.errstate(invalid="ignore"):
        ref = np.exp(dense - dense.max(axis=1, keepdims=True))
        ref = np.nan_to_num(ref / np.maximum(ref.sum(axis=1, keepdims=True),
                                             1e-9))
    np.testing.assert_allclose(alpha, ref[dst, np.arange(n * deg)],
                               rtol=1e-5, atol=1e-6)


def test_gat_sym_backward_matches_autodiff(ahat):
    """The gather-only symmetric backward must produce the same gradients as
    JAX's mechanical transpose of the streaming forward."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from sgcn_tpu.models.gat import (GAT_PLAN_FIELDS, gat_layer_local,
                                     gat_layer_sym)
    from sgcn_tpu.parallel import make_mesh_1d, shard_stacked
    from sgcn_tpu.partition import balanced_random_partition

    n, k, fin, fout = ahat.shape[0], 4, 6, 5
    plan = build_comm_plan(ahat, balanced_random_partition(n, k, seed=3), k)
    plan.ensure_cell()
    assert plan.symmetric
    mesh = make_mesh_1d(k)
    rng = np.random.default_rng(2)
    h = rng.standard_normal((n, fin)).astype(np.float32)
    params = init_gat_params(jax.random.PRNGKey(1), [(fin, fout)])[0]
    hb = shard_stacked(mesh, plan.scatter_rows(h))
    pa = shard_stacked(mesh, {f: getattr(plan, f) for f in GAT_PLAN_FIELDS})

    def make(layer):
        def per_chip(pa, h):
            pa = jax.tree.map(lambda x: x[0], pa)

            def obj(w, a1, a2, hl):
                out = layer(w, a1, a2, hl, pa["send_idx"], pa["halo_src"],
                            pa["cell_idx"], pa["cell_w"], pa["ctail_dst"],
                            pa["ctail_src"], pa["ctail_w"],
                            pa["row_valid"], plan.cell_buckets, "v")
                # per-chip LOCAL objective: grad conventions for a psum'd
                # objective w.r.t. replicated closure params differ across
                # jax versions (the 0.4.37 transpose inflates k×); the local
                # form is convention-independent, and per-chip partial grads
                # are exactly the trainer's contract (fullbatch psums them)
                return jnp.sum(out * jnp.cos(out * 0.3))

            g = jax.grad(obj, argnums=(0, 1, 2, 3))(
                params["w"], params["a1"], params["a2"], h[0])
            return jax.tree.map(lambda x: x[None], g)

        fn = jax.jit(jax.shard_map(per_chip, mesh=mesh,
                                   in_specs=(P("v"), P("v")),
                                   out_specs=P("v")))
        return fn(pa, hb)

    g_auto = make(gat_layer_local)
    g_sym = make(gat_layer_sym)
    # Param grads are per-chip PARTIALS on both paths (the trainer completes
    # them with its explicit psum); compare the chip-summed totals.
    for ga, gs, name in zip(g_auto[:3], g_sym[:3], ("w", "a1", "a2")):
        np.testing.assert_allclose(np.asarray(gs).sum(axis=0),
                                   np.asarray(ga).sum(axis=0),
                                   rtol=2e-4, atol=2e-5, err_msg=name)
    # dh is vertex-sharded (no replication), so it must match per chip
    np.testing.assert_allclose(np.asarray(g_sym[3]), np.asarray(g_auto[3]),
                               rtol=2e-4, atol=2e-5, err_msg="h")


def test_gat_bf16_packed_tracks_f32(ahat):
    """bf16 compute takes the bit-packed one-gather-per-edge aggregation;
    trajectory must track the f32 path within bf16 tolerance."""
    n = ahat.shape[0]
    rng = np.random.default_rng(6)
    feats = rng.standard_normal((n, 8)).astype(np.float32)
    labels = rng.integers(0, 3, size=n).astype(np.int32)
    pv = balanced_random_partition(n, 4, seed=5)
    plan = build_comm_plan(ahat, pv, 4)
    from sgcn_tpu.train import make_train_data
    data = make_train_data(plan, feats, labels)
    # widths even (packing pairs lanes); seed shared
    f32 = FullBatchTrainer(plan, fin=8, widths=[6, 3 + 1], seed=2,
                           model="gat", activation="none")
    b16 = FullBatchTrainer(plan, fin=8, widths=[6, 3 + 1], seed=2,
                           model="gat", activation="none",
                           compute_dtype="bfloat16")
    l32 = [f32.step(data) for _ in range(5)]
    l16 = [b16.step(data) for _ in range(5)]
    np.testing.assert_allclose(l16, l32, rtol=0.05, atol=0.03)
    assert l16[-1] < l16[0]
    # odd layer width: falls back to the two-pass form, which must keep the
    # exchange table in the compute dtype (not silently promote to f32)
    odd = FullBatchTrainer(plan, fin=8, widths=[6, 3], seed=2,
                           model="gat", activation="none",
                           compute_dtype="bfloat16")
    lo = [odd.step(data) for _ in range(3)]
    assert np.isfinite(lo).all() and lo[-1] < lo[0]
