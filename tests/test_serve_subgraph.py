"""Sub-graph serving tests (tier-1): ``sgcn_tpu/serve/subgraph.py`` +
engine ``mode='subgraph'`` (docs/serving.md phase 2).

The contracts pinned here:

  * **routed-logit bit-parity** — the compact L-hop receptive-set forward's
    logits are f32-BIT-identical (``==``) to the trainer's
    ``evaluate()``/``predict`` path on the cora fixture, for GCN and GAT
    under BOTH comm schedules (the per-row fold recipes reproduce each
    owner chip's addition sequence exactly; the GAT stabilizers arrive
    precomputed);
  * **no-recompile across growth** — the doubling-ladder shape keys mean a
    repeated traffic sweep (any query count, any receptive-set size seen
    before) never compiles again: ``compile_count`` pinned over a replayed
    sweep;
  * **weight hot-swap** — ``swap_weights`` verifies provenance (plan
    digest + model config) BEFORE touching engine state, swaps with ZERO
    re-compiles (``compile_count`` pinned), bumps ``weights_rev``, and the
    served logits flip to the new checkpoint's bit-exact values;
  * **checkpoint watch** — ``--watch-checkpoint-dir``'s poller picks up
    the newest intact checkpoint from a PR-13 rotation directory once per
    flush window;
  * **concurrent dispatch** — ``submit``/``result`` double-buffering
    returns the same bits as sequential ``query`` calls, in order, and the
    concurrent loadgen accounts deterministically on an injected clock;
  * **telemetry** — the v5 ``swap`` event round-trips and older streams
    reject it; serve events carry the sub-graph gauges.
"""

import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "fixtures")

from conftest import er_graph  # noqa: E402
from sgcn_tpu.io.datasets import load_npz_dataset  # noqa: E402
from sgcn_tpu.parallel import build_comm_plan  # noqa: E402
from sgcn_tpu.partition import balanced_random_partition  # noqa: E402
from sgcn_tpu.partition.emit import read_partvec  # noqa: E402
from sgcn_tpu.prep import normalize_adjacency  # noqa: E402
from sgcn_tpu.serve import (MicroBatcher, ServeEngine,  # noqa: E402
                            SubgraphIndex, run_loadgen)
from sgcn_tpu.train import FullBatchTrainer, make_train_data  # noqa: E402
from sgcn_tpu.utils.checkpoint import save_checkpoint  # noqa: E402


@pytest.fixture(scope="module")
def cora():
    a, feats, labels = load_npz_dataset(os.path.join(FIX, "cora_like.npz"))
    ahat = normalize_adjacency(a)
    pv = read_partvec(os.path.join(FIX, "cora_like.4.hp"))
    plan = build_comm_plan(ahat, pv, 4)
    return {"plan": plan, "feats": np.asarray(feats, np.float32),
            "labels": labels, "widths": [16, 7]}


@pytest.fixture(scope="module")
def tiny():
    ahat = normalize_adjacency(er_graph())
    pv = balanced_random_partition(48, 4, seed=0)
    plan = build_comm_plan(ahat, pv, 4)
    feats = np.random.default_rng(0).standard_normal((48, 8)).astype(
        np.float32)
    labels = (np.arange(48) % 3).astype(np.int32)
    return {"plan": plan, "feats": feats, "labels": labels,
            "widths": [8, 3]}


# ----------------------------------------------------------------- parity
@pytest.mark.parametrize("model,sched,halo_dtype", [
    ("gcn", "a2a", None), ("gcn", "ragged", None),
    ("gat", "a2a", None), ("gat", "ragged", None),
    # the third audited serve_subgraph mode: the bf16 wire round-trip on
    # remote-sourced contributions must mirror the full exchange's cast
    # placement exactly, or == breaks only in the narrowed configuration
    ("gcn", "a2a", "bfloat16"),
])
def test_subgraph_parity_bit_identical(cora, model, sched, halo_dtype):
    """The acceptance criterion: sub-graph routed logits ``==`` the
    trainer's eval-path logits for every (model, schedule, wire-dtype)
    combination — across several batch shapes, so multiple receptive-set
    buckets are exercised."""
    import jax

    plan, feats, labels = cora["plan"], cora["feats"], cora["labels"]
    widths = cora["widths"]
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths,
                          model=model, comm_schedule=sched,
                          halo_dtype=halo_dtype,
                          activation="none" if model == "gat" else "relu",
                          seed=1)
    data = make_train_data(plan, feats, labels)
    expected = tr.predict(data).astype(np.float32)
    eng = ServeEngine(plan, fin=feats.shape[1], widths=widths, model=model,
                      comm_schedule=sched, halo_dtype=halo_dtype,
                      activation="none" if model == "gat" else "relu",
                      params=jax.tree.map(np.asarray, tr.params),
                      max_batch=32, mode="subgraph")
    eng.set_features(feats)
    rng = np.random.default_rng(0)
    for nq in (1, 5, 17, 32):
        sel = rng.permutation(plan.n)[:nq]
        got = eng.query(sel)
        assert got.dtype == np.float32
        assert np.array_equal(got, expected[sel]), (
            f"{model}/{sched}: sub-graph logits differ from evaluate() at "
            f"nq={nq} (max |diff| {np.abs(got - expected[sel]).max()})")
    g = eng.gauges()
    assert g["serve_mode"] == "subgraph"
    # query-proportionality on the fixture itself: the receptive sets are
    # far below the k·B rows the full forward computes per batch
    assert 0 < g["touched_rows_per_query"] < g["full_rows_per_forward"]
    assert 0 < g["subgraph_flops_per_query"] < g["full_forward_flops"]


# ----------------------------------------------------- buckets / recompile
def test_subgraph_no_recompile_across_replayed_growth(tiny):
    """The doubling-ladder contract, on BOTH axes at once: a sweep that
    grows the query count AND (via hub-adjacent queries) the receptive-set
    size compiles its shape keys once — replaying the whole sweep compiles
    nothing."""
    plan, feats = tiny["plan"], tiny["feats"]
    eng = ServeEngine(plan, fin=feats.shape[1], widths=tiny["widths"],
                      max_batch=8, buckets=(2, 8), mode="subgraph")
    eng.set_features(feats)
    rng = np.random.default_rng(1)
    sweep = [rng.integers(0, plan.n, size=nq) for nq in
             (1, 2, 3, 5, 8, 2, 8, 1)]
    outs = [eng.query(q) for q in sweep]
    warm = eng.compile_count
    assert warm > 0
    replay = [eng.query(q) for q in sweep]
    assert eng.compile_count == warm, (
        "replaying an already-served sweep recompiled — the ladder "
        "contract is that no seen (query count, receptive size) may")
    for a, b in zip(outs, replay):
        np.testing.assert_array_equal(a, b)
    # the gauges expose the ladder: every compiled key is recorded
    assert len(eng.gauges()["buckets"]) == warm


def test_subgraph_index_receptive_sets(tiny):
    """The receptive helper itself: 0 hops = the queries; each hop adds
    exactly the recipe neighbors (closed neighborhood, sorted, deduped)."""
    plan = tiny["plan"]
    idx = SubgraphIndex(plan, "gcn")
    q = np.array([3, 7])
    r0 = idx.receptive(q, 0)
    np.testing.assert_array_equal(r0, np.unique(q))
    r1 = idx.receptive(q, 1)
    r2 = idx.receptive(q, 2)
    assert set(r0) <= set(r1) <= set(r2)
    assert (np.sort(r2) == r2).all()
    # 1-hop closure agrees with the adjacency matrix
    ahat = normalize_adjacency(er_graph())
    dense = ahat.toarray()
    nbrs = set(q.tolist())
    for v in q:
        nbrs |= set(np.nonzero(dense[v])[0].tolist())
    assert set(r1) == nbrs


# ---------------------------------------------------------------- hot-swap
def test_hot_swap_provenance_and_pinned_compiles(tiny, tmp_path):
    plan, feats, labels = tiny["plan"], tiny["feats"], tiny["labels"]
    widths = tiny["widths"]
    data = make_train_data(plan, feats, labels)
    tr_a = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths, seed=0)
    tr_b = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths, seed=9)
    tr_b.step(data)
    ckpt_a = save_checkpoint(tr_a, str(tmp_path / "a.npz"), step=0)
    ckpt_b = save_checkpoint(tr_b, str(tmp_path / "b.npz"), step=1)
    exp_a = tr_a.predict(data).astype(np.float32)
    exp_b = tr_b.predict(data).astype(np.float32)

    eng = ServeEngine(plan, fin=feats.shape[1], widths=widths,
                      checkpoint=ckpt_a, max_batch=8, mode="subgraph")
    eng.set_features(feats)
    sel = np.arange(0, plan.n, 5)[:8]
    np.testing.assert_array_equal(eng.query(sel), exp_a[sel])
    warm = eng.compile_count
    assert eng.weights_rev == 0

    # provenance rejection BEFORE any state change: wrong plan digest
    other = build_comm_plan(normalize_adjacency(er_graph()),
                            balanced_random_partition(48, 4, seed=9), 4)
    tr_o = FullBatchTrainer(other, fin=feats.shape[1], widths=widths,
                            seed=0)
    ckpt_o = save_checkpoint(tr_o, str(tmp_path / "o.npz"))
    with pytest.raises(ValueError, match="plan digest mismatch"):
        eng.swap_weights(ckpt_o)
    # wrong model config
    tr_w = FullBatchTrainer(plan, fin=feats.shape[1], widths=[16, 3],
                            seed=0)
    ckpt_w = save_checkpoint(tr_w, str(tmp_path / "w.npz"))
    with pytest.raises(ValueError, match="model config mismatch"):
        eng.swap_weights(ckpt_w)
    assert eng.weights_rev == 0 and eng.compile_count == warm
    np.testing.assert_array_equal(eng.query(sel), exp_a[sel])

    # the real swap: zero recompiles, bumped rev, bit-exact new logits
    meta = eng.swap_weights(ckpt_b)
    assert meta["step"] == 1
    assert eng.weights_rev == 1
    got = eng.query(sel)
    assert eng.compile_count == warm, (
        "swap_weights recompiled — params are AOT-program inputs and the "
        "swap must be zero re-lowering by contract")
    np.testing.assert_array_equal(got, exp_b[sel])


def test_hot_swap_refreshes_gat_stabilizers(tiny, tmp_path):
    """The GAT-specific swap hazard: the per-layer stabilizers are a
    function of (params, features), so a swap that kept the old cg values
    would break bit-parity — the engine must recompute them."""
    import jax

    plan, feats, labels = tiny["plan"], tiny["feats"], tiny["labels"]
    widths = tiny["widths"]
    data = make_train_data(plan, feats, labels)
    tr_a = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths,
                            model="gat", activation="none", seed=0)
    tr_b = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths,
                            model="gat", activation="none", seed=7)
    ckpt_b = save_checkpoint(tr_b, str(tmp_path / "b.npz"), step=1)
    exp_b = tr_b.predict(data).astype(np.float32)
    eng = ServeEngine(plan, fin=feats.shape[1], widths=widths, model="gat",
                      activation="none",
                      params=jax.tree.map(np.asarray, tr_a.params),
                      max_batch=8, mode="subgraph")
    eng.set_features(feats)
    sel = np.arange(8)
    eng.query(sel)                      # warm under revision 0
    old_cg = eng._stabilizers.copy()
    eng.swap_weights(ckpt_b)
    assert not np.array_equal(eng._stabilizers, old_cg)
    np.testing.assert_array_equal(eng.query(sel), exp_b[sel])


def test_watch_checkpoint_dir_hot_swaps(tiny, tmp_path):
    """The ``--watch-checkpoint-dir`` machinery: a rotation directory grows
    a newer checkpoint; the next flush window's poll swaps it in; corrupt
    newest falls back to the previous intact one."""
    from sgcn_tpu.resilience.checkpoint import CheckpointManager

    plan, feats, labels = tiny["plan"], tiny["feats"], tiny["labels"]
    widths = tiny["widths"]
    data = make_train_data(plan, feats, labels)
    mgr = CheckpointManager(str(tmp_path / "ckpts"), keep_last=3)
    tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths, seed=0)
    p0 = mgr.save(tr, 0)
    eng = ServeEngine(plan, fin=feats.shape[1], widths=widths,
                      checkpoint=p0, max_batch=8, mode="subgraph")
    eng.set_features(feats)
    eng.attach_checkpoint_watch(str(tmp_path / "ckpts"))
    sel = np.arange(6)
    eng.query(sel)
    assert eng.weights_rev == 0        # nothing newer than the loaded step

    tr.step(data)
    mgr.save(tr, 1)
    exp1 = tr.predict(data).astype(np.float32)
    got = eng.query(sel)               # poll at this flush window swaps
    assert eng.weights_rev == 1
    np.testing.assert_array_equal(got, exp1[sel])

    # a corrupt newest checkpoint is skipped with a warning; the engine
    # keeps serving the last intact revision
    tr.step(data)
    p2 = mgr.save(tr, 2)
    with open(p2, "r+b") as fh:
        fh.seek(100)
        fh.write(b"\xff\xff\xff\xff")
    with pytest.warns(RuntimeWarning, match="corrupt"):
        eng.query(sel)
    assert eng.weights_rev == 1


# -------------------------------------------------------------- concurrent
def test_concurrent_submit_matches_sequential(tiny):
    """Double-buffered dispatch returns the sequential path's exact bits,
    in submission order — including with two batches in flight back to
    back."""
    plan, feats = tiny["plan"], tiny["feats"]
    eng = ServeEngine(plan, fin=feats.shape[1], widths=tiny["widths"],
                      max_batch=8, mode="subgraph")
    eng.set_features(feats)
    rng = np.random.default_rng(3)
    batches = [rng.integers(0, plan.n, size=nq) for nq in (3, 8, 1, 5)]
    sequential = [eng.query(b) for b in batches]
    handles = [eng.submit(b) for b in batches]       # all in flight
    for h, exp in zip(handles, sequential):
        np.testing.assert_array_equal(h.result(), exp)


def test_concurrent_loadgen_deterministic_accounting():
    """``run_loadgen(concurrent=True)`` on an injected clock: every query
    served exactly once, in order, with the double-buffer draining its
    tail; a batch's latency ends when ITS result is consumed (after the
    next submit), so the figures are deterministic and slightly larger
    than the sequential path's — the honest accounting."""
    now = [0.0]

    def clock():
        return now[0]

    def sleep(dt):
        now[0] += dt

    class _Handle:
        def __init__(self, eng, batch):
            self._eng, self._batch = eng, batch

        def result(self):
            now[0] += self._eng._service       # the blocking wait
            self._eng.resolved.append([p.qid for p in self._batch])
            return np.zeros((len(self._batch), 2), np.float32)

    class _AsyncFake:
        def __init__(self, batcher, service_s=0.01):
            self.batcher = batcher
            self._service = service_s
            self.submitted, self.resolved = [], []

        def submit(self, qids):
            self.submitted.append(list(qids))
            return _Handle(self, self.batcher._last_flushed)

    b = MicroBatcher(max_batch=4, latency_budget_ms=1000.0, buckets=(4,),
                     clock=clock)
    eng = _AsyncFake(b, service_s=0.01)

    # run_loadgen hands Pending batches to execute(); the fake handle needs
    # them for latency bookkeeping, so remember the last flush
    orig_take = b._take

    def take():
        out = orig_take()
        b._last_flushed = out
        return out

    b._take = take
    res = run_loadgen(eng, np.arange(8), offered_qps=100.0,
                      clock=clock, sleep=sleep, concurrent=True)
    assert res.queries == 8
    assert res.batches == 2 and res.batch_sizes == [4, 4]
    assert eng.submitted == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert eng.resolved == [[0, 1, 2, 3], [4, 5, 6, 7]]
    # batch 1 submitted at t=0.03, resolved only after batch 2 is in
    # flight (t=0.07) + its own 10 ms wait → q0's latency is 80 ms; batch 2
    # drains from the tail at t=0.09 → q4 (arrived 0.04) waited 50 ms
    assert res.latencies_ms[0] == pytest.approx(80.0)
    assert res.latencies_ms[4] == pytest.approx(50.0)


# ------------------------------------------------------------- telemetry
def test_swap_event_schema_roundtrip(tmp_path):
    from sgcn_tpu.obs import RunRecorder, load_run
    from sgcn_tpu.obs.schema import validate_event

    with RunRecorder(str(tmp_path), run_kind="serve") as rec:
        rec.record_swap(path="ckpt_00000002.npz", weights_rev=2,
                        checkpoint_step=2, wall_s=0.5)
        rec.record_serve(queries=10, achieved_qps=5.0, latency_p50_ms=1.0,
                         latency_p95_ms=2.0, latency_p99_ms=3.0,
                         serve_mode="subgraph", weights_rev=2,
                         touched_rows_per_query=6.5,
                         subgraph_flops_per_query=1234.0)
    log = load_run(str(tmp_path))
    (sw,) = [e for e in log.events if e["kind"] == "swap"]
    assert sw["weights_rev"] == 2 and sw["checkpoint_step"] == 2
    (sv,) = log.serves()
    assert sv["serve_mode"] == "subgraph"
    assert sv["touched_rows_per_query"] == 6.5
    # the swap kind is v5-only: an older stream must not carry it
    with pytest.raises(ValueError, match="unknown event kind"):
        validate_event(dict(sw, v=4))
    with pytest.raises(ValueError, match="non-finite/negative"):
        validate_event(dict(sw, weights_rev=-1))
    with pytest.raises(ValueError, match="serve_mode"):
        validate_event(dict(sv, serve_mode="cached"))


def test_serve_window_carries_subgraph_gauges(tiny, tmp_path):
    """record_window on a sub-graph engine emits the v5 serve-event keys
    and the analytic gauges reconcile with the engine's accumulators."""
    from sgcn_tpu.obs import RunRecorder, load_run
    from sgcn_tpu.serve.loadgen import ServeResult

    plan, feats = tiny["plan"], tiny["feats"]
    eng = ServeEngine(plan, fin=feats.shape[1], widths=tiny["widths"],
                      max_batch=8, mode="subgraph")
    eng.set_features(feats)
    with RunRecorder(str(tmp_path), run_kind="serve") as rec:
        eng.attach_recorder(rec)
        eng.query(np.arange(8))
        res = ServeResult(latencies_ms=[1.0] * 8, window_s=1.0, batches=1,
                          batch_sizes=[8])
        eng.record_window(res, mode="open")
    log = load_run(str(tmp_path))
    (sv,) = log.serves()
    g = eng.gauges()
    assert sv["serve_mode"] == "subgraph" and sv["weights_rev"] == 0
    assert sv["touched_rows_per_query"] == g["touched_rows_per_query"]
    assert sv["subgraph_flops_per_query"] == g["subgraph_flops_per_query"]
    assert sv["wire_rows_per_query"] == g["wire_rows_per_query"]
