"""Tier-1 gate for the static-analysis subsystem (``sgcn_tpu/analysis``).

Three layers of assurance, in one module:

  * the **matrix audit at HEAD** — every supported mode's real program
    lowers clean against its plan-derived expectation (collective census,
    wire dtype/shape, no host callbacks, donation), including the banded
    fixture that pins empty-round ELISION and the bf16-wire contract
    across every schedule × staleness combination (the PR-9 satellite:
    previously only numerically implied);
  * **mutation checks** — each rule class provably FAILS on a seeded
    violation (an f32 wire under a bf16 config, a doubled collective, a
    smuggled host callback, dropped donation, host time in traced code,
    an unregistered consumer tuple, an unenumerated mode flag).  A lint
    that cannot fail is decoration; these tests are the no-vacuous-lint
    acceptance criterion;
  * **parser units** — the shared HLO parser (``analysis.hlo``) against
    synthetic StableHLO / scheduled-HLO snippets, since both the auditor
    and ``tests/test_overlap_hlo.py`` ride it.

The module-scoped ``full_report`` fixture runs the whole matrix ONCE
(~75 s at HEAD — inside the tier-1 per-test budget, charged to the first
test that uses it); everything else asserts against that one report.
"""

import importlib

import pytest

from sgcn_tpu.analysis import hlo
from sgcn_tpu.analysis.ast_rules import (rule_consumer_registered,
                                         rule_mode_flag_enumerated,
                                         rule_sanctioned_sync_only,
                                         rule_traced_host_free,
                                         run_ast_pass)
from sgcn_tpu.analysis.hlo_audit import audit_mode, audit_plan, run_audit
from sgcn_tpu.analysis.modes import (Mode, is_supported, supported_modes,
                                     train_matrix_verdicts)


@pytest.fixture(scope="module")
def full_report():
    return run_audit()


def _violations(entry):
    return [v for prog in entry["programs"].values()
            for v in prog["violations"]]


def _rules_hit(entry):
    return {v["rule"] for v in _violations(entry)}


# ------------------------------------------------------------ matrix @ HEAD
def test_full_matrix_clean_at_head(full_report):
    """Acceptance criterion: the auditor covers the full supported mode
    matrix and every census/dtype/shape/donation check passes at HEAD."""
    bad = {mid: _violations(e) for mid, e in full_report["modes"].items()
           if not e["ok"]}
    assert full_report["ok"] and not bad, bad
    assert full_report["n_modes"] == len(full_report["modes"])


def test_matrix_covers_the_advertised_axes(full_report):
    """gcn/gat × a2a/ragged × staleness 0/1 × f32/bf16, plus serve buckets
    and the mini-batch envelope — the coverage the issue names, pinned as
    specific mode ids so a silently narrowed enumerator fails here."""
    ids = set(full_report["modes"])
    for required in (
            "train/gcn/a2a/s0/f32", "train/gcn/a2a/s0/bf16",
            "train/gcn/ragged/s0/f32", "train/gcn/ragged/s0/bf16",
            "train/gcn/a2a/s1/f32", "train/gcn/a2a/s1/bf16",
            "train/gcn/ragged/s1/f32", "train/gcn/ragged/s1/bf16",
            "train/gcn/a2a/s1/f32/delta", "train/gcn/ragged/s1/bf16/delta",
            "train/gat/a2a/fused", "train/gat/a2a/split",
            "train/gat/a2a/packed", "train/gat/ragged/fused",
            "train/gat/ragged/split", "train/gat/ragged/packed",
            "serve/gcn/a2a/s0/f32", "serve/gcn/ragged/s0/bf16",
            "serve/gat/a2a/fused", "serve/gat/ragged/fused",
            "minibatch/gcn/ragged/s0/f32",
            "train/gcn/a2a/s0/f32/rep", "train/gcn/a2a/s0/bf16/rep",
            "train/gcn/ragged/s0/f32/rep", "train/gcn/ragged/s0/bf16/rep",
            "train/gcn/a2a/s1/f32/rep", "train/gcn/a2a/s1/bf16/rep",
            "train/gcn/ragged/s1/f32/rep", "train/gcn/ragged/s1/bf16/rep",
            "train/gcn/ragged/s0/f32@banded",
            "train/gcn/ragged/s1/f32@banded",
            "train/gcn/ragged/s1/f32/rep@banded",
            # the schedule-/model-agnostic Pallas kernel family (ISSUE 15)
            "train/gcn/a2a/s0/f32/pallas", "train/gcn/a2a/s0/bf16/pallas",
            "train/gcn/ragged/s0/f32/pallas",
            "train/gcn/ragged/s0/bf16/pallas",
            "train/gat/a2a/fused/pallas", "train/gat/a2a/split/pallas",
            "train/gat/ragged/fused/pallas",
            "train/gat/ragged/split/pallas",
            "train/gcn/ragged/s0/f32/pallas@banded"):
        assert required in ids, f"mode {required} missing from the audit"


def test_stale_modes_audit_both_programs(full_report):
    """Every pipelined mode lowers BOTH its stale and full-sync programs —
    the f32 delta re-base is a sync-step-only wire contract."""
    for mid, entry in full_report["modes"].items():
        if "/s1/" in mid:
            assert set(entry["programs"]) == {"stale", "sync"}, mid


def test_replica_modes_audit_both_programs_and_shrink_the_wire(full_report):
    """Every replica mode lowers BOTH its replica and refresh programs,
    and the replica program's compiled wire is STRICTLY smaller than the
    refresh program's (the acceptance contract: replicated rows excluded
    from the send buckets show up as smaller static wire shapes, via
    CommPlan.wire_buffer_shapes(replica=True)).  The clean matrix entry
    already pins the exact shapes; this pins the strict shrink so a
    degenerate fixture (replicas that shrink nothing) cannot make the
    rule vacuous."""
    plan = audit_plan()
    plan.ensure_ragged()
    from sgcn_tpu.analysis.hlo_audit import AUDIT_REPLICA_B
    plan.ensure_replicas(AUDIT_REPLICA_B)
    assert plan.nrep_s < plan.s
    assert sum(plan.nrep_rr_sizes) < sum(plan.rr_sizes)
    for mid, entry in full_report["modes"].items():
        if mid.endswith("/rep") and "/s1/" in mid:
            # the COMPOSED replica × stale modes lower the stale/sync
            # program pair (the stale carry subsumes the replica tables);
            # the shrunken-wire contract is the stale program's census
            assert set(entry["programs"]) == {"stale", "sync"}, mid
            continue
        if mid.endswith("/rep"):
            assert set(entry["programs"]) == {"rep", "sync"}, mid
            # same dispatch COUNTS (no round became empty at this budget),
            # strictly smaller buffers — the shape check inside the census
            # asserted the exact values already
            c_rep = entry["programs"]["rep"]["census"]
            c_sync = entry["programs"]["sync"]["census"]
            kind = ("collective_permute" if "/ragged/" in mid
                    else "all_to_all")
            assert c_rep[kind] > 0 and c_sync[kind] > 0, mid


def test_empty_rounds_elided_in_census(full_report):
    """The banded fixture keeps 2 of k−1 ring rounds; the compiled ragged
    program must carry collective_permutes for EXACTLY the live rounds.
    Exact mode: 3 exchanges (2 fwd + 1 bwd — aggregate-first layer 0's
    backward exchange is dead code) × 2 live rounds; stale mode: 4
    exchanges × 2."""
    from sgcn_tpu.ops.pspmm import ragged_live_rounds

    live = ragged_live_rounds(audit_plan("banded").ragged_round_sizes())
    assert len(live) == 2
    exact = full_report["modes"]["train/gcn/ragged/s0/f32@banded"]
    assert exact["programs"]["step"]["census"]["collective_permute"] == 6
    stale = full_report["modes"]["train/gcn/ragged/s1/f32@banded"]
    for prog in stale["programs"].values():
        assert prog["census"]["collective_permute"] == 8


def test_bf16_wire_contract_every_mode(full_report):
    """The PR-9 satellite: ``--halo-dtype bfloat16`` puts bf16 on EVERY
    ppermute/all_to_all wire operand for a2a/ragged × staleness 0/1 —
    pinned from the audit census (previously only numerically implied by
    loss-tolerance tests).  The one documented exception: a delta-mode
    SYNC step re-bases the feature wire at full f32."""
    for sched in ("a2a", "ragged"):
        for sid in ("s0", "s1"):
            entry = full_report["modes"][f"train/gcn/{sched}/{sid}/bf16"]
            assert entry["ok"]
            for label, prog in entry["programs"].items():
                assert prog["census"]["wire_dtypes"] == ["bf16"], \
                    (sched, sid, label)
        # delta mode: stale steps ship the bf16 increment, the sync step's
        # re-base is the full f32 row — while the grad wire stays bf16
        entry = full_report["modes"][f"train/gcn/{sched}/s1/bf16/delta"]
        assert entry["programs"]["stale"]["census"]["wire_dtypes"] == \
            ["bf16"]
        assert entry["programs"]["sync"]["census"]["wire_dtypes"] == \
            ["bf16", "f32"]
    # serve inherits the same wire lever forward-only
    for sched in ("a2a", "ragged"):
        prog, = full_report["modes"][
            f"serve/gcn/{sched}/s0/bf16"]["programs"].values()
        assert prog["census"]["wire_dtypes"] == ["bf16"]


def test_gat_packed_wire_narrows(full_report):
    """The GAT bf16 wire contract: the packed form ships fout/2+1 f32
    lanes (bit-paired bf16) on EVERY layer — the audit's shape check pins
    it, and the matrix entry being clean means the forward actually does
    it (the audit caught HEAD⁻¹ shipping full-width f32 tables on every
    layer past the first; see models/gat.py gat_forward_local)."""
    for sched in ("a2a", "ragged"):
        assert full_report["modes"][f"train/gat/{sched}/packed"]["ok"]
    from sgcn_tpu.models.gat import gat_table_form
    assert gat_table_form(8, "bfloat16") == "packed"
    assert gat_table_form(8, None) == "fused"


def test_serve_programs_donate_nothing(full_report):
    for mid, entry in full_report["modes"].items():
        if mid.startswith("serve/"):
            for prog in entry["programs"].values():
                assert prog["census"]["donated_args"] == 0, mid


def test_train_programs_donate_params_and_state(full_report):
    """params + opt state (+ stale carries) carry jax.buffer_donor — the
    donation side of the satellite, pinned so it cannot regress."""
    e = full_report["modes"]["train/gcn/a2a/s0/f32"]
    # 2 weight leaves + adam (count, 2×mu, 2×nu)
    assert e["programs"]["step"]["census"]["donated_args"] == 7
    s = full_report["modes"]["train/gcn/a2a/s1/f32"]
    # + carries (2 halos, 2 ghalos minus the dead layer-0 one, 2 bases)
    assert s["programs"]["stale"]["census"]["donated_args"] >= 12


def test_composition_matrix_matches_doc():
    """The enumerator is the machine face of docs/comm_schedule.md's
    composition matrix — these literals ARE that table's support column
    (schedule × staleness × delta × replicas × model); a drift in either
    direction fails here."""
    v = train_matrix_verdicts()
    doc_rows = {
        ("a2a", 0, False, False, "gcn"): True,
        ("a2a", 0, False, False, "gat"): True,
        ("a2a", 1, False, False, "gcn"): True,
        ("a2a", 1, False, False, "gat"): False,
        ("a2a", 1, True, False, "gcn"): True,
        ("a2a", 1, True, False, "gat"): False,
        ("ragged", 0, False, False, "gcn"): True,
        ("ragged", 0, False, False, "gat"): True,
        ("ragged", 1, False, False, "gcn"): True,
        ("ragged", 1, False, False, "gat"): False,
        ("ragged", 1, True, False, "gcn"): True,
        ("ragged", 1, True, False, "gat"): False,
        # delta without staleness is a construction-time error everywhere
        ("a2a", 0, True, False, "gcn"): False,
        ("a2a", 0, True, False, "gat"): False,
        ("ragged", 0, True, False, "gcn"): False,
        ("ragged", 0, True, False, "gat"): False,
        # hot-halo replication: GCN-only; composes with the stale
        # pipeline (PR-12: the stale carry subsumes the replica tables),
        # but not with the delta cache (docs/replication.md)
        ("a2a", 0, False, True, "gcn"): True,
        ("ragged", 0, False, True, "gcn"): True,
        ("a2a", 0, False, True, "gat"): False,
        ("ragged", 0, False, True, "gat"): False,
        ("a2a", 1, False, True, "gcn"): True,
        ("ragged", 1, False, True, "gcn"): True,
        ("a2a", 1, True, True, "gcn"): False,
        ("ragged", 1, True, True, "gcn"): False,
    }
    for key, supported in doc_rows.items():
        assert v[key][0] is supported, (key, v[key])


def test_supported_modes_all_self_consistent():
    for m in supported_modes():
        ok, reason = is_supported(m)
        assert ok, (m, reason)
    ids = [m.mode_id for m in supported_modes()]
    assert len(ids) == len(set(ids)), "duplicate mode ids"


# ------------------------------------------------------------- mutations
def test_mutation_f32_wire_under_bf16_config(monkeypatch):
    """Seeded violation: the exchange silently drops the requested bf16
    wire cast.  The auditor must flag wire-dtype — this is the regression
    class the subsystem exists for."""
    pspmm = importlib.import_module("sgcn_tpu.ops.pspmm")

    real = pspmm.halo_exchange

    def no_narrow(h, send_idx, halo_src, axis_name=pspmm.AXIS,
                  halo_dtype=None):
        return real(h, send_idx, halo_src, axis_name, None)

    monkeypatch.setattr(pspmm, "halo_exchange", no_narrow)
    entry = audit_mode(Mode("train", "gcn", "a2a",
                            halo_dtype="bfloat16"))
    assert not entry["ok"]
    assert "wire-dtype" in _rules_hit(entry)


def test_mutation_extra_collective(monkeypatch):
    """Seeded violation: a doubled all_to_all per exchange (the 'extra
    hidden synchronization' class) must fail the collective census."""
    pspmm = importlib.import_module("sgcn_tpu.ops.pspmm")

    real = pspmm.a2a_or_identity

    def doubled(buf, axis_name):
        return real(real(buf, axis_name), axis_name)

    monkeypatch.setattr(pspmm, "a2a_or_identity", doubled)
    entry = audit_mode(Mode("train", "gcn", "a2a"))
    assert not entry["ok"]
    assert "collective-census" in _rules_hit(entry)


def test_mutation_missing_ragged_round(monkeypatch):
    """Seeded violation: a live ring round's ppermute silently replaced by
    a local identity (rows never cross the wire — shapes and downstream
    folds unchanged, so nothing else notices) — strictly fewer
    collective_permutes than the plan's live rounds must fail the census.
    Note the seeding is in the PROGRAM, not in ``ragged_live_rounds``:
    the elision rule is deliberately single-sourced, so patching the
    helper would move the expectation along with the op."""
    import jax

    pspmm = importlib.import_module("sgcn_tpu.ops.pspmm")

    real = pspmm.ppermute_or_identity

    def dropped(buf, axis_name, d):
        if d == 1:
            (recv,) = jax.lax.optimization_barrier((buf,))
            return recv
        return real(buf, axis_name, d)

    monkeypatch.setattr(pspmm, "ppermute_or_identity", dropped)
    entry = audit_mode(Mode("train", "gcn", "ragged"))
    assert not entry["ok"]
    assert "collective-census" in _rules_hit(entry)


def test_mutation_replica_rows_still_shipped(monkeypatch):
    """Seeded violation for the replica wire rule: the replica step
    silently keeps shipping the FULL buckets (replicated rows never leave
    the wire — numerically indistinguishable because the carry overwrite
    lands the same rows, so only the compiled wire shapes betray it).
    The auditor must flag wire-shape on the 'rep' program — the mutation
    that proves the shrunken-wire expectation is not vacuous."""
    pspmm = importlib.import_module("sgcn_tpu.ops.pspmm")

    real = pspmm._replica_halo

    def full_wire(x, rep, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
                  rep_slots, axis_name, halo_dtype, fresh):
        if not fresh:
            # ship the full exchange, then overwrite replica slots anyway —
            # same halo table bits, un-shrunken wire
            halo = pspmm.halo_exchange(x, send_idx, halo_src, axis_name,
                                       halo_dtype)
            halo = halo.at[rep_slots].set(rep.astype(halo.dtype),
                                          mode="drop")
            return halo, rep
        return real(x, rep, send_idx, halo_src, nrep_send_idx,
                    nrep_halo_src, rep_slots, axis_name, halo_dtype, fresh)

    monkeypatch.setattr(pspmm, "_replica_halo", full_wire)
    entry = audit_mode(Mode("train", "gcn", "a2a", replica=True))
    assert not entry["ok"]
    assert not entry["programs"]["rep"]["ok"]
    assert "wire-shape" in _rules_hit(entry)


def test_mutation_host_callback_in_step(monkeypatch):
    """Seeded violation: a jax.debug.print smuggled into the forward —
    the python-callback custom call must be flagged."""
    import jax

    import sgcn_tpu.models.gcn as gcn

    real = gcn.get_activation

    def chatty(name):
        act = real(name)

        def wrapped(x):
            jax.debug.print("step {}", x.sum())
            return act(x)

        return wrapped

    monkeypatch.setattr(gcn, "get_activation", chatty)
    entry = audit_mode(Mode("train", "gcn", "a2a"))
    assert not entry["ok"]
    assert "host-callback" in _rules_hit(entry)


def test_mutation_dropped_donation(monkeypatch):
    """Seeded violation: donate_argnums stripped from the step compile —
    every params/opt-state argument loses its jax.buffer_donor marker and
    the donation rule must fail (the 'dropped donation' class: the step
    double-buffers every update and nobody notices on a small graph)."""
    import jax

    real_jit = jax.jit

    def undonated_jit(f, *a, **kw):
        kw.pop("donate_argnums", None)
        return real_jit(f, *a, **kw)

    monkeypatch.setattr(jax, "jit", undonated_jit)
    entry = audit_mode(Mode("train", "gcn", "a2a"))
    assert not entry["ok"]
    assert "donation" in _rules_hit(entry)


def test_mutation_ast_host_time_in_traced_module():
    src = "import time\n\ndef f(x):\n    return x * time.time()\n"
    v = rule_traced_host_free("sgcn_tpu/ops/custom.py", src)
    assert v and "time.time" in v[0]
    src = ("import numpy as np\n\ndef f(x):\n"
           "    return x + np.random.default_rng(0).random()\n")
    v = rule_traced_host_free("sgcn_tpu/models/custom.py", src)
    assert v and "np.random" in v[0]
    # aliased spellings — the natural forms of the violation must not slip
    v = rule_traced_host_free(
        "sgcn_tpu/ops/custom.py",
        "import time as t\n\ndef f(x):\n    return x * t.time()\n")
    assert v and "time.time" in v[0]
    v = rule_traced_host_free(
        "sgcn_tpu/models/custom.py",
        "from numpy.random import default_rng\n\ndef f(x):\n"
        "    return x + default_rng(0).random()\n")
    assert v and "numpy.random.default_rng" in v[0]
    # jax.random is traced-safe and must stay clean, aliased or not
    assert not rule_traced_host_free(
        "sgcn_tpu/ops/custom.py",
        "import jax\n\ndef f(k):\n    return jax.random.normal(k, (2,))\n")
    assert not rule_traced_host_free(
        "sgcn_tpu/ops/custom.py",
        "from jax import random\n\ndef f(k):\n"
        "    return random.normal(k, (2,))\n")


def test_mutation_ast_raw_sync_in_step():
    src = ("import jax\n\ndef step(x):\n"
           "    jax.block_until_ready(x)\n    return x\n")
    v = rule_sanctioned_sync_only("sgcn_tpu/train/custom.py", src)
    assert v and "block_until_ready" in v[0]
    v = rule_sanctioned_sync_only(
        "sgcn_tpu/serve/custom.py",
        "import jax\n\ndef g(x):\n    return jax.device_get(x)\n")
    assert v and "device_get" in v[0]


def test_mutation_ast_unregistered_consumer_tuple():
    src = 'MY_NEW_PLAN_FIELDS = ("send_idx", "halo_src")\n'
    v = rule_consumer_registered("sgcn_tpu/models/custom.py", src)
    assert v and "CONSUMER_TUPLE_SOURCES" in v[0]
    # registered names and non-string tuples pass
    assert not rule_consumer_registered(
        "sgcn_tpu/models/custom.py", 'SHAPES = (1, 2)\n')


def test_mutation_ast_unenumerated_mode_flag():
    src = ('import argparse\np = argparse.ArgumentParser()\n'
           'p.add_argument("--halo-compression", default=None)\n')
    v = rule_mode_flag_enumerated({"sgcn_tpu/train/__main__.py": src})
    assert any("--halo-compression" in x for x in v)
    # a trainer CLI missing an enumerated axis is the reverse drift
    assert any("dead matrix axis" in x for x in v)


def test_ast_pass_clean_at_head():
    rep = run_ast_pass()
    assert rep["ok"], rep


# ---------------------------------------------------------------- parsers
_SYNTH_STABLEHLO = """\
module @jit_step attributes {mhlo.num_partitions = 8 : i32} {
  func.func public @main(%arg0: tensor<8x8xf32> {jax.buffer_donor = true, mhlo.sharding = "{replicated}"}, %arg1: tensor<8x10x4xbf16> {mhlo.sharding = "{devices=[8,1,1]<=[8]}"}) -> (tensor<8x8xf32>) {
    %0 = "stablehlo.all_to_all"(%arg1) <{channel_handle = #stablehlo.channel_handle<handle = 1, type = 1>, concat_dimension = 0 : i64, replica_groups = dense<[[0, 1, 2, 3, 4, 5, 6, 7]]> : tensor<1x8xi64>, split_count = 8 : i64, split_dimension = 0 : i64}> : (tensor<8x10x4xbf16>) -> tensor<8x10x4xbf16>
    %1 = "stablehlo.all_reduce"(%arg0) <{channel_handle = #stablehlo.channel_handle<handle = 2, type = 1>, replica_groups = dense<[[0, 1, 2, 3, 4, 5, 6, 7]]> : tensor<1x8xi64>, use_global_device_ids}> ({
    ^bb0(%a: tensor<f32>, %b: tensor<f32>):
      %s = stablehlo.add %a, %b : tensor<f32>
      stablehlo.return %s : tensor<f32>
    }) : (tensor<8x8xf32>) -> tensor<8x8xf32>
    %2 = stablehlo.custom_call @Sharding(%1) : (tensor<8x8xf32>) -> tensor<8x8xf32>
    %3 = stablehlo.custom_call @xla_python_cpu_callback(%2) : (tensor<8x8xf32>) -> tensor<8x8xf32>
    return %3 : tensor<8x8xf32>
  }
}
"""


def test_collective_op_parser_units():
    ops = hlo.collective_ops(_SYNTH_STABLEHLO)
    kinds = [op.kind for op in ops]
    assert kinds == ["all_to_all", "all_reduce"]
    a2a, ar = ops
    assert a2a.wire == ((8, 10, 4), "bf16")
    assert ar.wire == ((8, 8), "f32") and ar.reducer == "add"
    assert hlo.host_callback_targets(_SYNTH_STABLEHLO) == \
        ["xla_python_cpu_callback"]
    assert hlo.unknown_custom_calls(_SYNTH_STABLEHLO) == []
    args = hlo.main_args(_SYNTH_STABLEHLO)
    assert [a.donated for a in args] == [True, False]
    assert args[1].type == ((8, 10, 4), "bf16")
    assert hlo.parse_tensor_type("i32") == ((), "i32")


_SYNTH_SCHEDULED = """\
  %all-to-all-start.1 = ((f32[]), f32[]) all-to-all-start(%x)
  %fusion.1 = f32[] fusion(%y), kind=kLoop
  %fusion.2 = f32[] fusion(%z), kind=kLoop
  %all-to-all-done.1 = f32[] all-to-all-done(%all-to-all-start.1)
  %all-to-all-start.2 = ((f32[]), f32[]) all-to-all-start(%w)
  %all-to-all-done.2 = f32[] all-to-all-done(%all-to-all-start.2)
"""


def test_full_mesh_groups_flags_sub_mesh():
    """The sub-mesh psum census: a reduction over multiple replica groups
    (the realistic printed form of a half-mesh psum, every device still
    named) must fail the full-mesh check; the real single-group form over
    all k devices must pass."""
    from sgcn_tpu.analysis.hlo_audit import _full_mesh_groups

    full = hlo.HloOp(kind="all_reduce", line=0, text=(
        'replica_groups = dense<[[0, 1, 2, 3, 4, 5, 6, 7]]> : '
        'tensor<1x8xi64>, use_global_device_ids'))
    assert _full_mesh_groups(full, 8)
    half = hlo.HloOp(kind="all_reduce", line=0, text=(
        'replica_groups = dense<[[0, 1, 2, 3], [4, 5, 6, 7]]> : '
        'tensor<2x4xi64>, use_global_device_ids'))
    assert not _full_mesh_groups(half, 8)
    small = hlo.HloOp(kind="all_reduce", line=0, text=(
        'replica_groups = dense<[[0, 1, 2, 3]]> : tensor<1x4xi64>'))
    assert not _full_mesh_groups(small, 8)


def test_async_window_parser_units():
    assert hlo.count_async_starts(_SYNTH_SCHEDULED) == 2
    assert hlo.async_windows(_SYNTH_SCHEDULED) == [2, 0]
    with pytest.raises(ValueError, match="unknown start"):
        hlo.async_windows(
            "  %all-to-all-done.9 = f32[] all-to-all-done(%all-to-all-start.9)\n")
    with pytest.raises(ValueError, match="unmatched"):
        hlo.async_windows(
            "  %all-to-all-start.3 = ((f32[]), f32[]) all-to-all-start(%q)\n")


def test_wire_buffer_shapes_helper():
    plan = audit_plan()
    (a2a,) = plan.wire_buffer_shapes("a2a")
    assert a2a == (plan.k, plan.s)
    ragged = plan.wire_buffer_shapes("ragged")
    assert all(len(s) == 1 and s[0] > 0 for s in ragged)
    assert len(ragged) == len([x for x in plan.ragged_round_sizes()
                               if x > 0])
    banded = audit_plan("banded")
    assert len(banded.wire_buffer_shapes("ragged")) == 2
    with pytest.raises(ValueError, match="unknown comm schedule"):
        plan.wire_buffer_shapes("p2p")


def test_live_rounds_helper():
    from sgcn_tpu.ops.pspmm import ragged_live_rounds

    assert ragged_live_rounds((3, 0, 2)) == (1, 3)
    assert ragged_live_rounds(()) == ()
    banded = audit_plan("banded")
    k = banded.k
    assert ragged_live_rounds(banded.ragged_round_sizes()) == (1, k - 1)
