"""Test harness: run every collective on 8 virtual CPU devices.

The reference's de-facto test mode is "cluster on one box": the Gloo backend
puts each rank on ``cpu:<rank>`` (``GPU/PGCN.py:166-169``) so the full
distributed algorithm runs multi-process on one host.  Our equivalent is
multi-device CPU JAX: 8 host platform devices, so every shard_map /
all_to_all / psum in the suite executes a real collective without TPUs.

This must run before JAX initializes a backend, hence top of conftest.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import scipy.sparse as sp  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 budget run (-m 'not slow'); "
        "run the full suite with plain `pytest tests/`")


# Per-test wall-clock budget for NON-slow tests: the tier-1 suite runs under
# one external timeout, and the seed's failure mode was a single unmarked
# test silently eating it (rc=124 with zero diagnostics).  A passing test
# that overruns this budget is turned into a FAILURE naming the fix (mark it
# slow), so the suite can never silently regress back.  0 disables.  The
# static half of the same lint (subprocess-mesh tests must be slow-marked or
# explicitly budgeted) lives in tests/test_collection_lint.py.
TIER1_PER_TEST_BUDGET_S = float(os.environ.get("SGCN_TEST_BUDGET_S", "300"))


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    report = outcome.get_result()
    if (report.when == "call" and report.passed
            and TIER1_PER_TEST_BUDGET_S > 0
            and call.duration > TIER1_PER_TEST_BUDGET_S
            and "slow" not in item.keywords):
        report.outcome = "failed"
        report.longrepr = (
            f"{item.nodeid} took {call.duration:.1f}s, over the "
            f"{TIER1_PER_TEST_BUDGET_S:.0f}s tier-1 per-test budget for "
            "unmarked tests — mark it @pytest.mark.slow (or raise "
            "SGCN_TEST_BUDGET_S if the budget itself is wrong); see "
            "tests/test_collection_lint.py")


def er_graph(n: int = 48, p: float = 0.15, seed: int = 1) -> sp.csr_matrix:
    """Symmetric Erdős–Rényi graph, no self-loops, float32."""
    rng = np.random.default_rng(seed)
    dense = rng.random((n, n)) < p
    dense = np.triu(dense, 1)
    dense = dense | dense.T
    return sp.csr_matrix(dense.astype(np.float32))


@pytest.fixture(scope="session")
def graph():
    return er_graph()


@pytest.fixture(scope="session")
def ahat(graph):
    from sgcn_tpu.prep import normalize_adjacency
    return normalize_adjacency(graph)
