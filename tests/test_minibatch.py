"""Mini-batch trainer + plan-padding tests (PGCN-Mini-batch capability)."""

import numpy as np
import pytest

from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.parallel.plan import pad_comm_plan
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.train import FullBatchTrainer, make_train_data
from sgcn_tpu.train.minibatch import (
    MiniBatchTrainer, sample_adjacency, sample_batches,
)

K = 4


def test_pad_comm_plan_preserves_forward(ahat):
    n = ahat.shape[0]
    rng = np.random.default_rng(3)
    pv = balanced_random_partition(n, K, seed=1)
    plan = build_comm_plan(ahat, pv, K)
    padded = pad_comm_plan(plan, plan.b + 5, plan.s + 3, plan.r + 7,
                           plan.e + 11)
    feats = rng.standard_normal((n, 9)).astype(np.float32)
    labels = rng.integers(0, 3, n).astype(np.int32)
    a = FullBatchTrainer(plan, fin=9, widths=[6, 3], seed=2)
    b = FullBatchTrainer(padded, fin=9, widths=[6, 3], seed=2)
    pa = a.predict(make_train_data(plan, feats, labels))
    pb = b.predict(make_train_data(padded, feats, labels))
    np.testing.assert_allclose(pa, pb, rtol=1e-5, atol=1e-6)


def test_sample_batches_shapes():
    bs = sample_batches(100, 32, seed=0)
    assert len(bs) == 3 * (100 // 32 + 1)
    for b in bs:
        assert len(b) == 32
        assert len(np.unique(b)) == 32


def test_sample_adjacency(ahat):
    batch = np.array([0, 3, 5, 10, 11])
    sub = sample_adjacency(ahat, batch)
    assert sub.shape == (5, 5)
    dense = ahat.toarray()[np.ix_(batch, batch)]
    np.testing.assert_allclose(sub.toarray(), dense, rtol=1e-6)


def test_minibatch_training_converges(ahat):
    n = ahat.shape[0]
    rng = np.random.default_rng(5)
    pv = balanced_random_partition(n, K, seed=2)
    feats = rng.standard_normal((n, 8)).astype(np.float32)
    labels = rng.integers(0, 3, n).astype(np.int32)
    tr = MiniBatchTrainer(ahat, pv, K, fin=8, widths=[8, 3],
                          batch_size=24, nbatches=4, lr=0.02, seed=0)
    report = tr.fit(feats, labels, epochs=6, verbose=False)
    assert report["nbatches"] == 4
    assert report["loss_history"][-1] < report["loss_history"][0]
    assert report["total_exchanged_rows"] > 0
    # batch comm must not exceed full-graph comm per exchange
    full = build_comm_plan(ahat, pv, K)
    for p in tr.plans:
        assert p.predicted_send_volume.sum() <= full.predicted_send_volume.sum()


def test_minibatch_fullgraph_eval(ahat):
    n = ahat.shape[0]
    rng = np.random.default_rng(6)
    pv = balanced_random_partition(n, K, seed=2)
    feats = rng.standard_normal((n, 8)).astype(np.float32)
    labels = (np.arange(n) % 3).astype(np.int32)
    tr = MiniBatchTrainer(ahat, pv, K, fin=8, widths=[8, 3],
                          batch_size=24, nbatches=3, lr=0.05, seed=1)
    tr.fit(feats, labels, epochs=8, verbose=False)
    loss, acc = tr.evaluate_fullgraph(feats, labels)
    assert np.isfinite(loss)
    assert 0.0 <= acc <= 1.0


def test_minibatch_empty_train_batches_no_nan(ahat):
    """A batch with zero train-mask vertices must not NaN-poison the weights
    (semi-supervised masks are sparse; many random batches miss them all)."""
    n = ahat.shape[0]
    rng = np.random.default_rng(9)
    pv = balanced_random_partition(n, K, seed=4)
    feats = rng.standard_normal((n, 6)).astype(np.float32)
    labels = rng.integers(0, 3, n).astype(np.int32)
    train_mask = np.zeros(n, dtype=np.float32)
    train_mask[rng.choice(n, 4, replace=False)] = 1.0   # 4 labeled vertices
    tr = MiniBatchTrainer(ahat, pv, K, fin=6, widths=[4, 3],
                          batch_size=12, nbatches=6, seed=2)
    report = tr.fit(feats, labels, train_mask, epochs=3, verbose=False)
    assert np.isfinite(report["loss_history"]).all()
    leaves = __import__("jax").tree.leaves(tr.inner.params)
    assert all(np.isfinite(np.asarray(w)).all() for w in leaves)


def test_minibatch_stats_vocabulary(ahat):
    """fit() reports the full-batch trainer's 8-number comm vocabulary, and
    volume equals the sum of per-batch plan predictions (VERDICT r2 #6)."""
    n = ahat.shape[0]
    rng = np.random.default_rng(7)
    pv = balanced_random_partition(n, K, seed=2)
    feats = rng.standard_normal((n, 8)).astype(np.float32)
    labels = rng.integers(0, 3, n).astype(np.int32)
    epochs, nlayers = 3, 2
    tr = MiniBatchTrainer(ahat, pv, K, fin=8, widths=[8, 3],
                          batch_size=24, nbatches=4, lr=0.02, seed=0)
    report = tr.fit(feats, labels, epochs=epochs, warmup=1, verbose=False)
    for f in ("total_send_volume", "max_send_volume", "total_send_msgs",
              "max_send_msgs", "total_recv_volume", "max_recv_volume",
              "total_recv_msgs", "max_recv_msgs"):
        assert f in report, f
    # every batch stepped `epochs` times + batch 0 stepped once for warm-up;
    # each step = 2·nlayers exchanges of the batch plan's boundary rows
    want = 0
    for i, p in enumerate(tr.plans):
        steps = epochs + (1 if i == 0 else 0)
        want += steps * 2 * nlayers * int(p.predicted_send_volume.sum())
    assert report["total_send_volume"] == want
    assert report["total_send_volume"] == report["total_recv_volume"]
    assert report["total_send_volume"] == report["total_exchanged_rows"]


def test_minibatch_gat_trains(ahat):
    """GAT mini-batch: shared combined-edge envelope (buckets + tail) across
    batch plans, one compiled step, finite decreasing loss."""
    n = ahat.shape[0]
    rng = np.random.default_rng(9)
    pv = balanced_random_partition(n, K, seed=4)
    feats = rng.standard_normal((n, 6)).astype(np.float32)
    labels = rng.integers(0, 3, n).astype(np.int32)
    tr = MiniBatchTrainer(ahat, pv, K, fin=6, widths=[5, 3],
                          batch_size=16, model="gat", activation="none",
                          seed=0)
    # every batch plan shares ONE combined-edge envelope
    envs = {(p.cell_buckets, p.ctl) for p in tr.plans}
    assert len(envs) == 1
    report = tr.fit(feats, labels, epochs=3, verbose=False)
    assert np.isfinite(report["loss_history"]).all()


def test_fused_epoch_matches_stepwise(ahat):
    """The one-program epoch sweep (fori over batches on-device) must follow
    the exact trajectory of sequential per-batch step() calls."""
    n = ahat.shape[0]
    rng = np.random.default_rng(5)
    pv = balanced_random_partition(n, K, seed=2)
    feats = rng.standard_normal((n, 7)).astype(np.float32)
    labels = rng.integers(0, 3, size=n).astype(np.int32)
    kw = dict(batch_size=16, nbatches=4, lr=0.05, seed=3)
    seq = MiniBatchTrainer(ahat, pv, K, fin=7, widths=[6, 3], **kw)
    fused = MiniBatchTrainer(ahat, pv, K, fin=7, widths=[6, 3], **kw)
    batches = seq.make_batches(feats, labels)
    seq_losses = []
    for _ in range(2):
        seq_losses.append(np.mean([seq.step(b) for b in batches]))
    fused_losses = fused.run_epochs_fused(feats, labels, epochs=2)
    np.testing.assert_allclose(fused_losses, seq_losses, rtol=2e-5, atol=1e-6)
    # params identical afterward
    for a, b in zip(seq.inner.params, fused.inner.params):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    # comm accounting carries the full 8-number vocabulary
    rep = fused.fused_stats_report()
    expected = sum(int(p.predicted_send_volume.sum())
                   for p in fused.plans) * 2 * 2 * 2  # ep × layers × fwd+bwd
    assert rep["total_send_volume"] == expected
