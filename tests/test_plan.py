"""Comm-plan invariants (reference predicate: GPU/PGCN.py:37-51; the
volume-accounting invariant is SURVEY.md §4's property test)."""

import numpy as np
import pytest

from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.partition import balanced_random_partition, random_partition


@pytest.mark.parametrize("k", [1, 2, 4, 8])
def test_plan_shapes_and_partition(ahat, k):
    n = ahat.shape[0]
    pv = balanced_random_partition(n, k, seed=2)
    plan = build_comm_plan(ahat, pv, k)
    assert plan.n == n and plan.k == k
    assert plan.part_sizes.sum() == n
    assert plan.b >= plan.part_sizes.max()
    # every vertex maps to a unique (owner, slot)
    slots = plan.owner * plan.b + plan.local_idx
    assert len(np.unique(slots)) == n
    # all local nnz accounted for
    assert plan.nnz.sum() == ahat.nnz


def test_scatter_gather_roundtrip(ahat):
    n = ahat.shape[0]
    pv = random_partition(n, 4, seed=0)
    plan = build_comm_plan(ahat, pv, 4)
    x = np.random.default_rng(0).random((n, 3)).astype(np.float32)
    np.testing.assert_array_equal(plan.gather_rows(plan.scatter_rows(x)), x)


def test_halo_matches_bruteforce(ahat):
    """Each chip's halo = exactly the remote cols its nonzeros reference."""
    n = ahat.shape[0]
    k = 4
    pv = balanced_random_partition(n, k, seed=3)
    plan = build_comm_plan(ahat, pv, k)
    coo = ahat.tocoo()
    for p in range(k):
        em = pv[coo.row] == p
        expected = np.unique(coo.col[em][pv[coo.col[em]] != p])
        assert plan.halo_counts[p] == len(expected)
        # send lists must cover the halo exactly once
        got = []
        for q in range(k):
            cnt = plan.send_counts[q, p]
            if cnt:
                # local indices on q → recover global ids via the plan's own
                # inverse relabeling (row_order='degree' means local rank is
                # NOT global-id rank)
                owned_q = np.where(pv == q)[0]
                l2g = np.full(plan.b, -1, dtype=np.int64)
                l2g[plan.local_idx[owned_q]] = owned_q
                got.extend(l2g[plan.send_idx[q, p, :cnt]])
        np.testing.assert_array_equal(np.sort(got), expected)


def test_volume_invariant(ahat):
    """Plan-predicted send volume == brute-force boundary count == Σ(λ−1).

    This is the reference's empirical invariant: trainer-measured comm volume
    matches the partitioner's connectivity metric (GCN-HP/main.cpp:335-345 vs
    Parallel-GCN/main.c:506-524)."""
    n = ahat.shape[0]
    k = 4
    pv = balanced_random_partition(n, k, seed=5)
    plan = build_comm_plan(ahat, pv, k)
    coo = ahat.tocoo()
    # connectivity: for each vertex v, λ(v) = #distinct parts holding nonzeros
    # in column v (including owner if it references v); volume contributed by
    # v's owner = #parts ≠ owner(v) that reference v.
    lam_minus_1 = 0
    for v in range(n):
        rows = coo.row[coo.col == v]
        parts = np.unique(pv[rows])
        lam_minus_1 += len(np.setdiff1d(parts, [pv[v]]))
    assert plan.predicted_send_volume.sum() == lam_minus_1


def test_edges_sorted_and_padded(ahat):
    k = 4
    plan = build_comm_plan(ahat, balanced_random_partition(ahat.shape[0], k, 7), k)
    for p in range(k):
        cnt = plan.nnz[p]
        d = plan.edge_dst[p, :cnt]
        assert (np.diff(d) >= 0).all()
        assert (plan.edge_w[p, cnt:] == 0).all()


def test_single_part_has_no_comm(ahat):
    plan = build_comm_plan(ahat, np.zeros(ahat.shape[0], dtype=np.int64), 1)
    assert plan.predicted_send_volume.sum() == 0
    assert plan.halo_counts.sum() == 0


def test_powerlaw_hub_widths_capped():
    """A hub vertex must not blow up the bucket widths (the SpMM unrolls one
    gather per width slot — program size scales with Σ wb); its overflow
    edges spill to the COO tail instead."""
    import scipy.sparse as sp
    from sgcn_tpu.prep import normalize_adjacency
    n, hub_deg = 600, 500
    rows = [0] * hub_deg + list(range(n - 1))
    cols = list(range(1, hub_deg + 1)) + list(range(1, n))
    a = sp.coo_matrix((np.ones(len(rows), np.float32), (rows, cols)),
                      shape=(n, n))
    a = sp.csr_matrix(((a + a.T) > 0).astype(np.float32))
    ahat = normalize_adjacency(a)
    plan = build_comm_plan(ahat, np.zeros(n, dtype=np.int64), 1)
    assert max(wb for _, wb in plan.ell_buckets) <= 64
    assert plan.ltail_nnz.sum() > 0          # hub overflow in the tail
    # parity: the layout must still compute exactly Â·H
    h = np.random.default_rng(0).standard_normal((n, 3)).astype(np.float32)
    hb = plan.scatter_rows(h)[0]
    out = np.zeros_like(hb)
    off = r0 = 0
    for nb, wb in plan.ell_buckets:
        for t in range(wb):
            seg = slice(off + t * nb, off + (t + 1) * nb)
            out[r0:r0 + nb] += (hb[plan.ell_idx[0][seg]]
                                * plan.ell_w[0][seg][:, None])
        off += nb * wb
        r0 += nb
    np.add.at(out, plan.ltail_dst[0], hb[plan.ltail_src[0]]
              * plan.ltail_w[0][:, None])
    np.testing.assert_allclose(plan.gather_rows(out[None]), ahat @ h,
                               rtol=1e-4, atol=1e-5)


def test_empty_part_and_fewer_vertices_than_parts():
    """Degenerate partitions must build valid plans and train finitely:
    a part that owns zero vertices (a real partitioner outcome on small or
    skewed graphs) and n < k (more chips than vertices).  The reference's
    per-rank file pipeline would simply emit empty A.r/H.r files for such a
    rank (GCN-HP/main.cpp:213-282); here the padded per-chip blocks play
    that role."""
    from sgcn_tpu.io.datasets import er_graph
    from sgcn_tpu.prep import normalize_adjacency
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    rng = np.random.default_rng(0)

    ahat = normalize_adjacency(er_graph(40, 4, 0))
    pv = np.array([i % 8 for i in range(40)])
    pv[pv == 3] = 2                       # part 3 owns nothing
    plan = build_comm_plan(ahat, pv, 8)
    feats = rng.standard_normal((40, 8)).astype(np.float32)
    labels = rng.integers(0, 3, 40).astype(np.int32)
    for kw in ({}, {"model": "gat", "activation": "none"}):
        tr = FullBatchTrainer(plan, fin=8, widths=[8, 3], **kw)
        data = make_train_data(plan, feats, labels)
        losses = [float(tr.step(data)) for _ in range(2)]
        assert np.all(np.isfinite(losses)), (kw, losses)

    ahat2 = normalize_adjacency(er_graph(5, 2, 1))
    plan2 = build_comm_plan(ahat2, np.arange(5), 8)
    tr2 = FullBatchTrainer(plan2, fin=4, widths=[4, 2])
    d2 = make_train_data(plan2,
                         rng.standard_normal((5, 4)).astype(np.float32),
                         np.array([0, 1, 0, 1, 0], np.int32))
    assert np.isfinite(float(tr2.step(d2)))
