"""Sigmoid+BCE loss flavor of the MPI trainer (Parallel-GCN/main.c:70-90).

The C stack's backward chain ``T=H(1-H); H=(H-Y)/T; G=H⊙σ'(Z)`` collapses to
``σ(z)-y``; these tests pin that gradient identity, the `err` metric formula
(Σ -y·log σ(z), main.c:318-323), and that distributed training under the
flavor actually learns.
"""

import jax
import jax.numpy as jnp
import numpy as np

from sgcn_tpu.models.gcn import masked_err_local, masked_sigmoid_bce_local
from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.train import FullBatchTrainer, make_train_data
from sgcn_tpu.parallel.mesh import shard_stacked


def test_bce_gradient_is_sigmoid_minus_onehot():
    """d(mean BCE)/dz = (σ(z) − y)/count — grbgcn's exact update direction
    (gradient_update with G = (H−Y)/n, Parallel-GCN/main.c:325-335)."""
    rng = np.random.default_rng(0)
    z = jnp.asarray(rng.standard_normal((10, 4)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 4, 10), jnp.int32)
    valid = jnp.ones(10, jnp.float32)

    def wrapped(zz):
        return jax.shard_map(
            lambda q: masked_sigmoid_bce_local(q[0], labels, valid,
                                               axis_name="v")[None],
            mesh=make_mesh_1d(1), in_specs=jax.sharding.PartitionSpec("v"),
            out_specs=jax.sharding.PartitionSpec("v"))(zz[None])[0]

    grad = jax.grad(lambda q: wrapped(q).sum())(z)
    want = (jax.nn.sigmoid(z) - jax.nn.one_hot(labels, 4)) / 10.0
    np.testing.assert_allclose(np.asarray(grad), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_err_metric_formula():
    rng = np.random.default_rng(1)
    z = jnp.asarray(rng.standard_normal((8, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 3, 8), jnp.int32)
    valid = jnp.asarray((rng.random(8) > 0.3).astype(np.float32))

    err = jax.shard_map(
        lambda q: masked_err_local(q[0], labels, valid, axis_name="v")[None],
        mesh=make_mesh_1d(1), in_specs=jax.sharding.PartitionSpec("v"),
        out_specs=jax.sharding.PartitionSpec("v"))(z[None])[0]
    p = np.asarray(jax.nn.log_sigmoid(z))
    want = -(p[np.arange(8), np.asarray(labels)] * np.asarray(valid)).sum()
    np.testing.assert_allclose(float(err), want, rtol=1e-5)


def test_distributed_bce_training_learns(ahat):
    """Full sharded training under the MPI flavor (sigmoid activations + BCE)
    must drive both the loss and the err metric down."""
    n = ahat.shape[0]
    k = 4
    rng = np.random.default_rng(2)
    feats = rng.standard_normal((n, 8)).astype(np.float32)
    labels = (np.arange(n) % 3).astype(np.int32)
    plan = build_comm_plan(ahat, balanced_random_partition(n, k, seed=1), k)
    mesh = make_mesh_1d(k)
    tr = FullBatchTrainer(plan, fin=8, widths=[16, 3], mesh=mesh,
                          activation="sigmoid", loss="bce", lr=0.05)
    data = make_train_data(plan, feats, labels)
    data = type(data)(**shard_stacked(mesh, vars(data)))
    first = tr.step(data)
    err_first = float(tr.last_err)
    # the err metric (SUM over rows of the label-class −log σ term only)
    # transiently RISES for the first few steps while BCE suppresses the
    # off-class logits, then declines as the label logits recover — anchor
    # the "drives err down" claim at the post-transient peak, not step 0
    # (the step-0 anchor is sensitive to the XLA version's exact rounding)
    err_peak = err_first
    for _ in range(6):
        last = tr.step(data)
        err_peak = max(err_peak, float(tr.last_err))
    for _ in range(24):
        last = tr.step(data)
    err_last = float(tr.last_err)
    assert last < first
    assert err_last < err_peak
    assert err_first > 0


def test_eval_loss_honors_bce_flavor(ahat):
    """evaluate() must report the TRAINED objective: under --loss bce the
    eval loss is sigmoid+BCE, not softmax xent (VERDICT r2 weak #5)."""
    n = ahat.shape[0]
    k = 4
    rng = np.random.default_rng(3)
    feats = rng.standard_normal((n, 8)).astype(np.float32)
    labels = (np.arange(n) % 3).astype(np.int32)
    plan = build_comm_plan(ahat, balanced_random_partition(n, k, seed=1), k)
    mesh = make_mesh_1d(k)
    tr = FullBatchTrainer(plan, fin=8, widths=[16, 3], mesh=mesh,
                          activation="sigmoid", loss="bce", lr=0.05)
    data = make_train_data(plan, feats, labels)
    sdata = type(data)(**shard_stacked(mesh, vars(data)))
    loss_eval, _ = tr.evaluate(sdata)
    # oracle: mean elementwise BCE over all rows from the global logits
    logits = tr.predict(sdata)
    y = np.eye(3, dtype=np.float32)[labels]
    bce = (np.maximum(logits, 0) - logits * y
           + np.log1p(np.exp(-np.abs(logits))))
    want = bce.sum() / n
    np.testing.assert_allclose(loss_eval, want, rtol=1e-4)
