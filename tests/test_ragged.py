"""Ragged neighbor-exchange schedule (``comm_schedule='ragged'``): the
per-round-sized ppermute halo ring replacing the globally-padded all_to_all.

Contract pinned here (docs/comm_schedule.md):

  * f32 BIT-parity with the dense a2a schedule — forward, gradients, and
    whole training trajectories on the cora fixture are exactly equal (the
    plan sorts halo edges in round order so the ragged fold applies per-row
    updates in the dense segment-sum's sequence);
  * per-round sizing: round d's buffer is max_p send_counts[p, (p+d)%k],
    empty rounds vanish from the traced program, and the wire-row total is
    strictly below the dense k²·S whenever the partition is skewed;
  * the shard proxy runs the ragged program on one device under the same
    optimization_barrier fidelity contract as the dense exchange;
  * composition with the stale pipelined exchange is SUPPORTED since the
    round-structured carry (``pspmm_stale_ragged``) — its parity and gauge
    coverage lives in tests/test_stale_ragged.py.
"""

import os
import re

import numpy as np
import pytest
import scipy.sparse as sp

from sgcn_tpu.io.datasets import er_graph, load_npz_dataset
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.parallel.mesh import AXIS, make_mesh_1d, shard_stacked
from sgcn_tpu.partition import balanced_random_partition
from sgcn_tpu.partition.emit import read_partvec
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def ring_graph(n: int) -> sp.csr_matrix:
    """Cycle graph: vertex i ~ i±1 (mod n) — under a contiguous partition
    each part talks ONLY to its two neighbors, the maximally skewed
    send-count pattern (most (src, dst) pairs empty)."""
    i = np.arange(n)
    rows = np.concatenate([i, i])
    cols = np.concatenate([(i + 1) % n, (i - 1) % n])
    return sp.csr_matrix((np.ones(2 * n, np.float32), (rows, cols)),
                         shape=(n, n))


@pytest.fixture(scope="module")
def skewplan():
    """Ring graph, 8 contiguous parts: only ring distances 1 and k−1 carry
    rows, so the dense a2a pads 56 of 64 peer buckets for nothing —
    padding_efficiency far below the 0.5 auto-select threshold."""
    n, k = 512, 8
    ahat = normalize_adjacency(ring_graph(n))
    pv = np.repeat(np.arange(k), n // k)
    plan = build_comm_plan(ahat, pv, k)
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((n, 16)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    return plan, feats, labels


@pytest.fixture(scope="module")
def asymplan():
    """ER graph under an UNBALANCED partition: symmetric Â (the ragged
    op's requirement) but asymmetric send_counts — the general shape the
    bit-parity claim must survive."""
    n, k = 600, 4
    ahat = normalize_adjacency(er_graph(n, 8, seed=0))
    pv = np.zeros(n, dtype=np.int64)
    pv[n // 2: n // 2 + n // 4] = 1
    pv[n // 2 + n // 4: n // 2 + n // 4 + n // 8] = 2
    pv[n // 2 + n // 4 + n // 8:] = 3
    plan = build_comm_plan(ahat, pv, k)
    assert not np.array_equal(plan.send_counts, plan.send_counts.T)
    rng = np.random.default_rng(1)
    feats = rng.standard_normal((n, 16)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    return plan, feats, labels


@pytest.fixture(scope="module")
def cora():
    a, feats, labels = load_npz_dataset(os.path.join(FIX, "cora_like.npz"))
    ahat = normalize_adjacency(a)
    pv = read_partvec(os.path.join(FIX, "cora_like.4.hp"))
    plan = build_comm_plan(ahat, pv, 4)
    return plan, feats.astype(np.float32), labels.astype(np.int32)


def test_round_sizes_and_empty_round_skip(skewplan):
    """rr_sizes follows S_d = max_p send_counts[p, (p+d)%k]; ring distances
    2..k−2 are empty and must vanish from the traced program."""
    plan, *_ = skewplan
    plan.ensure_ragged()
    k, sc = plan.k, plan.send_counts
    idx = np.arange(k)
    for d in range(1, k):
        assert plan.rr_sizes[d - 1] == int(sc[idx, (idx + d) % k].max())
    assert plan.rr_sizes[0] > 0 and plan.rr_sizes[-1] > 0
    assert all(s == 0 for s in plan.rr_sizes[1:-1])      # middle rounds empty
    # empty rounds carry no edges either
    assert all(e == 0 for e in plan.rr_edge_sizes[1:-1])
    # wire rows: 2 live rounds of the per-round max vs the global k²·S pad
    assert plan.wire_rows_per_exchange("ragged") == \
        plan.k * (plan.rr_sizes[0] + plan.rr_sizes[-1])
    assert plan.wire_rows_per_exchange("ragged") < \
        plan.wire_rows_per_exchange("a2a")
    assert plan.padding_efficiency() < 0.5


def test_ensure_ragged_receive_layout(asymplan):
    """Every receive slot lands in the contiguous per-owner halo slice, in
    send order — the invariant the fold-as-you-arrive split rides on."""
    plan, *_ = asymplan
    plan.ensure_ragged()
    k, s = plan.k, plan.s
    owner_rank = plan.halo_src // s
    off = 0
    for d, sd in enumerate(plan.rr_sizes, start=1):
        for p in range(k):
            o = (p - d) % k
            rc = int(plan.send_counts[o, p])
            got = plan.rhalo_dst[p, off: off + rc]
            hs = int(plan.halo_counts[p])
            expect = np.nonzero(owner_rank[p, :hs] == o)[0]
            np.testing.assert_array_equal(got, expect)
            # padding slots target the drop row r
            assert np.all(plan.rhalo_dst[p, off + rc: off + sd] == plan.r)
        off += sd


def test_op_level_bit_parity_fwd_and_grad(asymplan):
    """pspmm_ragged_sym vs pspmm_ell_sym on the asymmetric-count plan:
    forward AND gradients bitwise equal, and halo_exchange_ragged delivers
    the dense exchange's exact halo rows."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from sgcn_tpu.ops.pspmm import (halo_exchange, halo_exchange_ragged,
                                    pspmm_ell_sym, pspmm_ragged_sym)

    plan, *_ = asymplan
    plan.ensure_ragged()
    k = plan.k
    mesh = make_mesh_1d(k)
    rng = np.random.default_rng(0)
    h = shard_stacked(mesh, rng.standard_normal(
        (k, plan.b, 8)).astype(np.float32))
    fields = ("send_idx", "halo_src", "ell_idx", "ell_w", "ltail_dst",
              "ltail_src", "ltail_w", "hedge_dst", "hedge_src", "hedge_w",
              "rsend_idx", "rhalo_dst", "redge_dst", "redge_src", "redge_w")
    pa = shard_stacked(mesh, {f: getattr(plan, f) for f in fields})
    bk, rrs, rre, r = (plan.ell_buckets, plan.rr_sizes, plan.rr_edge_sizes,
                       plan.r)

    def dense_chip(pa, h):
        pa, h = jax.tree.map(lambda x: x[0], (pa, h))
        out = pspmm_ell_sym(h, pa["send_idx"], pa["halo_src"], pa["ell_idx"],
                            pa["ell_w"], pa["ltail_dst"], pa["ltail_src"],
                            pa["ltail_w"], pa["hedge_dst"], pa["hedge_src"],
                            pa["hedge_w"], bk)
        halo = halo_exchange(h, pa["send_idx"], pa["halo_src"])
        return out[None], halo[None]

    def ragged_chip(pa, h):
        pa, h = jax.tree.map(lambda x: x[0], (pa, h))
        out = pspmm_ragged_sym(h, pa["rsend_idx"], pa["ell_idx"], pa["ell_w"],
                               pa["ltail_dst"], pa["ltail_src"],
                               pa["ltail_w"], pa["redge_dst"],
                               pa["redge_src"], pa["redge_w"], bk, rrs, rre)
        halo = halo_exchange_ragged(h, pa["rsend_idx"], pa["rhalo_dst"],
                                    rrs, r)
        return out[None], halo[None]

    specs = dict(mesh=mesh, in_specs=(P(AXIS), P(AXIS)),
                 out_specs=(P(AXIS), P(AXIS)))
    dj = jax.jit(jax.shard_map(dense_chip, **specs))
    rj = jax.jit(jax.shard_map(ragged_chip, **specs))
    od, hd = dj(pa, h)
    orr, hr = rj(pa, h)
    np.testing.assert_array_equal(np.asarray(od), np.asarray(orr))
    hd, hr = np.asarray(hd), np.asarray(hr)
    for p in range(k):
        hc = int(plan.halo_counts[p])
        np.testing.assert_array_equal(hd[p, :hc], hr[p, :hc])

    gd = jax.grad(lambda x: jnp.sum(jnp.sin(dj(pa, x)[0])))(h)
    gr = jax.grad(lambda x: jnp.sum(jnp.sin(rj(pa, x)[0])))(h)
    np.testing.assert_array_equal(np.asarray(gd), np.asarray(gr))


def test_trainer_bit_identical_on_cora(cora):
    """THE acceptance contract: the ragged schedule's epoch losses and
    trained parameters are f32-BIT-identical to the dense a2a schedule's on
    the cora fixture (exact ELL path; stale composition is deferred)."""
    plan, feats, labels = cora
    tr_a = FullBatchTrainer(plan, fin=feats.shape[1], widths=[16, 7], seed=3)
    tr_r = FullBatchTrainer(plan, fin=feats.shape[1], widths=[16, 7], seed=3,
                            comm_schedule="ragged")
    assert tr_r.comm_schedule == "ragged"
    d = make_train_data(plan, feats, labels)
    la = [tr_a.step(d) for _ in range(3)]
    lr = [tr_r.step(d) for _ in range(3)]
    assert la == lr                                  # bitwise, not allclose
    for wa, wr in zip(tr_a.params, tr_r.params):
        np.testing.assert_array_equal(np.asarray(wa), np.asarray(wr))
    # the two schedules agree on the TRUE volume and disagree on the wire
    ra, rr = tr_a.stats.report(), tr_r.stats.report()
    assert ra["true_rows_per_exchange"] == rr["true_rows_per_exchange"]
    assert rr["wire_rows_per_exchange"] < ra["wire_rows_per_exchange"]
    assert ra["comm_schedule"] == "a2a" and rr["comm_schedule"] == "ragged"


def test_attribution_wire_below_dense_on_skew(skewplan):
    """Acceptance: on a skewed-partition fixture with padding_efficiency
    < 0.5, attribution reports halo_bytes_wire strictly below the dense
    schedule's — and the roofline event fields validate + reconcile with
    CommStats' gauges."""
    import time

    from sgcn_tpu.obs.attribution import roofline_fields, step_cost
    from sgcn_tpu.obs.schema import validate_event
    from sgcn_tpu.utils.stats import CommStats

    plan, *_ = skewplan
    assert plan.padding_efficiency() < 0.5
    ca = step_cost(plan, 16, [8, 4], comm_schedule="a2a")
    cr = step_cost(plan, 16, [8, 4], comm_schedule="ragged")
    assert cr.halo_bytes_true_per_step == ca.halo_bytes_true_per_step
    assert cr.halo_bytes_wire_per_step < ca.halo_bytes_wire_per_step
    assert ca.halo_bytes_wire_per_step >= ca.halo_bytes_true_per_step
    # legacy field keeps its true-volume meaning (old readers unchanged)
    assert ca.halo_bytes_per_step == ca.halo_bytes_true_per_step

    for cost, schedule in ((ca, "a2a"), (cr, "ragged")):
        st = CommStats.from_plan(plan, schedule=schedule)
        assert st.wire_rows_per_exchange == cost.halo_wire_rows
        assert st.padding_efficiency == cost.padding_efficiency
        rf = roofline_fields(cost, 0.1, exchanges=4, exposed_exchanges=4)
        # exposed bytes charge the WIRE, not the true volume
        assert rf["exposed_halo_bytes"] == cost.halo_bytes_wire_per_step
        validate_event({"kind": "step", "v": 1, "ts": time.time(),
                        "step": 1, "loss": 1.0, "wall_s": 0.1,
                        "roofline": rf})


def test_auto_select_and_env(skewplan, monkeypatch):
    """'auto' picks ragged below the padding-efficiency threshold, a2a on a
    well-packed plan; $SGCN_COMM_SCHEDULE supplies the default."""
    plan, feats, labels = skewplan
    tr = FullBatchTrainer(plan, fin=16, widths=[8, 4], comm_schedule="auto")
    assert tr.comm_schedule == "ragged"

    # near-uniform counts: balanced random partition of an ER expander has
    # every peer bucket filled, efficiency ≈ (k−1)/k — a2a wins
    n, k = 600, 4
    ahat = normalize_adjacency(er_graph(n, 8, seed=2))
    pv = balanced_random_partition(n, k, seed=3)
    uplan = build_comm_plan(ahat, pv, k)
    assert uplan.padding_efficiency() >= 0.5
    tr_u = FullBatchTrainer(uplan, fin=16, widths=[8, 4],
                            comm_schedule="auto")
    assert tr_u.comm_schedule == "a2a"

    monkeypatch.setenv("SGCN_COMM_SCHEDULE", "ragged")
    tr_env = FullBatchTrainer(plan, fin=16, widths=[8, 4])
    assert tr_env.comm_schedule == "ragged"


def test_proxy_runs_ragged_program(skewplan):
    """k>1-plan-on-1-device: the ragged layout built BEFORE slicing rides
    the proxy, the per-round sends stay materialized (optimization_barrier
    fidelity, like a2a_or_identity), and training is finite."""
    from sgcn_tpu.parallel.proxy import shard_proxy_data, shard_proxy_plan

    plan, feats, labels = skewplan
    plan.ensure_ragged()
    proxy = shard_proxy_plan(plan, chip=2)
    assert proxy.rr_sizes == plan.rr_sizes          # static tuple rides along
    assert proxy.rsend_idx.shape == (1,) + plan.rsend_idx.shape[1:]
    np.testing.assert_array_equal(proxy.redge_w[0], plan.redge_w[2])
    tr = FullBatchTrainer(proxy, fin=16, widths=[8, 4], seed=2,
                          comm_schedule="ragged")
    data = shard_proxy_data(plan, 2, feats, labels)
    losses = tr.run_epochs(data, 2)
    assert np.all(np.isfinite(losses))
    txt = tr._step.lower(
        tr.params, tr.opt_state, tr.pa, data.h0, data.labels,
        data.train_valid).as_text()
    # one barrier per LIVE round per exchange direction — at least the two
    # live ring rounds must stay pinned
    assert txt.count("optimization_barrier") >= 2


def test_ensure_ragged_needs_full_plan(skewplan):
    """Building the ragged layout from an already-sliced plan must fail
    loudly (round sizes are maxes over ALL chips)."""
    from sgcn_tpu.parallel.proxy import shard_proxy_plan

    plan, *_ = skewplan
    sliced = shard_proxy_plan(
        build_comm_plan(normalize_adjacency(ring_graph(128)),
                        np.repeat(np.arange(4), 32), 4), chip=0)
    with pytest.raises(ValueError, match="BEFORE shard_proxy_plan"):
        sliced.ensure_ragged()


def test_gating(asymplan, cora):
    """Invalid combinations fail loudly at construction: asymmetric plans,
    unknown values.  GAT + ragged is a SUPPORTED contract since the
    multi-lane ring (tests/test_gat_ragged.py owns its parity coverage),
    and ragged + staleness is the SUPPORTED composed mode since the
    round-structured carry (tests/test_stale_ragged.py owns its parity
    coverage)."""
    plan, *_ = cora
    tr_comp = FullBatchTrainer(plan, fin=8, widths=[8, 7], halo_staleness=1,
                               comm_schedule="ragged")
    assert tr_comp.comm_schedule == "ragged" and tr_comp.halo_staleness == 1
    tr_gat = FullBatchTrainer(plan, fin=8, widths=[8, 7], model="gat",
                              comm_schedule="ragged")
    assert tr_gat.comm_schedule == "ragged"
    with pytest.raises(ValueError, match="a2a"):
        FullBatchTrainer(plan, fin=8, widths=[8, 7], comm_schedule="bogus")
    # stale + auto resolves by the wire-byte-only rule (the hidden exchange
    # makes the latency threshold moot), which picks ragged whenever the
    # ring ships fewer wire rows — true on any supported k>1 plan
    tr = FullBatchTrainer(plan, fin=8, widths=[8, 7], halo_staleness=1,
                          comm_schedule="auto")
    assert tr.comm_schedule == "ragged"
    assert "wire-byte rule" in tr.comm_decision["rule"]

    import dataclasses
    aplan = dataclasses.replace(asymplan[0], symmetric=False)
    with pytest.raises(ValueError, match="asymmetric"):
        FullBatchTrainer(aplan, fin=16, widths=[8, 4],
                         comm_schedule="ragged")


def test_minibatch_ragged_shared_envelope(skewplan):
    """The mini-batch trainer pads every batch plan's round sizes to a
    shared envelope (one compiled step) and stays bit-identical to its a2a
    twin, batch for batch."""
    from sgcn_tpu.train.minibatch import MiniBatchTrainer

    _, feats, labels = skewplan
    n, k = 512, 8
    ahat = normalize_adjacency(ring_graph(n))
    pv = np.repeat(np.arange(k), n // k)
    kw = dict(fin=16, widths=[8, 4], batch_size=128, nbatches=2, seed=4)
    tr_a = MiniBatchTrainer(ahat, pv, k, comm_schedule="a2a", **kw)
    tr_r = MiniBatchTrainer(ahat, pv, k, comm_schedule="ragged", **kw)
    assert tr_r.inner.comm_schedule == "ragged"
    assert len({p.rr_sizes for p in tr_r.plans}) == 1   # shared envelope
    ba = tr_a.make_batches(feats, labels)
    br = tr_r.make_batches(feats, labels)
    la = [tr_a.step(b) for b in ba]
    lr = [tr_r.step(b) for b in br]
    assert la == lr                                  # bitwise, not allclose
    # the per-step comm snapshot carries the same wire gauges as the
    # full-batch path (docs/observability.md) and stays self-consistent
    snap = tr_r._comm_snapshot(br[0].stats)
    assert snap["comm_schedule"] == "ragged"
    assert snap["wire_rows_per_exchange"] == \
        tr_r.plans[0].wire_rows_per_exchange("ragged")
    assert snap["wire_rows_total"] == \
        snap["exchanges"] * snap["wire_rows_per_exchange"]
    # a batch may sample NO cross-partition edges while the shared wire
    # envelope stays nonzero — efficiency 0.0 is then the honest figure
    assert 0 <= snap["padding_efficiency"] <= 1
