"""Partition-quality comm sweep over the reference's k family.

The reference sweeps its partitioners over whole dataset directories with
k ∈ {1,2,3,9,27} (``GPU/graph/run.sh:1-13``) and {2,3,9,15,21,27}
(``GPU/hypergraph/run.sh:1-13``) and judges by the self-reported cut /
connectivity metrics.  This script is that experiment for our generators:
for each graph family and k it partitions with hp (colnet km1), gp
(edge-cut) and rp (random), then scores all three by the REAL comm plan's
predicted halo volume (``build_comm_plan`` — the number the trainer will
actually send), and writes ``bench_artifacts/partition_comm_sweep.json``.

Graphs:
  * ``cora2708``     — citation structure at cora's true shape (community
                       structure: partitioners should crush random);
  * ``ba40k_deg14``  — power-law, ogbn-like degree profile (weak community
                       structure: honest modest margins);
  * ``er40k_deg14``  — no structure at all (the floor: margins near 1).

Usage: PYTHONPATH=/root/repo python scripts/partition_comm_sweep.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sgcn_tpu.io.datasets import ba_graph, cora_like, er_graph   # noqa: E402
from sgcn_tpu.parallel import build_comm_plan                    # noqa: E402
from sgcn_tpu.partition import (                                 # noqa: E402
    balanced_random_partition, partition_graph, partition_hypergraph_colnet,
)
from sgcn_tpu.prep import normalize_adjacency                    # noqa: E402

KS = (2, 3, 9, 15, 21, 27)      # GPU/hypergraph/run.sh:1-13


def graphs():
    a, _, _ = cora_like(n=2708, nclasses=7, vocab=1433, words_per_doc=18,
                        avg_deg=4, seed=11)
    yield "cora2708", normalize_adjacency(a)
    yield "ba40k_deg14", normalize_adjacency(ba_graph(40_000, 7, seed=0))
    yield "er40k_deg14", normalize_adjacency(er_graph(40_000, 14, seed=0))


def main() -> None:
    rows = []
    for name, ahat in graphs():
        n = ahat.shape[0]
        for k in KS:
            row = {"graph": name, "k": k}
            t0 = time.time()
            pv_h, _ = partition_hypergraph_colnet(ahat, k, seed=1)
            row["hp_time_s"] = round(time.time() - t0, 2)
            t0 = time.time()
            pv_g, _ = partition_graph(ahat, k, seed=1)
            row["gp_time_s"] = round(time.time() - t0, 2)
            pv_r = balanced_random_partition(n, k, seed=1)
            for mode, pv in (("hp", pv_h), ("gp", pv_g), ("rp", pv_r)):
                row[mode] = int(build_comm_plan(ahat, pv, k)
                                .predicted_send_volume.sum())
            row["hp_vs_rp"] = round(row["rp"] / max(row["hp"], 1), 2)
            row["gp_vs_hp"] = round(row["gp"] / max(row["hp"], 1), 2)
            rows.append(row)
            print(json.dumps(row), flush=True)
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench_artifacts",
        "partition_comm_sweep.json")
    with open(out, "w") as f:
        json.dump(rows, f, indent=1)
    print("wrote", out)


if __name__ == "__main__":
    main()
