"""Render a run-telemetry directory into a human-readable summary.

Usage::

    python scripts/obs_report.py RUNDIR [--steps N]

Loads (and schema-validates) the directory written by ``--metrics-out``
(trainer CLI, ``bench.py``) and prints:

  * the manifest header (run kind, config highlights, git rev, backend,
    plan digest + partitioner provenance);
  * the step table: loss / grad-norm / wall-time statistics, roofline
    utilization, the hidden-vs-exposed comm split, and — for stale-halo
    runs — the drift-gauge columns (staleness age, per-layer drift,
    quantization error);
  * the measured-time layer (schema v2, ``sgcn_tpu/obs/tracing.py``):
    span breakdown (per-name count/total, nesting), the per-step
    ``measured_vs_model`` reconciliation (ratio + absolute error per
    component), and — when the manifest records a ``--profile`` trace —
    the trace-derived attribution: per-class op seconds, measured overlap
    fraction / exposed-comm time, per-device straggler skew, joined
    against the analytic exposed-comm fraction;
  * eval records, summary report, and the heartbeat timeline (the
    "slow vs stalled" signal of the launch/dryrun layers).

Read-only; a run directory that fails validation prints the schema error
and exits non-zero — this script is also the quickest way to check one.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt(x, nd=4):
    if x is None:
        return "-"
    if isinstance(x, float):
        return f"{x:.{nd}g}"
    return str(x)


def _stats(vals):
    if not vals:
        return "-"
    lo, hi = min(vals), max(vals)
    mean = sum(vals) / len(vals)
    return f"{_fmt(mean)} (min {_fmt(lo)}, max {_fmt(hi)})"


def render(path: str, max_steps: int = 12) -> str:
    from sgcn_tpu.obs import load_run

    log = load_run(path)
    m = log.manifest
    lines = [f"run: {path}"]
    if m:
        lines.append(f"  kind={m['run_kind']}  schema=v{m['v']}  "
                     f"git={(m.get('git_rev') or '?')[:10]}")
    else:
        lines.append("  (no manifest — heartbeats/spans written through "
                     "$SGCN_METRICS_OUT without a RunRecorder, e.g. the "
                     "launch/dryrun layers or a killed bench)")
    be = m.get("backend")
    if be:
        lines.append(f"  backend: {be.get('platform')} × "
                     f"{be.get('device_count')} devices, "
                     f"{be.get('process_count')} process(es)")
    pl = m.get("plan")
    if pl:
        lines.append(
            f"  plan: n={pl['n']} k={pl['k']} b={pl['b']} r={pl['r']} "
            f"symmetric={pl['symmetric']} digest={pl['digest']}")
        lines.append(
            f"        send rows/exchange={pl['send_rows_per_exchange']} "
            f"messages/exchange={pl['messages_per_exchange']}")
    pt = m.get("partitioner")
    if pt:
        lines.append("  partitioner: "
                     + " ".join(f"{k}={v}" for k, v in pt.items()))
    cfg = m.get("config", {})
    knobs = {k: cfg[k] for k in ("model", "loss", "halo_staleness",
                                 "halo_delta", "sync_every", "dtype",
                                 "halo_dtype", "epochs", "batch_size")
             if cfg.get(k)}
    if knobs:
        lines.append("  config: "
                     + " ".join(f"{k}={v}" for k, v in knobs.items()))
    cs = m.get("comm_schedule")
    if cs:
        # the transport-selection decision log (resolve_comm_schedule) —
        # how an 'auto' pick is reconstructible from the run dir alone
        lines.append(f"  comm schedule: {cs.get('asked')} -> "
                     f"{cs.get('resolved')} ({cs.get('rule')})")
        if cs.get("wire_rows_a2a") is not None:
            lines.append(
                f"    scored wire rows/exchange: a2a "
                f"{cs['wire_rows_a2a']}, ragged "
                f"{cs.get('wire_rows_ragged')} (true {cs.get('true_rows')})")
        if cs.get("replica_budget"):
            lines.append(
                f"    replica-aware (B={cs['replica_budget']}, "
                f"{cs.get('replica_rows', '?')} rows): shrunken wire "
                f"a2a {cs.get('wire_rows_a2a_replica', '?')}, ragged "
                f"{cs.get('wire_rows_ragged_replica', '?')} (true "
                f"{cs.get('true_rows_replica', '?')})")
        pd = cs.get("pallas_dispatch")
        if pd:
            # per-degree-bucket kernel choice of the Pallas family
            # (ISSUE 15; docs/comm_schedule.md)
            fams = [(k, pd[k]) for k in ("local", "halo", "combined")
                    if pd.get(k)]
            lines.append(
                f"    pallas dispatch ({pd.get('model')}, tb="
                f"{pd.get('tb')}, emax cap {pd.get('emax_cap')}): "
                + "; ".join(
                    f"{name} [" + " ".join(
                        f"{c.get('tiles')}x{c.get('emax')}:"
                        f"{c.get('kernel')}" for c in classes) + "]"
                    for name, classes in fams))
        ra = cs.get("replica_auto")
        if ra:
            lines.append(
                f"    replica budget auto ({ra.get('rule')}): B="
                f"{ra.get('chosen')} of {ra.get('boundary_rows')} boundary "
                f"rows, λ·degree score covered "
                f"{_fmt(ra.get('score_covered'))}")
        ctl = cs.get("controller")
        if ctl:
            lines.append(
                f"    controller ({ctl.get('kind')}): band "
                f"{ctl.get('band')}, sync_every "
                f"{ctl.get('initial_sync_every')} -> "
                f"{ctl.get('sync_every')}, {len(ctl.get('retunes', []))} "
                "retune(s)")
            for d in ctl.get("retunes", []):
                old, new = (d.get("sync_every") or ["?", "?"])[:2]
                lines.append(
                    f"      step {d.get('step')}: drift_rel_max "
                    f"{_fmt(d.get('drift_rel_max'))} {d.get('rule')} — "
                    f"sync_every {old} -> {new}")

    mem = m.get("memory") if m else None
    if mem:
        # the per-chip HBM footprint reconciliation (schema v6,
        # docs/observability.md): analytic model per array family, joined
        # against XLA's memory_analysis() when a compile was measured
        tot = mem.get("total", {})
        lines.append(
            "  memory (per-chip analytic model"
            + (", measured join" if tot.get("measured_bytes") is not None
               else "") + "):")
        fams = sorted((mem.get("families") or {}).items(),
                      key=lambda kv: -(kv[1].get("model_bytes") or 0))
        for name, row in fams:
            mb = row.get("model_bytes")
            if not mb:
                continue
            lines.append(f"    {name:<16s} {mb:>12,} B")
        for label, row in (("TOTAL", tot),
                           ("arguments", mem.get("arguments", {})),
                           ("donated", mem.get("donated", {}))):
            if row.get("model_bytes") is None:
                continue
            joined = (f"  measured {row['measured_bytes']:,} B "
                      f"(ratio {_fmt(row.get('ratio'), 2)})"
                      if row.get("measured_bytes") is not None else "")
            lines.append(f"    {label:<16s} {row['model_bytes']:>12,} B"
                         + joined)

    steps = log.steps()
    if steps:
        lines.append(f"\nsteps: {len(steps)}")
        lines.append("  loss:      first " + _fmt(steps[0]["loss"])
                     + " → last " + _fmt(steps[-1]["loss"]))
        gn = [s["grad_norm"] for s in steps if s.get("grad_norm") is not None]
        if gn:
            lines.append("  grad_norm: " + _stats(gn))
        lines.append("  wall_s:    "
                     + _stats([s["wall_s"] for s in steps]))
        roofs = [s["roofline"] for s in steps if s.get("roofline")]
        if roofs:
            lines.append("  roofline:  gather "
                         + _stats([r["achieved_gather_GBs"] for r in roofs])
                         + " GB/s, stream-ceiling frac "
                         + _stats([r["stream_ceiling_frac"] for r in roofs]))
            ef = [r["exposed_comm_frac"] for r in roofs
                  if "exposed_comm_frac" in r]
            if ef:
                lines.append("  exposed-comm frac: " + _stats(ef))
        comm = steps[-1].get("comm")
        if comm:
            lines.append(
                f"  comm (cumulative): {comm['exchanges']} exchanges = "
                f"{comm['exposed_exchanges']} exposed + "
                f"{comm['hidden_exchanges']} hidden; send rows "
                f"{comm['total_send_volume']} = "
                f"{comm['exposed_send_volume']} + "
                f"{comm['hidden_send_volume']}")
            if "wire_rows_per_exchange" in comm:
                # padded-vs-true split of the selected exchange schedule
                # (docs/comm_schedule.md)
                lines.append(
                    f"  wire ({comm.get('comm_schedule', 'a2a')} schedule): "
                    f"{comm['wire_rows_per_exchange']} padded rows/exchange "
                    f"for {comm.get('true_rows_per_exchange', '?')} true — "
                    f"padding efficiency "
                    f"{_fmt(comm.get('padding_efficiency'), 3)}")
        drifts = [s["drift"] for s in steps if s.get("drift")]
        if drifts:
            lines.append("\ndrift gauges (stale-halo mode):")
            nl = len(drifts[-1]["halo_drift_rms"])
            lines.append("  staleness age: last "
                         + str(drifts[-1]["staleness_age"]) + ", max "
                         + str(max(d["staleness_age"] for d in drifts)))
            ages = [d["round_age"] for d in drifts
                    if d.get("round_age") is not None]
            if ages:
                # composed stale × ragged mode: per-round consumed-buffer
                # age ("-" = empty round, ships nothing)
                live = sum(1 for x in ages[-1] if x is not None)
                max_age = max((x for ra in ages for x in ra
                               if x is not None), default=0)
                lines.append(
                    "  round ages (ragged ring): last ["
                    + " ".join("-" if x is None else str(x)
                               for x in ages[-1])
                    + f"]  ({live}/{len(ages[-1])} live rounds, "
                    + f"max age {max_age})")
            for layer in range(nl):
                dr = [d["halo_drift_rms"][layer] for d in drifts]
                rel = [d["halo_drift_rel"][layer] for d in drifts]
                qe = [d["halo_quant_err_rms"][layer] for d in drifts]
                lines.append(f"  layer {layer}: ‖stale−fresh‖ " + _stats(dr)
                             + f", relative {_fmt(rel[-1])} (last)"
                             + (f", quant-err {_stats(qe)}"
                                if any(qe) else ""))
        reps = [s["replica"] for s in steps if s.get("replica")]
        if reps:
            # hot-halo replication (--replica-budget, docs/replication.md):
            # drift is measured AT each refresh (the drift the refresh
            # erased) — between refreshes no fresh value exists to compare
            lines.append("\nreplica gauges (hot-halo replication):")
            last = reps[-1]
            lines.append(
                f"  replica rows: {last['replica_rows']}; refresh age: "
                f"last {last['refresh_age']}, max "
                + str(max(r["refresh_age"] for r in reps)))
            syncs = [r for r in reps if r.get("sync_step")]
            if syncs:
                for layer in range(len(last["replica_drift_rms"])):
                    dr = [r["replica_drift_rms"][layer] for r in syncs]
                    rel = [r["replica_drift_rel"][layer] for r in syncs]
                    lines.append(
                        f"  layer {layer}: ‖replica−fresh‖ at refresh "
                        + _stats(dr) + f", relative {_fmt(rel[-1])} (last)")
            partials = [r for r in reps if r.get("refresh_kind") == "partial"]
            if partials:
                # drift-banded partial refresh (--refresh-band): the
                # actually-shipped side-channel rows per refresh — the
                # per-step face of CommStats' partial_refresh_* totals
                shipped = [sum(r["refresh_rows"]) for r in partials]
                lines.append(
                    f"  partial refreshes: {len(partials)}, shipped "
                    f"rows/refresh " + _stats(shipped)
                    + f" (side-channel wire rows "
                    f"{partials[-1].get('refresh_wire_rows')})")
        hdr = (" step      loss  grad_norm    wall_s  exposed  age"
               "  drift_rms(last layer)")
        lines.append("\n" + hdr)
        show = steps if len(steps) <= max_steps else (
            steps[: max_steps // 2] + [None] + steps[-max_steps // 2:])
        for s in show:
            if s is None:
                lines.append("  ...")
                continue
            d = s.get("drift") or {}
            r = s.get("roofline") or {}
            lines.append(
                f" {s['step']:>4} {_fmt(s['loss'], 6):>9} "
                f"{_fmt(s.get('grad_norm'), 4):>10} "
                f"{_fmt(s['wall_s'], 4):>9} "
                f"{_fmt(r.get('exposed_comm_frac'), 3):>8} "
                f"{_fmt(d.get('staleness_age')):>4} "
                f"{_fmt((d.get('halo_drift_rms') or [None])[-1], 4):>10}")

    # ------------------------------------------- memory reconciliation (v6)
    mems = [e for e in log.events if e["kind"] == "memory"]
    if mems:
        lines.append(f"\nmemory events (per compiled program): {len(mems)}")
        for ev in mems:
            joined = ""
            if ev.get("measured_peak_bytes") is not None:
                joined = (f"  measured peak {ev['measured_peak_bytes']:,} B"
                          f" (ratio {_fmt(ev.get('ratio'), 2)})")
            lines.append(
                f"  {ev.get('workload', '?')}/{ev['program']}: model "
                f"{ev['model_bytes']:,} B" + joined)

    # ---------------------------------------------- measured-time layer (v2)
    spans = [e for e in log.events if e["kind"] == "span"]
    if spans:
        lines.append(f"\nspans: {len(spans)}")
        by_name: dict = {}
        for sp in spans:
            agg = by_name.setdefault(sp["name"], [0, 0.0, 0])
            agg[0] += 1
            agg[1] += sp["dur_s"]
            agg[2] = max(agg[2], int(sp.get("depth", 0)))
        for name, (cnt, tot, depth) in sorted(by_name.items(),
                                              key=lambda kv: -kv[1][1]):
            lines.append(f"  {name}: n={cnt} total {_fmt(tot)}s "
                         f"avg {_fmt(tot / cnt)}s"
                         + (f" (max depth {depth})" if depth else ""))
    if steps:
        mvms = [s["measured_vs_model"] for s in steps
                if isinstance(s.get("measured_vs_model"), dict)]
        if mvms:
            lines.append("\nmeasured vs model (per-step reconciliation):")
            lines.append("  phase total: "
                         + _stats([m["phase_total_s"] for m in mvms]) + " s")
            for comp in mvms[-1]["components"]:
                ratios = [m["components"][comp]["ratio"] for m in mvms
                          if m["components"].get(comp, {}).get("ratio")
                          is not None]
                last = mvms[-1]["components"][comp]
                lines.append(
                    f"  {comp}: model {_fmt(last.get('model_s'))}s, "
                    f"measured {_fmt(last.get('measured_s'))}s (last)"
                    + (f"; ratio {_stats(ratios)}" if ratios else ""))
    # even a manifest-less dir (killed bench) resolves a trace copied under
    # the run dir — trace_path_for_run's last-resort rundir glob
    from sgcn_tpu.obs.tracing import summarize_trace, trace_path_for_run
    tpath = trace_path_for_run(m or {}, path)
    if tpath:
        try:
            ts = summarize_trace(tpath)
        except (OSError, ValueError, KeyError) as e:
            lines.append(f"\ntrace: {tpath} failed to parse: {e}")
            ts = None
        if ts is not None:
            lines.append(f"\ntrace ({os.path.basename(tpath)}, "
                         f"{ts.n_events} classified ops):")
            lines.append("  measured op classes: " + "  ".join(
                f"{c}={_fmt(ts.classes.get(c, 0.0))}s"
                for c in ("spmm", "dense", "exchange", "collective_wait",
                          "other")))
            roofs = [s["roofline"] for s in steps if s.get("roofline")]
            ef = [r["exposed_comm_frac"] for r in roofs
                  if "exposed_comm_frac" in r]
            if ts.measured_overlap_frac is not None:
                lines.append(
                    f"  comm: {_fmt(ts.comm_s)}s wall, "
                    f"{_fmt(ts.exposed_comm_s)}s exposed — measured "
                    f"overlap frac {_fmt(ts.measured_overlap_frac, 3)}")
                if ef:
                    lines.append(
                        "  vs analytic exposed-comm frac "
                        f"{_fmt(sum(ef) / len(ef), 3)} (event-stream mean) "
                        "— the measured-vs-model overlap join")
            if steps:
                per = ts.per_step(len(steps))
                lines.append("  per step (/" + str(len(steps)) + "): "
                             + "  ".join(
                                 f"{k}={_fmt(v)}s"
                                 for k, v in per.items() if v))
                # the exchange component of measured_vs_model, joined
                # post-hoc (the trace only exists after the run): the ONE
                # join implementation lives in tracing.exchange_join
                from sgcn_tpu.obs.tracing import exchange_join
                ehb = [r["exposed_halo_bytes"] for r in roofs
                       if "exposed_halo_bytes" in r]
                if ehb:
                    j = exchange_join(per, sum(ehb) / len(ehb))
                    line = (f"  exchange join: model {_fmt(j['model_s'])}s "
                            f"vs measured {_fmt(j['measured_s'])}s per step")
                    if "ratio" in j:
                        line += f" (ratio {_fmt(j['ratio'], 3)})"
                    nevals = len(log.evals())
                    if nevals:
                        # eval forward passes share the profiled region but
                        # are not steps — their collectives inflate the
                        # measured side, so it is an upper bound here
                        line += (f" [{nevals} evals in trace — measured is "
                                 "an upper bound]")
                    lines.append(line)
            if ts.skew:
                lines.append(
                    f"  straggler: {ts.skew['straggler']} at "
                    f"{_fmt(ts.skew['busy_max_over_mean'], 4)}x mean busy "
                    "(per-device skew gauge)")

    # ------------------------------------------------ resilience layer (v4)
    ckpts, resumes = log.checkpoints(), log.resumes()
    if ckpts or resumes:
        lines.append(f"\nresilience: {len(ckpts)} checkpoint(s), "
                     f"{len(resumes)} resume(s) (docs/resilience.md)")
        for rv in resumes:
            tag = []
            if rv.get("fallback"):
                tag.append("FELL BACK past corrupt newest: "
                           + ", ".join(os.path.basename(s)
                                       for s in rv.get("skipped", [])))
            if rv.get("partial_state"):
                tag.append("PARTIAL STATE (params-only)")
            lines.append(
                f"  resume @ step {int(rv['step'])} from "
                f"{os.path.basename(rv['path'])}"
                + (f"  [{'; '.join(tag)}]" if tag else ""))
        if ckpts:
            last = ckpts[-1]
            saves = [c["wall_s"] for c in ckpts if c.get("wall_s")
                     is not None]
            lines.append(
                f"  last checkpoint: step {int(last['step'])} → "
                f"{os.path.basename(last['path'])}"
                + (f" ({int(last['bytes'])} bytes)"
                   if last.get("bytes") is not None else "")
                + (f"; save wall_s " + _stats(saves) if saves else ""))

    serves = log.serves()
    if serves:
        lines.append(f"\nserve windows: {len(serves)} "
                     "(sgcn_tpu/serve latency gauges, schema v3)")
        for sv in serves:
            line = (f"  {int(sv['queries'])} queries @ "
                    f"{_fmt(sv['achieved_qps'])} QPS achieved"
                    + (f" (offered {_fmt(sv['offered_qps'])}, "
                       f"{sv.get('mode', '?')} loop)"
                       if sv.get("offered_qps") is not None else
                       f" ({sv.get('mode', '?')} loop)"))
            lines.append(line)
            lines.append(
                f"    latency ms: p50 {_fmt(sv['latency_p50_ms'])}  "
                f"p95 {_fmt(sv['latency_p95_ms'])}  "
                f"p99 {_fmt(sv['latency_p99_ms'])}"
                + (f"  (budget {_fmt(sv['latency_budget_ms'])})"
                   if sv.get("latency_budget_ms") is not None else ""))
            if sv.get("batches") is not None:
                lines.append(
                    f"    batches {int(sv['batches'])} "
                    f"(mean {_fmt(sv.get('mean_batch'))} queries; "
                    f"{int(sv.get('full_flushes', 0))} full / "
                    f"{int(sv.get('deadline_flushes', 0))} deadline "
                    "flushes)")
            if sv.get("compiles") is not None:
                lines.append(
                    f"    compiles {int(sv['compiles'])} over buckets "
                    f"{sv.get('buckets')} — steady-state windows must "
                    "show 0 (the no-recompile contract)")
            if sv.get("shed") is not None:
                # deadline shedding (docs/resilience.md): overdue queries
                # returned as explicit shed markers instead of silently
                # blowing the published p99
                lines.append(
                    f"    shed {int(sv['shed'])} quer"
                    f"{'y' if sv['shed'] == 1 else 'ies'} past "
                    f"{_fmt(sv.get('shed_factor'))}× the latency budget "
                    "before dispatch (explicit markers, not p99 outliers)")
            if sv.get("wire_rows_per_query") is not None:
                lines.append(
                    f"    wire ({sv.get('comm_schedule', '?')} schedule): "
                    f"{_fmt(sv['wire_rows_per_query'])} rows/query "
                    "(analytic, plan-derived)")
            if sv.get("serve_mode") is not None:
                # v5: engine mode + weight revision + the sub-graph
                # engine's accumulated per-query analytic gauges
                extra = ""
                if sv.get("touched_rows_per_query") is not None:
                    extra = (f"; {_fmt(sv['touched_rows_per_query'])} "
                             "touched rows/query, "
                             f"{_fmt(sv.get('subgraph_flops_per_query'))} "
                             "FLOPs/query (analytic)")
                lines.append(
                    f"    engine: {sv['serve_mode']} mode, weights rev "
                    f"{int(sv.get('weights_rev', 0))}{extra}")

    swaps = [e for e in log.events if e.get("kind") == "swap"]
    if swaps:
        lines.append(f"\nweight hot-swaps: {len(swaps)} (zero-recompile, "
                     "schema v5)")
        for sw in swaps:
            lines.append(
                f"  rev {int(sw['weights_rev'])} ← "
                f"{os.path.basename(sw['path'])}"
                + (f" (ckpt step {int(sw['checkpoint_step'])})"
                   if sw.get("checkpoint_step") is not None else "")
                + (f", {_fmt(sw['wall_s'])} s"
                   if sw.get("wall_s") is not None else ""))

    for ev in log.evals():
        lines.append(f"\neval @ step {ev['step']}: loss {_fmt(ev['loss'])}"
                     + (f", acc {_fmt(ev['acc'])}" if "acc" in ev else ""))
    for sm in log.summaries():
        rep = sm["report"]
        keys = [k for k in ("metric", "value", "unit", "epochs", "epoch_s",
                            "err", "total_send_volume") if k in rep]
        lines.append("\nsummary: "
                     + " ".join(f"{k}={_fmt(rep[k])}" for k in keys))
    if log.heartbeats:
        lines.append(f"\nheartbeats: {len(log.heartbeats)}")
        t0 = log.heartbeats[0]["ts"]
        for hb in log.heartbeats[-20:]:
            lines.append(f"  +{hb['ts'] - t0:8.2f}s  pid {hb.get('pid')}  "
                         f"{hb['event']}"
                         + (f" — {hb['detail']}" if hb.get("detail") else ""))
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("rundir", help="directory written by --metrics-out")
    ap.add_argument("--steps", type=int, default=12,
                    help="max rows in the per-step table (head+tail)")
    args = ap.parse_args()
    try:
        print(render(args.rundir, max_steps=args.steps))
    except (OSError, ValueError) as e:
        print(f"obs_report: {args.rundir} failed to load: {e}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
