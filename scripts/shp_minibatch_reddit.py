"""SHP → mini-batch trainer composition at Reddit shape (VERDICT r3 item 6).

The reference pipeline: ``GPU/SHP/main.py`` pickles a baseline full-graph HP
partvec and a stochastic-HP partvec (``:131-140``), and
``GPU/PGCN-Mini-batch.py:217-218`` consumes one of them for distributed
mini-batch training.  The paper's SHP claim is that the stochastic partition
lowers EXPECTED mini-batch communication; round 3 only simulated that at toy
size.  This script measures it IN THE TRAINER at Reddit's vertex count:

  1. generate a power-law graph at Reddit's n (232 965 vertices; zero egress
     forbids the real 114M-edge Reddit, so degree is the products-like 50 —
     the vertex count and batch geometry are what SHP cares about),
  2. run the SHP pipeline (k=8, batch 4096 — the BASELINE.json Reddit
     config) producing pv_hp and pv_stchp,
  3. build the mini-batch trainer under EACH partvec on the virtual-8 CPU
     mesh, run the fused one-program epoch sweep, and report the
     TRAINER-side comm volumes (CommStats counters — the same numbers the
     reference prints at end of run, ``GPU/PGCN.py:230-238``) plus the
     fused-epoch wall-clock,
  4. write ``bench_artifacts/shp_reddit.json``.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     PYTHONPATH=/root/repo python scripts/shp_minibatch_reddit.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import argparse

    import jax
    jax.config.update("jax_platforms", "cpu")

    from sgcn_tpu.io.datasets import ba_graph, dcsbm_graph
    from sgcn_tpu.prep import normalize_adjacency
    from sgcn_tpu.shp.model import run_shp
    from sgcn_tpu.train.minibatch import MiniBatchTrainer

    ap = argparse.ArgumentParser()
    # dcsbm (VERDICT r4 item 5): the real Reddit is community-structured
    # (41 subreddit classes) like dcsbm, NOT an expander like ba — ba is
    # where partitioning cannot win, so it under-sells the SHP margin
    ap.add_argument("--graph", default="ba", choices=["ba", "dcsbm"])
    args = ap.parse_args()

    n, k, batch = 232_965, 8, 4096
    t0 = time.time()
    if args.graph == "ba":
        a = ba_graph(n, 25, seed=0)
        gnote = ("Reddit vertex count; synthetic power-law (zero egress), "
                 "deg ~50")
    else:
        a = dcsbm_graph(n, ncomm=50, avg_deg=50, seed=0)
        gnote = ("Reddit vertex count; dcsbm power-law+communities "
                 "(the real Reddit's structure profile), deg ~50")
    ahat = normalize_adjacency(a)
    del a
    print(f"graph n={n} nnz={ahat.nnz} {time.time()-t0:.0f}s", flush=True)

    # 100 sampled batches: each 4096-vertex batch touches ~1.8% of the
    # vertices, so the stochastic hypergraph needs enough samples to SEE the
    # batch distribution (an under-sampled one measurably LOSES to plain hp
    # — observed at toy scale with 6 batches); 100 keeps the stacked
    # hypergraph ~6M pins, well inside the partitioner's budget
    t0 = time.time()
    shp = run_shp(ahat, k, nsampled_batches=100, batch_size=batch,
                  sim_iters=20, seed=1)
    t_shp = time.time() - t0
    print(f"shp: km1_hp={shp['km1_hp']} km1_stchp={shp['km1_stchp']} "
          f"sim hp={shp['sim_comm_volume_hp']} "
          f"stchp={shp['sim_comm_volume_stchp']} ({t_shp:.0f}s)", flush=True)

    rng = np.random.default_rng(0)
    feats = rng.standard_normal((n, 64)).astype(np.float32)
    labels = rng.integers(0, 16, size=n).astype(np.int32)

    out = {
        "graph": {"family": args.graph, "n": n, "nnz": int(ahat.nnz),
                  "note": gnote},
        "k": k, "batch_size": batch,
        "shp_pipeline_s": round(t_shp, 1),
        "km1_fullgraph": {"hp": int(shp["km1_hp"]),
                          "stchp": int(shp["km1_stchp"])},
        "simulated_batch_volume": {
            "hp": int(shp["sim_comm_volume_hp"]),
            "stchp": int(shp["sim_comm_volume_stchp"])},
    }

    for name in ("hp", "stchp"):
        pv = shp[f"partvec_{name}"]
        t0 = time.time()
        tr = MiniBatchTrainer(ahat, pv, k, fin=64, widths=[64, 16],
                              batch_size=batch, seed=0)
        t_build = time.time() - t0
        # warm-up (compile) then timed fused sweeps
        losses = tr.run_epochs_fused(feats, labels, epochs=1)
        t0 = time.time()
        losses = tr.run_epochs_fused(feats, labels, epochs=3)
        epoch_s = (time.time() - t0) / 3
        rep = tr.fused_stats_report()
        # per-epoch deterministic plan volume (counters accumulate over the
        # warm-up too, so report the per-epoch plan prediction alongside)
        plan_vol = sum(int(p.predicted_send_volume.sum()) for p in tr.plans)
        out[name] = {
            "nbatches": len(tr.plans),
            "build_s": round(t_build, 1),
            "epoch_s_8dev_cpu": round(epoch_s, 4),
            "final_loss": float(np.asarray(losses)[-1]),
            "plan_send_rows_per_layer_pass": plan_vol,
            "trainer_total_send_volume": int(rep["total_send_volume"]),
            "trainer_total_send_msgs": int(rep["total_send_msgs"]),
        }
        print(name, json.dumps(out[name]), flush=True)

    out["volume_ratio_stchp_vs_hp"] = round(
        out["stchp"]["plan_send_rows_per_layer_pass"]
        / max(out["hp"]["plan_send_rows_per_layer_pass"], 1), 4)
    dst = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench_artifacts", "shp_reddit.json")
    # per-family blocks: the ba and dcsbm runs coexist in one artifact
    rec = {}
    if os.path.exists(dst):
        with open(dst) as f:
            rec = json.load(f)
        if "graph" in rec:           # migrate the old single-run layout
            rec = {rec["graph"]["family"]: rec}
    rec[args.graph] = out
    tmp = dst + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1)
    os.replace(tmp, dst)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
