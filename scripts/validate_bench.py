"""Schema-validate the checked-in bench evidence files.

Usage::

    python scripts/validate_bench.py [ROOT]      # default: repo root

Validates every ``BENCH_*.json`` / ``MULTICHIP_*.json`` at the root and
every ``bench_artifacts/*.json``, and exits non-zero listing each
violation.  Run by the tier-1 suite (``tests/test_validate_bench.py``), so
a hand-edited or wrongly-shaped artifact fails CI instead of silently
poisoning the evidence chain.

What counts as a violation:

  * **driver records** (``BENCH_*``): missing ``n/cmd/rc/tail``; an rc=0
    record without a parseable one-line result (``parsed``); a result with
    ``value: null`` but NO ``skipped``/``degraded`` marker — the graceful-
    degradation contract says a missing number must explain itself;
  * **measurement quality**: a ``measurement`` block claiming more clean
    differential estimates than were targeted (impossible by construction
    — a hand-edit tell);
  * **dryrun records** (``MULTICHIP_*``): ``ok: true`` with a non-zero rc,
    or ``ok: false`` with no ``skipped``/``degraded`` explanation;
  * **non-standard JSON**: ``NaN``/``Infinity`` tokens — ``json.dumps``
    emits them for non-finite floats, but they are not valid JSON and no
    checked-in artifact may carry them;
  * **ragged-schedule accounting** (PR-4; GAT flavor PR-5): a flagship
    result carrying ``comm_schedule`` must name a resolved schedule (never
    ``auto``); a ``ragged_ab_8dev`` / ``gat_ragged_ab_8dev`` A/B block must
    either be a per-partition dict whose configs carry positive timings,
    ``padding_efficiency`` in (0, 1], a padded/true ratio ≥ 1, and
    ``wire_rows_ragged ≤ wire_rows_a2a`` (per-round pads can never exceed
    the global pad — a violation is a hand-edit tell; the GAT block's hp
    config must win STRICTLY — the satellite's acceptance figure, asserted
    on wire rows, never epoch speed), or be ``null`` WITH a matching
    ``*_degraded`` marker;
  * **composed-mode accounting** (PR-6): a ``ragged_stale_ab_8dev`` block
    must carry all three arms (a2a+stale, ragged+exact, ragged+stale) with
    positive timings and an exposed-comm accounting in which the composed
    arm is ≤ both single levers on the exposed fraction and STRICTLY below
    both on exposed wire rows per step, plus the honest-measurement note
    (CPU-mesh epoch speed is never the asserted figure), or be ``null``
    with a degradation marker;
  * **measured-time provenance** (PR-7): an epoch-time claim (a numeric
    ``value`` on a ``*_epoch_time`` metric) must carry ``measured: true``
    — the flag ``bench.py`` sets only when the number came out of a live
    differential measurement in that process — or a ``skipped``/
    ``degraded`` marker.  Enforced from round ``BENCH_r06`` on (the first round generated after the flag landed; earlier
    records predate the flag and retro-stamping provenance onto history
    would itself be a hand-edit); a ``measured`` flag that is present but
    not literally ``true`` is a violation at ANY round;
  * **memory provenance** (ISSUE 18): any numeric ``*_bytes`` residency
    claim in a bench block must sit under ``analytic: true`` (plan-derived,
    ``sgcn_tpu.obs.memory``) or ``measured: true`` (XLA
    ``memory_analysis()``) provenance — itself or via an enclosing block;
    enforced from round ``BENCH_r06`` on like the measured-time rule, and
    a present-but-untrue ``analytic`` flag is a violation at ANY round;
  * **serving-bench accounting** (PR-8): a ``serve_qps_8dev`` block must
    carry both transport arms with positive achieved QPS, ordered positive
    latency quantiles under ``measured: true`` provenance, compile counters
    within the pre-compiled bucket count (a runtime recompile violates the
    bucket contract), a STRICT ragged-vs-a2a wire-row win on the skewed hp
    partition (the forward-only carry-over of the training schedules'
    acceptance figure — never CPU-mesh latency; the ``note`` says so), or
    be ``null`` with a ``serve_qps_degraded`` marker;
  * **replication accounting** (PR-10): a ``replica_ab_8dev`` block must
    carry ``replica_budget > 0`` and per-partition configs whose shrunken
    figures (replica true/wire rows, cumulative true bytes) never exceed
    the full ones, with the hp config winning STRICTLY on
    ``halo_bytes_true_total`` and wire rows/step (the CaPGNN before/after
    metric — never CPU-mesh epoch speed; the ``note`` says so) and the
    cache-aware km1 ≤ the cache-blind partition's cache objective
    (``check_replica_ab``), or be ``null`` with a ``replica_ab_degraded``
    marker;
  * **static-analysis report** (``bench_artifacts/analysis_report.json``,
    PR-9): a committed report must be a FULL-matrix run (``fast: false``)
    with ``ok: true`` and internally consistent — an ``ok`` flag
    contradicting its own violation lists, a red report committed as
    evidence, or a matrix shrunk below the supported floor are all
    hand-edit tells (``check_analysis_report``);
  * **resume provenance** (PR-13, ``docs/resilience.md``): a parsed result
    claiming a resume must name the checkpoint that seeded it — either the
    trainer CLI's ``resumed: {step, path, fallback}`` block (its identity
    fields validated), or a bare ``resumed: true`` flag WITH a
    ``checkpoint_meta`` ``{step, version}`` block; any other ``resumed``
    value is a violation anywhere, same rule as the ``measured`` flag
    (the provenance flag may only assert a real resume);
  * **the pow2-k RB constraint** (``products_ksweep.json``): ``hp_rb``
    entries at non-power-of-two k, or k < 32.  The PR-2 review incident:
    ``partition_hypergraph_rb`` recurses on k/2 and the auto-select
    (``native/sgcnpart.cpp``) only fires for pow2 k >= 32, so RB results
    at k ∈ {9, 15, 21, 27} were unreproducible with the code at HEAD and
    had to be reverted.  This check makes that class of edit impossible to
    land quietly; if non-pow2 RB support ever lands, regenerate the sweep
    with ``scripts/products_ksweep.py`` and update this rule WITH it.
"""

from __future__ import annotations

import glob
import json
import numbers
import os
import re
import sys

_BENCH_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _load_strict(path: str):
    """Parse refusing the NaN/Infinity extensions (hand-edit / bad-generator
    tell — not valid JSON, and every reader downstream would choke)."""
    def bad_constant(name):
        raise ValueError(f"non-standard JSON constant {name!r}")

    with open(path) as fh:
        return json.load(fh, parse_constant=bad_constant)


def _is_num(x) -> bool:
    return isinstance(x, numbers.Real) and not isinstance(x, bool)


# first bench round whose driver record must carry epoch-time provenance
# (bench.py emits ``measured: true`` since PR-7; earlier history predates
# the flag, and stamping it onto old records would itself be a hand-edit)
MEASURED_PROVENANCE_SINCE = 6


def check_measured_provenance(rec: dict, round_no: int | None) -> list[str]:
    """The epoch-time provenance rule (module docstring): numeric
    ``*_epoch_time`` values need ``measured: true`` from round
    ``MEASURED_PROVENANCE_SINCE`` on; a present-but-untrue flag is always
    a violation (asserting anything but a live measurement is a lie)."""
    if not isinstance(rec.get("parsed"), dict):
        return []
    parsed = rec["parsed"]
    errs = []
    # flag integrity applies to ANY record carrying the flag — including a
    # failed round (rc != 0): a hand-edited false/yes flag is a lie there
    # too, so only the numeric-claim rule below is rc-gated
    if "measured" in parsed and parsed["measured"] is not True:
        errs.append(f"measured={parsed['measured']!r}: the provenance flag "
                    "may only assert a live measurement (true) — drop it "
                    "or fix the generator")
    if rec.get("rc") != 0:
        return errs
    metric = parsed.get("metric")
    if (isinstance(metric, str) and metric.endswith("_epoch_time")
            and _is_num(parsed.get("value"))
            and parsed.get("measured") is not True
            and not (isinstance(parsed.get("skipped"), str)
                     or isinstance(parsed.get("degraded"), str))
            and (round_no is None
                 or round_no >= MEASURED_PROVENANCE_SINCE)):
        errs.append(f"numeric {metric} value without measured:true "
                    "provenance (or a skipped/degraded marker) — an "
                    "epoch-time claim must say it was measured live "
                    "(bench.py sets the flag; rounds < "
                    f"r{MEASURED_PROVENANCE_SINCE:02d} are grandfathered)")
    return errs


# first bench round whose residency-byte claims must carry provenance
# (bench.py stamps ``analytic: true`` on the memory_footprint_8dev block
# since ISSUE 18; earlier history predates the vocabulary)
MEMORY_PROVENANCE_SINCE = 6


def check_memory_provenance(rec: dict, round_no: int | None) -> list[str]:
    """The memory-provenance rule (ISSUE 18, the residency flavor of the
    epoch-time rule above): any numeric ``*_bytes`` claim in a bench block
    must sit in a dict that — itself or via an enclosing block — declares
    how the number was obtained: ``analytic: true`` (derived purely from
    the CommPlan + model config, ``sgcn_tpu.obs.memory``) or ``measured:
    true`` (XLA's own ``compiled.memory_analysis()``).  A residency byte
    with neither provenance is unfalsifiable.  Flag integrity — a
    present-but-untrue ``analytic`` flag — is a violation in ANY round
    (asserting plan-derivation falsely is a lie); the claim rule is
    rc- and round-gated like the measured-time rule."""
    if not isinstance(rec.get("parsed"), dict):
        return []
    errs: list[str] = []
    claim_gated = (rec.get("rc") == 0
                   and (round_no is None
                        or round_no >= MEMORY_PROVENANCE_SINCE))

    def walk(node, path: str, flagged: bool, root: bool = False) -> None:
        if isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, f"{path}[{i}]", flagged)
            return
        if not isinstance(node, dict):
            return
        if "analytic" in node and node["analytic"] is not True:
            errs.append(
                f"{path or 'parsed'}: analytic={node['analytic']!r} — the "
                "provenance flag may only assert a plan-derived figure "
                "(true); drop it or fix the generator")
        # the ROOT parsed dict's flags do not count as byte provenance:
        # its `measured: true` asserts the headline TIME value was timed
        # live (check_measured_provenance) — letting it inherit downward
        # would make this rule vacuous on every bench record
        here = (not root) and (flagged or node.get("analytic") is True
                               or node.get("measured") is True)
        for k, v in node.items():
            if (claim_gated and isinstance(k, str) and k.endswith("_bytes")
                    and _is_num(v) and not here):
                errs.append(
                    f"{path or 'parsed'}: numeric residency claim {k!r} "
                    "without analytic:true or measured:true provenance in "
                    "its block — a byte count must say whether it is "
                    "plan-derived (sgcn_tpu.obs.memory) or from XLA "
                    "memory_analysis() (rounds < "
                    f"r{MEMORY_PROVENANCE_SINCE:02d} are grandfathered)")
            walk(v, f"{path}/{k}" if path else k, here)

    walk(rec["parsed"], "", False, root=True)
    return errs


def check_bench_record(rec: dict) -> list[str]:
    errs = []
    for key, typ in (("n", numbers.Integral), ("cmd", str),
                     ("rc", numbers.Integral), ("tail", str)):
        if not isinstance(rec.get(key), typ):
            errs.append(f"missing/badly-typed driver key {key!r}")
    if errs:
        return errs
    if rec["rc"] == 0:
        parsed = rec.get("parsed")
        if not isinstance(parsed, dict):
            errs.append("rc=0 but no parsed one-line JSON result")
            return errs
        if not isinstance(parsed.get("metric"), str):
            errs.append("parsed result missing string 'metric'")
        if "value" not in parsed:
            errs.append("parsed result missing 'value'")
        elif parsed["value"] is None:
            if not (isinstance(parsed.get("skipped"), str)
                    or isinstance(parsed.get("degraded"), str)):
                errs.append("value=null without a skipped/degraded marker "
                            "(graceful-degradation contract)")
        elif not _is_num(parsed["value"]):
            errs.append(f"value is {type(parsed['value']).__name__}, "
                        "expected number or null")
        meas = parsed.get("measurement")
        if isinstance(meas, dict) and meas:
            ce, te = meas.get("clean_estimates"), meas.get("target_estimates")
            if not (isinstance(ce, numbers.Integral)
                    and isinstance(te, numbers.Integral)
                    and 1 <= ce <= te):
                errs.append(f"measurement block inconsistent: "
                            f"clean={ce} target={te}")
        if "comm_schedule" in parsed and parsed["comm_schedule"] not in (
                "a2a", "ragged"):
            errs.append(f"comm_schedule={parsed['comm_schedule']!r} is not "
                        "a resolved schedule (a2a|ragged; 'auto' must "
                        "resolve before emission)")
        if "ragged_ab_8dev" in parsed:
            errs += check_ragged_ab(parsed)
        if "gat_ragged_ab_8dev" in parsed:
            errs += check_ragged_ab(parsed, prefix="gat_ragged_ab")
        if "ragged_stale_ab_8dev" in parsed:
            errs += check_ragged_stale_ab(parsed)
        if "pallas_ragged_ab_8dev" in parsed:
            errs += check_pallas_ragged_ab(parsed)
        if "replica_ab_8dev" in parsed:
            errs += check_replica_ab(parsed)
        if "controller_ab_8dev" in parsed:
            errs += check_controller_ab(parsed)
        if "serve_qps_8dev" in parsed:
            errs += check_serve_qps(parsed)
        if "serve_subgraph_ab_8dev" in parsed:
            errs += check_serve_subgraph_ab(parsed)
    if isinstance(rec.get("parsed"), dict):
        # flag integrity applies even to failed rounds (cf. `measured`)
        errs += check_resume_provenance(rec["parsed"])
    return errs


def check_resume_provenance(parsed: dict) -> list[str]:
    """The resume-provenance rule (module docstring): a resume claim must
    name the checkpoint that seeded it, in one of the two shapes the repo
    produces — the trainer CLI's ``resumed: {step, path, fallback}``
    block (the report ``--resume auto`` emits, which IS the identity), or
    a bare ``resumed: true`` flag accompanied by a ``checkpoint_meta``
    ``{step, version}`` block.  Anything else is unverifiable."""
    if "resumed" not in parsed:
        return []
    errs = []
    res = parsed["resumed"]
    if isinstance(res, dict):
        # the trainer CLI's shape: the block itself names the checkpoint
        if not (isinstance(res.get("step"), numbers.Integral)
                and res["step"] >= 0
                and isinstance(res.get("path"), str) and res["path"]):
            errs.append(f"resumed block {res!r} missing its checkpoint "
                        "identity ({step >= 0, path} — the trainer CLI's "
                        "--resume auto shape, docs/resilience.md)")
        return errs
    if res is not True:
        errs.append(f"resumed={res!r}: the provenance flag may only "
                    "assert a real resume (true, or the trainer's "
                    "{step, path, ...} block) — drop it or fix the "
                    "generator")
        return errs
    meta = parsed.get("checkpoint_meta")
    if not (isinstance(meta, dict)
            and isinstance(meta.get("step"), numbers.Integral)
            and meta["step"] >= 0
            and isinstance(meta.get("version"), numbers.Integral)
            and meta["version"] >= 1):
        errs.append("resumed:true without a matching checkpoint_meta "
                    "block ({step >= 0, version >= 1} at minimum) — a "
                    "resume claim must name the checkpoint that seeded it "
                    "(docs/resilience.md)")
    return errs


def check_controller_ab(parsed: dict) -> list[str]:
    """The adaptive-controller A/B contract (PR-12,
    docs/comm_schedule.md): a ``controller_ab_8dev`` block must carry the
    controller arm plus all four static arms with positive paired epoch
    times and a consistent exposed-wire accounting in which the
    controller's exposed wire rows per step are <= EVERY static arm and
    STRICTLY below at least one — the controller's acceptance figure
    (never CPU-mesh epoch time; the honest-measurement ``note`` must say
    so).  ``null`` needs a ``controller_ab_degraded`` marker."""
    errs = []
    block = parsed["controller_ab_8dev"]
    if block is None:
        if not isinstance(parsed.get("controller_ab_degraded"), str):
            errs.append("controller_ab_8dev null without a "
                        "controller_ab_degraded marker "
                        "(graceful-degradation contract)")
        return errs
    if not isinstance(block, dict):
        return [f"controller_ab_8dev is {type(block).__name__}, expected "
                "dict or null"]
    arms = block.get("arms")
    if not isinstance(arms, dict):
        return ["controller_ab_8dev carries no arms dict"]
    required = ("controller", "a2a_exact", "ragged_exact", "ragged_stale",
                "replica_stale")
    missing = [a for a in required if not isinstance(arms.get(a), dict)]
    if missing:
        return [f"controller_ab_8dev missing arm(s) {missing}"]
    for nm in required:
        e = arms[nm]
        if not (_is_num(e.get("epoch_s")) and e["epoch_s"] > 0):
            errs.append(f"controller_ab_8dev.arms.{nm}.epoch_s="
                        f"{e.get('epoch_s')!r}")
        if not (_is_num(e.get("exposed_wire_rows_per_step"))
                and e["exposed_wire_rows_per_step"] >= 0):
            errs.append(f"controller_ab_8dev.arms.{nm}."
                        "exposed_wire_rows_per_step="
                        f"{e.get('exposed_wire_rows_per_step')!r}")
    if errs:
        return errs
    ce = arms["controller"]["exposed_wire_rows_per_step"]
    statics = [nm for nm in required if nm != "controller"]
    worse = [nm for nm in statics
             if ce > arms[nm]["exposed_wire_rows_per_step"]]
    if worse:
        errs.append(
            f"controller_ab_8dev: controller exposed wire rows/step {ce} "
            f"above static arm(s) {worse} — the controller's acceptance "
            "inequality")
    if not any(ce < arms[nm]["exposed_wire_rows_per_step"]
               for nm in statics):
        errs.append(
            f"controller_ab_8dev: controller exposed wire rows/step {ce} "
            "not STRICTLY below any static arm — a universal tie is not "
            "a win")
    cp = block.get("clean_pairs")
    if not (_is_num(cp) and cp >= 1):
        errs.append(f"controller_ab_8dev: clean_pairs={cp!r}")
    note = block.get("note")
    if not (isinstance(note, str) and "exposed" in note):
        errs.append("controller_ab_8dev: missing the honest-measurement "
                    "note naming exposed wire rows as the asserted figure "
                    "(CPU-mesh epoch speed is not the claim)")
    return errs


def check_serve_qps(parsed: dict) -> list[str]:
    """The serving-bench block contract (PR-8): a ``serve_qps_8dev`` block
    must carry both transport arms (a2a, ragged) with positive achieved QPS,
    ordered positive latency quantiles UNDER ``measured: true`` provenance
    (latency claims are live host-clock measurements, same rule as the
    epoch-time flag), zero steady-state recompiles implied by consistent
    bucket/compile counters, and the wire-row accounting in which the
    ragged arm ships STRICTLY fewer wire rows than a2a on the skewed hp
    partition — the forward-only carry-over of the training schedules' win
    (never CPU-mesh latency; the block's ``note`` must say so).  ``null``
    needs a ``serve_qps_degraded`` marker."""
    errs = []
    block = parsed["serve_qps_8dev"]
    if block is None:
        if not isinstance(parsed.get("serve_qps_degraded"), str):
            errs.append("serve_qps_8dev null without a serve_qps_degraded "
                        "marker (graceful-degradation contract)")
        return errs
    if not isinstance(block, dict):
        return [f"serve_qps_8dev is {type(block).__name__}, expected "
                "dict or null"]
    if block.get("measured") is not True:
        errs.append("serve_qps_8dev: latency claims without measured:true "
                    "provenance — quantiles must come from a live "
                    "measurement in the emitting process")
    if not (_is_num(block.get("offered_qps")) and block["offered_qps"] > 0):
        errs.append(f"serve_qps_8dev: offered_qps="
                    f"{block.get('offered_qps')!r}")
    arms = block.get("arms")
    if not isinstance(arms, dict):
        return errs + ["serve_qps_8dev carries no arms dict"]
    missing = [a for a in ("a2a", "ragged") if not isinstance(arms.get(a),
                                                             dict)]
    if missing:
        return errs + [f"serve_qps_8dev missing arm(s) {missing}"]
    for nm in ("a2a", "ragged"):
        e = arms[nm]
        if not (_is_num(e.get("achieved_qps")) and e["achieved_qps"] > 0):
            errs.append(f"serve_qps_8dev.arms.{nm}.achieved_qps="
                        f"{e.get('achieved_qps')!r}")
        p50, p99 = e.get("latency_p50_ms"), e.get("latency_p99_ms")
        if not (_is_num(p50) and _is_num(p99) and 0 < p50 <= p99):
            errs.append(f"serve_qps_8dev.arms.{nm}: latency quantiles "
                        f"p50={p50!r} p99={p99!r} (need 0 < p50 <= p99)")
        for key in ("wire_rows_per_exchange", "wire_rows_per_query"):
            if not (_is_num(e.get(key)) and e[key] >= 0):
                errs.append(f"serve_qps_8dev.arms.{nm}.{key}="
                            f"{e.get(key)!r}")
        comp = e.get("compiles")
        bkts = e.get("buckets")
        if comp is not None and isinstance(bkts, list):
            if not (_is_num(comp) and comp <= len(bkts)):
                errs.append(
                    f"serve_qps_8dev.arms.{nm}: compiles={comp!r} exceeds "
                    f"the {len(bkts)} pre-compiled buckets — a runtime "
                    "recompile violates the bucket contract")
    if errs:
        return errs
    wa = arms["a2a"]["wire_rows_per_exchange"]
    wr = arms["ragged"]["wire_rows_per_exchange"]
    if not wr < wa:
        errs.append(f"serve_qps_8dev: wire_rows_ragged={wr!r} not STRICTLY "
                    f"below wire_rows_a2a={wa!r} on the skewed partition — "
                    "the forward-only carry-over of the schedule's "
                    "acceptance figure")
    tr_, wq = (arms["ragged"].get("true_rows_per_exchange"),
               arms["ragged"]["wire_rows_per_exchange"])
    if _is_num(tr_) and tr_ > wq:
        errs.append(f"serve_qps_8dev: true_rows={tr_!r} above "
                    f"wire_rows_ragged={wq!r}")
    note = block.get("note")
    if not (isinstance(note, str) and "wire" in note):
        errs.append("serve_qps_8dev: missing the honest-measurement note "
                    "naming the wire-row accounting as the asserted figure "
                    "(CPU-mesh latency is not the cross-transport claim)")
    return errs


def check_serve_subgraph_ab(parsed: dict) -> list[str]:
    """The sub-graph serving A/B contract (PR-14, docs/serving.md phase 2):
    a ``serve_subgraph_ab_8dev`` block must carry both engine arms (full,
    subgraph) with positive achieved QPS and ordered positive latency
    quantiles UNDER ``measured: true`` provenance, positive analytic
    per-query figures, and the acceptance inequality: the sub-graph arm's
    analytic rows/query AND FLOPs/query must both sit ≥10× below the full
    arm's (the ``*_cut`` fields must agree with the arms they summarize —
    never CPU-mesh latency; the ``note`` must say so).  ``null`` needs a
    ``serve_subgraph_degraded`` marker."""
    errs = []
    block = parsed["serve_subgraph_ab_8dev"]
    if block is None:
        if not isinstance(parsed.get("serve_subgraph_degraded"), str):
            errs.append("serve_subgraph_ab_8dev null without a "
                        "serve_subgraph_degraded marker "
                        "(graceful-degradation contract)")
        return errs
    if not isinstance(block, dict):
        return [f"serve_subgraph_ab_8dev is {type(block).__name__}, "
                "expected dict or null"]
    if block.get("measured") is not True:
        errs.append("serve_subgraph_ab_8dev: latency claims without "
                    "measured:true provenance")
    arms = block.get("arms")
    if not isinstance(arms, dict):
        return errs + ["serve_subgraph_ab_8dev carries no arms dict"]
    missing = [a for a in ("full", "subgraph")
               if not isinstance(arms.get(a), dict)]
    if missing:
        return errs + [f"serve_subgraph_ab_8dev missing arm(s) {missing}"]
    for nm in ("full", "subgraph"):
        e = arms[nm]
        if not (_is_num(e.get("achieved_qps")) and e["achieved_qps"] > 0):
            errs.append(f"serve_subgraph_ab_8dev.arms.{nm}.achieved_qps="
                        f"{e.get('achieved_qps')!r}")
        p50, p99 = e.get("latency_p50_ms"), e.get("latency_p99_ms")
        if not (_is_num(p50) and _is_num(p99) and 0 < p50 <= p99):
            errs.append(f"serve_subgraph_ab_8dev.arms.{nm}: latency "
                        f"quantiles p50={p50!r} p99={p99!r} "
                        "(need 0 < p50 <= p99)")
        for key in ("rows_per_query", "flops_per_query"):
            if not (_is_num(e.get(key)) and e[key] > 0):
                errs.append(f"serve_subgraph_ab_8dev.arms.{nm}.{key}="
                            f"{e.get(key)!r}")
    det = block.get("analytic")
    if not isinstance(det, dict):
        errs.append("serve_subgraph_ab_8dev carries no analytic block — "
                    "the asserted cuts must come from the DETERMINISTIC "
                    "fixed-chunking gauges, not the real-clock arms")
    if errs:
        return errs
    for fk, sk, cut_key in (
            ("full_rows_per_query", "subgraph_rows_per_query",
             "rows_per_query_cut"),
            ("full_flops_per_query", "subgraph_flops_per_query",
             "flops_per_query_cut")):
        full_v, sub_v = det.get(fk), det.get(sk)
        if not (_is_num(full_v) and _is_num(sub_v) and full_v > 0
                and sub_v > 0):
            errs.append(f"serve_subgraph_ab_8dev.analytic: {fk}={full_v!r} "
                        f"/ {sk}={sub_v!r}")
            continue
        cut = block.get(cut_key)
        if not (_is_num(cut) and cut >= 10.0):
            errs.append(f"serve_subgraph_ab_8dev: {cut_key}={cut!r} below "
                        "the >=10x acceptance cut (the query-proportional "
                        "claim)")
        elif abs(cut - full_v / max(sub_v, 1e-9)) > 0.01 * max(cut, 1.0):
            errs.append(f"serve_subgraph_ab_8dev: {cut_key}={cut!r} "
                        f"inconsistent with its own analytic block "
                        f"({full_v}/{sub_v}) — the summary must be "
                        "derivable from its record")
    note = block.get("note")
    if not (isinstance(note, str) and "ANALYTIC" in note):
        errs.append("serve_subgraph_ab_8dev: missing the honest-"
                    "measurement note naming the ANALYTIC per-query gauges "
                    "as the asserted figures (CPU-mesh latency is not the "
                    "cross-arm claim)")
    return errs


def check_ragged_stale_ab(parsed: dict) -> list[str]:
    """The composed-mode three-way A/B contract (PR-6): the
    ``ragged_stale_ab_8dev`` block must carry all three arms (a2a+stale,
    ragged+exact, ragged+stale) with positive paired-differential timings
    and a consistent exposed-comm accounting in which the composed arm's
    exposed fraction is <= both single levers and its exposed wire rows
    per step are STRICTLY below both — the acceptance figure of the
    composition (never CPU-mesh epoch speed; the block must say so in its
    honest-measurement ``note``).  ``null`` needs a degradation marker."""
    errs = []
    block = parsed["ragged_stale_ab_8dev"]
    if block is None:
        if not isinstance(parsed.get("ragged_stale_ab_degraded"), str):
            errs.append("ragged_stale_ab_8dev null without a "
                        "ragged_stale_ab_degraded marker "
                        "(graceful-degradation contract)")
        return errs
    if not isinstance(block, dict):
        return [f"ragged_stale_ab_8dev is {type(block).__name__}, expected "
                "dict or null"]
    arms = block.get("arms")
    if not isinstance(arms, dict):
        return ["ragged_stale_ab_8dev carries no arms dict"]
    required = ("a2a_stale", "ragged_exact", "ragged_stale")
    missing = [a for a in required if not isinstance(arms.get(a), dict)]
    if missing:
        return [f"ragged_stale_ab_8dev missing arm(s) {missing}"]
    for nm in required:
        e = arms[nm]
        if not (_is_num(e.get("epoch_s")) and e["epoch_s"] > 0):
            errs.append(f"ragged_stale_ab_8dev.arms.{nm}.epoch_s="
                        f"{e.get('epoch_s')!r}")
        frac = e.get("exposed_comm_frac")
        if not (_is_num(frac) and 0 <= frac <= 1):
            errs.append(f"ragged_stale_ab_8dev.arms.{nm}: "
                        f"exposed_comm_frac={frac!r} outside [0, 1]")
        for key in ("wire_rows_per_exchange", "exposed_wire_rows_per_step"):
            if not (_is_num(e.get(key)) and e[key] >= 0):
                errs.append(f"ragged_stale_ab_8dev.arms.{nm}.{key}="
                            f"{e.get(key)!r}")
    if errs:
        return errs
    comp, a2s, rex = (arms["ragged_stale"], arms["a2a_stale"],
                      arms["ragged_exact"])
    if not (comp["exposed_comm_frac"] <= a2s["exposed_comm_frac"]
            and comp["exposed_comm_frac"] <= rex["exposed_comm_frac"]):
        errs.append("ragged_stale_ab_8dev: composed exposed_comm_frac "
                    f"{comp['exposed_comm_frac']} exceeds a single lever's "
                    "— the composition's acceptance inequality")
    if not (comp["exposed_wire_rows_per_step"]
            < a2s["exposed_wire_rows_per_step"]
            and comp["exposed_wire_rows_per_step"]
            < rex["exposed_wire_rows_per_step"]):
        errs.append("ragged_stale_ab_8dev: composed exposed wire rows "
                    f"{comp['exposed_wire_rows_per_step']} not STRICTLY "
                    "below both single levers "
                    f"({a2s['exposed_wire_rows_per_step']}, "
                    f"{rex['exposed_wire_rows_per_step']})")
    cp = block.get("clean_pairs")
    if not (_is_num(cp) and cp >= 1):
        errs.append(f"ragged_stale_ab_8dev: clean_pairs={cp!r}")
    note = block.get("note")
    if not (isinstance(note, str) and "exposed" in note):
        errs.append("ragged_stale_ab_8dev: missing the honest-measurement "
                    "note naming exposed-comm accounting as the asserted "
                    "figure (CPU-mesh epoch speed is not the claim)")
    return errs


def check_ragged_ab(parsed: dict, prefix: str = "ragged_ab") -> list[str]:
    """The a2a-vs-ragged A/B block contract (see module docstring); the
    same rules validate the GCN block (``ragged_ab_8dev``) and the GAT one
    (``gat_ragged_ab_8dev``, PR-5).  The GAT block additionally requires a
    STRICT wire-row win on the skewed hp partition — the satellite's
    acceptance figure (never epoch speed: the virtual mesh has no ICI)."""
    errs = []
    name = f"{prefix}_8dev"
    block = parsed[name]
    if block is None:
        if not isinstance(parsed.get(f"{prefix}_degraded"), str):
            errs.append(f"{name} null without a {prefix}_degraded "
                        "marker (graceful-degradation contract)")
        return errs
    if not isinstance(block, dict):
        return [f"{name} is {type(block).__name__}, expected "
                "dict or null"]
    configs = [c for c in ("random", "hp") if c in block]
    if not configs:
        return [f"{name} carries no random/hp partition config"]
    for cfg in configs:
        e = block[cfg]
        if not isinstance(e, dict):
            errs.append(f"{name}.{cfg} is not a dict")
            continue
        for key in ("epoch_s_a2a", "epoch_s_ragged"):
            if not (_is_num(e.get(key)) and e[key] > 0):
                errs.append(f"{name}.{cfg}.{key}={e.get(key)!r}")
        pe = e.get("padding_efficiency")
        if not (_is_num(pe) and 0 < pe <= 1):
            errs.append(f"{name}.{cfg}: padding_efficiency={pe!r} "
                        "outside (0, 1]")
        ratio = e.get("padded_true_ratio_a2a")
        if ratio is not None and not (_is_num(ratio) and ratio >= 1):
            errs.append(f"{name}.{cfg}: padded_true_ratio_a2a="
                        f"{ratio!r} below 1 (padding cannot shrink the "
                        "true volume)")
        wa, wr = e.get("wire_rows_a2a"), e.get("wire_rows_ragged")
        if not (_is_num(wa) and _is_num(wr) and wr <= wa):
            errs.append(f"{name}.{cfg}: wire_rows_ragged={wr!r} "
                        f"exceeds wire_rows_a2a={wa!r} — per-round pads "
                        "can never exceed the global pad")
        if (prefix == "gat_ragged_ab" and cfg == "hp"
                and _is_num(wa) and _is_num(wr) and not wr < wa):
            errs.append(f"{name}.hp: wire_rows_ragged={wr!r} not STRICTLY "
                        f"below wire_rows_a2a={wa!r} on the skewed "
                        "partition — the schedule's acceptance figure")
        tr = e.get("true_rows")
        if _is_num(tr) and _is_num(wr) and tr > wr:
            errs.append(f"{name}.{cfg}: true_rows={tr!r} above "
                        f"wire_rows_ragged={wr!r}")
    return errs


def check_pallas_ragged_ab(parsed: dict) -> list[str]:
    """The kernel × schedule A/B block contract (ISSUE 15,
    ``pallas_ragged_ab_8dev``): three arms (``ell_ragged`` /
    ``pallas_ragged`` / ``pallas_a2a``) with positive MEASURED epoch times
    (emulate-mode — the honest-measurement note must say CPU epoch speed
    is never the claim), and the DETERMINISTIC acceptance counters: the
    pallas ragged arm's wire rows EQUAL the ELL ragged arm's (the kernel
    must not touch the transport), strictly below the pallas a2a arm's on
    the skewed hp partition, and ZERO analytic HBM halo-table bytes in
    both ragged arms while the a2a arm books a positive figure.  ``null``
    needs a ``pallas_ragged_ab_degraded`` marker."""
    errs = []
    block = parsed["pallas_ragged_ab_8dev"]
    if block is None:
        if not isinstance(parsed.get("pallas_ragged_ab_degraded"), str):
            errs.append("pallas_ragged_ab_8dev null without a "
                        "pallas_ragged_ab_degraded marker "
                        "(graceful-degradation contract)")
        return errs
    if not isinstance(block, dict):
        return [f"pallas_ragged_ab_8dev is {type(block).__name__}, "
                "expected dict or null"]
    note = str(block.get("timing", ""))
    if "never" not in note or "claim" not in note:
        errs.append("pallas_ragged_ab_8dev.timing missing the "
                    "honest-measurement note (CPU epoch speed is never "
                    "the claim)")
    arms = ("ell_ragged", "pallas_ragged", "pallas_a2a")
    for arm in arms:
        e = block.get(arm)
        if not isinstance(e, dict):
            errs.append(f"pallas_ragged_ab_8dev.{arm} missing")
            continue
        if not (_is_num(e.get("epoch_s")) and e["epoch_s"] > 0):
            errs.append(f"pallas_ragged_ab_8dev.{arm}.epoch_s="
                        f"{e.get('epoch_s')!r}")
        if e.get("measured") is not True:
            errs.append(f"pallas_ragged_ab_8dev.{arm}: epoch_s claim "
                        "without measured: true provenance")
    if all(isinstance(block.get(a), dict) for a in arms):
        wr = block["pallas_ragged"].get("wire_rows_per_exchange")
        we = block["ell_ragged"].get("wire_rows_per_exchange")
        wa = block["pallas_a2a"].get("wire_rows_per_exchange")
        if not (_is_num(wr) and _is_num(we) and wr == we):
            errs.append(f"pallas_ragged_ab_8dev: pallas ragged wire "
                        f"{wr!r} != ELL ragged wire {we!r} — the kernel "
                        "must not touch the transport")
        if not (_is_num(wr) and _is_num(wa) and wr < wa):
            errs.append(f"pallas_ragged_ab_8dev: pallas ragged wire "
                        f"{wr!r} not STRICTLY below the a2a pad {wa!r} "
                        "on the skewed partition")
        for arm in ("ell_ragged", "pallas_ragged"):
            hb = block[arm].get("halo_table_bytes_per_step")
            if hb != 0:
                errs.append(f"pallas_ragged_ab_8dev.{arm}: "
                            f"halo_table_bytes_per_step={hb!r} — the "
                            "ragged arms must book ZERO HBM halo-table "
                            "bytes (in-kernel fold)")
        ha = block["pallas_a2a"].get("halo_table_bytes_per_step")
        if not (_is_num(ha) and ha > 0):
            errs.append(f"pallas_ragged_ab_8dev.pallas_a2a: "
                        f"halo_table_bytes_per_step={ha!r} (the dense "
                        "exchange assembles halo tables — a zero here "
                        "means the analytic model broke)")
    return errs


def check_replica_ab(parsed: dict) -> list[str]:
    """The hot-halo-replication A/B block contract (PR-10,
    docs/replication.md): a ``replica_ab_8dev`` block must carry B > 0,
    per-partition configs with positive paired epoch times and equal step
    counts implied by the cumulative gauges, shrunken figures never above
    the full ones, and — STRICTLY, on the skewed hp partition — the
    acceptance inequalities: ``halo_bytes_true_total`` and wire rows/step
    lower with B>0 than the no-replica arm, plus the cache-aware km1 <=
    the cache-blind partition's cache objective.  ``null`` needs a
    ``replica_ab_degraded`` marker.  Never epoch speed: the virtual mesh
    has no ICI."""
    errs = []
    block = parsed["replica_ab_8dev"]
    if block is None:
        if not isinstance(parsed.get("replica_ab_degraded"), str):
            errs.append("replica_ab_8dev null without a replica_ab_degraded "
                        "marker (graceful-degradation contract)")
        return errs
    if not isinstance(block, dict):
        return [f"replica_ab_8dev is {type(block).__name__}, expected "
                "dict or null"]
    if not (_is_num(block.get("replica_budget"))
            and block["replica_budget"] > 0):
        errs.append(f"replica_ab_8dev: replica_budget="
                    f"{block.get('replica_budget')!r} (need B > 0)")
    configs = [c for c in ("random", "hp") if c in block]
    if not configs:
        return errs + ["replica_ab_8dev carries no random/hp partition "
                       "config"]
    for cfg in configs:
        e = block[cfg]
        if not isinstance(e, dict):
            errs.append(f"replica_ab_8dev.{cfg} is not a dict")
            continue
        for key in ("epoch_s_noreplica", "epoch_s_replica"):
            if not (_is_num(e.get(key)) and e[key] > 0):
                errs.append(f"replica_ab_8dev.{cfg}.{key}={e.get(key)!r}")
        if not (_is_num(e.get("replica_rows")) and e["replica_rows"] > 0):
            errs.append(f"replica_ab_8dev.{cfg}.replica_rows="
                        f"{e.get('replica_rows')!r} (B>0 must replicate "
                        "at least one boundary row)")
        for shrunk, full in (
                ("true_rows_per_exchange_replica", "true_rows_per_exchange"),
                ("wire_rows_per_exchange_replica", "wire_rows_per_exchange"),
                ("halo_bytes_true_total_replica",
                 "halo_bytes_true_total_noreplica"),
                ("wire_rows_per_step_replica", "wire_rows_per_step_"
                                               "noreplica")):
            s, f = e.get(shrunk), e.get(full)
            if not (_is_num(s) and _is_num(f) and s <= f):
                errs.append(f"replica_ab_8dev.{cfg}: {shrunk}={s!r} "
                            f"exceeds {full}={f!r} — deleting rows can "
                            "never grow the exchange")
    hp = block.get("hp")
    if isinstance(hp, dict):
        for shrunk, full in (
                ("halo_bytes_true_total_replica",
                 "halo_bytes_true_total_noreplica"),
                ("wire_rows_per_step_replica",
                 "wire_rows_per_step_noreplica")):
            s, f = hp.get(shrunk), hp.get(full)
            if _is_num(s) and _is_num(f) and not s < f:
                errs.append(f"replica_ab_8dev.hp: {shrunk}={s!r} not "
                            f"STRICTLY below {full}={f!r} on the skewed "
                            "partition — the feature's acceptance figure")
        kc, kb = (hp.get("km1_cache_aware"),
                  hp.get("km1_cache_blind_partition"))
        if not (_is_num(kc) and _is_num(kb) and kc <= kb):
            errs.append(f"replica_ab_8dev.hp: km1_cache_aware={kc!r} not "
                        f"<= the cache-blind partition's objective {kb!r} "
                        "— the co-optimizer's acceptance inequality")
    note = block.get("note")
    if not (isinstance(note, str) and "wire" in note):
        errs.append("replica_ab_8dev: missing the honest-measurement note "
                    "naming the byte accounting as the asserted figure "
                    "(CPU-mesh epoch speed is not the claim)")
    return errs


# the supported-matrix floor a committed analysis report may not shrink
# below (48 mode entries at PR-15 HEAD: PR-14's 39 + the eight Pallas
# kernel-family modes — {a2a,ragged} × (GCN × {f32,bf16 wire} ∪ GAT ×
# {fused,split}) — + the banded-fixture ragged-pallas elision entry; the
# matrix only grows)
ANALYSIS_MIN_MODES = 48


def check_analysis_report(rec: dict) -> list[str]:
    """The committed-analysis-report contract (module docstring): schema'd,
    full-matrix, green, and self-consistent — every ``ok`` flag must agree
    with the violation lists under it."""
    errs = []
    if rec.get("schema") != "sgcn_analysis_report":
        return [f"schema={rec.get('schema')!r}, expected "
                "'sgcn_analysis_report'"]
    if not isinstance(rec.get("v"), numbers.Integral):
        errs.append("missing integer schema version 'v'")
    if rec.get("fast") is not False:
        errs.append("committed report must be a FULL-matrix run "
                    "(fast: false) — the --fast subset is a smoke, not "
                    "evidence")
    if rec.get("ok") is not True:
        errs.append("ok is not true — fix the violations (or the rules) "
                    "instead of committing a red report as evidence")
    hlo = rec.get("hlo")
    if not isinstance(hlo, dict) or not isinstance(hlo.get("modes"), dict):
        errs.append("missing hlo.modes block")
        return errs
    modes = hlo["modes"]
    if hlo.get("n_modes") != len(modes):
        errs.append(f"hlo.n_modes={hlo.get('n_modes')!r} != "
                    f"{len(modes)} mode entries — inconsistent")
    if len(modes) < ANALYSIS_MIN_MODES:
        errs.append(f"{len(modes)} mode entries below the supported-"
                    f"matrix floor {ANALYSIS_MIN_MODES} — the matrix "
                    "only grows; a shrunk report is a silently narrowed "
                    "audit")
    for mid, entry in modes.items():
        progs = entry.get("programs")
        if not isinstance(progs, dict) or not progs:
            errs.append(f"hlo.modes[{mid}]: no programs block")
            continue
        viols = [v for p in progs.values()
                 for v in p.get("violations", [])]
        if bool(entry.get("ok")) == bool(viols):
            errs.append(f"hlo.modes[{mid}]: ok={entry.get('ok')!r} "
                        f"contradicts {len(viols)} recorded violation(s)")
        for label, p in progs.items():
            if bool(p.get("ok")) == bool(p.get("violations")):
                errs.append(f"hlo.modes[{mid}].programs[{label}]: "
                            f"ok={p.get('ok')!r} contradicts its "
                            "violation list")
            if p.get("ok") is not True:
                errs.append(f"hlo.modes[{mid}].programs[{label}]: "
                            f"ok={p.get('ok')!r} — a committed report "
                            "must be green in every program")
        if entry.get("ok") is not True:
            # green-only must hold per ENTRY, not just at the top — else
            # the one-line hand-edit (flip the top-level booleans) passes
            errs.append(f"hlo.modes[{mid}]: ok={entry.get('ok')!r} — a "
                        "committed report must be green in every mode")
    if hlo.get("ok") is not True:
        errs.append("hlo.ok is not true")
    ast_block = rec.get("ast")
    if not isinstance(ast_block, dict) or not isinstance(
            ast_block.get("rules"), dict):
        errs.append("missing ast.rules block")
        return errs
    for name, entry in ast_block["rules"].items():
        if bool(entry.get("ok")) == bool(entry.get("violations")):
            errs.append(f"ast.rules[{name}]: ok={entry.get('ok')!r} "
                        "contradicts its violation list")
        if entry.get("ok") is not True:
            errs.append(f"ast.rules[{name}]: ok={entry.get('ok')!r} — a "
                        "committed report must be green in every rule")
    if ast_block.get("ok") is not True:
        errs.append("ast.ok is not true")
    return errs


def check_multichip_record(rec: dict) -> list[str]:
    errs = []
    if not isinstance(rec.get("n_devices"), numbers.Integral):
        errs.append("missing/badly-typed n_devices")
    if not isinstance(rec.get("ok"), bool):
        errs.append("missing/badly-typed ok")
        return errs
    if rec["ok"]:
        if rec.get("rc", 0) != 0:
            errs.append(f"ok=true with rc={rec.get('rc')}")
    elif rec.get("rc", 0) == 0 and not (rec.get("skipped")
                                        or rec.get("degraded")):
        # a clean exit claiming failure must say why; a non-zero rc is its
        # own explanation (historical pre-contract records: rc=1 round 1,
        # rc=124 round 5)
        errs.append("ok=false, rc=0, and no skipped/degraded explanation")
    return errs


def _pow2(k: int) -> bool:
    return k >= 1 and (k & (k - 1)) == 0


def check_products_ksweep(rec: dict) -> list[str]:
    errs = []
    sweep = rec.get("sweep")
    if not isinstance(sweep, dict):
        return ["missing 'sweep' block"]
    for fam, by_k in sweep.items():
        for kstr, entry in by_k.items():
            try:
                k = int(kstr)
            except ValueError:
                errs.append(f"{fam}: non-integer k key {kstr!r}")
                continue
            for method, block in entry.items():
                if not isinstance(block, dict):
                    continue
                km1 = block.get("km1")
                if not (_is_num(km1) and km1 > 0):
                    errs.append(f"{fam}/k={k}/{method}: km1={km1!r}")
                ts = block.get("time_s")
                if ts is not None and not (_is_num(ts) and ts > 0):
                    errs.append(f"{fam}/k={k}/{method}: time_s={ts!r}")
            if "hp_rb" in entry and not (_pow2(k) and k >= 32):
                errs.append(
                    f"{fam}/k={k}: hp_rb entry at non-pow2 or <32 k — "
                    "partition_hypergraph_rb recurses on k/2 and the "
                    "auto-select fires only for pow2 k>=32; this shape is "
                    "unreproducible with the code at HEAD (the reverted "
                    "PR-2 hand-edit)")
    return errs


def check_products_partition(rec: dict) -> list[str]:
    errs = []
    g = rec.get("graph")
    if not (isinstance(g, dict) and _is_num(g.get("n"))
            and _is_num(g.get("nnz"))):
        errs.append("missing graph{n, nnz}")
    if not _is_num(rec.get("k")):
        errs.append("missing k")
    for method in ("hp", "rp", "gp"):
        block = rec.get(method)
        if not (isinstance(block, dict) and _is_num(block.get("km1"))):
            errs.append(f"missing {method}.km1")
    return errs


def check_shard_epoch_model(rec: dict) -> list[str]:
    errs = []
    cfg = rec.get("config")
    if not (isinstance(cfg, dict) and _is_num(cfg.get("k"))
            and _is_num(cfg.get("n"))):
        errs.append("missing config{k, n}")
    models = [m for m in ("gcn", "gat")
              if isinstance(rec.get(m), dict) and "error" not in rec[m]]
    if not models:
        errs.append("no usable gcn/gat model block")
    for m in models:
        v = rec[m].get("epoch_s_8chip_model")
        if not (_is_num(v) and v > 0):
            errs.append(f"{m}.epoch_s_8chip_model={v!r}")
    return errs


# artifact filename -> dedicated checker (everything else: strict-parse only)
_ARTIFACT_CHECKS = {
    "analysis_report.json": check_analysis_report,
    "products_ksweep.json": check_products_ksweep,
    "products_partition.json": check_products_partition,
    "products_partition_dcsbm.json": check_products_partition,
    "shard_epoch_model.json": check_shard_epoch_model,
    "shard_epoch_model_dcsbm.json": check_shard_epoch_model,
    "shard_epoch_model_bf16wire.json": check_shard_epoch_model,
}


def validate_tree(root: str) -> list[str]:
    """Validate every bench evidence file under ``root``; return violations
    as ``path: message`` strings (empty = clean)."""
    problems: list[str] = []

    def run(path, checker):
        try:
            rec = _load_strict(path)
        except (ValueError, json.JSONDecodeError) as e:
            problems.append(f"{os.path.relpath(path, root)}: unparseable "
                            f"({e})")
            return
        for msg in (checker(rec) if checker else []):
            problems.append(f"{os.path.relpath(path, root)}: {msg}")

    for path in sorted(glob.glob(os.path.join(root, "BENCH_*.json"))):
        m = _BENCH_ROUND_RE.search(os.path.basename(path))
        rnd = int(m.group(1)) if m else None
        run(path, lambda rec, rnd=rnd: (check_bench_record(rec)
                                        + check_measured_provenance(rec, rnd)
                                        + check_memory_provenance(rec, rnd)))
    for path in sorted(glob.glob(os.path.join(root, "MULTICHIP_*.json"))):
        run(path, check_multichip_record)
    for path in sorted(glob.glob(os.path.join(root, "bench_artifacts",
                                              "*.json"))):
        run(path, _ARTIFACT_CHECKS.get(os.path.basename(path)))
    return problems


def main() -> int:
    root = sys.argv[1] if len(sys.argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    problems = validate_tree(root)
    if problems:
        print(f"validate_bench: {len(problems)} violation(s):")
        for p in problems:
            print(f"  {p}")
        return 1
    n = (len(glob.glob(os.path.join(root, "BENCH_*.json")))
         + len(glob.glob(os.path.join(root, "MULTICHIP_*.json")))
         + len(glob.glob(os.path.join(root, "bench_artifacts", "*.json"))))
    print(f"validate_bench: {n} file(s) clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
