"""Products-scale partitioner proof (VERDICT r3 item 1).

Runs the native partitioners on the SAME graph the products-shape bench uses
(``bench.py --graph ba -n 2450000 --avg-deg 50`` => ``ba_graph(n, 25, 0)``,
normalized) at k=8, and records the evidence the reference produces offline
for its benchmark matrices (``GCN-HP/main.cpp:284-356`` partitions the real
ogbn-scale mtx and self-reports cut/conn + chrono time;
``GPU/hypergraph/run.sh:1-13`` sweeps whole dataset dirs):

  * wall-clock of each partitioner (hp colnet km1, gp edge-cut, random),
  * balance (nnz-weighted and vertex-count max/mean),
  * km1 = sum over columns (lambda - 1) — equal to the halo send volume in
    feature rows per layer per direction (every column has its diagonal
    nonzero after normalization, so the owner is always among the pins),

then writes

  * ``bench_artifacts/products_partition.npz``   (hp + gp part vectors)
  * ``bench_artifacts/products_partition.json``  (all metrics + provenance)

``bench.py`` surfaces the JSON as the ``products_partition_8dev`` block so
BENCH_r*.json carries a products-scale km1 from the real partitioner without
re-running a ~20-minute single-core job inside the bench itself.

Usage: PYTHONPATH=/root/repo python scripts/products_partition.py [-n N] [-k K]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np
import scipy.sparse as sp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sgcn_tpu.io.datasets import ba_graph                      # noqa: E402
from sgcn_tpu.partition import (                               # noqa: E402
    balanced_random_partition, partition_graph, partition_hypergraph_colnet,
)
from sgcn_tpu.prep import normalize_adjacency                  # noqa: E402


def km1_of(a: sp.csr_matrix, pv: np.ndarray, k: int) -> int:
    """Connectivity-1 of a part vector over the column-net model, vectorized:
    dedup (column, part-of-row) pairs, then km1 = #pairs - #nonempty columns."""
    coo = a.tocoo()
    pairs = np.unique(coo.col.astype(np.int64) * k + pv[coo.row])
    ncols = len(np.unique(pairs // k))
    return int(len(pairs) - ncols)


def balance_of(pv: np.ndarray, w: np.ndarray, k: int) -> dict:
    pwn = np.bincount(pv, weights=w, minlength=k)
    pwc = np.bincount(pv, minlength=k)
    return {"nnz_max_over_mean": round(float(pwn.max() / pwn.mean()), 4),
            "count_max_over_mean": round(float(pwc.max() / pwc.mean()), 4)}


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("-n", type=int, default=2_450_000)
    p.add_argument("--attach", type=int, default=25)   # avg deg ~= 2*attach
    p.add_argument("--family", default="ba", choices=["ba", "dcsbm"],
                   help="ba = the bench graph (expander: partitioners beat "
                        "random only marginally, an honest property of "
                        "preferential attachment); dcsbm = power-law + "
                        "planted communities (the real-ogbn structure "
                        "profile, where partition quality is measurable)")
    p.add_argument("-k", type=int, default=8)
    p.add_argument("-o", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench_artifacts"))
    args = p.parse_args()

    t0 = time.time()
    if args.family == "ba":
        a = ba_graph(args.n, args.attach, seed=0)
        graph_meta = {
            "family": "ba", "n": int(args.n), "attach": args.attach,
            "seed": 0,
            "matches_bench": "bench.py --graph ba -n %d --avg-deg %d"
                             % (args.n, 2 * args.attach)}
    else:
        from sgcn_tpu.io.datasets import dcsbm_graph
        a = dcsbm_graph(args.n, ncomm=200, avg_deg=2 * args.attach, seed=0)
        graph_meta = {
            "family": "dcsbm", "n": int(args.n), "ncomm": 200,
            "avg_deg": 2 * args.attach, "seed": 0,
            "why": "power-law + communities: the structure profile of the "
                   "real ogbn-products, where partition quality is "
                   "measurable (BA is an expander)"}
    ahat = normalize_adjacency(a)
    w = np.diff(ahat.indptr).astype(np.float64)
    print(f"graph: n={args.n} nnz={ahat.nnz} gen+norm {time.time()-t0:.1f}s",
          flush=True)

    k = args.k
    graph_meta["nnz"] = int(ahat.nnz)
    out: dict = {
        "graph": graph_meta,
        "k": k,
        "host": "single CPU core (see BASELINE.md measurement notes)",
    }

    t0 = time.time()
    pv_rp = balanced_random_partition(args.n, k, seed=1)
    t_rp = time.time() - t0
    t0 = time.time()
    km1_rp = km1_of(ahat, pv_rp, k)
    print(f"rp: km1={km1_rp} part {t_rp:.1f}s score {time.time()-t0:.1f}s",
          flush=True)
    out["rp"] = {"km1": km1_rp, "time_s": round(t_rp, 2),
                 **balance_of(pv_rp, w, k)}

    t0 = time.time()
    pv_hp, km1_hp = partition_hypergraph_colnet(ahat, k, seed=0)
    t_hp = time.time() - t0
    assert km1_hp == km1_of(ahat, pv_hp, k)   # self-reported metric is honest
    print(f"hp: km1={km1_hp} time {t_hp:.1f}s", flush=True)
    out["hp"] = {"km1": int(km1_hp), "time_s": round(t_hp, 2),
                 **balance_of(pv_hp, w, k),
                 "vs_random": round(km1_rp / max(km1_hp, 1), 2)}

    t0 = time.time()
    pv_gp, cut_gp = partition_graph(ahat, k, seed=0)
    t_gp = time.time() - t0
    km1_gp = km1_of(ahat, pv_gp, k)
    print(f"gp: cut={cut_gp} km1={km1_gp} time {t_gp:.1f}s", flush=True)
    out["gp"] = {"edge_cut": int(cut_gp), "km1": km1_gp,
                 "time_s": round(t_gp, 2), **balance_of(pv_gp, w, k),
                 "vs_random": round(km1_rp / max(km1_gp, 1), 2)}

    os.makedirs(args.o, exist_ok=True)
    stem = ("products_partition" if args.family == "ba"
            else f"products_partition_{args.family}")
    np.savez_compressed(os.path.join(args.o, stem + ".npz"),
                        pv_hp=pv_hp.astype(np.int32),
                        pv_gp=pv_gp.astype(np.int32))
    with open(os.path.join(args.o, stem + ".json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
