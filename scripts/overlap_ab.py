"""Comm/compute-overlap evidence: wall-clock A/B of the split-edge-list form
(``pspmm_overlap`` — local SpMM has no data dependence on the halo
all_to_all, so the scheduler may run them concurrently) against the combined
form (``pspmm_exchange`` — every gather waits for the exchange).

This is the scheduler-level counterpart of the structural jaxpr test
(``tests/test_pspmm.py``: collective-independence of the local scatter-add)
and of the reference's Irecv/compute/Waitany loop
(``Parallel-GCN/main.c:238-299``).

Runs on whatever devices are visible; use the virtual 8-device CPU mesh via
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``
when only one real chip is reachable.  A RANDOM partition maximizes halo
traffic (every part's boundary ≈ its whole vertex set), making the exchange
as expensive as possible relative to local compute.

Prints one JSON line; optionally archives a profiler trace with --trace.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=40_000)
    ap.add_argument("--deg", type=int, default=14)
    ap.add_argument("-f", type=int, default=128)
    ap.add_argument("--layers", type=int, default=3)
    ap.add_argument("--trace", default=None,
                    help="directory for a jax.profiler trace of the overlap form")
    args = ap.parse_args()

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from jax.sharding import PartitionSpec as P

    from sgcn_tpu.io.datasets import er_graph
    from sgcn_tpu.ops import pspmm_exchange, pspmm_overlap
    from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d, shard_stacked
    from sgcn_tpu.partition import balanced_random_partition
    from sgcn_tpu.prep import normalize_adjacency

    k = len(jax.devices())
    ahat = normalize_adjacency(er_graph(args.n, args.deg, seed=0))
    pv = balanced_random_partition(args.n, k, seed=0)   # comm-heavy on purpose
    plan = build_comm_plan(ahat, pv, k)
    mesh = make_mesh_1d(k)
    rng = np.random.default_rng(0)
    h = rng.standard_normal((args.n, args.f)).astype(np.float32)
    hb = shard_stacked(mesh, plan.scatter_rows(h))

    fields = ("send_idx", "halo_src", "edge_dst", "edge_src", "edge_w",
              "ledge_dst", "ledge_src", "ledge_w",
              "hedge_dst", "hedge_src", "hedge_w")
    pa = shard_stacked(mesh, {f: getattr(plan, f) for f in fields})

    def compiled(form, iters):
        def per_chip(pa, h):
            pa = jax.tree.map(lambda x: x[0], pa)

            def body(i, x):
                for _ in range(args.layers):
                    if form == "overlap":
                        x = pspmm_overlap(
                            x, pa["send_idx"], pa["halo_src"],
                            pa["ledge_dst"], pa["ledge_src"], pa["ledge_w"],
                            pa["hedge_dst"], pa["hedge_src"], pa["hedge_w"])
                    else:
                        x = pspmm_exchange(
                            x, pa["send_idx"], pa["halo_src"],
                            pa["edge_dst"], pa["edge_src"], pa["edge_w"])
                    x = x * 0.2     # keep values bounded across iterations
                return x

            return jax.lax.fori_loop(0, iters, body, h[0])[None]

        return jax.jit(jax.shard_map(per_chip, mesh=mesh,
                                     in_specs=(P("v"), P("v")),
                                     out_specs=P("v")))

    def measure(form, lo=2, hi=10, reps=5):
        def once(iters):
            fn = compiled(form, iters)
            float(np.asarray(fn(pa, hb)).ravel()[0])    # compile + warm
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                out = fn(pa, hb)
                float(np.asarray(out).ravel()[0])       # sync
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))

        return max((once(hi) - once(lo)) / (hi - lo), 1e-9)

    t_overlap = measure("overlap")
    t_exchange = measure("exchange")

    if args.trace:
        fn = compiled("overlap", 4)
        float(np.asarray(fn(pa, hb)).ravel()[0])
        with jax.profiler.trace(args.trace):
            float(np.asarray(fn(pa, hb)).ravel()[0])

    print(json.dumps({
        "metric": "pspmm_overlap_ab",
        "devices": k,
        "n": args.n,
        "layers": args.layers,
        "comm_volume_rows": int(plan.predicted_send_volume.sum()),
        "t_overlap_s": round(t_overlap, 6),
        "t_exchange_s": round(t_exchange, 6),
        "overlap_speedup": round(t_exchange / t_overlap, 4),
    }))


if __name__ == "__main__":
    main()
