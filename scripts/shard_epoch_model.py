"""8-chip products epoch model from REAL per-chip shard measurements (r5 #1).

The north star (`BASELINE.json:5`) is an 8-chip ogbn-products 2-layer/128
full-batch GCN epoch; this box has ONE physical chip.  The plan pads every
per-chip array to identical shapes, so chip c's compiled program — send-side
gather, halo gather, bucketed local+halo SpMM, dense matmuls, loss, symmetric
backward, Adam — is the same program every chip runs (MAX over ranks = any
rank).  This script:

  1. rebuilds the products-shape bench graph and the saved hp partition
     (``bench_artifacts/products_partition*.npz``, from
     ``scripts/products_partition.py``),
  2. builds the REAL k=8 comm plan and extracts one chip's shard
     (``sgcn_tpu.parallel.proxy``),
  3. measures that per-chip program on the real TPU with the round-3
     differential protocol (tunnel constant cancels),
  4. models the collectives the single chip cannot time from the plan's
     exact padded exchange bytes over a bidirectional-ring ICI model
     (v5e: 45 GB/s one-way per link — the conservative 1D-ring reading of
     the 2x4 slice; the 2D torus routes all_to_all faster), and
  5. writes ``bench_artifacts/shard_epoch_model[_dcsbm][_bf16wire|_abwire]
     .json`` (dtype-suffixed so --halo-dtype runs never overwrite the f32
     baseline artifact) with the composed 8-chip epoch-time model:
        lower bound  max(compute, comm)   (XLA overlaps the a2a with the
                                           local slot passes — proven on the
                                           compiled v5e 8-chip schedule,
                                           tests/test_overlap_hlo.py)
        upper bound  compute + comm       (zero overlap)

Reference protocol being matched: per-epoch wall-clock, MAX over ranks,
after warm-up (``GPU/PGCN.py:202-228``, ``Parallel-GCN/main.c:441-445``).

Usage:
  PYTHONPATH=/root/repo python scripts/shard_epoch_model.py
      [--graph ba|dcsbm] [--chip 0] [--models gcn,gat] [--epochs 4]
      [--halo-dtype float32|bfloat16|ab]
  ('ab' measures the f32 AND bf16 wire back to back under ONE plan — the
  drift-proof same-session comparison; GCN only)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
ART = os.path.join(REPO, "bench_artifacts")

# v5e ICI: one-way per-link bandwidth (scaling-book spec value).  The 8-chip
# slice is a 2x4 torus; the model uses the 1D bidirectional ring its mesh
# axis maps to — conservative (2D routing can only be faster).
W_LINK = 45e9


def ring_a2a_seconds(per_chip_bytes: float, k: int) -> float:
    """All-to-all time on a bidirectional ring: every chip ships
    ``per_chip_bytes`` split uniformly over k-1 peers; balanced shortest-path
    routing loads each directed link with ``bytes * avg_hops / 2``."""
    d = np.arange(1, k)
    avg_hops = np.minimum(d, k - d).mean()
    return per_chip_bytes * avg_hops / 2 / W_LINK


def ring_allreduce_seconds(grad_bytes: float, k: int) -> float:
    """Ring allreduce (reduce-scatter + all-gather): 2(k-1)/k passes."""
    return 2 * (k - 1) / k * grad_bytes / W_LINK




def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--graph", default="ba", choices=["ba", "dcsbm"])
    p.add_argument("--chip", type=int, default=0)
    p.add_argument("--models", default="gcn,gat",
                   help="comma list drawn from {gcn, gat}")
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--halo-dtype", default="float32",
                   choices=["float32", "bfloat16", "ab"],
                   help="dtype of the a2a halo buffer (exchange-only bf16 "
                        "halves ICI bytes; tables/activations stay f32). "
                        "'ab' measures BOTH under one plan in one session "
                        "— the only drift-proof comparison at GB-table "
                        "scale (BASELINE.md rate-drift caveat)")
    p.add_argument("--fin", type=int, default=128)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--classes", type=int, default=40)
    p.add_argument("--layers", type=int, default=2)
    args = p.parse_args()
    models = [m for m in args.models.split(",") if m]
    bad = set(models) - {"gcn", "gat"}
    if bad or not models:
        p.error(f"--models must be a comma list from {{gcn,gat}}, got "
                f"{args.models!r}")   # fail BEFORE minutes of graph/plan build
    if args.halo_dtype == "ab" and models != ["gcn"] \
            and args.models != "gcn,gat":   # explicit non-gcn request
        p.error("--halo-dtype ab measures the GCN wire A/B only; "
                "drop --models or pass --models gcn")

    from bench import diff_time_q
    from sgcn_tpu.models.gcn import exchange_widths
    from sgcn_tpu.parallel import build_comm_plan
    from sgcn_tpu.parallel.proxy import shard_proxy_data, shard_proxy_plan
    from sgcn_tpu.prep import normalize_adjacency
    from sgcn_tpu.train import FullBatchTrainer
    from sgcn_tpu.utils.backend import enable_tpu_async_collectives

    enable_tpu_async_collectives()

    suffix = "" if args.graph == "ba" else f"_{args.graph}"
    with open(os.path.join(ART, f"products_partition{suffix}.json")) as fh:
        rec = json.load(fh)
    g = rec["graph"]
    k = rec["k"]
    t0 = time.time()
    if args.graph == "ba":
        from sgcn_tpu.io.datasets import ba_graph
        a = ba_graph(g["n"], g["attach"], seed=g["seed"])
    else:
        from sgcn_tpu.io.datasets import dcsbm_graph
        a = dcsbm_graph(g["n"], ncomm=g["ncomm"], avg_deg=g["avg_deg"],
                        seed=g["seed"])
    ahat = normalize_adjacency(a)
    del a
    print(f"graph regen {time.time()-t0:.0f}s nnz={ahat.nnz}", flush=True)

    pv = np.load(os.path.join(ART, f"products_partition{suffix}.npz"))
    t0 = time.time()
    plan = build_comm_plan(ahat, pv["pv_hp"].astype(np.int64), k)
    print(f"plan build {time.time()-t0:.0f}s b={plan.b} s={plan.s} "
          f"r={plan.r} e={plan.e}", flush=True)
    del ahat

    widths = [args.hidden] * (args.layers - 1) + [args.classes]
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((plan.n, args.fin)).astype(np.float32)
    labels = rng.integers(0, args.classes, size=plan.n).astype(np.int32)
    proxy = shard_proxy_plan(plan, chip=args.chip)
    data = shard_proxy_data(plan, args.chip, feats, labels)
    del feats, labels

    # ---------------------------------------------------------- comm model
    ew = exchange_widths(args.fin, widths)
    true_rows = int(plan.predicted_send_volume[args.chip])
    grad_bytes = 4 * sum(
        i * o for i, o in zip([args.fin] + widths[:-1], widths))
    psum_s = ring_allreduce_seconds(grad_bytes, k)   # one grad psum per step

    def comm_model(halo_dtype: str, wire_widths) -> dict:
        halo_itemsize = 2 if halo_dtype == "bfloat16" else 4
        # padded bytes actually crossing ICI per chip per pass: (k-1) peer
        # buckets of S rows (the self-bucket stays on chip)
        pass_bytes = [(k - 1) * plan.s * w * halo_itemsize
                      for w in wire_widths]
        # fwd + bwd exchange per layer (symmetric VJP reuses the fwd form)
        a2a_s = sum(2 * ring_a2a_seconds(b, k) for b in pass_bytes)
        return {
            "model": "bidirectional ring over the 1D mesh axis; 2D-torus "
                     "routing of the 2x4 v5e slice can only be faster",
            "w_link_GBs": W_LINK / 1e9,
            "exchange_widths": list(wire_widths),
            "halo_dtype": halo_dtype,
            "padded_a2a_bytes_per_chip_per_pass": pass_bytes,
            "true_send_rows_chip": true_rows,
            "padded_send_rows_chip": int((k - 1) * plan.s),
            "a2a_s_per_epoch": a2a_s,
            "grad_bytes": grad_bytes,
            "psum_s_per_epoch": psum_s,
            "comm_s_per_epoch": a2a_s + psum_s,
        }

    # the GAT trainer rejects halo_dtype (its exchange narrows via the
    # packed compute_dtype path) — its wire is modeled f32 regardless; it
    # ships the POST-projection [p ‖ u] rows (fout + 1 lanes per layer),
    # not the GCN's project-first-rule widths
    if args.halo_dtype == "ab":
        # same-session wire A/B: one plan, one device data placement, both
        # wire dtypes measured back to back — the drift-proof form
        jobs = [("gcn", "gcn", "float32"),
                ("gcn_bf16wire", "gcn", "bfloat16")]
    else:
        jobs = [(m, m, args.halo_dtype if m == "gcn" else "float32")
                for m in models]
    comm_by_entry = {
        entry: comm_model(dt, ew if model == "gcn"
                          else [w + 1 for w in widths])
        for entry, model, dt in jobs}
    print("comm model:", json.dumps(comm_by_entry[jobs[0][0]]), flush=True)

    # ------------------------------------------------- measured compute leg
    out = {
        "config": {
            "graph": args.graph, "n": g["n"], "nnz": g["nnz"], "k": k,
            "fin": args.fin, "widths": widths, "chip": args.chip,
            "partitioner": "hp",
            "plan": {"b": plan.b, "s": plan.s, "r": plan.r, "e": plan.e},
        },
        "comm": comm_by_entry,
        "protocol": "per-chip shard program measured on the real v5e chip "
                    "(differential, median of 3); collectives modeled from "
                    "the plan's padded exchange bytes",
    }
    for entry, model, wire_dt in jobs:
        comm = comm_by_entry[entry]
        t0 = time.time()
        try:
            kw = ({"activation": "none"} if model == "gat" else
                  ({"halo_dtype": wire_dt}
                   if wire_dt != "float32" else {}))
            tr = FullBatchTrainer(proxy, fin=args.fin, widths=widths,
                                  seed=2, model=model, **kw)
        except MemoryError as e:
            out[entry] = {"error": f"capacity guard: {e}"}
            print(f"{entry}: {out[entry]}", flush=True)
            continue

        def make_run(nep):
            def run():
                losses = tr.run_epochs(data, nep, sync=False)
                return float(losses[-1])
            return run

        try:
            compute_s, n_clean = diff_time_q(make_run, 1,
                                             max(3, args.epochs))
        except RuntimeError as e:
            out[entry] = {"error": f"measurement failed: {e}"}
            print(f"{entry}: {out[entry]}", flush=True)
            continue
        comm_s = comm["comm_s_per_epoch"]
        out[entry] = {
            "per_chip_compute_s": compute_s,
            "clean_estimates": n_clean,
            "setup_plus_measure_s": round(time.time() - t0, 1),
            "epoch_s_8chip_model": compute_s + comm_s,
            "epoch_s_8chip_model_overlapped": max(compute_s, comm_s),
        }
        print(f"{entry}: {json.dumps(out[entry])}", flush=True)
        del tr

    dt = {"float32": "", "bfloat16": "_bf16wire",
          "ab": "_abwire"}[args.halo_dtype]
    path = os.path.join(ART, f"shard_epoch_model{suffix}{dt}.json")
    if os.path.exists(path):
        # merge: a partial re-run (e.g. after a tunnel flake killed one
        # model's measurement) must not discard the other model's entry —
        # but ONLY under the identical config; a changed config would
        # mislabel the kept measurement
        with open(path) as fh:
            prev = json.load(fh)
        if prev.get("config") == out["config"]:
            for key, val in out.items():
                # any measurement entry (gcn / gat / gcn_bf16wire / ...):
                # never overwrite a previous GOOD number with a new error
                if isinstance(val, dict) and "error" in val and \
                        isinstance(prev.get(key), dict) and \
                        "error" not in prev[key] and \
                        "per_chip_compute_s" in prev[key]:
                    continue
                prev[key] = val
            out = prev
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmp, path)
    print("wrote", path, flush=True)


if __name__ == "__main__":
    main()
