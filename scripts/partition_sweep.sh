#!/bin/bash
# Sweep partitioners over k for every dataset directory — role of the
# reference's GPU/graph/run.sh and GPU/hypergraph/run.sh batch drivers
# (k ∈ {2,3,9,15,21,27} over each dataset dir).
#
# Usage: scripts/partition_sweep.sh DATA_DIR [modes] [k1 k2 ...]
#   DATA_DIR contains one subdirectory per dataset with <name>.A.mtx inside.
set -euo pipefail

DATA_DIR=${1:?usage: partition_sweep.sh DATA_DIR [modes] [k...]}
MODES=${2:-hp,gp,rp}
shift $(( $# > 2 ? 2 : $# ))
KS=("${@:-2 3 9 15 21 27}")
[ ${#KS[@]} -eq 1 ] && KS=(${KS[0]})

for d in "$DATA_DIR"/*/; do
  name=$(basename "$d")
  a="$d/$name.A.mtx"
  [ -f "$a" ] || continue
  for k in "${KS[@]}"; do
    echo "== $name k=$k modes=$MODES"
    python -m sgcn_tpu.partition -a "$a" -k "$k" -m "$MODES"
  done
done
