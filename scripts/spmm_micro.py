"""SpMM microbenchmarks on the real chip — the data behind the kernel design.

MEASUREMENT PROTOCOL (round 3): this box reaches its chip through a tunnel
with a ~110 ms fixed cost per jitted CALL (not per op) — every round-2
in-loop number silently included ``110ms / iters``.  All timings here are
therefore **differential**: run the same jitted fori_loop at two iteration
counts and report ``(t(hi) - t(lo)) / (hi - lo)``, which cancels the
per-call constant exactly.  Blocking is via scalar readback (``float()``),
because ``jax.block_until_ready`` returns early on the axon platform.

Times each candidate strategy for the hot op (Â·H row-gather + reduce,
Parallel-GCN/main.c:269-272 role).

Run: python scripts/spmm_micro.py [--n 169343] [--deg 14] [--f 128]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def _run_loop(body, init, iters, reps=5):
    jfn = jax.jit(lambda c: jax.lax.fori_loop(0, iters, body, c),
                  static_argnums=())
    def run():
        out = jfn(init)
        leaf = jax.tree.leaves(out)[-1]
        return float(jnp.asarray(leaf).ravel()[0])   # scalar readback = sync
    run()                                            # compile + warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def timed(body, init, lo=4, hi=24):
    """Differential per-iteration seconds of `body` inside lax.fori_loop."""
    tlo = _run_loop(body, init, lo)
    thi = _run_loop(body, init, hi)
    return max((thi - tlo) / (hi - lo), 1e-9)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=169_343)
    p.add_argument("--deg", type=int, default=14)
    p.add_argument("--f", type=int, default=128)
    p.add_argument("--ellk", type=int, default=24)
    args = p.parse_args()
    n, f, ellk = args.n, args.f, args.ellk
    rng = np.random.default_rng(0)

    table = jnp.asarray(rng.standard_normal((n, f)), jnp.float32)
    nrows = n * ellk
    # +8 slack so a loop-varying window offset defeats loop hoisting
    idx_full = jnp.asarray(rng.integers(0, n, size=nrows + 8), jnp.int32)
    w = jnp.asarray(rng.standard_normal((n, ellk)), jnp.float32)
    gb = nrows * f * 4 / 1e9

    # 0) streaming ceiling: elementwise over the gathered volume
    big = jnp.asarray(rng.standard_normal((nrows // 8 * 8, f)), jnp.float32)

    def ew(i, c):
        x, s = c
        y = x * 1.000001 + 0.5
        return y, s + y[0, 0]

    t = timed(ew, (big, jnp.float32(0)))
    print(f"stream r+w {2*big.size*4/1e9:.2f}GB    {t*1e3:8.2f} ms   "
          f"{2*big.size*4/t/1e9:7.1f} GB/s")

    # 1) full ELL spmm: take + weighted reduce (the shipped hot path)
    def ell_spmm(i, c):
        table, s = c
        idx = jax.lax.dynamic_slice(idx_full, (i % 8,), (nrows,))
        g = jnp.take(table, idx, axis=0).reshape(n, ellk, f)
        out = jnp.einsum("nkf,nk->nf", g, w)
        return table, s + out[0, 0]

    t = timed(ell_spmm, (table, jnp.float32(0)))
    print(f"ell_spmm take+reduce  {t*1e3:8.2f} ms   {gb/t:7.1f} GB/s gathered "
          f"({nrows/t/1e6:.0f} Mrows/s)")

    # 1b) sorted indices (locality probe)
    idx_sorted = jnp.sort(idx_full)

    def ell_spmm_sorted(i, c):
        table, s = c
        idx = jax.lax.dynamic_slice(idx_sorted, (i % 8,), (nrows,))
        g = jnp.take(table, idx, axis=0).reshape(n, ellk, f)
        out = jnp.einsum("nkf,nk->nf", g, w)
        return table, s + out[0, 0]

    t = timed(ell_spmm_sorted, (table, jnp.float32(0)))
    print(f"ell_spmm sorted idx   {t*1e3:8.2f} ms   {gb/t:7.1f} GB/s gathered")

    # 1c) gather only (sum consumes all rows, no einsum)
    def take_only(i, c):
        table, s = c
        idx = jax.lax.dynamic_slice(idx_full, (i % 8,), (nrows,))
        g = jnp.take(table, idx, axis=0)
        return table, s + g.sum()

    t = timed(take_only, (table, jnp.float32(0)))
    print(f"take+sum              {t*1e3:8.2f} ms   {gb/t:7.1f} GB/s gathered")

    # 1d) bf16 table gather
    tb16 = table.astype(jnp.bfloat16)

    def ell_bf16(i, c):
        tb, s = c
        idx = jax.lax.dynamic_slice(idx_full, (i % 8,), (nrows,))
        g = jnp.take(tb, idx, axis=0).reshape(n, ellk, f).astype(jnp.float32)
        out = jnp.einsum("nkf,nk->nf", g, w)
        return tb, s + out[0, 0]

    t = timed(ell_bf16, (tb16, jnp.float32(0)))
    print(f"ell_spmm bf16 table   {t*1e3:8.2f} ms   {gb/2/t:7.1f} GB/s gathered")

    # 2) dense matmul rooflines
    wdense = jnp.asarray(rng.standard_normal((f, f)), jnp.float32)

    def dense(i, c):
        x, s = c
        y = x @ wdense
        return x, s + y[0, 0]

    t = timed(dense, (table, jnp.float32(0)))
    print(f"dense (n,{f})@({f},{f})  {t*1e3:8.2f} ms   "
          f"{2*n*f*f/t/1e12:7.2f} TFLOP/s  ({(2*n*f*4)/t/1e9:.0f} GB/s)")

    m = 4096
    a4 = jnp.full((m, m), 0.001, jnp.bfloat16)

    def mm4k(i, c):
        a, s = c
        y = ((a @ a) * 1e-3).astype(jnp.bfloat16)
        return y, s + y[0, 0].astype(jnp.float32)

    t = timed(mm4k, (a4, jnp.float32(0)))
    print(f"matmul 4096^3 bf16    {t*1e3:8.2f} ms   {2*m**3/t/1e12:7.1f} TFLOP/s")

    # 3) dynamic_gather (take_along_axis) in-VMEM shuffle throughput
    from jax.experimental import pallas as pl

    S = 2048
    chunk = jnp.asarray(rng.standard_normal((S, f)), jnp.float32)
    gidx = jnp.asarray(rng.integers(0, S, size=(S, 1)), jnp.int32)

    def tga_kernel(idx_ref, x_ref, o_ref):
        ii = jnp.broadcast_to(idx_ref[:], (S, f))
        o_ref[:] = jnp.take_along_axis(x_ref[:], ii, axis=0)

    def vmem_gather(i, c):
        chunk, s = c
        y = pl.pallas_call(
            tga_kernel,
            out_shape=jax.ShapeDtypeStruct((S, f), jnp.float32),
        )((gidx + i) % S, chunk)
        return chunk, s + y[0, 0]

    try:
        t = timed(vmem_gather, (chunk, jnp.float32(0)))
        print(f"pallas take_along S={S} {t*1e3:8.3f} ms   "
              f"{S*f*4/t/1e9:7.1f} GB/s shuffled ({S/t/1e6:.1f} Mrows/s)")
    except Exception as e:
        print(f"pallas take_along_axis: FAILED {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
