"""Augment the products partition artifact with REAL comm-plan numbers.

``build_comm_plan`` is the exact structure the 8-chip trainer ships
(padded all_to_all buckets, halo gather indices); its
``predicted_send_volume`` is the number the trainer's CommStats counters
measure (asserted equal in tests).  Building it at products scale under
the saved hp/gp partvecs upgrades the artifact from "partitioner metrics"
to "what the 8-chip trainer would actually exchange per layer pass".

Run after scripts/products_partition.py:
  PYTHONPATH=/root/repo python scripts/products_plan_volume.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sgcn_tpu.io.datasets import ba_graph                      # noqa: E402
from sgcn_tpu.parallel import build_comm_plan                  # noqa: E402
from sgcn_tpu.prep import normalize_adjacency                  # noqa: E402

ART = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "bench_artifacts")


def main() -> None:
    with open(os.path.join(ART, "products_partition.json")) as f:
        rec = json.load(f)
    g = rec["graph"]
    assert g["family"] == "ba"
    t0 = time.time()
    ahat = normalize_adjacency(ba_graph(g["n"], g["attach"], seed=g["seed"]))
    print(f"graph regen {time.time()-t0:.0f}s", flush=True)
    pv = np.load(os.path.join(ART, "products_partition.npz"))
    k = rec["k"]
    for name in ("hp", "gp"):
        t0 = time.time()
        plan = build_comm_plan(ahat, pv[f"pv_{name}"].astype(np.int64), k)
        rec[name]["plan_build_s"] = round(time.time() - t0, 1)
        rec[name]["plan_send_rows_per_pass"] = int(
            plan.predicted_send_volume.sum())
        rec[name]["plan_messages_per_pass"] = int(
            plan.predicted_message_count.sum())
        rec[name]["plan_b"] = int(plan.b)       # padded rows/chip
        rec[name]["plan_r_max"] = int(plan.halo_counts.max())
        print(name, {kk: rec[name][kk] for kk in
                     ("plan_send_rows_per_pass", "plan_messages_per_pass",
                      "plan_b", "plan_r_max", "plan_build_s")}, flush=True)
        del plan
    # atomic replace: the original carries a ~25-minute partitioner run's
    # provenance — never truncate it in place
    dst = os.path.join(ART, "products_partition.json")
    tmp = dst + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1)
    os.replace(tmp, dst)
    print("updated products_partition.json")


if __name__ == "__main__":
    main()
