"""Bisect the GAT runtime worker-crash blind spot (VERDICT r4 item 8).

Round 4's record (`models/gat.py` "KNOWN BLIND SPOT"): the 2-layer
BA-products f32 GAT step passed compile AND the calibrated HBM capacity
model, then killed the TPU worker at runtime.  The guard since fences tail
sizes > 20M edges — calibrated on two points, fragile.  This script makes
the fence principled: it sweeps the hub-tail length at fixed everything-else
(synthetic plans with a controlled COO tail; bucket cells held constant)
and records, for each point, compile-ok / run-ok / crash — narrowing the
edge to a measured boundary.

DANGER: a positive hit KILLS the TPU worker and resets chip state (the
round-4 drift event) — run this LAST in a session, never before
measurements you care about.  Each point runs in a SUBPROCESS so a dead
worker fails the point, not the sweep; the tunnel usually revives for the
next point after a delay.

Writes ``bench_artifacts/gat_crash_bisect.json`` incrementally.

Run: PYTHONPATH=/root/repo python -u scripts/gat_crash_bisect.py
     [--tails 8,12,16,20,24,29] [--n 2450000]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "bench_artifacts")

# child payload: build a products-shape BA graph, truncate the built
# combined tail to the requested length post-build (bucket cells stay
# untouched — the control the bisect needs), and run ONE 2-layer GAT step
# with the capacity guard bypassed
CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ["SGCN_GAT_UNSAFE"] = "1"           # bypass the fence ON PURPOSE
import numpy as np
from sgcn_tpu.io.datasets import ba_graph
from sgcn_tpu.parallel import build_comm_plan
from sgcn_tpu.prep import normalize_adjacency
from sgcn_tpu.train import FullBatchTrainer, make_train_data

n, tail_target = {n}, {tail}
ahat = normalize_adjacency(ba_graph(n, 25, seed=0))
pv = np.zeros(n, dtype=np.int64)
plan = build_comm_plan(ahat, pv, 1)
plan.ensure_cell()
true_tail = int(plan.ctail_nnz[0])
print(f"TAILINFO true_tail={{true_tail}} target={{tail_target}}", flush=True)
if true_tail < tail_target:
    print("SKIP tail smaller than target", flush=True)
    sys.exit(3)
# truncate the combined tail to the target length (keeps dst-sorted order;
# the dropped edges simply don't contribute — numerics irrelevant here)
import dataclasses
plan = dataclasses.replace(
    plan,
    ctail_dst=plan.ctail_dst[:, :tail_target],
    ctail_src=plan.ctail_src[:, :tail_target],
    ctail_w=plan.ctail_w[:, :tail_target],
    ctail_nnz=np.minimum(plan.ctail_nnz, tail_target),
)
rng = np.random.default_rng(0)
feats = rng.standard_normal((n, 128)).astype(np.float32)
labels = rng.integers(0, 40, n).astype(np.int32)
tr = FullBatchTrainer(plan, fin=128, widths=[128, 40], model="gat",
                      activation="none", seed=2)
data = make_train_data(plan, feats, labels)
# explicit AOT compile so the parent can tell compile-OOM from runtime
# crash (jax.jit compiles lazily inside the first call otherwise)
from sgcn_tpu.parallel.mesh import shard_stacked
sdata = type(data)(**shard_stacked(tr.mesh, vars(data)))
compiled = tr._step.lower(tr.params, tr.opt_state, tr.pa, sdata.h0,
                          sdata.labels, sdata.train_valid).compile()
print("COMPILED", flush=True)
loss = tr.step(data)
print(f"RAN loss={{loss}}", flush=True)
"""


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--tails", default="8,12,16,20,24,29",
                   help="tail lengths to probe, in MILLIONS of edges")
    p.add_argument("--n", type=int, default=2_450_000)
    p.add_argument("--timeout", type=int, default=2400)
    args = p.parse_args()

    path = os.path.join(ART, "gat_crash_bisect.json")
    rec = {"n": args.n, "points": {}}
    if os.path.exists(path):
        with open(path) as fh:
            prev = json.load(fh)
        if prev.get("n") == args.n:     # cache is per-n; stale n restarts
            rec = prev

    def tpu_alive() -> bool:
        try:
            pr = subprocess.run(
                [sys.executable, "-c",
                 "import jax; jax.devices()"],
                capture_output=True, timeout=120)
            return pr.returncode == 0
        except subprocess.TimeoutExpired:
            return False

    for tm in (float(x) for x in args.tails.split(",")):
        tail = int(tm * 1e6)
        key = f"{tm:g}M"
        if key in rec["points"]:
            print(f"{key}: cached {rec['points'][key]['status']}", flush=True)
            continue
        # a dead worker would misclassify this point as compile-fail and
        # poison the cache — verify the chip is reachable first
        alive = False
        for _ in range(5):
            if tpu_alive():
                alive = True
                break
            print("TPU unreachable; waiting 120s", flush=True)
            time.sleep(120)
        if not alive:
            print(f"{key}: TPU down, NOT cached — rerun later", flush=True)
            continue
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, "-u", "-c",
                 CHILD.format(repo=REPO, n=args.n, tail=tail)],
                capture_output=True, text=True, timeout=args.timeout)
            out = proc.stdout
            if "RAN loss=" in out:
                status = "ran"
            elif proc.returncode == 3:
                status = "tail-too-small"
            elif "COMPILED" in out:
                status = "runtime-crash"      # compiled, then died
            else:
                status = "compile-fail"
            detail = (out.strip().splitlines()[-1:] or [""])[0] \
                + (" | " + proc.stderr.strip().splitlines()[-1]
                   if proc.returncode not in (0, 3) and proc.stderr else "")
        except subprocess.TimeoutExpired:
            status, detail = "timeout", f"> {args.timeout}s"
        rec["points"][key] = {"status": status, "detail": detail[:400],
                              "elapsed_s": round(time.time() - t0, 1)}
        print(f"{key}: {json.dumps(rec['points'][key])}", flush=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(rec, fh, indent=1)
        os.replace(tmp, path)
        if rec["points"][key]["status"] in ("runtime-crash", "timeout"):
            print("worker likely dead; pausing 180s for tunnel revival",
                  flush=True)
            time.sleep(180)
    print("wrote", path, flush=True)


if __name__ == "__main__":
    main()
