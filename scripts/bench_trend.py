"""Trend report + regression gate over the ``BENCH_r*.json`` history.

Usage::

    python scripts/bench_trend.py [ROOT] [--check] [--time-band X]

Every round's bench driver record is already schema-checked individually
(``scripts/validate_bench.py``); this script is the TREND contract on top:
the per-round numbers form series, and ``--check`` fails when the newest
point of a series regresses outside its tolerance band.  Run in tier-1 by
``tests/test_bench_trend.py``, so a landed bench regression fails CI
instead of silently becoming the new baseline.

Rules:

  * **Series identity** — points are only compared when they measure the
    same thing: the flagship/minibatch epoch time keys on
    ``(metric, graph, unit)`` plus any scalar bench-config fields the
    record carries (``_TIME_CFG_KEYS``: problem size, model, dtype, …;
    a ``partitioner`` of ``"none"`` normalizes to absent); the 8-dev
    diagnostic gauges additionally key on their own config (``n_8dev``,
    ``graph_8dev``, ``partitioner_8dev``).  A config change starts a new
    series rather than faking a regression.
  * **Tolerance bands, per metric kind** — measured wall-clock values
    (``unit == "s"``; other units form report-only series, since a
    throughput-style metric improves UPWARD and must not trip a
    lower-is-better band) get a MULTIPLICATIVE band (default ``--time-band
    2.0``: the newest point must be ≤ 2× the MEDIAN previous point).  The
    anchor is the median, not the historical best — one lucky fast outlier
    must not permanently tighten the gate — and the band sits above this
    host's measured cross-session drift (BASELINE.md: identical code
    2.18 s vs 3.63 s across sessions = 1.665×), so only a regression on
    top of normal drift trips it.  Deterministic counters
    (``COUNTER_KEYS``: ``km1_8dev``, ``comm_volume_rows_8dev``) get a ZERO
    band: they are plan-derived, reproducible bit-for-bit, and may never
    increase within a series.
  * **Serving series** (PR-8, gate since ISSUE 18) — the
    ``serve_qps_8dev``/``serve_subgraph_ab_8dev`` arms' measured latency
    quantiles are GATED with the same median-anchored multiplicative band
    as the epoch times (latency is lower-is-better by construction; rounds
    r01–r05 established the anchor per ROADMAP item 3c); achieved QPS
    stays REPORT-ONLY (it improves upward), and the plan-derived
    per-query/per-exchange wire-row gauges are zero-band counters like
    ``km1_8dev``.
  * **Memory-footprint series** (ISSUE 18) — the ``memory_footprint_8dev``
    block's analytic per-chip byte counts (per mode, per array family —
    ``sgcn_tpu.obs.memory``, no clock or allocator anywhere) are ZERO-band
    counters scoped on the block's (n, nnz, k): a byte that grows at fixed
    config is a new resident array, not noise.
  * **Degradation-marker aware** — a record with ``rc != 0``, or a null
    ``value`` carrying a ``skipped``/``degraded`` marker, is a GAP in the
    series (reported, never compared): the graceful-degradation contract
    says a missing number explains itself, and a gap must not poison the
    trend either way.

Exit status: 0 clean (or report-only mode), 1 with violations listed.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import numbers
import os
import re
import sys
from collections import defaultdict

ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

# deterministic (plan-derived) gauges: zero tolerance, may never increase
COUNTER_KEYS = ("km1_8dev", "comm_volume_rows_8dev")
# flagship keys that scope a counter series to one diagnostic config
_DIAG_CFG_KEYS = ("n_8dev", "graph_8dev", "partitioner_8dev")
# serving-bench series (PR-8, the serve_qps_8dev block): achieved QPS is
# REPORT-ONLY (it improves UPWARD, so the lower-is-better band never
# applies — the PR-7 unit rule), while the measured latency quantiles are
# GATED since ISSUE 18 (ROADMAP item 3c): rounds r01–r05 established the
# band, and latency is lower-is-better by construction, so the newest
# point must stay within the median-anchored multiplicative band exactly
# like the epoch-time series (degraded/skipped rounds stay gaps).  The
# plan-derived per-query wire-row gauge remains a zero-band counter.
SERVE_REPORT_KEYS = ("achieved_qps",)
SERVE_LATENCY_KEYS = ("latency_p50_ms", "latency_p99_ms")
SERVE_COUNTER_KEYS = ("wire_rows_per_query", "wire_rows_per_exchange")
# serve config fields that scope a serving series (a different graph size /
# density / depth / rate / batch shape is a different measurement, not a
# regression — nnz/nlayers matter because the zero-band wire-row counters
# are plan- and depth-derived)
_SERVE_CFG_KEYS = ("n", "graph", "nnz", "nlayers", "k", "offered_qps",
                   "max_batch")
# sub-graph serving A/B series (PR-14, the serve_subgraph_ab_8dev block):
# the block's `analytic` gauges are computed over a FIXED chunking of the
# seeded query trace (plan-derived, no clock anywhere) — ZERO-band
# counters scoped on (n, nnz, nlayers, k, schedule, max_batch) per
# ROADMAP item 3(d).  The ARMS' per-query figures are NOT counters: they
# ride the open loop's real-clock batch composition (deadline flushes
# vary with host load), so only latency/QPS report-only series come from
# the arms (SERVE_REPORT_KEYS, the PR-7 unit rule).
SUBGRAPH_COUNTER_KEYS = ("full_rows_per_query", "full_flops_per_query",
                         "subgraph_rows_per_query",
                         "subgraph_flops_per_query", "wire_rows_per_query")
_SUBGRAPH_CFG_KEYS = ("n", "nnz", "nlayers", "k", "schedule", "max_batch")
# hot-halo replication A/B series (PR-10 block, registered PR-12): every
# one of these is plan-derived and bit-reproducible at fixed config, so
# they are ZERO-band counters — the measured −11.2% true-rows win is
# regression-gated per round, not asserted once.  Scoped per partition arm
# (random/hp — the partitioner axis lives in the series name) and on the
# block's (n, graph, k, B, sync_every) config.
REPLICA_COUNTER_KEYS = (
    "true_rows_per_exchange", "true_rows_per_exchange_replica",
    "wire_rows_per_exchange", "wire_rows_per_exchange_replica",
    "wire_rows_per_step_noreplica", "wire_rows_per_step_replica",
    "km1", "km1_cache_aware", "replica_rows")
# ONE cfg-key tuple for both replica-family blocks (replica_ab +
# controller_ab share the scoping axes by construction — the controller
# child runs the same fixture shape)
_REPLICA_CFG_KEYS = ("n", "graph", "k", "replica_budget", "sync_every")
# controller A/B series (PR-12 block): the STATIC arms' exposed wire rows
# per step are schedule-derived zero-band counters; the controller arm's
# figure depends on its drift-driven retunes, so it registers REPORT-ONLY
# (a retune threshold flip across jax versions must not read as a counter
# regression) — the per-round winner check lives in validate_bench.
CONTROLLER_COUNTER_KEYS = ("exposed_wire_rows_per_step",)
# kernel × schedule A/B series (ISSUE 15, the pallas_ragged_ab_8dev
# block): per-arm wire rows and analytic halo-table bytes are plan-derived
# and bit-reproducible at fixed config — ZERO-band counters (the
# zero-halo-table contract of the pallas ragged arm is literally a zero
# that may never move); the emulate-mode epoch times stay out entirely
# (CPU kernel-emulation speed is not a tracked claim, unlike the real
# trainers' epoch series).
PALLAS_RAGGED_COUNTER_KEYS = ("wire_rows_per_exchange",
                              "halo_table_bytes_per_step")
_PALLAS_RAGGED_CFG_KEYS = ("n", "graph", "k")
# analytic per-chip HBM footprint series (ISSUE 18, the
# memory_footprint_8dev block): every figure is derived from the CommPlan
# + model config alone (sgcn_tpu.obs.memory — no clock, no compile, no
# allocator anywhere), so the per-mode per-family byte counts are ZERO-band
# counters scoped on the block's (n, nnz, k) — the mode flags live in the
# series name.  A byte that grows at fixed config is a real residency
# regression (a new resident array family), never noise.
_MEMORY_CFG_KEYS = ("n", "nnz", "k")
# scalar bench-config fields that scope a wall-clock series: a round run at
# a different problem size / model / dtype is a DIFFERENT measurement, not
# a regression (graph already keys separately)
_TIME_CFG_KEYS = ("n", "model", "dtype", "layers", "epochs", "partitioner")

DEFAULT_TIME_BAND = 2.0


def _median(vals: list) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def _is_num(x) -> bool:
    # non-finite floats must not enter a series: every NaN comparison is
    # False, so one NaN value (or a NaN-poisoned median anchor) would make
    # the gate read clean forever (validate_bench rejects NaN at the file
    # level; this guards the gate when run standalone)
    return (isinstance(x, numbers.Real) and not isinstance(x, bool)
            and math.isfinite(x))


def load_history(root: str) -> list:
    """``[(round, filename, record)]`` sorted by round number."""
    out = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        with open(path) as fh:
            out.append((int(m.group(1)), os.path.basename(path),
                        json.load(fh)))
    return sorted(out)


def extract_series(history) -> tuple[dict, list]:
    """Split the history into comparable series and gaps.

    Returns ``(series, gaps)``: ``series`` maps a key tuple to
    ``[(round, value)]`` in round order; ``gaps`` is ``[(round, reason)]``
    for rounds that measured nothing (degradation-marker aware)."""
    series: dict = defaultdict(list)
    gaps: list = []
    for rnd, fname, rec in history:
        if rec.get("rc") != 0:
            gaps.append((rnd, f"rc={rec.get('rc')} "
                              f"(tail: {str(rec.get('tail'))[-60:].strip()})"))
            continue
        parsed = rec.get("parsed")
        if not isinstance(parsed, dict):
            gaps.append((rnd, "no parsed result"))
            continue
        v = parsed.get("value")
        metric = parsed.get("metric", "?")
        if v is None:
            reason = (parsed.get("degraded") or parsed.get("skipped")
                      or "value null")
            gaps.append((rnd, f"{metric}: {reason}"))
            continue    # a degraded round is a GAP for its counters too —
            #             a partial diagnostic must not enter the zero-band
            #             series either
        elif _is_num(v):
            # only wall-clock values (unit "s", lower-is-better by
            # construction) are gate-able; other units form report-only
            # series — a throughput metric improving upward must not trip
            # the band
            unit = parsed.get("unit", "s")
            kind = "time" if unit == "s" else "metric"
            cfg = tuple(None if (c := parsed.get(k)) == "none" else c
                        for k in _TIME_CFG_KEYS)
            key = (kind, metric, parsed.get("graph", "er"), unit) + cfg
            series[key].append((rnd, float(v)))
        # deterministic 8-dev diagnostic counters, scoped to their config
        cfg = tuple(parsed.get(k) for k in _DIAG_CFG_KEYS)
        if any(c is not None for c in cfg):
            for ck in COUNTER_KEYS:
                if _is_num(parsed.get(ck)):
                    series[("counter", ck) + cfg].append(
                        (rnd, float(parsed[ck])))
        # hot-halo replication A/B: zero-band plan-derived counters per
        # partition arm (see REPLICA_COUNTER_KEYS)
        rb = parsed.get("replica_ab_8dev")
        if isinstance(rb, dict):
            rcfg = tuple(rb.get(k) for k in _REPLICA_CFG_KEYS)
            for part in ("random", "hp"):
                e = rb.get(part)
                if not isinstance(e, dict):
                    continue
                for ck in REPLICA_COUNTER_KEYS:
                    if _is_num(e.get(ck)):
                        series[("counter", f"replica_{part}_{ck}")
                               + rcfg].append((rnd, float(e[ck])))
        # controller A/B: static arms zero-band, controller arm report-only
        cb = parsed.get("controller_ab_8dev")
        if isinstance(cb, dict) and isinstance(cb.get("arms"), dict):
            ccfg = tuple(cb.get(k) for k in _REPLICA_CFG_KEYS)
            for arm, e in cb["arms"].items():
                if not isinstance(e, dict):
                    continue
                for ck in CONTROLLER_COUNTER_KEYS:
                    if not _is_num(e.get(ck)):
                        continue
                    kind = ("metric" if arm == "controller" else "counter")
                    key = ((kind, f"controller_{arm}_{ck}", "controller",
                            "rows") + ccfg if kind == "metric"
                           else (kind, f"controller_{arm}_{ck}") + ccfg)
                    series[key].append((rnd, float(e[ck])))
        # kernel × schedule A/B: zero-band plan-derived counters per arm
        # (see PALLAS_RAGGED_COUNTER_KEYS — the zero-halo-table contract)
        pb = parsed.get("pallas_ragged_ab_8dev")
        if isinstance(pb, dict):
            pcfg = tuple(pb.get(k) for k in _PALLAS_RAGGED_CFG_KEYS)
            for arm in ("ell_ragged", "pallas_ragged", "pallas_a2a"):
                e = pb.get(arm)
                if not isinstance(e, dict):
                    continue
                for ck in PALLAS_RAGGED_COUNTER_KEYS:
                    if _is_num(e.get(ck)):
                        series[("counter", f"pallas_ragged_{arm}_{ck}")
                               + pcfg].append((rnd, float(e[ck])))
        # serving-bench series (see SERVE_* docstrings above): per transport
        # arm, report-only QPS + GATED latency + zero-band wire-row counters
        sv = parsed.get("serve_qps_8dev")
        if isinstance(sv, dict) and isinstance(sv.get("arms"), dict):
            scfg = tuple(sv.get(k) for k in _SERVE_CFG_KEYS)
            for arm, e in sv["arms"].items():
                if not isinstance(e, dict):
                    continue
                for rk in SERVE_REPORT_KEYS:
                    if _is_num(e.get(rk)):
                        series[("metric", f"serve_{arm}_{rk}", "serve",
                                rk.rsplit("_", 1)[-1]) + scfg].append(
                            (rnd, float(e[rk])))
                for rk in SERVE_LATENCY_KEYS:
                    if _is_num(e.get(rk)):
                        series[("latency", f"serve_{arm}_{rk}", "serve",
                                "ms") + scfg].append((rnd, float(e[rk])))
                for ck in SERVE_COUNTER_KEYS:
                    if _is_num(e.get(ck)):
                        series[("counter", f"serve_{arm}_{ck}")
                               + scfg].append((rnd, float(e[ck])))
        # sub-graph serving A/B: zero-band DETERMINISTIC analytic counters
        # from the fixed-chunking block + report-only latency/QPS from the
        # measured arms (see SUBGRAPH_COUNTER_KEYS)
        sg = parsed.get("serve_subgraph_ab_8dev")
        if isinstance(sg, dict):
            gcfg = tuple(sg.get(k) for k in _SUBGRAPH_CFG_KEYS)
            for arm, e in (sg.get("arms") or {}).items():
                if not isinstance(e, dict):
                    continue
                for rk in SERVE_REPORT_KEYS:
                    if _is_num(e.get(rk)):
                        series[("metric", f"serve_subgraph_{arm}_{rk}",
                                "serve", rk.rsplit("_", 1)[-1])
                               + gcfg].append((rnd, float(e[rk])))
                for rk in SERVE_LATENCY_KEYS:
                    if _is_num(e.get(rk)):
                        series[("latency", f"serve_subgraph_{arm}_{rk}",
                                "serve", "ms") + gcfg].append(
                            (rnd, float(e[rk])))
            det = sg.get("analytic")
            if isinstance(det, dict):
                for ck in SUBGRAPH_COUNTER_KEYS:
                    if _is_num(det.get(ck)):
                        series[("counter", f"serve_subgraph_{ck}")
                               + gcfg].append((rnd, float(det[ck])))
        # analytic per-chip HBM footprint gauges (see _MEMORY_CFG_KEYS):
        # zero-band counters — plan-derived bytes per (mode, array family)
        mf = parsed.get("memory_footprint_8dev")
        if isinstance(mf, dict) and isinstance(mf.get("modes"), dict):
            mcfg = tuple(mf.get(k) for k in _MEMORY_CFG_KEYS)
            for mid, e in mf["modes"].items():
                if not isinstance(e, dict):
                    continue
                for ck, v in sorted(e.items()):
                    if ck.endswith("_bytes") and _is_num(v):
                        series[("counter", f"memory_{mid}_{ck}")
                               + mcfg].append((rnd, float(v)))
    return dict(series), gaps


def check_series(series: dict, time_band: float = DEFAULT_TIME_BAND) -> list:
    """Gate the newest point of every multi-point series against its band;
    returns violation strings (empty = clean)."""
    problems = []
    # cfg slots mix None/str/int — sort on the stringified key
    for key, pts in sorted(series.items(),
                           key=lambda kv: tuple(map(str, kv[0]))):
        if len(pts) < 2:
            continue
        prev, (last_rnd, last) = pts[:-1], pts[-1]
        best = min(v for _, v in prev)
        kind = key[0]
        if kind == "metric":
            continue        # non-"s" units: reported, never gated (no
            #                 universal better-direction for them)
        if kind in ("time", "latency"):
            # median anchor: a single lucky fast point must not tighten
            # the gate forever, and the band must clear this host's
            # documented 1.665x cross-session drift (BASELINE.md).
            # "latency" is the serve-quantile flavor (ms, lower-is-better
            # like "s" — gated since ISSUE 18 once r01–r05 set the anchor)
            anchor = _median([v for _, v in prev])
            limit = anchor * time_band
            if last > limit:
                what = ("a serve-latency regression"
                        if kind == "latency"
                        else "a measured-time regression")
                problems.append(
                    f"{_key_name(key)}: r{last_rnd:02d} value {last:g} "
                    f"exceeds the {time_band}x band over the median "
                    f"previous point {anchor:g} (limit {limit:g}) — "
                    f"{what} landed in the bench history")
        else:
            if last > best:
                problems.append(
                    f"{_key_name(key)}: r{last_rnd:02d} value {last:g} "
                    f"above the best previous {best:g} — deterministic "
                    "plan-derived counters may never regress within one "
                    "config")
    return problems


def _key_name(key: tuple) -> str:
    if key[0] in ("metric", "latency") and len(key) > 2 and key[2] == "serve":
        names = (_SUBGRAPH_CFG_KEYS
                 if key[1].startswith("serve_subgraph_")
                 else _SERVE_CFG_KEYS)
        cfg = [f"{k}={c}" for k, c in zip(names, key[4:])
               if c is not None]
        return f"{key[1]} ({key[3]}" \
               + (", " + ", ".join(cfg) if cfg else "") + ")"
    if key[0] == "metric" and len(key) > 2 and key[2] == "controller":
        cfg = [f"{k}={c}" for k, c in zip(_REPLICA_CFG_KEYS, key[4:])
               if c is not None]
        return f"{key[1]} (report-only" \
               + (", " + ", ".join(cfg) if cfg else "") + ")"
    if key[0] == "counter" and key[1].startswith("serve_subgraph_"):
        cfg = [f"{k}={c}" for k, c in zip(_SUBGRAPH_CFG_KEYS, key[2:])
               if c is not None]
        return f"{key[1]} ({', '.join(cfg)})"
    if key[0] == "counter" and key[1].startswith("serve_"):
        cfg = [f"{k}={c}" for k, c in zip(_SERVE_CFG_KEYS, key[2:])
               if c is not None]
        return f"{key[1]} ({', '.join(cfg)})"
    if key[0] == "counter" and key[1].startswith("memory_"):
        cfg = [f"{k}={c}" for k, c in zip(_MEMORY_CFG_KEYS, key[2:])
               if c is not None]
        return f"{key[1]} ({', '.join(cfg)})"
    if key[0] == "counter" and key[1].startswith(("replica_",
                                                   "controller_")):
        cfg = [f"{k}={c}" for k, c in zip(_REPLICA_CFG_KEYS, key[2:])
               if c is not None]
        return f"{key[1]} ({', '.join(cfg)})"
    if key[0] in ("time", "metric"):
        cfg = [f"{k}={c}" for k, c in zip(_TIME_CFG_KEYS, key[4:])
               if c is not None]
        return f"{key[1]} (graph={key[2]}, {key[3]}" \
               + (", " + ", ".join(cfg) if cfg else "") + ")"
    return f"{key[1]} ({', '.join(str(c) for c in key[2:] if c is not None)})"


def render(series: dict, gaps: list, problems: list) -> str:
    lines = ["bench trend:"]
    for key, pts in sorted(series.items(),
                           key=lambda kv: tuple(map(str, kv[0]))):
        trail = "  ".join(f"r{r:02d}={v:g}" for r, v in pts)
        lines.append(f"  {_key_name(key)}: {trail}")
        if len(pts) >= 2:
            first, last = pts[0][1], pts[-1][1]
            if first > 0:
                # report-only series (kind "metric") have no universal
                # better-direction — label the trend neutrally
                word = ("change" if key[0] == "metric"
                        else "improvement" if last <= first
                        else "regression")
                lines.append(f"    net {word}: "
                             f"{first:g} -> {last:g} ({last / first:.3g}x)")
    if gaps:
        lines.append("  gaps (degraded/skipped rounds, never compared):")
        for rnd, reason in gaps:
            lines.append(f"    r{rnd:02d}: {reason}")
    if problems:
        lines.append(f"  VIOLATIONS ({len(problems)}):")
        for p in problems:
            lines.append(f"    {p}")
    else:
        lines.append("  gate: clean")
    return "\n".join(lines)


def check_tree(root: str, time_band: float = DEFAULT_TIME_BAND):
    """Full pipeline for one root: ``(problems, report_text)``."""
    series, gaps = extract_series(load_history(root))
    problems = check_series(series, time_band=time_band)
    return problems, render(series, gaps, problems)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root", nargs="?",
                    default=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    help="directory holding the BENCH_r*.json history")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on tolerance-band violations "
                         "(the tier-1 gate mode)")
    ap.add_argument("--time-band", type=float, default=DEFAULT_TIME_BAND,
                    help="multiplicative band for measured wall-clock "
                         "series (newest <= band x median previous); "
                         f"default {DEFAULT_TIME_BAND}")
    args = ap.parse_args()
    problems, report = check_tree(args.root, time_band=args.time_band)
    print(report)
    if args.check and problems:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
