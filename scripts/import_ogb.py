"""Convert on-disk OGB / Reddit / planetoid-snapshot datasets to the repo's
``.npz`` layout (VERDICT r4 item 6).

Zero egress on this box means the real downloads cannot be fetched HERE, but
the north-star configs (`BASELINE.json`: ogbn-products, ogbn-arxiv, Reddit,
cora) must be one file-drop away from a real-data run.  This script is that
file-drop converter — runnable wherever the download exists, tested in CI on
a synthetic directory mimicking each layout.

Supported inputs:

  * ``--kind ogb <root>`` — an OGB node-prop dataset directory in the raw
    CSV layout the ogb package materializes
    (``<root>/raw/edge.csv.gz``, ``node-feat.csv.gz``, ``node-label.csv.gz``
    and ``<root>/split/<split_name>/{train,valid,test}.csv.gz``), e.g. the
    ``ogbn_products/`` or ``ogbn_arxiv/`` folder.  Directed inputs (arxiv)
    are symmetrized — the reference stacks train on undirected graphs
    (``GPU/PGCN.py:52-63`` densifies A+Aᵀ semantics; the MPI stack's mtx
    inputs are symmetric).
  * ``--kind reddit <root>`` — the GraphSAINT/DGL Reddit pair
    (``reddit_data.npz`` + ``reddit_graph.npz``).
  * ``--kind npz <file>`` — any planetoid-style CSR snapshot the repo
    already reads (``sgcn_tpu.io.datasets.load_npz_dataset``), e.g. the
    public ``cora.npz``; re-emitted in the repo layout with generated
    planetoid splits.

Output: ``<out>.npz`` (the ``save_npz_dataset`` layout every trainer CLI
accepts via ``--npz``) and ``<out>.splits.npz`` with float32 0/1
``train_mask``/``valid_mask``/``test_mask``.

Usage examples (on a machine with the data):
  python scripts/import_ogb.py --kind ogb ~/ogbn_products -o products
  python scripts/import_ogb.py --kind ogb ~/ogbn_arxiv -o arxiv
  python scripts/import_ogb.py --kind reddit ~/reddit -o reddit
  python scripts/import_ogb.py --kind npz ~/cora.npz -o cora
Then e.g.:
  python -m sgcn_tpu.train --npz products.npz -p products.8.hp -s 8 ...
"""

from __future__ import annotations

import argparse
import gzip
import os
import sys

import numpy as np
import scipy.sparse as sp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sgcn_tpu.io.datasets import (          # noqa: E402
    load_npz_dataset, planetoid_split, save_npz_dataset)


def _read_csv_gz(path: str, dtype):
    """Tolerate both .csv.gz and plain .csv (ogb ships gz).  pandas parses
    the products-scale CSVs (~124M edge lines) orders of magnitude faster
    than np.loadtxt; fall back only when pandas is absent."""
    if not os.path.exists(path) and path.endswith(".gz"):
        path = path[:-3]
    try:
        import pandas as pd
        arr = pd.read_csv(path, header=None, dtype=dtype).to_numpy()
        return np.atleast_2d(arr)
    except ImportError:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt") as fh:
            return np.loadtxt(fh, delimiter=",", dtype=dtype, ndmin=2)


def _find_split_dir(root: str) -> str | None:
    sd = os.path.join(root, "split")
    if not os.path.isdir(sd):
        return None
    subs = [os.path.join(sd, d) for d in sorted(os.listdir(sd))
            if os.path.isdir(os.path.join(sd, d))]
    return subs[0] if subs else None   # ogb has exactly one (time/sales_ranking)


def import_ogb_raw(root: str):
    """OGB raw-CSV layout -> (csr adjacency, features, labels, splits)."""
    raw = os.path.join(root, "raw")
    edges = _read_csv_gz(os.path.join(raw, "edge.csv.gz"), np.int64)
    feats = _read_csv_gz(os.path.join(raw, "node-feat.csv.gz"),
                         np.float32)
    labels = _read_csv_gz(os.path.join(raw, "node-label.csv.gz"),
                          np.int64).ravel().astype(np.int32)
    n = feats.shape[0]
    if labels.shape[0] != n:
        raise ValueError(f"{n} feature rows vs {labels.shape[0]} labels")
    src, dst = edges[:, 0], edges[:, 1]
    a = sp.coo_matrix((np.ones(len(src), np.float32), (src, dst)),
                      shape=(n, n)).tocsr()
    # symmetrize (arxiv is directed; products' one-direction edge list also
    # needs the mirror); COO->CSR summed duplicate edge lines to 2.0, so
    # re-binarize explicitly — non-unit weights would multiply through
    # normalize_adjacency into Â
    a = sp.csr_matrix(a.maximum(a.T))
    a.setdiag(0)
    a.eliminate_zeros()
    a.data[:] = 1.0
    sd = _find_split_dir(root)
    if sd is None:
        raise FileNotFoundError(
            f"no split directory under {root}/split — wrong nesting level "
            f"(point at the dataset dir, e.g. .../ogbn_products) or a "
            f"partial download; a silent empty-splits npz would only crash "
            f"later in the trainer")
    splits = {}
    for name in ("train", "valid", "test"):
        idx = _read_csv_gz(os.path.join(sd, f"{name}.csv.gz"),
                           np.int64).ravel()
        m = np.zeros(n, np.float32)
        m[idx] = 1.0
        splits[f"{name}_mask"] = m
    return a, feats, labels, splits


def import_reddit(root: str):
    """GraphSAINT/DGL Reddit pair -> same tuple as import_ogb_raw."""
    d = np.load(os.path.join(root, "reddit_data.npz"))
    g = np.load(os.path.join(root, "reddit_graph.npz"))
    feats = np.asarray(d["feature"], np.float32)
    labels = np.asarray(d["label"]).ravel().astype(np.int32)
    n = feats.shape[0]
    a = sp.csr_matrix((g["data"], (g["row"], g["col"])), shape=(n, n))
    a = sp.csr_matrix(a.maximum(a.T), dtype=np.float32)
    a.setdiag(0)
    a.eliminate_zeros()
    # node_types: 1=train 2=valid 3=test (the GraphSAINT convention)
    nt = np.asarray(d["node_types"]).ravel()
    splits = {f"{nm}_mask": (nt == code).astype(np.float32)
              for nm, code in (("train", 1), ("valid", 2), ("test", 3))}
    return a, feats, labels, splits


def import_npz(path: str, seed: int = 0):
    a, feats, labels = load_npz_dataset(path)
    a = sp.csr_matrix(a.maximum(a.T), dtype=np.float32)
    a.setdiag(0)
    a.eliminate_zeros()
    train, test = planetoid_split(labels, seed=seed)
    splits = {"train_mask": train, "valid_mask": np.zeros_like(train),
              "test_mask": test}
    return a, feats, labels, splits


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("root", help="dataset directory (or .npz file for "
                               "--kind npz)")
    p.add_argument("--kind", required=True,
                   choices=["ogb", "reddit", "npz"])
    p.add_argument("-o", "--out", required=True,
                   help="output prefix: writes <out>.npz + <out>.splits.npz")
    args = p.parse_args()

    if args.kind == "ogb":
        a, feats, labels, splits = import_ogb_raw(args.root)
    elif args.kind == "reddit":
        a, feats, labels, splits = import_reddit(args.root)
    else:
        a, feats, labels, splits = import_npz(args.root)

    save_npz_dataset(args.out + ".npz", a, feats, labels)
    np.savez_compressed(args.out + ".splits.npz", **splits)
    deg = a.nnz / max(1, a.shape[0])
    print(f"wrote {args.out}.npz: n={a.shape[0]} nnz={a.nnz} "
          f"avg_deg={deg:.1f} f={feats.shape[1]} "
          f"classes={int(labels.max()) + 1}")
    print(f"wrote {args.out}.splits.npz: "
          + " ".join(f"{k}={int(v.sum())}" for k, v in splits.items()))


if __name__ == "__main__":
    main()
