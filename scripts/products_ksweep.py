"""Products-scale partitioner k-sweep (VERDICT r4 item 3, second half).

The reference sweeps k over its large graphs offline
(``GPU/hypergraph/run.sh:1-13`` drives whole dataset directories through the
part-vector generators).  This sweep runs the native hp (colnet km1) and gp
(edge-cut) partitioners at k ∈ {8, 16, 32, 64} on both products-shape bench
graphs (BA power-law and dcsbm power-law+communities, n=2.45M, ~125M nnz),
recording km1 / wall-clock / balance per point.

km1 of the column-net model EQUALS the comm plan's send rows per layer pass
(verified at products scale, BENCH_r04 ``plan_send_rows_per_pass``), so the
sweep IS the comm-volume-vs-k curve without 8 more ~2-minute plan builds.

Writes ``bench_artifacts/products_ksweep.json``.  Single-core job, ~1-2 h;
run it nohup'd:  PYTHONPATH=/root/repo python -u scripts/products_ksweep.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))
ART = os.path.join(REPO, "bench_artifacts")


def balance(pv: np.ndarray, k: int) -> float:
    cnt = np.bincount(pv, minlength=k)
    return float(cnt.max() / cnt.mean())


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--graphs", default="dcsbm,ba")
    p.add_argument("--ks", default="8,16,32,64")
    p.add_argument("-n", type=int, default=2_450_000)
    args = p.parse_args()

    from products_partition import km1_of
    from sgcn_tpu.io.datasets import ba_graph, dcsbm_graph
    from sgcn_tpu.partition import (partition_graph,
                                    partition_hypergraph_colnet)
    from sgcn_tpu.prep import normalize_adjacency

    ks = [int(x) for x in args.ks.split(",")]
    path = os.path.join(ART, "products_ksweep.json")
    out: dict = {"n": args.n, "ks": ks, "host": "single core",
                 "rp_method": "balanced_random_partition seed 314159",
                 "note": "km1 == plan send rows per layer pass "
                         "(plan-volume invariant)", "sweep": {}}
    if os.path.exists(path):
        with open(path) as fh:
            prev = json.load(fh)
        # resume only the SAME sweep: cached points under a different n
        # would be silently relabeled
        if prev.get("n") == args.n:
            prev["ks"] = sorted(set(prev.get("ks", [])) | set(ks))
            out = prev
    for gname in args.graphs.split(","):
        t0 = time.time()
        if gname == "ba":
            a = ba_graph(args.n, 25, seed=0)
        else:
            a = dcsbm_graph(args.n, ncomm=200, avg_deg=50, seed=0)
        ahat = normalize_adjacency(a)
        del a
        csr = ahat.tocsr()
        print(f"{gname}: graph {time.time()-t0:.0f}s nnz={ahat.nnz}",
              flush=True)
        block = out["sweep"].setdefault(gname, {})
        for k in ks:
            kk = str(k)
            if kk in block:
                print(f"{gname} k={k}: cached", flush=True)
                continue
            t0 = time.time()
            pv_hp, km1_hp = partition_hypergraph_colnet(ahat, k, seed=0)
            t_hp = time.time() - t0
            t0 = time.time()
            pv_gp, _cut = partition_graph(ahat, k, seed=0)
            t_gp = time.time() - t0
            km1_gp = km1_of(csr, np.asarray(pv_gp), k)
            # permutation-based random, seed decorrelated from the graph
            # generator: iid integers(0,k) from default_rng(0) share the
            # uniform stream dcsbm_graph(seed=0) used for community
            # assignment and partially ALIGN with the communities
            # (measured: km1 404k vs a true-random 694k at 100k cells)
            from sgcn_tpu.partition import balanced_random_partition
            pv_rp = np.asarray(balanced_random_partition(
                args.n, k, seed=314159))
            km1_rp = km1_of(csr, pv_rp, k)
            block[kk] = {
                "hp": {"km1": int(km1_hp), "time_s": round(t_hp, 1),
                       "balance": balance(np.asarray(pv_hp), k)},
                "gp": {"km1": int(km1_gp), "time_s": round(t_gp, 1),
                       "balance": balance(np.asarray(pv_gp), k)},
                "rp_km1": int(km1_rp),
            }
            print(f"{gname} k={k}: {json.dumps(block[kk])}", flush=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(out, fh, indent=1)
            os.replace(tmp, path)
        del ahat, csr
    print("wrote", path, flush=True)


if __name__ == "__main__":
    main()
