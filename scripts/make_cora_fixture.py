"""Regenerate the committed cora-format fixture under tests/fixtures/.

The reference's accuracy experiment runs on the real cora download
(GPU/PGCN-Accuracy.py, README.md:110); zero egress means the repo instead
commits a deterministic generative stand-in with cora's exact format (sparse
binary bag-of-words features, 7 classes, citation-style graph) emitted in
BOTH real-data ingestion layouts:

  * ``cora_like.npz``          — planetoid/ogbn-style snapshot (--npz);
  * ``cora_like.{A,H,Y}.mtx``  — the reference's MatrixMarket family
                                  (-a/--features-mtx/--labels-mtx);
  * ``cora_like.4.hp``         — native hypergraph partitioner output (-p).

Run from the repo root: ``python scripts/make_cora_fixture.py``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sgcn_tpu.io.datasets import cora_like, save_fixture, save_npz_dataset
from sgcn_tpu.partition.emit import write_partvec
from sgcn_tpu.partition.native import partition_hypergraph_colnet

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "fixtures")


def main() -> None:
    os.makedirs(OUT, exist_ok=True)
    a, feats, labels = cora_like(n=600, nclasses=7, vocab=64, seed=7)
    prefix = os.path.join(OUT, "cora_like")
    save_npz_dataset(prefix + ".npz", a, feats, labels)
    save_fixture(prefix, a, labels=labels, features=feats)
    pv, _km1 = partition_hypergraph_colnet(a, k=4, seed=1)
    write_partvec(prefix + ".4.hp", pv)
    print("wrote fixture family under", OUT)


if __name__ == "__main__":
    main()
