"""Regenerate the committed cora-format fixture under tests/fixtures/.

The reference's accuracy experiment runs on the real cora download
(GPU/PGCN-Accuracy.py, README.md:110); zero egress means the repo instead
commits a deterministic generative stand-in with cora's exact format (sparse
binary bag-of-words features, 7 classes, citation-style graph) emitted in
BOTH real-data ingestion layouts:

  * ``cora_like.npz``          — planetoid/ogbn-style snapshot (--npz);
  * ``cora_like.{A,H,Y}.mtx``  — the reference's MatrixMarket family
                                  (-a/--features-mtx/--labels-mtx);
  * ``cora_like.4.hp``         — native hypergraph partitioner output (-p).

Run from the repo root: ``python scripts/make_cora_fixture.py``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sgcn_tpu.io.datasets import cora_like, save_fixture, save_npz_dataset
from sgcn_tpu.partition.emit import write_partvec
from sgcn_tpu.partition.native import partition_hypergraph_colnet

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "fixtures")


def main() -> None:
    os.makedirs(OUT, exist_ok=True)
    # small smoke fixture (fast tests)
    a, feats, labels = cora_like(n=600, nclasses=7, vocab=64, seed=7)
    prefix = os.path.join(OUT, "cora_like")
    save_npz_dataset(prefix + ".npz", a, feats, labels)
    save_fixture(prefix, a, labels=labels, features=feats)
    pv, _km1 = partition_hypergraph_colnet(a, k=4, seed=1)
    write_partvec(prefix + ".4.hp", pv)
    # cora's TRUE shape (VERDICT r3 item 3): 2708 papers x 1433-word binary
    # BoW x 7 classes, ~avg-deg-4 citations (real cora: 5429 edges), real
    # ~18-word documents — the dims of the reference's actual accuracy run
    # (GPU/PGCN-Accuracy.py, README.md:110)
    a, feats, labels = cora_like(n=2708, nclasses=7, vocab=1433,
                                 words_per_doc=18, avg_deg=4, seed=11)
    prefix = os.path.join(OUT, "cora2708")
    save_npz_dataset(prefix + ".npz", a, feats, labels)
    save_fixture(prefix, a, labels=labels, features=feats)
    for k in (4, 8):
        pv, _km1 = partition_hypergraph_colnet(a, k=k, seed=1)
        write_partvec(prefix + f".{k}.hp", pv)
    print("wrote fixture families under", OUT)


if __name__ == "__main__":
    main()
