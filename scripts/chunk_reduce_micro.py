"""Microbenchmark: scan-over-slots vs scan-over-row-chunks for the bucketed
slot reduce (`sgcn_tpu.ops.pspmm.bucketed_slot_reduce` scan branch).

Hypothesis tested (round-3 continuation): the scan-over-slots form carries
the full (nb, f) accumulator through every scan step — at ogbn-products
scale ~1.2 GB of carry READ + WRITE per slot on top of the gather — so
scanning over ROW CHUNKS instead (slots fully unrolled inside the body,
per-chunk output emitted through scan `ys`, no carry) should recover the
unrolled path's rate.

MEASURED RESULT (v5e, nb=2.4M, wb=16, f=128): the hypothesis is WRONG.
  scan-over-slots  (unroll=2):      0.219 s   176 Mrows/s
  scan-over-chunks (nc=196608, 12): 0.403 s    95 Mrows/s   (0.54x)
Chunking LOSES: ~196k-row gathers inside a scan run at roughly half the
per-gather rate of 2.4M-row gathers — per-gather overhead dominates before
any carry-traffic saving shows up.  Note the big-table rate itself (176
Mrows/s on a 1.2 GB table) sits well below the 350–460 Mrows/s measured on
a 169k-row table (`spmm_micro.py`), i.e. the gather rate degrades with
table size; that part is a hardware/XLA property no re-blocking of the
reduction fixed.  The shipped `bucketed_slot_reduce` therefore keeps the
scan-over-slots form.

Run on the real chip:  python scripts/chunk_reduce_micro.py
Differential protocol (BASELINE.md): per-iteration time from two on-device
fori_loop iteration counts, cancelling the ~110 ms tunnel dispatch constant.
CAVEAT: the timing sink reads one output element; XLA's DCE can narrow a
concatenated-output variant (negative/zero differential reveals it — see
the variant-c result printed last; treat it as a lower bound only if its
differential is sane).
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sgcn_tpu.ops.pspmm import (_CONCURRENT_TEMP_LIMIT as _LIMIT,
                                  _SCHED_OVERLAP_SLOTS as _OVERLAP,
                                  _SCAN_LIVE_LIMIT)


def reduce_scan_slots(flat_idx, flat_w, nb, wb, h, unroll):
    seg_i = flat_idx.reshape(wb, nb)
    seg_w = flat_w.reshape(wb, nb)

    def body(carry, iw):
        i_t, w_t = iw
        return carry + jnp.take(h, i_t, axis=0) * w_t[:, None], None

    acc0 = jnp.zeros((nb, h.shape[1]), h.dtype)
    acc, _ = lax.scan(body, acc0, (seg_i, seg_w), unroll=unroll)
    return acc


def reduce_scan_chunks(flat_idx, flat_w, nb, wb, h, nc):
    f = h.shape[1]
    nchunks = nb // nc
    main = nchunks * nc

    def body(carry, c):
        acc = None
        for t in range(wb):
            idx = lax.dynamic_slice(flat_idx, (t * nb + c * nc,), (nc,))
            w = lax.dynamic_slice(flat_w, (t * nb + c * nc,), (nc,))
            contrib = jnp.take(h, idx, axis=0) * w[:, None]
            acc = contrib if acc is None else acc + contrib
        return carry, acc

    _, ys = lax.scan(body, jnp.int32(0), jnp.arange(nchunks))
    out_main = ys.reshape(main, f)
    if main == nb:
        return out_main
    rem = nb - main
    acc = None
    for t in range(wb):
        idx = lax.dynamic_slice(flat_idx, (t * nb + main,), (rem,))
        w = lax.dynamic_slice(flat_w, (t * nb + main,), (rem,))
        contrib = jnp.take(h, idx, axis=0) * w[:, None]
        acc = contrib if acc is None else acc + contrib
    return jnp.concatenate([out_main, acc], axis=0)


def reduce_chunks_unrolled(flat_idx, flat_w, nb, wb, h, nc):
    """Variant c: Python-unrolled chunk loop, no scan at all."""
    f = h.shape[1]
    outs = []
    off = 0
    while off < nb:
        c = min(nc, nb - off)
        acc = None
        for t in range(wb):
            idx = flat_idx[t * nb + off: t * nb + off + c]
            w = flat_w[t * nb + off: t * nb + off + c]
            contrib = jnp.take(h, idx, axis=0) * w[:, None]
            acc = contrib if acc is None else acc + contrib
        outs.append(acc)
        off += c
    return jnp.concatenate(outs, axis=0)


def diff_time(fn, args, lo=2, hi=6, reps=3):
    """Differential fori_loop timing with the spmm_micro safeguards
    (ADVICE r3): the gather TABLE (last arg) is extended by 8 slack rows
    and dynamic-sliced at ``i % 8`` inside the loop, so every iteration's
    gathers are loop-VARYING and while-loop invariant code motion cannot
    hoist the body; the slice feeds only the gather source, NOT the scan
    xs (a varying-offset slice reshaped into scan xs is the known
    pathological-compile shape on this stack — see the measurement-protocol
    notes).  The sink sums the WHOLE output so DCE cannot narrow the
    gathers to the first chunk; that sum adds an identical ~2 ms to every
    strategy's iteration, well under the ~200 ms bodies being compared."""
    *rest, h = args
    h_ext = jnp.concatenate([h, h[:8]], axis=0)

    def prog(nit):
        @jax.jit
        def run(h_ext, *a):
            def body(i, acc):
                h_i = lax.dynamic_slice(h_ext, (i % 8, 0), h.shape)
                return acc + fn(*a, h_i).sum()
            return lax.fori_loop(0, nit, body, jnp.float32(0))
        return run

    def once(nit):
        run = prog(nit)
        float(run(h_ext, *rest))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(run(h_ext, *rest))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    t_lo, t_hi = once(lo), once(hi)
    return (t_hi - t_lo) / (hi - lo)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--nb", type=int, default=2_400_000)
    p.add_argument("--wb", type=int, default=16)
    p.add_argument("-f", type=int, default=128)
    p.add_argument("--dtype", default="float32")
    args = p.parse_args()

    nb, wb, f = args.nb, args.wb, args.f
    dt = jnp.dtype(args.dtype)
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.standard_normal((nb, f)), dt)
    flat_idx = jnp.asarray(rng.integers(0, nb, size=nb * wb), jnp.int32)
    flat_w = jnp.asarray(rng.standard_normal(nb * wb), dt)

    slot_bytes = nb * f * dt.itemsize
    unroll = max(1, min(4, _SCAN_LIVE_LIMIT // max(slot_bytes, 1)))
    per_row = f * dt.itemsize
    nc = max(1, _LIMIT // (min(wb, _OVERLAP) * per_row))
    nc = min(nc, nb)
    rows = nb * wb

    t = diff_time(lambda i, w, hh: reduce_scan_slots(i, w, nb, wb, hh, unroll),
                  (flat_idx, flat_w, h))
    print(f"scan-over-slots  (unroll={unroll}): {t:.4f}s  "
          f"{rows / t / 1e6:.0f} Mrows/s")

    t2 = diff_time(lambda i, w, hh: reduce_scan_chunks(i, w, nb, wb, hh, nc),
                   (flat_idx, flat_w, h))
    nchunks = nb // nc
    print(f"scan-over-chunks (nc={nc}, {nchunks} chunks): {t2:.4f}s  "
          f"{rows / t2 / 1e6:.0f} Mrows/s")
    print(f"speedup: {t / t2:.2f}x")

    t3 = diff_time(lambda i, w, hh: reduce_chunks_unrolled(i, w, nb, wb, hh, nc),
                   (flat_idx, flat_w, h))
    print(f"unrolled-chunks  (nc={nc}): {t3:.4f}s  "
          f"{rows / t3 / 1e6:.0f} Mrows/s")


if __name__ == "__main__":
    main()
