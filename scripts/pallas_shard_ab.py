"""Pallas VMEM-kernel A/B in its selection regime, on the real chip
(VERDICT r4 weak #7: the kernel was wired + parity-tested but its ~1.3×
claim was a round-1 measurement under the since-corrected timing protocol).

The kernel's window is per-chip tables small enough to pin in VMEM — what
k-way sharding produces as k grows (`ops/pallas_spmm.py::use_pallas_spmm`).
One physical chip can measure exactly that via the shard proxy: build a
k-way plan whose per-chip [local] and [halo] tables fit the budget, take
chip 0's shard, and run the SAME per-chip program with the Pallas
aggregator on and off (SGCN_PALLAS_SPMM=1/0), differential protocol,
back-to-back in one session.

Writes ``bench_artifacts/pallas_shard_ab.json``.

Run (TPU): PYTHONPATH=/root/repo python -u scripts/pallas_shard_ab.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
ART = os.path.join(REPO, "bench_artifacts")


def main() -> None:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("-n", type=int, default=40_000)
    p.add_argument("--avg-deg", type=int, default=14)
    p.add_argument("-k", type=int, default=32)
    p.add_argument("-f", type=int, default=64)
    p.add_argument("--epochs", type=int, default=8)
    args = p.parse_args()

    from bench import diff_time_q
    from sgcn_tpu.io.datasets import er_graph
    from sgcn_tpu.ops.pallas_spmm import use_pallas_spmm
    from sgcn_tpu.parallel import build_comm_plan
    from sgcn_tpu.parallel.proxy import shard_proxy_data, shard_proxy_plan
    from sgcn_tpu.partition import partition_hypergraph_colnet
    from sgcn_tpu.prep import normalize_adjacency
    from sgcn_tpu.train import FullBatchTrainer

    widths = [args.f, 16]
    ahat = normalize_adjacency(er_graph(args.n, args.avg_deg, seed=0))
    pv, km1 = partition_hypergraph_colnet(ahat, args.k, seed=0)
    plan = build_comm_plan(ahat, np.asarray(pv, np.int64), args.k)
    proxy = shard_proxy_plan(plan, chip=0)
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((args.n, args.f)).astype(np.float32)
    labels = rng.integers(0, 16, args.n).astype(np.int32)
    data = shard_proxy_data(plan, 0, feats, labels)

    out = {
        "config": {"n": args.n, "avg_deg": args.avg_deg, "k": args.k,
                   "fin": args.f, "widths": widths, "km1": int(km1),
                   "plan": {"b": plan.b, "r": plan.r, "e": plan.e}},
        "protocol": "chip-0 shard program on the real chip, pallas vs ELL "
                    "aggregator, differential median-of-3, same session",
    }
    for name, env in (("pallas", "1"), ("ell", "0")):
        os.environ["SGCN_PALLAS_SPMM"] = env
        fired = use_pallas_spmm(proxy, args.f, widths)
        if name == "pallas" and not fired:
            out["error"] = (f"selector did not fire: b={plan.b} r={plan.r} "
                            f"fmax={max([args.f] + widths)}")
            print(out["error"], flush=True)
            break
        t0 = time.time()
        tr = FullBatchTrainer(proxy, fin=args.f, widths=widths, seed=2)
        assert (tr._fwd_static.get("pallas_tb") is not None) == \
            (name == "pallas")

        def make_run(nep):
            def run():
                losses = tr.run_epochs(data, nep, sync=False)
                return float(losses[-1])
            return run

        epoch_s, n_clean = diff_time_q(make_run, 1, max(3, args.epochs))
        out[name] = {"epoch_s": epoch_s, "clean_estimates": n_clean,
                     "setup_plus_measure_s": round(time.time() - t0, 1)}
        print(name, json.dumps(out[name]), flush=True)
        del tr
    os.environ.pop("SGCN_PALLAS_SPMM", None)
    if "pallas" in out and "ell" in out:
        out["pallas_vs_ell"] = round(
            out["ell"]["epoch_s"] / out["pallas"]["epoch_s"], 3)
    path = os.path.join(ART, "pallas_shard_ab.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmp, path)
    print("wrote", path, flush=True)


if __name__ == "__main__":
    main()
