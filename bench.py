"""Benchmark: full-batch partitioned GCN per-epoch wall-clock on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol mirrors the reference's (GPU/PGCN.py:202-228): 1 warm-up epoch, then
timed epochs; epoch = full forward + backward + optimizer step over the whole
graph. The synthetic workload is sized like ogbn-arxiv (169k vertices, ~1.2M
undirected edges, 128 features, 3 layers), matching BASELINE.md config #2.

``vs_baseline`` is the speedup of our jitted TPU epoch over the reference
implementation style run on this host: a torch (CPU) ``torch.sparse.mm`` GCN
epoch with identical shapes — the reference's own compute stack, since no
NCCL/V100 cluster numbers are published in-repo (BASELINE.md).
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

import numpy as np
import scipy.sparse as sp


def synth_graph(n: int, avg_deg: int, seed: int = 0) -> sp.csr_matrix:
    """Random undirected benchmark graph (see sgcn_tpu.io.datasets.er_graph)."""
    from sgcn_tpu.io.datasets import er_graph
    return er_graph(n, avg_deg, seed)


def bench_jax(ahat, feats, labels, widths, epochs: int):
    import jax
    from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d
    from sgcn_tpu.train import FullBatchTrainer, make_train_data
    from sgcn_tpu.parallel.mesh import shard_stacked

    k = len(jax.devices())
    n = ahat.shape[0]
    part_metrics = {"partitioner": "none", "km1": 0}
    if k > 1:
        # the flagship bench exercises the paper's core idea: comm volume is
        # driven by the native hypergraph partitioner, never random
        # (GPU/PGCN.py:171-173 consumes a partitioner vector)
        from sgcn_tpu.partition import partition_hypergraph_colnet
        pv, km1 = partition_hypergraph_colnet(ahat, k, seed=0)
        part_metrics = {"partitioner": "hp", "km1": int(km1)}
    else:
        pv = np.zeros(n, dtype=np.int64)
    plan = build_comm_plan(ahat, pv, k)
    part_metrics["comm_volume_rows"] = int(plan.predicted_send_volume.sum())
    part_metrics["comm_messages"] = int(plan.predicted_message_count.sum())
    mesh = make_mesh_1d(k)
    trainer = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths, mesh=mesh)
    data = make_train_data(plan, feats, labels)
    data = type(data)(**shard_stacked(mesh, vars(data)))
    trainer.step(data)                            # warm-up (compile) + sync
    # median of per-round timings: the tunneled chip is shared, single runs
    # can be 2x noisy. Steps within a round are dispatched asynchronously and
    # the round blocks once on the last loss scalar — one host round-trip per
    # round (the tunnel's ~90 ms RTT would otherwise swamp per-epoch time;
    # a host-attached TPU pays µs for the same dispatch).
    rounds = []
    for _ in range(5):
        t0 = time.perf_counter()
        loss = None
        for _ in range(epochs):
            loss = trainer.step(data, sync=False)
        loss_val = float(loss[()])                # block on the final scalar
        rounds.append((time.perf_counter() - t0) / epochs)
        if not np.isfinite(loss_val):
            raise RuntimeError(f"non-finite loss {loss_val}")
    return statistics.median(rounds), part_metrics


def bench_torch_reference(ahat, feats, labels, widths, epochs: int) -> float:
    """Reference-style torch implementation (sparse mm + Linear + ReLU),
    same math as GPU/PGCN.py:136-148 on one process."""
    import torch
    import torch.nn.functional as F

    coo = ahat.tocoo()
    idx = torch.tensor(np.stack([coo.row, coo.col]), dtype=torch.long)
    a = torch.sparse_coo_tensor(idx, torch.tensor(coo.data), coo.shape).coalesce()
    h0 = torch.tensor(feats)
    y = torch.tensor(labels, dtype=torch.long)
    dims = list(zip([feats.shape[1]] + widths[:-1], widths))
    ws = [torch.nn.Parameter(torch.empty(i, o)) for i, o in dims]
    for w in ws:
        torch.nn.init.xavier_uniform_(w)
    opt = torch.optim.Adam(ws, lr=0.01)

    def epoch():
        opt.zero_grad()
        h = h0
        for i, w in enumerate(ws):
            z = torch.sparse.mm(a, h) @ w
            h = z if i == len(ws) - 1 else F.relu(z)
        loss = F.cross_entropy(h, y)
        loss.backward()
        opt.step()

    epoch()                                   # warm-up
    t0 = time.perf_counter()
    for _ in range(epochs):
        epoch()
    return (time.perf_counter() - t0) / epochs


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("-n", type=int, default=169_343)      # ogbn-arxiv scale
    p.add_argument("--avg-deg", type=int, default=14)
    p.add_argument("-f", type=int, default=128)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--classes", type=int, default=40)
    p.add_argument("-l", "--layers", type=int, default=3)
    p.add_argument("-e", "--epochs", type=int, default=5)
    p.add_argument("--skip-torch", action="store_true")
    args = p.parse_args()

    from sgcn_tpu.prep import normalize_adjacency
    a = synth_graph(args.n, args.avg_deg)
    ahat = normalize_adjacency(a)
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((args.n, args.f)).astype(np.float32)
    labels = rng.integers(0, args.classes, size=args.n).astype(np.int32)
    widths = [args.hidden] * (args.layers - 1) + [args.classes]

    epoch_s, part_metrics = bench_jax(ahat, feats, labels, widths, args.epochs)
    if args.skip_torch:
        vs = 1.0
    else:
        ref_s = bench_torch_reference(ahat, feats, labels, widths,
                                      max(2, args.epochs // 2))
        vs = ref_s / epoch_s
    print(json.dumps({
        "metric": "fullbatch_gcn_epoch_time",
        "value": round(epoch_s, 6),
        "unit": "s",
        "vs_baseline": round(vs, 3),
        **part_metrics,
    }))


if __name__ == "__main__":
    main()
