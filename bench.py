"""Benchmark: full-batch partitioned GCN per-epoch wall-clock on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol mirrors the reference's (GPU/PGCN.py:202-228): 1 warm-up epoch, then
timed epochs; epoch = full forward + backward + optimizer step over the whole
graph. The synthetic workload is sized like ogbn-arxiv (169k vertices, ~1.2M
undirected edges, 128 features, 3 layers), matching BASELINE.md config #2.

``vs_baseline`` is the speedup of our jitted TPU epoch over the reference
implementation style run on this host: a torch (CPU) ``torch.sparse.mm`` GCN
epoch with identical shapes — the reference's own compute stack, since no
NCCL/V100 cluster numbers are published in-repo (BASELINE.md).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np
import scipy.sparse as sp


def synth_graph(n: int, avg_deg: int, seed: int = 0,
                kind: str = "er") -> sp.csr_matrix:
    """Synthetic undirected benchmark graph at ogbn shape.

    ``er`` (default, the historical bench graph) has no degree tail;
    ``ba`` is preferential-attachment with a power-law tail — the profile
    of the real ogbn graphs, and the only one that exercises the
    degree-bucket/hub-spill layout the SpMM is designed around;
    ``dcsbm`` adds planted communities on top of the power-law tail — the
    only family where the partitioner can actually SHRINK the exchange
    (BA/ER are expanders), so it is the one that shows comm-volume-driven
    epoch differences on the multi-chip path."""
    from sgcn_tpu.io.datasets import ba_graph, dcsbm_graph, er_graph
    if kind == "ba":
        return ba_graph(n, max(1, avg_deg // 2), seed)
    if kind == "dcsbm":
        return dcsbm_graph(n, ncomm=max(8, n // 12_000), avg_deg=avg_deg,
                           seed=seed)
    return er_graph(n, avg_deg, seed)


def diff_time(make_run, lo: int, hi: int, reps: int = 5,
              retries: int = 6, estimates: int = 3) -> float:
    """See _diff_time_quality for the companion measurement-quality record."""
    value, n_clean = diff_time_q(make_run, lo, hi, reps, retries, estimates)
    _diff_time_quality["clean_estimates"] = n_clean
    _diff_time_quality["target_estimates"] = estimates
    return value


# Quality of the MOST RECENT diff_time call: how many clean differential
# estimates backed the reported median (ADVICE r3: a single-draw number must
# be distinguishable from a median-of-3 in the emitted JSON).
_diff_time_quality: dict = {}


def diff_time_q(make_run, lo: int, hi: int, reps: int = 5,
                retries: int = 6, estimates: int = 3) -> tuple[float, int]:
    """The round-3 differential protocol, shared by every bench mode:
    ``make_run(nep)`` returns a zero-arg callable that runs ``nep``
    on-device epochs and returns a synced finite scalar; the per-call
    tunnel constant (~110 ms) cancels in ``(t_hi − t_lo)/(hi − lo)``.

    Reports the MEDIAN of ``estimates`` independent differentials: a single
    differential is vulnerable to transients in either endpoint (an
    inflated ``t_lo`` shrinks it — one such draw under-reported the
    flagship by 1.7× in round 3; an inflated ``t_hi`` overstates it), and
    the per-point median-of-reps cannot remove a transient spanning a whole
    point.  Compiled programs are cached per epoch count, so the extra
    estimates cost only run time."""
    def once(nep):
        run = make_run(nep)
        run()                                     # compile + warm, retired
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            v = run()
            ts.append(time.perf_counter() - t0)
            if not np.isfinite(v):
                raise RuntimeError(f"non-finite loss {v}")
        return statistics.median(ts)

    est = []
    for _ in range(retries):
        t_lo, t_hi = once(lo), once(hi)
        if t_hi > t_lo:
            est.append((t_hi - t_lo) / (hi - lo))
            if len(est) == estimates:
                return statistics.median(est), len(est)
    if est:
        # fewer clean estimates than asked: still a differential, but the
        # robustness claim no longer holds — say so where the reader looks
        print(f"# diff_time: only {len(est)}/{estimates} clean differential "
              f"estimate(s) after {retries} attempts (chip contention?); "
              "treat the reported time as a single-draw measurement",
              file=sys.stderr)
        return statistics.median(est), len(est)
    # never fabricate a near-zero number out of tunnel noise
    raise RuntimeError(
        f"differential timing failed: t({hi} ep)={t_hi:.4f}s <= "
        f"t({lo} ep)={t_lo:.4f}s in every attempt (chip contention?)")


def paired_differential(make_a, make_b, nep: int, reps: int = 6,
                        what: str = "A/B"):
    """Rep-level PAIRED differential timing of two arms — THE shared A/B
    protocol of the one-process children (stale, ragged-schedule).

    This 2-core host drifts by tens of percent over minutes (measured
    exact-arm pre/post spreads up to 1.6×), so two separately-timed phases
    — or two separate child processes — turn a <10% effect into a coin
    flip.  Each rep times the four runs (arm-A lo/hi, arm-B lo/hi) back to
    back within seconds, forms BOTH differentials from the same machine
    state, and the medians over clean reps are compared.  ``make_*`` are
    ``make_run``-style factories (nep → zero-arg runner returning a synced
    finite scalar); returns ``(a_s, b_s, clean_pairs)`` per-epoch times.
    """
    times, clean = paired_differential_multi([make_a, make_b], nep,
                                             reps=reps, what=what)
    return times[0], times[1], clean


def paired_differential_multi(makes, nep: int, reps: int = 6,
                              what: str = "A/B"):
    """N-arm generalization of ``paired_differential`` (same protocol, same
    drift rationale): each rep times every arm's lo/hi back to back and a
    rep only counts when EVERY arm's differential is clean, so all medians
    come from identical machine states.  Returns ``(per_arm_epoch_s,
    clean_reps)``."""
    runs_lo = [m(1) for m in makes]
    runs_hi = [m(nep) for m in makes]
    for r in runs_lo + runs_hi:
        r()                                   # compile + warm, retired

    def timed(run):
        t0 = time.perf_counter()
        v = run()
        dt = time.perf_counter() - t0
        if not np.isfinite(v):
            raise RuntimeError(f"non-finite loss {v}")
        return dt

    diffs: list[list[float]] = [[] for _ in makes]
    for _ in range(reps):
        t_lo = [timed(r) for r in runs_lo]
        t_hi = [timed(r) for r in runs_hi]
        if all(h > lo for h, lo in zip(t_hi, t_lo)):
            for i, (h, lo) in enumerate(zip(t_hi, t_lo)):
                diffs[i].append((h - lo) / (nep - 1))
    if not diffs[0]:
        raise RuntimeError(f"{what}: no clean paired differentials")
    return [statistics.median(d) for d in diffs], len(diffs[0])


class _PhaseDeadlineExpired(RuntimeError):
    """A bench phase exceeded its own deadline (degraded, not a bug)."""


class _phase_deadline:
    """SIGALRM watchdog for an in-process phase: raises
    ``_PhaseDeadlineExpired`` when ``seconds`` elapse (0/None = off).

    Best-effort by design — the alarm interrupts at the next Python
    bytecode, so a wedged C call (a hung TPU tunnel) can outlive it; the
    subprocess phases carry their own hard timeouts for that case."""

    def __init__(self, seconds: float | None, phase: str):
        self.seconds = seconds or 0
        self.phase = phase

    def __enter__(self):
        if self.seconds > 0:
            import signal

            def fire(signum, frame):
                raise _PhaseDeadlineExpired(
                    f"{self.phase} phase exceeded its {self.seconds:.0f}s "
                    "deadline")

            self._old = signal.signal(signal.SIGALRM, fire)
            signal.alarm(int(self.seconds))
        return self

    def __exit__(self, *exc):
        if self.seconds > 0:
            import signal
            signal.alarm(0)
            signal.signal(signal.SIGALRM, self._old)
        return False


def _backend_unavailable(e: Exception) -> bool:
    """Classify an exception as "the accelerator backend is unavailable"
    (skip with a marker) vs a genuine code failure (propagate) — the shared
    classifier, so this path and the driver's stay in agreement."""
    from sgcn_tpu.utils.backend import looks_backend_unavailable

    return looks_backend_unavailable(f"{type(e).__name__}: {e}")


def bench_jax(ahat, feats, labels, widths, epochs: int, model: str = "gcn",
              dtype: str | None = None, remat: bool = False,
              halo_staleness: int = 0, halo_delta: bool = False,
              sync_every: int = 0, step_dispatch: bool = False,
              comm_schedule: str | None = None):
    import jax

    # The axon sitecustomize pre-registers the TPU plugin at interpreter
    # startup; the env var alone doesn't stick, the config knob does
    # (same workaround as __graft_entry__.py).
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d
    from sgcn_tpu.train import FullBatchTrainer, make_train_data
    from sgcn_tpu.parallel.mesh import shard_stacked

    k = len(jax.devices())
    n = ahat.shape[0]
    part_metrics = {"partitioner": "none", "km1": 0}
    if dtype is not None:
        part_metrics["compute_dtype"] = dtype
    if k > 1:
        # the flagship bench exercises the paper's core idea: comm volume is
        # driven by the native hypergraph partitioner, never random
        # (GPU/PGCN.py:171-173 consumes a partitioner vector)
        from sgcn_tpu.partition import partition_hypergraph_colnet
        pv, km1 = partition_hypergraph_colnet(ahat, k, seed=0)
        part_metrics = {"partitioner": "hp", "km1": int(km1)}
    else:
        pv = np.zeros(n, dtype=np.int64)
    plan = build_comm_plan(ahat, pv, k)
    part_metrics["comm_volume_rows"] = int(plan.predicted_send_volume.sum())
    part_metrics["comm_messages"] = int(plan.predicted_message_count.sum())
    mesh = make_mesh_1d(k)
    # PGAT semantics: bare stacked modules, no inter-layer activation
    # (GPU/PGAT.py:202-213; same default as the trainer CLI)
    kw = {"model": "gat", "activation": "none"} if model == "gat" else {}
    if halo_staleness:
        kw.update(halo_staleness=halo_staleness, halo_delta=halo_delta,
                  sync_every=sync_every)
        part_metrics.update(halo_staleness=halo_staleness,
                            halo_delta=halo_delta, sync_every=sync_every)
    if comm_schedule is not None:
        kw["comm_schedule"] = comm_schedule
    trainer = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths,
                               mesh=mesh, compute_dtype=dtype, remat=remat,
                               **kw)
    # padded-vs-true accounting of the SELECTED transport (the resolved
    # schedule when 'auto' was asked; docs/comm_schedule.md) — both models
    # ship a transport now, so both report it
    part_metrics["comm_schedule"] = trainer.comm_schedule
    part_metrics["padding_efficiency"] = round(
        trainer.stats.padding_efficiency, 6)
    part_metrics["wire_rows_per_exchange"] = \
        trainer.stats.wire_rows_per_exchange
    data = make_train_data(plan, feats, labels)
    data = type(data)(**shard_stacked(mesh, vars(data)))
    # DIFFERENTIAL timing (round-3 protocol, see diff_time): the reference's
    # "timed epochs after warm-up" quantity (GPU/PGCN.py:202-228) free of
    # the tunnel's per-dispatch constant.
    #
    # ``step_dispatch`` times one step() dispatch per epoch instead of the
    # fused on-device fori sweep — the stale-pipelining A/B runs both arms
    # this way: the CPU runtime overlaps the stale mode's consumer-less
    # all_to_all across step boundaries in per-step dispatch, but executes
    # fori bodies without that freedom, so the fused sweep would hide the
    # very effect being measured (dispatch cost still cancels in the
    # differential).
    if step_dispatch:
        def make_run(nep):
            def run():
                loss = None
                for _ in range(nep):
                    loss = trainer.step(data, sync=False)
                return float(loss)        # in-order dispatch: syncs the run
            return run
    else:
        def make_run(nep):
            def run():
                losses = trainer.run_epochs(data, nep, sync=False)
                return float(losses[-1])          # scalar readback = sync
            return run

    epoch_s = diff_time(make_run, 1, max(3, epochs))
    if model == "gcn" and plan.symmetric:
        if "pallas_tb" in trainer._fwd_static:
            # the trainer auto-selected the Pallas VMEM aggregator: the ELL
            # gather model below does not describe the compiled program, so
            # emitting achieved_gather_GBs / stream_ceiling_frac would
            # describe a program that didn't run — say so instead
            part_metrics["roofline_skipped"] = (
                "pallas aggregator selected (plan tables fit VMEM); the ELL "
                "gather-stream roofline does not describe this program")
        else:
            # roofline self-description (VERDICT r4 item 7): achieved
            # gathered GB/s vs the measured stream ceiling, from the SAME
            # analytic cost model the run-telemetry subsystem attributes
            # per-step events with (sgcn_tpu.obs.attribution — this used to
            # be hand-rolled here).  Plan fields are per-chip padded sizes,
            # so this is per-chip traffic (= global when k=1); bf16 compute
            # gathers 2-byte lanes
            from sgcn_tpu.obs.attribution import (roofline_fields, step_cost)
            cost = step_cost(plan, feats.shape[1], widths,
                             compute_dtype=dtype,
                             comm_schedule=trainer.comm_schedule)
            roof = roofline_fields(cost, epoch_s)
            part_metrics["gather_GB_per_epoch_per_chip"] = round(
                cost.gather_bytes / 1e9, 3)
            part_metrics["achieved_gather_GBs"] = round(
                roof["achieved_gather_GBs"], 1)
            part_metrics["stream_ceiling_frac"] = round(
                roof["stream_ceiling_frac"], 3)
            part_metrics["model_step_GFLOP"] = roof["model_step_GFLOP"]
    return epoch_s, part_metrics


def bench_minibatch(ahat, feats, labels, widths, batch_size: int,
                    epochs: int, dtype: str | None = None,
                    comm_schedule: str | None = None):
    """Mini-batch trainer epoch (PGCN-Mini-batch role, Reddit-config shape):
    one pass over all pre-sampled batches, run as ONE on-device program
    (``run_epochs_fused``) and timed differentially like the flagship."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sgcn_tpu.train.minibatch import MiniBatchTrainer

    k = len(jax.devices())
    n = ahat.shape[0]
    if k > 1:
        from sgcn_tpu.partition import partition_hypergraph_colnet
        pv, _ = partition_hypergraph_colnet(ahat, k, seed=0)
    else:
        pv = np.zeros(n, dtype=np.int64)
    tr = MiniBatchTrainer(ahat, pv, k, fin=feats.shape[1], widths=widths,
                          batch_size=batch_size, compute_dtype=dtype,
                          comm_schedule=comm_schedule)

    def make_run(nep):
        def run():
            losses = tr.run_epochs_fused(feats, labels, epochs=nep,
                                         sync=False)
            return float(losses[-1])
        return run

    epoch_s = diff_time(make_run, 1, max(3, epochs))
    return epoch_s, {
        "nbatches": len(tr.plans),
        "batch_size": batch_size,
        # the RESOLVED transport — never measure one schedule while the
        # JSON claims another (same honesty rule as the flagship block).
        # Per-EXCHANGE wire rows are uniform across batches (all plans
        # share one padded envelope), so plans[0] speaks for every exchange
        # — same key, same semantics as the flagship/CommStats figure
        "comm_schedule": tr.inner.comm_schedule,
        "wire_rows_per_exchange":
            tr.plans[0].wire_rows_per_exchange(tr.inner.comm_schedule),
        "padding_efficiency": round(
            sum(int(p.predicted_send_volume.sum()) for p in tr.plans)
            / max(sum(p.wire_rows_per_exchange(tr.inner.comm_schedule)
                      for p in tr.plans), 1), 6),
        # deterministic per-epoch figure (the trainer-level CommStats
        # counters accumulate over warm-ups/retries and are not a metric)
        "comm_volume_rows_per_epoch":
            sum(int(p.predicted_send_volume.sum()) for p in tr.plans)
            * 2 * len(widths),
    }


# The roofline vocabulary (measured stream ceiling, gather-byte model) moved
# to sgcn_tpu/obs/attribution.py — ONE cost model shared by this bench, the
# per-step run-telemetry events, and scripts/obs_report.py.


def bench_dense_equiv(n: int, fin: int, widths, epochs: int) -> float:
    """Dense-matmul roofline epoch at identical shapes — the honest
    single-chip yardstick next to the torch-CPU comparison (VERDICT r2).

    Same layer stack, loss, backward, and Adam update, but each sparse
    aggregation Â·H is replaced by an (n,f)×(f,f) dense matmul over the same
    activation rows.  That stand-in does strictly MORE FLOPs than the SpMM
    (2·n·f² vs 2·nnz·f, ~9× at ogbn-arxiv shape) while mapping perfectly to
    the MXU, so ``epoch_s / dense_equiv_s`` isolates how much the gather-bound
    sparse path costs relative to a compiler-friendly dense epoch."""
    import jax
    import jax.numpy as jnp
    import optax

    key = jax.random.PRNGKey(0)
    dims = list(zip([fin] + widths[:-1], widths))
    keys = jax.random.split(key, len(dims) + 1)
    params = [jax.random.normal(k, d, jnp.float32) * 0.05
              for k, d in zip(keys[:-1], dims)]
    mixers = [jnp.eye(i, dtype=jnp.float32) for i, _ in dims]
    h0 = jax.random.normal(keys[-1], (n, fin), jnp.float32)
    labels = jnp.zeros((n,), jnp.int32)
    opt = optax.adam(0.01)
    opt_state = opt.init(params)

    def loss_fn(ps):
        h = h0
        for i, (w, m) in enumerate(zip(ps, mixers)):
            z = (h @ m) @ w
            h = z if i == len(ps) - 1 else jax.nn.relu(z)
        logp = jax.nn.log_softmax(h)
        return -logp[jnp.arange(n), labels].mean()

    def multi(nep):
        @jax.jit
        def run(ps, st):
            def body(i, c):
                ps, st, _ = c
                loss, g = jax.value_and_grad(loss_fn)(ps)
                up, st = opt.update(g, st, ps)
                return optax.apply_updates(ps, up), st, loss
            return jax.lax.fori_loop(0, nep, body,
                                     (ps, st, jnp.float32(0)))
        return run

    # same differential protocol as bench_jax (tunnel per-call constant)
    compiled = {}                 # nep -> jitted program (reused across retries)

    def make_run(nep):
        if nep not in compiled:
            compiled[nep] = multi(nep)
        run = compiled[nep]
        return lambda: float(run(params, opt_state)[2])

    try:
        return diff_time(make_run, 1, max(3, epochs))
    except RuntimeError:
        return float("nan")   # diagnostic yardstick only; caller emits null


def bench_torch_reference(ahat, feats, labels, widths, epochs: int) -> float:
    """Reference-style torch implementation (sparse mm + Linear + ReLU),
    same math as GPU/PGCN.py:136-148 on one process."""
    import torch
    import torch.nn.functional as F

    coo = ahat.tocoo()
    idx = torch.tensor(np.stack([coo.row, coo.col]), dtype=torch.long)
    a = torch.sparse_coo_tensor(idx, torch.tensor(coo.data), coo.shape).coalesce()
    h0 = torch.tensor(feats)
    y = torch.tensor(labels, dtype=torch.long)
    dims = list(zip([feats.shape[1]] + widths[:-1], widths))
    ws = [torch.nn.Parameter(torch.empty(i, o)) for i, o in dims]
    for w in ws:
        torch.nn.init.xavier_uniform_(w)
    opt = torch.optim.Adam(ws, lr=0.01)

    def epoch():
        opt.zero_grad()
        h = h0
        for i, w in enumerate(ws):
            z = torch.sparse.mm(a, h) @ w
            h = z if i == len(ws) - 1 else F.relu(z)
        loss = F.cross_entropy(h, y)
        loss.backward()
        opt.step()

    epoch()                                   # warm-up
    t0 = time.perf_counter()
    for _ in range(epochs):
        epoch()
    return (time.perf_counter() - t0) / epochs


def _run_vdev_child(n: int, avg_deg: int, f: int, widths, epochs: int,
                    graph: str, extra_args=(), timeout_s: int = 1200):
    """Run one flagship config on the virtual 8-device CPU mesh in a
    subprocess (``__graft_entry__._virtual_mesh_env`` recipe) and return its
    parsed one-line JSON.  Raises on child failure/timeout — callers decide
    how to degrade."""
    env = dict(os.environ)
    flags = [x for x in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in x]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    env["SGCN_RESTARTS"] = "1"
    cmd = [sys.executable, os.path.abspath(__file__), "--vdev-child",
           "-n", str(n), "--avg-deg", str(avg_deg), "-f", str(f),
           "--hidden", str(widths[0]), "--classes", str(widths[-1]),
           "-l", str(len(widths)), "-e", str(epochs), "--skip-torch",
           "--graph", graph, *extra_args]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout_s,
                          cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(f"rc={proc.returncode}: {proc.stderr[-500:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_vdev_partitioned(n: int, avg_deg: int, f: int, widths, epochs: int,
                           graph: str = "ba"):
    """Measure the actual distributed algorithm on a virtual 8-device CPU
    mesh: hp-partitioned graph, real halo exchanges (all_to_all) every layer,
    grad psum — the paper's core protocol (GPU/PGCN.py:202-238) — even though
    this box exposes one TPU chip.  Re-execs this script in a subprocess with
    the conftest env and parses its one-line JSON.  Returns a degraded
    partial block on any child failure (the flagship number must not die
    with the diagnostic one).

    The child graph defaults to the power-law (ba) family — the profile of
    the real ogbn graphs — and the child partitions live with one multilevel
    restart (SGCN_RESTARTS=1) so the partitioner fits the child's time
    budget; the full-restart partitioner quality evidence lives in the
    products_partition artifact instead."""
    try:
        child = _run_vdev_child(n, avg_deg, f, widths, epochs, graph)
        return {
            "epoch_s_8dev_cpu": child["value"],
            "n_8dev": n,
            "graph_8dev": graph,
            "partitioner_8dev": child.get("partitioner"),
            "km1_8dev": child.get("km1"),
            "comm_volume_rows_8dev": child.get("comm_volume_rows"),
            "comm_messages_8dev": child.get("comm_messages"),
        }
    except subprocess.TimeoutExpired as e:      # noqa: F841 — diagnostic path
        print("# vdev8 run exceeded its deadline", file=sys.stderr)
        return {"epoch_s_8dev_cpu": None, "vdev_degraded": "deadline"}
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# vdev8 run failed: {e!r}", file=sys.stderr)
        return {"epoch_s_8dev_cpu": None, "vdev_degraded": repr(e)[:200]}


def bench_stale_ab(n: int, avg_deg: int, f: int, widths, epochs: int,
                   graph: str):
    """A/B the exact vs pipelined (staleness-1) exchange on the 8-virtual-
    device CPU mesh — the measurable form of "the exchange left the critical
    path" this box can produce without an 8-chip ICI mesh.  BOTH arms run in
    ONE child process (``--stale-ab-child``), sharing the graph, partition,
    plan, data and process state, interleaved exact→stale→exact — the
    between-process variance of separate children (~±20% on a 2-core host)
    is larger than the effect and would make the comparison a coin flip.
    Degrades to a marked partial block on child failure."""
    block: dict = {"stale_ab_8dev": None}
    try:
        child = _run_vdev_child(
            n, avg_deg, f, widths, epochs, graph,
            extra_args=("--stale-ab-child",))
        child.pop("metric", None)
        child.pop("value", None)
        block["stale_ab_8dev"] = child
        return block
    except subprocess.TimeoutExpired:
        print("# stale A/B run exceeded its deadline", file=sys.stderr)
        block["stale_ab_degraded"] = "deadline"
        return block
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# stale A/B run failed: {e!r}", file=sys.stderr)
        block["stale_ab_degraded"] = repr(e)[:200]
        return block


def bench_stale_ab_child(ahat, feats, labels, widths, epochs: int,
                         graph: str) -> dict:
    """One-process exact-vs-staleness-1 A/B (the ``--stale-ab-child`` body).

    One plan, one mesh, both trainers; per-step dispatch timing for both
    arms (the mode in which the runtime may float the stale a2a across the
    step boundary — a fused fori sweep executes loop bodies without that
    freedom and hides the effect).  The exact arm is timed BEFORE and AFTER
    the stale arm and averaged, so slow machine drift cancels instead of
    crediting either arm.  The stale arm is pure pipelining: stale feature
    and gradient exchanges, no delta wire, no periodic sync."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d
    from sgcn_tpu.parallel.mesh import shard_stacked
    from sgcn_tpu.partition import partition_hypergraph_colnet
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    k = len(jax.devices())
    n = ahat.shape[0]
    if k > 1:
        pv, km1 = partition_hypergraph_colnet(ahat, k, seed=0)
    else:
        pv, km1 = np.zeros(n, dtype=np.int64), 0
    plan = build_comm_plan(ahat, pv, k)
    mesh = make_mesh_1d(k)
    data = make_train_data(plan, feats, labels)
    data = type(data)(**shard_stacked(mesh, vars(data)))

    def arm(**kw):
        tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths,
                              mesh=mesh, **kw)

        def make_run(nep):
            def run():
                loss = None
                for _ in range(nep):
                    loss = tr.step(data, sync=False)
                return float(loss)    # in-order dispatch syncs the run
            return run
        return make_run

    # arm-level measured span (never per-step: instrumentation inside the
    # timed differential loop would perturb the measurement itself) — lands
    # in the parent bench's run dir through the inherited $SGCN_METRICS_OUT
    from sgcn_tpu.obs.tracing import scoped_span
    with scoped_span("bench:stale_ab", phase="ab_child",
                     detail=f"n={n} graph={graph}"):
        exact_s, stale_s, clean = paired_differential(
            arm(), arm(halo_staleness=1), max(8, epochs), what="stale A/B")
    return {
        "epoch_s_exact": round(exact_s, 6),
        "epoch_s_stale1": round(stale_s, 6),
        # the A/B delta IS the exposed-comm time estimate: same program
        # minus the per-layer exchange dependence
        "exposed_comm_s_estimate": round(exact_s - stale_s, 6),
        "stale_speedup": round(exact_s / stale_s, 3),
        "clean_pairs": clean,
        "n": n, "graph": graph, "km1": int(km1),
        "timing": "per-step dispatch, one process, rep-level paired "
                  "differentials (see paired_differential)",
    }


def bench_ragged_ab(n: int, avg_deg: int, f: int, widths, epochs: int,
                    graph: str = "ba", model: str = "gcn"):
    """A/B the dense a2a vs the ragged ppermute-ring schedule on the
    8-virtual-device CPU mesh, across one BALANCED (random) and one SKEWED
    (native hp) partition of the same power-law graph — the configs where
    the padded/true ratio differs most (docs/comm_schedule.md).  One child
    process runs all four arms over shared process state (the
    between-process variance lesson of ``bench_stale_ab``).  Degrades to a
    marked partial block on child failure.  ``model='gat'`` runs the SAME
    harness with the GAT trainer (the ``gat_ragged_ab_8dev`` block): the
    ring then carries the ``(fout+1)``-lane attention tables in both
    exchange directions."""
    prefix = "ragged_ab" if model == "gcn" else "gat_ragged_ab"
    block: dict = {f"{prefix}_8dev": None}
    try:
        child = _run_vdev_child(n, avg_deg, f, widths, epochs, graph,
                                extra_args=(f"--{prefix.replace('_', '-')}"
                                            "-child",))
        child.pop("metric", None)
        child.pop("value", None)
        block[f"{prefix}_8dev"] = child
        return block
    except subprocess.TimeoutExpired:
        print(f"# {model} ragged A/B run exceeded its deadline",
              file=sys.stderr)
        block[f"{prefix}_degraded"] = "deadline"
        return block
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# {model} ragged A/B run failed: {e!r}", file=sys.stderr)
        block[f"{prefix}_degraded"] = repr(e)[:200]
        return block


def bench_ragged_ab_child(ahat, feats, labels, widths, epochs: int,
                          graph: str, model: str = "gcn") -> dict:
    """One-process a2a-vs-ragged A/B (the ``--ragged-ab-child`` /
    ``--gat-ragged-ab-child`` body).

    Per partition (balanced random, skewed hp): one plan, one mesh, both
    schedule trainers; rep-level PAIRED differentials exactly like
    ``bench_stale_ab_child`` (this 2-core host drifts too much for
    separately timed phases); per-step dispatch so neither arm hides
    behind the fused sweep.  Each config emits the padded/true wire-row
    ratio next to its timings — the quantity the ragged schedule exists to
    shrink.  The wire-row win on the skewed partition is ASSERTED here (and
    re-checked by ``scripts/validate_bench.py``): epoch speed on the
    virtual CPU mesh is reported honestly but never the claim — no ICI, so
    the byte win is the TPU-relevant figure."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d
    from sgcn_tpu.parallel.mesh import shard_stacked
    from sgcn_tpu.partition import (balanced_random_partition,
                                    partition_hypergraph_colnet)
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    k = len(jax.devices())
    n = ahat.shape[0]
    out: dict = {"n": n, "graph": graph, "k": k, "model": model,
                 "timing": "per-step dispatch, one process, rep-level "
                           "paired differentials (see paired_differential)"}
    parts: list[tuple[str, np.ndarray, int | None]] = [
        ("random", balanced_random_partition(n, k, seed=1), None)]
    if k > 1:
        pv_hp, km1 = partition_hypergraph_colnet(ahat, k, seed=0)
        parts.append(("hp", pv_hp, int(km1)))
    mesh = make_mesh_1d(k)
    nep = max(6, epochs)
    model_kw = ({"model": "gat", "activation": "none"}
                if model == "gat" else {})
    for name, pv, km1 in parts:
        plan = build_comm_plan(ahat, pv, k)
        plan.ensure_ragged()
        data = make_train_data(plan, feats, labels)
        data = type(data)(**shard_stacked(mesh, vars(data)))

        def arm(schedule):
            tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths,
                                  mesh=mesh, comm_schedule=schedule,
                                  **model_kw)

            def make_run(n_ep):
                def run():
                    loss = None
                    for _ in range(n_ep):
                        loss = tr.step(data, sync=False)
                    return float(loss)    # in-order dispatch syncs the run
                return run
            return make_run

        # arm-level span (see bench_stale_ab_child: never inside the loop)
        from sgcn_tpu.obs.tracing import scoped_span
        with scoped_span(f"bench:{model}_ragged_ab:{name}",
                         phase="ab_child", detail=f"n={n} graph={graph}"):
            a2a_s, rag_s, clean = paired_differential(
                arm("a2a"), arm("ragged"), nep,
                what=f"{model} ragged A/B ({name})")
        true = int(plan.predicted_send_volume.sum())
        wire_a2a = plan.wire_rows_per_exchange("a2a")
        wire_rag = plan.wire_rows_per_exchange("ragged")
        if name == "hp" and not wire_rag < wire_a2a:
            # the acceptance invariant of the schedule: per-round pads must
            # beat the global pad on the skewed partition
            raise RuntimeError(
                f"{model} ragged A/B (hp): wire_rows_ragged={wire_rag} not "
                f"below wire_rows_a2a={wire_a2a}")
        cfg = {
            "epoch_s_a2a": round(a2a_s, 6),
            "epoch_s_ragged": round(rag_s, 6),
            "ragged_speedup": round(a2a_s / rag_s, 3),
            "clean_pairs": clean,
            "padding_efficiency": round(plan.padding_efficiency(), 6),
            # the padded/true wire-row ratio of each schedule — the dense
            # a2a's is the overhead the ragged ring deletes
            "padded_true_ratio_a2a": (round(wire_a2a / true, 3)
                                      if true else None),
            "padded_true_ratio_ragged": (round(wire_rag / true, 3)
                                         if true else None),
            "wire_rows_a2a": wire_a2a,
            "wire_rows_ragged": wire_rag,
            "true_rows": true,
            "rounds": len(plan.rr_sizes),
        }
        if km1 is not None:
            cfg["km1"] = km1
        out[name] = cfg
    return out


def bench_pallas_ragged_ab(n: int, avg_deg: int, f: int, widths, epochs: int,
                           graph: str = "ba"):
    """Three-way A/B of the schedule-agnostic Pallas aggregation
    (``pallas_ragged_ab_8dev``, ISSUE 15): ELL-ragged vs Pallas-ragged vs
    Pallas-a2a on the 8-virtual-device CPU mesh over the skewed hp
    partition.  EMULATE-mode (no TPU here — the kernel's jnp emulation
    runs, so CPU epoch time is reported honestly and is NEVER the claim);
    the acceptance figures are the DETERMINISTIC counters: the Pallas
    ragged arm ships wire rows identical to the ELL ragged arm's, carries
    ZERO analytic HBM halo-table bytes (the ring receives feed the kernel
    directly), and trains f32-bit-identically to the Pallas a2a arm.
    Degrades to a marked partial block on child failure."""
    block: dict = {"pallas_ragged_ab_8dev": None}
    try:
        child = _run_vdev_child(n, avg_deg, f, widths, epochs, graph,
                                extra_args=("--pallas-ragged-ab-child",))
        child.pop("metric", None)
        child.pop("value", None)
        block["pallas_ragged_ab_8dev"] = child
        return block
    except subprocess.TimeoutExpired:
        print("# pallas ragged A/B run exceeded its deadline",
              file=sys.stderr)
        block["pallas_ragged_ab_degraded"] = "deadline"
        return block
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# pallas ragged A/B run failed: {e!r}", file=sys.stderr)
        block["pallas_ragged_ab_degraded"] = repr(e)[:200]
        return block


def bench_pallas_ragged_ab_child(ahat, feats, labels, widths, epochs: int,
                                 graph: str) -> dict:
    """One-process kernel × schedule A/B (the ``--pallas-ragged-ab-child``
    body): arms ``ell_ragged`` / ``pallas_ragged`` / ``pallas_a2a`` over
    the skewed hp partition, rep-level paired differentials
    (``paired_differential_multi``).  The VMEM budget is forced generous
    and ``SGCN_PALLAS_SPMM=1`` pins the selection for the pallas arms —
    off-TPU the kernel runs in emulate mode, so the epoch times describe
    THIS host's XLA programs (honest, never the claim); the asserted
    figures are plan-derived deterministic counters."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sgcn_tpu.models.gcn import exchange_widths
    from sgcn_tpu.ops.pallas_spmm import pallas_spmm_fits
    from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d
    from sgcn_tpu.parallel.mesh import shard_stacked
    from sgcn_tpu.partition import partition_hypergraph_colnet
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    k = len(jax.devices())
    n = ahat.shape[0]
    out: dict = {"n": n, "graph": graph, "k": k,
                 "timing": "per-step dispatch, one process, rep-level "
                           "paired differentials; EMULATE-mode kernels "
                           "(CPU mesh) — epoch speed is reported "
                           "honestly but is never the claim; the "
                           "deterministic counters are"}
    pv, km1 = partition_hypergraph_colnet(ahat, k, seed=0)
    out["km1"] = int(km1)
    plan = build_comm_plan(ahat, pv, k)
    plan.ensure_ragged()
    os.environ["SGCN_PALLAS_VMEM"] = str(256 * 1024 * 1024)
    assert pallas_spmm_fits(plan, feats.shape[1], widths,
                            schedule="ragged")
    mesh = make_mesh_1d(k)
    data = make_train_data(plan, feats, labels)
    data = type(data)(**shard_stacked(mesh, vars(data)))
    nep = max(6, epochs)

    arms = (("ell_ragged", "0", "ragged"),
            ("pallas_ragged", "1", "ragged"),
            ("pallas_a2a", "1", "a2a"))
    trainers = {}

    def make_trainer(env, schedule):
        os.environ["SGCN_PALLAS_SPMM"] = env
        try:
            return FullBatchTrainer(plan, fin=feats.shape[1],
                                    widths=widths, mesh=mesh,
                                    comm_schedule=schedule, seed=2)
        finally:
            os.environ.pop("SGCN_PALLAS_SPMM", None)

    def arm(name, env, schedule):
        tr = make_trainer(env, schedule)
        assert ("pallas_tb" in tr._fwd_static) == env.startswith("1")
        trainers[name] = tr

        def make_run(n_ep):
            def run():
                loss = None
                for _ in range(n_ep):
                    loss = tr.step(data, sync=False)
                return float(loss)
            return run
        return make_run

    from sgcn_tpu.obs.tracing import scoped_span
    with scoped_span("bench:pallas_ragged_ab:hp", phase="ab_child",
                     detail=f"n={n} graph={graph}"):
        times, clean = paired_differential_multi(
            [arm(*a) for a in arms], nep, what="pallas ragged A/B (hp)")

    # f32 bit-identity between the two pallas arms (same tile fold order
    # across transports — the tentpole parity contract, asserted on fresh
    # trainers so the timed state does not leak in)
    losses = {}
    for name, env, schedule in arms[1:]:
        tr = make_trainer(env, schedule)
        losses[name] = [float(tr.step(data)) for _ in range(3)]
    if losses["pallas_ragged"] != losses["pallas_a2a"]:
        raise RuntimeError(
            f"pallas ragged/a2a losses not bit-identical: {losses}")

    # deterministic counters: identical ragged wire, zero halo-table bytes
    # in the pallas-ragged arm's analytic roofline
    wire_rag = plan.wire_rows_per_exchange("ragged")
    wire_a2a = plan.wire_rows_per_exchange("a2a")
    fs = exchange_widths(feats.shape[1], list(widths))
    halo_tab_a2a = 2 * sum(int(plan.r) * int(f_) * 4 for f_ in fs) * k
    for (name, _env, schedule), t in zip(arms, times):
        cfg = {
            "epoch_s": round(t, 6),
            "measured": True,
            "wire_rows_per_exchange": (wire_rag if schedule == "ragged"
                                       else wire_a2a),
            # per-step bytes of materialized (R, f_ℓ) halo tables across
            # the mesh (fwd+bwd): the ragged arms fold receives directly
            # (ELL: redge scatter-add; pallas: in-kernel), only the dense
            # a2a assembles halo tables
            "halo_table_bytes_per_step": (0 if schedule == "ragged"
                                          else halo_tab_a2a),
        }
        out[name] = cfg
    out["clean_reps"] = clean
    out["true_rows"] = int(plan.predicted_send_volume.sum())
    if not out["pallas_ragged"]["wire_rows_per_exchange"] == \
            out["ell_ragged"]["wire_rows_per_exchange"]:
        raise RuntimeError("pallas ragged arm's wire differs from ELL "
                           "ragged's — the transport must be untouched")
    if out["pallas_ragged"]["halo_table_bytes_per_step"] != 0:
        raise RuntimeError("pallas ragged arm books halo-table bytes")
    out["pallas_dispatch"] = trainers["pallas_ragged"].comm_decision.get(
        "pallas_dispatch")
    return out


def bench_ragged_stale_ab(n: int, avg_deg: int, f: int, widths, epochs: int,
                          graph: str = "ba"):
    """Three-way A/B of the COMPOSED mode (``ragged_stale_ab_8dev``):
    a2a+stale vs ragged+exact vs ragged+stale on the 8-virtual-device CPU
    mesh over the skewed hp partition — the configs whose union the
    composition claims to beat.  One child process runs all three arms over
    shared state (the between-process variance lesson of
    ``bench_stale_ab``); degrades to a marked partial block on failure."""
    block: dict = {"ragged_stale_ab_8dev": None}
    try:
        child = _run_vdev_child(n, avg_deg, f, widths, epochs, graph,
                                extra_args=("--ragged-stale-ab-child",))
        child.pop("metric", None)
        child.pop("value", None)
        block["ragged_stale_ab_8dev"] = child
        return block
    except subprocess.TimeoutExpired:
        print("# ragged-stale A/B run exceeded its deadline", file=sys.stderr)
        block["ragged_stale_ab_degraded"] = "deadline"
        return block
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# ragged-stale A/B run failed: {e!r}", file=sys.stderr)
        block["ragged_stale_ab_degraded"] = repr(e)[:200]
        return block


def bench_ragged_stale_ab_child(ahat, feats, labels, widths, epochs: int,
                                graph: str, sync_every: int = 4) -> dict:
    """One-process three-way A/B (the ``--ragged-stale-ab-child`` body):
    the composed (ragged + staleness-1) mode against BOTH single levers on
    the same hp-partitioned plan, mesh and data.

    The asserted figure is the EXPOSED-COMM accounting, not CPU-mesh epoch
    speed (no ICI here — timings are reported honestly but are not the
    claim): per arm, the exposed-comm fraction (exposed / total exchanges
    from ``CommStats`` over the steps the arm actually ran) and the average
    exposed wire rows per step it implies.  The composed arm must be ≤ both
    single levers on the fraction and STRICTLY below both on exposed wire
    rows per step: vs ragged+exact because most of its steps are hidden,
    vs a2a+stale because its exposed (sync) steps ship the ragged ring's
    smaller wire.  Both inequalities are asserted here and re-checked by
    ``scripts/validate_bench.py``."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d
    from sgcn_tpu.parallel.mesh import shard_stacked
    from sgcn_tpu.partition import partition_hypergraph_colnet
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    k = len(jax.devices())
    n = ahat.shape[0]
    if k > 1:
        pv, km1 = partition_hypergraph_colnet(ahat, k, seed=0)
    else:
        pv, km1 = np.zeros(n, dtype=np.int64), 0
    plan = build_comm_plan(ahat, pv, k)
    plan.ensure_ragged()
    mesh = make_mesh_1d(k)
    data = make_train_data(plan, feats, labels)
    data = type(data)(**shard_stacked(mesh, vars(data)))

    arms_spec = {
        "a2a_stale": dict(comm_schedule="a2a", halo_staleness=1,
                          sync_every=sync_every),
        "ragged_exact": dict(comm_schedule="ragged"),
        "ragged_stale": dict(comm_schedule="ragged", halo_staleness=1,
                             sync_every=sync_every),
    }
    trainers = {name: FullBatchTrainer(plan, fin=feats.shape[1],
                                       widths=widths, mesh=mesh, **kw)
                for name, kw in arms_spec.items()}

    def make(tr):
        def make_run(nep):
            def run():
                loss = None
                for _ in range(nep):
                    loss = tr.step(data, sync=False)
                return float(loss)    # in-order dispatch syncs the run
            return run
        return make_run

    names = list(trainers)
    # arm-level span (see bench_stale_ab_child: never inside the loop)
    from sgcn_tpu.obs.tracing import scoped_span
    with scoped_span("bench:ragged_stale_ab", phase="ab_child",
                     detail=f"n={n} graph={graph}"):
        times, clean = paired_differential_multi(
            [make(trainers[nm]) for nm in names], max(6, epochs),
            what="ragged-stale A/B")
    nl = len(widths)
    arms: dict = {}
    for nm, t in zip(names, times):
        rep = trainers[nm].stats.report()
        frac = (rep["exposed_exchanges"] / rep["exchanges"]
                if rep["exchanges"] else 1.0)
        arms[nm] = {
            "epoch_s": round(t, 6),
            "wire_rows_per_exchange": rep["wire_rows_per_exchange"],
            "exposed_comm_frac": round(frac, 6),
            # average exposed wire rows per training step (2L exchanges) —
            # the schedule-and-staleness-aware cost the composition shrinks
            "exposed_wire_rows_per_step": round(
                frac * rep["wire_rows_per_exchange"] * 2 * nl, 2),
        }
    comp, a2s, rex = (arms["ragged_stale"], arms["a2a_stale"],
                      arms["ragged_exact"])
    # the composition's acceptance inequality — never epoch speed
    if not (comp["exposed_comm_frac"] <= a2s["exposed_comm_frac"]
            and comp["exposed_comm_frac"] <= rex["exposed_comm_frac"]):
        raise RuntimeError(
            f"composed exposed_comm_frac {comp['exposed_comm_frac']} not "
            f"<= both single levers ({a2s['exposed_comm_frac']}, "
            f"{rex['exposed_comm_frac']})")
    if not (comp["exposed_wire_rows_per_step"]
            < a2s["exposed_wire_rows_per_step"]
            and comp["exposed_wire_rows_per_step"]
            < rex["exposed_wire_rows_per_step"]):
        raise RuntimeError(
            f"composed exposed wire rows {comp['exposed_wire_rows_per_step']}"
            f" not strictly below both single levers "
            f"({a2s['exposed_wire_rows_per_step']}, "
            f"{rex['exposed_wire_rows_per_step']})")
    return {
        "n": n, "graph": graph, "k": k, "km1": int(km1),
        "sync_every": sync_every,
        "clean_pairs": clean,
        "padding_efficiency": round(plan.padding_efficiency(), 6),
        "true_rows": int(plan.predicted_send_volume.sum()),
        "arms": arms,
        "note": "CPU-mesh epoch speed is reported honestly but is NOT the "
                "asserted figure (no ICI; k-1 ring dispatches are host "
                "overhead here) — the acceptance figure is the exposed-comm "
                "accounting: the composed arm's exposed fraction <= both "
                "single levers and its exposed wire rows per step strictly "
                "below both",
        "timing": "per-step dispatch, one process, rep-level paired "
                  "differentials across all three arms "
                  "(see paired_differential_multi)",
    }


def bench_replica_ab(n: int, avg_deg: int, f: int, widths, epochs: int,
                     graph: str = "ba"):
    """A/B hot-halo replication (``--replica-budget``) against the
    no-replica trainer on the 8-virtual-device CPU mesh, across one
    BALANCED (random) and one SKEWED (native cache-aware hp) partition of
    the same power-law graph — the ``replica_ab_8dev`` block
    (docs/replication.md).  One child process runs all four arms over
    shared state; degrades to a marked partial block on child failure."""
    block: dict = {"replica_ab_8dev": None}
    try:
        child = _run_vdev_child(n, avg_deg, f, widths, epochs, graph,
                                extra_args=("--replica-ab-child",))
        child.pop("metric", None)
        child.pop("value", None)
        block["replica_ab_8dev"] = child
        return block
    except subprocess.TimeoutExpired:
        print("# replica A/B run exceeded its deadline", file=sys.stderr)
        block["replica_ab_degraded"] = "deadline"
        return block
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# replica A/B run failed: {e!r}", file=sys.stderr)
        block["replica_ab_degraded"] = repr(e)[:200]
        return block


def bench_replica_ab_child(ahat, feats, labels, widths, epochs: int,
                           graph: str, sync_every: int = 4) -> dict:
    """One-process replica-vs-no-replica A/B (the ``--replica-ab-child``
    body).

    Per partition (balanced random, skewed CACHE-AWARE hp — the native
    driver co-optimizing the cut with the replica budget): one plan, one
    mesh, both trainers; rep-level PAIRED differentials like every other
    one-process child.  Both arms dispatch the same step count, so the
    cumulative CommStats byte gauges are directly comparable — and the
    asserted figures are exactly the replication contract:

      * ``halo_bytes_true_total`` STRICTLY lower with B>0 on the hp arm
        (replicated rows genuinely leave the exchange — the CaPGNN
        before/after metric the ROADMAP names);
      * average wire rows per STEP strictly lower (shrunken send pads);
      * the native cache-aware km1 <= the cache-blind driver's partition
        evaluated under the SAME objective (independent numpy evaluator).

    CPU-mesh epoch speed is reported honestly but never the claim — no
    ICI, so wire bytes are the TPU-relevant figure."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d
    from sgcn_tpu.parallel.mesh import shard_stacked
    from sgcn_tpu.partition import (balanced_random_partition,
                                    partition_hypergraph_colnet,
                                    partition_hypergraph_colnet_cache)
    from sgcn_tpu.partition.native import cache_aware_km1
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    k = len(jax.devices())
    n = ahat.shape[0]
    # budget ~ the hub head of a power-law graph: n/16 rows is a few % of
    # the vertex set but a double-digit share of Σλ on BA-style skew (hubs
    # are consumed by most chips), so the A/B demonstrates a real wire win
    # while the replica tables stay small (RP × L rows per chip)
    budget = max(64, n // 16)
    nl = len(widths)
    out: dict = {"n": n, "graph": graph, "k": k, "model": "gcn",
                 "replica_budget": budget, "sync_every": sync_every,
                 "timing": "per-step dispatch, one process, rep-level "
                           "paired differentials (see paired_differential)"}
    parts: list[tuple[str, np.ndarray, dict]] = [
        ("random", balanced_random_partition(n, k, seed=1), {})]
    if k > 1:
        # the hp arm trains on the CACHE-AWARE partition; the cache-blind
        # driver's partition is scored under the SAME objective by the
        # independent numpy evaluator — the km1 acceptance inequality
        pv_blind, km1_blind = partition_hypergraph_colnet(ahat, k, seed=0)
        pv_hp, km1_hp, km1_cache = partition_hypergraph_colnet_cache(
            ahat, k, budget, seed=0)
        blind_cache = cache_aware_km1(ahat, pv_blind, budget)
        if not km1_cache <= blind_cache:
            raise RuntimeError(
                f"cache-aware km1 {km1_cache} not <= the cache-blind "
                f"partition's cache objective {blind_cache}")
        parts.append(("hp", pv_hp, {
            "km1": int(km1_hp), "km1_blind": int(km1_blind),
            "km1_cache_aware": int(km1_cache),
            "km1_cache_blind_partition": int(blind_cache)}))
    mesh = make_mesh_1d(k)
    nep = max(6, epochs)
    for name, pv, extra in parts:
        plan = build_comm_plan(ahat, pv, k)
        data = make_train_data(plan, feats, labels)
        data = type(data)(**shard_stacked(mesh, vars(data)))

        def arm(b):
            tr = FullBatchTrainer(plan, fin=feats.shape[1], widths=widths,
                                  mesh=mesh, replica_budget=b,
                                  sync_every=sync_every if b else 0)

            def make_run(n_ep):
                def run():
                    loss = None
                    for _ in range(n_ep):
                        loss = tr.step(data, sync=False)
                    return float(loss)    # in-order dispatch syncs the run
                return run
            return tr, make_run

        tr_none, mk_none = arm(0)
        tr_rep, mk_rep = arm(budget)
        # arm-level span (see bench_stale_ab_child: never inside the loop)
        from sgcn_tpu.obs.tracing import scoped_span
        with scoped_span(f"bench:replica_ab:{name}", phase="ab_child",
                         detail=f"n={n} graph={graph} B={budget}"):
            none_s, rep_s, clean = paired_differential(
                mk_none, mk_rep, nep, what=f"replica A/B ({name})")
        rn, rr = tr_none.stats.report(), tr_rep.stats.report()
        if rn["exchanges"] != rr["exchanges"]:
            raise RuntimeError(
                f"replica A/B ({name}): arms ran unequal exchange counts "
                f"({rn['exchanges']} vs {rr['exchanges']}) — totals not "
                "comparable")
        steps = rn["exchanges"] // (2 * nl)
        cfg = {
            "epoch_s_noreplica": round(none_s, 6),
            "epoch_s_replica": round(rep_s, 6),
            "replica_speedup": round(none_s / rep_s, 3),
            "clean_pairs": clean,
            "steps": steps,
            "replica_rows": int(plan.replica_rows),
            "replica_send_saving": int(plan.replica_send_saving),
            "true_rows_per_exchange": rn["true_rows_per_exchange"],
            "true_rows_per_exchange_replica":
                rr["true_rows_per_exchange_replica"],
            "wire_rows_per_exchange": rn["wire_rows_per_exchange"],
            "wire_rows_per_exchange_replica":
                rr["wire_rows_per_exchange_replica"],
            # cumulative over the SAME dispatched step sequence — the
            # before/after metric of the feature (CaPGNN, ROADMAP item 2)
            "halo_bytes_true_total_noreplica": rn["halo_bytes_true_total"],
            "halo_bytes_true_total_replica": rr["halo_bytes_true_total"],
            "wire_rows_per_step_noreplica": round(
                rn["wire_rows_total"] / steps, 2),
            "wire_rows_per_step_replica": round(
                rr["wire_rows_total"] / steps, 2),
            **extra,
        }
        if name == "hp":
            # the acceptance inequalities of the feature — STRICT on the
            # skewed partition (re-checked by scripts/validate_bench.py)
            if not (cfg["halo_bytes_true_total_replica"]
                    < cfg["halo_bytes_true_total_noreplica"]):
                raise RuntimeError(
                    f"replica A/B (hp): halo_bytes_true_total "
                    f"{cfg['halo_bytes_true_total_replica']} not below "
                    f"{cfg['halo_bytes_true_total_noreplica']}")
            if not (cfg["wire_rows_per_step_replica"]
                    < cfg["wire_rows_per_step_noreplica"]):
                raise RuntimeError(
                    f"replica A/B (hp): wire rows/step "
                    f"{cfg['wire_rows_per_step_replica']} not below "
                    f"{cfg['wire_rows_per_step_noreplica']}")
        out[name] = cfg
    out["note"] = (
        "CPU-mesh epoch speed is reported honestly but is NOT the asserted "
        "figure (no ICI) — the acceptance figures are the wire/true-byte "
        "accounting: halo_bytes_true_total and wire rows/step strictly "
        "lower with B>0 on the hp arm, and cache-aware km1 <= the "
        "cache-blind partition's cache objective")
    return out


def bench_controller_ab(n: int, avg_deg: int, f: int, widths, epochs: int,
                        graph: str = "ba"):
    """A/B the adaptive communication controller (``--comm-schedule auto``
    + ``--replica-budget auto`` + drift-banded ``--sync-every`` retune)
    against FOUR static settings on the skewed-hp partition of a power-law
    graph — the ``controller_ab_8dev`` block (docs/comm_schedule.md).  One
    child process runs all five arms over shared state; degrades to a
    marked partial block on failure."""
    block: dict = {"controller_ab_8dev": None}
    try:
        child = _run_vdev_child(n, avg_deg, f, widths, epochs, graph,
                                extra_args=("--controller-ab-child",))
        child.pop("metric", None)
        child.pop("value", None)
        block["controller_ab_8dev"] = child
        return block
    except subprocess.TimeoutExpired:
        print("# controller A/B run exceeded its deadline", file=sys.stderr)
        block["controller_ab_degraded"] = "deadline"
        return block
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# controller A/B run failed: {e!r}", file=sys.stderr)
        block["controller_ab_degraded"] = repr(e)[:200]
        return block


def bench_controller_ab_child(ahat, feats, labels, widths, epochs: int,
                              graph: str, sync_every: int = 4) -> dict:
    """One-process controller-vs-static A/B (the ``--controller-ab-child``
    body): the adaptive controller against four static settings on the
    SAME skewed-hp-partitioned plan, mesh and data.

    The asserted figure is EXPOSED WIRE ROWS PER STEP (the
    ``exposed_wire_rows_total`` gauge over the steps each arm actually
    dispatched) — never CPU-mesh epoch time (no ICI here; timings are
    reported honestly but are not the claim).  The controller arm must be
    ≤ every static arm and STRICTLY below at least one: against the exact
    arms because its steady-state exchanges are hidden AND shrunken,
    against the stale/replica arms because its drift-banded retune can
    only widen the sync cadence when the measured drift permits (and
    holds it otherwise — a tie, never a regression).  Re-checked by
    ``scripts/validate_bench.py::check_controller_ab``."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sgcn_tpu.parallel import build_comm_plan, make_mesh_1d
    from sgcn_tpu.parallel.mesh import shard_stacked
    from sgcn_tpu.partition import partition_hypergraph_colnet
    from sgcn_tpu.train import FullBatchTrainer, make_train_data

    k = len(jax.devices())
    n = ahat.shape[0]
    if k > 1:
        pv, km1 = partition_hypergraph_colnet(ahat, k, seed=0)
    else:
        pv, km1 = np.zeros(n, dtype=np.int64), 0
    plan = build_comm_plan(ahat, pv, k)
    plan.ensure_ragged()
    mesh = make_mesh_1d(k)
    data = make_train_data(plan, feats, labels)
    data = type(data)(**shard_stacked(mesh, vars(data)))
    budget = max(64, n // 16)

    arms_spec = {
        "a2a_exact": dict(),
        "ragged_exact": dict(comm_schedule="ragged"),
        "ragged_stale": dict(comm_schedule="ragged", halo_staleness=1,
                             sync_every=sync_every),
        "replica_stale": dict(comm_schedule="ragged", halo_staleness=1,
                              replica_budget=budget,
                              sync_every=sync_every),
        "controller": dict(comm_schedule="auto", halo_staleness=1,
                           replica_budget="auto", sync_every=sync_every),
    }
    trainers = {name: FullBatchTrainer(plan, fin=feats.shape[1],
                                       widths=widths, mesh=mesh, **kw)
                for name, kw in arms_spec.items()}

    def make(tr):
        def make_run(nep):
            def run():
                loss = None
                for _ in range(nep):
                    loss = tr.step(data, sync=False)
                return float(loss)    # in-order dispatch syncs the run
            return run
        return make_run

    names = list(trainers)
    from sgcn_tpu.obs.tracing import scoped_span
    with scoped_span("bench:controller_ab", phase="ab_child",
                     detail=f"n={n} graph={graph}"):
        times, clean = paired_differential_multi(
            [make(trainers[nm]) for nm in names], max(8, epochs),
            what="controller A/B")
    nl = len(widths)
    arms: dict = {}
    for nm, t in zip(names, times):
        rep = trainers[nm].stats.report()
        steps = rep["exchanges"] // (2 * nl)
        frac = (rep["exposed_exchanges"] / rep["exchanges"]
                if rep["exchanges"] else 1.0)
        arms[nm] = {
            "epoch_s": round(t, 6),
            "steps": steps,
            "wire_rows_per_exchange": rep["wire_rows_per_exchange"],
            "exposed_comm_frac": round(frac, 6),
            # EXACT exposed wire rows per dispatched step — the subset-
            # priced gauge (full vs shrunken × exposed vs hidden) the
            # composition exists to shrink; the hidden figure shows where
            # the replica shrink lands (hidden exchanges ship nrep_* pads)
            "exposed_wire_rows_per_step": round(
                rep["exposed_wire_rows_total"] / max(steps, 1), 2),
            "hidden_wire_rows_per_step": round(
                rep["hidden_wire_rows_total"] / max(steps, 1), 2),
        }
    ctr = trainers["controller"]
    cdec = ctr.comm_decision
    arms["controller"].update(
        resolved_schedule=ctr.comm_schedule,
        replica_budget=int(ctr.replica_budget),
        sync_every_final=int(ctr.sync_every),
        retunes=len((cdec.get("controller") or {}).get("retunes", [])),
    )
    ce = arms["controller"]["exposed_wire_rows_per_step"]
    statics = [nm for nm in names if nm != "controller"]
    worse = [nm for nm in statics
             if ce > arms[nm]["exposed_wire_rows_per_step"]]
    if worse:
        raise RuntimeError(
            f"controller exposed wire rows/step {ce} above static arm(s) "
            f"{ {nm: arms[nm]['exposed_wire_rows_per_step'] for nm in worse} }")
    if not any(ce < arms[nm]["exposed_wire_rows_per_step"]
               for nm in statics):
        raise RuntimeError(
            f"controller exposed wire rows/step {ce} not STRICTLY below "
            "any static arm — the controller must beat at least one "
            "setting, not merely tie the field")
    return {
        "n": n, "graph": graph, "k": k, "km1": int(km1),
        "replica_budget": budget, "sync_every": sync_every,
        "clean_pairs": clean,
        "arms": arms,
        "note": "CPU-mesh epoch speed is reported honestly but is NOT the "
                "asserted figure (no ICI) — the acceptance figure is "
                "exposed wire rows per step: the controller arm <= every "
                "static arm, strictly below at least one",
        "timing": "per-step dispatch, one process, rep-level paired "
                  "differentials across all five arms "
                  "(see paired_differential_multi)",
    }


def bench_serve_qps(n: int, avg_deg: int, f: int, widths, graph: str = "ba"):
    """Sustained-QPS serving bench on the 8-virtual-device CPU mesh (the
    ``serve_qps_8dev`` block): synthetic open-loop traffic at a fixed
    offered rate against the forward-only serve engine
    (``sgcn_tpu/serve/``), reporting achieved QPS + p50/p99 latency per
    transport, and an a2a-vs-ragged serving A/B asserting the wire-row win
    carries over to the forward-only path.  One child process runs both
    arms over shared state (the between-process variance lesson of
    ``bench_stale_ab``); degrades to a marked partial block on failure."""
    block: dict = {"serve_qps_8dev": None}
    try:
        child = _run_vdev_child(n, avg_deg, f, widths, 2, graph,
                                extra_args=("--serve-qps-child",))
        child.pop("metric", None)
        child.pop("value", None)
        block["serve_qps_8dev"] = child
        return block
    except subprocess.TimeoutExpired:
        print("# serve QPS run exceeded its deadline", file=sys.stderr)
        block["serve_qps_degraded"] = "deadline"
        return block
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# serve QPS run failed: {e!r}", file=sys.stderr)
        block["serve_qps_degraded"] = repr(e)[:200]
        return block


def bench_serve_qps_child(ahat, feats, labels, widths, graph: str,
                          offered_qps: float = 50.0,
                          latency_budget_ms: float = 100.0,
                          max_batch: int = 16, queries: int = 200) -> dict:
    """One-process serving A/B (the ``--serve-qps-child`` body): the SAME
    hp-partitioned plan, features and open-loop query trace served through
    an a2a engine and a ragged engine back to back.

    The asserted figure is the WIRE-ROW accounting: inference has no
    gradient ring, so the forward halo exchange is the entire comm cost and
    the ragged ring must ship strictly fewer wire rows than the dense pad
    on the skewed hp partition (asserted here and re-checked by
    ``scripts/validate_bench.py``).  CPU-mesh latency/QPS are measured live
    and reported honestly — p50/p99 under ``measured: true`` provenance —
    but never the cross-transport claim (no ICI: the ring's k−1 dispatches
    are host overhead here)."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sgcn_tpu.parallel import build_comm_plan
    from sgcn_tpu.partition import partition_hypergraph_colnet
    from sgcn_tpu.serve import ServeEngine, run_loadgen, synthetic_query_ids

    k = len(jax.devices())
    n = ahat.shape[0]
    if k > 1:
        pv, km1 = partition_hypergraph_colnet(ahat, k, seed=0)
    else:
        pv, km1 = np.zeros(n, dtype=np.int64), 0
    plan = build_comm_plan(ahat, pv, k)
    plan.ensure_ragged()
    qids = synthetic_query_ids(n, queries, seed=0)
    out: dict = {
        "n": n, "graph": graph, "k": k, "km1": int(km1),
        # nnz + nlayers scope the trend series: the wire-row counters are
        # plan-derived, so a denser graph or a deeper model is a DIFFERENT
        # measurement, not a regression (the _TIME_CFG_KEYS lesson)
        "nnz": int(ahat.nnz), "nlayers": len(widths),
        "offered_qps": offered_qps,
        "latency_budget_ms": latency_budget_ms,
        "max_batch": max_batch,
        # live host-clock latency measurement from THIS process — the serve
        # flavor of the epoch-time provenance flag (validate_bench checks)
        "measured": True,
        "weights": "random-init",   # serving latency is weight-agnostic;
        #                             parity vs evaluate() is tier-1's job
        "arms": {},
        "note": "CPU-mesh latency/QPS are measured live and reported "
                "honestly but are NOT the cross-transport claim (no ICI; "
                "ring dispatches are host overhead here) — the asserted "
                "figure is the wire-row accounting: the forward exchange "
                "is serving's entire comm cost, and ragged must ship "
                "strictly fewer wire rows than a2a on the skewed hp "
                "partition",
    }
    wire = {}
    from sgcn_tpu.obs.tracing import scoped_span
    for sched in ("a2a", "ragged"):
        eng = ServeEngine(plan, fin=feats.shape[1], widths=widths,
                          comm_schedule=sched, max_batch=max_batch,
                          latency_budget_ms=latency_budget_ms, seed=0)
        eng.set_features(feats)
        eng.warmup(qids)     # every bucket, outside the measured window
        with scoped_span(f"bench:serve_qps:{sched}", phase="serve_child",
                         detail=f"n={n} graph={graph}"):
            res = run_loadgen(eng, qids, offered_qps=offered_qps)
        g = eng.gauges()
        wire[sched] = g["wire_rows_per_exchange"]
        out["arms"][sched] = {
            **res.summary(),
            "deadline_flushes": eng.batcher.deadline_flushes,
            "full_flushes": eng.batcher.full_flushes,
            "compiles": g["compiles"],
            "buckets": g["buckets"],
            "wire_rows_per_exchange": g["wire_rows_per_exchange"],
            "wire_rows_per_query": g["wire_rows_per_query"],
            "true_rows_per_exchange": g["true_rows_per_exchange"],
        }
    if k > 1 and not wire["ragged"] < wire["a2a"]:
        # the acceptance invariant carried over from training: per-round
        # pads must beat the global pad on the skewed partition
        raise RuntimeError(
            f"serve A/B (hp): wire_rows_ragged={wire['ragged']} not below "
            f"wire_rows_a2a={wire['a2a']}")
    return out


def bench_serve_subgraph(n: int, avg_deg: int, f: int, widths,
                         graph: str = "ba"):
    """Full-forward vs sub-graph serving A/B on the 8-virtual-device CPU
    mesh (the ``serve_subgraph_ab_8dev`` block): shared open-loop traffic
    against the hp partition through a ``mode='full'`` engine and a
    ``mode='subgraph'`` engine, asserting the ≥10× per-query
    FLOP/touched-row cut on the ANALYTIC gauges (docs/serving.md phase 2).

    ``avg_deg`` is capped at the CORA-LIKE sparsity the acceptance claim
    names (avg degree ~4): the receptive-set size — and therefore the cut
    — is a property of the graph's density, not of the engine (measured on
    the BA family at n=20000: deg 4 cuts rows/query ~41×, deg 10 only
    ~10× because hub 2-hop neighborhoods swallow the graph).  The block
    reports both arms' analytic figures either way, so a future denser-
    graph round is a new trend series, not a hidden regression.  Degrades
    to a marked partial block on failure."""
    avg_deg = min(int(avg_deg), 4)
    block: dict = {"serve_subgraph_ab_8dev": None}
    try:
        child = _run_vdev_child(n, avg_deg, f, widths, 2, graph,
                                extra_args=("--serve-subgraph-ab-child",))
        child.pop("metric", None)
        child.pop("value", None)
        block["serve_subgraph_ab_8dev"] = child
        return block
    except subprocess.TimeoutExpired:
        print("# serve subgraph A/B exceeded its deadline", file=sys.stderr)
        block["serve_subgraph_degraded"] = "deadline"
        return block
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# serve subgraph A/B failed: {e!r}", file=sys.stderr)
        block["serve_subgraph_degraded"] = repr(e)[:200]
        return block


def bench_serve_subgraph_child(ahat, feats, labels, widths, graph: str,
                               offered_qps: float = 50.0,
                               latency_budget_ms: float = 100.0,
                               max_batch: int = 16,
                               queries: int = 200) -> dict:
    """One-process full-vs-subgraph serving A/B (the
    ``--serve-subgraph-ab-child`` body): the SAME hp-partitioned plan,
    features and open-loop query trace served through the PR-8 full-forward
    engine and the sub-graph engine, both with double-buffered dispatch.

    The asserted figures are the ANALYTIC per-query gauges: at cora-like
    query rates a full forward computes ``k·B`` rows per micro-batch while
    the sub-graph program touches only the routed queries' L-hop receptive
    sets — both the touched-row and the FLOP per-query cut must be ≥10×
    (re-checked by ``scripts/validate_bench.py::check_serve_subgraph_ab``).
    CPU-mesh latency/QPS are measured live and reported honestly — never
    the cross-arm claim (the host-side receptive-set packing is the
    sub-graph arm's dominant cost on a no-ICI mesh; the FLOP bill is the
    TPU-relevant figure)."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sgcn_tpu.parallel import build_comm_plan
    from sgcn_tpu.partition import partition_hypergraph_colnet
    from sgcn_tpu.serve import ServeEngine, run_loadgen, synthetic_query_ids

    k = len(jax.devices())
    n = ahat.shape[0]
    if k > 1:
        pv, km1 = partition_hypergraph_colnet(ahat, k, seed=0)
    else:
        pv, km1 = np.zeros(n, dtype=np.int64), 0
    plan = build_comm_plan(ahat, pv, k)
    qids = synthetic_query_ids(n, queries, seed=0)
    out: dict = {
        "n": n, "graph": graph, "k": k, "km1": int(km1),
        "nnz": int(ahat.nnz), "nlayers": len(widths),
        "schedule": "a2a",
        "offered_qps": offered_qps,
        "latency_budget_ms": latency_budget_ms,
        "max_batch": max_batch,
        "measured": True,
        "weights": "random-init",
        "arms": {},
        "note": "CPU-mesh latency/QPS are measured live and reported "
                "honestly but are NOT the cross-arm claim (no ICI; the "
                "sub-graph arm's receptive-set packing is host overhead "
                "here) — the asserted figures are the ANALYTIC per-query "
                "gauges: touched rows/query and FLOPs/query must both cut "
                ">=10x vs the full forward at this query rate",
    }
    from sgcn_tpu.obs.tracing import scoped_span
    gauges = {}
    for arm, mode in (("full", "full"), ("subgraph", "subgraph")):
        eng = ServeEngine(plan, fin=feats.shape[1], widths=widths,
                          comm_schedule="a2a", max_batch=max_batch,
                          latency_budget_ms=latency_budget_ms, seed=0,
                          mode=mode)
        eng.set_features(feats)
        eng.warmup(qids)     # every bucket, outside the measured window
        # the sub-graph arm's shape keys depend on the TRAFFIC's receptive
        # sets, not just the query-count buckets — one unmeasured pass over
        # the same open-loop trace warms them so the measured window's
        # latency describes serving, not compilation (the PR-8 warmup
        # lesson, extended to the receptive-size ladder)
        run_loadgen(eng, qids, offered_qps=offered_qps, concurrent=True)
        eng.batcher.deadline_flushes = 0
        eng.batcher.full_flushes = 0
        with scoped_span(f"bench:serve_subgraph:{arm}",
                         phase="serve_subgraph_child",
                         detail=f"n={n} graph={graph}"):
            res = run_loadgen(eng, qids, offered_qps=offered_qps,
                              concurrent=True)
        g = eng.gauges()
        gauges[arm] = g
        batches = max(res.batches, 1)
        nq = max(res.queries, 1)
        if mode == "full":
            rows_q = g["full_rows_per_forward"] * batches / nq
            flops_q = g["full_forward_flops"] * batches / nq
        else:
            rows_q = g["touched_rows_per_query"]
            flops_q = g["subgraph_flops_per_query"]
        out["arms"][arm] = {
            **res.summary(),
            "deadline_flushes": eng.batcher.deadline_flushes,
            "full_flushes": eng.batcher.full_flushes,
            "compiles": g["compiles"],
            "rows_per_query": round(float(rows_q), 3),
            "flops_per_query": round(float(flops_q), 3),
            "wire_rows_per_query": g["wire_rows_per_query"],
        }
    out["arms"]["subgraph"]["touched_rows_per_query"] = \
        gauges["subgraph"]["touched_rows_per_query"]
    out["arms"]["subgraph"]["recipe_edges_total"] = \
        gauges["subgraph"]["recipe_edges_total"]
    # DETERMINISTIC analytic gauges (the zero-band trend series + the
    # asserted cut): the measured arms' per-query figures depend on the
    # open loop's REAL-CLOCK batch composition (deadline flushes vary with
    # host load), so the acceptance figures are recomputed over a FIXED
    # chunking of the same query trace — plan/seed-derived only, byte-
    # reproducible across rounds at equal config
    out["analytic"] = _subgraph_deterministic_gauges(
        plan, feats, qids, max_batch, widths,
        offered_qps=offered_qps, latency_budget_ms=latency_budget_ms)
    rows_cut = (out["analytic"]["full_rows_per_query"]
                / max(out["analytic"]["subgraph_rows_per_query"], 1e-9))
    flops_cut = (out["analytic"]["full_flops_per_query"]
                 / max(out["analytic"]["subgraph_flops_per_query"], 1e-9))
    out["rows_per_query_cut"] = round(float(rows_cut), 3)
    out["flops_per_query_cut"] = round(float(flops_cut), 3)
    if k > 1 and not (rows_cut >= 10.0 and flops_cut >= 10.0):
        # the acceptance invariant: sub-graph serving must be
        # query-proportional enough to cut BOTH analytic per-query bills
        # >=10x at this query rate
        raise RuntimeError(
            f"serve subgraph A/B (hp): per-query cut below 10x "
            f"(rows {rows_cut:.2f}x, flops {flops_cut:.2f}x)")
    return out


def _subgraph_deterministic_gauges(plan, feats, qids, max_batch: int,
                                   widths, offered_qps: float = 50.0,
                                   latency_budget_ms: float = 100.0) -> dict:
    """Per-query analytic figures of the full-vs-subgraph A/B over a FIXED
    chunking of ``qids`` — no clock anywhere, so these are zero-band
    bench-trend counters (``scripts/bench_trend.py``); the measured arms
    keep their real batch compositions for the honest latency/QPS report.

    The chunk size is the open loop's EXPECTED deadline-flush batch,
    derived from config alone: ``offered_qps × latency_budget`` queries
    arrive per budget window (capped at ``max_batch``).  Chunking at
    ``max_batch`` instead would under-state the full forward's per-query
    bill — small batches are exactly what makes graph-proportional
    serving expensive, the regime the ≥10× claim names."""
    import numpy as np

    from sgcn_tpu.obs.attribution import forward_flops, subgraph_batch_flops
    from sgcn_tpu.serve import SubgraphIndex, VertexRouter
    from sgcn_tpu.serve.batcher import pad_pow2

    chunk_size = min(int(max_batch), max(1, int(round(
        offered_qps * latency_budget_ms / 1e3))))
    index = SubgraphIndex(plan, "gcn")
    router = VertexRouter(plan)
    qids = np.asarray(qids, dtype=np.int64)
    nq = max(len(qids), 1)
    nlayers = len(widths)
    touched = edges = wire = 0
    nbatches = 0
    for i in range(0, len(qids), chunk_size):
        chunk = qids[i: i + chunk_size]
        by = router.route(chunk)
        sets = [index.receptive(q, nlayers) for q in by.values()]
        touched += sum(len(u) for u in sets)
        edges += sum(index.edges_in(u) for u in sets)
        wire += pad_pow2(len(chunk), 1)       # the logit psum's padded rows
        nbatches += 1
    fin = feats.shape[1]
    return {
        "chunking": f"fixed {chunk_size} = min(max_batch, "
                    "offered_qps x latency_budget)",
        "full_rows_per_query": round(plan.k * plan.b * nbatches / nq, 3),
        "full_flops_per_query": round(
            forward_flops(plan, fin, widths) * nbatches / nq, 3),
        "subgraph_rows_per_query": round(touched / nq, 3),
        "subgraph_flops_per_query": round(
            subgraph_batch_flops(touched, edges, fin, widths) / nq, 3),
        "wire_rows_per_query": round(wire / nq, 3),
    }


def bench_ab_baseline(args, rev: str) -> dict:
    """Same-session code A/B for the GB-table regime (VERDICT r4 item 9).

    Products-scale absolute rates drift with chip/tunnel state across
    sessions (BASELINE.md: identical code measured 2.18 s one session and
    3.63 s another, while a same-session worktree A/B of the two code
    versions gave 3.631 vs 3.630 s).  So when benching at table sizes in
    the drift regime, the previous round's pinned code runs in THIS session
    too: check `rev` out into a temp worktree, run the same flagship config
    there (yardsticks and diagnostics skipped), and emit its number as
    ``same_session_baseline_s``.  Comparable numbers or the pair is wrong —
    the cross-session delta is then attributable to code, not chip state.
    """
    import shutil
    import tempfile

    wt = tempfile.mkdtemp(prefix="sgcn_ab_")
    try:
        subprocess.run(["git", "worktree", "add", "--detach", wt, rev],
                       capture_output=True, text=True, check=True,
                       cwd=os.path.dirname(os.path.abspath(__file__)))
        cmd = [sys.executable, os.path.join(wt, "bench.py"),
               "-n", str(args.n), "--avg-deg", str(args.avg_deg),
               "-f", str(args.f), "--hidden", str(args.hidden),
               "--classes", str(args.classes), "-l", str(args.layers),
               "-e", str(args.epochs), "--graph", args.graph,
               "--model", args.model, "--skip-torch", "--skip-vdev"]
        # the numeric config must match or the A/B attributes dtype/remat
        # effects to code; and the child must not recurse into its own
        # pinned baseline (rev chains once the pin file is committed)
        if args.dtype:
            cmd += ["--dtype", args.dtype]
        if args.remat:
            cmd += ["--remat"]
        probe = subprocess.run(
            [sys.executable, os.path.join(wt, "bench.py"), "--help"],
            capture_output=True, text=True, cwd=wt)
        if "--ab-baseline" in probe.stdout:
            cmd += ["--ab-baseline", "none"]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=3600, cwd=wt)
        if proc.returncode != 0:
            raise RuntimeError(f"rc={proc.returncode}: {proc.stderr[-300:]}")
        child = json.loads(proc.stdout.strip().splitlines()[-1])
        return {"same_session_baseline_s": child["value"],
                "same_session_baseline_rev": rev}
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# same-session baseline run failed: {e!r}", file=sys.stderr)
        return {"same_session_baseline_s": None,
                "same_session_baseline_rev": rev}
    finally:
        subprocess.run(["git", "worktree", "remove", "--force", wt],
                       capture_output=True,
                       cwd=os.path.dirname(os.path.abspath(__file__)))
        shutil.rmtree(wt, ignore_errors=True)


def shard_epoch_model_block() -> dict:
    """Surface the measured 8-chip products epoch model (VERDICT r4 item 1):
    chip-0's shard of the k=8 hp-partitioned products-shape graph measured
    on the real chip (``scripts/shard_epoch_model.py``), composed with the
    plan's exact exchange bytes over the ring-ICI model.  Regenerated
    offline (~25 min TPU per graph family), not inside the bench."""
    base = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_artifacts")
    block = {}
    for fam, fname in (("ba", "shard_epoch_model.json"),
                       ("dcsbm", "shard_epoch_model_dcsbm.json"),
                       ("ba_bf16wire", "shard_epoch_model_bf16wire.json")):
        path = os.path.join(base, fname)
        if not os.path.exists(path):
            continue
        try:
            with open(path) as fh:
                rec = json.load(fh)
            fam_block = {"k": rec["config"]["k"], "n": rec["config"]["n"],
                         "source": f"bench_artifacts/{fname}"}
            for model in ("gcn", "gat"):
                if model in rec and "error" not in rec[model]:
                    fam_block[model] = {
                        "per_chip_compute_s":
                            round(rec[model]["per_chip_compute_s"], 4),
                        "comm_s_model":
                            round(rec["comm"][model]["comm_s_per_epoch"], 4)
                            if isinstance(rec.get("comm"), dict)
                            and model in rec.get("comm", {}) else None,
                        "epoch_s_8chip_model":
                            round(rec[model]["epoch_s_8chip_model"], 4),
                        "epoch_s_8chip_model_overlapped": round(
                            rec[model]["epoch_s_8chip_model_overlapped"], 4),
                    }
            if len(fam_block) > 3:
                block[fam] = fam_block
        except Exception as e:                  # noqa: BLE001 — diagnostic path
            print(f"# shard epoch model artifact unreadable: {e!r}",
                  file=sys.stderr)
    return {"epoch_s_8chip_model": block} if block else {}


def memory_footprint_block(n: int, avg_deg: int, f: int, widths,
                           graph: str = "ba", k: int = 8) -> dict:
    """Analytic per-chip HBM footprint gauges (the ``memory_footprint_8dev``
    block, ISSUE 18): the plan-derived residency model of
    ``sgcn_tpu.obs.memory`` evaluated for a representative mode set on the
    8-chip diagnostic shape.  No clock, no compile, no allocator anywhere —
    every byte count is a pure function of (CommPlan, model config), so
    ``scripts/bench_trend.py`` registers each (mode, array family) figure
    as a ZERO-band counter series scoped on (n, nnz, k).  ``analytic:
    true`` is the provenance flag the memory-provenance rule of
    ``scripts/validate_bench.py`` requires on residency-byte claims."""
    block: dict = {"memory_footprint_8dev": None}
    try:
        ahat = synth_graph(n, avg_deg, seed=0, kind=graph)
        from sgcn_tpu.obs.memory import memory_model
        from sgcn_tpu.parallel import build_comm_plan
        from sgcn_tpu.partition import balanced_random_partition

        pv = balanced_random_partition(ahat.shape[0], k, seed=1)
        plan = build_comm_plan(ahat, pv, k)
        modes = {
            "train_gcn_a2a": dict(workload="train", model="gcn",
                                  comm_schedule="a2a"),
            "train_gcn_ragged": dict(workload="train", model="gcn",
                                     comm_schedule="ragged"),
            "train_gcn_ragged_stale": dict(workload="train", model="gcn",
                                           comm_schedule="ragged",
                                           halo_staleness=1),
            "train_gat_a2a": dict(workload="train", model="gat",
                                  comm_schedule="a2a"),
            "serve_gcn_ragged": dict(workload="serve", model="gcn",
                                     comm_schedule="ragged"),
        }
        out: dict = {"n": int(ahat.shape[0]), "nnz": int(ahat.nnz),
                     "k": int(k), "graph": graph, "fin": int(f),
                     "nlayers": len(widths), "analytic": True, "modes": {}}
        for mid, kw in modes.items():
            m = memory_model(plan, f, list(widths), **kw)
            out["modes"][mid] = {
                "analytic": True,
                "model_bytes": int(m.total_bytes),
                **{f"{name}_bytes": int(v)
                   for name, v in sorted(m.families.items()) if v},
            }
        block["memory_footprint_8dev"] = out
        return block
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# memory footprint block failed: {e!r}", file=sys.stderr)
        block["memory_footprint_degraded"] = repr(e)[:200]
        return block


def products_partition_block() -> dict:
    """Products-scale partitioner evidence (VERDICT r3 item 1): the native
    hypergraph/graph partitioners run OFFLINE on the exact products-shape
    bench graph (2.45M vertices, 122M nnz, power-law) — a ~20-minute
    single-core job regenerated by ``scripts/products_partition.py``, not
    re-run inside the bench.  Surfaces the recorded km1 / wall-clock /
    balance so every BENCH_r*.json carries the products-scale partitioner
    numbers with provenance."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_artifacts", "products_partition.json")
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as fh:
            rec = json.load(fh)
        block = {
            "n": rec["graph"]["n"],
            "nnz": rec["graph"]["nnz"],
            "k": rec["k"],
            "km1_8dev": rec["hp"]["km1"],
            "km1_random": rec["rp"]["km1"],
            "hp_time_s": rec["hp"]["time_s"],
            "hp_nnz_balance": rec["hp"]["nnz_max_over_mean"],
            "gp_km1": rec["gp"]["km1"],
            "gp_time_s": rec["gp"]["time_s"],
            "source": "bench_artifacts/products_partition.json "
                      "(offline single-core run of scripts/"
                      "products_partition.py on the bench graph)",
        }
        if "plan_send_rows_per_pass" in rec["hp"]:
            # the REAL 8-chip comm plan built under the saved partvec
            # (scripts/products_plan_volume.py); equals km1 exactly — the
            # plan-volume invariant verified at products scale
            block["plan_send_rows_per_pass"] = \
                rec["hp"]["plan_send_rows_per_pass"]
            block["plan_messages_per_pass"] = \
                rec["hp"]["plan_messages_per_pass"]
            block["plan_b_per_chip"] = rec["hp"]["plan_b"]
        return {"products_partition_8dev": block}
    except Exception as e:                      # noqa: BLE001 — diagnostic path
        print(f"# products partition artifact unreadable: {e!r}",
              file=sys.stderr)
        return {}


def _emit_result(result: dict, args) -> None:
    """Print the one-line JSON and, under ``--metrics-out``, also persist it
    as a run directory (manifest + summary event) through the telemetry
    subsystem — the same loadable shape as a trainer run, so bench results
    and training runs share one loader (``sgcn_tpu.obs.load_run``)."""
    print(json.dumps(result))
    out = getattr(args, "metrics_out", None)
    if not out:
        return
    try:
        from sgcn_tpu.obs import RunRecorder

        with RunRecorder(out, config={k: v for k, v in vars(args).items()},
                         run_kind="bench") as rec:
            rec.record_summary(result)
    except Exception as e:              # noqa: BLE001 — observability only
        print(f"# --metrics-out write failed: {e!r}", file=sys.stderr)


def main() -> None:
    # async all-to-all on TPU meshes (no-op single-chip / CPU): the halo
    # exchange only overlaps the local slot passes when the collective is
    # async — see sgcn_tpu/utils/backend.py and tests/test_overlap_hlo.py
    from sgcn_tpu.utils.backend import enable_tpu_async_collectives
    enable_tpu_async_collectives()
    p = argparse.ArgumentParser()
    p.add_argument("-n", type=int, default=169_343)      # ogbn-arxiv scale
    p.add_argument("--avg-deg", type=int, default=14)
    p.add_argument("-f", type=int, default=128)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--classes", type=int, default=40)
    p.add_argument("-l", "--layers", type=int, default=3)
    p.add_argument("--model", default="gcn", choices=["gcn", "gat"],
                   help="gat = attention-weighted aggregation (PGAT role); "
                        "torch/dense yardsticks are GCN-shaped, so they are "
                        "skipped for gat")
    p.add_argument("-e", "--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=None,
                   help="bench the mini-batch trainer (fused epoch sweep) "
                        "instead of the full-batch flagship")
    p.add_argument("--dtype", default=None, choices=["bfloat16"],
                   help="mixed-precision compute (f32 master params)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize layer activations in the backward "
                        "(HBM-for-FLOPs trade for huge vertex counts)")
    p.add_argument("--halo-staleness", type=int, default=0, choices=[0, 1],
                   help="1 = pipelined one-step-stale halo exchange (the "
                        "a2a leaves the critical path; GCN symmetric only)")
    p.add_argument("--halo-delta", action="store_true",
                   help="halo-delta cache: boundary rows ship as bf16 "
                        "deltas accumulated into the carried halo "
                        "(requires --halo-staleness 1)")
    p.add_argument("--sync-every", type=int, default=0,
                   help="stale mode: run a full-sync (exact) step every N "
                        "steps to bound drift (0 = only the first step)")
    p.add_argument("--skip-stale-ab", action="store_true",
                   help="skip the exact-vs-staleness-1 A/B on the virtual "
                        "8-device mesh")
    p.add_argument("--stale-ab-n", type=int, default=40_000,
                   help="graph size for the stale A/B children (two extra "
                        "CPU-mesh runs; smaller than --vdev-n by default)")
    p.add_argument("--comm-schedule", default=None,
                   choices=["a2a", "ragged", "auto"],
                   help="halo transport for the flagship run "
                        "(docs/comm_schedule.md): dense all_to_all, "
                        "per-round-sized ppermute ring, or plan-driven "
                        "auto-select; default $SGCN_COMM_SCHEDULE else a2a")
    p.add_argument("--skip-ragged-ab", action="store_true",
                   help="skip the a2a-vs-ragged schedule A/B on the "
                        "virtual 8-device mesh")
    p.add_argument("--ragged-ab-n", type=int, default=30_000,
                   help="graph size for the ragged A/B child (one extra "
                        "CPU-mesh run covering a balanced-random and a "
                        "skewed hp partition)")
    p.add_argument("--skip-gat-ragged-ab", action="store_true",
                   help="skip the GAT a2a-vs-ragged schedule A/B on the "
                        "virtual 8-device mesh")
    p.add_argument("--gat-ragged-ab-n", type=int, default=15_000,
                   help="graph size for the GAT ragged A/B child (one "
                        "extra CPU-mesh run; smaller than --ragged-ab-n — "
                        "the attention tables make the arms heavier)")
    p.add_argument("--skip-replica-ab", action="store_true",
                   help="skip the hot-halo replication A/B child "
                        "(replica_ab_8dev)")
    p.add_argument("--replica-ab-n", type=int, default=30_000,
                   help="graph size for the replica A/B child (one extra "
                        "8-vdev process, four arms over two partitions)")
    p.add_argument("--skip-controller-ab", action="store_true",
                   help="skip the adaptive-controller five-arm A/B "
                        "(controller_ab_8dev: controller vs four static "
                        "comm settings on the skewed-hp partition)")
    p.add_argument("--controller-ab-n", type=int, default=20_000,
                   help="graph size for the controller A/B child (five "
                        "arms in one extra CPU-mesh run)")
    p.add_argument("--skip-serve-qps", action="store_true",
                   help="skip the sustained-QPS serving bench "
                        "(serve_qps_8dev: open-loop traffic + a2a-vs-ragged "
                        "serving A/B) on the virtual 8-device mesh")
    p.add_argument("--serve-qps-n", type=int, default=20_000,
                   help="graph size for the serve QPS child (forward-only, "
                        "lighter than the training A/Bs)")
    p.add_argument("--skip-serve-subgraph", action="store_true",
                   help="skip the full-vs-subgraph serving A/B "
                        "(serve_subgraph_ab_8dev: shared open-loop traffic, "
                        ">=10x analytic per-query FLOP/touched-row cut)")
    p.add_argument("--skip-memory-footprint", action="store_true",
                   help="skip the analytic per-chip HBM footprint gauges "
                        "(memory_footprint_8dev: plan-derived bytes per "
                        "mode x array family, zero-band trend counters)")
    p.add_argument("--serve-subgraph-n", type=int, default=20_000,
                   help="graph size for the serve subgraph A/B child")
    p.add_argument("--skip-pallas-ragged-ab", action="store_true",
                   help="skip the kernel × schedule A/B (ELL-ragged vs "
                        "Pallas-ragged vs Pallas-a2a, emulate-mode) on "
                        "the virtual 8-device mesh")
    p.add_argument("--pallas-ragged-ab-n", type=int, default=15_000,
                   help="graph size for the pallas ragged A/B child "
                        "(three arms in one extra CPU-mesh run)")
    p.add_argument("--skip-ragged-stale-ab", action="store_true",
                   help="skip the three-way composed-mode A/B (a2a+stale "
                        "vs ragged+exact vs ragged+stale) on the virtual "
                        "8-device mesh")
    p.add_argument("--ragged-stale-ab-n", type=int, default=20_000,
                   help="graph size for the composed-mode A/B child "
                        "(three arms in one extra CPU-mesh run)")
    p.add_argument("--step-dispatch", action="store_true",
                   help="time one step() dispatch per epoch instead of the "
                        "fused on-device epoch loop (the stale A/B timing "
                        "mode)")
    p.add_argument("--deadline", type=float, default=None,
                   help="flagship-phase deadline in seconds; on expiry the "
                        "bench emits a degraded partial JSON (rc 0) instead "
                        "of dying to an external timeout.  Default: "
                        "$SGCN_BENCH_DEADLINE, else 840s for sub-1M-vertex "
                        "runs and off at GB-table scale")
    p.add_argument("--graph", default="er",
                   choices=["er", "ba", "dcsbm"],
                   help="synthetic graph family: er (no hubs) or ba "
                        "(power-law tail, the ogbn-like profile)")
    p.add_argument("--skip-torch", action="store_true")
    p.add_argument("--ab-baseline", default=None, metavar="REV",
                   help="git rev to run the SAME config from in this "
                        "session (same_session_baseline_s).  Default: for "
                        "GB-table runs (-n >= 1M) the rev pinned in "
                        "bench_artifacts/ab_baseline_rev; 'none' disables")
    p.add_argument("--metrics-out", default=None, metavar="DIR",
                   help="also persist the result as a telemetry run "
                        "directory (manifest + summary event, "
                        "sgcn_tpu.obs; render with scripts/obs_report.py)")
    p.add_argument("--skip-vdev", action="store_true",
                   help="skip the virtual-8-device partitioned diagnostic run")
    p.add_argument("--vdev-n", type=int, default=120_000,
                   help="graph size for the virtual-8-device run (CPU-bound)")
    p.add_argument("--vdev-graph", default="ba",
                   choices=["er", "ba", "dcsbm"],
                   help="graph family for the virtual-8-device run "
                        "(default ba: the ogbn-like power-law profile)")
    p.add_argument("--vdev-child", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--stale-ab-child", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--ragged-ab-child", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--gat-ragged-ab-child", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--ragged-stale-ab-child", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--pallas-ragged-ab-child", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--replica-ab-child", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--controller-ab-child", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--serve-qps-child", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--serve-subgraph-ab-child", action="store_true",
                   help=argparse.SUPPRESS)
    args = p.parse_args()

    if args.metrics_out:
        # measured spans from THIS process and every A/B child land in the
        # run directory's event stream (obs.tracing.emit_span is env-gated,
        # exactly like heartbeats; children inherit the env)
        os.environ["SGCN_METRICS_OUT"] = os.path.abspath(args.metrics_out)

    # --comm-schedule ragged + --halo-staleness 1 is the supported COMPOSED
    # mode (pspmm_stale_ragged) — the flagship can bench it directly
    if (args.halo_delta or args.sync_every) and not args.halo_staleness:
        # match the trainer CLI: silently measuring exact mode while the
        # JSON reader believes it was the delta wire would be a lie
        raise SystemExit(
            "--halo-delta/--sync-every configure the stale pipelined "
            "exchange; add --halo-staleness 1")

    from sgcn_tpu.prep import normalize_adjacency
    a = synth_graph(args.n, args.avg_deg, kind=args.graph)
    ahat = normalize_adjacency(a)
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((args.n, args.f)).astype(np.float32)
    labels = rng.integers(0, args.classes, size=args.n).astype(np.int32)
    widths = [args.hidden] * (args.layers - 1) + [args.classes]

    if args.stale_ab_child:
        print(json.dumps({
            "metric": "stale_ab",
            "value": None,      # the arm fields below are the payload
            **bench_stale_ab_child(ahat, feats, labels, widths, args.epochs,
                                   graph=args.graph),
        }))
        return

    if args.ragged_ab_child:
        print(json.dumps({
            "metric": "ragged_ab",
            "value": None,      # the per-partition blocks are the payload
            **bench_ragged_ab_child(ahat, feats, labels, widths, args.epochs,
                                    graph=args.graph),
        }))
        return

    if args.gat_ragged_ab_child:
        print(json.dumps({
            "metric": "gat_ragged_ab",
            "value": None,      # the per-partition blocks are the payload
            **bench_ragged_ab_child(ahat, feats, labels, widths, args.epochs,
                                    graph=args.graph, model="gat"),
        }))
        return

    if args.pallas_ragged_ab_child:
        print(json.dumps({
            "metric": "pallas_ragged_ab",
            "value": None,      # the three-arm block is the payload
            **bench_pallas_ragged_ab_child(ahat, feats, labels, widths,
                                           args.epochs, graph=args.graph),
        }))
        return

    if args.ragged_stale_ab_child:
        print(json.dumps({
            "metric": "ragged_stale_ab",
            "value": None,      # the three-arm block is the payload
            **bench_ragged_stale_ab_child(ahat, feats, labels, widths,
                                          args.epochs, graph=args.graph),
        }))
        return

    if args.replica_ab_child:
        print(json.dumps({
            "metric": "replica_ab",
            "value": None,      # the per-partition blocks are the payload
            **bench_replica_ab_child(ahat, feats, labels, widths,
                                     args.epochs, graph=args.graph),
        }))
        return

    if args.controller_ab_child:
        print(json.dumps({
            "metric": "controller_ab",
            "value": None,      # the five-arm block is the payload
            **bench_controller_ab_child(ahat, feats, labels, widths,
                                        args.epochs, graph=args.graph),
        }))
        return

    if args.serve_qps_child:
        print(json.dumps({
            "metric": "serve_qps_ab",
            "value": None,      # the per-transport arm blocks are the payload
            **bench_serve_qps_child(ahat, feats, labels, widths,
                                    graph=args.graph),
        }))
        return

    if args.serve_subgraph_ab_child:
        print(json.dumps({
            "metric": "serve_subgraph_ab",
            "value": None,      # the per-mode arm blocks are the payload
            **bench_serve_subgraph_child(ahat, feats, labels, widths,
                                         graph=args.graph),
        }))
        return

    if args.batch_size is not None:
        if args.model != "gcn":
            raise SystemExit(
                "--batch-size benches the GCN mini-batch trainer; "
                "--model gat is not wired through it")
        if args.remat:
            raise SystemExit("--remat is not wired through the mini-batch "
                             "trainer; drop it or bench full-batch")
        mb_s, mb_metrics = bench_minibatch(ahat, feats, labels, widths,
                                           args.batch_size, args.epochs,
                                           dtype=args.dtype,
                                           comm_schedule=args.comm_schedule)
        if args.dtype:
            mb_metrics["compute_dtype"] = args.dtype
        _emit_result({
            "metric": "minibatch_gcn_epoch_time",
            "value": round(mb_s, 6),
            "unit": "s",
            "graph": args.graph,
            # provenance: this number came out of a live differential
            # measurement in THIS process — scripts/validate_bench.py
            # requires the flag on every epoch-time claim from round 6 on
            "measured": True,
            "measurement": dict(_diff_time_quality),
            **mb_metrics,
        }, args)
        return

    # graceful degradation (round-5 verdict headline): a missing TPU backend
    # or a blown phase deadline must yield a VALID partial JSON with a
    # skipped/degraded marker, not rc=1/rc=124.  Genuine code bugs still
    # raise.
    deadline = args.deadline
    if deadline is None:
        deadline = float(os.environ.get("SGCN_BENCH_DEADLINE", "0")) or \
            (840.0 if args.n < 1_000_000 else 0.0)
    partial = {
        "metric": f"fullbatch_{args.model}_epoch_time",
        "value": None, "unit": "s", "graph": args.graph,
    }
    from sgcn_tpu.obs.tracing import scoped_span
    try:
        with _phase_deadline(deadline, "flagship"), \
                scoped_span("bench:flagship", phase="flagship",
                            detail=f"{args.model} n={args.n}"):
            epoch_s, part_metrics = bench_jax(
                ahat, feats, labels, widths, args.epochs,
                model=args.model, dtype=args.dtype, remat=args.remat,
                halo_staleness=args.halo_staleness,
                halo_delta=args.halo_delta, sync_every=args.sync_every,
                step_dispatch=args.step_dispatch,
                comm_schedule=args.comm_schedule)
    except _PhaseDeadlineExpired as e:
        _emit_result({**partial, "degraded": str(e)}, args)
        return
    except Exception as e:                      # noqa: BLE001 — classify below
        if _backend_unavailable(e):
            _emit_result({**partial,
                          "skipped": f"TPU backend unavailable: "
                                     f"{str(e)[:300]}"}, args)
            return
        raise
    flagship_quality = dict(_diff_time_quality)   # before later diff_time calls
    if args.model == "gat":
        args.skip_torch = True          # yardsticks below are GCN-shaped
        args.skip_vdev = True
    # two honest yardsticks (VERDICT r2 weak #2/#6): the reference-style torch
    # CPU stack (kept, as vs_torch_cpu) and the dense-matmul roofline epoch at
    # identical shapes (epoch_vs_dense >= 1; 1.0 = sparse path at MXU parity).
    # The dense epoch is single-device, so the ratio is only meaningful for
    # the single-chip run — on a multi-chip mesh it would conflate parallel
    # speedup with gather efficiency; emit null there.
    import jax as _jax
    single = len(_jax.devices()) == 1 and args.model == "gcn"
    try:
        dense_s = bench_dense_equiv(args.n, args.f, widths, args.epochs) \
            if single else None
    except Exception as e:                      # noqa: BLE001 — yardstick only
        print(f"# dense yardstick failed: {e!r}", file=sys.stderr)
        dense_s = None
    if args.skip_torch:
        vs = None                               # never fabricate parity
    else:
        try:
            ref_s = bench_torch_reference(ahat, feats, labels, widths,
                                          max(2, args.epochs // 2))
            vs = round(ref_s / epoch_s, 3)
        except Exception as e:                  # noqa: BLE001 — yardstick only
            print(f"# torch yardstick failed: {e!r}", file=sys.stderr)
            vs = None
    vdev_metrics = {}
    if not (args.skip_vdev or args.vdev_child):
        vdev_metrics = bench_vdev_partitioned(
            args.vdev_n, args.avg_deg, args.f, widths, max(2, args.epochs // 2),
            graph=args.vdev_graph)
        if (args.model == "gcn" and args.halo_staleness == 0
                and not args.skip_stale_ab):
            vdev_metrics.update(bench_stale_ab(
                args.stale_ab_n, args.avg_deg, args.f, widths,
                max(2, args.epochs // 2), graph=args.vdev_graph))
        if (args.model == "gcn" and args.halo_staleness == 0
                and not args.skip_ragged_ab):
            vdev_metrics.update(bench_ragged_ab(
                args.ragged_ab_n, args.avg_deg, args.f, widths,
                max(2, args.epochs // 2), graph=args.vdev_graph))
        if (args.model == "gcn" and args.halo_staleness == 0
                and not args.skip_gat_ragged_ab):
            # the GAT schedule A/B rides the same diagnostic sweep (the
            # gat flagship path skips vdev entirely, so it runs here)
            vdev_metrics.update(bench_ragged_ab(
                args.gat_ragged_ab_n, args.avg_deg, args.f, widths,
                max(2, args.epochs // 2), graph=args.vdev_graph,
                model="gat"))
        if (args.model == "gcn" and args.halo_staleness == 0
                and not args.skip_ragged_stale_ab):
            # the composed-mode three-way A/B (docs/comm_schedule.md):
            # a2a+stale vs ragged+exact vs ragged+stale
            vdev_metrics.update(bench_ragged_stale_ab(
                args.ragged_stale_ab_n, args.avg_deg, args.f, widths,
                max(2, args.epochs // 2), graph=args.vdev_graph))
        if (args.model == "gcn" and args.halo_staleness == 0
                and not args.skip_pallas_ragged_ab):
            # kernel × schedule composition A/B (ISSUE 15): ELL-ragged vs
            # Pallas-ragged vs Pallas-a2a, emulate-mode deterministic
            # counters the claim
            vdev_metrics.update(bench_pallas_ragged_ab(
                args.pallas_ragged_ab_n, args.avg_deg, args.f, widths,
                max(2, args.epochs // 2), graph=args.vdev_graph))
        if (args.model == "gcn" and args.halo_staleness == 0
                and not args.skip_replica_ab):
            # the hot-halo replication A/B (docs/replication.md): B>0 vs
            # no-replica over balanced-random + cache-aware hp partitions
            vdev_metrics.update(bench_replica_ab(
                args.replica_ab_n, args.avg_deg, args.f, widths,
                max(2, args.epochs // 2), graph=args.vdev_graph))
        if (args.model == "gcn" and args.halo_staleness == 0
                and not args.skip_controller_ab):
            # the adaptive-controller five-arm A/B (docs/comm_schedule.md):
            # controller vs four static comm settings, exposed wire
            # rows/step the acceptance figure
            vdev_metrics.update(bench_controller_ab(
                args.controller_ab_n, args.avg_deg, args.f, widths,
                max(2, args.epochs // 2), graph=args.vdev_graph))
        if (args.model == "gcn" and args.halo_staleness == 0
                and not args.skip_serve_qps):
            # the serving roofline next to the training one (docs/serving.md)
            vdev_metrics.update(bench_serve_qps(
                args.serve_qps_n, args.avg_deg, args.f, widths,
                graph=args.vdev_graph))
        if (args.model == "gcn" and args.halo_staleness == 0
                and not args.skip_serve_subgraph):
            # full-vs-subgraph serving A/B (docs/serving.md phase 2)
            vdev_metrics.update(bench_serve_subgraph(
                args.serve_subgraph_n, args.avg_deg, args.f, widths,
                graph=args.vdev_graph))
    extra = {}
    if not args.vdev_child:
        extra.update(products_partition_block())
        extra.update(shard_epoch_model_block())
        if not args.skip_memory_footprint:
            # analytic footprint gauges: pure plan math (no child process,
            # no mesh) — runs for the gat flagship too
            extra.update(memory_footprint_block(
                args.vdev_n, args.avg_deg, args.f, widths,
                graph=args.vdev_graph))
    ab_rev = args.ab_baseline
    if ab_rev is None and args.n >= 1_000_000:
        pin = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench_artifacts", "ab_baseline_rev")
        if os.path.exists(pin):
            with open(pin) as fh:
                ab_rev = fh.read().strip()
    if ab_rev and ab_rev != "none" and not args.vdev_child:
        extra.update(bench_ab_baseline(args, ab_rev))
    if single and args.n >= 1_000_000:
        # the measured large-table cliff (BASELINE.md micro table): this
        # single-chip number sits at the DEGRADED gather rate; per-chip
        # sharding shrinks tables k-fold back toward the fast regime
        extra["gather_rate_context"] = (
            "1.2 GB feature table gathers at ~176 Mrows/s vs ~444 Mrows/s "
            "at 83 MB on this chip; k-way sharding moves per-chip tables "
            "back to the fast side (BASELINE.md)")
    _emit_result({
        "metric": f"fullbatch_{args.model}_epoch_time",
        "value": round(epoch_s, 6),
        "unit": "s",
        "graph": args.graph,
        # provenance: a live differential measurement from THIS process
        # (degraded/skipped partials carry a marker instead of the flag) —
        # scripts/validate_bench.py enforces it from round 6 on
        "measured": True,
        "vs_baseline": vs,
        "vs_torch_cpu": vs,
        # ADVICE r3: label the yardstick — vs_baseline is measured against
        # the reference's own compute stack (torch.sparse CPU) on THIS host;
        # the BASELINE.json north star (<=1.2x NCCL/V100 at 8 chips) needs
        # hardware this box does not have and is NOT what this ratio claims.
        "vs_baseline_is": "torch-CPU reference-stack proxy on this host, "
                          "not the V100/NCCL north star (BASELINE.json)",
        "dense_equiv_s": round(dense_s, 6)
            if dense_s and np.isfinite(dense_s) else None,
        "epoch_vs_dense": round(epoch_s / dense_s, 3)
            if dense_s and np.isfinite(dense_s) else None,
        "measurement": flagship_quality,
        **part_metrics,
        **vdev_metrics,
        **extra,
    }, args)


if __name__ == "__main__":
    main()
