"""JAX API compatibility layer.

The codebase is written against the current public jax API (``jax.shard_map``,
``jax.lax.pcast``).  Some execution containers pin an older jaxlib (observed:
0.4.37) where the same functionality lives under ``jax.experimental`` or does
not exist because the subsystem it belongs to (the varying-axes replication
types) postdates the release.  Importing this module installs the missing
names once, guarded so a current jax is untouched:

  * ``jax.shard_map``  ← ``jax.experimental.shard_map.shard_map`` (identical
    call signature for the ``mesh``/``in_specs``/``out_specs`` kwargs every
    call site uses);
  * ``jax.lax.axis_size``  ← ``lax.psum(1, axis)``, which constant-folds to
    the axis size as a Python int (no collective emitted);
  * ``jax.lax.pcast``  ← identity.  ``pcast(x, axis, to='varying')`` only
    adjusts the replication TYPE of ``x`` under the new type system; a jax
    without that system has nothing to adjust, so identity is exact (the
    ``check_rep`` machinery of the experimental shard_map tracks replication
    by value instead).

Imported for its side effect by ``sgcn_tpu/__init__`` so every entry point
(tests, trainers, bench, driver) sees one consistent API.
"""

from __future__ import annotations

import jax
from jax import lax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    jax.shard_map = _shard_map

if not hasattr(lax, "axis_size"):
    def _axis_size(axis_name):
        # psum of the Python literal 1 constant-folds to the axis size (a
        # Python int) in every jax that lacks lax.axis_size — no collective
        # is emitted, so this is a static-shape-safe drop-in
        return lax.psum(1, axis_name)

    lax.axis_size = _axis_size

if not hasattr(lax, "pcast"):
    def _pcast(x, axis_name=None, *, to=None):   # noqa: ARG001 — API shape
        return x

    lax.pcast = _pcast
