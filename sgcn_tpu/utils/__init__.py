from .stats import CommStats
from .timers import PhaseTimer

__all__ = ["CommStats", "PhaseTimer"]
