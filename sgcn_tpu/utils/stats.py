"""Communication statistics — the reference's fixed observability vocabulary.

Both reference stacks count, per rank, ``send/recv_comm_volume`` (feature rows
shipped) and ``send/recv_message_count``, then aggregate SUM and MAX across
ranks into one end-of-run line (``Parallel-GCN/main.c:61-64,506-524``;
``GPU/PGCN.py:78-83,230-238``).

Under the static all_to_all plan the per-exchange volume is known exactly at
plan time (it equals the plan's predicted connectivity volume — the invariant
the reference checks empirically), so counters advance deterministically per
step instead of being tallied inside the hot loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class CommStats:
    k: int
    send_volume_per_exchange: np.ndarray   # (k,) boundary rows per halo exchange
    send_msgs_per_exchange: np.ndarray     # (k,) non-empty peer messages
    recv_volume_per_exchange: np.ndarray   # (k,)
    recv_msgs_per_exchange: np.ndarray     # (k,)
    exchanges: int = 0                     # cumulative halo exchanges performed
    # Subset of ``exchanges`` issued OFF the critical path (the pipelined
    # stale-halo mode: the a2a has no same-step consumer, so its latency is
    # hidden behind local compute).  The volume still crosses the wire —
    # hence one total and a hidden/exposed split, never two totals.
    hidden_exchanges: int = 0
    # Padded-vs-true accounting of the SELECTED exchange schedule
    # (docs/comm_schedule.md): the send/recv volumes above count TRUE
    # boundary rows (Σ(λ−1), what the partitioner minimizes); the schedule
    # ships a statically padded superset — k²·S rows for the dense a2a,
    # Σ_d k·S_d for the ragged ppermute ring.  One true count, one wire
    # count, never a blended number.
    schedule: str = "a2a"
    wire_rows_per_exchange: int = 0        # padded rows on the wire (global
    #                                        over the chips in view)
    padding_efficiency: float = 1.0        # true / wire of the SELECTED
    #                                        schedule
    # Per-layer wire LANE widths (f32-lane equivalents) of one step's
    # exchange sequence — the real table widths the model ships: GCN's
    # project-first ``exchange_widths``, GAT's attention-table lanes (fused
    # fout+1, packed fout/2+1, split pair = fout+1 across its buffers;
    # ``models.gat.gat_exchange_lane_widths``).  With them set, ``report()``
    # carries byte gauges (halo_bytes_true/halo_bytes_wire per step) that
    # must reconcile EXACTLY with the obs roofline's attribution
    # (tests/test_metrics_cli.py, tests/test_gat_ragged.py).  Empty = rows
    # only (pre-PR-5 reports).
    lane_widths: tuple = ()
    wire_itemsize: int = 4                 # bytes per f32-equivalent lane,
    #                                        FORWARD (feature) direction
    # Gradient-direction wire itemsize (None = same as wire_itemsize): the
    # halo-delta cache narrows ONLY the feature wire, so a delta run ships
    # bf16 forward and (by default) f32 backward — one blended number would
    # misstate both directions (docs/observability.md, per-step split).
    wire_itemsize_bwd: int | None = None
    # Cumulative byte gauges with PER-STEP itemsize resolution: a delta
    # run's sync steps re-base on an f32 feature wire while its stale steps
    # ship bf16, so the cumulative bytes are accumulated step by step
    # (count_step's wire_itemsize override) rather than derived per_step ×
    # steps.  Zero until lane_widths is set.
    halo_bytes_true_total: int = 0
    halo_bytes_wire_total: int = 0
    # Hot-halo replication (``--replica-budget``, docs/replication.md):
    # replica steps ship the SHRUNKEN no-replica exchange — fewer true rows
    # (replicated rows leave the volume, not just the pad) AND fewer wire
    # rows — while refresh (sync) steps ship the full exchange.  One
    # full-exchange figure, one replica figure, per-step booking; set by
    # ``set_replica`` (None = no replication, every step full).
    replica_send_volume_per_exchange: np.ndarray | None = None  # (k,)
    replica_recv_volume_per_exchange: np.ndarray | None = None  # (k,)
    replica_send_msgs_per_exchange: np.ndarray | None = None    # (k,)
    replica_recv_msgs_per_exchange: np.ndarray | None = None    # (k,)
    replica_wire_rows_per_exchange: int | None = None
    replica_rows: int = 0                 # plan.replica_rows (gauge only)
    replica_exchanges: int = 0            # exchanges that rode the shrunken
    #                                       wire (subset of ``exchanges``)
    # COMPOSED replica × stale booking: replica-booked exchanges that were
    # ALSO latency-hidden (subset of both ``replica_exchanges`` and
    # ``hidden_exchanges``) — the pure replica mode keeps every shrunken
    # exchange synchronous, the composed mode hides all of them, and the
    # exposed/hidden volume split must price each subset at its own
    # per-exchange figure or the hidden + exposed == total contract breaks.
    hidden_replica_exchanges: int = 0
    # Drift-banded PARTIAL refresh (``--refresh-band``,
    # docs/replication.md): the refresh side channel's cumulative booking,
    # at the ACTUAL per-step shipped rows the program reported (these ride
    # ON TOP of the shrunken base exchange the step is replica-booked at;
    # the per-step face is the step event's ``replica.refresh_rows`` — the
    # two must reconcile exactly).
    partial_refresh_steps: int = 0
    partial_refresh_rows_total: int = 0        # true rows, fwd + bwd
    partial_refresh_wire_rows_total: int = 0   # padded side-channel rows

    @classmethod
    def from_plan(cls, plan, schedule: str = "a2a",
                  lane_widths: tuple = (),
                  wire_itemsize: int = 4,
                  wire_itemsize_bwd: int | None = None) -> "CommStats":
        off = plan.offwire_send_counts()
        send_vol = plan.predicted_send_volume.astype(np.int64)
        send_msg = plan.predicted_message_count.astype(np.int64)
        if off.shape[0] == off.shape[1]:
            recv_vol, recv_msg = off.sum(axis=0), (off > 0).sum(axis=0)
        else:
            # shard-proxy slice (rows != k): peers' sends are not in view.
            # Per-chip recv == send holds ONLY for a symmetric exchange
            # pattern — for anything else the reuse below would FABRICATE
            # recv counters, so fail loudly instead (round-5 advisor
            # finding).
            if not getattr(plan, "symmetric", False):
                raise ValueError(
                    "CommStats.from_plan: shard-proxy slice of an ASYMMETRIC "
                    "plan — peers' sends are out of view and per-chip recv "
                    "!= send, so recv counters cannot be derived; proxy a "
                    "symmetric plan or build stats from the full plan")
            recv_vol, recv_msg = send_vol, send_msg
        wire = int(plan.wire_rows_per_exchange(schedule))
        true = int(send_vol.sum())
        return cls(
            k=plan.k,
            send_volume_per_exchange=send_vol,
            send_msgs_per_exchange=send_msg,
            recv_volume_per_exchange=recv_vol,
            recv_msgs_per_exchange=recv_msg,
            schedule=schedule,
            wire_rows_per_exchange=wire,
            padding_efficiency=(true / wire if wire else 1.0),
            lane_widths=tuple(int(w) for w in lane_widths),
            wire_itemsize=int(wire_itemsize),
            wire_itemsize_bwd=(None if wire_itemsize_bwd is None
                               else int(wire_itemsize_bwd)),
        )

    def set_replica(self, plan) -> None:
        """Record the shrunken no-replica exchange's figures from a plan
        with the replication layout built (``CommPlan.ensure_replicas``) —
        ``count_step(replica=True)`` then books replica steps at these.
        The replica counts are symmetric-exchange figures like the full
        ones (recv = column sums)."""
        if plan.nrep_send_counts is None:
            raise ValueError(
                "CommStats.set_replica needs the plan's replication layout "
                "(ensure_replicas)")
        counts = plan.nrep_send_counts.astype(np.int64)
        self.replica_send_volume_per_exchange = counts.sum(axis=1)
        self.replica_recv_volume_per_exchange = counts.sum(axis=0)
        self.replica_send_msgs_per_exchange = (counts > 0).sum(axis=1)
        self.replica_recv_msgs_per_exchange = (counts > 0).sum(axis=0)
        self.replica_wire_rows_per_exchange = int(
            plan.wire_rows_per_exchange(self.schedule, replica=True))
        self.replica_rows = int(plan.replica_rows)

    def _accumulate_bytes(self, fwd_sweeps: int, bwd_sweeps: int,
                          fwd_itemsize: int | None = None,
                          replica: bool = False) -> None:
        """Advance the cumulative byte gauges by ``fwd_sweeps`` forward +
        ``bwd_sweeps`` backward exchange SWEEPS (one sweep = one exchange
        per layer, at that layer's lane width — ``lane_widths`` already
        sums over layers), at this step's wire itemsizes (``fwd_itemsize``
        overrides the forward default — the delta-mode sync step's f32
        re-base).  ``replica=True`` books the step at the SHRUNKEN
        no-replica volumes (``set_replica``)."""
        if not self.lane_widths:
            return
        fwd = self.wire_itemsize if fwd_itemsize is None else fwd_itemsize
        bwd = (self.wire_itemsize if self.wire_itemsize_bwd is None
               else self.wire_itemsize_bwd)
        lane = sum(self.lane_widths)
        if replica:
            per_true = int(self.replica_send_volume_per_exchange.sum())
            wire = self.replica_wire_rows_per_exchange
        else:
            per_true = int(self.send_volume_per_exchange.sum())
            wire = self.wire_rows_per_exchange
        factor = lane * (fwd * fwd_sweeps + bwd * bwd_sweeps)
        self.halo_bytes_true_total += per_true * factor
        self.halo_bytes_wire_total += wire * factor

    def count_step(self, nlayers: int, hidden: bool = False,
                   wire_itemsize: int | None = None,
                   replica: bool = False) -> None:
        """One training step = nlayers forward + nlayers backward exchanges
        (the backward halo exchange mirrors the forward —
        ``Parallel-GCN/main.c:340-372``).  ``hidden=True`` marks the step's
        exchanges as latency-hidden (stale pipelined mode).
        ``wire_itemsize`` overrides this step's FORWARD wire itemsize in
        the cumulative byte gauges (the delta cache's f32 re-base syncs).
        ``replica=True`` books the step's exchanges at the shrunken
        no-replica volumes (``set_replica`` first) — the replica mode's
        non-refresh steps."""
        if replica and self.replica_send_volume_per_exchange is None:
            raise ValueError(
                "count_step(replica=True) before set_replica()")
        self.exchanges += 2 * nlayers
        if hidden:
            self.hidden_exchanges += 2 * nlayers
        if replica:
            self.replica_exchanges += 2 * nlayers
        if hidden and replica:
            # composed replica × stale: the shrunken exchange is ALSO off
            # the critical path — the split volumes price it accordingly
            self.hidden_replica_exchanges += 2 * nlayers
        self._accumulate_bytes(1, 1, fwd_itemsize=wire_itemsize,
                               replica=replica)

    def count_partial_refresh_step(self, nlayers: int, refresh_rows,
                                   wire_rows: int) -> None:
        """One ``--refresh-band`` PARTIAL refresh step: the shrunken
        replica-step exchange (booked exactly like
        ``count_step(replica=True)``) plus the replica-only side channel —
        one extra a2a per layer per direction shipping ``wire_rows``
        padded rows, of which ``refresh_rows[ℓ]`` (the per-layer count the
        program measured and reported) actually carried a drifted row.
        The gradient side channel ships the same masked rows plus a 0/1
        indicator lane (one extra f32-equivalent lane in the byte gauge).
        """
        refresh_rows = [int(x) for x in refresh_rows]
        if len(refresh_rows) != nlayers:
            raise ValueError(
                f"count_partial_refresh_step: {len(refresh_rows)} per-layer "
                f"row counts for {nlayers} layers")
        self.count_step(nlayers=nlayers, replica=True)
        self.partial_refresh_steps += 1
        self.partial_refresh_rows_total += 2 * sum(refresh_rows)
        self.partial_refresh_wire_rows_total += 2 * nlayers * int(wire_rows)
        if self.lane_widths:
            fwd = self.wire_itemsize
            bwd = (self.wire_itemsize if self.wire_itemsize_bwd is None
                   else self.wire_itemsize_bwd)
            for rows, lane in zip(refresh_rows, self.lane_widths):
                self.halo_bytes_true_total += rows * lane * (fwd + bwd)
                self.halo_bytes_wire_total += int(wire_rows) * (
                    lane * fwd + (lane + 1) * bwd)

    def count_forward(self, nlayers: int) -> None:
        self.exchanges += nlayers
        self._accumulate_bytes(1, 0)

    # ----------------------------------------------------- checkpoint state
    # the CUMULATIVE counters a resume must carry over so the end-of-run
    # comm report of a resumed run reconciles exactly with the
    # uninterrupted one (docs/resilience.md).  Per-exchange figures are NOT
    # here: they are plan-derived and rebuilt by from_plan on every start.
    _CUMULATIVE_ATTRS = (
        "exchanges", "hidden_exchanges", "replica_exchanges",
        "hidden_replica_exchanges", "halo_bytes_true_total",
        "halo_bytes_wire_total", "partial_refresh_steps",
        "partial_refresh_rows_total", "partial_refresh_wire_rows_total")

    def state(self) -> dict:
        """JSON-able snapshot of the cumulative gauges."""
        return {a: int(getattr(self, a)) for a in self._CUMULATIVE_ATTRS}

    def load_state(self, state: dict) -> None:
        """Restore ``state()`` onto a freshly-built counter (``from_plan``
        + ``set_replica`` already re-derived the per-exchange figures)."""
        for a in self._CUMULATIVE_ATTRS:
            if a in state:
                setattr(self, a, int(state[a]))

    def cumulative(self) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Per-rank cumulative (send_vol, send_msgs, recv_vol, recv_msgs).
        Replica-booked exchanges (``count_step(replica=True)``) advance at
        the shrunken per-exchange volumes — replicated rows genuinely left
        the exchange, so the reference's 8-number line must not claim
        them."""
        per = (self.send_volume_per_exchange, self.send_msgs_per_exchange,
               self.recv_volume_per_exchange, self.recv_msgs_per_exchange)
        if not self.replica_exchanges:
            return tuple(p * self.exchanges for p in per)
        rep = (self.replica_send_volume_per_exchange,
               self.replica_send_msgs_per_exchange,
               self.replica_recv_volume_per_exchange,
               self.replica_recv_msgs_per_exchange)
        full = self.exchanges - self.replica_exchanges
        return tuple(p * full + rp * self.replica_exchanges
                     for p, rp in zip(per, rep))

    @staticmethod
    def report_from_cumulative(sv, sm, rv, rm) -> dict:
        # the reference's 8-number line: SUM and MAX over ranks of each counter
        return {
            "total_send_volume": int(sv.sum()),
            "max_send_volume": int(sv.max()) if sv.size else 0,
            "total_send_msgs": int(sm.sum()),
            "max_send_msgs": int(sm.max()) if sm.size else 0,
            "total_recv_volume": int(rv.sum()),
            "max_recv_volume": int(rv.max()) if rv.size else 0,
            "total_recv_msgs": int(rm.sum()),
            "max_recv_msgs": int(rm.max()) if rm.size else 0,
        }

    def report(self) -> dict:
        """The reference's 8-number line plus the exposed/hidden split:
        exchanges whose latency sits ON the step's critical path (exposed —
        every exact-mode exchange) vs exchanges issued with no same-step
        consumer (hidden — the stale pipelined mode's), with the wire volume
        attributed to each.  Total keys keep their reference meaning (all
        bytes cross the wire either way)."""
        rep = self.report_from_cumulative(*self.cumulative())
        exposed = self.exchanges - self.hidden_exchanges
        hidden = self.hidden_exchanges
        per_ex = int(self.send_volume_per_exchange.sum())
        rex = self.replica_exchanges
        hrex = self.hidden_replica_exchanges   # composed replica × stale
        erex = rex - hrex                      # exposed replica-booked
        per_ex_rep = (int(self.replica_send_volume_per_exchange.sum())
                      if rex else per_ex)
        rep_wire = (self.replica_wire_rows_per_exchange
                    if rex else self.wire_rows_per_exchange)
        wire = self.wire_rows_per_exchange
        # the --refresh-band side channel's padded rows ride on (exposed)
        # refresh steps — they join every wire total below
        pwire = self.partial_refresh_wire_rows_total
        rep.update(
            exchanges=self.exchanges,
            exposed_exchanges=exposed,
            hidden_exchanges=hidden,
            # each (exposed/hidden) × (full/replica-booked) subset prices
            # at its own per-exchange volume, so hidden + exposed == total
            # holds in every mode (pure replica: all shrunken exchanges
            # exposed; composed replica × stale: all of them hidden)
            exposed_send_volume=(per_ex * (exposed - erex)
                                 + per_ex_rep * erex),
            hidden_send_volume=(per_ex * (hidden - hrex)
                                + per_ex_rep * hrex),
            # per-schedule padded-vs-true accounting: true rows are what the
            # partitioner optimizes, wire rows what the schedule ships; the
            # obs roofline must agree with these EXACTLY
            # (tests/test_metrics_cli.py)
            comm_schedule=self.schedule,
            true_rows_per_exchange=per_ex,
            wire_rows_per_exchange=wire,
            wire_rows_total=(wire * (self.exchanges - rex)
                             + rep_wire * rex + pwire),
            # the exposed/hidden WIRE-row split — the controller A/B's
            # acceptance figure (exposed wire rows/step, never epoch time)
            exposed_wire_rows_total=(wire * (exposed - erex)
                                     + rep_wire * erex + pwire),
            hidden_wire_rows_total=(wire * (hidden - hrex)
                                    + rep_wire * hrex),
            padding_efficiency=self.padding_efficiency,
        )
        if self.replica_wire_rows_per_exchange is not None:
            # hot-halo replication gauges (docs/replication.md): the
            # shrunken exchange's figures next to the full ones, plus how
            # many exchanges rode it
            rep.update(
                replica_exchanges=rex,
                hidden_replica_exchanges=hrex,
                replica_rows=self.replica_rows,
                true_rows_per_exchange_replica=int(
                    self.replica_send_volume_per_exchange.sum()),
                wire_rows_per_exchange_replica=
                self.replica_wire_rows_per_exchange,
            )
        if self.partial_refresh_steps:
            # partial-refresh booking at the ACTUAL shipped rows — the
            # cumulative face of the step events' replica.refresh_rows
            rep.update(
                partial_refresh_steps=self.partial_refresh_steps,
                partial_refresh_rows_total=self.partial_refresh_rows_total,
                partial_refresh_wire_rows_total=
                self.partial_refresh_wire_rows_total,
            )
        if self.lane_widths:
            # lane-weighted byte gauges: one fwd + one bwd exchange per
            # layer per step, each at that layer's true wire width and its
            # DIRECTION's itemsize — the CommStats side of the attribution
            # reconciliation contract.  The *_per_step keys describe the
            # steady-state (stale/default) step; the *_total keys are
            # cumulative with per-step itemsize resolution (delta-mode sync
            # steps book their f32 re-base wire at 4 bytes).
            bwd = (self.wire_itemsize if self.wire_itemsize_bwd is None
                   else self.wire_itemsize_bwd)
            lane_b = sum(self.lane_widths) * (self.wire_itemsize + bwd)
            rep.update(
                halo_bytes_true_per_step=per_ex * lane_b,
                halo_bytes_wire_per_step=self.wire_rows_per_exchange
                * lane_b,
                halo_bytes_true_total=self.halo_bytes_true_total,
                halo_bytes_wire_total=self.halo_bytes_wire_total,
            )
        return rep

    @staticmethod
    def merged_report(stats_list) -> dict:
        """Aggregate many counters (e.g. one per mini-batch plan) the way one
        rank accumulates across batches in the reference: per-rank sums first,
        SUM/MAX over ranks second (``GPU/PGCN-Mini-batch.py`` shares the
        counter dict across batches; ``Parallel-GCN/main.c:506-524``).

        Carries the hidden/exposed split through the merge (each counter's
        per-exchange volume is its OWN plan's, so the split volumes sum per
        counter, never from the merged totals) — the merged report satisfies
        the same ``hidden + exposed == total`` reconciliation contract as a
        single ``report()`` (``sgcn_tpu.obs.schema.COMM_SPLIT_KEYS``)."""
        parts = [s.cumulative() for s in stats_list]
        sums = [np.sum([p[i] for p in parts], axis=0) for i in range(4)]
        rep = CommStats.report_from_cumulative(*sums)
        exchanges = sum(s.exchanges for s in stats_list)
        hidden = sum(s.hidden_exchanges for s in stats_list)
        schedules = {s.schedule for s in stats_list} or {"a2a"}
        wire_total = sum(
            s.wire_rows_per_exchange * (s.exchanges - s.replica_exchanges)
            + (s.replica_wire_rows_per_exchange or 0) * s.replica_exchanges
            + s.partial_refresh_wire_rows_total
            for s in stats_list)

        def _split_vol(s, hidden_side: bool) -> int:
            # same subset pricing as a single report(): (exposed/hidden) ×
            # (full/replica-booked), each at its own per-exchange volume —
            # the composed replica × stale mode hides shrunken exchanges,
            # so the old "replica implies exposed" shortcut would misprice
            # exactly the mode this split exists to describe
            per = int(s.send_volume_per_exchange.sum())
            per_rep = (int(s.replica_send_volume_per_exchange.sum())
                       if s.replica_exchanges else per)
            hrex = s.hidden_replica_exchanges
            if hidden_side:
                return (per * (s.hidden_exchanges - hrex) + per_rep * hrex)
            erex = s.replica_exchanges - hrex
            exp = s.exchanges - s.hidden_exchanges
            return per * (exp - erex) + per_rep * erex

        rep.update(
            exchanges=exchanges,
            exposed_exchanges=exchanges - hidden,
            hidden_exchanges=hidden,
            exposed_send_volume=sum(_split_vol(s, False)
                                    for s in stats_list),
            hidden_send_volume=sum(_split_vol(s, True)
                                   for s in stats_list),
            # cross-counter wire accounting: each counter's wire rows are
            # its OWN plan's (per-batch envelopes differ), so totals sum per
            # counter; efficiency is the cumulative true/wire ratio
            comm_schedule=(schedules.pop() if len(schedules) == 1
                           else "mixed"),
            wire_rows_total=wire_total,
            padding_efficiency=(rep["total_send_volume"] / wire_total
                                if wire_total else 1.0),
        )
        if any(s.lane_widths for s in stats_list):
            # cumulative byte gauges sum per counter (each counter's lane
            # widths and per-step itemsizes are its own plan's/config's)
            rep.update(
                halo_bytes_true_total=sum(
                    s.halo_bytes_true_total for s in stats_list),
                halo_bytes_wire_total=sum(
                    s.halo_bytes_wire_total for s in stats_list),
            )
        return rep
