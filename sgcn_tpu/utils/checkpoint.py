"""Checkpoint / resume for trainer state, with provenance.

The reference has NO checkpointing (SURVEY.md §5.4): weights are re-randomized
every run and only the offline partition artifacts act as a cache.  For long
TPU runs that is a real gap, so the framework adds a minimal, dependency-free
checkpoint: all pytree leaves of (params, opt_state) plus a step counter in
one ``.npz``, restored into the trainer's existing tree structure (which also
re-applies the mesh sharding via device_put on assignment).

Provenance (PR-8): ``save_checkpoint`` additionally records the comm plan's
digest (``obs.recorder.plan_digest`` — the same 16-hex identity the run
manifest carries) and the model config (model kind, input width, layer dims,
activation/loss, the GAT fused-form mode) when the trainer exposes them.
``load_checkpoint`` and the serve engine (``sgcn_tpu/serve/engine.py``)
verify both and fail with a CLEAR message on mismatch — before provenance, a
wrong-config restore either died deep inside tree-structure shape errors or,
worse, a checkpoint from a DIFFERENT graph/run with coincidentally matching
leaf shapes restored cleanly and served the wrong model.  Weights themselves
are partition-independent (no leaf is vertex-indexed), so a deliberate
same-graph re-partition restore stays possible: ``load_checkpoint(...,
verify=False)``.  The mini-batch trainer suppresses the digest entirely
(its inner plan is a per-batch plan, not a run identity — the
``checkpoint_plan`` sentinel below).  Checkpoints written before this
change carry no provenance and still load (nothing to verify).

Durability + full state (PR-13, ``docs/resilience.md``): checkpoints are
now written ATOMICALLY (temp + fsync + rename — a kill mid-save leaves the
previous checkpoint intact, never a truncated ``.npz``), carry a per-array
CRC32 recorded in the meta block (a bit-flipped or truncated file fails
with a clear ``CheckpointCorruptError``, not a numpy deep-failure), and are
FULL-state: beyond (params, opt_state) they persist the trainer's
algorithmic state — the stale-halo / replica carry leaves, the sync/refresh
step counters, the controller's effective ``sync_every`` + retune log, and
the cumulative CommStats gauges — so a resumed stale/replica run is
f32-bit-identical to the uninterrupted one and its comm totals reconcile
across the seam.  The format is versioned (``CKPT_VERSION``): pre-PR-13
checkpoints (no version key) still load as params-only with a LOUD
"partial state" warning when the trainer carries algorithmic state the file
cannot supply.

Works for any trainer exposing ``params`` / ``opt_state`` / ``mesh``
(FullBatchTrainer, MiniBatchTrainer.inner).
"""

from __future__ import annotations

import json
import warnings
import zlib

import jax
import numpy as np

from ..parallel.mesh import replicate

# non-leaf keys the .npz may carry next to the ``leaf_<i>`` arrays — counting
# leaves as ``len(files) - 1`` broke the moment a second metadata key landed,
# so loaders count ``leaf_`` keys explicitly instead
_META_STEP = "__step__"
_META_DIGEST = "__plan_digest__"
_META_MODEL = "__model_config__"
# full-state format (v2): version stamp, JSON train-state block
# (counters/controller/comm-stats, docs/resilience.md), per-array CRC32 map
_META_VERSION = "__ckpt_version__"
_META_STATE = "__train_state__"
_META_CHECKSUMS = "__checksums__"

# current writer version.  v1 = the pre-PR-13 params-only format (no
# version key); v2 adds carry_<i> arrays + train state + checksums.  A file
# claiming a NEWER version than this reader fails loudly (verify path) —
# silently dropping state a newer writer recorded is exactly the class of
# bug this layer exists to prevent.
CKPT_VERSION = 2


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed structural or checksum validation —
    truncated container, unreadable member, or a per-array CRC mismatch.
    Distinct from ``ValueError`` (provenance/shape mismatches of an INTACT
    file) so the durable loader (``resilience.CheckpointManager``) can fall
    back to the previous checkpoint on corruption while still failing fast
    on a genuinely wrong restore."""


def _crc(arr: np.ndarray) -> int:
    """CRC32 over an array's dtype, shape and raw bytes."""
    arr = np.ascontiguousarray(arr)
    h = zlib.crc32(repr((arr.dtype.str, arr.shape)).encode())
    return zlib.crc32(arr.tobytes(), h) & 0xFFFFFFFF


# container/member failure modes of a damaged .npz: zipfile raises
# BadZipFile (incl. its own CRC check), zlib.error on a bad stream, OSError
# on short reads, ValueError/KeyError on mangled headers
_NPZ_DAMAGE = (OSError, ValueError, KeyError, zlib.error)


def _open_guarded(path: str):
    """``np.load`` with container damage mapped to CheckpointCorruptError."""
    import zipfile

    try:
        return np.load(path)
    except zipfile.BadZipFile as e:
        raise CheckpointCorruptError(
            f"checkpoint {path!r} is not a readable .npz (truncated or "
            f"damaged container: {e}) — likely a kill mid-write of a "
            "non-atomic writer, or on-disk corruption; the durable loader "
            "falls back to the previous intact checkpoint") from e
    except _NPZ_DAMAGE as e:
        raise CheckpointCorruptError(
            f"checkpoint {path!r} failed to open: {e}") from e


def _read_arrays(data, keys, path: str, checksums: dict | None) -> dict:
    """Read + checksum-verify the named members of an open npz."""
    import zipfile

    out = {}
    for key in keys:
        try:
            arr = data[key]
        except (zipfile.BadZipFile, *_NPZ_DAMAGE) as e:
            raise CheckpointCorruptError(
                f"checkpoint {path!r}: member {key!r} is unreadable "
                f"({e}) — corrupt checkpoint; the durable loader falls "
                "back to the previous intact one") from e
        if checksums is not None and key in checksums:
            have = _crc(arr)
            if have != int(checksums[key]):
                raise CheckpointCorruptError(
                    f"checkpoint {path!r}: checksum mismatch on {key!r} "
                    f"(recorded {int(checksums[key])}, computed {have}) — "
                    "corrupt checkpoint; the durable loader falls back to "
                    "the previous intact one")
        out[key] = arr
    return out


def _norm(path: str) -> str:
    # np.savez appends .npz itself; normalize so save/load accept the same path
    return path if path.endswith(".npz") else path + ".npz"


def model_config_of(trainer) -> dict | None:
    """The checkpoint's model-identity block, read off a trainer's attrs
    (best-effort: a trainer that predates an attribute simply omits it).
    ``gat_fused`` records the table-form lever (``$SGCN_GAT_FUSED``) the
    params were trained under — the fused/split/packed forms share one param
    tree, so it is provenance, not a load-blocking field."""
    cfg = {}
    for attr, key in (("model", "model"), ("fin", "fin"),
                      ("widths", "widths"), ("activation", "activation"),
                      ("final_activation", "final_activation"),
                      ("loss_name", "loss")):
        v = getattr(trainer, attr, None)
        if v is not None:
            cfg[key] = list(v) if key == "widths" else v
    if cfg.get("model") == "gat":
        import os
        cfg["gat_fused"] = os.environ.get("SGCN_GAT_FUSED", "1")
    return cfg or None


def save_checkpoint(trainer, path: str, step: int = 0) -> str:
    """Write one atomic full-state checkpoint (module docstring): the
    (params, opt_state) leaves, the trainer's resume state (carry leaves +
    counters + controller + comm gauges, ``resume_state()``) when it
    exposes one, provenance, the format version, and a per-array CRC map —
    committed via temp + fsync + rename so a kill at ANY byte leaves
    either the previous checkpoint or the complete new one."""
    leaves = jax.tree.leaves((trainer.params, trainer.opt_state))
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrays[_META_STEP] = np.asarray(step, dtype=np.int64)
    # ``checkpoint_plan`` (may be explicitly None) overrides ``plan``: the
    # mini-batch trainer checkpoints through its inner trainer, whose plan
    # is a padded per-BATCH plan — its digest varies with batch_size/
    # nbatches/pad envelope, so it is not a stable run identity and
    # recording it would make every cross-batch-shape resume a digest error
    plan = getattr(trainer, "checkpoint_plan", getattr(trainer, "plan", None))
    if plan is not None:
        from ..obs.recorder import plan_digest
        arrays[_META_DIGEST] = np.asarray(plan_digest(plan))
    cfg = model_config_of(trainer)
    if cfg is not None:
        arrays[_META_MODEL] = np.asarray(json.dumps(cfg))
    if hasattr(trainer, "resume_state"):
        state, carry_leaves = trainer.resume_state()
        for i, arr in enumerate(carry_leaves):
            arrays[f"carry_{i}"] = arr
        arrays[_META_STATE] = np.asarray(json.dumps(state))
    arrays[_META_VERSION] = np.asarray(CKPT_VERSION, dtype=np.int64)
    # checksum EVERY array, meta blocks included — a bit flip in __step__
    # or a still-parseable __train_state__ digit would otherwise pass
    # "intact" verification and silently resume at the wrong step.  The
    # checksum map itself is the one uncovered array: any mangling of it
    # either fails to parse (CheckpointCorruptError) or miscompares some
    # covered array (ditto) — both fail safe toward the fallback path.
    arrays[_META_CHECKSUMS] = np.asarray(json.dumps(
        {key: _crc(np.asarray(arr)) for key, arr in arrays.items()}))
    path = _norm(path)
    from ..resilience.atomic import atomic_write
    with atomic_write(path, "wb") as fh:
        np.savez(fh, **arrays)
    return path


def read_checkpoint_meta(path: str) -> dict:
    """Provenance block of a checkpoint file: ``{step, plan_digest,
    model_config, n_leaves, version, state, checksums, n_carry}`` —
    digest/config/state ``None`` for files that predate them, ``version``
    1 for pre-PR-13 params-only files.  Cheap (``np.load`` is lazy; only
    metadata arrays read).  A damaged container raises
    ``CheckpointCorruptError`` with a clear message."""
    with _open_guarded(_norm(path)) as data:
        meta = _read_meta_open(data, path)
    return meta


def _read_meta_open(data, path: str) -> dict:
    import zipfile

    try:
        checksums = (json.loads(str(data[_META_CHECKSUMS].item()))
                     if _META_CHECKSUMS in data.files else None)
        if checksums is not None:
            # verify the META arrays up front (leaves/carries are checked
            # by _read_arrays at their own read): corruption in the step
            # counter or the train-state JSON must fail as loudly as a
            # damaged leaf
            for key in (_META_STEP, _META_DIGEST, _META_MODEL,
                        _META_VERSION, _META_STATE):
                if key in data.files and key in checksums:
                    have = _crc(np.asarray(data[key]))
                    if have != int(checksums[key]):
                        raise CheckpointCorruptError(
                            f"checkpoint {path!r}: checksum mismatch on "
                            f"metadata {key!r} (recorded "
                            f"{int(checksums[key])}, computed {have}) — "
                            "corrupt checkpoint; the durable loader falls "
                            "back to the previous intact one")
        return {
            "step": int(data[_META_STEP]) if _META_STEP in data.files else 0,
            "plan_digest": (str(data[_META_DIGEST].item())
                            if _META_DIGEST in data.files else None),
            "model_config": (json.loads(str(data[_META_MODEL].item()))
                             if _META_MODEL in data.files else None),
            "version": (int(data[_META_VERSION])
                        if _META_VERSION in data.files else 1),
            "state": (json.loads(str(data[_META_STATE].item()))
                      if _META_STATE in data.files else None),
            "checksums": checksums,
            "n_leaves": sum(1 for f in data.files if f.startswith("leaf_")),
            "n_carry": sum(1 for f in data.files if f.startswith("carry_")),
        }
    except (zipfile.BadZipFile, *_NPZ_DAMAGE) as e:
        # json.JSONDecodeError is a ValueError, so a mangled metadata JSON
        # lands here too — every flavor of damage is one exception class
        raise CheckpointCorruptError(
            f"checkpoint {path!r}: metadata block unreadable ({e}) — "
            "corrupt checkpoint") from e


def verify_checkpoint_provenance(meta: dict, plan=None,
                                 model: str | None = None,
                                 fin: int | None = None,
                                 widths=None,
                                 activation: str | None = None,
                                 final_activation: str | None = None,
                                 what: str = "checkpoint") -> None:
    """Raise ``ValueError`` with a CLEAR message when the checkpoint's
    recorded provenance contradicts the given plan / model config.  Fields
    the checkpoint does not record are skipped (pre-provenance files load)."""
    if plan is not None and meta.get("plan_digest") is not None:
        from ..obs.recorder import plan_digest
        have = plan_digest(plan)
        if have != meta["plan_digest"]:
            raise ValueError(
                f"{what}: plan digest mismatch — checkpoint was saved under "
                f"plan {meta['plan_digest']}, this run's plan is {have}: a "
                "different graph, partvec, k or comm layout.  Model weights "
                "are partition-independent, so a same-graph re-partition can "
                "be restored deliberately (load_checkpoint(..., "
                "verify=False)); a different GRAPH cannot — check "
                "read_checkpoint_meta before overriding.")
    cfg = meta.get("model_config") or {}
    # activation is part of the served function, not just bookkeeping: the
    # same param tree under a different activation restores cleanly and
    # computes different logits — exactly the silent-wrong-model class this
    # layer exists to catch
    for key, want in (("model", model), ("fin", fin),
                      ("widths", list(widths) if widths is not None
                       else None),
                      ("activation", activation),
                      ("final_activation", final_activation)):
        if want is not None and cfg.get(key) is not None and cfg[key] != want:
            raise ValueError(
                f"{what}: model config mismatch on {key!r} — checkpoint "
                f"records {cfg[key]!r}, this run asks for {want!r}; "
                "reconstruct the trainer/engine with the checkpoint's "
                "config (read_checkpoint_meta shows it).")


def load_checkpoint_leaves(path: str) -> tuple[list, dict]:
    """``(leaves, meta)`` — every ``leaf_<i>`` array in index order plus the
    provenance block, checksum-verified when the file records checksums
    (corruption raises ``CheckpointCorruptError`` with a clear message,
    never a numpy deep-failure).  The serve engine restores params-only
    trees from this (the leading leaves of the ``(params, opt_state)``
    flattening) — carry arrays are NOT read here, so serving a full-state
    checkpoint pays for the params only."""
    path = _norm(path)
    with _open_guarded(path) as data:
        meta = _read_meta_open(data, path)
        _check_version(meta, path)
        arrays = _read_arrays(
            data, [f"leaf_{i}" for i in range(meta["n_leaves"])],
            path, meta["checksums"])
    return [arrays[f"leaf_{i}"] for i in range(meta["n_leaves"])], meta


def _check_version(meta: dict, path: str) -> None:
    if meta["version"] > CKPT_VERSION:
        raise ValueError(
            f"checkpoint {path!r} is format v{meta['version']}, this "
            f"reader understands up to v{CKPT_VERSION} — written by a "
            "newer sgcn_tpu; silently dropping state a newer writer "
            "recorded is not an option, upgrade the reader")


def verify_checkpoint_file(path: str) -> dict:
    """Full structural + checksum verification of EVERY data array (leaves
    and carries); returns the meta block.  Raises
    ``CheckpointCorruptError`` on any damage.  A standalone integrity
    probe — no trainer needed — for operators auditing a checkpoint
    directory; the resume path itself does NOT call this
    (``CheckpointManager.load_latest`` verifies through
    ``load_checkpoint``, which checks everything before its first
    assignment, in one read pass)."""
    path = _norm(path)
    with _open_guarded(path) as data:
        meta = _read_meta_open(data, path)
        _check_version(meta, path)
        keys = ([f"leaf_{i}" for i in range(meta["n_leaves"])]
                + [f"carry_{i}" for i in range(meta["n_carry"])])
        _read_arrays(data, keys, path, meta["checksums"])
    return meta


def _trainer_is_stateful(trainer) -> bool:
    """Does this trainer hold algorithmic state beyond (params, opt_state)
    — carries or a live controller — that a params-only restore would
    silently reinitialize?"""
    return (getattr(trainer, "halo_carry", None) is not None
            or getattr(trainer, "replica_carry", None) is not None
            or getattr(trainer, "controller", None) is not None)


def load_checkpoint(trainer, path: str, verify: bool = True) -> int:
    """Restore the FULL trainer state in place; returns the saved step
    counter.

    The trainer must have been constructed with the same model config — the
    recorded provenance (plan digest, model kind, dims) is verified FIRST
    with a clear message, then the leaf count and shapes are validated
    against its current trees.  ``verify=False`` skips the provenance check
    (weights are partition-independent, so a deliberate same-graph
    re-partition restore is legitimate); the shape validation always runs.

    Full-state restore (format v2, ``docs/resilience.md``): the stale/
    replica carry leaves, step counters, effective ``sync_every`` +
    controller log and cumulative CommStats gauges are restored through
    ``trainer.restore_resume_state`` — a resumed run is then f32-bit-
    identical to the uninterrupted one.  A PRE-full-state checkpoint (or a
    mode mismatch between the file's carry and the trainer's) loads
    params-only with a LOUD ``RuntimeWarning`` naming exactly which state
    was not restored — never silently."""
    # ONE container open for everything this restore may need: meta,
    # leaves, and the carry arrays when the file has them (re-opening the
    # zip for the carries would double resume I/O on the shared
    # filesystems multi-host runs live on)
    path_n = _norm(path)
    with _open_guarded(path_n) as data:
        meta = _read_meta_open(data, path_n)
        _check_version(meta, path_n)
        keys = ([f"leaf_{i}" for i in range(meta["n_leaves"])]
                + [f"carry_{i}" for i in range(meta["n_carry"])])
        arrays = _read_arrays(data, keys, path_n, meta["checksums"])
    leaves = [arrays[f"leaf_{i}"] for i in range(meta["n_leaves"])]
    file_carry = [arrays[f"carry_{i}"] for i in range(meta["n_carry"])]
    if verify:
        verify_checkpoint_provenance(
            meta, plan=getattr(trainer, "plan", None),
            model=getattr(trainer, "model", None),
            fin=getattr(trainer, "fin", None),
            widths=getattr(trainer, "widths", None),
            activation=getattr(trainer, "activation", None),
            final_activation=getattr(trainer, "final_activation", None),
            what=f"load_checkpoint({path!r})")
    cur = jax.tree.leaves((trainer.params, trainer.opt_state))
    if len(cur) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves, trainer expects {len(cur)}")
    for have, want in zip(leaves, cur):
        want = np.asarray(want)
        if tuple(have.shape) != want.shape:
            raise ValueError(
                f"checkpoint leaf shape {have.shape} != trainer {want.shape}")
        if have.dtype != want.dtype:
            raise ValueError(
                f"checkpoint leaf dtype {have.dtype} != trainer {want.dtype}")
    # ---- full-state validation BEFORE any assignment (a failed load must
    # leave the trainer untouched, not half-restored)
    state, carry_leaves = meta.get("state"), []
    restore_state = state is not None and hasattr(trainer,
                                                  "restore_resume_state")
    if restore_state:
        want_carry = (trainer._carry_attr()
                      if hasattr(trainer, "_carry_attr") else None)
        have_carry = state.get("carry")
        # a carry-MODE mismatch (either direction) downgrades the whole
        # restore to params-only: importing the other mode's step
        # counters, effective sync_every and cumulative comm gauges would
        # publish hidden/replica accounting this trainer's mode never
        # produced (and a foreign sync_every silently reshapes the sync
        # schedule) — all-or-nothing keeps the report internally
        # consistent
        if have_carry is not None and want_carry != have_carry:
            restore_state = False
            warnings.warn(
                f"load_checkpoint({path!r}): checkpoint carries "
                f"{have_carry!r} state but this trainer runs "
                f"{want_carry or 'exact'} mode — full state IGNORED "
                "(params-only restore: carries, step counters, sync "
                "schedule and comm gauges are NOT imported); rebuild the "
                "trainer with the checkpoint's mode flags for a bit-"
                "identical resume", RuntimeWarning, stacklevel=2)
        elif want_carry is not None and have_carry is None:
            restore_state = False
            warnings.warn(
                f"load_checkpoint({path!r}): PARTIAL STATE — this trainer "
                f"carries {want_carry!r} state the checkpoint (saved by "
                "a carry-free mode) does not record; params-only restore "
                "(the carry re-initializes at the next sync step, the "
                "counters and comm gauges restart), so the resumed "
                "trajectory is NOT bit-identical to the uninterrupted "
                "run", RuntimeWarning, stacklevel=2)
        elif have_carry is not None:
            carry_leaves = file_carry
            live = [np.asarray(x) for x in
                    jax.tree.leaves(getattr(trainer, have_carry))]
            if len(carry_leaves) != len(live):
                raise ValueError(
                    f"checkpoint has {len(carry_leaves)} carry leaves, "
                    f"trainer expects {len(live)} — different sync "
                    "schedule/transport flags than the saving run")
            for have, want in zip(carry_leaves, live):
                if tuple(have.shape) != tuple(want.shape):
                    raise ValueError(
                        f"checkpoint carry leaf shape {have.shape} != "
                        f"trainer {want.shape} — different mode/transport "
                        "flags than the saving run")
    elif _trainer_is_stateful(trainer):
        # pre-full-state file (v1) into a stateful trainer: the loud
        # partial-state contract (module docstring)
        warnings.warn(
            f"load_checkpoint({path!r}): PARTIAL STATE — checkpoint "
            f"format v{meta['version']} records params/opt_state only; "
            "this trainer's carry/controller/step-counter state is NOT "
            "restored (carries re-initialize at the next sync step, the "
            "comm gauges restart at zero).  Re-save with this version for "
            "full-state resume", RuntimeWarning, stacklevel=2)
    treedef = jax.tree.structure((trainer.params, trainer.opt_state))
    params, opt_state = jax.tree.unflatten(treedef, leaves)
    trainer.params = replicate(trainer.mesh, params)
    trainer.opt_state = replicate(trainer.mesh, opt_state)
    if restore_state:
        trainer.restore_resume_state(state, carry_leaves)
    # expose the restore OUTCOME so callers (the CLI's resume event, run
    # reports) can say whether this was a certified full-state resume or a
    # params-only downgrade — the RuntimeWarnings above are for humans,
    # this flag is for the telemetry stream (obs `resume.partial_state`)
    trainer.last_restore_partial = (not restore_state
                                    and _trainer_is_stateful(trainer))
    return meta["step"]
