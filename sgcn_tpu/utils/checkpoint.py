"""Checkpoint / resume for trainer state, with provenance.

The reference has NO checkpointing (SURVEY.md §5.4): weights are re-randomized
every run and only the offline partition artifacts act as a cache.  For long
TPU runs that is a real gap, so the framework adds a minimal, dependency-free
checkpoint: all pytree leaves of (params, opt_state) plus a step counter in
one ``.npz``, restored into the trainer's existing tree structure (which also
re-applies the mesh sharding via device_put on assignment).

Provenance (PR-8): ``save_checkpoint`` additionally records the comm plan's
digest (``obs.recorder.plan_digest`` — the same 16-hex identity the run
manifest carries) and the model config (model kind, input width, layer dims,
activation/loss, the GAT fused-form mode) when the trainer exposes them.
``load_checkpoint`` and the serve engine (``sgcn_tpu/serve/engine.py``)
verify both and fail with a CLEAR message on mismatch — before provenance, a
wrong-config restore either died deep inside tree-structure shape errors or,
worse, a checkpoint from a DIFFERENT graph/run with coincidentally matching
leaf shapes restored cleanly and served the wrong model.  Weights themselves
are partition-independent (no leaf is vertex-indexed), so a deliberate
same-graph re-partition restore stays possible: ``load_checkpoint(...,
verify=False)``.  The mini-batch trainer suppresses the digest entirely
(its inner plan is a per-batch plan, not a run identity — the
``checkpoint_plan`` sentinel below).  Checkpoints written before this
change carry no provenance and still load (nothing to verify).

Works for any trainer exposing ``params`` / ``opt_state`` / ``mesh``
(FullBatchTrainer, MiniBatchTrainer.inner).
"""

from __future__ import annotations

import json

import jax
import numpy as np

from ..parallel.mesh import replicate

# non-leaf keys the .npz may carry next to the ``leaf_<i>`` arrays — counting
# leaves as ``len(files) - 1`` broke the moment a second metadata key landed,
# so loaders count ``leaf_`` keys explicitly instead
_META_STEP = "__step__"
_META_DIGEST = "__plan_digest__"
_META_MODEL = "__model_config__"


def _norm(path: str) -> str:
    # np.savez appends .npz itself; normalize so save/load accept the same path
    return path if path.endswith(".npz") else path + ".npz"


def model_config_of(trainer) -> dict | None:
    """The checkpoint's model-identity block, read off a trainer's attrs
    (best-effort: a trainer that predates an attribute simply omits it).
    ``gat_fused`` records the table-form lever (``$SGCN_GAT_FUSED``) the
    params were trained under — the fused/split/packed forms share one param
    tree, so it is provenance, not a load-blocking field."""
    cfg = {}
    for attr, key in (("model", "model"), ("fin", "fin"),
                      ("widths", "widths"), ("activation", "activation"),
                      ("final_activation", "final_activation"),
                      ("loss_name", "loss")):
        v = getattr(trainer, attr, None)
        if v is not None:
            cfg[key] = list(v) if key == "widths" else v
    if cfg.get("model") == "gat":
        import os
        cfg["gat_fused"] = os.environ.get("SGCN_GAT_FUSED", "1")
    return cfg or None


def save_checkpoint(trainer, path: str, step: int = 0) -> str:
    leaves = jax.tree.leaves((trainer.params, trainer.opt_state))
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrays[_META_STEP] = np.asarray(step, dtype=np.int64)
    # ``checkpoint_plan`` (may be explicitly None) overrides ``plan``: the
    # mini-batch trainer checkpoints through its inner trainer, whose plan
    # is a padded per-BATCH plan — its digest varies with batch_size/
    # nbatches/pad envelope, so it is not a stable run identity and
    # recording it would make every cross-batch-shape resume a digest error
    plan = getattr(trainer, "checkpoint_plan", getattr(trainer, "plan", None))
    if plan is not None:
        from ..obs.recorder import plan_digest
        arrays[_META_DIGEST] = np.asarray(plan_digest(plan))
    cfg = model_config_of(trainer)
    if cfg is not None:
        arrays[_META_MODEL] = np.asarray(json.dumps(cfg))
    path = _norm(path)
    np.savez(path, **arrays)
    return path


def read_checkpoint_meta(path: str) -> dict:
    """Provenance block of a checkpoint file: ``{step, plan_digest,
    model_config, n_leaves}`` — digest/config ``None`` for pre-provenance
    checkpoints.  Cheap (``np.load`` is lazy; only metadata arrays read)."""
    with np.load(_norm(path)) as data:
        return {
            "step": int(data[_META_STEP]) if _META_STEP in data.files else 0,
            "plan_digest": (str(data[_META_DIGEST].item())
                            if _META_DIGEST in data.files else None),
            "model_config": (json.loads(str(data[_META_MODEL].item()))
                             if _META_MODEL in data.files else None),
            "n_leaves": sum(1 for f in data.files if f.startswith("leaf_")),
        }


def verify_checkpoint_provenance(meta: dict, plan=None,
                                 model: str | None = None,
                                 fin: int | None = None,
                                 widths=None,
                                 activation: str | None = None,
                                 final_activation: str | None = None,
                                 what: str = "checkpoint") -> None:
    """Raise ``ValueError`` with a CLEAR message when the checkpoint's
    recorded provenance contradicts the given plan / model config.  Fields
    the checkpoint does not record are skipped (pre-provenance files load)."""
    if plan is not None and meta.get("plan_digest") is not None:
        from ..obs.recorder import plan_digest
        have = plan_digest(plan)
        if have != meta["plan_digest"]:
            raise ValueError(
                f"{what}: plan digest mismatch — checkpoint was saved under "
                f"plan {meta['plan_digest']}, this run's plan is {have}: a "
                "different graph, partvec, k or comm layout.  Model weights "
                "are partition-independent, so a same-graph re-partition can "
                "be restored deliberately (load_checkpoint(..., "
                "verify=False)); a different GRAPH cannot — check "
                "read_checkpoint_meta before overriding.")
    cfg = meta.get("model_config") or {}
    # activation is part of the served function, not just bookkeeping: the
    # same param tree under a different activation restores cleanly and
    # computes different logits — exactly the silent-wrong-model class this
    # layer exists to catch
    for key, want in (("model", model), ("fin", fin),
                      ("widths", list(widths) if widths is not None
                       else None),
                      ("activation", activation),
                      ("final_activation", final_activation)):
        if want is not None and cfg.get(key) is not None and cfg[key] != want:
            raise ValueError(
                f"{what}: model config mismatch on {key!r} — checkpoint "
                f"records {cfg[key]!r}, this run asks for {want!r}; "
                "reconstruct the trainer/engine with the checkpoint's "
                "config (read_checkpoint_meta shows it).")


def load_checkpoint_leaves(path: str) -> tuple[list, dict]:
    """``(leaves, meta)`` — every ``leaf_<i>`` array in index order plus the
    provenance block.  The serve engine restores params-only trees from
    this (the leading leaves of the ``(params, opt_state)`` flattening)."""
    meta = read_checkpoint_meta(path)
    with np.load(_norm(path)) as data:
        leaves = [data[f"leaf_{i}"] for i in range(meta["n_leaves"])]
    return leaves, meta


def load_checkpoint(trainer, path: str, verify: bool = True) -> int:
    """Restore params/opt_state in place; returns the saved step counter.

    The trainer must have been constructed with the same model config — the
    recorded provenance (plan digest, model kind, dims) is verified FIRST
    with a clear message, then the leaf count and shapes are validated
    against its current trees.  ``verify=False`` skips the provenance check
    (weights are partition-independent, so a deliberate same-graph
    re-partition restore is legitimate); the shape validation always runs.
    """
    leaves, meta = load_checkpoint_leaves(path)
    if verify:
        verify_checkpoint_provenance(
            meta, plan=getattr(trainer, "plan", None),
            model=getattr(trainer, "model", None),
            fin=getattr(trainer, "fin", None),
            widths=getattr(trainer, "widths", None),
            activation=getattr(trainer, "activation", None),
            final_activation=getattr(trainer, "final_activation", None),
            what=f"load_checkpoint({path!r})")
    cur = jax.tree.leaves((trainer.params, trainer.opt_state))
    if len(cur) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves, trainer expects {len(cur)}")
    for have, want in zip(leaves, cur):
        want = np.asarray(want)
        if tuple(have.shape) != want.shape:
            raise ValueError(
                f"checkpoint leaf shape {have.shape} != trainer {want.shape}")
        if have.dtype != want.dtype:
            raise ValueError(
                f"checkpoint leaf dtype {have.dtype} != trainer {want.dtype}")
    treedef = jax.tree.structure((trainer.params, trainer.opt_state))
    params, opt_state = jax.tree.unflatten(treedef, leaves)
    trainer.params = replicate(trainer.mesh, params)
    trainer.opt_state = replicate(trainer.mesh, opt_state)
    return meta["step"]
