"""Checkpoint / resume for trainer state.

The reference has NO checkpointing (SURVEY.md §5.4): weights are re-randomized
every run and only the offline partition artifacts act as a cache.  For long
TPU runs that is a real gap, so the framework adds a minimal, dependency-free
checkpoint: all pytree leaves of (params, opt_state) plus a step counter in
one ``.npz``, restored into the trainer's existing tree structure (which also
re-applies the mesh sharding via device_put on assignment).

Works for any trainer exposing ``params`` / ``opt_state`` / ``mesh``
(FullBatchTrainer, MiniBatchTrainer.inner).
"""

from __future__ import annotations

import jax
import numpy as np

from ..parallel.mesh import replicate


def _norm(path: str) -> str:
    # np.savez appends .npz itself; normalize so save/load accept the same path
    return path if path.endswith(".npz") else path + ".npz"


def save_checkpoint(trainer, path: str, step: int = 0) -> str:
    leaves = jax.tree.leaves((trainer.params, trainer.opt_state))
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrays["__step__"] = np.asarray(step, dtype=np.int64)
    path = _norm(path)
    np.savez(path, **arrays)
    return path


def load_checkpoint(trainer, path: str) -> int:
    """Restore params/opt_state in place; returns the saved step counter.

    The trainer must have been constructed with the same model config — the
    leaf count and shapes are validated against its current trees.
    """
    with np.load(_norm(path)) as data:
        step = int(data["__step__"])
        leaves = [data[f"leaf_{i}"]
                  for i in range(len(data.files) - 1)]
    cur = jax.tree.leaves((trainer.params, trainer.opt_state))
    if len(cur) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves, trainer expects {len(cur)}")
    for have, want in zip(leaves, cur):
        want = np.asarray(want)
        if tuple(have.shape) != want.shape:
            raise ValueError(
                f"checkpoint leaf shape {have.shape} != trainer {want.shape}")
        if have.dtype != want.dtype:
            raise ValueError(
                f"checkpoint leaf dtype {have.dtype} != trainer {want.dtype}")
    treedef = jax.tree.structure((trainer.params, trainer.opt_state))
    params, opt_state = jax.tree.unflatten(treedef, leaves)
    trainer.params = replicate(trainer.mesh, params)
    trainer.opt_state = replicate(trainer.mesh, opt_state)
    return step
