"""Phase timers — the CAGNET baseline's phase-time breakdown, generalized.

The reference accumulates ``data_comm / local_spmm / all_reduce / local_update``
wall-clock per phase (``Cagnet/main.c:35-38,148-151,171-175,395-413``).  Under
jit whole steps fuse into one program, so phase timing is host-side around
block_until_ready boundaries; for intra-step attribution use
``jax.profiler.trace`` (exposed via ``trace()``) and the trace parser in
``sgcn_tpu.obs.tracing``.

Nesting contract: phases may nest (the span API in ``obs/tracing.py`` wraps
this timer, and a step-level span runs inside ``fit()``'s epoch phase).
``totals`` holds SELF time — a child phase's time is attributed to the child
only, so Σ totals over all names equals elapsed wall and nothing is counted
twice.  ``inclusive`` holds wall time per name with a reentrancy guard (a
phase re-entered under itself adds nothing — the outermost frame already
covers it), which is what callers timing a whole region want
(``FullBatchTrainer.fit``).  The pre-nesting behavior — every frame adds its
full duration to ``totals`` — double-counted any nested or reentrant entry.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax


class PhaseTimer:
    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)   # SELF time
        self.counts: dict[str, int] = defaultdict(int)
        self.inclusive: dict[str, float] = defaultdict(float)  # wall time,
        #   reentrancy-guarded (outermost frame of a name counts once)
        self._stack: list[list] = []     # [name, accumulated child seconds]

    @contextlib.contextmanager
    def phase(self, name: str, sync=None):
        """Time a phase. ``sync`` is a zero-arg callable returning the arrays to
        block on (evaluated after the body, so it sees post-body values —
        passing a value directly would capture stale pre-body buffers)."""
        frame = [name, 0.0]
        self._stack.append(frame)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            # the pop/accounting must survive a raising sync (async dispatch
            # errors surface exactly at block_until_ready): a dead frame
            # left on the stack would poison every later phase's totals
            try:
                if sync is not None:
                    jax.block_until_ready(sync())
            finally:
                dt = time.perf_counter() - t0
                self._stack.pop()
                # self time: children already claimed frame[1] of this window
                self.totals[name] += dt - frame[1]
                self.counts[name] += 1
                if all(f[0] != name for f in self._stack):
                    self.inclusive[name] += dt
                if self._stack:
                    self._stack[-1][1] += dt

    def inclusive_total(self, name: str) -> float:
        """Wall time spent under ``name`` (reentrancy-guarded) — equals
        ``totals[name]`` when the phase never had children."""
        return self.inclusive[name]

    def report(self) -> dict:
        return {
            name: {"total_s": self.totals[name], "count": self.counts[name],
                   "avg_s": self.totals[name] / max(self.counts[name], 1),
                   "inclusive_s": self.inclusive[name]}
            for name in self.totals
        }

    @staticmethod
    @contextlib.contextmanager
    def trace(logdir: str):
        """Full XLA profiler trace (TensorBoard-viewable)."""
        with jax.profiler.trace(logdir):
            yield
