"""Phase timers — the CAGNET baseline's phase-time breakdown, generalized.

The reference accumulates ``data_comm / local_spmm / all_reduce / local_update``
wall-clock per phase (``Cagnet/main.c:35-38,148-151,171-175,395-413``).  Under
jit whole steps fuse into one program, so phase timing is host-side around
block_until_ready boundaries; for intra-step attribution use
``jax.profiler.trace`` (exposed via ``trace()``).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax


class PhaseTimer:
    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str, sync=None):
        """Time a phase. ``sync`` is a zero-arg callable returning the arrays to
        block on (evaluated after the body, so it sees post-body values —
        passing a value directly would capture stale pre-body buffers)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                jax.block_until_ready(sync())
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> dict:
        return {
            name: {"total_s": self.totals[name], "count": self.counts[name],
                   "avg_s": self.totals[name] / max(self.counts[name], 1)}
            for name in self.totals
        }

    @staticmethod
    @contextlib.contextmanager
    def trace(logdir: str):
        """Full XLA profiler trace (TensorBoard-viewable)."""
        with jax.profiler.trace(logdir):
            yield
