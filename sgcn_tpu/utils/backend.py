"""CLI backend selection shared by the trainer and baseline CLIs.

``-b cpu`` is the reference's Gloo "cluster on one box" mode
(``GPU/PGCN.py:166-169``): k virtual host CPU devices standing in for k
chips.  The XLA flag must be in the environment before XLA initializes its
backend — package imports may already have imported ``jax`` (module import
is fine; backend init is lazy), so the platform choice itself goes through
``jax.config.update``, which works post-import.
"""

from __future__ import annotations

import os


def use_cpu_devices(nparts: int) -> None:
    """Force ``nparts`` virtual host CPU devices for this process."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={nparts}"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
