"""CLI backend selection shared by the trainer and baseline CLIs.

``-b cpu`` is the reference's Gloo "cluster on one box" mode
(``GPU/PGCN.py:166-169``): k virtual host CPU devices standing in for k
chips.  The XLA flag must be in the environment before XLA initializes its
backend — package imports may already have imported ``jax`` (module import
is fine; backend init is lazy), so the platform choice itself goes through
``jax.config.update``, which works post-import.
"""

from __future__ import annotations

import os


# The ONE classification of "the accelerator backend is unavailable" shared
# by every driver-facing degradation path (bench.py, __graft_entry__.py):
# matching text means "skip with a marker, rc 0"; anything else is a genuine
# code failure that must keep propagating.  Keep the markers NARROW — a
# broad substring (an earlier draft matched bare "initialization") turns
# real bugs into green skipped runs.
BACKEND_UNAVAILABLE_MARKERS = (
    "unable to initialize backend", "failed to initialize", "no devices",
    "backend unavailable", "deadline_exceeded", "unavailable:",
    "failed precondition", "failed_precondition", "tpu platform",
)


def looks_backend_unavailable(text: str) -> bool:
    """True when ``text`` (an exception string or a child's stderr) reads as
    an accelerator-backend bring-up failure rather than a code bug."""
    text = (text or "").lower()
    return any(m in text for m in BACKEND_UNAVAILABLE_MARKERS)


def use_cpu_devices(nparts: int) -> None:
    """Force ``nparts`` virtual host CPU devices for this process."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={nparts}"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


# The halo exchange only OVERLAPS with the local slot passes when the TPU
# compiler emits the collective as an async start/done pair — and v5e's
# default is a SYNCHRONOUS all-to-all (measured: the AOT-compiled 8-chip
# step carries plain `all-to-all` ops until this flag is set, then 3 async
# windows bracketing 83-192 compute fusions each — tests/test_overlap_hlo.py).
# The reference's Irecv/compute/Waitany overlap (Parallel-GCN/main.c:238-299)
# therefore NEEDS this option on real multi-chip TPU runs.
ASYNC_COLLECTIVE_FLAGS = ("--xla_tpu_enable_async_all_to_all=true",)


def enable_tpu_async_collectives() -> None:
    """Opt-in (``SGCN_ASYNC_A2A=1``): append the async-collective XLA flags
    before XLA's backend initializes.

    Opt-in rather than automatic because XLA_FLAGS acceptance is
    runtime-dependent: this box's tunneled TPU client FATALLY rejects
    ``xla_tpu_enable_async_all_to_all`` as an env flag (it only takes it as
    a compile option — which is how ``tests/test_overlap_hlo.py`` proves
    the async schedule), while pod libtpu runtimes take it from the env.
    ``launch/tpu.slurm`` exports it for cluster runs; single-chip and CPU
    runs have no cross-chip exchange to overlap, so missing it costs
    nothing there."""
    if os.environ.get("SGCN_ASYNC_A2A") != "1":
        return
    flags = os.environ.get("XLA_FLAGS", "")
    add = [f for f in ASYNC_COLLECTIVE_FLAGS if f.split("=")[0] not in flags]
    if add:
        os.environ["XLA_FLAGS"] = " ".join([flags, *add]).strip()
