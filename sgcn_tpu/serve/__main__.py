"""Serving CLI — sustained synthetic query traffic over a checkpoint + plan.

::

    python -m sgcn_tpu.serve --npz SNAP.npz --normalize -p PARTVEC -s 8 \\
        -b cpu --checkpoint CKPT.npz --qps 100 --latency-budget-ms 50 \\
        --queries 500 --comm-schedule ragged --metrics-out RUNDIR

Mirrors the trainer CLI's data/backend flags (``sgcn_tpu.train``), loads the
model config from the checkpoint's provenance block when present (CLI flags
are the fallback for pre-provenance checkpoints / ``--random-init``), drives
the open- (``--qps N``) or closed-loop (``--qps 0``) generator, and prints
ONE JSON line: achieved QPS + p50/p95/p99 latency + batching/compile/wire
gauges.  Under ``--metrics-out`` the window also lands as a schema-v3
``serve`` event (rendered by ``scripts/obs_report.py``).

The backend env setup must happen before JAX initializes, so heavy imports
are deferred into ``main`` after arg parsing (same rule as the trainer CLI).
"""

from __future__ import annotations

import argparse
import json
import sys


def _mem_budget(text: str) -> int:
    """``--memory-budget`` values: bytes with optional binary suffix
    (``512M``, ``2G``; ``obs/memory.py::parse_bytes``)."""
    from ..obs.memory import parse_bytes

    try:
        return parse_bytes(text)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e)) from e


def main() -> None:
    p = argparse.ArgumentParser(description="sgcn_tpu partitioned inference")
    p.add_argument("-a", "--adjacency", default=None,
                   help=".mtx adjacency (or use --npz)")
    p.add_argument("--npz", default=None,
                   help="planetoid/ogbn-style .npz snapshot")
    p.add_argument("--features-mtx", default=None)
    p.add_argument("--normalize", action="store_true",
                   help="apply Â normalization to the input adjacency")
    p.add_argument("-p", "--partvec", required=True,
                   help="part vector: text (.gp/.hp/.rp) or pickle")
    p.add_argument("-b", "--backend", default="jax", choices=["jax", "cpu"])
    p.add_argument("-s", "--nparts", type=int, required=True)
    p.add_argument("--checkpoint", default=None,
                   help="trainer checkpoint .npz; its provenance block "
                        "(plan digest + model config) is verified and "
                        "supplies model/widths when present")
    p.add_argument("--random-init", action="store_true",
                   help="serve fresh Glorot-init weights instead of a "
                        "checkpoint (latency benching only — the JSON "
                        "records it)")
    p.add_argument("--model", default=None, choices=["gcn", "gat"],
                   help="fallback when the checkpoint carries no config")
    p.add_argument("-l", "--nlayers", type=int, default=2)
    p.add_argument("-f", "--nfeatures", type=int, default=16)
    p.add_argument("--hidden", type=int, default=None)
    p.add_argument("--classes", type=int, default=None,
                   help="output width (default: labels' class count when "
                        "the snapshot carries labels, else nfeatures)")
    p.add_argument("--comm-schedule", default=None,
                   choices=["a2a", "ragged", "auto"],
                   help="halo transport of the forward exchange "
                        "(docs/comm_schedule.md; inference has no gradient "
                        "ring, so this is the ENTIRE comm cost)")
    p.add_argument("--halo-dtype", default=None, choices=["bfloat16"],
                   help="wire-only exchange dtype (GCN)")
    p.add_argument("--qps", type=float, default=0.0,
                   help="offered query rate (open loop); 0 = closed loop "
                        "(saturation probe)")
    p.add_argument("--queries", type=int, default=200,
                   help="total synthetic queries in the window")
    p.add_argument("--latency-budget-ms", type=float, default=50.0,
                   help="micro-batcher deadline: flush once the oldest "
                        "pending query has waited this long")
    p.add_argument("--shed-factor", type=float, default=None, metavar="F",
                   help="deadline shedding (docs/resilience.md): a query "
                        "whose age already exceeds latency-budget-ms × F "
                        "at dispatch is returned as an explicit shed "
                        "marker instead of silently blowing the p99; "
                        "the shed count lands in the serve event (F >= 1; "
                        "default: never shed)")
    p.add_argument("--serve-mode", default="full",
                   choices=["full", "subgraph"],
                   help="'full' recomputes the whole partitioned forward "
                        "per micro-batch (PR-8); 'subgraph' computes only "
                        "the routed queries' L-hop receptive sets — "
                        "query-proportional FLOPs, bit-identical logits "
                        "(docs/serving.md phase 2)")
    p.add_argument("--concurrent", action="store_true",
                   help="double-buffered dispatch: submit batch t+1 while "
                        "batch t's device program runs (the serve:overlap "
                        "span measures the host/device overlap)")
    p.add_argument("--watch-checkpoint-dir", default=None, metavar="DIR",
                   help="poll a --checkpoint-dir rotation directory (PR-13 "
                        "CheckpointManager layout) once per flush window "
                        "and hot-swap the newest INTACT checkpoint into "
                        "the running server — zero re-compiles, swap "
                        "events in the obs stream")
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--buckets", default=None,
                   help="comma-separated padded batch-size buckets to "
                        "pre-compile (default: doubling ladder up to "
                        "max-batch)")
    p.add_argument("--query-skew", type=float, default=0.0,
                   help="Zipf exponent of the synthetic query distribution "
                        "(0 = uniform)")
    p.add_argument("--metrics-out", default=None, metavar="DIR",
                   help="run-telemetry directory (sgcn_tpu.obs): manifest + "
                        "serve/span events; render with "
                        "scripts/obs_report.py")
    p.add_argument("--memory-budget", type=_mem_budget, default=None,
                   metavar="BYTES",
                   help="per-chip HBM budget (suffixes K/M/G/T, e.g. 2G): "
                        "the analytic footprint model "
                        "(sgcn_tpu.obs.memory) is checked before any "
                        "bucket compiles; over budget fails with the "
                        "itemized per-family breakdown")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    if not args.checkpoint and not args.random_init:
        raise SystemExit("need --checkpoint CKPT or --random-init")
    if args.checkpoint and args.random_init:
        raise SystemExit("--checkpoint and --random-init are exclusive")

    if args.metrics_out:
        import os
        os.environ["SGCN_METRICS_OUT"] = args.metrics_out

    from ..utils.backend import enable_tpu_async_collectives, use_cpu_devices
    if args.backend == "cpu":
        use_cpu_devices(args.nparts)
    enable_tpu_async_collectives()

    import numpy as np

    from ..io.mtx import read_dense_features, read_mtx
    from ..parallel.plan import build_comm_plan
    from ..partition.emit import read_partvec, read_partvec_pickle
    from ..prep import normalize_adjacency

    feats = labels = None
    if args.npz:
        from ..io.datasets import load_npz_dataset
        a, feats, labels = load_npz_dataset(args.npz)
    elif args.adjacency:
        a = read_mtx(args.adjacency)
    else:
        raise SystemExit("need -a/--adjacency or --npz")
    if args.normalize:
        a = normalize_adjacency(a)
    n = a.shape[0]
    try:
        pv = read_partvec(args.partvec)
    except (UnicodeDecodeError, ValueError):
        pv = read_partvec_pickle(args.partvec)
    if len(pv) != n:
        raise SystemExit(f"partvec length {len(pv)} != n {n}")
    k = args.nparts
    if pv.max() >= k:
        raise SystemExit(f"partvec references part {pv.max()} >= k {k}")

    if args.features_mtx:
        feats = read_dense_features(args.features_mtx)
    f = feats.shape[1] if feats is not None else args.nfeatures
    if feats is None:
        # the trainer CLI's synthetic harness inputs (GPU/PGCN.py:186-192)
        feats = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, f))

    # model config: checkpoint provenance wins; CLI flags fill the gaps.
    # activation comes ONLY from provenance — it is part of the served
    # function (same params, different activation = different logits), and
    # the engine re-verifies it against the checkpoint at load
    model, widths = args.model, None
    activation = final_activation = None
    if args.checkpoint:
        from ..utils.checkpoint import read_checkpoint_meta
        meta = read_checkpoint_meta(args.checkpoint)
        cfg = meta.get("model_config") or {}
        model = model or cfg.get("model")
        activation = cfg.get("activation")
        final_activation = cfg.get("final_activation")
        if cfg.get("widths"):
            widths = list(cfg["widths"])
        if cfg.get("fin") is not None and cfg["fin"] != f:
            raise SystemExit(
                f"checkpoint was trained on fin={cfg['fin']} features, "
                f"this dataset has {f}")
    model = model or "gcn"
    if widths is None:
        nclasses = args.classes or (
            int(labels.max()) + 1 if labels is not None else f)
        hidden = args.hidden or f
        widths = [hidden] * (args.nlayers - 1) + [nclasses]

    plan = build_comm_plan(a, pv, k)
    buckets = (tuple(int(b) for b in args.buckets.split(","))
               if args.buckets else None)

    from ..obs import RunRecorder
    from ..obs.memory import MemoryBudgetError
    from .engine import ServeEngine
    from .loadgen import run_loadgen, synthetic_query_ids

    try:
        engine = ServeEngine(
            plan, fin=f, widths=widths, model=model,
            activation=activation,
            final_activation=final_activation or "none",
            comm_schedule=args.comm_schedule, halo_dtype=args.halo_dtype,
            checkpoint=args.checkpoint, max_batch=args.max_batch,
            buckets=buckets, latency_budget_ms=args.latency_budget_ms,
            shed_factor=args.shed_factor, seed=args.seed,
            mode=args.serve_mode, memory_budget=args.memory_budget)
    except MemoryBudgetError as e:
        raise SystemExit(str(e)) from e
    engine.set_features(feats)
    if args.watch_checkpoint_dir:
        engine.attach_checkpoint_watch(args.watch_checkpoint_dir)

    recorder = None
    if args.metrics_out:
        recorder = RunRecorder(args.metrics_out, config=vars(args),
                               run_kind="serve")
        recorder.set_plan(plan, partitioner={"partvec": args.partvec,
                                             "k": k})
        recorder.set_backend(engine.mesh)
        engine.attach_recorder(recorder)

    qids = synthetic_query_ids(n, args.queries, seed=args.seed,
                               skew=args.query_skew)
    mode = "open" if args.qps > 0 else "closed"
    engine.warmup(qids)      # every bucket, outside the measured window
    if args.serve_mode == "subgraph":
        # the sub-graph compile keys also encode each batch's RECEPTIVE
        # sets, which query-count warmup alone cannot cover — one
        # unmeasured pass over the same traffic warms the receptive-size
        # ladder so the measured window's quantiles describe serving, not
        # compilation (the same trace-shaped warm pass the bench child
        # runs; flush counters reset so the window's figures stay its own)
        run_loadgen(engine, qids,
                    offered_qps=args.qps if args.qps > 0 else None,
                    concurrent=args.concurrent)
        engine.batcher.deadline_flushes = 0
        engine.batcher.full_flushes = 0
    result = run_loadgen(engine, qids,
                         offered_qps=args.qps if args.qps > 0 else None,
                         concurrent=args.concurrent)
    engine.record_window(result, offered_qps=args.qps or None, mode=mode)

    report = {
        "metric": "serve_qps",
        "value": result.summary()["achieved_qps"],
        "unit": "qps",
        "mode": mode,
        "offered_qps": args.qps or None,
        # live host-clock measurement from THIS process — the same
        # provenance contract as the bench epoch times
        "measured": True,
        **result.summary(),
        "deadline_flushes": engine.batcher.deadline_flushes,
        "full_flushes": engine.batcher.full_flushes,
        "latency_budget_ms": args.latency_budget_ms,
        "model": model,
        "widths": widths,
        "weights": ("checkpoint" if args.checkpoint else "random-init"),
        **engine.gauges(),
    }
    if recorder is not None:
        recorder.record_summary(report)
        recorder.close()
    print(json.dumps(report), flush=True)


if __name__ == "__main__":
    sys.exit(main())
