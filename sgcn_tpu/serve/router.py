"""Vertex router: query vertex ids → owning partition + local slot.

The serving analogue of the trainers' data-placement step: a query names a
GLOBAL vertex id, but logits live sharded per chip under the plan's vertex
relabeling (``CommPlan.owner`` / ``CommPlan.local_idx`` — the same arrays
``scatter_rows``/``gather_rows`` ride).  The router resolves that mapping on
the host and validates ids loudly.  ``route`` additionally groups queries by
owning chip — LOAD-BEARING since sub-graph serving (``serve/subgraph.py``,
``docs/serving.md`` phase 2): each chip computes only its routed queries'
L-hop receptive sets, so co-located queries share receptive rows and the
grouping directly shrinks the per-batch touched-row bill.  (Under the
full-forward engine it remains a diagnostic: that forward runs on all k
chips regardless of ownership.)

The gather itself happens IN the compiled forward program (each chip selects
its own queries and a psum replicates the result — ``engine.py``), so the
router's output is indices, never feature rows.
"""

from __future__ import annotations

import numpy as np

# CommPlan fields the serve subsystem reads for routing — declared as a
# consumer tuple like the model PLAN_FIELDS so the plan-contract lint
# (tests/test_plan_contract.py) covers the serve engine from day one.  Both
# are GLOBAL vertex-indexed arrays (never per-chip-stacked): the router runs
# on the host over the full square plan.
SERVE_ROUTER_FIELDS = ("owner", "local_idx")


class VertexRouter:
    """Owner/slot lookup + co-location grouping over one ``CommPlan``."""

    def __init__(self, plan):
        self.n = int(plan.n)
        self.k = int(plan.k)
        self.owner = np.asarray(plan.owner, dtype=np.int32)
        self.local_idx = np.asarray(plan.local_idx, dtype=np.int32)

    def lookup(self, qids) -> tuple[np.ndarray, np.ndarray]:
        """``(owners, locals)`` for a batch of global vertex ids; raises on
        out-of-range ids (a bad query must fail at the router, not as a
        wrong-row gather deep inside the compiled program)."""
        q = np.asarray(qids, dtype=np.int64).reshape(-1)
        if q.size and (q.min() < 0 or q.max() >= self.n):
            bad = q[(q < 0) | (q >= self.n)][:5]
            raise ValueError(
                f"query vertex ids out of range [0, {self.n}): {bad.tolist()}")
        return self.owner[q], self.local_idx[q]

    def route(self, qids) -> dict[int, np.ndarray]:
        """Group a batch of query ids by owning partition; chips with no
        queries are absent.  The batching primitive of sub-graph serving
        (see the module docstring): ``build_batch`` computes one receptive
        set per GROUP, so co-located queries amortize their shared
        neighborhoods."""
        q = np.asarray(qids, dtype=np.int64).reshape(-1)
        owners, _ = self.lookup(q)
        order = np.argsort(owners, kind="stable")
        out: dict[int, np.ndarray] = {}
        for chip in np.unique(owners):
            out[int(chip)] = q[order][owners[order] == chip]
        return out
