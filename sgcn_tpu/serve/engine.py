"""AOT-compiled partitioned inference engine (forward-only, no VJP).

The serving counterpart of ``train.fullbatch``: load a checkpoint and a
``CommPlan``, verify provenance (plan digest + model config — a wrong-plan
or wrong-config restore must fail at load, not as a deep tree-shape error
or a cleanly-restored wrong model), and AOT-compile
(``jax.jit(...).lower(...).compile()``, the trick ``FullBatchTrainer
.lower_step`` already uses) ONE forward program per padded batch-size
bucket.  No optimizer state, no gradient ring — the per-layer halo exchange
is the ENTIRE comm cost, so the training transports transfer directly: the
engine supports the same ``comm_schedule``/``halo_dtype`` levers, resolved
through the SAME ``resolve_forward_setup`` the trainer uses (that shared
resolver is what makes the served logits f32-bit-identical to the trainer's
``evaluate()`` — tier-1-pinned by ``tests/test_serve.py``).

Query path per micro-batch (host stages spanned via ``SpanTimer``, the
schema-v2 machinery):

  * ``serve:route``          — global vertex ids → (owner, local slot)
    through the ``VertexRouter``;
  * ``serve:batch``          — pad the batch up to its compiled bucket
    (owner −1 on padding: matches no chip, contributes zero);
  * ``serve:compile_lookup`` — fetch the bucket's AOT executable (a MISS
    compiles and bumps ``compile_count`` — steady-state traffic must never
    miss, the no-recompile contract);
  * ``serve:forward``        — run the program and block on the replicated
    ``(Q, nout)`` result.  The halo exchange executes INSIDE this one XLA
    program, so it cannot carry its own measured span — it is attributed
    analytically instead (``halo_*`` fields of ``gauges()``, the same
    measured-vs-analytic discipline as ``docs/observability.md``).

In-program query gather: each chip ``take``s its local logits rows for the
whole padded query vector, masks to the queries it owns, and one ``psum``
replicates the summed result — exact in f32 (every non-owner contributes
literal zeros), one tiny collective per batch instead of shipping the full
``(k, B, nout)`` logits to the host.
"""

from __future__ import annotations

import numpy as np

from ..parallel.mesh import AXIS, make_mesh_1d, replicate, shard_stacked
from ..utils.timers import PhaseTimer
from .batcher import MicroBatcher, default_buckets
from .router import VertexRouter

# host-side stages of one served micro-batch, in order — the span names the
# engine emits (docs/serving.md glossary).  ``serve:overlap`` wraps the
# host-side route/pack/dispatch of batch t+1 while batch t's device program
# is still in flight (double-buffered dispatch — run_loadgen(concurrent=True)
# emits it, and the PR-7 trace parser measures the overlap it names).
SERVE_STAGES = ("serve:route", "serve:batch", "serve:compile_lookup",
                "serve:forward", "serve:overlap")


class InFlightBatch:
    """Handle of one dispatched micro-batch (``ServeEngine.submit``): the
    device program is already running asynchronously; ``result()`` blocks on
    the replicated logits and slices off the bucket padding.  The separation
    is what double-buffered dispatch rides — the caller routes/packs/submits
    batch t+1 BEFORE consuming batch t's result."""

    def __init__(self, engine, out, nq: int):
        self._engine = engine
        self._out = out
        self._nq = nq

    def result(self) -> np.ndarray:
        with self._engine.spans.span("serve:forward"):
            out = np.asarray(self._out)            # readback = sync
        return out[: self._nq]


class CheckpointWatcher:
    """Poll a ``CheckpointManager`` directory (PR-13 rotation layout) and
    hot-swap the newest INTACT checkpoint into a running engine — the
    ``--watch-checkpoint-dir`` machinery: one ``poll`` per flush window,
    zero re-compiles (params are inputs to the AOT programs), corrupt
    candidates skipped with a loud warning (the manager's newest-intact
    rule), provenance mismatches raised loudly (a wrong-plan checkpoint in
    the watch directory is a config bug, not something to serve past)."""

    def __init__(self, directory: str, last_step: int = -1):
        from ..resilience.checkpoint import CheckpointManager

        self.manager = CheckpointManager(directory)
        self.last_step = int(last_step)

    def poll(self, engine) -> bool:
        """Swap in the newest intact checkpoint stamped past ``last_step``;
        returns True when a swap happened.  Corruption is detected by the
        swap itself (``load_checkpoint_leaves`` checksums every array
        BEFORE provenance checking or any engine state change), so each
        candidate is read exactly once — a separate verify pass would
        double the checkpoint I/O sitting in front of queued queries."""
        import warnings

        from ..utils.checkpoint import CheckpointCorruptError

        for step, path in reversed(self.manager.checkpoints()):
            if step <= self.last_step:
                return False
            try:
                engine.swap_weights(path)
            except CheckpointCorruptError as e:
                warnings.warn(
                    f"checkpoint watch: {path!r} is corrupt ({e}); trying "
                    "the previous candidate", RuntimeWarning, stacklevel=2)
                continue
            self.last_step = step
            return True
        return False


class ServeEngine:
    """Forward-only partitioned inference over one plan + checkpoint."""

    def __init__(
        self,
        plan,
        fin: int,
        widths: list[int],
        model: str = "gcn",
        activation: str | None = None,
        final_activation: str = "none",
        comm_schedule: str | None = None,
        halo_dtype: str | None = None,
        mesh=None,
        params=None,
        checkpoint: str | None = None,
        max_batch: int = 64,
        buckets: tuple | None = None,
        latency_budget_ms: float = 50.0,
        shed_factor: float | None = None,
        seed: int = 0,
        precompile: bool = True,
        mode: str = "full",
        memory_budget: int | None = None,
    ):
        """``mode='full'`` is the PR-8 engine: one full partitioned forward
        per micro-batch.  ``mode='subgraph'`` is query-proportional
        (``docs/serving.md`` phase 2): each batch computes only the routed
        queries' L-hop receptive sets (``serve/subgraph.py``) with no
        per-layer exchange — routed logits stay f32-bit-identical to
        ``evaluate()`` either way."""
        if halo_dtype is not None and model != "gcn":
            raise ValueError(
                "halo_dtype is a GCN wire lever; the GAT exchange ships "
                "attention tables (same rule as the trainer)")
        if mode not in ("full", "subgraph"):
            raise ValueError(f"unknown serve mode {mode!r} "
                             "(know 'full', 'subgraph')")
        from ..train.fullbatch import resolve_forward_setup

        self.plan = plan
        self.fin = int(fin)
        self.widths = list(widths)
        self.model = model
        self.mode = mode
        self.weights_rev = 0          # bumped by every swap_weights — the
        # serve-event attribution key for windows spanning a hot-swap
        # PGAT semantics: bare stacked modules, no inter-layer activation —
        # the trainer CLI's default; parity with evaluate() needs the same
        self.activation = activation if activation is not None else (
            "none" if model == "gat" else "relu")
        self.final_activation = final_activation
        self.halo_dtype = halo_dtype
        self.setup = resolve_forward_setup(
            plan, fin, widths, model=model, comm_schedule=comm_schedule,
            serve_subgraph=(mode == "subgraph"))
        self.comm_schedule = self.setup.comm_schedule
        self.comm_decision = self.setup.decision
        # analytic per-chip HBM footprint (obs/memory.py) + the
        # --memory-budget plan-time gate — before params/array shipping,
        # failing loudly with the itemized per-family table
        from ..obs.memory import check_memory_budget, memory_model
        self.memory = memory_model(
            plan, fin, widths,
            workload="serve_subgraph" if mode == "subgraph" else "serve",
            model=model, halo_dtype=halo_dtype, setup=self.setup)
        self._memory_measured = None       # best measured join so far (the
        # widest compiled bucket's memory_analysis — _ensure_compiled)
        check_memory_budget(self.memory, memory_budget,
                            what=f"{model} serve engine ({mode})")
        self.mesh = mesh if mesh is not None else make_mesh_1d(plan.k)
        self.router = VertexRouter(plan)
        self.batcher = MicroBatcher(
            max_batch=max_batch,
            latency_budget_ms=latency_budget_ms,
            buckets=buckets if buckets is not None
            else default_buckets(max_batch),
            shed_factor=shed_factor)
        self.recorder = None
        self.timer = PhaseTimer()
        from ..obs.tracing import SpanTimer
        self.spans = SpanTimer(timer=self.timer)

        # ---- params: checkpoint (provenance-verified) or given/fresh init
        dims = list(zip([fin] + self.widths[:-1], self.widths))
        if checkpoint is not None:
            params = self._load_params(checkpoint, dims)
        elif params is None:
            import jax
            params = self.setup.init_fn(jax.random.PRNGKey(seed), dims)
        self.params = replicate(self.mesh, params)
        self.pa = shard_stacked(self.mesh, self.setup.ship_arrays(plan))
        self._h0 = None                    # set_features()
        self._compiled: dict[int, object] = {}   # bucket size → executable
        self.compile_count = 0
        # sub-graph serving state (mode='subgraph')
        self.sgindex = None
        self._features = None              # global (n, fin) numpy rows
        self._sg_compiled: dict[tuple, object] = {}   # shape key → program
        self._stabilizers = None           # GAT per-layer cg (host f32)
        self._cg_dev = None
        self._stab_prog = None
        self._watch = None                 # CheckpointWatcher
        self._sg_totals = {"queries": 0, "batches": 0, "touched_rows": 0,
                           "recipe_edges": 0, "wire_rows": 0, "flops": 0}
        if mode == "subgraph":
            # resolve_forward_setup(serve_subgraph=True) already refused
            # the Pallas aggregator (the one fold the compact mirror
            # cannot reproduce bit-exactly)
            from .subgraph import SubgraphIndex
            self.sgindex = SubgraphIndex(plan, model)
        if precompile and mode == "full":
            for b in self.batcher.buckets:
                self._ensure_compiled(b)

    # ------------------------------------------------------------- loading
    def _load_params(self, path: str, dims):
        """Restore the params tree (opt state skipped — inference has none)
        from a trainer checkpoint, verifying plan digest + model config
        FIRST so a wrong-plan/model restore fails with a clear message."""
        import jax

        from ..utils.checkpoint import (load_checkpoint_leaves,
                                        verify_checkpoint_provenance)
        leaves, meta = load_checkpoint_leaves(path)
        verify_checkpoint_provenance(
            meta, plan=self.plan, model=self.model, fin=self.fin,
            widths=self.widths, activation=self.activation,
            final_activation=self.final_activation,
            what=f"serve engine ({path!r})")
        template = self.setup.init_fn(jax.random.PRNGKey(0), dims)
        tleaves, treedef = jax.tree.flatten(template)
        if len(leaves) < len(tleaves):
            raise ValueError(
                f"checkpoint {path!r} has {len(leaves)} leaves, the "
                f"{self.model} params tree needs {len(tleaves)} — not a "
                "checkpoint of this model config")
        # (params, opt_state) flattens params-first; the leading leaves ARE
        # the params in tree order
        got = leaves[: len(tleaves)]
        for have, want in zip(got, tleaves):
            if tuple(have.shape) != tuple(np.shape(want)):
                raise ValueError(
                    f"checkpoint param leaf shape {have.shape} != expected "
                    f"{np.shape(want)} — wrong fin/widths for this "
                    "checkpoint (read_checkpoint_meta shows its config)")
        self.checkpoint_meta = meta
        return jax.tree.unflatten(treedef, got)

    # ------------------------------------------------------------ features
    def set_features(self, features: np.ndarray) -> None:
        """Scatter + shard the global ``(n, fin)`` feature rows once — the
        serving working set every forward reads (features are part of the
        model's input, not of a query)."""
        features = np.asarray(features, dtype=np.float32)
        if features.shape != (self.plan.n, self.fin):
            raise ValueError(
                f"features shape {features.shape} != "
                f"({self.plan.n}, {self.fin})")
        h0 = self.plan.scatter_rows(features)
        self._h0 = shard_stacked(self.mesh, h0)
        self._features = features
        if self.mode == "subgraph" and self.model == "gat":
            self._refresh_stabilizers()

    # ------------------------------------------------- GAT stabilizer cache
    def _refresh_stabilizers(self) -> None:
        """Precompute the per-layer softmax stabilizers ``cg`` of the FULL
        graph under the current (params, features) — the one full-graph
        quantity the sub-graph program consumes as an input
        (``gat_forward_local(collect_stabilizers=True)``; see
        ``serve/subgraph.py``).  Constant until the next weight swap or
        feature load, so the cost is one full forward per swap, amortized
        over every query served from it."""
        import jax
        from jax.sharding import PartitionSpec as P

        if self._stab_prog is None:
            fwd = self.setup.forward_fn
            fwd_static = self.setup.fwd_static
            symmetric = self.plan.symmetric

            def per_chip(params, pa, h0):
                pa = jax.tree.map(lambda x: x[0], pa)
                _, cgs = fwd(
                    params, h0[0], pa,
                    activation=self.activation,
                    final_activation=self.final_activation,
                    symmetric=symmetric,
                    collect_stabilizers=True,
                    **fwd_static,
                )
                return cgs                       # pmax'd → replicated

            self._stab_prog = jax.jit(jax.shard_map(
                per_chip, mesh=self.mesh,
                in_specs=(P(), P(AXIS), P(AXIS)), out_specs=P()))
        self._stabilizers = np.asarray(
            self._stab_prog(self.params, self.pa, self._h0),
            dtype=np.float32)
        self._cg_dev = None                      # re-replicated on next use

    def _cgs(self):
        """Replicated device (L,) stabilizer vector (zeros for GCN — the
        program never reads them and jit prunes the argument)."""
        if self._cg_dev is None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            host = (self._stabilizers if self._stabilizers is not None
                    else np.zeros((self.nlayers,), np.float32))
            self._cg_dev = jax.device_put(
                host, NamedSharding(self.mesh, P()))
        return self._cg_dev

    # ------------------------------------------------------------- compile
    def lower_bucket(self, q: int):
        """AOT-LOWER the bucket-``q`` forward+gather program (no compile,
        no execution) — the serve entry point of the static-analysis HLO
        audit (``sgcn_tpu/analysis``): the lowered module is exactly the
        program ``_ensure_compiled(q)`` compiles, so the audit checks the
        real serving step's collective census (L halo exchanges + ONE
        logit-gather psum), wire dtypes and the no-donation contract
        (engine params are reused across batches — a donated buffer here
        would be a use-after-free by design)."""
        import jax
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P
        import jax.numpy as jnp

        fwd = self.setup.forward_fn
        fwd_static = self.setup.fwd_static
        extra = ({"halo_dtype": self.halo_dtype}
                 if self.halo_dtype is not None else {})
        symmetric = self.plan.symmetric

        def per_chip(params, pa, h0, q_owner, q_local):
            pa = jax.tree.map(lambda x: x[0], pa)
            h0 = h0[0]
            logits = fwd(
                params, h0, pa,
                activation=self.activation,
                final_activation=self.final_activation,
                symmetric=symmetric,
                **fwd_static, **extra,
            ).astype("float32")
            sel = jnp.take(logits, q_local, axis=0)        # (Q, nout)
            mine = (q_owner == lax.axis_index(AXIS)).astype(
                jnp.float32)[:, None]
            # non-owners contribute exact zeros, so the psum'd row IS the
            # owner's f32 logits row bit-for-bit
            return lax.psum(sel * mine, AXIS)

        smapped = jax.shard_map(
            per_chip,
            mesh=self.mesh,
            in_specs=(P(), P(AXIS), P(AXIS), P(), P()),
            out_specs=P(),
        )
        rep = NamedSharding(self.mesh, P())
        shd = NamedSharding(self.mesh, P(AXIS))
        params_s = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=rep),
            self.params)
        pa_s = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=shd),
            self.pa)
        h0_s = jax.ShapeDtypeStruct((self.plan.k, self.plan.b, self.fin),
                                    np.dtype(np.float32), sharding=shd)
        qs = jax.ShapeDtypeStruct((q,), np.dtype(np.int32), sharding=rep)
        return jax.jit(smapped).lower(params_s, pa_s, h0_s, qs, qs)

    def _ensure_compiled(self, q: int):
        if q not in self._compiled:
            self._compiled[q] = self.lower_bucket(q).compile()
            self.compile_count += 1
            self._join_memory(f"bucket{q}", self._compiled[q])
        return self._compiled[q]

    def _join_memory(self, program: str, compiled) -> None:
        """Join XLA's measured per-device figures against the analytic
        footprint for one freshly compiled program (schema v6): keeps the
        peak-heaviest join as the engine's measured side and, under a
        recorder, re-publishes the manifest memory block and appends one
        ``memory`` event — the serving half of the model-vs-measured
        memory contract (docs/observability.md)."""
        from ..obs.memory import measure_compiled

        measured = measure_compiled(compiled)
        if measured is None:
            return
        if (self._memory_measured is None
                or measured["peak_bytes"]
                > self._memory_measured["peak_bytes"]):
            self._memory_measured = measured
        if self.recorder is not None:
            self.recorder.set_memory(
                self.memory.block(self._memory_measured))
            self.recorder.record_memory(
                program=program, model=self.memory, measured=measured)

    def lower_subgraph(self, key: tuple):
        """AOT-LOWER the sub-graph program for one shape key (no compile,
        no execution) — the ``serve_subgraph`` entry point of the
        static-analysis HLO audit: the lowered module is exactly the
        program a real batch of this key runs, and its audited contract is
        the tentpole's: NO collective beyond the single logit-gather psum
        (every per-layer exchange is gone — sources are computed locally
        from host-gathered features), zero donation, no host callbacks."""
        import jax
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P
        import jax.numpy as jnp

        from .subgraph import (batch_struct, key_buckets,
                               subgraph_forward_gat, subgraph_forward_gcn)

        if self.sgindex is None:
            raise ValueError("engine was built with mode='full' — "
                             "sub-graph programs exist under "
                             "mode='subgraph'")
        model, qb = key[0], key[1]
        buckets = key_buckets(self.sgindex, key)

        def per_chip(params, cgs, arrays, q_owner, q_pos):
            arrays = jax.tree.map(lambda x: x[0], arrays)
            if model == "gcn":
                h = subgraph_forward_gcn(
                    params, arrays["feats"], arrays, buckets,
                    activation=self.activation,
                    final_activation=self.final_activation,
                    halo_dtype=self.halo_dtype)
            else:
                h = subgraph_forward_gat(
                    params, cgs, arrays["feats"], arrays, buckets,
                    activation=self.activation,
                    final_activation=self.final_activation)
            h = h.astype("float32")
            sel = jnp.take(h, q_pos, axis=0)           # (Qb, nout)
            mine = q_owner == lax.axis_index(AXIS)
            # where, not multiply: the receptive set's outer-shell rows are
            # computed with incomplete neighborhoods and may hold NaN —
            # a non-owner's masked gather must contribute EXACT zeros
            return lax.psum(jnp.where(mine[:, None], sel, 0.0), AXIS)

        smapped = jax.shard_map(
            per_chip, mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS), P(), P()), out_specs=P())
        rep = NamedSharding(self.mesh, P())
        shd = NamedSharding(self.mesh, P(AXIS))
        params_s = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=rep),
            self.params)
        cgs_s = jax.ShapeDtypeStruct((self.nlayers,), np.dtype(np.float32),
                                     sharding=rep)
        arr_s = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=shd),
            batch_struct(self.sgindex, key, self.fin))
        qs = jax.ShapeDtypeStruct((qb,), np.dtype(np.int32), sharding=rep)
        return jax.jit(smapped).lower(params_s, cgs_s, arr_s, qs, qs)

    def _ensure_compiled_sg(self, key: tuple):
        if key not in self._sg_compiled:
            self._sg_compiled[key] = self.lower_subgraph(key).compile()
            self.compile_count += 1
            self._join_memory(f"subgraph{key[1]}", self._sg_compiled[key])
        return self._sg_compiled[key]

    # --------------------------------------------------------------- query
    def submit(self, qids) -> "InFlightBatch":
        """Dispatch one micro-batch WITHOUT blocking: host stages (route,
        pack, compile lookup) run and the device program launches
        asynchronously; the returned handle's ``result()`` blocks.  This is
        the double-buffered dispatch primitive — submit batch t+1 while
        batch t runs, then consume t (``run_loadgen(concurrent=True)``)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._h0 is None:
            raise ValueError(
                "no features loaded — call set_features(features) before "
                "serving queries")
        qids = np.asarray(qids, dtype=np.int64).reshape(-1)
        nq = len(qids)
        if nq == 0:
            return InFlightBatch(
                self, np.zeros((0, self.widths[-1]), np.float32), 0)
        if self._watch is not None:
            # one poll per flush window: a newer intact checkpoint in the
            # watched directory hot-swaps in before this batch dispatches
            self._watch.poll(self)
        if self.mode == "subgraph":
            return self._submit_subgraph(qids)
        with self.spans.span("serve:route"):
            owners, locals_ = self.router.lookup(qids)
        with self.spans.span("serve:batch"):
            bucket = self.batcher.bucket_for(nq)
            q_owner = np.full(bucket, -1, np.int32)    # pad: matches no chip
            q_local = np.zeros(bucket, np.int32)
            q_owner[:nq] = owners
            q_local[:nq] = locals_
            rep = NamedSharding(self.mesh, P())
            q_owner = jax.device_put(q_owner, rep)
            q_local = jax.device_put(q_local, rep)
        with self.spans.span("serve:compile_lookup"):
            prog = self._ensure_compiled(bucket)
        out = prog(self.params, self.pa, self._h0, q_owner, q_local)
        return InFlightBatch(self, out, nq)

    def _submit_subgraph(self, qids) -> "InFlightBatch":
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..obs.attribution import subgraph_batch_flops
        from .subgraph import build_batch

        if self._features is None:
            raise ValueError(
                "sub-graph serving gathers receptive-set features on the "
                "host — call set_features(features) first")
        if self.model == "gat" and self._stabilizers is None:
            self._refresh_stabilizers()
        with self.spans.span("serve:route"):
            # router-grouped receptive sets: co-located queries share
            # receptive rows, the spill-minimizing batching route() exists
            # for (docs/serving.md phase 2)
            batch = build_batch(self.sgindex, self.router, self._features,
                                qids, self.nlayers)
        with self.spans.span("serve:batch"):
            rep = NamedSharding(self.mesh, P())
            shd = NamedSharding(self.mesh, P(AXIS))
            arrs = jax.tree.map(lambda a: jax.device_put(a, shd),
                                batch.arrays)
            q_owner = jax.device_put(batch.q_owner, rep)
            q_pos = jax.device_put(batch.q_pos, rep)
        with self.spans.span("serve:compile_lookup"):
            prog = self._ensure_compiled_sg(batch.key)
        out = prog(self.params, self._cgs(), arrs, q_owner, q_pos)
        t = self._sg_totals
        t["queries"] += batch.nq
        t["batches"] += 1
        t["touched_rows"] += batch.touched_rows
        t["recipe_edges"] += batch.recipe_edges
        t["wire_rows"] += batch.key[1]              # padded psum rows
        t["flops"] += subgraph_batch_flops(
            batch.touched_rows, batch.recipe_edges, self.fin, self.widths,
            model=self.model)
        return InFlightBatch(self, out, batch.nq)

    def query(self, qids) -> np.ndarray:
        """Serve one micro-batch of global vertex ids → ``(len(qids), nout)``
        f32 logits.  Stages are spanned (``SERVE_STAGES``); the batch is
        padded to its bucket(s) so no query count — and in sub-graph mode no
        receptive-set size — triggers a recompile after warm-up."""
        return self.submit(qids).result()

    def swap_weights(self, checkpoint: str) -> dict:
        """Hot-swap a new checkpoint into the running engine with ZERO
        re-lowering/re-compilation: provenance (plan digest + model config)
        is verified FIRST — a mismatch raises before any engine state
        changes — then the new leaves replace ``self.params`` (params are
        ordinary inputs to every AOT program, so ``compile_count`` is
        pinned across the swap), ``weights_rev`` bumps for window
        attribution, and the GAT stabilizer cache refreshes (one full
        forward — the per-swap cost sub-graph serving amortizes).  Returns
        the new checkpoint's meta block."""
        import time as _time

        t0 = _time.perf_counter()
        dims = list(zip([self.fin] + self.widths[:-1], self.widths))
        params = self._load_params(checkpoint, dims)   # verifies first
        self.params = replicate(self.mesh, params)
        self.weights_rev += 1
        if self.mode == "subgraph" and self.model == "gat" \
                and self._h0 is not None:
            self._refresh_stabilizers()
        if self.recorder is not None:
            self.recorder.record_swap(
                path=checkpoint, weights_rev=self.weights_rev,
                checkpoint_step=self.checkpoint_meta.get("step"),
                wall_s=_time.perf_counter() - t0)
        return self.checkpoint_meta

    def attach_checkpoint_watch(self, directory: str) -> "CheckpointWatcher":
        """Watch a PR-13 rotation directory: each flush window polls once
        and hot-swaps the newest intact checkpoint in (CLI:
        ``--watch-checkpoint-dir``)."""
        last = -1
        if getattr(self, "checkpoint_meta", None):
            step = self.checkpoint_meta.get("step")
            if step is not None:        # step 0 is a real stamp, not falsy
                last = int(step)
        self._watch = CheckpointWatcher(directory, last_step=last)
        return self._watch

    def warmup(self, qids) -> None:
        """Serve one throwaway batch per pre-compiled bucket (cycling
        ``qids`` to fill each).  A bucket's FIRST dispatch pays runtime
        autotuning even with an AOT program, and deadline flushes land on
        the small buckets — run this before a measured window or the
        overhead lands in the published p99."""
        qids = np.asarray(qids, dtype=np.int64).reshape(-1)
        if qids.size == 0:
            raise ValueError("warmup needs at least one query id")
        for b in self.batcher.buckets:
            self.query(np.resize(qids, b))

    # -------------------------------------------------------------- gauges
    @property
    def nlayers(self) -> int:
        return len(self.widths)

    def gauges(self) -> dict:
        """Analytic per-batch/per-query gauges of the serving forward —
        plan-derived (full mode) or accumulated over the served batches'
        true receptive sets (sub-graph mode); deterministic either way
        (zero-band in the bench trend).  In full mode the forward runs
        ``nlayers`` exchanges per micro-batch regardless of batch size, so
        the steady-state per-QUERY wire cost is the full-batch amortization
        ``nlayers · wire_rows/exchange ÷ max_batch``."""
        from ..obs.attribution import forward_flops

        # plan-derived per-chip residency (obs/memory.py) — `analytic: true`
        # is the provenance flag scripts/validate_bench.py requires on any
        # *_bytes residency claim in a bench block
        mem = {"analytic": True,
               "model_bytes": self.memory.total_bytes,
               **{f"{name}_bytes": int(v)
                  for name, v in self.memory.families.items() if v}}
        if self._memory_measured is not None:
            mem["measured"] = True
            mem["measured_peak_bytes"] = self._memory_measured["peak_bytes"]
        if self.mode == "subgraph":
            t = self._sg_totals
            nq = max(t["queries"], 1)
            return {
                "serve_mode": "subgraph",
                "memory": mem,
                "comm_schedule": self.comm_schedule,
                "weights_rev": self.weights_rev,
                # prefixed: these are ENGINE-LIFETIME accumulators (warmup
                # included), not one window's measured counts — a bare
                # "queries" key would shadow ServeResult.summary()'s in the
                # CLI report merge (observed: 24-query window reported 32)
                "subgraph_queries_total": t["queries"],
                "subgraph_batches_total": t["batches"],
                "touched_rows_total": t["touched_rows"],
                "touched_rows_per_query": round(t["touched_rows"] / nq, 6),
                "recipe_edges_total": t["recipe_edges"],
                "subgraph_flops_per_query": round(t["flops"] / nq, 3),
                # the ONLY wire traffic is the logit-gather psum's padded
                # (Qb, nout) buffer — per query ~one logits row
                "wire_rows_per_query": round(t["wire_rows"] / nq, 6),
                # the full-forward figures a batch of this plan WOULD have
                # paid — the A/B denominators (bench.py serve_subgraph_ab)
                "full_rows_per_forward": int(self.plan.k * self.plan.b),
                "full_forward_flops": forward_flops(
                    self.plan, self.fin, self.widths, model=self.model),
                "buckets": sorted(self._sg_compiled),
                "compiles": self.compile_count,
            }
        wire = self.plan.wire_rows_per_exchange(self.comm_schedule)
        true = int(self.plan.predicted_send_volume.sum())
        return {
            "serve_mode": "full",
            "memory": mem,
            "comm_schedule": self.comm_schedule,
            "weights_rev": self.weights_rev,
            "exchanges_per_batch": self.nlayers,
            "wire_rows_per_exchange": wire,
            "true_rows_per_exchange": true,
            "wire_rows_per_batch": self.nlayers * wire,
            "wire_rows_per_query": round(
                self.nlayers * wire / self.batcher.max_batch, 6),
            "full_rows_per_forward": int(self.plan.k * self.plan.b),
            "full_forward_flops": forward_flops(
                self.plan, self.fin, self.widths, model=self.model),
            "buckets": list(self.batcher.buckets),
            "compiles": self.compile_count,
        }

    # ------------------------------------------------------------ recorder
    def attach_recorder(self, recorder) -> None:
        """Attach a ``RunRecorder``: stage spans become schema events and
        the transport decision lands in the manifest (the same
        reconstructibility contract as the trainers)."""
        self.recorder = recorder
        self.spans.recorder = recorder
        if self.comm_decision:
            recorder.set_comm_schedule(self.comm_decision)
        if getattr(self, "memory", None) is not None:
            # includes the measured join when a bucket already compiled
            # (precompile=True attaches after __init__)
            recorder.set_memory(self.memory.block(self._memory_measured))

    def record_window(self, result, offered_qps: float | None = None,
                      mode: str = "open") -> None:
        """Emit one schema-v3 ``serve`` event for a completed traffic
        window (``loadgen.ServeResult``) with the batching counters and the
        analytic wire gauge riding along."""
        if self.recorder is None:
            return
        g = self.gauges()
        self.recorder.record_serve(
            queries=result.queries,
            achieved_qps=result.achieved_qps,
            latency_p50_ms=result.p50_ms,
            latency_p95_ms=result.p95_ms,
            latency_p99_ms=result.p99_ms,
            window_s=result.window_s,
            offered_qps=offered_qps,
            mode=mode,
            batches=result.batches,
            mean_batch=result.mean_batch,
            deadline_flushes=self.batcher.deadline_flushes,
            full_flushes=self.batcher.full_flushes,
            latency_budget_ms=self.batcher.latency_budget_ms,
            compiles=self.compile_count,
            buckets=list(self.batcher.buckets),
            comm_schedule=self.comm_schedule,
            wire_rows_per_query=g["wire_rows_per_query"],
            # v5 additive: hot-swap attribution + sub-graph gauges (a
            # window spanning a swap_weights names both revisions via the
            # swap event between two serve events)
            serve_mode=self.mode,
            weights_rev=self.weights_rev,
            touched_rows_per_query=g.get("touched_rows_per_query"),
            subgraph_flops_per_query=g.get("subgraph_flops_per_query"),
            # v4 additive: deadline-shed count of the window — present
            # only when shedding is configured, so pre-shedding events
            # keep their exact shape
            shed=(getattr(result, "shed", 0)
                  if self.batcher.shed_factor is not None else None),
            shed_factor=self.batcher.shed_factor,
        )
