"""AOT-compiled partitioned inference engine (forward-only, no VJP).

The serving counterpart of ``train.fullbatch``: load a checkpoint and a
``CommPlan``, verify provenance (plan digest + model config — a wrong-plan
or wrong-config restore must fail at load, not as a deep tree-shape error
or a cleanly-restored wrong model), and AOT-compile
(``jax.jit(...).lower(...).compile()``, the trick ``FullBatchTrainer
.lower_step`` already uses) ONE forward program per padded batch-size
bucket.  No optimizer state, no gradient ring — the per-layer halo exchange
is the ENTIRE comm cost, so the training transports transfer directly: the
engine supports the same ``comm_schedule``/``halo_dtype`` levers, resolved
through the SAME ``resolve_forward_setup`` the trainer uses (that shared
resolver is what makes the served logits f32-bit-identical to the trainer's
``evaluate()`` — tier-1-pinned by ``tests/test_serve.py``).

Query path per micro-batch (host stages spanned via ``SpanTimer``, the
schema-v2 machinery):

  * ``serve:route``          — global vertex ids → (owner, local slot)
    through the ``VertexRouter``;
  * ``serve:batch``          — pad the batch up to its compiled bucket
    (owner −1 on padding: matches no chip, contributes zero);
  * ``serve:compile_lookup`` — fetch the bucket's AOT executable (a MISS
    compiles and bumps ``compile_count`` — steady-state traffic must never
    miss, the no-recompile contract);
  * ``serve:forward``        — run the program and block on the replicated
    ``(Q, nout)`` result.  The halo exchange executes INSIDE this one XLA
    program, so it cannot carry its own measured span — it is attributed
    analytically instead (``halo_*`` fields of ``gauges()``, the same
    measured-vs-analytic discipline as ``docs/observability.md``).

In-program query gather: each chip ``take``s its local logits rows for the
whole padded query vector, masks to the queries it owns, and one ``psum``
replicates the summed result — exact in f32 (every non-owner contributes
literal zeros), one tiny collective per batch instead of shipping the full
``(k, B, nout)`` logits to the host.
"""

from __future__ import annotations

import numpy as np

from ..parallel.mesh import AXIS, make_mesh_1d, replicate, shard_stacked
from ..utils.timers import PhaseTimer
from .batcher import MicroBatcher, default_buckets
from .router import VertexRouter

# host-side stages of one served micro-batch, in order — the span names the
# engine emits (docs/serving.md glossary)
SERVE_STAGES = ("serve:route", "serve:batch", "serve:compile_lookup",
                "serve:forward")


class ServeEngine:
    """Forward-only partitioned inference over one plan + checkpoint."""

    def __init__(
        self,
        plan,
        fin: int,
        widths: list[int],
        model: str = "gcn",
        activation: str | None = None,
        final_activation: str = "none",
        comm_schedule: str | None = None,
        halo_dtype: str | None = None,
        mesh=None,
        params=None,
        checkpoint: str | None = None,
        max_batch: int = 64,
        buckets: tuple | None = None,
        latency_budget_ms: float = 50.0,
        shed_factor: float | None = None,
        seed: int = 0,
        precompile: bool = True,
    ):
        if halo_dtype is not None and model != "gcn":
            raise ValueError(
                "halo_dtype is a GCN wire lever; the GAT exchange ships "
                "attention tables (same rule as the trainer)")
        from ..train.fullbatch import resolve_forward_setup

        self.plan = plan
        self.fin = int(fin)
        self.widths = list(widths)
        self.model = model
        # PGAT semantics: bare stacked modules, no inter-layer activation —
        # the trainer CLI's default; parity with evaluate() needs the same
        self.activation = activation if activation is not None else (
            "none" if model == "gat" else "relu")
        self.final_activation = final_activation
        self.halo_dtype = halo_dtype
        self.setup = resolve_forward_setup(
            plan, fin, widths, model=model, comm_schedule=comm_schedule)
        self.comm_schedule = self.setup.comm_schedule
        self.comm_decision = self.setup.decision
        self.mesh = mesh if mesh is not None else make_mesh_1d(plan.k)
        self.router = VertexRouter(plan)
        self.batcher = MicroBatcher(
            max_batch=max_batch,
            latency_budget_ms=latency_budget_ms,
            buckets=buckets if buckets is not None
            else default_buckets(max_batch),
            shed_factor=shed_factor)
        self.recorder = None
        self.timer = PhaseTimer()
        from ..obs.tracing import SpanTimer
        self.spans = SpanTimer(timer=self.timer)

        # ---- params: checkpoint (provenance-verified) or given/fresh init
        dims = list(zip([fin] + self.widths[:-1], self.widths))
        if checkpoint is not None:
            params = self._load_params(checkpoint, dims)
        elif params is None:
            import jax
            params = self.setup.init_fn(jax.random.PRNGKey(seed), dims)
        self.params = replicate(self.mesh, params)
        self.pa = shard_stacked(self.mesh, self.setup.ship_arrays(plan))
        self._h0 = None                    # set_features()
        self._compiled: dict[int, object] = {}   # bucket size → executable
        self.compile_count = 0
        if precompile:
            for b in self.batcher.buckets:
                self._ensure_compiled(b)

    # ------------------------------------------------------------- loading
    def _load_params(self, path: str, dims):
        """Restore the params tree (opt state skipped — inference has none)
        from a trainer checkpoint, verifying plan digest + model config
        FIRST so a wrong-plan/model restore fails with a clear message."""
        import jax

        from ..utils.checkpoint import (load_checkpoint_leaves,
                                        verify_checkpoint_provenance)
        leaves, meta = load_checkpoint_leaves(path)
        verify_checkpoint_provenance(
            meta, plan=self.plan, model=self.model, fin=self.fin,
            widths=self.widths, activation=self.activation,
            final_activation=self.final_activation,
            what=f"serve engine ({path!r})")
        template = self.setup.init_fn(jax.random.PRNGKey(0), dims)
        tleaves, treedef = jax.tree.flatten(template)
        if len(leaves) < len(tleaves):
            raise ValueError(
                f"checkpoint {path!r} has {len(leaves)} leaves, the "
                f"{self.model} params tree needs {len(tleaves)} — not a "
                "checkpoint of this model config")
        # (params, opt_state) flattens params-first; the leading leaves ARE
        # the params in tree order
        got = leaves[: len(tleaves)]
        for have, want in zip(got, tleaves):
            if tuple(have.shape) != tuple(np.shape(want)):
                raise ValueError(
                    f"checkpoint param leaf shape {have.shape} != expected "
                    f"{np.shape(want)} — wrong fin/widths for this "
                    "checkpoint (read_checkpoint_meta shows its config)")
        self.checkpoint_meta = meta
        return jax.tree.unflatten(treedef, got)

    # ------------------------------------------------------------ features
    def set_features(self, features: np.ndarray) -> None:
        """Scatter + shard the global ``(n, fin)`` feature rows once — the
        serving working set every forward reads (features are part of the
        model's input, not of a query)."""
        features = np.asarray(features, dtype=np.float32)
        if features.shape != (self.plan.n, self.fin):
            raise ValueError(
                f"features shape {features.shape} != "
                f"({self.plan.n}, {self.fin})")
        h0 = self.plan.scatter_rows(features)
        self._h0 = shard_stacked(self.mesh, h0)

    # ------------------------------------------------------------- compile
    def lower_bucket(self, q: int):
        """AOT-LOWER the bucket-``q`` forward+gather program (no compile,
        no execution) — the serve entry point of the static-analysis HLO
        audit (``sgcn_tpu/analysis``): the lowered module is exactly the
        program ``_ensure_compiled(q)`` compiles, so the audit checks the
        real serving step's collective census (L halo exchanges + ONE
        logit-gather psum), wire dtypes and the no-donation contract
        (engine params are reused across batches — a donated buffer here
        would be a use-after-free by design)."""
        import jax
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P
        import jax.numpy as jnp

        fwd = self.setup.forward_fn
        fwd_static = self.setup.fwd_static
        extra = ({"halo_dtype": self.halo_dtype}
                 if self.halo_dtype is not None else {})
        symmetric = self.plan.symmetric

        def per_chip(params, pa, h0, q_owner, q_local):
            pa = jax.tree.map(lambda x: x[0], pa)
            h0 = h0[0]
            logits = fwd(
                params, h0, pa,
                activation=self.activation,
                final_activation=self.final_activation,
                symmetric=symmetric,
                **fwd_static, **extra,
            ).astype("float32")
            sel = jnp.take(logits, q_local, axis=0)        # (Q, nout)
            mine = (q_owner == lax.axis_index(AXIS)).astype(
                jnp.float32)[:, None]
            # non-owners contribute exact zeros, so the psum'd row IS the
            # owner's f32 logits row bit-for-bit
            return lax.psum(sel * mine, AXIS)

        smapped = jax.shard_map(
            per_chip,
            mesh=self.mesh,
            in_specs=(P(), P(AXIS), P(AXIS), P(), P()),
            out_specs=P(),
        )
        rep = NamedSharding(self.mesh, P())
        shd = NamedSharding(self.mesh, P(AXIS))
        params_s = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=rep),
            self.params)
        pa_s = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=shd),
            self.pa)
        h0_s = jax.ShapeDtypeStruct((self.plan.k, self.plan.b, self.fin),
                                    np.dtype(np.float32), sharding=shd)
        qs = jax.ShapeDtypeStruct((q,), np.dtype(np.int32), sharding=rep)
        return jax.jit(smapped).lower(params_s, pa_s, h0_s, qs, qs)

    def _ensure_compiled(self, q: int):
        if q not in self._compiled:
            self._compiled[q] = self.lower_bucket(q).compile()
            self.compile_count += 1
        return self._compiled[q]

    # --------------------------------------------------------------- query
    def query(self, qids) -> np.ndarray:
        """Serve one micro-batch of global vertex ids → ``(len(qids), nout)``
        f32 logits.  Stages are spanned (``SERVE_STAGES``); the batch is
        padded to its bucket so no size triggers a recompile."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._h0 is None:
            raise ValueError(
                "no features loaded — call set_features(features) before "
                "serving queries")
        qids = np.asarray(qids, dtype=np.int64).reshape(-1)
        nq = len(qids)
        if nq == 0:
            return np.zeros((0, self.widths[-1]), np.float32)
        with self.spans.span("serve:route"):
            owners, locals_ = self.router.lookup(qids)
        with self.spans.span("serve:batch"):
            bucket = self.batcher.bucket_for(nq)
            q_owner = np.full(bucket, -1, np.int32)    # pad: matches no chip
            q_local = np.zeros(bucket, np.int32)
            q_owner[:nq] = owners
            q_local[:nq] = locals_
            rep = NamedSharding(self.mesh, P())
            q_owner = jax.device_put(q_owner, rep)
            q_local = jax.device_put(q_local, rep)
        with self.spans.span("serve:compile_lookup"):
            prog = self._ensure_compiled(bucket)
        with self.spans.span("serve:forward"):
            out = prog(self.params, self.pa, self._h0, q_owner, q_local)
            out = np.asarray(out)                      # readback = sync
        return out[:nq]

    def warmup(self, qids) -> None:
        """Serve one throwaway batch per pre-compiled bucket (cycling
        ``qids`` to fill each).  A bucket's FIRST dispatch pays runtime
        autotuning even with an AOT program, and deadline flushes land on
        the small buckets — run this before a measured window or the
        overhead lands in the published p99."""
        qids = np.asarray(qids, dtype=np.int64).reshape(-1)
        if qids.size == 0:
            raise ValueError("warmup needs at least one query id")
        for b in self.batcher.buckets:
            self.query(np.resize(qids, b))

    # -------------------------------------------------------------- gauges
    @property
    def nlayers(self) -> int:
        return len(self.widths)

    def gauges(self) -> dict:
        """Analytic per-batch/per-query exchange gauges of the serving
        forward — plan-derived, deterministic (zero-band in the bench trend).
        The forward runs ``nlayers`` exchanges per micro-batch regardless of
        batch size, so the steady-state per-QUERY wire cost is the full-
        batch amortization ``nlayers · wire_rows/exchange ÷ max_batch``."""
        wire = self.plan.wire_rows_per_exchange(self.comm_schedule)
        true = int(self.plan.predicted_send_volume.sum())
        return {
            "comm_schedule": self.comm_schedule,
            "exchanges_per_batch": self.nlayers,
            "wire_rows_per_exchange": wire,
            "true_rows_per_exchange": true,
            "wire_rows_per_batch": self.nlayers * wire,
            "wire_rows_per_query": round(
                self.nlayers * wire / self.batcher.max_batch, 6),
            "buckets": list(self.batcher.buckets),
            "compiles": self.compile_count,
        }

    # ------------------------------------------------------------ recorder
    def attach_recorder(self, recorder) -> None:
        """Attach a ``RunRecorder``: stage spans become schema events and
        the transport decision lands in the manifest (the same
        reconstructibility contract as the trainers)."""
        self.recorder = recorder
        self.spans.recorder = recorder
        if self.comm_decision:
            recorder.set_comm_schedule(self.comm_decision)

    def record_window(self, result, offered_qps: float | None = None,
                      mode: str = "open") -> None:
        """Emit one schema-v3 ``serve`` event for a completed traffic
        window (``loadgen.ServeResult``) with the batching counters and the
        analytic wire gauge riding along."""
        if self.recorder is None:
            return
        g = self.gauges()
        self.recorder.record_serve(
            queries=result.queries,
            achieved_qps=result.achieved_qps,
            latency_p50_ms=result.p50_ms,
            latency_p95_ms=result.p95_ms,
            latency_p99_ms=result.p99_ms,
            window_s=result.window_s,
            offered_qps=offered_qps,
            mode=mode,
            batches=result.batches,
            mean_batch=result.mean_batch,
            deadline_flushes=self.batcher.deadline_flushes,
            full_flushes=self.batcher.full_flushes,
            latency_budget_ms=self.batcher.latency_budget_ms,
            compiles=self.compile_count,
            buckets=list(self.batcher.buckets),
            comm_schedule=self.comm_schedule,
            wire_rows_per_query=g["wire_rows_per_query"],
            # v4 additive: deadline-shed count of the window — present
            # only when shedding is configured, so pre-shedding events
            # keep their exact shape
            shed=(getattr(result, "shed", 0)
                  if self.batcher.shed_factor is not None else None),
            shed_factor=self.batcher.shed_factor,
        )
