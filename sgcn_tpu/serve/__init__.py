"""Serving subsystem: AOT-compiled partitioned inference (docs/serving.md).

The first non-training workload: ``ServeEngine`` loads a checkpoint + plan
(provenance-verified), AOT-compiles a forward-only per-partition step per
padded batch-size bucket, ``VertexRouter`` maps query vertex ids to owning
chips, ``MicroBatcher`` batches against a latency budget, and ``loadgen``
drives synthetic open/closed-loop traffic.  CLI: ``python -m sgcn_tpu.serve``.
"""

from .batcher import MicroBatcher, default_buckets
from .engine import (SERVE_STAGES, CheckpointWatcher, InFlightBatch,
                     ServeEngine)
from .loadgen import ServeResult, run_loadgen, synthetic_query_ids
from .router import SERVE_ROUTER_FIELDS, VertexRouter
from .subgraph import SERVE_SUBGRAPH_FIELDS, SubgraphIndex

__all__ = [
    "CheckpointWatcher", "InFlightBatch", "MicroBatcher",
    "SERVE_ROUTER_FIELDS", "SERVE_STAGES", "SERVE_SUBGRAPH_FIELDS",
    "ServeEngine", "ServeResult", "SubgraphIndex", "VertexRouter",
    "default_buckets", "run_loadgen", "synthetic_query_ids",
]
