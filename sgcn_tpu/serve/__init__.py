"""Serving subsystem: AOT-compiled partitioned inference (docs/serving.md).

The first non-training workload: ``ServeEngine`` loads a checkpoint + plan
(provenance-verified), AOT-compiles a forward-only per-partition step per
padded batch-size bucket, ``VertexRouter`` maps query vertex ids to owning
chips, ``MicroBatcher`` batches against a latency budget, and ``loadgen``
drives synthetic open/closed-loop traffic.  CLI: ``python -m sgcn_tpu.serve``.
"""

from .batcher import MicroBatcher, default_buckets
from .engine import SERVE_STAGES, ServeEngine
from .loadgen import ServeResult, run_loadgen, synthetic_query_ids
from .router import SERVE_ROUTER_FIELDS, VertexRouter

__all__ = [
    "MicroBatcher", "SERVE_ROUTER_FIELDS", "SERVE_STAGES", "ServeEngine",
    "ServeResult", "VertexRouter", "default_buckets", "run_loadgen",
    "synthetic_query_ids",
]
