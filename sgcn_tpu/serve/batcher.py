"""Latency-budgeted dynamic micro-batching with pre-compiled size buckets.

Two flush triggers, whichever fires first (the classic serving trade:
batching amortizes the per-dispatch cost, the deadline bounds what any one
query waits):

  * **max-batch** — ``submit`` returns the flushed batch the moment it holds
    ``max_batch`` queries;
  * **deadline** — ``poll(now)`` returns the pending batch once the OLDEST
    pending query has waited ``latency_budget_ms`` (age of the head of the
    queue, not the mean: the budget is a per-query promise).

**Deadline shedding** (graceful degradation, ``docs/resilience.md``): with
``shed_factor`` set, a flushed query whose age ALREADY exceeds
``latency_budget_ms × shed_factor`` at dispatch time is returned as an
explicit shed marker (``split_shed``) instead of being served — under
overload the p99 of SERVED queries stays honest and the shed count becomes
a first-class gauge (the v4 ``shed`` key of the serve event) rather than a
silent latency blow-out.  ``None`` (default) never sheds — the pre-existing
batcher exactly.

Shapes under jit are static, so a variable-size batch would recompile the
forward per distinct size — the engine instead pre-compiles a small ladder
of padded ``buckets`` (doubling up to ``max_batch`` by default) and every
flush is padded UP to the smallest covering bucket (``bucket_for``).  No
query count can therefore trigger a compile after warm-up; the engine's
``compile_count`` gauge and ``tests/test_serve.py`` hold that contract.

The clock is injected (``clock=``) so deadline behavior is deterministically
testable; nothing here touches jax.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


def default_buckets(max_batch: int) -> tuple[int, ...]:
    """Doubling bucket ladder 1, 2, 4, … capped and terminated at
    ``max_batch`` — log₂(max_batch) compiled programs cover every batch
    size with ≤ 2× padding."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


def pad_pow2(x: int, lo: int = 8) -> int:
    """The SAME doubling rule applied to one dynamic dimension: the
    smallest power of two ≥ ``max(x, 1)``, floored at ``lo``.  Sub-graph
    serving (``serve/subgraph.py``) pads every receptive-set dimension
    (per-degree-class row counts, edge counts, query count) through this,
    so each compile-key dimension takes at most ``log2`` distinct values
    and a repeated (or smaller) workload never recompiles — the batcher's
    bucket contract extended from query counts to receptive-set shapes."""
    x = max(int(x), 1)
    out = lo
    while out < x:
        out *= 2
    return out


@dataclass
class Pending:
    """One queued query: global vertex id + the arrival time its latency is
    measured from."""

    qid: int
    t_arrival: float


@dataclass
class MicroBatcher:
    """See module docstring.  ``buckets`` must cover ``max_batch``."""

    max_batch: int = 64
    latency_budget_ms: float = 50.0
    buckets: tuple = None
    clock: object = time.monotonic
    # deadline shedding (module docstring): shed queries older than
    # budget × shed_factor at dispatch; None = never shed
    shed_factor: float | None = None
    # flush counters — the serve event's batching gauges
    full_flushes: int = 0
    deadline_flushes: int = 0
    shed_count: int = 0
    _pending: list = field(default_factory=list)

    def __post_init__(self):
        if self.buckets is None:
            self.buckets = default_buckets(self.max_batch)
        self.buckets = tuple(sorted(int(b) for b in self.buckets))
        if any(b < 1 for b in self.buckets):
            raise ValueError(f"buckets must be positive: {self.buckets}")
        if self.buckets[-1] < self.max_batch:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} below max_batch "
                f"{self.max_batch} — a full flush would have no compiled "
                "program to run on")
        if self.latency_budget_ms < 0:
            raise ValueError(
                f"latency_budget_ms must be >= 0, got "
                f"{self.latency_budget_ms}")
        if self.shed_factor is not None and self.shed_factor < 1:
            raise ValueError(
                f"shed_factor must be >= 1 (shedding below the deadline "
                f"flush itself would drop queries the budget still "
                f"covers), got {self.shed_factor}")

    def bucket_for(self, nqueries: int) -> int:
        """Smallest pre-compiled bucket covering ``nqueries``."""
        for b in self.buckets:
            if b >= nqueries:
                return b
        raise ValueError(
            f"batch of {nqueries} exceeds the largest bucket "
            f"{self.buckets[-1]} (max_batch {self.max_batch})")

    def submit(self, qid: int, t_arrival: float | None = None):
        """Queue one query; returns the flushed batch (list of ``Pending``)
        when this submit fills ``max_batch``, else ``None``."""
        t = self.clock() if t_arrival is None else float(t_arrival)
        self._pending.append(Pending(int(qid), t))
        if len(self._pending) >= self.max_batch:
            self.full_flushes += 1
            return self._take()
        return None

    def next_deadline(self) -> float | None:
        """Absolute clock time the pending head's budget expires (None when
        nothing is pending) — what a loadgen sleeps toward."""
        if not self._pending:
            return None
        return self._pending[0].t_arrival + self.latency_budget_ms / 1e3

    def poll(self, now: float | None = None):
        """Deadline flush: the pending batch once the oldest query's wait
        reaches the budget, else ``None``."""
        if not self._pending:
            return None
        now = self.clock() if now is None else float(now)
        if now >= self.next_deadline():
            self.deadline_flushes += 1
            return self._take()
        return None

    def flush(self):
        """Unconditional drain (end of a traffic window); ``None`` if empty.
        Not a deadline flush — counters stay untouched."""
        return self._take() if self._pending else None

    def split_shed(self, batch, now: float | None = None):
        """Partition a flushed batch into ``(dispatch, shed)`` at dispatch
        time: queries whose age already exceeds
        ``latency_budget_ms × shed_factor`` are shed — an explicit marker
        the caller returns to the client instead of a silently late
        result.  With ``shed_factor=None`` every query dispatches (the
        pre-shedding behavior, counters untouched)."""
        if self.shed_factor is None or not batch:
            return batch, []
        now = self.clock() if now is None else float(now)
        cutoff = self.latency_budget_ms * self.shed_factor / 1e3
        keep = [p for p in batch if now - p.t_arrival <= cutoff]
        shed = [p for p in batch if now - p.t_arrival > cutoff]
        self.shed_count += len(shed)
        return keep, shed

    def __len__(self) -> int:
        return len(self._pending)

    def _take(self):
        out, self._pending = self._pending, []
        return out
