"""Synthetic closed/open-loop query traffic over a ``ServeEngine``.

Two generator modes, the standard serving-bench pair:

  * **open loop** (``offered_qps > 0``): queries arrive on a fixed schedule
    ``t_i = t0 + i/qps`` regardless of how fast the server drains them — the
    honest overload model (a slow server accumulates queue delay instead of
    silently throttling its own offered load).  Latency is measured from the
    SCHEDULED arrival, so queue time counts.
  * **closed loop** (``offered_qps`` None/0): the next query is submitted as
    soon as the batcher accepts it — the saturation probe; achieved QPS is
    then the engine's ceiling at this batch shape.

The loop drives the ``MicroBatcher`` exactly as a server would: submit on
arrival, execute on a max-batch flush, and sleep toward whichever comes
first of the next arrival and the pending head's deadline.  The tail is
mode-split: an OPEN-loop tail still honors the latency budget (a real
server cannot know the trace ended, so the pending batch deadline-flushes
like any other), while a CLOSED-loop tail drains immediately with an
ordinary flush (the generator knows no further query is coming, so waiting
out the budget would only deflate the ceiling QPS and inflate p99).
Clock/sleep are injectable for deterministic tests.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np


def synthetic_query_ids(n: int, count: int, seed: int = 0,
                        skew: float = 0.0) -> np.ndarray:
    """``count`` query vertex ids over ``[0, n)``.  ``skew=0`` is uniform;
    ``skew>0`` draws from a Zipf-like power law over a random vertex
    permutation (real serving traffic concentrates on hub entities — the
    skewed mode exercises co-location batching)."""
    rng = np.random.default_rng(seed)
    if skew <= 0:
        return rng.integers(0, n, size=count, dtype=np.int64)
    ranks = rng.permutation(n)
    weights = 1.0 / np.arange(1, n + 1, dtype=np.float64) ** skew
    weights /= weights.sum()
    return ranks[rng.choice(n, size=count, p=weights)].astype(np.int64)


@dataclass
class ServeResult:
    """Measured outcome of one traffic window.  ``shed`` counts the
    queries the batcher's deadline shedding returned as explicit markers
    instead of serving (``MicroBatcher.split_shed``) — shed queries appear
    in NO latency quantile: the published p50/p95/p99 describe served
    queries only, which is the point of shedding."""

    latencies_ms: list = field(default_factory=list)
    window_s: float = 0.0
    batches: int = 0
    batch_sizes: list = field(default_factory=list)
    shed: int = 0

    @property
    def queries(self) -> int:
        return len(self.latencies_ms)

    @property
    def achieved_qps(self) -> float:
        return self.queries / self.window_s if self.window_s > 0 else 0.0

    @property
    def mean_batch(self) -> float:
        return (sum(self.batch_sizes) / len(self.batch_sizes)
                if self.batch_sizes else 0.0)

    def _pct(self, p: float) -> float:
        if not self.latencies_ms:
            return 0.0
        return float(np.percentile(np.asarray(self.latencies_ms), p))

    @property
    def p50_ms(self) -> float:
        return self._pct(50)

    @property
    def p95_ms(self) -> float:
        return self._pct(95)

    @property
    def p99_ms(self) -> float:
        return self._pct(99)

    def summary(self) -> dict:
        out = {
            "queries": self.queries,
            "window_s": round(self.window_s, 6),
            "achieved_qps": round(self.achieved_qps, 3),
            "latency_p50_ms": round(self.p50_ms, 3),
            "latency_p95_ms": round(self.p95_ms, 3),
            "latency_p99_ms": round(self.p99_ms, 3),
            "batches": self.batches,
            "mean_batch": round(self.mean_batch, 3),
        }
        if self.shed:
            out["shed"] = self.shed
        return out


def run_loadgen(engine, qids, offered_qps: float | None = None,
                clock=time.monotonic, sleep=time.sleep,
                concurrent: bool = False) -> ServeResult:
    """Drive ``engine`` (and its batcher) through ``qids``; see module
    docstring for the open/closed-loop semantics.

    ``concurrent=True`` enables DOUBLE-BUFFERED dispatch: batch t+1 is
    routed/packed/submitted (``engine.submit`` — JAX async dispatch) while
    batch t's device program is still running, and t's result is consumed
    only after t+1 is in flight — host batching leaves the critical path.
    The submit-while-in-flight section is spanned ``serve:overlap`` so the
    PR-7 trace parser can measure the overlap it names.  At most one batch
    is in flight behind the current one, results are consumed strictly in
    submission order, and a query's latency still ends when ITS batch's
    result is consumed (queue + overlap wait both count — the honest
    figure)."""
    import contextlib

    qids = np.asarray(qids, dtype=np.int64).reshape(-1)
    batcher = engine.batcher
    res = ServeResult()
    t0 = clock()
    inflight: list = []                  # [(handle, batch)] — ≤ 1 deep

    def account(batch):
        done = clock()
        for p in batch:
            res.latencies_ms.append((done - p.t_arrival) * 1e3)
        res.batches += 1
        res.batch_sizes.append(len(batch))

    def resolve_one():
        handle, batch = inflight.pop(0)
        handle.result()
        account(batch)

    def execute(batch):
        if not batch:
            return
        # deadline shedding (batcher.split_shed): overdue queries become
        # explicit shed markers — they are counted, never served, and
        # never enter the latency quantiles
        batch, shed = batcher.split_shed(batch, clock())
        res.shed += len(shed)
        if not batch:
            return
        if not concurrent:
            engine.query([p.qid for p in batch])
            account(batch)
            return
        spans = getattr(engine, "spans", None)
        cm = (spans.span("serve:overlap") if spans is not None and inflight
              else contextlib.nullcontext())
        with cm:
            handle = engine.submit([p.qid for p in batch])
        inflight.append((handle, batch))
        if len(inflight) > 1:
            resolve_one()

    i = 0
    total = len(qids)
    while i < total or len(batcher):
        now = clock()
        next_arrival = (t0 + i / offered_qps if (offered_qps and i < total)
                        else (now if i < total else None))
        deadline = batcher.next_deadline()
        if next_arrival is not None and (deadline is None
                                         or next_arrival <= deadline):
            if next_arrival > now:
                sleep(next_arrival - now)
            batch = batcher.submit(int(qids[i]), t_arrival=next_arrival)
            i += 1
            execute(batch)
        elif deadline is not None and offered_qps:
            # open-loop tail (or an arrival gap): the budget is still the
            # flush trigger — the server cannot know the trace ended
            if deadline > now:
                sleep(deadline - now)
            execute(batcher.poll(clock()))
        elif deadline is not None:
            # closed-loop tail: no future arrival can fill the batch, so
            # drain now (ordinary flush — not a deadline miss)
            execute(batcher.flush())
        else:                            # i == total, nothing pending
            break
    while inflight:                      # drain the double-buffer tail
        resolve_one()
    res.window_s = clock() - t0
    return res
