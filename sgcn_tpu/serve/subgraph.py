"""L-hop induced sub-graph serving: receptive sets, fold recipes, compact
forwards (phase 2 of ``docs/serving.md``).

PR-8's engine recomputes the FULL partitioned forward for every
micro-batch — correct, but graph-proportional: the per-query FLOP bill is
``k·B·L`` computed rows regardless of how few vertices the batch names.  A
routed batch of query vertices has an exactly-L-hop receptive field, so
this module makes serving QUERY-proportional:

  * :class:`SubgraphIndex` (built once per plan) re-expresses every chip's
    per-row fold recipe in GLOBAL row space: for each vertex, the ordered
    (source, weight) slot sequence of its owner chip's ELL row (ALL
    ``wb`` slots of its degree bucket, weight-0 padding included), its
    local-tail and halo-edge lists (GCN), or its combined cell slots and
    hub-tail edges (GAT).  Orders are taken verbatim from the plan arrays
    — the halo family is (dst, round, recv-pos)-sorted at plan build time,
    which is what makes one recipe valid for BOTH the a2a and ragged
    schedules (the two transports already fold every row in that same
    sequence, the PR-4 bit-parity contract).
  * :meth:`SubgraphIndex.receptive` computes, per chip, the L-hop closed
    neighborhood of that chip's routed queries (``VertexRouter.route`` —
    this is where the router's co-location grouping becomes load-bearing:
    queries sharing a chip share receptive rows, so routed batches spill
    less).
  * :func:`build_batch` compacts the recipes onto the receptive set:
    per-chip padded tables in a compact row space ordered BY DEGREE-BUCKET
    CLASS (each row keeps its original bucket width), padded to
    doubling-ladder buckets (:func:`pad_pow2`) so neither query count nor
    receptive-set size ever recompiles the program.  The last class always
    carries at least one padding row; the FINAL compact row is the all-zero
    dump row every padding slot/edge points at.
  * :func:`subgraph_forward_gcn` / :func:`subgraph_forward_gat` run the
    compact forward per chip with NO inter-chip exchange: every source row
    a chip needs is computed locally from host-gathered input features, and
    the only collective in the program is the final logit-gather ``psum``
    (the audited contract of the ``serve_subgraph`` analysis mode).

**Bit-identity contract.**  Routed logits are f32-bit-identical (``==``) to
the trainer's ``evaluate()`` because every per-row reduction reproduces the
full program's per-row addition sequence AND op structure exactly:

  * the compact aggregations call the REAL kernels (``ops.pspmm.spmm_ell``
    / ``spmm_local``, ``models.gat._edge_pass`` slot passes) on compact
    bucket structures whose per-row chain lengths equal the full
    program's.  Chain-length fidelity is not pedantry: XLA:CPU contracts
    multiply-add chains into FMAs opportunistically per compiled shape, so
    a row folded through a LONGER (or zero-seeded) chain can round
    differently by an ulp even though the math is identical — measured on
    the 48-vertex fixture, and the reason each row keeps all ``wb`` slots
    of its original degree bucket (a weight-0 slot is exact under any
    contraction: ``fma(0, x, acc) = acc`` for finite ``x``);
  * dense projections are ordinary ``(M, K) @ (K, N)`` matmuls, whose
    per-row bits are position- and M-independent on this backend for
    ``N ≥ 2`` (measured; the one exception — the attention score matvec —
    was moved to the row-local ``models.gat.score_project`` form for
    exactly this reason);
  * the GAT per-layer softmax stabilizer ``cg`` is supplied as an INPUT —
    it is a full-graph ``pmax`` the compact program cannot derive, but it
    is constant per (params, features), so the engine precomputes it once
    per weight swap (``gat_forward_local(collect_stabilizers=True)``);
  * remote-sourced GCN contributions take the ``halo_dtype`` wire
    round-trip cast when the engine narrows the wire.

Differences confined to padding arithmetic can flip only the SIGN of a
zero, which ``==`` treats as equal; rows on the receptive set's outer
shell are computed with incomplete neighborhoods and may hold garbage, but
no complete row (and no query) ever reads them — consumers gather strictly
inside the previous level's closed neighborhood.  Two full-program regimes
are out of the compact mirror's scope and documented rather than silently
wrong: the Pallas VMEM aggregator (the engine refuses subgraph mode under
it) and the products-scale GAT paths (``_ONED_U_ROWS`` denominator form,
chunked hub tails) whose branch points depend on full-table sizes.

Everything host-side here is numpy; the forward functions are per-chip jax
code the engine wraps in ``shard_map``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

# the ONE doubling-ladder rule, shared with the query-count buckets —
# each compact-array dimension takes at most log2 distinct values, so a
# repeated (or smaller) workload never recompiles
from .batcher import pad_pow2

# CommPlan fields the sub-graph index reads (host-side, full square plan) —
# registered in analysis/registry.py like every consumer tuple.  The
# per-chip fold arrays are read on the HOST to build global recipes; the
# GAT cell family is materialized by ensure_cell() first.
SERVE_SUBGRAPH_FIELDS = (
    "owner", "local_idx", "send_idx", "halo_src",
    "ell_idx", "ell_w", "ltail_dst", "ltail_src", "ltail_w",
    "hedge_dst", "hedge_src", "hedge_w",
    "cell_idx", "cell_w", "ctail_dst", "ctail_src", "ctail_w",
)




def _row_class_table(buckets) -> tuple:
    """Per-LOCAL-row (class, width) of one bucketed width-major layout."""
    cls = []
    wid = []
    for j, (nb, wb) in enumerate(buckets):
        cls += [j] * nb
        wid += [wb] * nb
    return np.asarray(cls, np.int8), np.asarray(wid, np.int32)


def _row_slot_lists(flat_idx, flat_w, buckets, full: bool):
    """Per-row (srcs, ws) of one chip's bucketed width-major layout, in
    slot order.  ``full=True`` keeps every slot of the row's bucket width
    (weight-0 padding included — the chain-length contract of the module
    docstring); ``full=False`` keeps only real (weight ≠ 0) slots (the
    adjacency/gauge view).  Returns ``(counts (B,), srcs, ws)`` with the
    kept entries concatenated row-major."""
    counts, srcs, ws = [], [], []
    off = 0
    for nb, wb in buckets:
        blk_i = flat_idx[off: off + nb * wb].reshape(wb, nb).T  # (nb, wb)
        blk_w = flat_w[off: off + nb * wb].reshape(wb, nb).T
        keep = (np.ones_like(blk_w, bool) if full else blk_w != 0)
        counts.append(keep.sum(axis=1))
        srcs.append(blk_i[keep])        # row-major flatten = slot order
        ws.append(blk_w[keep])
        off += nb * wb
    return (np.concatenate(counts), np.concatenate(srcs),
            np.concatenate(ws))


def _csr_from_rows(n: int, row_glob, src_glob, w):
    """Assemble a global CSR from (row, src, w) triples whose per-row
    relative order must be preserved (stable sort by row)."""
    order = np.argsort(row_glob, kind="stable")
    row_s = row_glob[order]
    counts = np.bincount(row_s, minlength=n)
    ptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=ptr[1:])
    return ptr, src_glob[order].astype(np.int64), w[order].astype(np.float32)


class SubgraphIndex:
    """Host-side per-row fold recipes in GLOBAL row space (one per plan)."""

    def __init__(self, plan, model: str = "gcn"):
        if model not in ("gcn", "gat"):
            raise ValueError(f"unknown model {model!r}")
        if model == "gcn" and not plan.symmetric:
            raise ValueError(
                "sub-graph serving reproduces the symmetric ELL fold "
                "(spmm_ell + halo-edge family); this plan is asymmetric — "
                "serve with the full-forward engine")
        self.model = model
        self.n = int(plan.n)
        self.k = int(plan.k)
        glob = plan.global_row_ids()            # (k, B), -1 pad
        k, b = self.k, plan.b

        if model == "gcn":
            self.buckets = tuple(plan.ell_buckets)
            slot_arrays = (plan.ell_idx, plan.ell_w)
            tail_fams = (("ltail_dst", "ltail_src", "ltail_w"),
                         ("hedge_dst", "hedge_src", "hedge_w"))
            src_is_combined = False
        else:
            plan.ensure_cell()
            self.buckets = tuple(plan.cell_buckets)
            slot_arrays = (plan.cell_idx, plan.cell_w)
            tail_fams = (("ctail_dst", "ctail_src", "ctail_w"),)
            src_is_combined = True
        halo_glob = plan.halo_global_rows()     # (k, R), -1 pad
        full_glob = (np.concatenate([glob, halo_glob], axis=1)
                     if src_is_combined else None)
        row_cls, _ = _row_class_table(self.buckets)

        sr, ss, sw = [], [], []                 # FULL slot chains
        ar, asrc = [], []                       # real-edge adjacency
        fams = [([], [], []) for _ in tail_fams]
        cls_rows, cls_vals = [], []
        for c in range(k):
            g = glob[c]
            real = g >= 0
            cnt, srcs, ws = _row_slot_lists(
                np.asarray(slot_arrays[0][c]), np.asarray(slot_arrays[1][c]),
                self.buckets, full=True)
            rows = np.repeat(np.arange(b), cnt)
            keep = real[rows]
            src_map = full_glob[c] if src_is_combined else g
            sr.append(g[rows[keep]])
            ss.append(src_map[srcs[keep]])
            sw.append(ws[keep])
            cls_rows.append(g[real])
            cls_vals.append(row_cls[real])
            # real-edge view (adjacency + gauges): weight-0 slots dropped
            rk = keep & (ws != 0)
            ar.append(g[rows[rk]])
            asrc.append(src_map[srcs[rk]])
            for fam, (fr, fs, fw) in zip(tail_fams, fams):
                d = np.asarray(getattr(plan, fam[0])[c])
                s = np.asarray(getattr(plan, fam[1])[c])
                w = np.asarray(getattr(plan, fam[2])[c])
                fmap = (src_map if src_is_combined else
                        (g if fam[0] == "ltail_dst" else halo_glob[c]))
                fkeep = (w != 0) & real[d]
                fr.append(g[d[fkeep]])
                fs.append(fmap[s[fkeep]])
                fw.append(w[fkeep])
        self.slots = _csr_from_rows(self.n, np.concatenate(sr),
                                    np.concatenate(ss), np.concatenate(sw))
        self.tails = [
            _csr_from_rows(self.n, np.concatenate(fr), np.concatenate(fs),
                           np.concatenate(fw))
            for fr, fs, fw in fams]
        # per-global-row degree-bucket class (the chain-length contract)
        self.row_class = np.zeros(self.n, np.int8)
        self.row_class[np.concatenate(cls_rows)] = np.concatenate(cls_vals)
        adj_rows = [np.concatenate(ar)]
        adj_srcs = [np.concatenate(asrc)]
        for fr, fs, _fw in fams:
            adj_rows.append(np.concatenate(fr))
            adj_srcs.append(np.concatenate(fs))
        adj_rows = np.concatenate(adj_rows)
        adj_srcs = np.concatenate(adj_srcs)
        self.adj = _csr_from_rows(
            self.n, adj_rows, adj_srcs,
            np.zeros(len(adj_srcs), np.float32))[:2]

    # ------------------------------------------------------------ receptive
    def receptive(self, qids, nhops: int) -> np.ndarray:
        """Sorted global ids of the ``nhops``-hop CLOSED neighborhood of
        ``qids`` (the rows a ``nhops``-layer forward for these queries
        touches)."""
        ptr, src = self.adj
        u = np.unique(np.asarray(qids, dtype=np.int64))
        for _ in range(nhops):
            cnt = ptr[u + 1] - ptr[u]
            tot = int(cnt.sum())
            if tot == 0:
                break
            flat = (np.repeat(ptr[u], cnt)
                    + np.arange(tot) - np.repeat(np.cumsum(cnt) - cnt, cnt))
            u = np.unique(np.concatenate([u, src[flat]]))
        return u

    def edges_in(self, rows: np.ndarray) -> int:
        """True recipe edges folded when computing ``rows`` (the analytic
        per-batch SpMM-work gauge — real edges only, padding slots
        excluded)."""
        ptr, src = self.adj
        return int((ptr[rows + 1] - ptr[rows]).sum())


def _take_rows(csr, rows):
    """``(counts, srcs, ws)`` of ``rows`` from a global CSR, per-row order
    preserved, concatenated row-major."""
    ptr, src, w = csr
    cnt = ptr[rows + 1] - ptr[rows]
    tot = int(cnt.sum())
    flat = (np.repeat(ptr[rows], cnt)
            + np.arange(tot) - np.repeat(np.cumsum(cnt) - cnt, cnt))
    return cnt, src[flat], w[flat]


@dataclass
class SubgraphBatch:
    """One routed batch's compact device inputs + analytic gauges."""

    key: tuple                   # static shape key → compiled program
    arrays: dict = field(default_factory=dict)   # name → (k, ...) stacked
    q_owner: np.ndarray = None   # (Qb,) i32, −1 pad
    q_pos: np.ndarray = None     # (Qb,) i32 position in owner's compact set
    nq: int = 0
    touched_rows: int = 0        # Σ_c |U_c| (true, unpadded)
    recipe_edges: int = 0        # Σ_c true edges folded
    per_chip_rows: tuple = ()


def _compact_layout(index: SubgraphIndex, sets, class_pads):
    """Per-chip compact ordering: rows grouped by degree-bucket class (the
    plan's bucket order), padded to the shared ``class_pads`` counts.
    Returns per chip ``(compact_rows, pos_map)`` where ``pos_map`` maps a
    global id to its compact index (dump row for ids outside the set)."""
    total = int(sum(class_pads))
    dump = total - 1
    out = []
    for u in sets:
        cls = index.row_class[u] if len(u) else np.zeros(0, np.int8)
        pos_map = np.full(index.n, dump, np.int32)
        compact = np.full(total, -1, np.int64)
        off = 0
        for j, pad in enumerate(class_pads):
            rows_j = u[cls == j]
            compact[off: off + len(rows_j)] = rows_j
            pos_map[rows_j] = off + np.arange(len(rows_j), dtype=np.int32)
            off += pad
        out.append((compact, pos_map))
    return out, dump


def _class_counts(index: SubgraphIndex, u) -> np.ndarray:
    m = len(index.buckets)
    if not len(u):
        return np.zeros(m, np.int64)
    return np.bincount(index.row_class[u], minlength=m)


def _pack_slots(index, u, compact, pos_map, class_pads):
    """Flat WIDTH-MAJOR compact slot arrays mirroring the plan's bucketed
    layout at compact class counts: class ``j`` stores slot ``t`` of its
    ``class_pads[j]`` rows contiguously — exactly the shape
    ``ops.pspmm.bucketed_slot_reduce`` (via ``spmm_ell`` / the GAT slot
    passes) consumes, so the compiled fold has the full program's per-row
    chain structure."""
    widths = [wb for _, wb in index.buckets]
    total_slots = int(sum(p * w for p, w in zip(class_pads, widths)))
    dump = int(sum(class_pads)) - 1
    flat_i = np.full(total_slots, dump, np.int32)
    flat_w = np.zeros(total_slots, np.float32)
    off = row0 = 0
    for j, (pad, wb) in enumerate(zip(class_pads, widths)):
        rows_j = compact[row0: row0 + pad]
        real = rows_j >= 0
        rj = rows_j[real]
        if len(rj):
            cnt, srcs, ws = _take_rows(index.slots, rj)
            if not (cnt == wb).all():
                raise ValueError(
                    f"class-{j} recipe rows carry {set(cnt.tolist())} slots, "
                    f"bucket width is {wb} — the index and the plan's "
                    "bucket structure drifted")
            blk_i = pos_map[srcs].reshape(len(rj), wb)
            blk_w = ws.reshape(len(rj), wb)
            ri = np.nonzero(real)[0]
            for t in range(wb):
                flat_i[off + t * pad + ri] = blk_i[:, t]
                flat_w[off + t * pad + ri] = blk_w[:, t]
        off += pad * wb
        row0 += pad
    return flat_i, flat_w


def _pack_edges(csr, u, compact, pos_map, pad_to: int, dump: int):
    """Compact dst-sorted edge list ``(dst, src, w)`` padded to ``pad_to``
    (padding edges: dst = src = dump row, weight 0 — the dump row is the
    LAST compact row, so ``indices_are_sorted`` stays true)."""
    dst = np.full(pad_to, dump, np.int32)
    src = np.full(pad_to, dump, np.int32)
    w = np.zeros(pad_to, np.float32)
    real = compact >= 0
    rows = compact[real]
    if len(rows):
        cnt, srcs, ws = _take_rows(csr, rows)
        tot = int(cnt.sum())
        if tot > pad_to:
            raise ValueError(f"edge list {tot} exceeds pad {pad_to}")
        dst[:tot] = np.repeat(np.nonzero(real)[0], cnt).astype(np.int32)
        src[:tot] = pos_map[srcs]
        w[:tot] = ws
    return dst, src, w


def build_batch(index: SubgraphIndex, router, features: np.ndarray,
                qids, nhops: int, edge_lo: int = 16,
                rows_lo: int = 2) -> SubgraphBatch:
    """Route ``qids``, compute per-chip receptive sets, compact the
    recipes, pad to ladder buckets; see module docstring."""
    qids = np.asarray(qids, dtype=np.int64).reshape(-1)
    owners, _ = router.lookup(qids)
    by_chip = router.route(qids)
    sets = [index.receptive(by_chip[c], nhops) if c in by_chip
            else np.zeros(0, np.int64) for c in range(index.k)]
    counts = np.stack([_class_counts(index, u) for u in sets]).max(axis=0)
    m = len(index.buckets)
    class_pads = tuple(
        pad_pow2(int(counts[j]) + (1 if j == m - 1 else 0), rows_lo)
        for j in range(m))
    layout, dump = _compact_layout(index, sets, class_pads)
    total = int(sum(class_pads))
    feats = np.zeros((index.k, total, features.shape[1]), np.float32)
    valid = np.zeros((index.k, total), np.float32)
    for c, (compact, _) in enumerate(layout):
        real = compact >= 0
        feats[c, real] = features[compact[real]]
        valid[c, real] = 1.0
    arrays = {"feats": feats, "valid": valid}
    slot = [_pack_slots(index, u, compact, pos_map, class_pads)
            for u, (compact, pos_map) in zip(sets, layout)]
    tname = "slots" if index.model == "gcn" else "cell"
    arrays[f"{tname}_idx"] = np.stack([s[0] for s in slot])
    arrays[f"{tname}_w"] = np.stack([s[1] for s in slot])
    fam_names = (("tail", "rem") if index.model == "gcn" else ("ctail",))
    epads = []
    for csr, name in zip(index.tails, fam_names):
        ep = pad_pow2(max(
            (int(_take_rows(csr, compact[compact >= 0])[0].sum())
             if (compact >= 0).any() else 0)
            for compact, _ in layout), edge_lo)
        epads.append(ep)
        packed = [_pack_edges(csr, u, compact, pos_map, ep, dump)
                  for u, (compact, pos_map) in zip(sets, layout)]
        arrays[f"{name}_dst"] = np.stack([p[0] for p in packed])
        arrays[f"{name}_src"] = np.stack([p[1] for p in packed])
        arrays[f"{name}_w"] = np.stack([p[2] for p in packed])
    qb = pad_pow2(len(qids), 1)
    key = (index.model, qb) + class_pads + tuple(epads)
    q_owner = np.full(qb, -1, np.int32)
    q_pos = np.zeros(qb, np.int32)
    q_owner[:len(qids)] = owners
    for i, (g, c) in enumerate(zip(qids, owners)):
        q_pos[i] = int(layout[c][1][g])
    return SubgraphBatch(
        key=key, arrays=arrays, q_owner=q_owner, q_pos=q_pos, nq=len(qids),
        touched_rows=int(sum(len(u) for u in sets)),
        recipe_edges=int(sum(index.edges_in(u) for u in sets if len(u))),
        per_chip_rows=tuple(len(u) for u in sets))


def representative_key(index: SubgraphIndex, qb: int = 8,
                       rows_lo: int = 2, edge_lo: int = 16) -> tuple:
    """A smallest-ladder shape key for ``index`` — what the static-analysis
    audit lowers (``ServeEngine.lower_subgraph``): the module is identical
    for every key up to array extents, and the audited contract
    (collective census / donation / host callbacks) is extent-independent."""
    m = len(index.buckets)
    class_pads = tuple(pad_pow2(2 if j == m - 1 else 1, rows_lo)
                       for j in range(m))
    n_fams = 2 if index.model == "gcn" else 1
    return (index.model, qb) + class_pads + (edge_lo,) * n_fams


def key_buckets(index: SubgraphIndex, key: tuple) -> tuple:
    """The compact ``((nb, wb), ...)`` bucket structure one shape key
    compiles — class pads from the key × the plan's bucket widths (the
    static argument of the compact slot passes)."""
    m = len(index.buckets)
    class_pads = key[2: 2 + m]
    return tuple((int(p), int(wb))
                 for p, (_, wb) in zip(class_pads, index.buckets))


def batch_struct(index: SubgraphIndex, key: tuple, fin: int) -> dict:
    """ShapeDtypeStruct-shaped numpy zeros for one shape key — what
    ``ServeEngine.lower_subgraph`` feeds ``.lower()`` so the audited module
    is exactly the program a real batch of this key runs."""
    k = index.k
    m = len(index.buckets)
    class_pads = key[2: 2 + m]
    epads = key[2 + m:]
    total = int(sum(class_pads))
    slots = int(sum(p * wb for p, (_, wb) in zip(class_pads,
                                                 index.buckets)))
    tname = "slots" if index.model == "gcn" else "cell"
    out = {"feats": np.zeros((k, total, fin), np.float32),
           "valid": np.zeros((k, total), np.float32),
           f"{tname}_idx": np.zeros((k, slots), np.int32),
           f"{tname}_w": np.zeros((k, slots), np.float32)}
    fam_names = (("tail", "rem") if index.model == "gcn" else ("ctail",))
    for name, ep in zip(fam_names, epads):
        out[f"{name}_dst"] = np.zeros((k, int(ep)), np.int32)
        out[f"{name}_src"] = np.zeros((k, int(ep)), np.int32)
        out[f"{name}_w"] = np.zeros((k, int(ep)), np.float32)
    return out


# ---------------------------------------------------------------- forwards
def subgraph_forward_gcn(params, feats, arrays, buckets,
                         activation: str, final_activation: str,
                         halo_dtype=None):
    """Per-chip compact GCN forward over the receptive set (no exchange).

    Mirrors ``gcn_forward_local``'s layer loop (project-first rule,
    activations) by calling the REAL kernels on the compact tables:
    ``spmm_ell`` for the bucketed slot chain + local tail,
    ``spmm_local`` for the halo-edge family (remote sources taking the
    ``halo_dtype`` wire round-trip), combined exactly as
    ``_pspmm_ell_once`` combines them: ``z = local + remote``."""
    from ..models.activations import get_activation
    from ..models.gcn import PROJECT_FIRST_MIN_FIN
    from ..ops.pspmm import spmm_ell, spmm_local

    act = get_activation(activation)
    fact = get_activation(final_activation)
    nl = len(params)
    h = feats                                   # (T, fin)
    for i, w in enumerate(params):
        project_first = (w.shape[1] < h.shape[1]
                         and h.shape[1] >= PROJECT_FIRST_MIN_FIN)
        x = (h @ w) if project_first else h
        local = spmm_ell(arrays["slots_idx"], arrays["slots_w"],
                         arrays["tail_dst"], arrays["tail_src"],
                         arrays["tail_w"], x, buckets)
        xr = (x.astype(halo_dtype).astype(x.dtype)
              if halo_dtype is not None else x)
        remote = spmm_local(arrays["rem_dst"], arrays["rem_src"],
                            arrays["rem_w"], xr, x.shape[0])
        z = local + remote
        if not project_first:
            z = z @ w
        h = fact(z) if i == nl - 1 else act(z)
    return h


def subgraph_forward_gat(params, cgs, feats, arrays, buckets,
                         activation: str, final_activation: str):
    """Per-chip compact GAT forward over the receptive set (no exchange,
    no pmax — the per-layer stabilizers arrive as the ``cgs`` input).

    Mirrors ``_gat_factored_fwd_core`` at f32 by calling the REAL slot
    passes (``_mask_slot_pass`` / ``_pair_slot_pass`` via
    ``gat_table_form(fout, None)`` — the serve engine has no compute_dtype
    lever) on the compact cell tables.  ``valid`` pins the pad/dump rows'
    score at the stabilizer (``u = 1``): ``exp(−cg)`` can overflow for a
    very negative global max, and a NaN pad-table row would poison every
    masked gather that points at it."""
    import jax.numpy as jnp

    from ..models.activations import get_activation
    from ..models.gat import (_mask_slot_pass, _pair_slot_pass,
                              gat_table_form, score_project)

    act = get_activation(activation)
    fact = get_activation(final_activation)
    nl = len(params)
    h = feats
    rows = h.shape[0]
    valid = arrays["valid"]
    for i, p in enumerate(params):
        z = h @ p["w"]
        fout = z.shape[-1]
        z2 = score_project(z, p["a2"])
        z2 = jnp.where(valid > 0, z2, cgs[i])   # pad rows: u = exp(0) = 1
        u = jnp.exp(z2.astype(jnp.float32) - cgs[i])
        form = gat_table_form(fout, None)
        pfeat = u.astype(z.dtype)[:, None] * z
        if form == "fused":
            table = jnp.concatenate(
                [pfeat, u.astype(z.dtype)[:, None]], axis=-1)
            num, den = _mask_slot_pass(
                table, fout, arrays["cell_idx"], arrays["cell_w"],
                arrays["ctail_dst"], arrays["ctail_src"],
                arrays["ctail_w"], buckets, rows)
        else:
            num, den = _pair_slot_pass(
                pfeat, u.astype(z.dtype), fout, arrays["cell_idx"],
                arrays["cell_w"], arrays["ctail_dst"],
                arrays["ctail_src"], arrays["ctail_w"], buckets, rows)
        out = num / jnp.maximum(den, 1e-30)[:, None]
        h = fact(out) if i == nl - 1 else act(out)
        if i < nl - 1:
            h = h.astype(p["w"].dtype)          # f32 no-op (engine is f32)
    return h
