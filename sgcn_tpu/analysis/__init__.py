"""Static-analysis subsystem: compiled-program contract audit + AST
hot-path hygiene (``docs/static_analysis.md``).

The reference earns its overlap guarantee structurally (Irecv → local
SpMM → Waitany); ours lives in compiled XLA programs, where a silent
dispatch regression — an extra ``all_to_all``, an f32 wire under
``--halo-dtype bfloat16``, a dropped donation, a host callback inside a
step — passes every loss-parity test while destroying the TPU-relevant
wins.  This package makes those contracts machine-checked:

  * :mod:`~sgcn_tpu.analysis.modes` — the mode-matrix enumerator (ONE
    source of truth with the ``docs/comm_schedule.md`` composition
    matrix);
  * :mod:`~sgcn_tpu.analysis.hlo` — the shared HLO/StableHLO parser (also
    ridden by ``tests/test_overlap_hlo.py``);
  * :mod:`~sgcn_tpu.analysis.expect` — plan-derived expectations;
  * :mod:`~sgcn_tpu.analysis.hlo_audit` — lower every supported mode's
    real program on the virtual 8-dev mesh and check census / wire dtype
    / wire shape / host-callback / donation contracts;
  * :mod:`~sgcn_tpu.analysis.ast_rules` — the source-hygiene rule
    registry;
  * :mod:`~sgcn_tpu.analysis.registry` — the ``CommPlan`` consumer
    contract tuples (ridden by ``tests/test_plan_contract.py``).

CLI: ``python -m sgcn_tpu.analysis [--fast] [--json] [--out FILE]
[--memory]`` — emits the schema-validated JSON report
(``scripts/validate_bench.py`` checks committed copies); ``--memory``
adds the compiling footprint-reconciliation pass (the ``memory-model``
rule of ``hlo_audit.run_memory_audit``).
"""

from __future__ import annotations

ANALYSIS_SCHEMA = "sgcn_analysis_report"
ANALYSIS_SCHEMA_VERSION = 1


def build_report(fast: bool = False, hlo: bool = True,
                 ast_pass: bool = True, root: str | None = None,
                 memory: bool = False) -> dict:
    """Run the requested passes and assemble the analysis report.

    ``memory`` adds the COMPILING memory-reconciliation pass
    (``hlo_audit.run_memory_audit``): every supported mode's programs are
    compiled and XLA's ``memory_analysis()`` joined against the analytic
    per-chip footprint model under the ``memory-model`` rule.  Off by
    default — it compiles (~3 min for the full matrix) where the text
    audit only lowers."""
    report: dict = {
        "schema": ANALYSIS_SCHEMA,
        "v": ANALYSIS_SCHEMA_VERSION,
        "fast": bool(fast),
        "ok": True,
    }
    if ast_pass:
        from .ast_rules import run_ast_pass

        report["ast"] = run_ast_pass(root)
        report["ok"] = report["ok"] and report["ast"]["ok"]
    if hlo:
        import jax

        from .hlo_audit import run_audit

        report["jax"] = jax.__version__
        report["hlo"] = run_audit(fast=fast)
        report["ok"] = report["ok"] and report["hlo"]["ok"]
    if memory:
        from .hlo_audit import run_memory_audit

        report["memory"] = run_memory_audit(fast=fast)
        report["ok"] = report["ok"] and report["memory"]["ok"]
    return report
